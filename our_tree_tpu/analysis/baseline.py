"""The findings baseline: suppress the KNOWN, gate on the NEW.

``analysis/baseline.json`` (repo root) is the committed ledger of
findings the tree knowingly carries — each entry a fingerprint plus a
**required reason** (the loader rejects reasonless entries: a baseline
that can absorb findings without justification is just a mute button).
The CI gate (``--fail-on-new``) fails on any finding whose fingerprint
is not on file, so the analyzer ratchets: the baseline can only shrink
without review, never silently grow.

Schema::

    {"version": 1,
     "findings": [{"fingerprint": "...", "rule": "...",
                   "location": "...", "reason": "..."}, ...]}

``rule`` and ``location`` ride along for humans diffing the file; only
the fingerprint matches. ``--write-baseline`` regenerates the file from
the current findings, PRESERVING existing reasons by fingerprint and
stamping ``TODO: justify`` on new entries — a reasonless entry fails
the next load, so a lazily regenerated baseline cannot merge quietly.
"""

from __future__ import annotations

import json
import os

from .findings import Finding

VERSION = 1


class BaselineError(ValueError):
    """Malformed baseline file (bad schema, missing reasons)."""


def load(path: str) -> dict[str, dict]:
    """fingerprint -> entry. Raises BaselineError on schema problems."""
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    if data.get("version") != VERSION:
        raise BaselineError(
            f"{path}: unsupported baseline version {data.get('version')!r}")
    out: dict[str, dict] = {}
    for i, entry in enumerate(data.get("findings", [])):
        fp = entry.get("fingerprint")
        if not fp:
            raise BaselineError(f"{path}: entry {i} has no fingerprint")
        reason = (entry.get("reason") or "").strip()
        if not reason or reason.startswith("TODO"):
            raise BaselineError(
                f"{path}: entry {fp} ({entry.get('location', '?')}) has no "
                "reason — every baselined finding must say why it is "
                "acceptable")
        if fp in out:
            raise BaselineError(f"{path}: duplicate fingerprint {fp}")
        out[fp] = entry
    return out


def apply(findings: list[Finding], baseline: dict[str, dict]) -> list[str]:
    """Mark baselined findings in place; returns the STALE fingerprints
    (baseline entries no finding matched — fixed violations whose entries
    should be deleted, reported so the baseline cannot rot)."""
    seen = set()
    for f in findings:
        entry = baseline.get(f.fingerprint)
        if entry is not None:
            f.baselined = True
            f.baseline_reason = entry.get("reason", "")
            seen.add(f.fingerprint)
    return sorted(set(baseline) - seen)


def write(path: str, findings: list[Finding],
          old: dict[str, dict] | None = None) -> int:
    """Write the baseline for ``findings``, preserving reasons from
    ``old`` by fingerprint; new entries get a TODO reason the loader
    will reject until a human justifies them. Returns the entry count.

    When a rule's semantic **version** bumps, every fingerprint it
    minted changes, so a reason preserved only by fingerprint would be
    lost on regeneration. The fallback match on (rule, location)
    carries the human's justification across the migration — the entry
    still names the same violation at the same place; only the hash
    moved. A finding that genuinely moved or changed shape misses both
    matches and surfaces as TODO, which the loader rejects: migration
    cannot silently launder an unsound suppression."""
    old = old or {}
    by_rule_loc = {(e.get("rule"), e.get("location")): e
                   for e in old.values()}
    entries = []
    for f in sorted(findings, key=lambda f: (f.layer, f.rule, f.location)):
        prev = old.get(f.fingerprint)
        if prev is None:
            prev = by_rule_loc.get((f.rule, f.location), {})
        entries.append({
            "fingerprint": f.fingerprint,
            "rule": f.rule,
            "location": f.location,
            "reason": prev.get("reason", "TODO: justify this finding"),
        })
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": VERSION, "findings": entries}, fh, indent=2)
        fh.write("\n")
    return len(entries)
