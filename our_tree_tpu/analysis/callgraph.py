"""ot-san layer 0: the package-wide call graph + effect inference.

The concurrency rules in ``sanrules.py`` need whole-program facts no
single-file AST pass can see: *does this call transitively block?*,
*does this function run on the event loop or on a worker thread?*,
*which locks does this callee acquire?*  This module builds them:

1. **Index pass** — parse every ``.py`` under the analyzed roots into
   modules, classes (with an attribute-type table: ``self._lock =
   threading.Lock()`` makes ``_lock`` a thread-lock everywhere), and
   functions (methods, nested defs, lambdas — each a node).

2. **Edge pass** — resolve every call site against the import graph
   (aliases, ``from x import y``, relative imports), ``self``/``cls``
   method lookup (including package-local subclass overrides: the
   virtual calls through ``HttpStatusEndpoint._handle`` must see the
   router's ``healthz``), local variable types, and — last resort — a
   unique-method-name match guarded by a deny list of ambient names.
   Each edge is classified:

   * ``call`` — same-context invocation; effects propagate.
   * ``hop`` — ``asyncio.to_thread`` / ``loop.run_in_executor`` /
     ``LaneExecutor.submit`` (and other executor ``.submit``): the
     callee runs on a worker thread; **blocking does not propagate**
     back through the hop.  This is the effect boundary the serve tier
     is built on (docs/SERVE.md).
   * ``thread`` — ``threading.Thread(target=...)``, ``Timer``,
     Thread-subclass ``run``, ``watchdog.thread_kill_hook`` callbacks:
     the callee is a thread root.
   * ``loopcb`` — ``call_soon_threadsafe``/``call_soon``/``call_later``
     targets: the callee is a loop root even though it is sync.

3. **Effect fixpoints** — three monotone passes over the edges:

   * ``loop_affine``: async defs and loopcb targets, propagated into
     sync callees through ``call`` edges (never through hops).
   * ``thread_affine``: hop/thread targets and ``run`` methods of
     ``threading.Thread`` subclasses, propagated the same way.
   * ``blocking``: seeded from the stdlib primitive table below
     (socket/file I/O, ``time.sleep``, ``subprocess``, lock/queue/
     future waits, ``jax.block_until_ready``) plus typed-receiver
     tails (``<Lock>.acquire``, ``<Event>.wait``, ``<Future>.result``,
     ``<Queue>.get``...), propagated caller-ward through ``call``
     edges only — a blocking callee behind a hop is the *fix*, not a
     finding.  Each blocking function keeps a witness chain so the
     report can say ``incidentz -> bundle_index -> open``.

The graph is deliberately an over-approximation in resolution and an
under-approximation in dynamism (no getattr-string dispatch, no
decorator unwrapping): precision tuning lives in the deny list and the
primitive table, and the committed baseline absorbs — with reasons —
the residue that is deliberate.

Stdlib-only, like the whole of layer 1: ot-san must run without jax
importable.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

PKG = "our_tree_tpu"

# --------------------------------------------------------------------------
# Blocking primitive seeds (dotted names, resolved through import aliases).
# --------------------------------------------------------------------------

#: Dotted call -> short label.  These are the syscalls-with-latency the
#: event loop must never reach synchronously.
BLOCKING_CALLS = {
    "time.sleep": "time.sleep",
    "open": "open()", "io.open": "open()",
    "json.load": "json.load", "json.dump": "json.dump",
    "os.fsync": "os.fsync", "os.fdatasync": "os.fdatasync",
    "os.listdir": "os.listdir", "os.scandir": "os.scandir",
    "os.replace": "os.replace", "os.rename": "os.rename",
    "os.remove": "os.remove", "os.unlink": "os.unlink",
    "os.makedirs": "os.makedirs", "os.mkdir": "os.mkdir",
    "os.rmdir": "os.rmdir", "os.read": "os.read", "os.write": "os.write",
    "os.waitpid": "os.waitpid", "os.kill": "os.kill",
    "shutil.rmtree": "shutil.rmtree", "shutil.copy": "shutil.copy",
    "shutil.copyfile": "shutil.copyfile", "shutil.move": "shutil.move",
    "socket.create_connection": "socket.create_connection",
    "socket.getaddrinfo": "socket.getaddrinfo",
    "subprocess.run": "subprocess.run", "subprocess.call": "subprocess.call",
    "subprocess.check_call": "subprocess.check_call",
    "subprocess.check_output": "subprocess.check_output",
    "subprocess.Popen": "subprocess.Popen",
    "select.select": "select.select",
    "urllib.request.urlopen": "urlopen",
    "jax.block_until_ready": "jax.block_until_ready",
    "jax.device_put": "jax.device_put",
    "concurrent.futures.wait": "futures.wait",
}

#: Attribute tails that block on ANY receiver — names specific enough
#: that a false receiver is implausible in this tree.
BLOCKING_TAILS = {
    "block_until_ready": "block_until_ready",
}

#: (receiver type, method) -> label.  Receiver types come from the
#: class attribute / local variable type tables.
TYPED_BLOCKING = {
    ("tlock", "acquire"): "Lock.acquire",
    ("cond", "wait"): "Condition.wait",
    ("cond", "wait_for"): "Condition.wait_for",
    ("event", "wait"): "Event.wait",
    ("thread", "join"): "Thread.join",
    ("queue", "get"): "Queue.get",
    ("queue", "put"): "Queue.put",
    ("future", "result"): "Future.result",
    ("future", "exception"): "Future.exception",
    ("socket", "recv"): "socket.recv", ("socket", "accept"): "socket.accept",
    ("socket", "connect"): "socket.connect",
    ("socket", "sendall"): "socket.sendall",
}

#: Constructor dotted name -> receiver type kind, for the attribute and
#: local variable type tables.
TYPE_CTORS = {
    "threading.Lock": "tlock", "threading.RLock": "tlock",
    "threading.Condition": "cond", "threading.Event": "event",
    "threading.Semaphore": "tlock", "threading.BoundedSemaphore": "tlock",
    "threading.Thread": "thread", "threading.Timer": "thread",
    "asyncio.Lock": "alock", "asyncio.Event": "aevent",
    "asyncio.Condition": "alock", "asyncio.Semaphore": "alock",
    "queue.Queue": "queue", "queue.SimpleQueue": "queue",
    "queue.LifoQueue": "queue", "queue.PriorityQueue": "queue",
    "socket.socket": "socket",
    "concurrent.futures.ThreadPoolExecutor": "executor",
    "concurrent.futures.ProcessPoolExecutor": "executor",
}

#: Methods too ambient to resolve by unique name (dict/list/str/stdlib
#: surface) — the unique-method fallback refuses these.
_AMBIENT = frozenset({
    "get", "put", "pop", "append", "extend", "update", "clear", "copy",
    "keys", "values", "items", "add", "remove", "discard", "sort",
    "split", "join", "strip", "rstrip", "lstrip", "format", "encode",
    "decode", "startswith", "endswith", "replace", "lower", "upper",
    "read", "write", "flush", "close", "open", "send", "recv",
    "submit", "run", "start", "stop", "wait", "result", "cancel",
    "acquire", "release", "render", "stats", "state", "reset", "name",
    "done", "set", "is_set", "count", "index", "insert", "setdefault",
    "group", "groups", "match", "sub", "search",
})

#: Call tails whose positional arg N is a callable entered on a worker
#: thread; the call itself is a non-blocking hand-off.
_HOP_TAILS = {"run_in_executor": 1}
#: Call tails whose callable arg runs on the EVENT LOOP later.
_LOOPCB_TAILS = {"call_soon_threadsafe": 0, "call_soon": 0,
                 "call_later": 1, "call_at": 1}


# --------------------------------------------------------------------------
# Graph node shapes
# --------------------------------------------------------------------------

@dataclass
class Edge:
    """One resolved call site inside a function body."""
    kind: str                #: "call" | "hop" | "thread" | "loopcb"
    lineno: int
    label: str               #: display name of what is called
    target: "Func | None" = None   #: package function, when resolved
    prim: str | None = None  #: blocking-primitive label, when matched
    under_locks: tuple[str, ...] = ()  #: lock ids held at the call site


@dataclass
class LockAcq:
    """One ``with <lock>:`` acquisition."""
    lock_id: str             #: "Class.attr" / "module.NAME" canonical id
    kind: str                #: "tlock" | "alock"
    lineno: int
    is_async_with: bool
    under: tuple[str, ...]   #: lock ids already held (ordering edges)


@dataclass
class WriteSite:
    """One mutation of shared state (self.attr or module global)."""
    key: tuple               #: ("attr", class_qname, name) | ("global", module, name)
    lineno: int
    locked: bool             #: write happened under a thread lock
    owner: str | None        #: "# ot-san: owner=<seam>" annotation, if any


@dataclass
class Func:
    qname: str               #: dotted, e.g. "our_tree_tpu.serve.status.HttpStatusEndpoint._handle"
    module: str
    relpath: str
    name: str
    node: ast.AST
    is_async: bool
    lineno: int
    cls: "ClassInfo | None" = None
    parent: "Func | None" = None   #: enclosing function for nested defs
    edges: list[Edge] = field(default_factory=list)
    acquires: list[LockAcq] = field(default_factory=list)
    awaits_under: list[tuple[str, int]] = field(default_factory=list)
    sync_with_alock: list[tuple[str, int]] = field(default_factory=list)
    writes: list[WriteSite] = field(default_factory=list)
    globals_decl: set = field(default_factory=set)
    # effects (filled by the fixpoints)
    loop_affine: bool = False
    thread_affine: bool = False
    blocking: bool = False
    loop_root: bool = False      #: async def or loopcb target
    thread_root: bool = False
    absorb: str | None = None    #: "# ot-san: absorb=<tag>" boundary tag
    block_witness: tuple | None = None  #: (lineno, label, next Func|None)

    def display(self) -> str:
        return self.qname

    def block_chain(self, limit: int = 6) -> str:
        """Render the witness chain: ``f -> g -> open()``."""
        parts, cur, hops = [self.short()], self, 0
        w = self.block_witness
        while w is not None and hops < limit:
            lineno, label, nxt = w
            if nxt is None:
                parts.append(label)
                break
            parts.append(nxt.short())
            cur, w = nxt, nxt.block_witness
            hops += 1
        return " -> ".join(parts)

    def short(self) -> str:
        tail = self.qname
        if tail.startswith(PKG + "."):
            tail = tail[len(PKG) + 1:]
        return tail


@dataclass
class ClassInfo:
    qname: str
    module: str
    relpath: str
    name: str
    node: ast.ClassDef
    bases: list[str] = field(default_factory=list)  #: raw dotted base names
    methods: dict = field(default_factory=dict)     #: name -> Func
    attr_types: dict = field(default_factory=dict)  #: attr -> type kind
    attr_classes: dict = field(default_factory=dict)  #: attr -> class qname
    attr_owner_ann: dict = field(default_factory=dict)  #: attr -> owner seam
    is_thread_subclass: bool = False


@dataclass
class ModuleInfo:
    name: str                #: dotted ("our_tree_tpu.serve.status")
    relpath: str
    aliases: dict = field(default_factory=dict)   #: local name -> dotted prefix
    funcs: dict = field(default_factory=dict)     #: name -> Func
    classes: dict = field(default_factory=dict)   #: name -> ClassInfo
    var_types: dict = field(default_factory=dict)  #: module var -> type kind
    lines: list = field(default_factory=list)


def _dotted(node: ast.AST) -> str | None:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


class Graph:
    """The whole-program call graph over one set of source roots."""

    def __init__(self):
        self.modules: dict[str, ModuleInfo] = {}
        self.classes: dict[str, ClassInfo] = {}
        self.funcs: list[Func] = []
        #: simple method name -> [Func] across all classes (fallback).
        self.methods_by_name: dict[str, list[Func]] = {}
        #: class qname -> [subclass ClassInfo]
        self.subclasses: dict[str, list[ClassInfo]] = {}
        self.parse_errors: list[tuple[str, str]] = []
        #: malformed "# ot-san:" def-line annotations: (relpath, lineno)
        self.ann_malformed: list[tuple[str, int]] = []

    # ---------------------------------------------------------- build --
    def build(self, files: list[tuple[str, str]]):
        """``files`` is a list of (abspath, relpath)."""
        parsed = []
        for path, rel in files:
            try:
                with open(path, encoding="utf-8") as fh:
                    src = fh.read()
                tree = ast.parse(src, filename=rel)
            except (OSError, SyntaxError) as e:
                self.parse_errors.append((rel, str(e)))
                continue
            parsed.append((rel, src, tree))
        for rel, src, tree in parsed:
            self._index_module(rel, src, tree)
        self._link_classes()
        for mod in self.modules.values():
            self._edge_pass(mod)
        self._run_fixpoints()

    @staticmethod
    def _module_name(rel: str) -> str:
        name = rel[:-3] if rel.endswith(".py") else rel
        name = name.replace(os.sep, "/").replace("/", ".")
        if name.endswith(".__init__"):
            name = name[:-len(".__init__")]
        return name

    # -------------------------------------------------------- pass A --
    def _index_module(self, rel: str, src: str, tree: ast.Module):
        mod = ModuleInfo(self._module_name(rel), rel, lines=src.splitlines())
        self.modules[mod.name] = mod
        for stmt in tree.body:
            self._index_stmt(mod, stmt)

    def _index_stmt(self, mod: ModuleInfo, stmt: ast.stmt):
        if isinstance(stmt, ast.Import):
            for a in stmt.names:
                mod.aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(stmt, ast.ImportFrom):
            base = stmt.module or ""
            if stmt.level:  # relative: resolve against this module
                parts = mod.name.split(".")
                # level 1 = current package (drop the module segment)
                parts = parts[:len(parts) - stmt.level]
                base = ".".join(parts + ([stmt.module] if stmt.module else []))
            for a in stmt.names:
                if a.name == "*":
                    continue
                mod.aliases[a.asname or a.name] = (
                    f"{base}.{a.name}" if base else a.name)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn = Func(f"{mod.name}.{stmt.name}", mod.name, mod.relpath,
                      stmt.name, stmt,
                      isinstance(stmt, ast.AsyncFunctionDef), stmt.lineno)
            self._register_absorb(fn, mod)
            mod.funcs[stmt.name] = fn
            self.funcs.append(fn)
        elif isinstance(stmt, ast.ClassDef):
            ci = ClassInfo(f"{mod.name}.{stmt.name}", mod.name, mod.relpath,
                           stmt.name, stmt,
                           bases=[d for b in stmt.bases
                                  if (d := _dotted(b)) is not None])
            mod.classes[stmt.name] = ci
            self.classes[ci.qname] = ci
            for sub in stmt.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fn = Func(f"{ci.qname}.{sub.name}", mod.name,
                              mod.relpath, sub.name, sub,
                              isinstance(sub, ast.AsyncFunctionDef),
                              sub.lineno, cls=ci)
                    self._register_absorb(fn, mod)
                    ci.methods[sub.name] = fn
                    self.funcs.append(fn)
                    self.methods_by_name.setdefault(sub.name, []).append(fn)
                elif isinstance(sub, ast.AnnAssign) and isinstance(
                        sub.target, ast.Name):
                    self._maybe_type_attr(mod, ci, sub.target.id, sub.value,
                                          sub.lineno)
            init = ci.methods.get("__init__")
            if init is not None:
                for node in ast.walk(init.node):
                    if (isinstance(node, ast.Assign) and len(node.targets) == 1
                            and isinstance(node.targets[0], ast.Attribute)
                            and isinstance(node.targets[0].value, ast.Name)
                            and node.targets[0].value.id == "self"):
                        self._maybe_type_attr(mod, ci, node.targets[0].attr,
                                              node.value, node.lineno)
        elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            kind = self._ctor_kind(mod, stmt.value)
            if kind:
                mod.var_types[stmt.targets[0].id] = kind

    def _register_absorb(self, fn: Func, mod: ModuleInfo):
        tag = _absorb_annotation(mod.lines, fn.lineno)
        if tag == "":
            self.ann_malformed.append((fn.relpath, fn.lineno))
        elif tag:
            fn.absorb = tag

    def _maybe_type_attr(self, mod: ModuleInfo, ci: ClassInfo, attr: str,
                         value: ast.AST | None, lineno: int):
        if value is None:
            return
        kind = self._ctor_kind(mod, value)
        if kind:
            ci.attr_types[attr] = kind
        elif isinstance(value, ast.Call):
            d = _dotted(value.func)
            if d:
                resolved = self._expand(mod, d)
                target = self._lookup_class(resolved)
                if target is not None:
                    ci.attr_classes[attr] = target.qname
        # class-level "# ot-san: owner=" annotation on the init line
        owner = _owner_annotation(mod.lines, lineno)
        if owner:
            ci.attr_owner_ann[attr] = owner

    def _ctor_kind(self, mod: ModuleInfo, value: ast.AST) -> str | None:
        if not isinstance(value, ast.Call):
            return None
        d = _dotted(value.func)
        if d is None:
            return None
        return TYPE_CTORS.get(self._expand(mod, d))

    def _expand(self, mod: ModuleInfo, dotted: str) -> str:
        head, _, rest = dotted.partition(".")
        base = mod.aliases.get(head)
        if base is None:
            return dotted
        return f"{base}.{rest}" if rest else base

    def _link_classes(self):
        for ci in self.classes.values():
            mod = self.modules[ci.module]
            for raw in ci.bases:
                resolved = self._expand(mod, raw)
                if resolved in ("threading.Thread", "threading.Timer"):
                    ci.is_thread_subclass = True
                parent = self._lookup_class(resolved)
                if parent is not None:
                    self.subclasses.setdefault(parent.qname, []).append(ci)
                    if parent.is_thread_subclass:
                        ci.is_thread_subclass = True
        # second sweep: grandchildren of Thread subclasses
        changed = True
        while changed:
            changed = False
            for ci in self.classes.values():
                if ci.is_thread_subclass:
                    continue
                mod = self.modules[ci.module]
                for raw in ci.bases:
                    parent = self._lookup_class(self._expand(mod, raw))
                    if parent is not None and parent.is_thread_subclass:
                        ci.is_thread_subclass = True
                        changed = True

    def _lookup_class(self, dotted: str) -> ClassInfo | None:
        if dotted in self.classes:
            return self.classes[dotted]
        modname, _, cls = dotted.rpartition(".")
        m = self.modules.get(modname)
        if m is not None:
            return m.classes.get(cls)
        return None

    def _lookup_func(self, dotted: str) -> Func | None:
        modname, _, name = dotted.rpartition(".")
        m = self.modules.get(modname)
        if m is not None and name in m.funcs:
            return m.funcs[name]
        # Class.method
        ci = self._lookup_class(modname)
        if ci is not None:
            return ci.methods.get(name)
        return None

    # -------------------------------------------------------- pass B --
    def _edge_pass(self, mod: ModuleInfo):
        for fn in list(mod.funcs.values()):
            _BodyWalker(self, mod, fn).walk()
        for ci in mod.classes.values():
            for fn in list(ci.methods.values()):
                _BodyWalker(self, mod, fn).walk()

    # ------------------------------------------------------ fixpoints --
    def _run_fixpoints(self):
        # roots
        for fn in self.funcs:
            if fn.is_async:
                fn.loop_root = True
                fn.loop_affine = True
            if fn.cls is not None and fn.cls.is_thread_subclass \
                    and fn.name == "run":
                fn.thread_root = True
                fn.thread_affine = True
        for fn in self.funcs:
            for e in fn.edges:
                if e.target is None:
                    continue
                if e.kind in ("hop", "thread"):
                    e.target.thread_root = True
                    e.target.thread_affine = True
                elif e.kind == "loopcb" and not e.target.is_async:
                    e.target.loop_root = True
                    e.target.loop_affine = True
        # affinity propagation through call edges into SYNC callees
        for attr in ("loop_affine", "thread_affine"):
            work = [f for f in self.funcs if getattr(f, attr)]
            while work:
                fn = work.pop()
                for e in fn.edges:
                    t = e.target
                    if (e.kind == "call" and t is not None and not t.is_async
                            and not getattr(t, attr)):
                        setattr(t, attr, True)
                        work.append(t)
        # blocking: seed from prim edges, propagate caller-ward
        callers: dict[int, list[tuple[Func, Edge]]] = {}
        work = []
        for fn in self.funcs:
            for e in fn.edges:
                if e.kind != "call":
                    continue
                if e.prim is not None and not fn.blocking:
                    fn.blocking = True
                    fn.block_witness = (e.lineno, e.prim, None)
                    work.append(fn)
                if e.target is not None:
                    callers.setdefault(id(e.target), []).append((fn, e))
        while work:
            g = work.pop()
            # an absorb-annotated function is an effect boundary: its
            # blocking is bounded/amortized by design and does not
            # propagate to callers (it stays blocking internally)
            if g.absorb:
                continue
            for f, e in callers.get(id(g), ()):
                # an async callee's blocking is its own finding; calling
                # it (making the coroutine) does not block the caller
                if g.is_async or f.blocking:
                    continue
                f.blocking = True
                f.block_witness = (e.lineno, g.short(), g)
                work.append(f)

    # ------------------------------------------------------- queries --
    def resolve_method(self, cls: ClassInfo, name: str) -> list[Func]:
        """``self.<name>`` lookup: the class, its package bases, and —
        virtual dispatch — every package subclass override."""
        out, seen = [], set()

        def _own_and_bases(ci: ClassInfo):
            if ci.qname in seen:
                return
            seen.add(ci.qname)
            if name in ci.methods:
                out.append(ci.methods[name])
            mod = self.modules[ci.module]
            for raw in ci.bases:
                parent = self._lookup_class(self._expand(mod, raw))
                if parent is not None:
                    _own_and_bases(parent)

        _own_and_bases(cls)
        for sub in self._all_subclasses(cls):
            if name in sub.methods:
                fn = sub.methods[name]
                if fn not in out:
                    out.append(fn)
        return out

    def _all_subclasses(self, cls: ClassInfo) -> list[ClassInfo]:
        out, stack = [], list(self.subclasses.get(cls.qname, ()))
        while stack:
            ci = stack.pop()
            out.append(ci)
            stack.extend(self.subclasses.get(ci.qname, ()))
        return out

    def attr_type(self, cls: ClassInfo, attr: str) -> str | None:
        if attr in cls.attr_types:
            return cls.attr_types[attr]
        mod = self.modules[cls.module]
        for raw in cls.bases:
            parent = self._lookup_class(self._expand(mod, raw))
            if parent is not None:
                t = self.attr_type(parent, attr)
                if t:
                    return t
        return None


def _parse_ot_san(text: str) -> tuple[str, str] | None:
    """Parse an ``# ot-san: <key>=<value>`` comment off a source line.
    Returns (key, value) for a well-formed annotation, ("", "") for a
    malformed one (present but not matching the grammar — a typo must
    not silently waive a rule), None when no ot-san comment exists."""
    idx = text.find("# ot-san:")
    if idx < 0:
        return None
    body = text[idx + len("# ot-san:"):].strip()
    key, eq, value = body.partition("=")
    value = value.split()[0] if value.split() else ""
    if (eq and key in ("owner", "absorb") and value
            and all(c.isalnum() or c in "._:-" for c in value)):
        return key, value
    return "", ""


def _owner_annotation(lines: list[str], lineno: int) -> str | None:
    """``# ot-san: owner=<seam>`` on a write line (1-based): the seam
    name, ``""`` for malformed, None for absent."""
    if not (1 <= lineno <= len(lines)):
        return None
    ann = _parse_ot_san(lines[lineno - 1])
    if ann is None:
        return None
    key, value = ann
    return value if key == "owner" else ""


def _absorb_annotation(lines: list[str], lineno: int) -> str | None:
    """``# ot-san: absorb=<tag>`` on a ``def`` line or the line above
    it: the function is a designated effect BOUNDARY — its transitive
    blocking is bounded/amortized by design (buffered trace writes,
    once-per-process lazy init, the journal's fsync durability
    contract) and does not propagate to callers.  Returns the tag,
    ``""`` for malformed, None for absent."""
    for ln in (lineno, lineno - 1):
        if not (1 <= ln <= len(lines)):
            continue
        ann = _parse_ot_san(lines[ln - 1])
        if ann is None:
            continue
        key, value = ann
        return value if key == "absorb" else ""
    return None


class _BodyWalker:
    """Pass B over one function body: edges, lock events, writes."""

    def __init__(self, graph: Graph, mod: ModuleInfo, fn: Func):
        self.g = graph
        self.mod = mod
        self.fn = fn
        self.local_types: dict[str, str] = {}    #: var -> type kind
        self.local_classes: dict[str, str] = {}  #: var -> class qname
        self.local_funcs: dict[str, Func] = {}   #: nested def name -> Func

    def walk(self):
        body = getattr(self.fn.node, "body", [])
        if isinstance(body, list):
            for stmt in body:
                self._visit(stmt, ())
        else:  # lambda: body is an expression
            self._visit(body, ())

    # ------------------------------------------------------- helpers --
    def _lock_id(self, expr: ast.AST) -> tuple[str, str] | None:
        """Resolve a with-context expression to (lock id, kind)."""
        if isinstance(expr, ast.Attribute) and isinstance(
                expr.value, ast.Name) and expr.value.id in ("self", "cls") \
                and self.fn.cls is not None:
            kind = self.g.attr_type(self.fn.cls, expr.attr)
            if kind in ("tlock", "alock", "cond"):
                k = "tlock" if kind in ("tlock", "cond") else "alock"
                return f"{self.fn.cls.qname}.{expr.attr}", k
            return None
        d = _dotted(expr)
        if d is not None:
            if "." not in d:
                kind = self.local_types.get(d) or self.mod.var_types.get(d)
                if kind in ("tlock", "alock", "cond"):
                    k = "tlock" if kind in ("tlock", "cond") else "alock"
                    return f"{self.fn.module}.{d}", k
            else:
                resolved = self.g._expand(self.mod, d)
                modname, _, var = resolved.rpartition(".")
                m = self.g.modules.get(modname)
                if m is not None:
                    kind = m.var_types.get(var)
                    if kind in ("tlock", "alock", "cond"):
                        k = "tlock" if kind in ("tlock", "cond") else "alock"
                        return f"{modname}.{var}", k
        return None

    def _receiver_kind(self, expr: ast.AST) -> str | None:
        """Type kind of an attribute-call receiver, if known."""
        if isinstance(expr, ast.Attribute) and isinstance(
                expr.value, ast.Name) and expr.value.id in ("self", "cls") \
                and self.fn.cls is not None:
            return self.g.attr_type(self.fn.cls, expr.attr)
        if isinstance(expr, ast.Name):
            return (self.local_types.get(expr.id)
                    or self.mod.var_types.get(expr.id))
        return None

    def _receiver_class(self, expr: ast.AST) -> ClassInfo | None:
        if isinstance(expr, ast.Attribute) and isinstance(
                expr.value, ast.Name) and expr.value.id in ("self", "cls") \
                and self.fn.cls is not None:
            q = self.fn.cls.attr_classes.get(expr.attr)
            return self.g.classes.get(q) if q else None
        if isinstance(expr, ast.Name):
            q = self.local_classes.get(expr.id)
            return self.g.classes.get(q) if q else None
        return None

    def _resolve_callable_ref(self, node: ast.AST) -> Func | None:
        """Resolve a callable REFERENCE (hop/thread/loopcb arg)."""
        if isinstance(node, ast.Lambda):
            return self._nested_func(node, "<lambda>")
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d and self.g._expand(self.mod, d).endswith("partial") \
                    and node.args:
                return self._resolve_callable_ref(node.args[0])
            return None
        if isinstance(node, ast.Name):
            if node.id in self.local_funcs:
                return self.local_funcs[node.id]
            t = self._lookup_name(node.id)
            return t if isinstance(t, Func) else None
        if isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name) and node.value.id in (
                    "self", "cls") and self.fn.cls is not None:
                targets = self.g.resolve_method(self.fn.cls, node.attr)
                return targets[0] if targets else None
            d = _dotted(node)
            if d:
                return self.g._lookup_func(self.g._expand(self.mod, d))
        return None

    def _lookup_name(self, name: str):
        """Func | ClassInfo | None for a bare name in this module."""
        if name in self.mod.funcs:
            return self.mod.funcs[name]
        if name in self.mod.classes:
            return self.mod.classes[name]
        if name in self.mod.aliases:
            dotted = self.mod.aliases[name]
            return (self.g._lookup_func(dotted)
                    or self.g._lookup_class(dotted))
        return None

    def _nested_func(self, node, name: str) -> Func:
        fn = Func(f"{self.fn.qname}.{name}", self.fn.module, self.fn.relpath,
                  name, node, isinstance(node, ast.AsyncFunctionDef),
                  node.lineno, cls=self.fn.cls, parent=self.fn)
        self.g._register_absorb(fn, self.mod)
        self.g.funcs.append(fn)
        sub = _BodyWalker(self.g, self.mod, fn)
        sub.local_funcs = dict(self.local_funcs)
        sub.local_types = dict(self.local_types)
        sub.local_classes = dict(self.local_classes)
        sub.walk()
        return fn

    # --------------------------------------------------------- visit --
    def _visit(self, node: ast.AST, locks: tuple[str, ...]):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.local_funcs[node.name] = self._nested_func(node, node.name)
            return
        if isinstance(node, ast.Lambda):
            # bare lambda expression in non-callback position: its body
            # runs whenever it is called; analyzed as a nested func only
            # when passed to a hop/thread/loopcb (handled at the Call).
            return
        if isinstance(node, ast.ClassDef):
            return  # function-local classes: out of scope
        if isinstance(node, ast.Global):
            self.fn.globals_decl.update(node.names)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            self._visit_with(node, locks)
            return
        if isinstance(node, ast.Await):
            # awaiting while an asyncio.Lock is held is the normal
            # critical-section shape; only THREAD locks held across a
            # suspension are the deadlock/starvation hazard.
            for lk, kind in locks:
                if kind == "tlock":
                    self.fn.awaits_under.append((lk, node.lineno))
            self._visit(node.value, locks)
            return
        if isinstance(node, ast.Call):
            self._visit_call(node, locks)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            self._visit_assign(node, locks)
            return
        for child in ast.iter_child_nodes(node):
            self._visit(child, locks)

    def _visit_with(self, node, locks):
        new = list(locks)
        for item in node.items:
            self._visit(item.context_expr, tuple(new))
            li = self._lock_id(item.context_expr)
            if li is None:
                continue
            lock_id, kind = li
            if kind == "alock" and isinstance(node, ast.With):
                # sync `with` on an asyncio.Lock: a type error at
                # runtime — flagged, never treated as held
                self.fn.sync_with_alock.append((lock_id, node.lineno))
                continue
            self.fn.acquires.append(LockAcq(
                lock_id, kind, node.lineno,
                isinstance(node, ast.AsyncWith),
                tuple(i for i, _k in new)))
            new.append((lock_id, kind))
        for stmt in node.body:
            self._visit(stmt, tuple(new))

    def _visit_assign(self, node, locks):
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            key = None
            if isinstance(t, ast.Attribute) and isinstance(
                    t.value, ast.Name) and t.value.id == "self" \
                    and self.fn.cls is not None:
                key = ("attr", self.fn.cls.qname, t.attr)
            elif isinstance(t, ast.Name) and t.id in self.fn.globals_decl:
                key = ("global", self.fn.module, t.id)
            if key is not None:
                self.fn.writes.append(WriteSite(
                    key, node.lineno,
                    locked=any(k == "tlock" for _i, k in locks),
                    owner=_owner_annotation(self.mod.lines, node.lineno)))
        value = getattr(node, "value", None)
        if value is not None:
            self._visit(value, locks)
            # local type tracking: x = threading.Lock() / x = Cls(...)
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                kind = self.g._ctor_kind(self.mod, value)
                if kind:
                    self.local_types[name] = kind
                elif isinstance(value, ast.Call):
                    d = _dotted(value.func)
                    if d:
                        target = self.g._lookup_class(
                            self.g._expand(self.mod, d))
                        if target is not None:
                            self.local_classes[name] = target.qname
                    # <executor>.submit(...) returns a Future
                    if (isinstance(value.func, ast.Attribute)
                            and value.func.attr == "submit"):
                        self.local_types[name] = "future"

    # The call site classifier — the heart of pass B.
    def _visit_call(self, node: ast.Call, locks):
        handled_args: set[int] = set()
        fnode = node.func
        tail = fnode.attr if isinstance(fnode, ast.Attribute) else None
        label = _dotted(fnode) or (tail or "<call>")

        def add(kind, target=None, prim=None):
            self.fn.edges.append(Edge(
                kind, node.lineno, label, target=target, prim=prim,
                under_locks=tuple(i for i, _k in locks)))

        def hop_ref(idx, kind):
            if idx < len(node.args):
                ref = self._resolve_callable_ref(node.args[idx])
                handled_args.add(idx)
                if ref is not None:
                    add(kind, target=ref)
                    return
            add(kind)

        resolved = None
        d = _dotted(fnode)
        if d is not None:
            resolved = self.g._expand(self.mod, d)

        consumed = False
        if resolved == "asyncio.to_thread":
            hop_ref(0, "hop")
            consumed = True
        elif tail in _HOP_TAILS and resolved not in BLOCKING_CALLS:
            hop_ref(_HOP_TAILS[tail], "hop")
            consumed = True
        elif tail in _LOOPCB_TAILS:
            hop_ref(_LOOPCB_TAILS[tail], "loopcb")
            consumed = True
        elif resolved in ("threading.Thread", "threading.Timer"):
            ref = None
            for kw in node.keywords:
                if kw.arg == "target":
                    ref = self._resolve_callable_ref(kw.value)
            if ref is None and resolved == "threading.Timer" \
                    and len(node.args) >= 2:
                ref = self._resolve_callable_ref(node.args[1])
            add("thread", target=ref)
            consumed = True
        elif tail == "submit":
            rk = self._receiver_kind(fnode.value)
            rc = self._receiver_class(fnode.value)
            recv_txt = (_dotted(fnode.value) or "").lower()
            if rk == "executor" or "executor" in recv_txt or (
                    rc is not None and "executor" in rc.name.lower()):
                hop_ref(0, "hop")
                consumed = True
        elif tail == "start":
            rk = self._receiver_kind(fnode.value)
            rc = self._receiver_class(fnode.value)
            if rc is not None and rc.is_thread_subclass:
                run = rc.methods.get("run")
                add("thread", target=run)
                consumed = True
            elif rk == "thread":
                add("thread")
                consumed = True

        if not consumed:
            prim = None
            target: Func | ClassInfo | None = None
            if resolved is not None and "." not in d:
                # bare name: local defs shadow module symbols shadow prims
                if d in self.local_funcs:
                    target = self.local_funcs[d]
                else:
                    target = self._lookup_name(d)
                if target is None:
                    prim = BLOCKING_CALLS.get(resolved)
            elif resolved is not None:
                # try package entities first, then the prim table
                t = (self.g._lookup_func(resolved)
                     or self.g._lookup_class(resolved))
                if t is None and isinstance(fnode, ast.Attribute) \
                        and isinstance(fnode.value, ast.Name) \
                        and fnode.value.id in ("self", "cls") \
                        and self.fn.cls is not None:
                    methods = self.g.resolve_method(self.fn.cls, fnode.attr)
                    if methods:
                        for m in methods:
                            add("call", target=m)
                        consumed = True
                target = t
                if target is None and not consumed:
                    prim = BLOCKING_CALLS.get(resolved)
            if not consumed and target is None and prim is None \
                    and tail is not None:
                # typed receiver tails, then special tails, then the
                # unique-method fallback
                rk = self._receiver_kind(fnode.value)
                if rk is not None and (rk, tail) in TYPED_BLOCKING:
                    if not _nonblocking_override(node, rk, tail):
                        prim = TYPED_BLOCKING[(rk, tail)]
                elif tail in BLOCKING_TAILS:
                    prim = BLOCKING_TAILS[tail]
                else:
                    rc = self._receiver_class(fnode.value)
                    if rc is not None and tail in rc.methods:
                        target = rc.methods[tail]
                    elif tail not in _AMBIENT:
                        cands = self.g.methods_by_name.get(tail, ())
                        if len(cands) == 1:
                            target = cands[0]
            if not consumed:
                if isinstance(target, ClassInfo):
                    init = target.methods.get("__init__")
                    if init is not None:
                        add("call", target=init)
                    elif target.is_thread_subclass:
                        add("thread", target=target.methods.get("run"))
                elif isinstance(target, Func):
                    add("call", target=target)
                elif prim is not None:
                    add("call", prim=prim)

        # walk arguments (skipping callable refs already turned into
        # hop/thread/loopcb edges — their bodies are the callee's)
        for i, arg in enumerate(node.args):
            if i in handled_args:
                continue
            self._visit(arg, locks)
        for kw in node.keywords:
            self._visit(kw.value, locks)
        if isinstance(fnode, ast.Attribute):
            self._visit(fnode.value, locks)


def _nonblocking_override(node: ast.Call, rk: str, tail: str) -> bool:
    """``lock.acquire(blocking=False)`` / ``q.get(block=False)`` /
    ``q.get_nowait()`` do not block."""
    for kw in node.keywords:
        if kw.arg in ("blocking", "block") and isinstance(
                kw.value, ast.Constant) and kw.value.value is False:
            return True
    if rk == "queue" and node.args:
        first = node.args[0]
        if isinstance(first, ast.Constant) and first.value is False:
            return True
    return False


def build_graph(paths: list[str], repo_root: str) -> Graph:
    """Build the graph over ``paths`` (files or directories), with
    relpaths computed against ``repo_root`` — same contract as
    ``astrules.lint_paths``."""
    files: list[tuple[str, str]] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in sorted(dirnames)
                               if d not in ("__pycache__", ".git")]
                for f in sorted(filenames):
                    if f.endswith(".py"):
                        files.append(os.path.join(dirpath, f))
        elif p.endswith(".py"):
            files.append(p)
    pairs = [(os.path.abspath(f),
              os.path.relpath(os.path.abspath(f), repo_root)
              .replace(os.sep, "/")) for f in files]
    g = Graph()
    g.build(pairs)
    return g
