"""The one finding shape both analyzer layers emit.

A finding's identity is its **fingerprint** — a stable hash of the rule
plus a location anchor that survives line-number drift: AST findings
anchor on the normalized source *text* of the flagged line (plus an
occurrence index for textually identical lines), jaxpr findings on the
(entry point, primitive) pair, san findings on call-site text or a
canonical cycle/attribute string. Line numbers ride along for humans
and go stale harmlessly; the baseline matches by fingerprint only.

The fingerprint also folds in the emitting rule's **semantic version**:
tightening a rule's semantics (catching more, anchoring differently)
bumps its version, which invalidates every baseline entry minted under
the old semantics — stale entries are *reported*, never silently
honored. Bump the version whenever a rule change would make an old
suppression unsound; leave it alone for message-only edits.

Stdlib-only: layer 1 and the baseline machinery must load without jax.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

#: Severities, most severe first (report ordering).
SEVERITIES = ("error", "warning")


@dataclass
class Finding:
    rule: str          #: rule id, e.g. "constant-time" or "subprocess-isolate"
    severity: str      #: "error" | "warning"
    message: str       #: one human line naming the violation
    path: str          #: repo-relative file, or "<jaxpr>" for layer 2
    line: int = 0      #: 1-based; 0 = no source location (jaxpr findings
                       #: put any recovered file:line in the message)
    anchor: str = ""   #: stable identity component (see module docstring)
    layer: str = "ast"  #: "ast" | "jaxpr" | "san"
    version: int = 1   #: emitting rule's semantic version (fingerprinted)
    baselined: bool = field(default=False, compare=False)
    baseline_reason: str = field(default="", compare=False)

    @property
    def fingerprint(self) -> str:
        h = hashlib.sha1(
            f"{self.layer}|{self.rule}|v{self.version}|{self.path}|"
            f"{self.anchor}".encode()).hexdigest()[:16]
        return f"{self.layer}:{self.rule}:{h}"

    @property
    def location(self) -> str:
        if self.layer == "jaxpr":
            return f"<jaxpr:{self.anchor}>"
        return f"{self.path}:{self.line}" if self.line else self.path

    def render(self) -> str:
        tag = " [baselined]" if self.baselined else ""
        return (f"{self.location}: {self.severity}: {self.rule}: "
                f"{self.message}{tag}")

    def to_json(self) -> dict:
        return {
            "fingerprint": self.fingerprint, "rule": self.rule,
            "severity": self.severity, "message": self.message,
            "location": self.location, "layer": self.layer,
        }


def anchored(findings: list[Finding]) -> list[Finding]:
    """Disambiguate findings whose (rule, path, anchor) collide by
    suffixing an occurrence index — two textually identical violations
    in one file stay two baseline entries, in source order."""
    seen: dict[tuple, int] = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line)):
        key = (f.layer, f.rule, f.path, f.anchor)
        n = seen.get(key, 0)
        seen[key] = n + 1
        if n:
            f.anchor = f"{f.anchor}#{n}"
    return findings
