"""Layer 1: the AST linter — pluggable rules encoding repo invariants.

Each rule is a ``Rule`` instance with an ``id``, a severity, a one-line
``doc`` (the catalog in docs/ANALYSIS.md is generated from these), and a
``check(ctx)`` generator yielding ``(node, message)`` pairs. Rules see a
``FileContext`` (repo-relative path, source, parsed tree) and decide
scope themselves — e.g. the wallclock rule skips ``obs/`` (the tracer
owns the epoch clock), the subprocess rule skips ``resilience/isolate.py``
(the chokepoint *is* the allowed caller).

The rules are deliberately syntactic: they encode *who may say what
where*, not deep dataflow (that is the jaxpr auditor's job). A guarded
dispatch is recognized lexically — a call inside a ``with`` whose
context expression routes through ``watchdog.deadline`` (or a wrapper
whose name says so, like the root bench's ``_stage_alarm``). That is
exactly the shape the repo's seams actually have, and a seam that
launders a dispatch past the lexical check is a code-review problem no
static analyzer solves.

Stdlib-only except for ``resilience.faults.KNOWN_POINTS`` (itself a
stdlib-only module) — the fault-point rule checks literals against the
live registry so the two can never drift.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass
from typing import Callable, Iterator

from .findings import Finding, anchored

# The live injection-point registry (resilience/faults.py is stdlib-only
# and import-safe). Falling back to a frozen copy keeps the linter
# usable on a tree where faults.py itself is being refactored.
try:
    from ..resilience.faults import KNOWN_POINTS
except Exception:  # pragma: no cover - only on a broken tree
    KNOWN_POINTS = ("init_hang", "dispatch_fail", "build_fail", "lock_busy",
                    "dispatch_hang", "unit_crash", "serve_dispatch",
                    "lane_fail", "lane_hang", "dispatch_slow",
                    "backend_fail", "backend_hang",
                    "chunk_lost", "reassembly_stall", "transfer_abort",
                    "session_stall", "keystream_miss", "session_evict")

# The live metrics label-key allowlist (obs/metrics.py, also
# stdlib-only) — same live-registry-with-frozen-fallback pattern.
try:
    from ..obs.metrics import ALLOWED_LABEL_KEYS
except Exception:  # pragma: no cover - only on a broken tree
    ALLOWED_LABEL_KEYS = ("lane", "rung", "engine", "outcome", "bucket",
                          "stage", "nr",
                          "code", "state", "slots", "point", "kind",
                          "mode", "backend", "reason")


@dataclass
class FileContext:
    relpath: str          #: repo-relative, forward slashes
    src: str
    tree: ast.Module
    lines: list[str]

    def line_text(self, node: ast.AST) -> str:
        ln = getattr(node, "lineno", 0)
        if 1 <= ln <= len(self.lines):
            return self.lines[ln - 1].strip()
        return ""

    def in_dir(self, *parts: str) -> bool:
        return self.relpath.startswith(tuple(
            p if p.endswith("/") else p + "/" for p in parts))

    def is_file(self, *names: str) -> bool:
        return any(self.relpath.endswith(n) for n in names)


@dataclass
class Rule:
    id: str
    severity: str
    doc: str
    check: Callable[[FileContext], Iterator[tuple[ast.AST, str]]]
    #: Optional MECHANICAL rewriter (the ``otlint --fix`` seam): yields
    #: (node, replacement source) pairs for violations whose fix is a
    #: pure text substitution — the node's exact source span is
    #: replaced and the fixed file must re-lint clean (the
    #: fixture-pair tests pin that). Rules whose fix needs judgment
    #: (which seam to route through, what deadline to pick) leave this
    #: None: --fix is for rewrites a reviewer would rubber-stamp.
    fixer: Callable[[FileContext],
                    Iterator[tuple[ast.AST, str]]] | None = None
    #: Semantic version, folded into every fingerprint this rule mints.
    #: Bump when a semantics change should invalidate old baseline
    #: entries (they surface as stale, not as silent suppressions).
    version: int = 1


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted-name string of an expression ("jax",
    "self._jax.block_until_ready", "_sibling('faults').fire", ...)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return f"{_dotted(node.value)}.{node.attr}"
    if isinstance(node, ast.Call):
        base = _dotted(node.func)
        args = ",".join(
            repr(a.value) if isinstance(a, ast.Constant) else "?"
            for a in node.args)
        return f"{base}({args})"
    return ""


def _str_prefix(node: ast.AST) -> str:
    """The static string prefix of an expression, if any: a constant, the
    leading constant of an f-string, or of a +-concatenation."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr) and node.values:
        return _str_prefix(node.values[0])
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        return _str_prefix(node.left)
    return ""


def _mentions(node: ast.AST, needle: str) -> bool:
    return any(needle in (getattr(n, "id", "") + getattr(n, "attr", ""))
               for n in ast.walk(node))


# ---------------------------------------------------------------------------
# subprocess-isolate: child processes only via resilience.isolate
# ---------------------------------------------------------------------------

_SPAWN_CALLS = {"os.fork", "os.forkpty", "os.system", "os.popen",
                "pty.fork", "os.spawnv", "os.spawnvp", "os.spawnl",
                "os.spawnlp", "os.posix_spawn"}


def _check_subprocess(ctx: FileContext):
    if ctx.is_file("resilience/isolate.py"):
        return
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.split(".")[0] in ("subprocess",
                                                "multiprocessing"):
                    yield node, (
                        f"bare `import {alias.name}`: child processes go "
                        "through resilience.isolate.run_child (deadline, "
                        "process-group SIGKILL, retry policy, trace "
                        "nesting) — not hand-rolled spawns")
        elif isinstance(node, ast.ImportFrom):
            if (node.module or "").split(".")[0] in ("subprocess",
                                                     "multiprocessing"):
                yield node, (
                    f"bare `from {node.module} import ...`: route child "
                    "processes through resilience.isolate.run_child")
        elif isinstance(node, ast.Call):
            name = _dotted(node.func)
            if name in _SPAWN_CALLS:
                yield node, (
                    f"`{name}()` spawns outside the isolate chokepoint; "
                    "use resilience.isolate.run_child")


# ---------------------------------------------------------------------------
# dispatch-watchdog: raw device dispatch only under a watchdog guard
# ---------------------------------------------------------------------------

#: Receivers that denote the raw jax module (vs a harness backend object,
#: whose block_until_ready IS the guarded seam).
_JAX_RECEIVERS = ("jax", "self._jax", "_jax", "jax.experimental")
_DISPATCH_ATTRS = ("block_until_ready", "device_put")
#: Seam files where the raw call IS the guarded chokepoint (the barrier
#: carries the fault + injected-hang seam itself).
_DISPATCH_SEAM_FILES = ("harness/backends.py",)
#: The lane-executor module: the one place serve/ may run device work
#: off the main thread. Its worker invokes the submitted unit — and the
#: main-thread SIGALRM delivery cannot reach a worker thread, so the
#: invocation is legal ONLY inside the thread-kill-hook guard that gives
#: the watchdog its off-main kill path (fail the future, abandon the
#: worker).
_EXECUTOR_FILES = ("serve/dispatch.py",)


def _is_guard_cm(expr: ast.AST) -> bool:
    """A `with` context expression that arms a watchdog deadline: a call
    whose dotted name ends in `.deadline`/`deadline`, or a wrapper whose
    name says alarm/deadline (root bench's `_stage_alarm`)."""
    if not isinstance(expr, ast.Call):
        return False
    name = _dotted(expr.func)
    tail = name.rsplit(".", 1)[-1]
    return (tail == "deadline" or "alarm" in tail or "deadline" in tail)


def _is_kill_hook_cm(expr: ast.AST) -> bool:
    """A `with` context expression registering the worker thread's
    watchdog kill path (``watchdog.thread_kill_hook(...)``)."""
    if not isinstance(expr, ast.Call):
        return False
    tail = _dotted(expr.func).rsplit(".", 1)[-1]
    return "kill_hook" in tail


def _check_dispatch(ctx: FileContext):
    if ctx.is_file(*_DISPATCH_SEAM_FILES):
        return
    if ctx.is_file(*_EXECUTOR_FILES):
        # The worker seam: a device call runs off the main thread here,
        # where SIGALRM delivery cannot interrupt it — the submitted
        # unit may only be invoked under the thread-kill-hook guard
        # (the expiry path that fails the dispatch future and abandons
        # the wedged worker). An unguarded unit() is a hang with no
        # kill path and no evidence.
        def visit_exec(node, hooked):
            if isinstance(node, ast.With):
                if any(_is_kill_hook_cm(item.context_expr)
                       for item in node.items):
                    hooked = True
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "unit" and not hooked):
                yield node, (
                    "worker-thread `unit()` invocation outside the "
                    "`watchdog.thread_kill_hook` guard: a deadline armed "
                    "inside the unit would expire with no delivery path "
                    "— the waiter blocks forever and the hang leaves no "
                    "kill evidence")
            for child in ast.iter_child_nodes(node):
                yield from visit_exec(child, hooked)

        yield from visit_exec(ctx.tree, False)

    def visit(node, guarded):
        if isinstance(node, ast.With):
            if any(_is_guard_cm(item.context_expr) for item in node.items):
                guarded = True
        if isinstance(node, ast.Call):
            name = _dotted(node.func)
            recv, _, attr = name.rpartition(".")
            if (attr in _DISPATCH_ATTRS and recv in _JAX_RECEIVERS
                    and not guarded):
                yield node, (
                    f"raw `{name}()` outside a watchdog guard: wrap the "
                    "region in `watchdog.deadline(...)` (or route through "
                    "the harness backend barrier seam) so a wedged "
                    "transport becomes a DispatchTimeout, not a hang")
        for child in ast.iter_child_nodes(node):
            yield from visit(child, guarded)

    yield from visit(ctx.tree, False)


# ---------------------------------------------------------------------------
# degrade-chokepoint: demotions only through degrade()
# ---------------------------------------------------------------------------

#: Literal degrade kinds that are not "x->y" arrows.
_DEGRADE_KINDS_EXTRA = ("dispatch-timeout",)
_DEGRADE_PREFIXES = ("quarantined:",)

#: Call names that emit text (the `# degraded` format check only looks at
#: these — a string-method call like startswith("# degraded") is not an
#: emission).
_EMITTER_TAILS = ("print", "line", "write", "emit", "note", "log",
                  "info", "warning", "error")


def _kind_ok(kind: str) -> bool:
    if kind in _DEGRADE_KINDS_EXTRA or kind.startswith(_DEGRADE_PREFIXES):
        return True
    left, arrow, right = kind.partition("->")
    return bool(arrow and left and right and " " not in kind)


def _check_degrade(ctx: FileContext):
    in_degrade_mod = ctx.is_file("resilience/degrade.py")
    for node in ast.walk(ctx.tree):
        # (a) nobody reaches into the ledger's private state
        if (not in_degrade_mod and isinstance(node, ast.Attribute)
                and node.attr == "_EVENTS"):
            yield node, ("direct access to the degrade ledger's private "
                         "state; use degrade()/events()/detail()")
        if not isinstance(node, ast.Call):
            continue
        name = _dotted(node.func)
        # (b) a "# degraded" line not fed by the ledger masquerades as it
        if (not in_degrade_mod
                and name.rsplit(".", 1)[-1] in _EMITTER_TAILS):
            for arg in node.args:
                if (_str_prefix(arg).startswith("# degraded")
                        and not _mentions(arg, "degrade")):
                    yield node, (
                        "emits a `# degraded` line not derived from the "
                        "resilience.degrade ledger — record the demotion "
                        "with degrade() and report events()")
        # (c) degrade() called with a malformed kind literal
        if name.rsplit(".", 1)[-1] == "degrade" and node.args:
            first = node.args[0]
            if (isinstance(first, ast.Constant)
                    and isinstance(first.value, str)
                    and not _kind_ok(first.value)):
                yield node, (
                    f"degrade kind {first.value!r} is not a known form "
                    "(an `from->to` arrow, `dispatch-timeout`, or "
                    "`quarantined:<unit>`) — the ledger's consumers "
                    "parse these")


# ---------------------------------------------------------------------------
# wallclock: no time.time() outside obs/ (timed regions use monotonic
# clocks; epoch time belongs to the tracer and to mtime comparisons)
# ---------------------------------------------------------------------------


def _check_wallclock(ctx: FileContext):
    if ctx.in_dir("obs", "our_tree_tpu/obs"):
        return
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            name = _dotted(node.func)
            if name in ("time.time", "time.time_ns"):
                yield node, (
                    f"`{name}()` reads the wall clock: timed regions and "
                    "budgets use time.monotonic()/perf_counter() (NTP "
                    "steps corrupt durations); epoch time belongs to "
                    "obs.trace (trace.now_us) and to file-mtime "
                    "comparisons")


#: The wallclock rule's mechanical rewrite (`--fix`): the monotonic
#: twin of each wall-clock read. Call sites that genuinely need EPOCH
#: time (event timestamps) belong on ``trace.now_us()`` instead —
#: that is a judgment rewrite, left to the reviewer the finding names.
_WALLCLOCK_FIX = {"time.time": "time.monotonic()",
                  "time.time_ns": "time.monotonic_ns()"}


def _fix_wallclock(ctx: FileContext):
    if ctx.in_dir("obs", "our_tree_tpu/obs"):
        return
    for node in ast.walk(ctx.tree):
        if (isinstance(node, ast.Call) and not node.args
                and not node.keywords):
            new = _WALLCLOCK_FIX.get(_dotted(node.func))
            if new:
                yield node, new


# ---------------------------------------------------------------------------
# trace-attrs: span/detached_span/point/counter/gauge attrs statically
# JSON-serializable
# ---------------------------------------------------------------------------

_TRACE_METHODS = ("span", "detached_span", "point", "counter", "gauge")
_TRACE_RECEIVERS = ("trace", "_trace", "trace_mod", "obstrace",
                    "tr", "t", "tt", "m")


def _json_unsafe(node: ast.AST) -> str | None:
    """The reason an attr value is provably not JSON-clean, or None.
    Names/calls/arithmetic pass (runtime values are the tracer's
    default=repr problem); only structurally-wrong literals flag."""
    if isinstance(node, ast.Constant):
        if isinstance(node.value, bytes):
            return "bytes literal"
        if isinstance(node.value, complex):
            return "complex literal"
        if node.value is Ellipsis:
            return "Ellipsis"
    if isinstance(node, (ast.Set, ast.SetComp)):
        return "set (JSON has no set type)"
    if isinstance(node, ast.Lambda):
        return "lambda"
    for child in ast.iter_child_nodes(node):
        why = _json_unsafe(child)
        if why:
            return why
    return None


def _check_trace_attrs(ctx: FileContext):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not (isinstance(func, ast.Attribute)
                and func.attr in _TRACE_METHODS):
            continue
        recv = _dotted(func.value)
        if not (recv in _TRACE_RECEIVERS or "trace" in recv):
            continue
        for kw in node.keywords:
            if kw.arg is None or kw.value is None:
                continue
            why = _json_unsafe(kw.value)
            if why:
                yield node, (
                    f"trace attr `{kw.arg}` is not statically "
                    f"JSON-serializable ({why}); the tracer would stringify "
                    "it with repr(), making the event unreadable to "
                    "obs.report")


# ---------------------------------------------------------------------------
# fault-points: OT_FAULTS seam names drawn from faults.KNOWN_POINTS
# ---------------------------------------------------------------------------

_FAULT_METHODS = ("fire", "check", "check_lane", "check_backend",
                  "fire_backend", "scoped", "scoped_backend",
                  "scoped_chunk", "fire_chunk", "consume",
                  "remaining", "injected_hang", "injected_slow")


def _check_fault_points(ctx: FileContext):
    if ctx.is_file("resilience/faults.py"):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        func = node.func
        if not (isinstance(func, ast.Attribute)
                and func.attr in _FAULT_METHODS):
            continue
        recv = _dotted(func.value)
        if not ("fault" in recv or "watchdog" in recv):
            continue
        first = node.args[0]
        if (isinstance(first, ast.Constant)
                and isinstance(first.value, str)
                and first.value not in KNOWN_POINTS):
            yield node, (
                f"injection point {first.value!r} is not in "
                f"faults.KNOWN_POINTS {tuple(KNOWN_POINTS)}: an "
                "unregistered seam silently never fires, making fault "
                "CI vacuously green — register it in faults.py first")


# ---------------------------------------------------------------------------
# metrics-labels: registry labels from the fixed allowlist, values
# statically low-cardinality
# ---------------------------------------------------------------------------

_METRIC_METHODS = ("counter", "gauge", "gauge_max", "observe")
#: Keyword args that are the metric's VALUE, not labels ("exemplar" is
#: the bounded tail-exemplar payload, obs/metrics.py — identity-shaped
#: by design, bounded by the per-series exemplar cap, never a series
#: key).
_METRIC_VALUE_KWARGS = ("n", "value", "exemplar")
#: Identifier fragments that statically smell like unbounded
#: cardinality: a label value built from any of these turns the
#: process-global registry into a per-request/per-tenant memory leak
#: (and, for tenant/digest, leaks tenant identity into the /metrics
#: surface). Matched against "_"-split identifier parts, so `lane.idx`
#: passes while `req.id` and `tenant_digest` flag.
_HIGH_CARDINALITY_PARTS = frozenset(
    ("tenant", "digest", "nonce", "uuid", "id", "ids", "req", "request",
     "label", "token", "payload"))


def _high_cardinality_reason(node: ast.AST) -> str | None:
    """Why a label-value expression is provably high-cardinality, or
    None. Constants always pass (a literal is one value); f-strings
    always flag (string-assembly is the request-id idiom); otherwise
    every identifier mentioned is screened against the deny fragments."""
    if isinstance(node, ast.Constant):
        return None
    if isinstance(node, ast.JoinedStr):
        return "f-string label value (per-call string assembly)"
    for n in ast.walk(node):
        for name in (getattr(n, "id", ""), getattr(n, "attr", "")):
            if not name:
                continue
            parts = name.lower().split("_")
            hit = _HIGH_CARDINALITY_PARTS.intersection(parts)
            if hit:
                return f"derived from `{name}` ({sorted(hit)[0]})"
    return None


def _check_metrics_labels(ctx: FileContext):
    if ctx.is_file("obs/metrics.py"):
        return  # the registry's own internals
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not (isinstance(func, ast.Attribute)
                and func.attr in _METRIC_METHODS):
            continue
        recv = _dotted(func.value)
        if "metrics" not in recv:
            continue
        for kw in node.keywords:
            if kw.arg is None:
                yield node, (
                    "metrics call with a **splat: label keys must be "
                    "statically visible so the allowlist check means "
                    "something — spell the labels out")
                continue
            if kw.arg in _METRIC_VALUE_KWARGS:
                continue
            if kw.arg not in ALLOWED_LABEL_KEYS:
                yield node, (
                    f"metrics label key `{kw.arg}` is not in "
                    f"obs.metrics.ALLOWED_LABEL_KEYS "
                    f"{tuple(ALLOWED_LABEL_KEYS)}: labels multiply "
                    "series in a process-global registry — extend the "
                    "allowlist deliberately or drop the label")
                continue
            why = _high_cardinality_reason(kw.value)
            if why:
                yield node, (
                    f"metrics label `{kw.arg}` value looks "
                    f"high-cardinality: {why}. Request ids and tenant "
                    "digests as label values grow the registry without "
                    "bound (and leak identity onto /metrics) — label "
                    "with closed enums, count identity-free")


# ---------------------------------------------------------------------------
# serve-lane-seam: device dispatch in serve/ only through serve/lanes.py
# ---------------------------------------------------------------------------

#: Call-name tails that put bytes on (or read them back from) a device.
#: In serve/, every one of them belongs to the lane seam: a dispatch
#: outside it has no watchdog deadline of its own lane, no health
#: accounting, no failover — a fault there degrades the SERVICE, not a
#: lane, which is exactly the failure mode lanes exist to contain.
#: ``ctr_crypt_words_scattered_multikey`` is the multi-key twin (K
#: stacked schedules, one call) and ``ctr_scattered_words`` the native
#: host-tier dispatch behind it — the host tier has no device but it IS
#: a dispatch (watchdog, health, failover all still apply), so it may
#: not bypass the seam either.
_SERVE_DISPATCH_TAILS = ("ctr_crypt_words_scattered",
                         "ctr_crypt_words_scattered_multikey",
                         "ctr_scattered_words", "ctr_requests_words",
                         "block_until_ready", "device_put")


def _check_serve_lane(ctx: FileContext):
    if not ctx.in_dir("serve", "our_tree_tpu/serve"):
        return
    in_seam = ctx.is_file("serve/lanes.py")
    in_executor = ctx.is_file(*_EXECUTOR_FILES)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _dotted(node.func)
        tail = name.rsplit(".", 1)[-1]
        # Worker threads in serve/ exist ONLY inside the lane executor:
        # a thread spawned anywhere else carries device work (or work
        # that fans into it) outside the guarded entry point — no
        # thread-kill hook, no abandoned-worker accounting, no deadline
        # delivery. This applies to lanes.py too: the seam file owns
        # the DEVICE contact, the executor owns the THREADS.
        if tail == "Thread" and not in_executor:
            yield node, (
                f"`{name}()` spawns a worker thread in serve/ outside "
                "the lane executor (serve/dispatch.py): off-main device "
                "work is legal only on an executor worker, whose "
                "thread-kill hook gives the watchdog a delivery path "
                "(fail the future, abandon the worker)")
            continue
        if in_seam:
            continue
        if tail in _SERVE_DISPATCH_TAILS:
            yield node, (
                f"`{name}()` dispatches to a device from serve/ outside "
                "the lane seam: route the call through serve/lanes.py "
                "(Lane.engine_call) so it gets the lane's watchdog "
                "deadline, health accounting, and bit-exact failover")


# ---------------------------------------------------------------------------
# route-backend-seam: backend contact in route/ only through route/proxy.py;
# the whole routing tier stays device-free
# ---------------------------------------------------------------------------

#: Call tails that open a socket to (or exchange frames with) a backend.
#: In route/, every one of them belongs to the proxy seam: a backend
#: contact outside it has no attempt deadline, no health accounting, no
#: failover — a fault there degrades the ROUTER, not a backend, which
#: is exactly the failure mode the seam exists to contain.
_ROUTE_CONTACT_TAILS = ("open_connection", "create_connection",
                        "read_frame", "encode_frame")
#: The seam files plus the harness entry (route/bench.py drives workers
#: and references engines the way serve/bench.py does — it is the
#: operator tool, not the routing tier). route/fleet.py is seam tier:
#: the replica server + gossip exchange speak the framed wire directly
#: (they ARE transport endpoints), and all per-request backend contact
#: still flows through the proxy it wraps.
_ROUTE_SEAM_FILES = ("route/proxy.py", "route/fleet.py")
_ROUTE_HARNESS_FILES = ("route/bench.py",)


def _check_route_seam(ctx: FileContext):
    if not ctx.in_dir("route", "our_tree_tpu/route"):
        return
    harness = ctx.is_file(*_ROUTE_HARNESS_FILES)
    in_seam = ctx.is_file(*_ROUTE_SEAM_FILES)
    for node in ast.walk(ctx.tree):
        # The routing tier is DEVICE-FREE by construction: a jax import
        # anywhere in route/ (bench included) couples the front-end's
        # availability to a backend toolchain it exists to abstract
        # over — the router must start on any box.
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.split(".")[0] == "jax":
                    yield node, (
                        "`import jax` in route/: the routing tier is "
                        "device-free — engines live behind the backends; "
                        "a router that needs jax cannot front a mixed or "
                        "jax-less fleet")
        elif isinstance(node, ast.ImportFrom):
            if (node.module or "").split(".")[0] == "jax":
                yield node, (
                    "`from jax import ...` in route/: the routing tier "
                    "is device-free (see route-backend-seam)")
        if harness or in_seam or not isinstance(node, ast.Call):
            continue
        name = _dotted(node.func)
        tail = name.rsplit(".", 1)[-1]
        if tail in _ROUTE_CONTACT_TAILS:
            yield node, (
                f"`{name}()` contacts a backend from route/ outside "
                "the proxy seam: route the exchange through "
                "route/proxy.py (Backend.exchange / poll_healthz) so "
                "it gets the attempt deadline, health accounting, and "
                "bit-exact failover")
        elif tail in _SERVE_DISPATCH_TAILS:
            yield node, (
                f"`{name}()` dispatches engine work from route/: the "
                "router never touches engines — backends do; submit "
                "through the proxy instead")


RULES: tuple[Rule, ...] = (
    Rule("subprocess-isolate", "error",
         "Child processes only via resilience.isolate.run_child — no bare "
         "subprocess/multiprocessing/os.fork outside resilience/isolate.py.",
         _check_subprocess),
    Rule("dispatch-watchdog", "error",
         "Raw jax device dispatch (block_until_ready / device_put) only "
         "inside a watchdog.deadline guard or the harness barrier seam; "
         "the lane executor's worker may invoke its unit only under the "
         "watchdog.thread_kill_hook guard (the off-main delivery path).",
         _check_dispatch),
    Rule("degrade-chokepoint", "error",
         "Demotions only through resilience.degrade(): no private-ledger "
         "access, no hand-rolled `# degraded` lines, kinds well-formed.",
         _check_degrade),
    Rule("wallclock", "warning",
         "No time.time()/time_ns() outside obs/ — durations use monotonic "
         "clocks; epoch time is the tracer's and mtime comparisons'. "
         "--fix rewrites to the monotonic twin.",
         _check_wallclock, fixer=_fix_wallclock),
    Rule("trace-attrs", "error",
         "span/detached_span/point/counter/gauge attrs must be statically "
         "JSON-serializable (no bytes/set/lambda/complex literals).",
         _check_trace_attrs),
    Rule("fault-points", "error",
         "String literals passed to faults.fire/check/check_lane/scoped/"
         "consume/remaining and watchdog.injected_hang must be registered "
         "KNOWN_POINTS.",
         _check_fault_points),
    Rule("metrics-labels", "error",
         "obs.metrics label keys must come from ALLOWED_LABEL_KEYS and "
         "label values must be statically low-cardinality (no request "
         "ids, tenant digests, or f-strings) — the registry must never "
         "become an unbounded-cardinality memory leak.",
         _check_metrics_labels),
    Rule("serve-lane-seam", "error",
         "Dispatch in serve/ (scattered-CTR calls incl. the multi-key "
         "seam, the native host tier, block_until_ready, device_put) "
         "only inside serve/lanes.py — the lane seam owns deadlines, "
         "health, and failover; worker threads in serve/ exist only "
         "inside the lane executor (serve/dispatch.py).",
         _check_serve_lane),
    Rule("route-backend-seam", "error",
         "Backend contact in route/ (socket opens, wire frames) only "
         "inside route/proxy.py — the proxy seam owns attempt "
         "deadlines, health, and failover — and the routing tier is "
         "device-free: no jax import anywhere in route/.",
         _check_route_seam),
)


def lint_file(path: str, relpath: str) -> list[Finding]:
    """Run every rule over one file; unparseable files yield one
    finding (a syntax error in the package is itself a violation)."""
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    try:
        tree = ast.parse(src, filename=relpath)
    except SyntaxError as e:
        return [Finding("parse", "error", f"does not parse: {e.msg}",
                        relpath, e.lineno or 0, anchor="syntax-error")]
    ctx = FileContext(relpath, src, tree, src.splitlines())
    out: list[Finding] = []
    for rule in RULES:
        for node, message in rule.check(ctx):
            out.append(Finding(
                rule.id, rule.severity, message, relpath,
                getattr(node, "lineno", 0), anchor=ctx.line_text(node),
                version=rule.version))
    return out


def _walk_py(paths: list[str]) -> list[str]:
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__" and not d.startswith(".")]
                files.extend(os.path.join(dirpath, f)
                             for f in sorted(filenames) if f.endswith(".py"))
        elif p.endswith(".py"):
            files.append(p)
    return sorted(set(files))


def lint_paths(paths: list[str], repo_root: str) -> list[Finding]:
    """Lint every .py under ``paths`` (files or directories), findings
    keyed by repo-root-relative path."""
    out: list[Finding] = []
    for f in _walk_py(paths):
        rel = os.path.relpath(os.path.abspath(f),
                              os.path.abspath(repo_root)).replace(os.sep, "/")
        out.extend(lint_file(f, rel))
    return anchored(out)


# ---------------------------------------------------------------------------
# --fix: apply the rules' mechanical rewrites in place.
# ---------------------------------------------------------------------------


def fix_file(path: str, relpath: str,
             baseline: dict | None = None) -> int:
    """Apply every rule's fixer to one file IN PLACE; returns the
    rewrite count. Replacements splice the flagged node's exact source
    span (``end_lineno``/``end_col_offset``), applied bottom-up so
    earlier edits never shift later spans. Unparseable files are left
    alone (the parse finding stands).

    ``baseline`` (fingerprint -> entry, analysis/baseline.json's
    loaded form) EXEMPTS baselined violations from fixing: a reasoned
    baseline entry is a site where the "violation" is deliberate —
    devlock's epoch-vs-mtime staleness compare, the watchdog report's
    epoch filename — and a mechanical monotonic rewrite there would be
    semantically wrong, not clean. Exemption is per (rule, line): any
    baselined finding of the fixing rule on a line protects that
    line's candidates."""
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    try:
        tree = ast.parse(src, filename=relpath)
    except SyntaxError:
        return 0
    ctx = FileContext(relpath, src, tree, src.splitlines())
    protected: set[tuple[str, int]] = set()
    if baseline:
        for f in anchored(lint_file(path, relpath)):
            if f.fingerprint in baseline:
                protected.add((f.rule, f.line))
    edits: list[tuple] = []
    for rule in RULES:
        if rule.fixer is None:
            continue
        for node, replacement in rule.fixer(ctx):
            if getattr(node, "end_lineno", None) is None:
                continue
            if (rule.id, getattr(node, "lineno", 0)) in protected:
                continue
            edits.append((node.lineno, node.col_offset,
                          node.end_lineno, node.end_col_offset,
                          replacement))
    if not edits:
        return 0
    lines = src.splitlines(keepends=True)
    for l0, c0, l1, c1, new in sorted(edits, reverse=True):
        if l0 == l1:
            line = lines[l0 - 1]
            lines[l0 - 1] = line[:c0] + new + line[c1:]
        else:
            lines[l0 - 1:l1] = [lines[l0 - 1][:c0] + new
                                + lines[l1 - 1][c1:]]
    with open(path, "w", encoding="utf-8") as fh:
        fh.write("".join(lines))
    return len(edits)


def fix_paths(paths: list[str], repo_root: str,
              baseline: dict | None = None) -> dict[str, int]:
    """``otlint --fix`` over files/dirs: {repo-relative path: rewrites}
    for every file actually changed, baselined violations exempted
    (``fix_file``). The contract the fixture-pair tests pin: a fixed
    file re-lints CLEAN for the fixing rule."""
    out: dict[str, int] = {}
    for f in _walk_py(paths):
        rel = os.path.relpath(os.path.abspath(f),
                              os.path.abspath(repo_root)).replace(os.sep, "/")
        n = fix_file(f, rel, baseline=baseline)
        if n:
            out[rel] = n
    return out
