"""The otlint CLI driver: collect findings, apply the baseline, report.

``python -m our_tree_tpu.analysis`` with no arguments lints the package
plus the repo-root ``bench.py`` (the production entry that bare-loads
the resilience modules) and audits the default engine set. The CI
invocation is::

    python -m our_tree_tpu.analysis --baseline analysis/baseline.json \\
        --fail-on-new

which exits 1 on any finding not fingerprint-matched by the committed
baseline — new violations gate, known ones report as suppressed, and
STALE baseline entries (fixed violations) are named so the file cannot
rot. See docs/ANALYSIS.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import astrules, baseline as baseline_mod
from .findings import SEVERITIES


def _repo_root() -> str:
    """The repo root: parent of the our_tree_tpu package directory."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def _default_paths(root: str) -> list[str]:
    paths = [os.path.join(root, "our_tree_tpu")]
    bench = os.path.join(root, "bench.py")
    if os.path.exists(bench):
        paths.append(bench)
    # scripts/ rides the default gate too (ROADMAP carry-over): the
    # operator tools share the repo's seams, so they share its lint —
    # accepted legacy shapes live in analysis/baseline.json with
    # reasons, like every other known finding.
    scripts = os.path.join(root, "scripts")
    if os.path.isdir(scripts):
        paths.append(scripts)
    return paths


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m our_tree_tpu.analysis",
        description="otlint: repo-invariant AST linter + jaxpr auditor "
                    "(docs/ANALYSIS.md)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the our_tree_tpu "
                         "package + repo-root bench.py)")
    ap.add_argument("--baseline", default=None, metavar="PATH",
                    help="suppress findings fingerprint-matched by this "
                         "baseline file (analysis/baseline.json)")
    ap.add_argument("--fail-on-new", action="store_true",
                    help="exit 1 when any non-baselined finding exists "
                         "(the CI gate)")
    ap.add_argument("--write-baseline", default=None, metavar="PATH",
                    help="write the current findings as a baseline, "
                         "preserving reasons from --baseline; new entries "
                         "get a TODO reason the loader rejects until "
                         "justified")
    ap.add_argument("--fix", action="store_true",
                    help="apply the rules' MECHANICAL rewrites in "
                         "place before linting (currently: the "
                         "wallclock rule's time.time()/time_ns() -> "
                         "monotonic twin), then report the post-fix "
                         "state — a fixed file re-lints clean for the "
                         "fixing rule")
    ap.add_argument("--no-ast", action="store_true",
                    help="skip layer 1 (the AST linter)")
    ap.add_argument("--no-jaxpr", action="store_true",
                    help="skip layer 2 (the jaxpr auditor) — the AST layer "
                         "then runs without jax in sight")
    ap.add_argument("--san", action="store_true",
                    help="run the ot-san concurrency auditor (whole-"
                         "program call graph + effect inference: "
                         "loop-stall, lock-await, lock-order, "
                         "thread-ownership — docs/ANALYSIS.md)")
    ap.add_argument("--engines", default=None,
                    help="comma list of engines for the jaxpr audit, or "
                         "'all' (the default): jnp,bitslice plus every "
                         "pallas engine the running jax can trace — "
                         "untraceable pallas engines are skipped with a "
                         "stderr note, not reported as audit errors")
    ap.add_argument("--format", default="text", choices=("text", "json"))
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in astrules.RULES:
            print(f"{rule.id} ({rule.severity}): {rule.doc}")
        from . import sanrules

        for rule in sanrules.RULES:
            print(f"{rule.id} ({rule.severity}): [san v{rule.version}] "
                  f"{rule.doc}")
        from .jaxpr_audit import DEFAULT_ENGINES

        print("constant-time (error): [jaxpr] no gather/dynamic_slice/"
              "scatter indexed by secret-tainted values.")
        print("kernel-transfer (error): [jaxpr] no argument-derived "
              "device_put or host callbacks inside traced kernels.")
        print("dtype-widening (warning): [jaxpr] no avals wider than 32 "
              "bits.")
        print("shape-unroll (error): [jaxpr] traced graph size must not "
              "depend on the batch dim.")
        print(f"default audited engines: {', '.join(DEFAULT_ENGINES)} "
              "+ the pallas engines when the running jax can trace them")
        return 0

    root = _repo_root()
    findings = []
    if args.fix:
        # Baselined violations are EXEMPT from fixing (a reasoned
        # baseline entry marks a deliberate site — mechanically
        # rewriting it would be semantically wrong, e.g. devlock's
        # epoch-vs-mtime staleness compare), so the baseline loads
        # before the rewrites run — and a bare `--fix` with no
        # --baseline flag still protects the COMMITTED baseline's
        # sites (the one place the reasons live; an unprotected
        # default would rewrite exactly the sites the reasons exist
        # for).
        fix_baseline_path = args.baseline or os.path.join(
            root, "analysis", "baseline.json")
        fix_base: dict = {}
        if os.path.exists(fix_baseline_path):
            try:
                fix_base = baseline_mod.load(fix_baseline_path)
            except baseline_mod.BaselineError as e:
                print(f"BASELINE ERROR: {e}", file=sys.stderr)
                return 2
        paths = ([os.path.abspath(p) for p in args.paths]
                 if args.paths else _default_paths(root))
        fixed = astrules.fix_paths(paths, root, baseline=fix_base)
        for rel, n in sorted(fixed.items()):
            print(f"# otlint --fix: {rel}: {n} rewrite(s)",
                  file=sys.stderr)
        print(f"# otlint --fix: {sum(fixed.values())} rewrite(s) in "
              f"{len(fixed)} file(s)", file=sys.stderr)
    if not args.no_ast:
        paths = ([os.path.abspath(p) for p in args.paths]
                 if args.paths else _default_paths(root))
        findings += astrules.lint_paths(paths, root)
    if args.san:
        from . import sanrules

        paths = ([os.path.abspath(p) for p in args.paths]
                 if args.paths else _default_paths(root))
        findings += sanrules.analyze_paths(paths, root)
    if not args.no_jaxpr:
        from . import jaxpr_audit

        engines = "all"
        if args.engines and args.engines != "all":
            engines = tuple(e for e in args.engines.split(",") if e)
        findings += jaxpr_audit.audit(engines)

    # Staleness is judged only over the layers that actually RAN: a
    # `--no-jaxpr` lint must not report the jaxpr entries as fixed,
    # and a run without --san must not condemn the san entries.
    active_layers = set()
    if not args.no_ast:
        active_layers.add("ast")
    if args.san:
        active_layers.add("san")
    if not args.no_jaxpr:
        active_layers.add("jaxpr")

    stale: list[str] = []
    base: dict[str, dict] = {}
    if args.baseline and os.path.exists(args.baseline):
        try:
            base = baseline_mod.load(args.baseline)
        except baseline_mod.BaselineError as e:
            print(f"BASELINE ERROR: {e}", file=sys.stderr)
            return 2
        stale = [fp for fp in baseline_mod.apply(findings, base)
                 if fp.split(":", 1)[0] in active_layers]

    if args.write_baseline:
        n = baseline_mod.write(args.write_baseline, findings, base)
        print(f"# wrote {n} baseline entr{'y' if n == 1 else 'ies'} to "
              f"{args.write_baseline}", file=sys.stderr)

    new = [f for f in findings if not f.baselined]
    known = [f for f in findings if f.baselined]
    order = {s: i for i, s in enumerate(SEVERITIES)}
    key = lambda f: (order.get(f.severity, 9), f.path, f.line, f.rule)

    if args.format == "json":
        print(json.dumps({
            "new": [f.to_json() for f in sorted(new, key=key)],
            "baselined": [f.to_json() for f in sorted(known, key=key)],
            "stale_baseline": stale,
        }, indent=2))
    else:
        for f in sorted(new, key=key) + sorted(known, key=key):
            print(f.render())
        for fp in stale:
            entry = base.get(fp, {})
            print(f"# stale baseline entry {fp} "
                  f"({entry.get('location', '?')}, {entry.get('rule', '?')})"
                  " — the violation is gone; delete the entry",
                  file=sys.stderr)
        print(f"# otlint: {len(new)} new finding(s), {len(known)} "
              f"baselined, {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'}", file=sys.stderr)

    if new and args.fail_on_new:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
