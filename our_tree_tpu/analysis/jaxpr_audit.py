"""Layer 2: the jaxpr auditor — trace the crypto entry points, walk the
graph, taint-check the lookups.

The paper's phase-split design stays *correct and constant-time* on TPU
only as long as the kernels keep properties nothing type-checks:

* **Constant time.** The classic GPU-AES formulation leans on
  data-dependent T-table lookups (arxiv 1902.05234) — a timing channel
  on any hardware with an addressed memory path. The bitsliced engines
  exist precisely to avoid it; this auditor proves they still do. A
  taint analysis seeded from the key/plaintext arguments propagates
  through every equation; a ``gather``/``dynamic_slice``/``scatter``
  whose *index* operand is tainted is a secret-dependent lookup.
  Constant-index permutations (bitslice's ShiftRows ``x[SR_PERM]``) and
  iota-derived addressing stay untainted and pass.

* **No silent transfers.** A ``device_put`` of an argument-derived value
  mid-kernel, or any host callback, serializes the data-parallel phase
  through the host. Constant staging (closed-over table constants) is
  expected and exempt.

* **No dtype widening.** Avals wider than 32 bits mean an accidental
  x64 promotion — 2x HBM on every stream for a cipher defined on u8/u32.

* **No shape-specialized structure.** Each entry is traced at two batch
  sizes; if the equation count differs, Python-level code is unrolling
  over the data axis — the per-size recompile-storm hazard (one compile
  per shape is JAX's contract; O(N) graph growth per shape is not).

jax is imported lazily and pinned to CPU (``JAX_PLATFORMS``): auditing
is structural, runs in CI without an accelerator, and must never touch
a possibly-wedged device tunnel.
"""

from __future__ import annotations

import os

from .findings import Finding

#: The two engines every audit covers: the correctness oracle and the
#: TPU throughput circuit — the pair the constant-time story is really
#: about.
DEFAULT_ENGINES = ("jnp", "bitslice")

#: The Pallas kernel engines (models/aes.py registration order). Audited
#: by default too — via ``resolve_engines`` — wherever the running jax
#: can trace ``pallas_call`` at all (the PR-4 follow-up: "audit the
#: Pallas engines by default"); on a runtime that cannot (older jax
#: without the vma-carrying ShapeDtypeStruct), they are SKIPPED with a
#: stderr note rather than reported as audit-errors: the blindness is a
#: property of the host's jax, not of the entry points, and a baseline
#: entry for it would go stale the moment the runtime is upgraded.
PALLAS_ENGINES = ("pallas", "pallas-gt", "pallas-gt-bp", "pallas-dense",
                  "pallas-dense-bp")

_PALLAS_TRACEABLE: bool | None = None


def pallas_traceable() -> bool:
    """Can this runtime trace the Pallas engines? Probed once, by
    tracing (never executing) the smallest kernel entry."""
    global _PALLAS_TRACEABLE
    if _PALLAS_TRACEABLE is None:
        try:
            import jax
            import numpy as np

            from ..models import aes

            w = np.zeros((32, 4), np.uint32)
            rk = np.zeros(44, np.uint32)
            jax.make_jaxpr(
                lambda ww, kk: aes.ecb_encrypt_words(ww, kk, 10,
                                                     "pallas"))(w, rk)
            _PALLAS_TRACEABLE = True
        except Exception as e:  # noqa: BLE001 - the probe IS the question
            import sys

            print(f"# jaxpr audit: pallas engines not traceable under "
                  f"this jax ({type(e).__name__}: {str(e)[:120]}); "
                  f"auditing without them", file=sys.stderr)
            _PALLAS_TRACEABLE = False
    return _PALLAS_TRACEABLE


def resolve_engines(spec) -> tuple:
    """``"all"`` -> DEFAULT_ENGINES + the Pallas engines the runtime can
    trace; any other iterable passes through unchanged."""
    if spec == "all":
        return DEFAULT_ENGINES + (PALLAS_ENGINES if pallas_traceable()
                                  else ())
    return tuple(spec)

#: primitive -> which invar positions are *index* operands.
_INDEXED = {
    "gather": lambda n: (1,),
    "dynamic_slice": lambda n: range(1, n),
    "dynamic_update_slice": lambda n: range(2, n),
    "scatter": lambda n: (1,),
    "scatter-add": lambda n: (1,),
    "scatter-mul": lambda n: (1,),
    "scatter-min": lambda n: (1,),
    "scatter-max": lambda n: (1,),
    "take": lambda n: (1,),
}

_CALLBACKS = ("pure_callback", "io_callback", "debug_callback", "callback")

#: Sub-jaxpr invar mapping is positional for these primitives (cond's
#: branches take invars[1:]); anything else gets the conservative
#: any-tainted-in -> all-tainted-in treatment.
_POSITIONAL = ("pjit", "closed_call", "core_call", "scan", "xla_call",
               "custom_jvp_call", "custom_vjp_call", "remat", "checkpoint")


class _EntryAudit:
    """Taint walk + structural checks over one traced entry point."""

    def __init__(self, entry_name: str):
        self.entry = entry_name
        self.findings: list[Finding] = []
        self._flagged: set[tuple[str, str]] = set()
        self.eqn_count = 0

    # -- findings ----------------------------------------------------------
    def _add(self, rule: str, severity: str, prim: str, message: str):
        if (rule, prim) in self._flagged:
            return  # one finding per (rule, primitive) per entry
        self._flagged.add((rule, prim))
        self.findings.append(Finding(
            rule, severity, f"{self.entry}: {message}",
            path="<jaxpr>", anchor=f"{self.entry}:{prim}", layer="jaxpr"))

    def _where(self, eqn) -> str:
        try:
            from jax._src import source_info_util
            fr = source_info_util.user_frame(eqn.source_info)
            if fr is not None:
                parts = fr.file_name.replace(os.sep, "/").rsplit("/", 3)
                return f" at {'/'.join(parts[-2:])}:{fr.start_line}"
        except Exception:
            pass
        return ""

    # -- the walk ----------------------------------------------------------
    def walk(self, closed, in_taint: list[bool]) -> list[bool]:
        """Walk ``closed`` (a ClosedJaxpr) with per-invar taint; returns
        per-outvar taint. Constvars are untainted (closed-over tables)."""
        import jax

        jaxpr = closed.jaxpr
        taint: dict[int, bool] = {}

        def get(v) -> bool:
            return (False if isinstance(v, jax.core.Literal)
                    else taint.get(id(v), False))

        def put(v, t: bool) -> None:
            if not isinstance(v, jax.core.Literal):
                taint[id(v)] = t

        for v, t in zip(jaxpr.invars, in_taint):
            put(v, t)
        for v in jaxpr.constvars:
            put(v, False)

        for eqn in jaxpr.eqns:
            self.eqn_count += 1
            prim = eqn.primitive.name
            ins = [get(v) for v in eqn.invars]
            any_in = any(ins)

            idx_of = _INDEXED.get(prim)
            if idx_of is not None:
                if any(ins[i] for i in idx_of(len(eqn.invars))):
                    self._add(
                        "constant-time", "error", prim,
                        f"data-dependent `{prim}` indexed by a "
                        f"secret-tainted value{self._where(eqn)} — a "
                        "memory-address timing channel (the T-table "
                        "hazard); use a circuit/bitsliced formulation")
            elif prim == "device_put":
                if any_in:
                    self._add(
                        "kernel-transfer", "error", prim,
                        f"argument-derived `device_put` inside the traced "
                        f"kernel{self._where(eqn)} — a host<->device "
                        "transfer that serializes the parallel phase "
                        "(constant table staging is exempt)")
            elif any(prim.startswith(cb) for cb in _CALLBACKS):
                self._add(
                    "kernel-transfer", "error", prim,
                    f"host callback `{prim}` inside the traced "
                    f"kernel{self._where(eqn)}")

            for v in eqn.outvars:
                aval = getattr(v, "aval", None)
                dt = getattr(aval, "dtype", None)
                if (dt is not None and dt.kind in "iuf"
                        and dt.itemsize > 4):
                    self._add(
                        "dtype-widening", "warning", str(dt),
                        f"`{prim}` produces {dt}{self._where(eqn)} — "
                        "widening past 32 bits doubles HBM traffic for a "
                        "cipher defined on u8/u32 (check for x64 "
                        "promotion)")

            out_taint = self._sub_jaxprs(eqn, ins)
            if out_taint is None:
                out_taint = [any_in] * len(eqn.outvars)
            for v, t in zip(eqn.outvars, out_taint):
                put(v, t)

        return [get(v) for v in jaxpr.outvars]

    def _sub_jaxprs(self, eqn, ins: list[bool]):
        """Recurse into any sub-jaxpr params; returns eqn out-taint when
        derivable, else None (caller applies the conservative rule)."""
        prim = eqn.primitive.name
        subs = []
        for val in eqn.params.values():
            if hasattr(val, "jaxpr") and hasattr(val, "consts"):
                subs.append(val)  # ClosedJaxpr
            elif isinstance(val, (list, tuple)):
                subs.extend(v for v in val
                            if hasattr(v, "jaxpr") and hasattr(v, "consts"))
        if not subs:
            return None
        if prim == "scan" and len(subs) == 1:
            return self._scan_fixpoint(eqn, subs[0], ins)
        results = []
        for sub in subs:
            n = len(sub.jaxpr.invars)
            if prim in _POSITIONAL and len(ins) == n:
                sub_in = list(ins)
            elif prim == "cond" and len(ins) == n + 1:
                sub_in = list(ins[1:])
            else:
                sub_in = [any(ins)] * n
            results.append(self.walk(sub, sub_in))
        out = results[0]
        if (len(subs) == 1 and prim in _POSITIONAL + ("cond",)
                and len(out) == len(eqn.outvars)):
            return out
        flat_any = any(t for r in results for t in r) or any(ins)
        return [flat_any] * len(eqn.outvars)

    def _scan_fixpoint(self, eqn, sub, ins: list[bool]):
        """Taint a scan body to FIXPOINT on the carry: a secret that
        enters the loop state only after iteration 1 (carry-out feeding
        carry-in) must still taint lookups indexed by the carry — a
        single positional walk would audit the body under the *initial*
        carry's taint only and miss exactly the secret-evolves-the-state
        shape RC4's PRGA has. The loop monotonically adds taint to the
        carry slots, so it terminates in <= num_carry + 1 walks; the
        body's eqn count is booked once (re-walks rewind the counter —
        the shape-unroll comparison must not depend on taint iterations).
        """
        num_consts = eqn.params.get("num_consts", 0)
        num_carry = eqn.params.get("num_carry", 0)
        n = len(sub.jaxpr.invars)
        sub_in = list(ins) if len(ins) == n else [any(ins)] * n
        while True:
            count_before = self.eqn_count
            out = self.walk(sub, sub_in)  # body outvars = carry + ys
            changed = False
            for i in range(min(num_carry, len(out))):
                j = num_consts + i
                if j < len(sub_in) and out[i] and not sub_in[j]:
                    sub_in[j] = True
                    changed = True
            if not changed:
                return (out if len(out) == len(eqn.outvars)
                        else [any(out) or any(ins)] * len(eqn.outvars))
            self.eqn_count = count_before


def _flat_secret_mask(args, secret_positions) -> list[bool]:
    """Per-flat-invar secret mask from per-argument secret positions."""
    import jax

    mask: list[bool] = []
    for i, a in enumerate(args):
        leaves = jax.tree_util.tree_leaves(a)
        mask.extend([i in secret_positions] * len(leaves))
    return mask


def _entries(engines):
    """(name, fn, args_builder(nblocks), secret_arg_positions) for every
    audited public entry point. ``nblocks`` parameterizes the batch dim so
    the shape-specialization check can trace at two sizes."""
    import numpy as np

    from ..aead import gcm as aead_gcm
    from ..models import aes, arc4, rc4
    from ..ops import bitslice

    NR, RK_WORDS = 10, 44  # AES-128

    def w(n):
        return np.zeros((n, 4), np.uint32)

    def rk(_n):
        return np.zeros(RK_WORDS, np.uint32)

    def iv(_n):
        return np.zeros(4, np.uint32)

    def rk_stack(_n):  # the fixed-K stacked schedules (serve key slots)
        return np.zeros((4, RK_WORDS), np.uint32)

    def slots(n):  # per-block key-slot indices — PUBLIC batch layout
        return np.zeros(n, np.uint32)

    def hmat(_n):  # one mul-by-H GHASH matrix — KEY-DERIVED (secret)
        return np.zeros((128, 128), np.uint32)

    def hmat_stack(_n):  # the fused dispatch's (K, 128, 128) H stack
        return np.zeros((4, 128, 128), np.uint32)

    def keep(n):  # per-row segment-reset mask — PUBLIC batch layout
        return np.ones(n, np.uint32)

    out = []
    for eng in engines:
        out += [
            (f"aes-ecb-enc[{eng}]",
             lambda ww, kk, e=eng: aes.ecb_encrypt_words(ww, kk, NR, e),
             (w, rk), {0, 1}),
            (f"aes-ecb-dec[{eng}]",
             lambda ww, kk, e=eng: aes.ecb_decrypt_words(ww, kk, NR, e),
             (w, rk), {0, 1}),
            (f"aes-ctr[{eng}]",
             lambda ww, cc, kk, e=eng: aes.ctr_crypt_words(ww, cc, kk,
                                                           NR, e),
             (w, iv, rk), {0, 2}),  # the counter/nonce is public
            # The serve dispatch seam: CTR with per-block explicit
            # counters (many requests' streams concatenated —
            # serve/batcher.py). Its shape-unroll cleanliness at two
            # batch sizes is the bucket ladder's zero-recompile
            # contract, auditable without running a server.
            (f"aes-ctr-scattered[{eng}]",
             lambda ww, cc, kk, e=eng: aes.ctr_crypt_words_scattered(
                 ww, cc, kk, NR, e),
             (w, w, rk), {0, 2}),  # counters derive from public nonces
            # The MULTI-KEY serve seam: K stacked schedules + a
            # per-block key-slot vector (serve/batcher.py's rung-packer
            # dispatch shape). The slot vector is PUBLIC — batch layout,
            # never key/payload bytes — so the schedule gather it feeds
            # (rks[key_slots] / the masked-select reconstruction) must
            # audit untainted: a constant-time finding HERE would mean
            # key-dependent addressing leaked into the shared dispatch.
            (f"aes-ctr-scattered-multikey[{eng}]",
             lambda ww, cc, ks, sl, e=eng:
                 aes.ctr_crypt_words_scattered_multikey(ww, cc, ks, sl,
                                                        NR, e),
             (w, w, rk_stack, slots), {0, 2}),  # slot vector public
            (f"aes-cbc-dec[{eng}]",
             lambda ww, vv, kk, e=eng: aes.cbc_decrypt_words(ww, vv, kk,
                                                             NR, e),
             (w, iv, rk), {0, 2}),
            # The parallel CBC-decrypt serve seam (ot-aead): the
            # scattered multikey decrypt core under the PREV-stream XOR.
            # The prev stream (arg 1) is ciphertext-derived — secret;
            # the slot vector stays public batch layout.
            (f"aes-cbc-dec-scattered-multikey[{eng}]",
             lambda ww, pp, ks, sl, e=eng:
                 aes.cbc_decrypt_words_scattered_multikey(ww, pp, ks, sl,
                                                          NR, e),
             (w, w, rk_stack, slots), {0, 1, 2}),
            # The fused GCM dispatch (aead/gcm.py): scattered CTR +
            # segmented Horner GHASH in one program, both directions
            # (distinct compiled programs — the static direction arg).
            # Secret: payload words, the schedule stack, the mul-by-H
            # matrices (key-derived), and the AAD-prefix inject states;
            # public: counters, the slot vector, the seg_keep mask.
            # GHASH is taint-SENSITIVE by construction here: the mul-by-H
            # formulation is pure XOR/AND matvec, so a secret-indexed
            # lookup in this entry is a REAL finding (docs/ANALYSIS.md).
            (f"aes-gcm-fused-seal[{eng}]",
             lambda ww, cc, ks, sl, hm, inj, kp, e=eng:
                 aead_gcm.gcm_crypt_ghash_words(ww, cc, ks, sl, hm, inj,
                                                kp, NR, e, aead_gcm.SEAL),
             (w, w, rk_stack, slots, hmat_stack, w, keep), {0, 2, 4, 5}),
            (f"aes-gcm-fused-open[{eng}]",
             lambda ww, cc, ks, sl, hm, inj, kp, e=eng:
                 aead_gcm.gcm_crypt_ghash_words(ww, cc, ks, sl, hm, inj,
                                                kp, NR, e, aead_gcm.OPEN),
             (w, w, rk_stack, slots, hmat_stack, w, keep), {0, 2, 4, 5}),
            (f"aes-cfb-dec[{eng}]",
             lambda ww, vv, kk, e=eng: aes.cfb128_decrypt_words(ww, vv, kk,
                                                                NR, e),
             (w, iv, rk), {0, 2}),
        ]
    # The chained encrypt modes run the fused T-table scan body regardless
    # of engine (models/aes.py registration note) — audited once.
    out += [
        ("aes-cbc-enc[scan]",
         lambda ww, vv, kk: aes.cbc_encrypt_words(ww, vv, kk, NR),
         (w, iv, rk), {0, 2}),
        ("aes-cfb-enc[scan]",
         lambda ww, vv, kk: aes.cfb128_encrypt_words(ww, vv, kk, NR),
         (w, iv, rk), {0, 2}),
        # RC4: prep is the sequential phase (its PRGA is state-indexed by
        # definition — the audit documents it, the baseline reasons it);
        # crypt is the data-parallel XOR phase and MUST come out clean —
        # that cleanliness is the paper's phase-split story.
        ("rc4-prep[scan]",
         lambda st: arc4.keystream_scan(st, 128),
         (lambda n: (np.uint32(0), np.uint32(0),
                     np.zeros(256, np.uint32)),), {0}),
        ("rc4-crypt[xor]",
         arc4.crypt,
         (lambda n: np.zeros(16 * n, np.uint8),
          lambda n: np.zeros(16 * n, np.uint8)), {0, 1}),
        ("rc4-fused[scan]",
         rc4._fused_scan,
         (lambda n: (np.uint32(0), np.uint32(0),
                     np.zeros(256, np.uint32)),
          lambda n: np.zeros(16 * n, np.uint32)), {0, 1}),
        # The SERVED RC4 seam (serve/session.py): the batched PRGA
        # prefetch entry — n sessions' scans in one vmapped dispatch on
        # the lane wire layout — carries the same secret-indexed swaps
        # as rc4-prep (same baseline reason: the PRGA is state-indexed
        # by definition, confined to the keystream phase), and the
        # session XOR phase on the packed word layout MUST audit clean:
        # key-obliviousness is what lets many sessions' chunks coalesce
        # into one shared dispatch (the paper's phase-split story,
        # restated at the serve boundary).
        ("rc4-prep-batched[vmap]",
         lambda mm, xy: arc4.prep_batch_words(mm, xy, 64),
         (lambda n: np.zeros(256 * n, np.uint32),
          lambda n: np.zeros(2 * n, np.uint32)), {0, 1}),
        ("rc4-xor[words]",
         arc4.xor_words,
         (lambda n: np.zeros(4 * n, np.uint32),
          lambda n: np.zeros(4 * n, np.uint32)), {0, 1}),
        # The bitsliced kernels audited directly (not only through the
        # mode dispatchers): the acceptance bar for the whole layer.
        ("bitslice-enc[kernel]",
         lambda ww, kk: bitslice.encrypt_words(ww, kk, NR),
         (w, rk), {0, 1}),
        ("bitslice-dec[kernel]",
         lambda ww, kk: bitslice.decrypt_words(ww, kk, NR),
         (w, rk), {0, 1}),
        # The standalone GHASH kernel and the traced constant-time tag
        # compare (aead/gcm.py) — taint-sensitive entries: the mul-by-H
        # bit-matrix formulation exists precisely so these contain no
        # memory indirection at all (the byte-table GHASH variant is
        # host-only for the same reason, ops/gf.py module docstring);
        # a secret-indexed lookup here is a REAL finding.
        ("ghash[horner]",
         lambda ww, hm: aead_gcm.ghash_words(ww, hm),
         (w, hmat), {0, 1}),
        ("gcm-tag-eq[kernel]",
         lambda a, b: aead_gcm.tag_eq_words(a, b),
         (iv, iv), {0, 1}),
    ]
    return out


#: The two batch sizes the shape-specialization check compares. Multiples
#: of 32 blocks: the bitsliced lane packing and the scan unroll factors
#: both divide them, so a remainder-handling eqn can't alias as "the
#: graph grew with N".
_N_BASE, _N_ALT = 32, 64


def audit(engines=DEFAULT_ENGINES) -> list[Finding]:
    """Trace and audit every entry; returns the combined findings.

    An entry that fails to trace is itself a finding (``audit-error``):
    the auditor going blind on an entry point must fail CI, not pass it.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from ..utils.platform import pin_cpu_if_requested

    pin_cpu_if_requested()
    import jax

    engines = resolve_engines(engines)
    findings: list[Finding] = []
    for name, fn, builders, secrets in _entries(tuple(engines)):
        try:
            args = tuple(b(_N_BASE) for b in builders)
            closed = jax.make_jaxpr(fn)(*args)
            au = _EntryAudit(name)
            au.walk(closed, _flat_secret_mask(args, secrets))
            findings.extend(au.findings)

            alt = _EntryAudit(name)
            alt_args = tuple(b(_N_ALT) for b in builders)
            alt.walk(jax.make_jaxpr(fn)(*alt_args),
                     _flat_secret_mask(alt_args, secrets))
            if alt.eqn_count != au.eqn_count:
                findings.append(Finding(
                    "shape-unroll", "error",
                    f"{name}: traced graph size depends on the batch dim "
                    f"({au.eqn_count} eqns at N={_N_BASE} vs "
                    f"{alt.eqn_count} at N={_N_ALT}) — Python-level "
                    "unrolling over data; every size recompiles an O(N) "
                    "graph (recompile storm)",
                    path="<jaxpr>", anchor=f"{name}:shape", layer="jaxpr"))
        except Exception as e:  # noqa: BLE001 - any trace failure is data
            findings.append(Finding(
                "audit-error", "error",
                f"{name}: entry failed to trace "
                f"({type(e).__name__}: {str(e)[:200]}) — the auditor is "
                "blind on this entry; fix the entry or the audit registry",
                path="<jaxpr>", anchor=f"{name}:trace", layer="jaxpr"))
    return findings


def audit_fn(name: str, fn, args, secret_positions) -> list[Finding]:
    """Audit one callable directly (tests / ad-hoc use): trace ``fn`` at
    ``args`` with ``secret_positions`` (argument indices) tainted."""
    import jax

    closed = jax.make_jaxpr(fn)(*args)
    au = _EntryAudit(name)
    au.walk(closed, _flat_secret_mask(args, set(secret_positions)))
    return au.findings
