"""ot-san layer: concurrency rules over the whole-program call graph.

Three rule families consume ``callgraph.Graph`` (see that module for
the effect model); findings ride the same fingerprint/baseline
machinery as the AST and jaxpr layers, under layer id ``"san"``:

* **loop-stall** (error) — a call site inside a coroutine (or a sync
  function the loop enters via ``call_soon*``) whose callee
  transitively reaches a blocking primitive with no executor hop on
  the path.  The finding lands at the TOP loop frame — the exact call
  to wrap in ``asyncio.to_thread(...)`` or route through the lane
  executor seam — and the message carries the witness chain
  (``incidentz -> bundle_index -> open()``).  Deeper sync frames are
  not re-flagged: one bug, one fix site, one finding.

* **lock-await** / **lock-order** (error / warning) — the
  lock-discipline family.  ``lock-await`` flags an ``await`` while a
  ``threading.Lock`` is held (the loop suspends, every other thread
  contending that lock parks behind a coroutine that may not resume
  for a full scheduler turn) and the sync ``with`` on an
  ``asyncio.Lock`` (a runtime type error waiting to fire).
  ``lock-order`` builds the acquisition-order graph over ``with
  lock:`` nesting — including acquisitions made by callees while a
  lock is held — and reports each strongly-connected component of ≥2
  locks as a potential deadlock.  Lock identity is ``(Class, attr)``
  or ``(module, NAME)``: two *instances* of one class share an
  identity, so a cycle through a single class attribute may be
  instance-disjoint in practice — that is what the baseline reason is
  for.  Self-edges (re-acquiring the identity already held) are not
  reported, for the same instance-ambiguity reason.

* **thread-ownership** (error) — a class attribute or module global
  written from BOTH a loop-affine and a thread-affine context, where
  not every write is under a thread lock, must either flow through an
  allowlisted seam (metrics registry, queue, ``_notify_change``, the
  journal — i.e. stop being a raw attribute write) or carry a
  ``# ot-san: owner=<seam>`` annotation naming the seam that makes
  the sharing deliberate.  The annotation rides the write line or the
  attribute's ``__init__`` assignment; a malformed ``# ot-san:``
  comment is itself a finding (a typo must not silently waive the
  rule).  ``__init__`` writes are construction, not sharing, and are
  exempt.

Anchors are line-shift stable: call/await findings anchor on the
stripped source text of the flagged line (like the AST layer);
lock-order anchors on the canonical cycle member set; thread-ownership
anchors on the qualified attribute name.
"""

from __future__ import annotations

from dataclasses import dataclass

from . import callgraph
from .findings import Finding, anchored


@dataclass(frozen=True)
class SanRule:
    id: str
    severity: str
    doc: str
    version: int = 1


RULES = (
    SanRule(
        "loop-stall", "error",
        "no coroutine (or loop-entered sync function) may transitively "
        "reach a blocking primitive without an executor hop — blocking "
        "work crosses asyncio.to_thread / run_in_executor / the "
        "LaneExecutor seam."),
    SanRule(
        "lock-await", "error",
        "no `await` while a threading.Lock is held across the "
        "suspension, and no sync `with` on an asyncio.Lock."),
    SanRule(
        "lock-order", "warning",
        "the lock acquisition-order graph over `with lock:` nesting "
        "(including callee acquisitions) must be acyclic; each cycle "
        "is a potential deadlock."),
    SanRule(
        "thread-ownership", "error",
        "state mutated from both loop-affine and thread-affine "
        "contexts must be lock-protected on every write or carry a "
        "`# ot-san: owner=<seam>` annotation naming the designated "
        "seam (metrics registry, queue, _notify_change, journal)."),
)

_BY_ID = {r.id: r for r in RULES}

#: Modules that ARE the designated cross-thread seams: their internal
#: writes implement the synchronization the ownership rule points
#: everyone else at, so the rule does not recurse into them.
SEAM_MODULES = frozenset({
    "our_tree_tpu.obs.metrics",
    "our_tree_tpu.resilience.journal",
})


def _line_text(g: callgraph.Graph, fn: callgraph.Func, lineno: int) -> str:
    mod = g.modules.get(fn.module)
    if mod is not None and 1 <= lineno <= len(mod.lines):
        return mod.lines[lineno - 1].strip()
    return ""


def _mk(rule_id: str, message: str, path: str, line: int,
        anchor: str) -> Finding:
    r = _BY_ID[rule_id]
    return Finding(r.id, r.severity, message, path, line,
                   anchor=anchor, layer="san", version=r.version)


# --------------------------------------------------------------------------
# loop-stall
# --------------------------------------------------------------------------

def _loop_stall(g: callgraph.Graph) -> list[Finding]:
    out = []
    for fn in g.funcs:
        if not fn.loop_root:
            continue
        flagged: set[int] = set()
        for e in fn.edges:
            if e.kind != "call" or e.lineno in flagged:
                continue
            chain = None
            if e.prim is not None:
                chain = e.prim
            elif e.target is not None and not e.target.is_async \
                    and e.target.blocking and not e.target.absorb:
                chain = e.target.block_chain()
            if chain is None:
                continue
            flagged.add(e.lineno)
            out.append(_mk(
                "loop-stall",
                f"{fn.short()} runs on the event loop but "
                f"'{e.label}' reaches blocking {chain}; wrap the call "
                "in asyncio.to_thread(...) / loop.run_in_executor or "
                "route it through the lane-executor seam",
                fn.relpath, e.lineno, _line_text(g, fn, e.lineno)))
    return out


# --------------------------------------------------------------------------
# lock-await
# --------------------------------------------------------------------------

def _lock_await(g: callgraph.Graph) -> list[Finding]:
    out = []
    for fn in g.funcs:
        for lock, lineno in fn.awaits_under:
            out.append(_mk(
                "lock-await",
                f"{fn.short()} awaits while thread lock {lock} is "
                "held — the loop suspends inside the critical section "
                "and every thread contending the lock parks behind a "
                "coroutine; shrink the section or switch to "
                "asyncio.Lock",
                fn.relpath, lineno, _line_text(g, fn, lineno)))
        for lock, lineno in fn.sync_with_alock:
            out.append(_mk(
                "lock-await",
                f"{fn.short()} enters asyncio lock {lock} with a sync "
                "'with' — asyncio.Lock only supports 'async with'; "
                "this raises at runtime",
                fn.relpath, lineno, _line_text(g, fn, lineno)))
    return out


# --------------------------------------------------------------------------
# lock-order
# --------------------------------------------------------------------------

def _lock_order(g: callgraph.Graph) -> list[Finding]:
    # transitive acquire sets (call edges only: a hop's unit runs on
    # another thread and creates no wait-for edge at the submit site)
    direct: dict[int, set[str]] = {}
    for fn in g.funcs:
        direct[id(fn)] = {a.lock_id for a in fn.acquires}
    trans = {k: set(v) for k, v in direct.items()}
    changed = True
    while changed:
        changed = False
        for fn in g.funcs:
            mine = trans[id(fn)]
            for e in fn.edges:
                if e.kind == "call" and e.target is not None:
                    extra = trans.get(id(e.target), ())
                    if not mine.issuperset(extra):
                        mine.update(extra)
                        changed = True
    # ordering edges with witnesses
    edges: dict[tuple[str, str], tuple[str, int, str]] = {}

    def _edge(a: str, b: str, relpath: str, lineno: int, how: str):
        if a == b:
            return  # instance-ambiguous self-edge (see module docstring)
        edges.setdefault((a, b), (relpath, lineno, how))

    for fn in g.funcs:
        for acq in fn.acquires:
            for held in acq.under:
                _edge(held, acq.lock_id, fn.relpath, acq.lineno,
                      f"{fn.short()} acquires directly")
        for e in fn.edges:
            if e.kind != "call" or e.target is None or not e.under_locks:
                continue
            for m in trans.get(id(e.target), ()):
                for held in e.under_locks:
                    _edge(held, m, fn.relpath, e.lineno,
                          f"{fn.short()} calls {e.target.short()}")
    # SCCs of the lock digraph (iterative Tarjan)
    adj: dict[str, list[str]] = {}
    for (a, b) in edges:
        adj.setdefault(a, []).append(b)
        adj.setdefault(b, [])
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    sccs: list[list[str]] = []
    counter = [0]

    def _tarjan(root: str):
        work = [(root, iter(adj[root]))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(adj[w])))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                sccs.append(comp)

    for v in sorted(adj):
        if v not in index:
            _tarjan(v)

    out = []
    for comp in sccs:
        if len(comp) < 2:
            continue
        members = sorted(comp)
        witness = sorted(
            (f"{a} -> {b} ({w[0]}:{w[1]}, {w[2]})", w)
            for (a, b), w in edges.items()
            if a in comp and b in comp)
        path, line = (witness[0][1][0], witness[0][1][1]) if witness \
            else ("<lock-graph>", 0)
        detail = "; ".join(t for t, _ in witness[:4])
        out.append(_mk(
            "lock-order",
            f"lock-order cycle among {{{', '.join(members)}}} — "
            f"potential deadlock: {detail}",
            path, line, "cycle:" + ",".join(members)))
    return out


# --------------------------------------------------------------------------
# thread-ownership
# --------------------------------------------------------------------------

def _thread_ownership(g: callgraph.Graph) -> list[Finding]:
    out = []
    for relpath, lineno in g.ann_malformed:
        mod = next((m for m in g.modules.values() if m.relpath == relpath),
                   None)
        text = (mod.lines[lineno - 1].strip()
                if mod and 1 <= lineno <= len(mod.lines) else "")
        out.append(_mk(
            "thread-ownership",
            "malformed '# ot-san:' annotation — the grammar is "
            "'# ot-san: owner=<seam>' / '# ot-san: absorb=<tag>' with "
            "the value in [A-Za-z0-9._:-]+",
            relpath, lineno, text))
    sites: dict[tuple, list[tuple[callgraph.Func, callgraph.WriteSite]]] = {}
    for fn in g.funcs:
        if fn.name in ("__init__", "__new__", "__post_init__"):
            continue
        if fn.module in SEAM_MODULES:
            continue
        for w in fn.writes:
            if w.owner == "":
                out.append(_mk(
                    "thread-ownership",
                    f"{fn.short()}: malformed '# ot-san:' annotation — "
                    "the grammar is '# ot-san: owner=<seam>' with "
                    "<seam> in [A-Za-z0-9._:-]+",
                    fn.relpath, w.lineno, _line_text(g, fn, w.lineno)))
            sites.setdefault(w.key, []).append((fn, w))

    for key in sorted(sites, key=lambda k: (k[0], str(k[1]), k[2])):
        entries = sites[key]
        loop_side = [(f, w) for f, w in entries
                     if f.is_async or f.loop_affine]
        thread_side = [(f, w) for f, w in entries if f.thread_affine]
        if not loop_side or not thread_side:
            continue
        if all(w.locked for _f, w in loop_side + thread_side):
            continue
        if any(w.owner for _f, w in entries):
            continue
        if key[0] == "attr":
            ci = g.classes.get(key[1])
            if ci is not None and key[2] in ci.attr_owner_ann:
                continue
            path = ci.relpath if ci is not None else entries[0][0].relpath
            label = f"{key[1]}.{key[2]}"
        else:
            path = entries[0][0].relpath
            label = f"{key[1]}.{key[2]}"
        if label.startswith(callgraph.PKG + "."):
            label = label[len(callgraph.PKG) + 1:]

        def _fmt(side):
            return ", ".join(sorted({f"{f.relpath}:{w.lineno}"
                                     for f, w in side})[:3])

        anchor_line = min(w.lineno for _f, w in thread_side)
        out.append(_mk(
            "thread-ownership",
            f"{label} is written from the event loop "
            f"({_fmt(loop_side)}) AND from worker threads "
            f"({_fmt(thread_side)}) without a lock on every write — "
            "route the mutation through a designated seam (metrics "
            "registry, queue, _notify_change, journal) or annotate "
            "the owner: '# ot-san: owner=<seam>'",
            path, anchor_line, "owner:" + label))
    return out


# --------------------------------------------------------------------------
# entry points
# --------------------------------------------------------------------------

def analyze_graph(g: callgraph.Graph) -> list[Finding]:
    findings = []
    for rel, err in g.parse_errors:
        findings.append(Finding(
            "parse", "error", f"ot-san cannot parse: {err}", rel,
            anchor="syntax-error", layer="san"))
    findings += _loop_stall(g)
    findings += _lock_await(g)
    findings += _lock_order(g)
    findings += _thread_ownership(g)
    return anchored(findings)


def analyze_paths(paths: list[str], repo_root: str) -> list[Finding]:
    """Build the call graph over ``paths`` and run every san rule —
    same (paths, repo_root) contract as ``astrules.lint_paths``."""
    return analyze_graph(callgraph.build_graph(paths, repo_root))
