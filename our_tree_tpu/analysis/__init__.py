"""otlint — the repo-invariant static-analysis subsystem.

Two layers, one CLI (``python -m our_tree_tpu.analysis``), one findings
baseline (``analysis/baseline.json`` at the repo root):

* **Layer 1 — AST linter** (``astrules.py``): pluggable rules over the
  package source encoding the invariants PRs 1-3 established by
  convention — child processes only via ``resilience.isolate.run_child``
  (no bare ``subprocess``/``os.fork``), raw device dispatch only under a
  watchdog guard or inside the designated barrier seam, demotions only
  through the ``degrade()`` chokepoint (and its ``# degraded`` emission
  format only fed by the ledger), no wall-clock reads in timed code
  outside ``obs``, trace span/point attrs statically JSON-serializable,
  and every ``OT_FAULTS`` seam point drawn from ``faults.KNOWN_POINTS``.

* **Layer 2 — jaxpr auditor** (``jaxpr_audit.py``): traces the public
  crypto entry points (AES ECB/CBC/CFB/CTR per engine, RC4 prep/crypt,
  the bitsliced kernels) with abstract inputs and walks the jaxprs with
  a taint analysis seeded from the key/plaintext arguments. It flags
  data-dependent ``gather``/``dynamic_slice``/``scatter`` indexed by
  secret-tainted values (the AES T-table timing channel — the paper's
  phase-split correctness story depends on the TPU port *not* acquiring
  one silently; cf. arxiv 1902.05234, which leans on exactly such
  lookups), argument-derived host↔device transfers and host callbacks
  inside kernels, dtype widening past 32 bits, and shape-specialized
  structure (eqn graphs whose size depends on the batch dim — the
  recompile-storm hazard).

Findings carry ``file:line`` / entry-point provenance, a severity, and
a STABLE fingerprint (line-number-independent), so a committed baseline
suppresses known findings and CI gates on *new* ones only
(``--baseline analysis/baseline.json --fail-on-new``). The baseline is
not an escape hatch: every entry requires a reason, and the loader
rejects reasonless ones. See docs/ANALYSIS.md for the rule catalog,
the taint model, the baseline workflow, and how to add a rule.

Layer 1 is stdlib-only (usable without jax in sight); layer 2 imports
jax lazily and pins CPU — auditing is structural and must never touch
a possibly-wedged device tunnel.
"""

from .findings import Finding  # noqa: F401
