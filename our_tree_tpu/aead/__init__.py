"""Authenticated modes (AES-GCM) as first-class served workloads.

``ghash.py`` is the HOST half: numpy/int GHASH (the parity twin of the
traced kernel), the host AES block oracle the keycache derives
H = E_K(0^128) with, GCM's inc32 counter materialiser, and the J0 /
length-block helpers the batcher and the models API share. ``gcm.py``
is the TRACED half plus the public API: the Horner-form GHASH kernel,
the scattered-CTR-fused-with-GHASH multikey dispatch (the serve seam),
the constant-time tag compare, and ``gcm_seal``/``gcm_open``.
"""
