"""AES-GCM — the TRACED half and the public seal/open API.

The traced pieces are built on one design decision (ops/gf.py module
docstring): multiplication by the fixed per-key GHASH subkey H is a
GF(2) LINEAR map, so the kernel carries a precomputed 128x128 bit
matrix per key slot and GHASH becomes bit-extraction + integer matmul
+ mask — XOR/AND arithmetic only, zero memory indirection, the same
constant-time construction discipline as the bitsliced AES circuit.
The jaxpr auditor covers these entries (``ghash[horner]``,
``aes-gcm-fused[*]``, ``gcm-tag-eq`` — analysis/jaxpr_audit.py): a
secret-indexed lookup creeping in here is a REAL finding, baselined
only with reason.

``gcm_crypt_ghash_words`` is the serve dispatch seam: scattered CTR
(the existing multi-key engine cores, ``models.aes.MULTIKEY_CTR``)
FUSED with segmented Horner GHASH accumulation in ONE jitted call.
Batch layout (serve/batcher.py materialises it; ``gcm_seal``/
``gcm_open`` build the single-request K=1 form of the same):

* each request occupies 1 + n rows: row 0 carries counter J0 with a
  zero data word — its CTR output IS E_K(J0), the tag's final pad —
  and rows 1..n carry the payload under inc32 counters;
* ``seg_keep`` (N,) zeroes the Horner carry at each segment start (and
  at the J0 rows, whose GHASH lane is discarded), so one fixed-shape
  scan serves many requests — no per-request shapes, the bucket
  ladder's zero-recompile contract holds for GCM exactly as for CTR;
* ``inject_words`` XORs each request's host-computed AAD prefix state
  Y_aad into its first ciphertext block (GHASH is Horner, so seeding
  the chain's first step with Y_aad ^ C_1 continues the AAD chain
  bit-exactly);
* the kernel emits the running Y at EVERY row; the host finisher reads
  each request's last full-block row and applies the (tiny,
  per-request, variable-length) tail: optional partial-block multiply,
  the length block, and the E_K(J0) pad — ``ops.gf.gf128_mul`` on
  ints, one or two multiplies per request.

``tag_eq_words`` is the traced constant-time tag compare (full XOR +
OR fold, one terminal equality); ``ghash.np_tag_eq`` is its host twin.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..models import aes as _aes
from ..ops import gf
from ..ops.keyschedule import expand_key_enc
from ..utils import packing
from . import ghash as _gh

#: Fused-kernel directions (static compile args): GHASH always runs
#: over the CIPHERTEXT stream — the dispatch OUTPUT when sealing, the
#: dispatch INPUT when opening.
SEAL = "seal"
OPEN = "open"


class TagMismatchError(ValueError):
    """``gcm_open``'s authentication failure: no plaintext is returned
    (the serve path answers the same event as a per-request
    ``auth-failed`` refusal, never an exception escaping a batch)."""


# ---------------------------------------------------------------------------
# Bit-plane packing (the word-bit basis of gf.gf128_mul_matrix_words).
# ---------------------------------------------------------------------------


def _bits_of(w2: jnp.ndarray) -> jnp.ndarray:
    """(N, 4) u32 block words -> (N, 128) 0/1 u32 bit lanes, word-bit
    order (bit k = bit k%32 of word k//32)."""
    shifts = jnp.arange(32, dtype=jnp.uint32)
    return ((w2[:, :, None] >> shifts[None, None, :])
            & jnp.uint32(1)).reshape(w2.shape[0], 128)


def _words_of(bits: jnp.ndarray) -> jnp.ndarray:
    """(N, 128) 0/1 u32 bit lanes -> (N, 4) u32 block words."""
    shifts = jnp.arange(32, dtype=jnp.uint32)
    return jnp.sum(bits.reshape(bits.shape[0], 4, 32) << shifts,
                   axis=-1, dtype=jnp.uint32)


# ---------------------------------------------------------------------------
# Traced GHASH (Horner) + the fused scattered-CTR/GHASH dispatch.
# ---------------------------------------------------------------------------


@jax.jit
def _ghash_words_jit(words, hmat, y0_words):
    b2 = words.reshape(-1, 4)
    bits = _bits_of(b2)
    y0 = _bits_of(y0_words.reshape(1, 4))[0]

    def step(y, xb):
        y2 = jnp.matmul(hmat, y ^ xb) & jnp.uint32(1)
        return y2, None

    y, _ = jax.lax.scan(step, y0, bits, unroll=4)
    return _words_of(y[None])[0]


def ghash_words(words, hmat, y0_words=None):
    """Horner-form GHASH over a batch of blocks: ``words`` (N, 4) u32
    (or flat (4N,)), ``hmat`` the (128, 128) mul-by-H bit matrix,
    ``y0_words`` an optional (4,) initial state. Returns the final Y as
    (4,) u32 words. The standalone traced entry the auditor taints."""
    if y0_words is None:
        y0_words = jnp.zeros(4, jnp.uint32)
    return _ghash_words_jit(words, hmat, y0_words)


@functools.partial(jax.jit, static_argnums=(7, 8, 9, 10))
def _gcm_fused_jit(words, ctr_le_words, rks, key_slots, hmats,
                   inject_words, seg_keep, nr, engine, direction, knobs):
    del knobs  # compile-cache key only (models/aes.py:_engine_knobs_key)
    w2 = words.reshape(-1, 4)
    c2 = ctr_le_words.reshape(-1, 4)
    slots = key_slots.astype(jnp.uint32)
    fn = _aes.MULTIKEY_CTR.get(engine, _aes._multikey_bitslice)
    out = fn(w2, c2, rks, slots, nr)
    # GHASH runs over the ciphertext: the CTR output when sealing, the
    # input when opening. inject carries each segment's AAD prefix
    # state into its first block (XOR before bit extraction — GF(2)
    # addition commutes with the basis change).
    gh2 = (out if direction == SEAL else w2) ^ inject_words.reshape(-1, 4)
    bits = _bits_of(gh2)

    def step(y, xs):
        xb, keep, slot = xs
        m = jax.lax.dynamic_index_in_dim(hmats, slot, axis=0,
                                         keepdims=False)  # public index
        y2 = jnp.matmul(m, (y * keep) ^ xb) & jnp.uint32(1)
        return y2, y2

    _, ys = jax.lax.scan(
        step, jnp.zeros(128, jnp.uint32),
        (bits, seg_keep.astype(jnp.uint32), slots), unroll=2)
    return out.reshape(words.shape), _words_of(ys).reshape(words.shape)


def gcm_crypt_ghash_words(words, ctr_le_words, rks, key_slots, hmats,
                          inject_words, seg_keep, nr, engine="jnp",
                          direction=SEAL):
    """The fused GCM dispatch: scattered multi-key CTR + segmented
    Horner GHASH in one jitted call (module docstring has the batch
    layout). Returns ``(out_words, y_words)``, both in the caller's
    flat/(N, 4) shape: ``out_words`` is the CTR result (E_K(J0) on the
    J0 rows), ``y_words`` the running GHASH state after every row —
    the host finisher reads each request's last full-block row. Every
    array shape is closed over (N, K), so the bucket ladder's
    zero-recompile contract holds for GCM batches unchanged."""
    return _gcm_fused_jit(words, ctr_le_words, rks, key_slots, hmats,
                          inject_words, seg_keep, nr, engine, direction,
                          _aes._engine_knobs_key(engine))


@jax.jit
def _tag_eq_jit(a, b):
    d = a.reshape(-1) ^ b.reshape(-1)
    r = (d[0] | d[1]) | (d[2] | d[3])
    return r == jnp.uint32(0)


def tag_eq_words(a, b) -> jnp.ndarray:
    """Constant-time 128-bit tag compare on (4,) u32 words: full XOR,
    one OR fold, ONE terminal equality — no data-dependent early exit
    (the audit's ``gcm-tag-eq`` entry pins exactly this shape)."""
    return _tag_eq_jit(jnp.asarray(a, jnp.uint32),
                       jnp.asarray(b, jnp.uint32))


# ---------------------------------------------------------------------------
# The public models-facing API.
# ---------------------------------------------------------------------------

#: key digest -> (nr, rk, h_int, hmat) — deriving the mul-by-H matrix
#: is ~128 field multiplies of host int work; KATs/fuzz re-enter with
#: the same few keys constantly. Bounded: fallback eviction at 64 keys.
_KEY_CACHE: dict[bytes, tuple] = {}


def _key_material(key: bytes):
    key = bytes(key)
    hit = _KEY_CACHE.get(key)
    if hit is not None:
        return hit
    nr, rk = expand_key_enc(key)
    rk = np.asarray(rk, dtype=np.uint32)
    h = _gh.derive_h(nr, rk)
    ent = (nr, rk, h, gf.gf128_mul_matrix_words(h))
    if len(_KEY_CACHE) >= 64:
        _KEY_CACHE.pop(next(iter(_KEY_CACHE)))
    _KEY_CACHE[key] = ent
    return ent


def _finish_tag(y_int: int, h: int, tail_ct: bytes, aad_len: int,
                ct_len: int, ek_j0: np.ndarray) -> bytes:
    """The host per-request GHASH tail: optional zero-padded partial
    block, the length block, then the E_K(J0) pad. One or two
    ``gf128_mul`` calls — variable-length work the fixed-shape kernel
    deliberately leaves to the host."""
    if tail_ct:
        y_int = gf.gf128_mul(
            y_int ^ gf.block_to_int(_gh.pad16(tail_ct)), h)
    y_int = gf.gf128_mul(
        y_int ^ gf.block_to_int(_gh.length_block(aad_len, ct_len)), h)
    return bytes(np.frombuffer(gf.int_to_block(y_int), np.uint8)
                 ^ np.asarray(ek_j0, np.uint8))


def _gcm_arrays(j0: bytes, data: bytes, y_aad: int):
    """The single-request (K=1) fused-dispatch arrays for ``data``'s
    full blocks: row 0 = J0, rows 1..n = payload — the same layout the
    serve batcher materialises, so seal/open and the served path
    exercise ONE kernel."""
    nfull = len(data) // 16
    n = 1 + nfull
    words = np.zeros(4 * n, dtype=np.uint32)
    if nfull:
        words[4:] = packing.np_bytes_to_words(
            np.frombuffer(data[:16 * nfull], np.uint8))
    ctr = _gh.np_gcm_ctr_blocks(j0, np.arange(n, dtype=np.uint32))
    inject = np.zeros((n, 4), dtype=np.uint32)
    if nfull:
        inject[1] = packing.np_bytes_to_words(
            np.frombuffer(gf.int_to_block(y_aad), np.uint8))
    keep = np.ones(n, dtype=np.uint32)
    keep[0] = 0
    if nfull:
        keep[1] = 0
    return words, ctr.reshape(-1), inject.reshape(-1), keep, nfull


def _gcm_crypt(key: bytes, iv: bytes, aad: bytes, data: bytes,
               engine: str, direction: str):
    """Shared seal/open core: returns (crypt output bytes, tag)."""
    nr, rk, h, hmat = _key_material(key)
    j0 = _gh.j0_from_iv(h, iv)
    y_aad = _gh.ghash_int(h, _gh.pad16(aad))
    words, ctr, inject, keep, nfull = _gcm_arrays(j0, data, y_aad)
    engine = _aes.resolve_engine(engine)
    rks = np.asarray(rk, np.uint32)[None, :]
    slots = np.zeros(1 + nfull, dtype=np.uint32)
    hmats = hmat[None, :, :]
    out, ys = gcm_crypt_ghash_words(words, ctr, rks, slots, hmats,
                                    inject, keep, nr, engine, direction)
    out = np.asarray(out).reshape(-1, 4)
    ys = np.asarray(ys).reshape(-1, 4)
    ek_j0 = packing.np_words_to_bytes(out[0:1]).reshape(-1)
    full = packing.np_words_to_bytes(out[1:]).reshape(-1)[:16 * nfull]
    tail_in = data[16 * nfull:]
    if tail_in:
        # The partial tail block: one more keystream block host-side
        # (inc32^{nfull+1}(J0) through the host oracle — a reference-
        # grade single block, not a dispatch), truncated XOR.
        ks = _gh.np_aes_encrypt_block(
            nr, rk, _gh.inc32(j0, 1 + nfull))
        tail_out = bytes(np.frombuffer(tail_in, np.uint8)
                         ^ ks[:len(tail_in)])
    else:
        tail_out = b""
    out_bytes = bytes(full) + tail_out
    ct = out_bytes if direction == SEAL else bytes(data)
    y_int = (gf.block_to_int(
        packing.np_words_to_bytes(ys[nfull:nfull + 1]).reshape(-1))
        if nfull else y_aad)
    tag = _finish_tag(y_int, h, ct[16 * nfull:], len(aad), len(ct),
                      ek_j0)
    return out_bytes, tag


def gcm_seal(key, iv, aad=b"", plaintext=b"",
             engine: str = "jnp") -> tuple[bytes, bytes]:
    """AES-GCM authenticated encryption (SP 800-38D): returns
    ``(ciphertext, tag16)``. Arbitrary plaintext/AAD lengths; 96-bit
    IVs take the fast J0 path, any other length derives J0 by GHASH.
    ``engine`` picks the CTR core tier exactly as every mode entry
    does (``models.aes.resolve_engine``)."""
    key, iv = bytes(bytearray(key)), bytes(bytearray(iv))
    aad = bytes(bytearray(aad))
    pt = bytes(bytearray(plaintext))
    ct, tag = _gcm_crypt(key, iv, aad, pt, engine, SEAL)
    return ct, tag


def gcm_open(key, iv, aad, ciphertext, tag,
             engine: str = "jnp") -> bytes:
    """AES-GCM authenticated decryption: verifies the tag (traced
    constant-time compare) BEFORE returning plaintext; raises
    ``TagMismatchError`` on failure — never partial plaintext."""
    key, iv = bytes(bytearray(key)), bytes(bytearray(iv))
    aad = bytes(bytearray(aad))
    ct = bytes(bytearray(ciphertext))
    tag = bytes(bytearray(tag))
    pt, want = _gcm_crypt(key, iv, aad, ct, engine, OPEN)
    if len(tag) != 16 or not bool(tag_eq_words(
            packing.np_bytes_to_words(np.frombuffer(want, np.uint8)),
            packing.np_bytes_to_words(np.frombuffer(tag, np.uint8)))):
        raise TagMismatchError("GCM tag mismatch")
    return pt
