"""GHASH and GCM plumbing — the HOST half (numpy/int, no jax).

Everything the batcher, keycache, and models API need on the host side
of the AEAD seam:

* ``np_aes_encrypt_block`` — a from-scratch single-block AES oracle on
  numpy bytes (SBOX + ShiftRows permutation + MixColumns over
  ``ops/gf.py``), the thing the keycache derives H = E_K(0^128) with.
  Host-side on purpose: deriving H must not touch a device from the
  event loop (the lane seam owns device contact), and one block of AES
  in Python is microseconds against a key-expansion that already runs
  per miss.
* ``ghash_int`` — the int-based GHASH reference (Horner over 16-byte
  blocks with ``gf128_mul``): the parity twin every traced kernel
  output is pinned against, and the host finisher's per-request tail
  (partial block + length block) multiply.
* ``np_gcm_ctr_blocks`` — GCM's inc32 counter materialiser: unlike raw
  CTR's 128-bit ripple (``utils.packing.np_ctr_le_blocks``), ONLY the
  rightmost 32 bits increment (mod 2^32, SP 800-38D §6.2); the upper
  96 bits are pinned to J0's. Same (N, 4) u32 LE-word output layout,
  so GCM rides the existing scattered-CTR dispatch arrays unchanged.
* J0 derivation, zero-padding, the length block, and the constant-time
  host tag compare (full XOR fold, one terminal equality — no
  early-exit byte loop).
* ``np_gcm_seal``/``np_gcm_open`` — the pure-host reference GCM the
  fuzz-parity satellite cross-checks ``gcm_seal``/``gcm_open`` against
  (random lengths, AAD splits, empty AAD, non-block-aligned tails).
"""

from __future__ import annotations

import numpy as np

from ..ops import gf
from ..ops.keyschedule import expand_key_enc
from ..ops.tables import SBOX

#: ShiftRows as a byte-position permutation (same derivation as
#: ops/bitslice.py:SR_PERM; recomputed here so this module stays
#: jax-import-free — bitslice imports jax at module load).
_SR_PERM = np.array([4 * ((i // 4 + i % 4) % 4) + i % 4
                     for i in range(16)])

_MUL2 = gf.gmul_table(2).astype(np.uint8)
_MUL3 = gf.gmul_table(3).astype(np.uint8)

_SBOX_U8 = np.asarray(SBOX, dtype=np.uint8)


def np_aes_encrypt_block(nr: int, rk_words, block16) -> np.ndarray:
    """One AES block encrypt on host bytes. ``rk_words``: the expanded
    encrypt schedule ((4*(nr+1),) u32, the LE-word convention every
    engine shares); ``block16``: 16 input bytes. Returns (16,) u8."""
    s = np.frombuffer(bytes(bytearray(block16)), dtype=np.uint8).copy()
    rkb = np.ascontiguousarray(
        np.asarray(rk_words, dtype="<u4")).view(np.uint8)
    s ^= rkb[0:16]
    for r in range(1, nr + 1):
        s = _SBOX_U8[s[_SR_PERM]]
        if r != nr:
            a = s.reshape(4, 4)  # column-major: row i = column i's bytes
            s = np.empty_like(a)
            for c in range(4):
                a0, a1, a2, a3 = a[c]
                s[c, 0] = _MUL2[a0] ^ _MUL3[a1] ^ a2 ^ a3
                s[c, 1] = a0 ^ _MUL2[a1] ^ _MUL3[a2] ^ a3
                s[c, 2] = a0 ^ a1 ^ _MUL2[a2] ^ _MUL3[a3]
                s[c, 3] = _MUL3[a0] ^ a1 ^ a2 ^ _MUL2[a3]
            s = s.reshape(16)
        s = s ^ rkb[16 * r:16 * (r + 1)]
    return s


def derive_h(nr: int, rk_words) -> int:
    """H = E_K(0^128) as a field element int — the GHASH subkey the
    keycache stores beside the schedule."""
    return gf.block_to_int(np_aes_encrypt_block(nr, rk_words, b"\x00" * 16))


# ---------------------------------------------------------------------------
# GHASH (int reference) + the GCM framing helpers.
# ---------------------------------------------------------------------------


def pad16(b: bytes) -> bytes:
    """Zero-pad to the next 16-byte boundary (GCM's block padding)."""
    r = len(b) % 16
    return b + b"\x00" * (16 - r) if r else b


def length_block(aad_len: int, ct_len: int) -> bytes:
    """[len(A)]_64 || [len(C)]_64, both in BITS (SP 800-38D §7.1)."""
    return ((aad_len * 8).to_bytes(8, "big")
            + (ct_len * 8).to_bytes(8, "big"))


def ghash_int(h: int, data: bytes, y0: int = 0) -> int:
    """Horner GHASH over 16-byte blocks (``data`` must be a multiple of
    16 — callers ``pad16`` first). The int reference twin."""
    if len(data) % 16:
        raise ValueError("GHASH input must be zero-padded to blocks")
    y = y0
    for off in range(0, len(data), 16):
        y = gf.gf128_mul(y ^ gf.block_to_int(data[off:off + 16]), h)
    return y


def j0_from_iv(h: int, iv: bytes) -> bytes:
    """The pre-counter block: IV || 0^31 || 1 for the 96-bit fast path,
    GHASH(H, IV padded || [0]_64 || [len(IV)]_64) otherwise."""
    iv = bytes(bytearray(iv))
    if len(iv) == 12:
        return iv + b"\x00\x00\x00\x01"
    y = ghash_int(h, pad16(iv) + (0).to_bytes(8, "big")
                  + (len(iv) * 8).to_bytes(8, "big"))
    return gf.int_to_block(y)


def inc32(block16: bytes, k: int = 1) -> bytes:
    """The GCM counter increment: low 32 bits + k mod 2^32, upper 96
    bits untouched."""
    b = bytes(bytearray(block16))
    low = (int.from_bytes(b[12:], "big") + k) & 0xFFFFFFFF
    return b[:12] + low.to_bytes(4, "big")


def np_gcm_ctr_blocks(j0: bytes, idx: np.ndarray,
                      out: np.ndarray | None = None) -> np.ndarray:
    """Counter blocks ``inc32^idx[k](J0)`` as (N, 4) u32 LE words — the
    GCM twin of ``utils.packing.np_ctr_le_blocks``, same output layout
    (the scattered-CTR dispatch consumes it unchanged), different
    increment law: only the low 32 bits move. The common case is one
    broadcast of J0's three fixed words plus a vectorised low-word add."""
    b = np.frombuffer(bytes(bytearray(j0)), dtype=np.uint8)
    if b.size != 16:
        raise ValueError("J0 must be 16 bytes")
    le = b.view("<u4")
    idx = np.asarray(idx, dtype=np.uint32)
    if out is None:
        out = np.empty((idx.size, 4), dtype=np.uint32)
    out[:] = le
    ctr0 = np.uint32(int.from_bytes(bytes(b[12:]), "big"))
    with np.errstate(over="ignore"):  # mod-2^32 wrap is the inc32 law
        out[:, 3] = (ctr0 + idx).byteswap()
    return out


def np_tag_eq(a, b) -> bool:
    """Constant-time host tag compare: full XOR fold over every byte,
    ONE terminal equality — no early-exit loop (the traced twin is
    ``aead.gcm.tag_eq_words``; tests pin the two)."""
    aa = np.frombuffer(bytes(bytearray(a)), dtype=np.uint8)
    bb = np.frombuffer(bytes(bytearray(b)), dtype=np.uint8)
    if aa.size != bb.size:
        return False
    return int(np.bitwise_or.reduce(aa ^ bb)) == 0


# ---------------------------------------------------------------------------
# The pure-host reference GCM (fuzz-parity oracle).
# ---------------------------------------------------------------------------


def np_gcm_seal(key: bytes, iv: bytes, aad: bytes,
                plaintext: bytes) -> tuple[bytes, bytes]:
    """Reference AES-GCM seal entirely on host ints/numpy — the twin
    ``gcm_seal`` is fuzz-pinned against. O(blocks) Python AES: a
    reference, not a fast path."""
    nr, rk = expand_key_enc(bytes(key))
    h = derive_h(nr, rk)
    j0 = j0_from_iv(h, iv)
    pt = bytes(bytearray(plaintext))
    ct = bytearray()
    for i in range(0, len(pt), 16):
        ks = np_aes_encrypt_block(nr, rk, inc32(j0, 1 + i // 16))
        chunk = pt[i:i + 16]
        ct += bytes(np.frombuffer(chunk, np.uint8) ^ ks[:len(chunk)])
    aad = bytes(bytearray(aad))
    s = ghash_int(h, pad16(aad) + pad16(bytes(ct))
                  + length_block(len(aad), len(ct)))
    ek_j0 = np_aes_encrypt_block(nr, rk, j0)
    tag = bytes(np.frombuffer(gf.int_to_block(s), np.uint8) ^ ek_j0)
    return bytes(ct), tag


def np_gcm_open(key: bytes, iv: bytes, aad: bytes, ciphertext: bytes,
                tag: bytes) -> bytes | None:
    """Reference AES-GCM open; None on tag mismatch (never partial
    plaintext)."""
    nr, rk = expand_key_enc(bytes(key))
    h = derive_h(nr, rk)
    j0 = j0_from_iv(h, iv)
    ct = bytes(bytearray(ciphertext))
    aad = bytes(bytearray(aad))
    s = ghash_int(h, pad16(aad) + pad16(ct)
                  + length_block(len(aad), len(ct)))
    ek_j0 = np_aes_encrypt_block(nr, rk, j0)
    want = bytes(np.frombuffer(gf.int_to_block(s), np.uint8) ^ ek_j0)
    if not np_tag_eq(want, tag):
        return None
    pt = bytearray()
    for i in range(0, len(ct), 16):
        ks = np_aes_encrypt_block(nr, rk, inc32(j0, 1 + i // 16))
        chunk = ct[i:i + 16]
        pt += bytes(np.frombuffer(chunk, np.uint8) ^ ks[:len(chunk)])
    return bytes(pt)
