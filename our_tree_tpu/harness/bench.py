"""Benchmark sweep CLI — the TPU successor of the reference harnesses.

Replicates the reference sweep shape (sizes x workers x iterations with a
fixed seed, reference test.c:129-157 and aes-modes/test.c:353-446) and its
CSV result format exactly:

    <name>, <msg_bytes>, <workers>, t1, t2, ..., tN,

with RC4 additionally printing the separately-timed keystream-generation
line ("Generated a new key in <us>,", reference test.c:84-91) and the run
ending with the ARC4 known-answer self-test, mirroring test.c:156. Output
goes to stdout and (with --out) to a `results.<host>.tpu` file — the L3
results corpus of SURVEY.md §1, new backend column.

Differences from the reference, on purpose:
  * each timing row is followed by a `# derived: X GB/s` comment line
    (SURVEY.md §5: the reference format "plus derived GB/s"); the µs rows
    themselves stay byte-compatible, and `#` lines are trivially skipped
    by any row parser.
  * correctness is checked, not assumed: after the sweeps, one message is
    run through every worker count and bit-compared (the shard-invariance
    check whose absence let reference defect #1 go unnoticed), the RC4 XOR
    phase is verified against numpy, and the run ends with known-answer
    self-tests. (The timed iterations themselves are not re-verified.)
  * `--timing device` reports per-pass KERNEL time via the
    chained-difference methodology (1+k data-dependent passes in one
    dispatch, (T(1+k)-T(1))/k — backends.TpuBackend.
    chained_device_times_us): on a tunnelled transport the per-call
    dispatch+sync costs a fixed ~0.1 s that would otherwise floor every
    row at transport latency instead of kernel rate (VERDICT r4 weak #1).
    `--timing device-sync` keeps the per-call convention (kernel + sync
    round trip); default `e2e` includes staging like the reference GPU
    harness (main_ecb_e.cu:37-44).
  * sweeps are flags, not recompiles: --sizes-mb, --workers, --iters,
    --keybits, --modes, --backend, --engine.
"""

from __future__ import annotations

import argparse
import os
import socket
import sys
import time

import numpy as np

from ..obs import trace as trace_mod
from ..resilience import degrade as degrade_mod
from ..resilience import faults as faults_mod
from ..resilience import isolate as isolate_mod
from ..resilience import journal as journal_mod
from ..resilience import watchdog as watchdog_mod
from .backends import make_backend

MIB = 1 << 20

#: Fixed nonce/IV, in the spirit of the reference's hardcoded constants
#: (aes-modes/test.c:305-308).
NONCE = np.frombuffer(bytes.fromhex("f0f1f2f3f4f5f6f7f8f9fafbfcfdfeff"), np.uint8)
IV = np.frombuffer(bytes.fromhex("000102030405060708090a0b0c0d0e0f"), np.uint8)


class Emitter:
    def __init__(self, path: str | None):
        self.f = open(path, "w") if path else None
        self._capture: list[str] | None = None

    def line(self, text: str):
        print(text, flush=True)
        if self.f:
            self.f.write(text + "\n")
            self.f.flush()
        if self._capture is not None:
            self._capture.append(text)

    def begin_capture(self):
        """Start recording emitted lines (journal checkpointing: a resumed
        sweep re-emits a completed unit's lines verbatim)."""
        self._capture = []

    def end_capture(self) -> list[str]:
        lines, self._capture = self._capture or [], None
        return lines

    def capture_len(self) -> int:
        """Current capture length — a row checkpoint's slice mark."""
        return len(self._capture or ())

    def capture_since(self, mark: int) -> list[str]:
        """Lines captured since ``mark`` (one worker row's output)."""
        return list((self._capture or [])[mark:])

    def close(self):
        if self.f:
            self.f.close()


def _csv(times_us: list[int]) -> str:
    return "".join(f"{t}, " for t in times_us).rstrip()


def _derived(em, nbytes: int, times_us: list[int], floor_us: int = 0):
    """Derived GB/s next to the raw µs row (SURVEY.md §5 metrics: the
    reference format 'plus derived GB/s'). Best steady iteration, like
    BASELINE.md derives its numbers; a comment-style line so the µs rows
    stay byte-compatible with the reference parser. `floor_us` entries are
    the chained-timing jitter sentinel (backends.TpuBackend.FLOOR_US) —
    excluded so an artifact can never win best-of."""
    # A chained difference at (or truncated to) the floor is below the
    # methodology's resolution — jitter artifact or not, bytes/1µs would
    # not be a trustworthy rate, so such rows get no derived line rather
    # than a fabricated one.
    valid = [t for t in times_us if t > floor_us]
    if not valid:
        if times_us:
            em.line("# derived: n/a (all iterations at/below the chained-"
                    "timing resolution floor)")
        return
    v = nbytes / min(valid) / 1e3
    # Sequential-recurrence rows land far below 1 MB/s; fixed 3-decimal
    # formatting would print them all as "0.000".
    text = f"{v:.3f}" if v >= 0.1 else f"{v:.3g}"
    em.line(f"# derived: {text} GB/s (best of {len(valid)})")


def _time_us(fn) -> tuple[int, object]:
    # The backend-agnostic dispatch seam: every timed region of every
    # backend passes through here, so an armed dispatch_hang wedges the
    # sweep exactly where a dead transport would — inside a timed device
    # call — for the watchdog / --isolate supervisor to deal with. The
    # "timed-call" span is the per-phase attribution substrate
    # (obs.report sums these per unit as device-seam time); the injected
    # hang sleeps INSIDE it, so a SIGKILLed child leaves an orphaned
    # timed-call span naming exactly where it died.
    with trace_mod.span("timed-call", seam="harness._time_us"):
        watchdog_mod.injected_hang("dispatch_hang", "harness timed region")
        t0 = time.perf_counter_ns()
        out = fn()
        us = (time.perf_counter_ns() - t0) // 1000
    # Deterministic-clock test seam: with OT_FAKE_TIME_US set, every timed
    # region reports that fixed µs value (the work still runs — only the
    # CLOCK is faked). The journal-resume tests use it to make an
    # interrupted+resumed sweep corpus byte-comparable to an uninterrupted
    # one; timing rows are meaningless under it by construction.
    fake = os.environ.get("OT_FAKE_TIME_US")
    if fake:
        return max(int(fake), 1), out
    return us, out


def _chain_k(size: int, cap_mib: int = 2048, max_k: int = 2048,
             min_k: int = 4) -> int:
    """Chain length for chained-difference device timing (backends.py:
    chained_device_times_us) — THE one policy every chained row shares:
    scale inversely with buffer size so the chained work dominates timer
    noise at small buffers without making the 1 GiB rows pay hundreds of
    passes. `cap_mib` bounds the total chained bytes and `max_k` the pass
    count; the sequential scan modes pass small ones with `min_k=1`: a
    single scan pass is already seconds of serial recurrence (noise-free
    without chaining), so at sizes past `cap_mib` the chain collapses to
    one pass instead of costing minutes.

    Sizing rule: per-pass noise is (dispatch+sync jitter)/k — ms-scale on
    a tunnelled transport — so k must be large enough that noise is a few
    percent of a pass, or best-of-N picks the noise floor and the derived
    GB/s overstates the kernel (observed: 1.5 TB/s "XOR" rows, above HBM
    bandwidth, under the old 512 MiB cap). The fast XOR phase passes a
    bigger cap than the AES modes for the same reason (run_rc4)."""
    return max(min_k, min(max_k, (cap_mib * MIB) // max(size, 1)))


def _mode_crypt(backend, mode, ctx, workers, ctr_be=None, ivw=None,
                chained=True):
    """The ONE mode dispatch both timing paths share: returns
    crypt(words, acc). When `chained`, the carry is injected where the
    mode's expensive work reads it — CTR: the counter (a data-only carry
    lets XLA hoist the whole keystream out of a chained loop); every
    other mode: the data words. The per-call paths pass chained=False so
    the injection disappears entirely: the backend mode functions are
    themselves the jit boundary, so an eager `w ^ 0` here would be a
    full-buffer device (or numpy, --backend c) pass INSIDE the timed
    region."""
    mix = (lambda x, acc: x ^ acc) if chained else (lambda x, acc: x)
    if mode == "ctr":
        return lambda w, acc: backend.ctr(ctx, w, mix(ctr_be, acc), workers)
    if mode == "ecb":
        return lambda w, acc: backend.ecb(ctx, mix(w, acc), workers)
    if mode == "ecb-dec":
        return lambda w, acc: backend.ecb_dec(ctx, mix(w, acc), workers)
    if mode == "cbc":
        return lambda w, acc: backend.cbc(ctx, mix(w, acc), ivw, workers)
    if mode == "cbc-dec":
        return lambda w, acc: backend.cbc_dec(ctx, mix(w, acc), ivw, workers)
    if mode == "cfb128":
        return lambda w, acc: backend.cfb128(ctx, mix(w, acc), ivw, workers)
    raise ValueError(mode)


def run_aes_mode(em, backend, mode, size, workers_list, iters, keybits, rng,
                 timing, stream_chunk=0, rows=None):
    msg = rng.integers(0, 256, size, dtype=np.uint8)
    if mode in ("cbc", "cfb128") and workers_list != [1]:
        # Single-stream chained encrypt is a sequential recurrence — the
        # backend rejects workers > 1 rather than silently ignoring them, so
        # the sweep pins the row to one worker and says so in the results
        # (scaling chained modes means batching independent streams; the
        # sweep surface for that is cbc-batch).
        hint = ("use cbc-batch for multi-worker scaling" if mode == "cbc"
                else "chained modes scale by batching independent streams")
        em.line(f"{mode.upper()} single-stream is sequential; sweeping "
                f"workers=1 only ({hint}),")
        workers_list = [1]
    streaming = (
        stream_chunk and mode == "ctr" and size > stream_chunk
        and hasattr(backend, "ctr_stream")
    )
    if streaming:
        # Announce the convention switch in the results file itself: rows
        # below are chunk-streamed and necessarily e2e-timed, so a reader
        # of a mixed-size sweep can tell the timing conventions apart.
        em.line(f"Streaming {size} bytes in {stream_chunk}-byte chunks "
                "(counter carried across seams; e2e timing),")
    chained_ok = (timing == "device" and not streaming
                  and hasattr(backend, "chained_device_times_us"))
    needs_iv = mode in ("cbc", "cbc-dec", "cfb128")

    def one_row(workers):
        if chained_ok:
            # Chained-difference device timing (backends.py docstring): one
            # key per row (keys are data, not timing).
            key = rng.integers(0, 256, keybits // 8, dtype=np.uint8).tobytes()
            ctx = backend.make_key(key)
            crypt = _mode_crypt(
                backend, mode, ctx, workers,
                ctr_be=backend.ctr_be_words(NONCE) if mode == "ctr" else None,
                ivw=backend.iv_words(IV) if needs_iv else None)
            words = backend.stage_words(msg)
            backend.block_until_ready(words)
            k = (_chain_k(size, 8, max_k=4, min_k=1)
                 if mode in ("cbc", "cfb128") else _chain_k(size))
            times = backend.chained_device_times_us(crypt, words, iters, k)
            label = backend.name.upper()
            em.line(f"{label} AES-{keybits} {mode.upper()}, {size}, "
                    f"{workers}, {_csv(times)}")
            _derived(em, size, times, backend.FLOOR_US)
            return
        times = []
        warmed = False
        for it in range(iters):
            key = rng.integers(0, 256, keybits // 8, dtype=np.uint8).tobytes()
            ctx = backend.make_key(key)  # untimed, like the reference
            if streaming:
                # Message larger than device memory: chunked staging with
                # counter carry across seams (backends.ctr_stream). Staging
                # is inherent to the pipeline, so timing is always e2e here.
                if not warmed:  # absorb compilation once per worker row
                    backend.ctr_stream(ctx, msg, NONCE, stream_chunk, workers)
                    warmed = True
                us, _ = _time_us(
                    lambda: backend.ctr_stream(ctx, msg, NONCE, stream_chunk,
                                               workers)
                )
                times.append(us)
                continue
            # Same dispatch as the chained path (one table to keep in
            # sync); acc=0 makes crypt a plain per-call run. ecb-dec is
            # the inverse-circuit direction (VERDICT r2 #4): same sweep
            # shape as ECB so the enc/dec asymmetry reads straight off
            # adjacent rows; its "plaintext" rows decrypt random bytes —
            # throughput is data-independent, as in the reference's
            # decrypt path (aes-modes/aes.c:650-752, one code path).
            crypt = _mode_crypt(
                backend, mode, ctx, workers,
                ctr_be=backend.ctr_be_words(NONCE) if mode == "ctr" else None,
                ivw=backend.iv_words(IV) if needs_iv else None,
                chained=False)
            run = lambda w: crypt(w, 0)

            if not warmed:
                # One untimed call absorbs JIT compilation — the analogue of
                # the reference's numbers never containing a compiler in the
                # timed region. Rekeying later iterations does NOT recompile
                # (keys are data, not trace constants).
                backend.block_until_ready(run(backend.stage_words(msg)))
                warmed = True
            if timing in ("device", "device-sync"):
                # Per-call sync timing: kernel + the transport's fixed
                # dispatch+sync round trip (reached for "device" only when
                # the backend has no chained helper, e.g. --backend c).
                words = backend.stage_words(msg)
                backend.block_until_ready(words)
                us, out = _time_us(
                    lambda: backend.block_until_ready(run(words))
                )
            else:
                us, out = _time_us(
                    lambda: backend.block_until_ready(run(backend.stage_words(msg)))
                )
            times.append(us)
        label = backend.name.upper()
        em.line(f"{label} AES-{keybits} {mode.upper()}, {size}, {workers}, {_csv(times)}")
        _derived(em, size, times)

    for i, workers in enumerate(workers_list):
        # Per-worker-ROW resume granularity: a recorded row replays (its
        # lines re-emitted, the shared RNG stream restored to its
        # post-row state) and a fresh one runs inside a "row" span, so
        # a SIGKILLed unit's re-run resumes at the last completed row
        # and the trace tells replayed from fresh (docs/OBSERVABILITY.md).
        if rows is not None and rows.replay(workers):
            continue
        with trace_mod.span("row", mode=mode, size=size, workers=workers):
            one_row(workers)
        if rows is not None:
            rows.record(workers, last=(i == len(workers_list) - 1))


def run_cbc_batch(em, backend, size, workers_list, iters, keybits, rng,
                  timing, streams):
    """S independent CBC-encrypt streams, sharded over chips — the sweep
    surface for dist.cbc_encrypt_batch_sharded (sequence parallelism for
    chained modes: scale across streams, not within one)."""
    if not hasattr(backend, "cbc_batch"):
        raise ValueError("cbc-batch requires the tpu backend")
    streams = max(1, min(streams, size // 16))
    per = (size // streams) // 16 * 16
    used = per * streams
    em.line(f"Batch of {streams} independent CBC streams, {per} bytes each,")
    msg = rng.integers(0, 256, (streams, per), dtype=np.uint8)
    inv_key = rng.integers(0, 256, keybits // 8, dtype=np.uint8).tobytes()
    inv_ivs = rng.integers(0, 256, (streams, 16), dtype=np.uint8)
    inv_ref = None
    chained_ok = (timing == "device"
                  and hasattr(backend, "chained_device_times_us"))
    for workers in workers_list:
        if chained_ok:
            key = rng.integers(0, 256, keybits // 8, dtype=np.uint8).tobytes()
            ctx = backend.make_key(key)
            ivw = backend.stage_batch_words(
                rng.integers(0, 256, (streams, 16), dtype=np.uint8))
            crypt = lambda w, acc: backend.cbc_batch(ctx, w ^ acc, ivw,
                                                     workers)
            words = backend.stage_batch_words(msg)
            backend.block_until_ready(words)
            # min_k=1 like the cbc/cfb128 rows: per-stream this is the same
            # serial scan, so past cap_mib one pass is already noise-free.
            times = backend.chained_device_times_us(
                crypt, words, iters, _chain_k(used, 64, max_k=16, min_k=1))
        else:
            times = []
            warmed = False
            for _ in range(iters):
                key = rng.integers(0, 256, keybits // 8,
                                   dtype=np.uint8).tobytes()
                ctx = backend.make_key(key)
                ivs = rng.integers(0, 256, (streams, 16), dtype=np.uint8)
                ivw = backend.stage_batch_words(ivs)
                run = lambda w: backend.cbc_batch(ctx, w, ivw, workers)
                if not warmed:
                    backend.block_until_ready(
                        run(backend.stage_batch_words(msg)))
                    warmed = True
                if timing in ("device", "device-sync"):
                    words = backend.stage_batch_words(msg)
                    backend.block_until_ready(words)
                    us, _ = _time_us(
                        lambda: backend.block_until_ready(run(words)))
                else:
                    us, _ = _time_us(
                        lambda: backend.block_until_ready(
                            run(backend.stage_batch_words(msg))))
                times.append(us)
        em.line(f"{backend.name.upper()} AES-{keybits} CBC-BATCHx{streams}, "
                f"{used}, {workers}, {_csv(times)}")
        _derived(em, used, times,
                 getattr(backend, "FLOOR_US", 0) if chained_ok else 0)
        # Worker-count invariance on a fixed key/IV set (the same determinism
        # check the block-mode sweeps run); compare-and-discard so peak host
        # memory stays at one extra output regardless of the worker list.
        ctx = backend.make_key(inv_key)
        got = np.asarray(backend.block_until_ready(
            backend.cbc_batch(ctx, backend.stage_batch_words(msg),
                              backend.stage_batch_words(inv_ivs), workers)))
        if inv_ref is None:
            inv_ref = got
        elif not np.array_equal(got, inv_ref):
            em.line(f"CBC-BATCH SHARD-INVARIANCE FAILED at workers={workers}")
            raise SystemExit(2)
    if len(workers_list) > 1:  # one worker count = nothing was compared
        em.line(f"CBC-batch shard invariance {workers_list}: passed")


def run_rc4_batch(em, backend, size, workers_list, iters, rng, streams):
    """S independent RC4 keystream scans sharded over chips — the sweep
    surface for dist.arc4_prep_batch_sharded (the sequential keygen phase
    scaled across streams). Rows are device-timed by construction: the
    keystream is generated on device and stays there for the XOR phase, so
    there is no staging to include (announced in the output)."""
    if not hasattr(backend, "arc4_prep_batch"):
        raise ValueError("rc4-batch requires the tpu backend")
    streams = max(1, min(streams, size))
    per = size // streams
    used = per * streams
    em.line(f"Batch of {streams} independent RC4 keystreams, {per} bytes "
            "each (device timing: keystreams are born and stay on device),")
    keys = [rng.integers(0, 256, 16, dtype=np.uint8).tobytes()
            for _ in range(streams)]
    # The KSA phase is timed separately, mirroring the reference's separate
    # "Generated a new key in" keygen line (test.c:84-91).
    us, states = _time_us(lambda: backend.arc4_batch_states(keys))
    em.line(f"Generated {streams} key schedules in {us}, ")
    inv_ref = None
    for workers in workers_list:
        backend.block_until_ready(
            backend.arc4_prep_batch(states, per, workers))  # untimed compile
        times = []
        out = None
        for _ in range(iters):
            us, out = _time_us(
                lambda: backend.block_until_ready(
                    backend.arc4_prep_batch(states, per, workers))
            )
            times.append(us)
        em.line(f"RC4-KEYGEN-BATCHx{streams}, {used}, {workers}, {_csv(times)}")
        _derived(em, used, times)
        got = np.asarray(out)
        if inv_ref is None:
            inv_ref = got
        elif not np.array_equal(got, inv_ref):
            em.line(f"RC4-BATCH SHARD-INVARIANCE FAILED at workers={workers}")
            raise SystemExit(2)
    # Stream 0 against the single-stream scan: the batch path must produce
    # the same keystream bytes the resumable single-stream API does.
    from ..models.arc4 import ARC4

    if not np.array_equal(inv_ref[0], ARC4(keys[0]).prep(per)):
        em.line("RC4-BATCH PARITY FAILED vs single-stream prep")
        raise SystemExit(2)
    em.line("RC4-batch parity vs single-stream: passed")
    if len(workers_list) > 1:  # one worker count = nothing was compared
        em.line(f"RC4-batch shard invariance {workers_list}: passed")


def check_shard_invariance(em, backend, size, workers_list, keybits, rng):
    """Same key + data through every worker count -> identical ciphertext.

    This is the determinism check the reference never ran (SURVEY.md §5
    "race detection"): its defect #1 (CTR sweeps silently running ECB) would
    have been caught by exactly this comparison.
    """
    msg = rng.integers(0, 256, size, dtype=np.uint8)
    key = rng.integers(0, 256, keybits // 8, dtype=np.uint8).tobytes()
    ctx = backend.make_key(key)
    words = backend.stage_words(msg)
    ctr_be = backend.ctr_be_words(NONCE)
    ref_ecb = ref_ctr = None
    for workers in workers_list:
        e = np.asarray(backend.block_until_ready(backend.ecb(ctx, words, workers)))
        c = np.asarray(backend.block_until_ready(backend.ctr(ctx, words, ctr_be, workers)))
        if ref_ecb is None:
            ref_ecb, ref_ctr = e, c
        else:
            if not (np.array_equal(e, ref_ecb) and np.array_equal(c, ref_ctr)):
                em.line(f"SHARD-INVARIANCE FAILED at workers={workers}")
                raise SystemExit(2)
    em.line(f"Shard invariance {workers_list}: passed")


def run_rc4(em, backend, size, workers_list, iters, rng, timing="e2e",
            rows=None):
    msg = rng.integers(0, 256, size, dtype=np.uint8)
    chained_ok = (timing == "device"
                  and hasattr(backend, "chained_device_times_us"))

    def one_row(workers):
        em.line(f"RC4, {size}, {workers}, ")
        key = rng.integers(0, 256, 16, dtype=np.uint8).tobytes()
        # Phase 1+2 (key schedule + keystream gen): sequential, timed once
        # per (size, workers) row, like the reference (test.c:84-91).
        us, ks = _time_us(lambda: backend.arc4_setup_prep(key, size))
        em.line(f"Generated a new key in {us}, ")
        ks_dev = backend.to_device(np.asarray(ks))
        data_dev = backend.to_device(msg)
        out = backend.block_until_ready(
            backend.arc4_crypt(data_dev, ks_dev, workers)  # untimed compile
        )
        if chained_ok:
            # XOR-phase kernel rate via the chained difference (the u8
            # carry keeps the passes data-dependent; see backends.py).
            crypt = lambda d, acc: backend.arc4_crypt(
                d ^ acc.astype(d.dtype), ks_dev, workers)
            # XOR is ~25x faster per byte than the AES kernels: the chain
            # needs proportionally more passes before the chained work
            # dominates transport jitter (see _chain_k's sizing rule).
            times = backend.chained_device_times_us(
                crypt, data_dev, iters, _chain_k(size, 8192, 8192))
        else:
            times = []
            for _ in range(iters):
                us, out = _time_us(
                    lambda: backend.block_until_ready(
                        backend.arc4_crypt(data_dev, ks_dev, workers)
                    )
                )
                times.append(us)
        em.line(f"{_csv(times)}")
        _derived(em, size, times,
                 getattr(backend, "FLOOR_US", 0) if chained_ok else 0)
        # XOR phase correctness (the reference checked nothing here).
        if out is not None and not np.array_equal(np.asarray(out), msg ^ np.asarray(ks)):
            em.line(f"RC4 XOR MISMATCH at workers={workers}")
            raise SystemExit(2)

    for i, workers in enumerate(workers_list):
        # Same per-worker-ROW resume granularity as run_aes_mode.
        if rows is not None and rows.replay(workers):
            continue
        with trace_mod.span("row", mode="rc4", size=size, workers=workers):
            one_row(workers)
        if rows is not None:
            rows.record(workers, last=(i == len(workers_list) - 1))


def arc4_self_test(em):
    """Rescorla-1994 vectors through setup->prep->crypt, like arc4_self_test
    (reference arc4.c:124-183), printed in the reference's format."""
    from ..models.arc4 import ARC4

    vectors = [
        ("0123456789abcdef", "0123456789abcdef", "75b7878099e0c596"),
        ("0123456789abcdef", "0000000000000000", "7494c2e7104b0879"),
        ("0000000000000000", "0000000000000000", "de188941a3375d3a"),
    ]
    for i, (key, pt, ct) in enumerate(vectors, 1):
        rc = ARC4(bytes.fromhex(key))
        ks = rc.prep(8)
        out = rc.crypt(np.frombuffer(bytes.fromhex(pt), np.uint8), ks)
        ok = out.tobytes().hex() == ct
        em.line(f"ARC4 test #{i}: {'passed' if ok else 'FAILED'}")
        if not ok:
            raise SystemExit(2)


class _RowCheckpoint:
    """Intra-unit worker-row checkpointing (ROADMAP follow-up closed in
    the obs PR): each completed worker row of a journaled unit is
    recorded — its emitted lines plus the post-row RNG state — the
    moment it finishes, so a unit that dies midway (SIGKILLed child,
    watchdog failure) re-runs from the last completed row instead of
    from the top. ``replay(row)`` re-emits a recorded row verbatim and
    restores the shared RNG stream (later rows stay byte-identical to
    an uninterrupted run's); a fresh row runs under a "row" span while
    a replayed one emits a "row-replayed" point, so the trace tells the
    two apart. Lines are sliced out of the unit-level Emitter capture
    (``capture_len``/``capture_since``), so the completed unit's record
    still carries the full line list."""

    def __init__(self, journal, unit, em, rng):
        self._journal, self._unit = journal, unit
        self._em, self._rng = em, rng
        self._recs = journal.rows(unit)
        self._mark = 0
        self.replayed = 0

    def replay(self, row) -> bool:
        rec = self._recs.get(str(row))
        if rec is None:
            self._mark = self._em.capture_len()
            return False
        for line in rec.get("lines", []):
            self._em.line(line)
        state = rec.get("rng_state")
        if state is not None:
            self._rng.bit_generator.state = state
        trace_mod.point("row-replayed", unit=self._unit, row=str(row))
        self.replayed += 1
        return True

    def record(self, row, last=False) -> None:
        # The unit's LAST row is never recorded: the unit's own completed
        # record lands immediately after (nothing can fail in between),
        # so the row record would be pure journal bloat — and the common
        # single-worker sweep keeps a row-free journal.
        if last:
            return
        self._journal.record_row(self._unit, str(row),
                                 self._em.capture_since(self._mark),
                                 self._rng.bit_generator.state)


def _sweep_config(args, sizes, workers_list, modes) -> dict:
    """The sweep's identity: everything that shapes the unit sequence or
    the bytes each unit emits. A rerun whose config hashes differently
    must NOT replay a journal recorded under this one (wrong rows into
    wrong slots); SweepJournal invalidates and starts fresh. The ONE
    builder shared by the isolate parent, its children, and plain
    --journal runs — a drifted copy would make every child invalidate
    its parent's journal."""
    return {
        "backend": args.backend, "engine": args.engine, "sizes": sizes,
        "workers": workers_list, "iters": args.iters,
        "keybits": args.keybits, "modes": modes, "streams": args.streams,
        "seed": args.seed, "timing": args.timing,
        "stream_chunk_mb": args.stream_chunk_mb,
    }


def _unit_names(modes, sizes, workers_list) -> list[str]:
    """Ordered unit names as a pure function of the config — the
    journal's replay contract, and what lets the isolate parent plan a
    sweep without constructing a backend. MUST mirror the unit-closure
    construction in main() exactly (main() asserts it does)."""
    names = [f"{mode}:{size}" for mode in modes for size in sizes]
    if len(workers_list) > 1 and {"ecb", "ctr"} & set(modes):
        names.append("shard-invariance")
    if "rc4" in modes:
        names.append("arc4-self-test")
    return names


def main(argv=None) -> int:
    # Honor a JAX_PLATFORMS=cpu pin through jax.config before the backend
    # constructor's first jax call — the env var alone does not stop a
    # site-hook-registered accelerator plugin from initializing a (possibly
    # wedged) tunnel (utils/platform.py).
    from ..utils.platform import pin_cpu_if_requested

    pin_cpu_if_requested()
    # Mint (or adopt) the trace run id BEFORE anything can spawn a
    # child: publishing it into os.environ is what lets every isolated
    # child join this run instead of starting its own (obs/trace.py).
    trace_mod.ensure_run()
    ap = argparse.ArgumentParser(
        description="our-tree-tpu benchmark sweep (reference CSV format)"
    )
    ap.add_argument("--backend", default="tpu", choices=("tpu", "c"))
    ap.add_argument("--engine", default="auto",
                    help="tpu backend compute engine (auto/jnp/bitslice/pallas)")
    ap.add_argument("--sizes-mb", default="1,10,100,1000",
                    help="comma list of message sizes in MiB")
    ap.add_argument("--workers", default="",
                    help="comma list of worker counts (default: 1,2,4,8 capped "
                         "at the device count)")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--keybits", type=int, default=256, choices=(128, 192, 256))
    ap.add_argument("--modes", default="ecb,ecb-dec,ctr,cbc-dec,rc4",
                    help="comma list from ecb,ecb-dec,ctr,cbc,cbc-dec,"
                         "cfb128,rc4,cbc-batch,rc4-batch (decrypt rows "
                         "measure the inverse circuit; CTR is symmetric)")
    ap.add_argument("--streams", type=int, default=32,
                    help="independent streams for the batch modes "
                         "(cbc-batch/rc4-batch): the stream axis is the "
                         "parallel axis that shards over chips")
    ap.add_argument("--seed", type=int, default=1337)
    ap.add_argument("--timing", default="e2e",
                    choices=("e2e", "device", "device-sync"),
                    help="e2e includes host<->device staging (reference GPU "
                         "harness convention); device reports per-pass "
                         "kernel time via the chained-difference "
                         "methodology (excludes staging AND the remote "
                         "transport's fixed dispatch+sync cost — "
                         "backends.py:chained_device_times_us); "
                         "device-sync keeps the per-call sync convention "
                         "(kernel + transport round trip)")
    ap.add_argument("--stream-chunk-mb", type=int, default=0, metavar="MB",
                    help="CTR messages larger than this stream through the "
                         "device in MB-sized chunks with counter carry "
                         "across seams (tpu backend; for messages larger "
                         "than device memory, e.g. the 16 GiB config). "
                         "Streamed rows are always e2e-timed (staging is "
                         "inherent) and announced in the output. 0 disables")
    ap.add_argument("--profile", default=None, metavar="DIR",
                    help="capture a jax.profiler trace of the sweep into DIR "
                         "(tpu backend only)")
    ap.add_argument("--out", default=None,
                    help="also write results to this file "
                         "(e.g. results.$(hostname).tpu)")
    ap.add_argument("--default-out", action="store_true",
                    help="write to results.<host>.<backend>")
    ap.add_argument("--journal", default=None, metavar="PATH",
                    help="checkpoint/resume journal (JSONL; env "
                         "OT_SWEEP_JOURNAL is the default): completed "
                         "sweep units append as they finish, and a rerun "
                         "with the SAME config skips them — re-emitting "
                         "their recorded rows and restoring the RNG "
                         "stream — so a SIGKILL/tunnel wedge mid-corpus "
                         "resumes at the failed row instead of losing the "
                         "run (docs/RESILIENCE.md). A changed config "
                         "invalidates the journal")
    ap.add_argument("--isolate", action="store_true",
                    help="run each sweep unit in its own child process "
                         "with a wall deadline (--unit-deadline): a hung "
                         "unit is SIGKILLed and journaled as failed "
                         "instead of wedging the sweep, and a unit that "
                         "fails --quarantine-after times is quarantined — "
                         "skipped on this and every resumed run with "
                         "degraded:[quarantined:<unit>] stamped. Requires "
                         "--journal and an explicit --workers list (the "
                         "supervising parent never touches the device, so "
                         "it cannot ask it for a worker cap)")
    ap.add_argument("--unit-deadline", type=float, metavar="S",
                    default=float(os.environ.get("OT_UNIT_DEADLINE", 600)),
                    help="--isolate: per-unit wall deadline in seconds "
                         "before the child process group is SIGKILLed "
                         "(env OT_UNIT_DEADLINE)")
    ap.add_argument("--quarantine-after", type=int, metavar="N",
                    default=int(os.environ.get("OT_QUARANTINE_AFTER", 3)),
                    help="quarantine a unit after N recorded failures "
                         "(journal failure rows, counted across runs; "
                         "env OT_QUARANTINE_AFTER)")
    ap.add_argument("--dispatch-deadline", type=float, metavar="S",
                    default=watchdog_mod.default_deadline_s(),
                    help="in-process watchdog deadline around each unit's "
                         "device work (resilience/watchdog.py): on expiry "
                         "all-thread stacks are dumped, the unit fails "
                         "with DispatchTimeout, and a journaled sweep "
                         "moves on instead of wedging. 0 disables "
                         "(env OT_DISPATCH_DEADLINE)")
    ap.add_argument("--unquarantine", action="append", default=None,
                    metavar="UNIT",
                    help="clear UNIT's recorded failure rows from the "
                         "journal (repeatable) — the quarantine-release "
                         "flow: the unit runs again on the next sweep "
                         "instead of being skipped forever. Requires "
                         "--journal (or OT_SWEEP_JOURNAL); no sweep runs. "
                         "Emits a quarantine-release trace event")
    ap.add_argument("--isolate-child", default=None, metavar="UNIT",
                    help=argparse.SUPPRESS)  # internal: run exactly UNIT
    args = ap.parse_args(argv)

    sizes = []
    for tok in args.sizes_mb.split(","):
        if not tok:
            continue
        nbytes = int(float(tok) * MIB) // 16 * 16  # whole AES blocks only
        if nbytes <= 0:
            ap.error(f"--sizes-mb entry {tok!r} is below one 16-byte block")
        sizes.append(nbytes)
    modes = [m.strip() for m in args.modes.split(",") if m.strip()]
    journal_path = args.journal or os.environ.get("OT_SWEEP_JOURNAL")

    if args.unquarantine:
        # Quarantine release: a ledger edit, not a sweep — it must work
        # without a backend and regardless of the journal's config hash
        # (the operator releasing a unit may not reproduce the exact
        # sweep flags that quarantined it).
        if not journal_path:
            ap.error("--unquarantine requires --journal "
                     "(or OT_SWEEP_JOURNAL): the journal holds the "
                     "failure rows to clear")
        cleared = journal_mod.clear_failures(journal_path, args.unquarantine)
        for unit, n in sorted(cleared.items()):
            if n:  # a release point for a unit never quarantined would
                # pollute every trace audit that reconstructs releases
                trace_mod.point("quarantine-release", unit=unit, cleared=n)
            print(f"# unquarantine: {unit}: cleared {n} failure row(s)"
                  + ("" if n else " — none were on file"),
                  file=sys.stderr, flush=True)
        return 0

    isolate_parent = args.isolate and args.isolate_child is None
    if isolate_parent:
        # The supervising parent never constructs a backend (never
        # touches jax, let alone the device) — the whole point of
        # isolation is that only disposable children face a possibly
        # wedged transport. Everything config-shaped must therefore be
        # derivable without a device, hence the explicit-workers rule.
        if not journal_path:
            ap.error("--isolate requires --journal (or OT_SWEEP_JOURNAL): "
                     "the journal is the supervisor's unit ledger")
        if not args.workers:
            ap.error("--isolate requires an explicit --workers list (the "
                     "parent cannot ask the device for a worker cap)")
    if args.workers:
        workers_list = [int(w) for w in args.workers.split(",") if w]

    if isolate_parent:
        out_path = args.out
        if args.default_out and not out_path:
            out_path = (f"results.{socket.gethostname().split('.')[0]}"
                        f".{args.backend}")
        em = Emitter(out_path)
        config = _sweep_config(args, sizes, workers_list, modes)
        names = _unit_names(modes, sizes, workers_list)
        # Every sweep-shaping flag, forwarded so each child derives the
        # SAME config hash (a child hashing differently would invalidate
        # — truncate — the parent's journal mid-sweep). The assert makes
        # adding a field to _sweep_config without a matching flag here a
        # loud failure instead of that silent truncation.
        child_config_flags = {
            "backend": ("--backend", args.backend),
            "engine": ("--engine", args.engine),
            "sizes": ("--sizes-mb", args.sizes_mb),
            "workers": ("--workers", args.workers),
            "iters": ("--iters", str(args.iters)),
            "keybits": ("--keybits", str(args.keybits)),
            "modes": ("--modes", args.modes),
            "streams": ("--streams", str(args.streams)),
            "seed": ("--seed", str(args.seed)),
            "timing": ("--timing", args.timing),
            "stream_chunk_mb": ("--stream-chunk-mb",
                                str(args.stream_chunk_mb)),
        }
        assert set(child_config_flags) == set(config), (
            "sweep-config fields without a forwarded child flag: "
            f"{set(config) ^ set(child_config_flags)}")
        child_base = [
            sys.executable, "-m", "our_tree_tpu.harness.bench",
            *(tok for flag in child_config_flags.values() for tok in flag),
            "--journal", journal_path,
            "--quarantine-after", str(args.quarantine_after),
            "--dispatch-deadline", str(args.dispatch_deadline),
            "--isolate",
        ]
        try:
            with trace_mod.span("sweep", role="supervisor",
                                backend=args.backend, modes=args.modes):
                quarantined = isolate_mod.run_isolated_sweep(
                    units=names,
                    child_argv=lambda unit: child_base + ["--isolate-child",
                                                          unit],
                    journal_path=journal_path, config=config, emit=em.line,
                    unit_deadline_s=args.unit_deadline,
                    quarantine_after=args.quarantine_after)
            if quarantined:
                print(f"# isolate: quarantined unit(s): "
                      f"{','.join(quarantined)}", file=sys.stderr)
            if degrade_mod.events():
                em.line("# degraded: " + ",".join(degrade_mod.events()))
        finally:
            em.close()
        return 0

    backend = make_backend(args.backend, args.engine)
    if not args.workers:
        cap = getattr(backend, "max_workers", 8)
        workers_list = [w for w in (1, 2, 4, 8) if w <= cap] or [1]

    out_path = args.out
    if args.default_out and not out_path:
        out_path = f"results.{socket.gethostname().split('.')[0]}.{args.backend}"
    em = Emitter(out_path)
    rng = np.random.default_rng(args.seed)  # srand(1337) of the reference

    journal = None
    if journal_path:
        journal = journal_mod.SweepJournal(
            journal_path, _sweep_config(args, sizes, workers_list, modes))
        if journal.pending:
            print(f"# journal: {journal.pending} completed unit(s) on file "
                  f"({journal_path}); resuming", file=sys.stderr)

    # The sweep as an ordered list of named UNITS — the journal's resume
    # granularity. Unit order is a pure function of the config (the
    # journal's replay contract); names carry mode and byte size so a
    # human can read the journal.
    # Every unit closure takes the unit's row checkpoint (None outside
    # journaled runs; the batch + check units take and ignore it — their
    # cross-row invariance comparisons need every row live, so they keep
    # unit-level resume granularity).
    def aes_unit(mode, size):
        return lambda rows=None: run_aes_mode(
            em, backend, mode, size, workers_list, args.iters, args.keybits,
            rng, args.timing, stream_chunk=args.stream_chunk_mb * MIB,
            rows=rows)

    units = []
    for mode in modes:
        for size in sizes:
            if mode == "rc4":
                units.append((f"rc4:{size}",
                              lambda size=size, rows=None: run_rc4(
                                  em, backend, size, workers_list,
                                  args.iters, rng, args.timing, rows=rows)))
            elif mode == "cbc-batch":
                units.append((f"cbc-batch:{size}",
                              lambda size=size, rows=None: run_cbc_batch(
                                  em, backend, size, workers_list,
                                  args.iters, args.keybits, rng,
                                  args.timing, args.streams)))
            elif mode == "rc4-batch":
                units.append((f"rc4-batch:{size}",
                              lambda size=size, rows=None: run_rc4_batch(
                                  em, backend, size, workers_list,
                                  args.iters, rng, args.streams)))
            else:
                units.append((f"{mode}:{size}", aes_unit(mode, size)))
    if len(workers_list) > 1 and {"ecb", "ctr"} & set(modes):
        units.append(("shard-invariance",
                      lambda rows=None: check_shard_invariance(
                          em, backend, min(sizes), workers_list,
                          args.keybits, rng)))
    if "rc4" in modes:
        units.append(("arc4-self-test", lambda rows=None: arc4_self_test(em)))
    # The isolate supervisor plans from _unit_names without a backend;
    # any drift between that pure function and this closure list would
    # strand its children on units that don't exist.
    assert [n for n, _ in units] == _unit_names(modes, sizes, workers_list)

    profiler_cm = None
    if args.profile and args.backend == "tpu":
        # The ONE capture seam (obs/profiler.py — shared with serve's
        # --profile-window / /profilez and the incident recorder): the
        # jax trace lands in the operator's DIR as before, and when
        # tracing is on the window ALSO leaves its summary in the run
        # layout so `obs.report --profile` joins sweep captures the
        # same way it joins serve ones.
        from ..obs import profiler as profiler_mod

        profiler_cm = profiler_mod.sweep_capture(args.profile)
        profiler_cm.__enter__()
    target = args.isolate_child
    try:
        for name, run_unit in units:
            if journal is not None:
                if (target is None
                        and journal.fail_count(name)
                        >= args.quarantine_after):
                    # The quarantine ledger: this unit hung/crashed its
                    # way past the threshold in earlier (isolated or
                    # watchdogged) runs. Re-running it would re-burn the
                    # budget on a known-bad config; skipping silently
                    # would masquerade as health. Skip LOUDLY.
                    trace_mod.point("quarantine", unit=name,
                                    fails=journal.fail_count(name))
                    degrade_mod.degrade(
                        f"quarantined:{name}",
                        f"{journal.fail_count(name)} journaled failure(s)")
                    continue
                # Gate on is_completed: with failure rows on file a unit
                # can be legitimately absent from the replay list, and a
                # bare skip() would misread that as corruption. An
                # isolated CHILD consumes by NAME (journal.take): after a
                # quarantine release, a completed unit's record can sit
                # out of sweep order on file, and skip()'s order-mismatch
                # defense would rewrite the journal out from under the
                # supervising parent's open handle. The child iterates
                # units in sweep order anyway, so per-entry RNG
                # restoration lands in the right order either way; the
                # plain in-process path keeps the strict-order skip()
                # (its truncate-and-re-run fallback is safe when no
                # other process holds the file).
                entry = ((journal.take(name) if target is not None
                          else journal.skip(name))
                         if journal.is_completed(name) else None)
                if entry is not None:
                    # Completed in a previous (interrupted) run: re-emit
                    # the recorded rows verbatim, restore the shared RNG
                    # stream to its post-unit state, and restore the
                    # unit's recorded demotions into the live ledger — a
                    # degraded run resumed must still end with the same
                    # `# degraded:` trailer (and the same journal stamps)
                    # as its uninterrupted twin.
                    for line in entry.get("lines", []):
                        em.line(line)
                    state = entry.get("rng_state")
                    if state is not None:
                        rng.bit_generator.state = state
                    for kind in entry.get("degraded", []):
                        degrade_mod.degrade(kind, "restored from journal")
                    trace_mod.point("unit-replayed", unit=name)
                    continue
            if target is not None and name != target:
                # Isolated child aimed at a later unit: this one failed or
                # was quarantined — the SUPERVISOR owns its story. Skip.
                # (The RNG stream diverges from an uninterrupted run's
                # here; result rows never encode RNG bytes, so surviving
                # units' output is unaffected — docs/RESILIENCE.md.)
                continue
            before = set(degrade_mod.events())
            em.begin_capture()
            rows_cp = (_RowCheckpoint(journal, name, em, rng)
                       if journal is not None else None)
            try:
                # The "unit" span wraps the whole unit attempt — an
                # injected crash or a watchdog raise closes it with its
                # error status on the way out; a SIGKILL leaves it
                # orphaned, which IS the record of where the child died.
                with trace_mod.span("unit", unit=name):
                    # unit_crash: the injected stand-in for a child
                    # process dying mid-unit (segfaulting XLA compile,
                    # OOM-killed worker). In-process it IS a crash: the
                    # raise escapes main() and the sweep dies nonzero —
                    # which is exactly what --isolate exists to contain.
                    faults_mod.check("unit_crash", f"unit {name}")
                    with watchdog_mod.deadline(args.dispatch_deadline,
                                               what=f"sweep unit {name}"):
                        run_unit(rows=rows_cp)
            except watchdog_mod.DispatchTimeout as e:
                em.end_capture()  # partial rows already hit stdout/--out
                print(f"# watchdog: {e}", file=sys.stderr, flush=True)
                if target is not None:
                    # The child dies nonzero and the SUPERVISOR records
                    # the failure row — recording here too would double-
                    # count the attempt toward quarantine.
                    raise
                if journal is not None:
                    reason = f"watchdog:{args.dispatch_deadline:.0f}s"
                    journal.record_failure(name, reason)
                    trace_mod.point("unit-failed", unit=name, reason=reason)
                continue  # journaled sweep: a hung unit, not a hung sweep
            finally:
                lines = em.end_capture()
            if journal is not None:
                # The DELTA, not the cumulative snapshot: each entry names
                # the demotions its own unit introduced, so replay can
                # restore them without every entry re-listing history.
                journal.record(name, lines, rng.bit_generator.state,
                               [k for k in degrade_mod.events()
                                if k not in before])
            if target is not None:
                return 0  # child: exactly one unit per process
        if target is not None:
            # The target never came up: either it was already journaled
            # (benign race with the supervisor) or the configs diverged.
            return 0 if (journal is not None
                         and journal.resumed) else 3
        if journal is not None and journal.resumed:
            print(f"# journal: skipped {journal.resumed} completed unit(s)",
                  file=sys.stderr)
        # The visible degradation record (resilience.degrade): a corpus
        # produced by a demoted configuration (native->lax.scan keygen,
        # engine fallback) says so in the artifact itself, not only on a
        # stderr stream some orchestrator rotated away.
        if degrade_mod.events():
            em.line("# degraded: " + ",".join(degrade_mod.events()))
    finally:
        if profiler_cm is not None:
            profiler_cm.__exit__(None, None, None)
        if journal is not None:
            journal.close()
        em.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
