"""Hex decrypt CLI — the `aes_ecb_d` equivalent (reference main_ecb_d.cu).

    python -m our_tree_tpu.harness.decrypt KEY CIPHERTEXT [CIPHERTEXT...]

Hex key (16/24/32 bytes) + hex ciphertext(s); prints hex plaintext per
argument. This was the reference's only cross-backend correctness path
(SURVEY.md §4 tier 2): pipe ciphertext from any implementation through it
and compare. Extended with --mode/--encrypt so every mode is reachable,
not just ECB.

One semantic difference, on purpose: the reference CLI fed hex through its
*big-endian* GPU word convention (GETWORD, reference AES.cu:42), which is
also the convention its buggy kernels used. This CLI speaks the byte stream
directly (hex in = byte order on the wire), matching the portable-C oracle
that defines parity for this framework.
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

from ..models.aes import AES, AES_DECRYPT, AES_ENCRYPT
from ..resilience import watchdog as watchdog_mod


def main(argv=None) -> int:
    # Before any device op: a JAX_PLATFORMS=cpu caller must never
    # initialize a (possibly wedged) accelerator tunnel — see
    # utils/platform.py for why the env var alone does not guarantee that.
    from ..utils.platform import pin_cpu_if_requested

    pin_cpu_if_requested()
    ap = argparse.ArgumentParser(
        prog="decrypt", description="AES hex en/decrypt (aes_ecb_d equivalent)"
    )
    ap.add_argument("key", help="hex key, 16/24/32 bytes")
    ap.add_argument("data", nargs="+", help="hex ciphertext (multiple of 16 bytes)")
    ap.add_argument("--encrypt", action="store_true",
                    help="encrypt instead of decrypt")
    ap.add_argument("--mode", default="ecb",
                    choices=("ecb", "cbc", "ctr", "cfb128"))
    ap.add_argument("--iv", default="00" * 16,
                    help="hex IV (cbc/cfb128) / initial counter (ctr)")
    ap.add_argument("--iv-off", type=int, default=0,
                    help="cfb128 resume offset into the feedback register "
                         "(reference aes.h iv_off; 0..15)")
    ap.add_argument("--engine", default="auto")
    ap.add_argument("--deadline", type=float, metavar="S",
                    default=watchdog_mod.default_deadline_s(),
                    help="watchdog deadline per crypt dispatch (seconds): "
                         "a wedged device turns into a diagnosed error "
                         "with an all-thread stack dump instead of a CLI "
                         "that never returns. 0 disables "
                         "(env OT_DISPATCH_DEADLINE)")
    args = ap.parse_args(argv)

    try:
        key = bytes.fromhex(args.key)
    except ValueError:
        print("Invalid hex key.", file=sys.stderr)
        return 1
    if len(key) not in (16, 24, 32):
        print("Invalid AES key size.", file=sys.stderr)  # main_ecb_d.cu:21-24
        return 1

    try:
        iv = bytes.fromhex(args.iv)
    except ValueError:
        print("Invalid hex IV.", file=sys.stderr)
        return 1
    if args.mode != "ecb" and len(iv) != 16:
        print("IV must be 16 bytes.", file=sys.stderr)
        return 1
    if not 0 <= args.iv_off < 16:
        print("iv-off must be in [0, 16).", file=sys.stderr)
        return 1
    if args.iv_off and args.mode != "cfb128":
        # A nonzero resume offset only has cfb128 semantics here; silently
        # computing from offset 0 would be exit-code-0 wrong output.
        print("iv-off is only valid with --mode cfb128.", file=sys.stderr)
        return 1

    a = AES(key, engine=args.engine)
    direction = AES_ENCRYPT if args.encrypt else AES_DECRYPT
    for hexdata in args.data:
        try:
            data = bytes.fromhex(hexdata)
        except ValueError:
            print("Invalid hex data.", file=sys.stderr)
            return 1
        if args.mode in ("ecb", "cbc") and len(data) % 16:
            # main_ecb_d.cu:26-29's guard, on bytes not words
            print("Data size must be a multiple of AES block size.",
                  file=sys.stderr)
            return 1
        try:
            # The whole crypt — including any engine compile and the
            # readback `.tobytes()` forces — under the dispatch watchdog:
            # this CLI is the cross-backend parity path, and a wedged
            # device must yield a diagnosed nonzero exit (with a stack
            # dump naming where it stuck), not a pipe that never closes.
            with watchdog_mod.deadline(
                    args.deadline, what=f"decrypt {args.mode} dispatch"):
                watchdog_mod.injected_hang("dispatch_hang",
                                           "decrypt dispatch")
                if args.mode == "ecb":
                    out = a.crypt_ecb(direction, data)
                elif args.mode == "cbc":
                    out, _ = a.crypt_cbc(
                        direction, np.frombuffer(iv, np.uint8), data)
                elif args.mode == "cfb128":
                    # Byte-granular: any data length is legal, and
                    # --iv-off resumes mid-block exactly like the
                    # reference's iv_off carry (aes.c:822-863).
                    out, _, _ = a.crypt_cfb128(
                        direction, args.iv_off, np.frombuffer(iv, np.uint8),
                        data,
                    )
                else:  # ctr is symmetric
                    out, _, _, _ = a.crypt_ctr(
                        0, np.frombuffer(iv, np.uint8),
                        np.zeros(16, np.uint8), data,
                    )
                text = out.tobytes().hex()
        except watchdog_mod.DispatchTimeout as e:
            print(f"Dispatch watchdog fired: {e}", file=sys.stderr)
            return 1
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
