"""Benchmark/CLI layer — the reference's L2 orchestration layer, unified.

- `bench` (python -m our_tree_tpu.harness.bench): size x workers sweep in
  the reference CSV format, `--backend={tpu,c}`.
- `decrypt` (python -m our_tree_tpu.harness.decrypt): hex in/out cipher CLI,
  the aes_ecb_d equivalent.
"""

from .backends import make_backend  # noqa: F401
