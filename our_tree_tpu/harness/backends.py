"""Benchmark backends: one protocol, multiple execution engines.

The reference repo had one harness per backend, each a standalone `main()`
with copy-pasted sweep loops (test.c, aes-modes/test.c, main_ecb_e.cu —
SURVEY.md §1 L2). Here a backend is an object with a tiny protocol
(`ecb` / `ctr` / `cbc` / `cfb128` / `arc4_setup_prep` / `arc4_crypt`) and one
sweep driver serves them all; `--backend={tpu,c}` replaces recompiling a
different directory.

  * "tpu"  — the JAX framework paths (any registered engine, any number of
    mesh shards). Workers map to mesh shards: the moral successor of the
    reference's pthread chunking (aes-modes/test.c:33-35), scatter/gather by
    sharding instead of pointer arithmetic.
  * "c"    — the framework's own native C runtime (runtime/, clean-room,
    pthread-parallel like the reference harnesses), loaded via ctypes.
"""

from __future__ import annotations

import time

import numpy as np

from ..obs import trace as _trace
from ..resilience import degrade as _degrade
from ..resilience import faults as _faults
from ..resilience import watchdog as _watchdog


class TpuBackend:
    """JAX/TPU execution: batched kernels, optional multi-chip sharding."""

    name = "tpu"

    #: Chained-difference timings below this are jitter artifacts, emitted
    #: as exactly this sentinel so reporting code can tell a floor from a
    #: measurement (chained_device_times_us / bench._derived).
    FLOOR_US = 1

    def __init__(self, engine: str = "auto"):
        import os
        import sys

        import jax

        from ..models import aes as aes_mod
        from ..models.arc4 import ARC4
        from ..parallel import dist

        self._jax = jax
        self._aes_mod = aes_mod
        self._ARC4 = ARC4
        self._dist = dist
        self.engine = aes_mod.resolve_engine(engine)
        self.max_workers = len(jax.devices())
        self._meshes: dict[int, object] = {}

        # Reproduce the last tune sweep's winning tile/MC for this device
        # kind (scripts/tune_tpu.py persists them via utils/ranking) before
        # any kernel is traced — sweep/corpus rows then measure the tuned
        # production config, not the static defaults. Explicit OT_PALLAS_*
        # env still wins; no-op on CPU (interpreter mode).
        from ..ops import pallas_aes

        pallas_aes.apply_stored_knobs(jax.devices()[0])

        # ARC4 keystream implementation, resolved ONCE at construction so
        # the lazy native build (a `make` subprocess) can never land inside
        # a timed region, and so a fallback is visible rather than silent:
        #   auto   — native C core when buildable, else the lax.scan (noted
        #            on stderr: the two differ by orders of magnitude);
        #   native — require the C core, fail loudly if it can't build;
        #   jax    — pin the on-device scan (parity tests use this).
        mode = os.environ.get("OT_ARC4_PREP", "auto")
        if mode not in ("auto", "native", "jax"):
            raise ValueError(
                f"OT_ARC4_PREP must be auto|native|jax, got {mode!r}"
            )
        self._arc4_native = None
        if mode != "jax":
            try:
                from ..runtime import native

                native.load()  # builds now, outside any timed region
                self._arc4_native = native.NativeARC4
            except Exception as e:
                if mode == "native":
                    raise
                # Through the shared chokepoint: a sweep whose keygen rows
                # silently timed the ~1000x-slower scan path is exactly
                # the masquerade degrade() exists to prevent — the journal
                # and any JSON reporting stamp this demotion.
                _degrade.degrade(
                    "native->lax.scan",
                    f"native runtime unavailable ({type(e).__name__})")
                print(f"# arc4 prep: native runtime unavailable "
                      f"({type(e).__name__}); keygen rows will time the "
                      "lax.scan path", file=sys.stderr)

    # -- helpers -----------------------------------------------------------
    def _mesh(self, workers: int):
        if workers not in self._meshes:
            self._meshes[workers] = self._dist.make_mesh(workers)
        return self._meshes[workers]

    def stage_words(self, data: np.ndarray):
        """Byte buffer -> device (N, 4) u32 LE words (the H2D staging step,
        cf. cudaMemcpy in reference AES.cu:236)."""
        from ..utils import packing

        # Flat u32 staging: a (N, 4) boundary array would pad its 4-wide
        # minor dim to the TPU's 128-lane tile (~32x HBM footprint and
        # staging bandwidth); every cipher entry point accepts the flat
        # stream (models/aes.py:ctr_crypt_words).
        return self._jax.device_put(
            packing.np_bytes_to_words(np.ascontiguousarray(data))
        )

    def block_until_ready(self, x):
        """Completion barrier for timed regions.

        `jax.block_until_ready` alone is not a reliable barrier on
        remote/tunnelled device transports, where it can return before the
        work is done (the same platform property bench.py's chained-digest
        methodology exists for) — timing around it would under-report. One
        scalar host readback PER ADDRESSABLE SHARD forces real completion
        on every device stream at O(1) data cost each (a whole-leaf probe
        would only force the shard owning it; a full reduction would add an
        O(N) pass to the timed region); the fixed round-trips are honest
        sync cost (the reference's GPU timings likewise include their sync,
        main_ecb_e.cu:37-44).

        Carries the ``dispatch_fail`` and ``dispatch_hang`` injection
        points: the barrier is where a wedged transport's failure
        actually surfaces. ``OT_FAULTS=dispatch_fail:N`` makes the first
        N barriers raise — CI's stand-in for a mid-sweep tunnel death —
        and ``dispatch_hang`` makes the barrier block "forever" (a
        GIL-releasing sleep), the stand-in for the tunnel that never
        answers, which only the watchdog or the --isolate supervisor can
        end (docs/RESILIENCE.md).
        """
        # The "barrier" span is where a wedged transport's wall time
        # actually accrues — obs.report counts it as device-seam time.
        with _trace.span("barrier", seam="TpuBackend.block_until_ready"):
            _faults.check("dispatch_fail", "TpuBackend.block_until_ready")
            _watchdog.injected_hang("dispatch_hang",
                                    "TpuBackend.block_until_ready")
            self._jax.block_until_ready(x)
            for leaf in self._jax.tree_util.tree_leaves(x):
                if not getattr(leaf, "size", 0):
                    continue
                shards = getattr(leaf, "addressable_shards", None)
                if shards:
                    for s in shards:
                        np.asarray(s.data.ravel()[-1:])
                else:
                    np.asarray(leaf.ravel()[-1:])
        return x

    def chained_device_times_us(self, crypt, words, iters: int, k: int):
        """Per-pass device-kernel µs via the chained-difference methodology
        (bench.py's): 1+k data-dependent passes inside ONE jit dispatch,
        each reported time = (T(1+k) - T(1)) / k.

        On a remote/tunnelled transport a single dispatch+sync costs a
        fixed ~0.1 s round trip regardless of buffer size, so per-call
        sync timing (--timing device-sync) floors every row at the
        transport latency — the round-4 corpus's 1 GiB rows read ~5 GB/s
        while the chained headline measured 33.7 from the same kernel
        (VERDICT r4 weak #1). `crypt(words, acc)` must thread the u32
        carry into an input the expensive work DEPENDS on (CTR: the
        counter — a data-only carry lets XLA hoist the whole keystream
        out of the loop; other modes: the data words). The scalar digest
        readback is both the completion barrier and the silently-
        skipped-work guard; the sum (not XOR) reduction keeps the carry
        alive through an even element count. k is traced, so one
        executable serves both chain lengths.
        """
        import jax
        import jax.numpy as jnp

        # Chain lengths are sized for accelerator pass rates; on CPU (CI,
        # smokes — interpreter-mode kernels, ~1000x slower) a 512-pass
        # chain would turn a 1 MiB smoke row into minutes. The clamp keeps
        # CPU rows methodology-identical, just shorter.
        if jax.devices()[0].platform == "cpu":
            k = min(k, 4)

        @jax.jit
        def chained(w, kk):
            def body(_, acc):
                return jnp.sum(crypt(w, acc), dtype=jnp.uint32)

            return jax.lax.fori_loop(jnp.uint32(0), kk, body, jnp.uint32(0))

        def run(kk):
            # Injection on the dispatch itself (not only the staging
            # barrier): a tunnel that wedges BETWEEN rows dies here, in
            # the chained readback, and the sweep journal's resume story
            # is rehearsed against exactly this raise. The span makes
            # each chained dispatch+readback a device-seam region in the
            # trace (~µs of span overhead inside the timed window when
            # tracing is ON; a no-op check when off — kernel timings in
            # production runs are unaffected).
            with _trace.span("chained-dispatch", k=int(kk),
                             seam="TpuBackend.chained_device_times_us"):
                _faults.check("dispatch_fail",
                              "TpuBackend.chained_device_times_us")
                _watchdog.injected_hang("dispatch_hang",
                                        "TpuBackend.chained_device_times_us")
                t0 = time.perf_counter()
                int(chained(words, jnp.uint32(kk)))
                return time.perf_counter() - t0

        run(1)  # compile + warm (one executable for every chain length)
        t1 = min(run(1) for _ in range(2))
        # Floor at FLOOR_US, not 0: transport jitter can push a chained
        # difference negative when k*pass_time is below the round-trip
        # noise; a 0 row would divide a reference-format consumer's
        # bytes/min(times) by zero. The sentinel is excluded from derived
        # GB/s (bench._derived) so a jitter artifact can never masquerade
        # as a best-of measurement.
        return [max(int((run(1 + k) - t1) / k * 1e6), self.FLOOR_US)
                for _ in range(iters)]

    # -- AES ---------------------------------------------------------------
    def make_key(self, key: bytes):
        return self._aes_mod.AES(key, engine=self.engine)

    def ecb(self, ctx, words, workers: int):
        if workers == 1:
            return self._aes_mod.ecb_encrypt_words(
                words, ctx.rk_enc, ctx.nr, self.engine
            )
        return self._dist.ecb_crypt_sharded(
            words, ctx.rk_enc, ctx.nr, self._mesh(workers), engine=self.engine
        )

    def ecb_dec(self, ctx, words, workers: int):
        """ECB decrypt — the inverse circuit (tower-only: no comparably
        small Boyar–Peralta inverse exists, ops/bitslice.py:inv_sbox_planes)
        whose throughput the encrypt-side sweeps never measured (VERDICT r2
        #4; the reference exercised both directions via aes_self_test,
        aes-modes/aes.c:1084-1330, and its decrypt CLI, main_ecb_d.cu)."""
        if workers == 1:
            return self._aes_mod.ecb_decrypt_words(
                words, ctx.rk_dec, ctx.nr, self.engine
            )
        return self._dist.ecb_crypt_sharded(
            words, ctx.rk_dec, ctx.nr, self._mesh(workers), encrypt=False,
            engine=self.engine,
        )

    def cbc_dec(self, ctx, words, iv_words, workers: int):
        """CBC decrypt — parallel (batch inverse cipher + shifted XOR), so
        unlike CBC encrypt it shards over workers (dist.cbc_decrypt_sharded,
        one-block halo exchange)."""
        if workers == 1:
            out, _ = self._aes_mod.cbc_decrypt_words(
                words, iv_words, ctx.rk_dec, ctx.nr, self.engine
            )
            return out
        return self._dist.cbc_decrypt_sharded(
            words, iv_words, ctx.rk_dec, ctx.nr, self._mesh(workers),
            engine=self.engine,
        )

    def ctr(self, ctx, words, ctr_be, workers: int):
        if workers == 1:
            return self._aes_mod.ctr_crypt_words(
                words, ctr_be, ctx.rk_enc, ctx.nr, self.engine
            )
        return self._dist.ctr_crypt_sharded(
            words, ctr_be, ctx.rk_enc, ctx.nr, self._mesh(workers),
            engine=self.engine,
        )

    def ctr_stream(self, ctx, msg: np.ndarray, nonce: np.ndarray,
                   chunk_bytes: int, workers: int) -> np.ndarray:
        """CTR over a message larger than device memory: stage, encrypt, and
        read back chunk-by-chunk, carrying the 128-bit counter across chunk
        seams (host-side, via the same byte-ripple semantics as the cipher).

        This is how the framework runs the reference's biggest configs (a
        16 GiB message does not fit a single chip's HBM): the resume-state
        API (models/aes.py) is the per-chunk seam, exactly as the
        reference's `nc_off`/counter carry lets its CTR resume mid-stream
        (aes-modes/aes.c:869-901). Output assembles on host.
        """
        from ..models.aes import _inc_counter_bytes
        from ..utils import packing

        chunk_bytes -= chunk_bytes % 16
        if chunk_bytes <= 0:
            raise ValueError("chunk_bytes must be at least one 16-byte block")
        out = np.empty_like(msg)
        nonce = np.array(nonce, dtype=np.uint8, copy=True)

        # Double-buffered pipeline: jax dispatch is async, so the readback
        # of chunk i is deferred until chunk i+1's staging + launch are in
        # flight — H2D, compute, and D2H of adjacent chunks overlap instead
        # of serializing (the counter bookkeeping is pure host math and
        # needs nothing from the device). One chunk in flight bounds device
        # memory at two chunks' worth of buffers.
        pending = None  # (dst_offset, nfull, device array)

        def drain(p):
            off_p, nfull_p, o = p
            out[off_p : off_p + nfull_p * 16] = packing.np_words_to_bytes(
                np.asarray(o, dtype=np.uint32)
            ).reshape(-1)

        for off in range(0, msg.size, chunk_bytes):
            part = msg[off : off + chunk_bytes]
            nfull = part.size // 16
            if nfull:
                words = self.stage_words(part[: nfull * 16])
                o = self.ctr(ctx, words, self.ctr_be_words(nonce), workers)
                if pending is not None:
                    drain(pending)
                pending = (off, nfull, o)
                nonce = _inc_counter_bytes(nonce, nfull)
            if part.size % 16:  # trailing partial block (last chunk only)
                tail_out, _, nonce, _ = ctx.crypt_ctr(
                    0, nonce, np.zeros(16, np.uint8), part[nfull * 16 :]
                )
                out[off + nfull * 16 : off + part.size] = tail_out
        if pending is not None:
            drain(pending)
        return out

    def cbc(self, ctx, words, iv_words, workers: int):
        if workers != 1:
            raise ValueError(
                "single-stream CBC encrypt is a sequential recurrence and "
                "cannot shard over workers; use cbc-batch (independent "
                "streams sharded over chips) for multi-worker scaling"
            )
        out, _ = self._aes_mod.cbc_encrypt_words(words, iv_words, ctx.rk_enc, ctx.nr)
        return out

    def cfb128(self, ctx, words, iv_words, workers: int):
        if workers != 1:
            raise ValueError(
                "single-stream CFB128 encrypt is a sequential recurrence and "
                "cannot shard over workers; batch independent streams instead"
            )
        out, _ = self._aes_mod.cfb128_encrypt_words(words, iv_words, ctx.rk_enc, ctx.nr)
        return out

    # -- batch sequence parallelism (independent streams over chips) -------
    def stage_batch_words(self, data2d: np.ndarray):
        """(S, bytes_per_stream) byte matrix -> device (S, 4N) u32 words."""
        from ..utils import packing

        w = packing.np_bytes_to_words(np.ascontiguousarray(data2d).reshape(-1))
        return self._jax.device_put(w.reshape(data2d.shape[0], -1))

    def cbc_batch(self, ctx, words_2d, ivs_2d, workers: int):
        """S independent CBC-encrypt streams sharded over `workers` chips —
        what cannot parallelise within a chained stream scales across
        streams (parallel/dist.py:cbc_encrypt_batch_sharded)."""
        out, _ = self._dist.cbc_encrypt_batch_sharded(
            words_2d, ivs_2d, ctx.rk_enc, ctx.nr, self._mesh(workers),
            engine=self.engine,
        )
        return out

    def arc4_batch_states(self, keys: list[bytes]):
        """Host-side KSA for S streams (the reference's sequential `setup`
        phase, arc4.c:43-67) -> (x, y, m) state stacks for the batch scan."""
        return self._ARC4.batch_states(keys)

    def arc4_prep_batch(self, states, length: int, workers: int):
        """S independent keystream scans sharded over `workers` chips;
        returns the (S, length) uint8 keystream batch (device)."""
        _, ks = self._dist.arc4_prep_batch_sharded(
            states, length, self._mesh(workers)
        )
        return ks

    def ctr_be_words(self, nonce: np.ndarray):
        import jax.numpy as jnp

        from ..utils import packing

        return jnp.asarray(packing.np_bytes_to_words(nonce).byteswap())

    def iv_words(self, iv: np.ndarray):
        import jax.numpy as jnp

        from ..utils import packing

        return jnp.asarray(packing.np_bytes_to_words(iv))

    # -- ARC4 --------------------------------------------------------------
    def arc4_setup_prep(self, key: bytes, length: int):
        """Phase 1+2: key schedule + sequential keystream generation.

        The keystream recurrence is inherently serial — there is nothing
        for an accelerator to parallelise, and a per-byte `lax.scan` pays
        device-step latency on every byte. The phase split (the reference's
        design, SURVEY.md §0) means the sequential phase runs on the best
        *serial* processor available — the host CPU via the native C core —
        while the parallel XOR phase scales on the device mesh. The
        implementation was resolved at construction (OT_ARC4_PREP; see
        __init__); bit-equality of the two is pinned by test_native.
        """
        if self._arc4_native is not None:
            return self._arc4_native(key).prep(length)
        return self._ARC4(key).prep(length)

    def arc4_crypt(self, data_dev, ks_dev, workers: int):
        if workers == 1:
            from ..models.arc4 import crypt

            return crypt(data_dev, ks_dev)
        return self._dist.xor_sharded(data_dev, ks_dev, self._mesh(workers))

    def to_device(self, arr: np.ndarray):
        return self._jax.device_put(arr)


def make_backend(name: str, engine: str = "auto"):
    if name == "tpu":
        return TpuBackend(engine)
    if name == "c":
        from ..runtime.native import CBackend

        return CBackend()
    raise ValueError(f"unknown backend {name!r} (expected 'tpu' or 'c')")
