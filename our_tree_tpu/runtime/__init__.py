"""Native runtime layer: clean-room C cipher cores + pthread parallel bulk
ops (csrc/), ctypes bindings and the `--backend=c` harness backend
(native.py). The role of the reference's C/C++ layer (SURVEY.md §1 L0-L1),
rebuilt from the specifications."""

from .native import CBackend, NativeAES, NativeARC4, load  # noqa: F401
