/* ARC4 with the three-phase split (setup / prep / crypt).
 *
 * The phase split — sequential keystream generation separated from the
 * data-parallel XOR — is the reference repo's one original design idea
 * (SURVEY.md §0; arc4.c:72-112) and is preserved here as API shape. The
 * implementation is the textbook KSA/PRGA, written fresh; state {x, y, m}
 * persists across ot_arc4_prep calls so a stream can resume, matching the
 * cross-call resumability of the reference (arc4.c:93-94). The XOR phase is
 * ot_xor in ot_parallel.c.
 */
#include "ot_crypt.h"

void ot_arc4_setup(ot_arc4_ctx *ctx, const uint8_t *key, size_t keylen) {
    ctx->x = 0;
    ctx->y = 0;
    for (int i = 0; i < 256; i++) ctx->m[i] = (uint8_t)i;
    if (keylen == 0) return; /* identity permutation; callers validate */
    int j = 0;
    for (int i = 0; i < 256; i++) {
        j = (j + ctx->m[i] + key[(size_t)i % keylen]) & 0xFF;
        uint8_t t = ctx->m[i];
        ctx->m[i] = ctx->m[j];
        ctx->m[j] = t;
    }
}

void ot_arc4_prep(ot_arc4_ctx *ctx, uint8_t *keystream, size_t len) {
    int x = ctx->x, y = ctx->y;
    uint8_t *m = ctx->m;
    for (size_t i = 0; i < len; i++) {
        x = (x + 1) & 0xFF;
        uint8_t a = m[x];
        y = (y + a) & 0xFF;
        uint8_t b = m[y];
        m[x] = b;
        m[y] = a;
        keystream[i] = m[(a + b) & 0xFF];
    }
    ctx->x = x;
    ctx->y = y;
}
