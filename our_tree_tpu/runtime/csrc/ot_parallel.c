/* pthread-parallel bulk cipher entry points.
 *
 * Work split = the reference harnesses' scheme: a message divided into
 * contiguous chunks, one worker thread each, joined at the end
 * (aes-modes/test.c:33-35,76-86; test.c:50-55). Unlike the reference,
 * chunk seams are computed in whole blocks and CTR workers derive their
 * chunk's counter with a 128-bit add, so any worker count produces
 * bit-identical output (the shard-invariance property the TPU path tests).
 */
#include "ot_crypt.h"

#include <pthread.h>
#include <stdlib.h>
#include <string.h>

#define OT_MAX_THREADS 64

/* Hardware path selection, decided once: AES-NI when the CPU has it and
 * OT_C_FORCE_PORTABLE is unset (the knob parity tests use to compare the
 * two implementations on the same machine). pthread_once, not a bare
 * static: the first callers are the worker threads themselves, which
 * would otherwise race the write (C11 UB, TSan-visible). */
static int aesni_on;
static pthread_once_t aesni_once = PTHREAD_ONCE_INIT;

static void decide_aesni(void) {
    aesni_on = ot_aesni_available() && !getenv("OT_C_FORCE_PORTABLE");
}

static int use_aesni(void) {
    pthread_once(&aesni_once, decide_aesni);
    return aesni_on;
}

/* 128-bit big-endian add: ctr += k. */
static void ctr_add(uint8_t ctr[16], uint64_t k) {
    for (int i = 15; i >= 0 && k; i--) {
        uint64_t v = (uint64_t)ctr[i] + (k & 0xFF);
        ctr[i] = (uint8_t)v;
        k = (k >> 8) + (v >> 8);
    }
}

typedef struct {
    const ot_aes_ctx *ctx;
    const uint8_t *in;
    uint8_t *out;
    size_t nblocks;      /* whole blocks in this chunk */
    size_t tail;         /* trailing bytes (last chunk only, CTR) */
    int encrypt;
    uint8_t ctr[16];     /* chunk-start counter (CTR) / prev block (CBC) */
} job_t;

static void *ecb_worker(void *arg) {
    job_t *j = (job_t *)arg;
    if (use_aesni()) {
        ot_aesni_ecb_chunk(j->ctx, j->encrypt, j->in, j->out, j->nblocks);
        return NULL;
    }
    for (size_t b = 0; b < j->nblocks; b++) {
        if (j->encrypt)
            ot_aes_encrypt_block(j->ctx, j->in + 16 * b, j->out + 16 * b);
        else
            ot_aes_decrypt_block(j->ctx, j->in + 16 * b, j->out + 16 * b);
    }
    return NULL;
}

static void *ctr_worker(void *arg) {
    job_t *j = (job_t *)arg;
    uint8_t ks[16];
    if (use_aesni()) {
        ot_aesni_ctr_chunk(j->ctx, j->ctr, j->in, j->out, j->nblocks, j->tail);
        return NULL;
    }
    for (size_t b = 0; b < j->nblocks; b++) {
        ot_aes_encrypt_block(j->ctx, j->ctr, ks);
        ctr_add(j->ctr, 1);
        for (int i = 0; i < 16; i++)
            j->out[16 * b + i] = (uint8_t)(j->in[16 * b + i] ^ ks[i]);
    }
    if (j->tail) {
        ot_aes_encrypt_block(j->ctx, j->ctr, ks);
        ctr_add(j->ctr, 1);
        for (size_t i = 0; i < j->tail; i++)
            j->out[16 * j->nblocks + i] =
                (uint8_t)(j->in[16 * j->nblocks + i] ^ ks[i]);
    }
    return NULL;
}

static void *cbc_dec_worker(void *arg) {
    /* P_b = D(C_b) ^ C_{b-1}: each chunk only needs the ciphertext block
     * before it, so decryption parallelises where encryption cannot —
     * the same asymmetry the TPU path exploits (models/aes.py). */
    job_t *j = (job_t *)arg;
    uint8_t prev[16], cur[16];
    if (use_aesni()) {
        ot_aesni_cbc_dec_chunk(j->ctx, j->ctr, j->in, j->out, j->nblocks);
        return NULL;
    }
    memcpy(prev, j->ctr, 16);
    for (size_t b = 0; b < j->nblocks; b++) {
        memcpy(cur, j->in + 16 * b, 16);
        ot_aes_decrypt_block(j->ctx, cur, j->out + 16 * b);
        for (int i = 0; i < 16; i++) j->out[16 * b + i] ^= prev[i];
        memcpy(prev, cur, 16);
    }
    return NULL;
}

static int clamp_threads(int nthreads, size_t work_items) {
    if (nthreads < 1) nthreads = 1;
    if (nthreads > OT_MAX_THREADS) nthreads = OT_MAX_THREADS;
    if ((size_t)nthreads > work_items && work_items > 0)
        nthreads = (int)work_items;
    return nthreads;
}

static void run_jobs(void *(*worker)(void *), job_t *jobs, int n) {
    pthread_t tids[OT_MAX_THREADS];
    int spawned[OT_MAX_THREADS] = {0};
    for (int t = 1; t < n; t++)
        spawned[t] = pthread_create(&tids[t], NULL, worker, &jobs[t]) == 0;
    worker(&jobs[0]); /* calling thread does the first chunk */
    for (int t = 1; t < n; t++) {
        if (spawned[t])
            pthread_join(tids[t], NULL);
        else
            worker(&jobs[t]); /* spawn failed: do the chunk inline */
    }
}

void ot_aes_ecb(const ot_aes_ctx *ctx, int encrypt, const uint8_t *in,
                uint8_t *out, size_t nblocks, int nthreads) {
    nthreads = clamp_threads(nthreads, nblocks);
    job_t jobs[OT_MAX_THREADS];
    size_t per = nblocks / (size_t)nthreads, off = 0;
    for (int t = 0; t < nthreads; t++) {
        size_t take = per + ((size_t)t < nblocks % (size_t)nthreads ? 1 : 0);
        jobs[t] = (job_t){ctx, in + 16 * off, out + 16 * off, take, 0,
                          encrypt, {0}};
        off += take;
    }
    run_jobs(ecb_worker, jobs, nthreads);
}

void ot_aes_ctr(const ot_aes_ctx *ctx, uint8_t nonce[16], const uint8_t *in,
                uint8_t *out, size_t len, int nthreads) {
    size_t nblocks = len / 16, tail = len % 16;
    size_t total_blocks = nblocks + (tail ? 1 : 0);
    nthreads = clamp_threads(nthreads, total_blocks ? total_blocks : 1);
    job_t jobs[OT_MAX_THREADS];
    size_t per = nblocks / (size_t)nthreads, off = 0;
    for (int t = 0; t < nthreads; t++) {
        size_t take = per + ((size_t)t < nblocks % (size_t)nthreads ? 1 : 0);
        jobs[t] = (job_t){ctx, in + 16 * off, out + 16 * off, take,
                          (t == nthreads - 1) ? tail : 0, 1, {0}};
        memcpy(jobs[t].ctr, nonce, 16);
        ctr_add(jobs[t].ctr, (uint64_t)off); /* per-chunk counter offset */
        off += take;
    }
    run_jobs(ctr_worker, jobs, nthreads);
    ctr_add(nonce, (uint64_t)(nblocks + (tail ? 1 : 0)));
}

void ot_aes_cbc_decrypt(const ot_aes_ctx *ctx, uint8_t iv[16],
                        const uint8_t *in, uint8_t *out, size_t nblocks,
                        int nthreads) {
    nthreads = clamp_threads(nthreads, nblocks);
    if (nblocks == 0) return;
    job_t jobs[OT_MAX_THREADS];
    size_t per = nblocks / (size_t)nthreads, off = 0;
    uint8_t last[16];
    memcpy(last, in + 16 * (nblocks - 1), 16);
    for (int t = 0; t < nthreads; t++) {
        size_t take = per + ((size_t)t < nblocks % (size_t)nthreads ? 1 : 0);
        jobs[t] = (job_t){ctx, in + 16 * off, out + 16 * off, take, 0, 0, {0}};
        memcpy(jobs[t].ctr, off == 0 ? iv : in + 16 * (off - 1), 16);
        off += take;
    }
    run_jobs(cbc_dec_worker, jobs, nthreads);
    memcpy(iv, last, 16); /* aes.c:792 semantics: iv <- last ciphertext */
}

typedef struct {
    const uint8_t *a, *b;
    uint8_t *out;
    size_t len;
} xor_job_t;

static void *xor_worker(void *arg) {
    xor_job_t *j = (xor_job_t *)arg;
    for (size_t i = 0; i < j->len; i++) j->out[i] = (uint8_t)(j->a[i] ^ j->b[i]);
    return NULL;
}

void ot_xor(const uint8_t *data, const uint8_t *keystream, uint8_t *out,
            size_t len, int nthreads) {
    nthreads = clamp_threads(nthreads, len ? len : 1);
    xor_job_t jobs[OT_MAX_THREADS];
    pthread_t tids[OT_MAX_THREADS];
    size_t per = len / (size_t)nthreads, off = 0;
    for (int t = 0; t < nthreads; t++) {
        size_t take = per + ((size_t)t < len % (size_t)nthreads ? 1 : 0);
        jobs[t] = (xor_job_t){data + off, keystream + off, out + off, take};
        off += take;
    }
    int spawned[OT_MAX_THREADS] = {0};
    for (int t = 1; t < nthreads; t++)
        spawned[t] = pthread_create(&tids[t], NULL, xor_worker, &jobs[t]) == 0;
    xor_worker(&jobs[0]);
    for (int t = 1; t < nthreads; t++) {
        if (spawned[t])
            pthread_join(tids[t], NULL);
        else
            xor_worker(&jobs[t]);
    }
}
