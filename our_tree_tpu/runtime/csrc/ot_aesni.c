/* Hardware AES path: AES-NI bulk chunk workers.
 *
 * This is the framework's equivalent of the reference's SIMD backend
 * (aes-modes/aesni.c — SURVEY.md §2 component #2), built differently:
 * the reference expands keys with _mm_aeskeygenassist (AES-256 only) and
 * processes one block per loop iteration; here the portable core's byte
 * round keys (ot_aes.c, any key size) are simply loaded into xmm
 * registers, decryption uses the spec's equivalent-inverse-cipher
 * (_mm_aesimc-transformed middle keys, FIPS-197 §5.3.5), and the bulk
 * loops process STRIDE blocks interleaved so the aesenc pipeline stays
 * full — one in-flight block per issue slot, the ILP analogue of the
 * bitsliced engine's 32-blocks-per-lane packing.
 *
 * Everything is runtime-gated on cpuid (__builtin_cpu_supports), so the
 * portable core remains the fallback and OT_C_FORCE_PORTABLE pins it for
 * parity tests.
 */
#include "ot_crypt.h"

#if defined(__x86_64__) || defined(__i386__)
#include <string.h>
#include <wmmintrin.h>

int ot_aesni_available(void) {
    return __builtin_cpu_supports("aes") && __builtin_cpu_supports("sse2");
}

#define STRIDE 8

typedef struct {
    __m128i k[15];
} keyvec_t;

static void load_enc_keys(const ot_aes_ctx *ctx, keyvec_t *kv) {
    for (int i = 0; i <= ctx->nr; i++)
        kv->k[i] = _mm_loadu_si128((const __m128i *)ctx->rk[i]);
}

/* Equivalent inverse cipher: dk[0] = rk[nr], middle keys InvMixColumns-
 * transformed, dk[nr] = rk[0]. */
static void load_dec_keys(const ot_aes_ctx *ctx, keyvec_t *kv) {
    int nr = ctx->nr;
    kv->k[0] = _mm_loadu_si128((const __m128i *)ctx->rk[nr]);
    for (int i = 1; i < nr; i++)
        kv->k[i] =
            _mm_aesimc_si128(_mm_loadu_si128((const __m128i *)ctx->rk[nr - i]));
    kv->k[nr] = _mm_loadu_si128((const __m128i *)ctx->rk[0]);
}

/* w blocks (w <= STRIDE) through the full pipeline, interleaved. */
static void enc_group(const keyvec_t *kv, int nr, __m128i b[STRIDE], int w) {
    for (int i = 0; i < w; i++) b[i] = _mm_xor_si128(b[i], kv->k[0]);
    for (int r = 1; r < nr; r++)
        for (int i = 0; i < w; i++) b[i] = _mm_aesenc_si128(b[i], kv->k[r]);
    for (int i = 0; i < w; i++) b[i] = _mm_aesenclast_si128(b[i], kv->k[nr]);
}

static void dec_group(const keyvec_t *kv, int nr, __m128i b[STRIDE], int w) {
    for (int i = 0; i < w; i++) b[i] = _mm_xor_si128(b[i], kv->k[0]);
    for (int r = 1; r < nr; r++)
        for (int i = 0; i < w; i++) b[i] = _mm_aesdec_si128(b[i], kv->k[r]);
    for (int i = 0; i < w; i++) b[i] = _mm_aesdeclast_si128(b[i], kv->k[nr]);
}

void ot_aesni_ecb_chunk(const ot_aes_ctx *ctx, int encrypt, const uint8_t *in,
                        uint8_t *out, size_t nblocks) {
    keyvec_t kv;
    __m128i b[STRIDE];
    if (encrypt)
        load_enc_keys(ctx, &kv);
    else
        load_dec_keys(ctx, &kv);
    for (size_t off = 0; off < nblocks; off += STRIDE) {
        int w = (int)(nblocks - off < STRIDE ? nblocks - off : STRIDE);
        for (int i = 0; i < w; i++)
            b[i] = _mm_loadu_si128((const __m128i *)(in + 16 * (off + i)));
        if (encrypt)
            enc_group(&kv, ctx->nr, b, w);
        else
            dec_group(&kv, ctx->nr, b, w);
        for (int i = 0; i < w; i++)
            _mm_storeu_si128((__m128i *)(out + 16 * (off + i)), b[i]);
    }
}

/* 128-bit big-endian increment, local copy (ot_parallel.c owns the
 * canonical chunk-offset add; this is the per-block ripple). */
static void be_inc(uint8_t ctr[16]) {
    for (int i = 15; i >= 0; i--)
        if (++ctr[i]) break;
}

void ot_aesni_ctr_chunk(const ot_aes_ctx *ctx, uint8_t ctr[16],
                        const uint8_t *in, uint8_t *out, size_t nblocks,
                        size_t tail) {
    keyvec_t kv;
    __m128i b[STRIDE];
    load_enc_keys(ctx, &kv);
    /* The counter lives in two big-endian-valued qwords in registers; each
     * block is built with one bswap pair + a vector set. The earlier
     * per-block memcpy + byte-ripple through a stack buffer cost a
     * store-forwarding round-trip per block that outweighed the AES
     * pipeline itself. The 128-bit ripple semantics are unchanged:
     * ++lo == 0 carries into hi (reference aes-modes/aes.c:879-884). */
    uint64_t hi, lo;
    memcpy(&hi, ctr, 8);
    memcpy(&lo, ctr + 8, 8);
    hi = __builtin_bswap64(hi);
    lo = __builtin_bswap64(lo);
    for (size_t off = 0; off < nblocks; off += STRIDE) {
        int w = (int)(nblocks - off < STRIDE ? nblocks - off : STRIDE);
        for (int i = 0; i < w; i++) {
            b[i] = _mm_set_epi64x((long long)__builtin_bswap64(lo),
                                  (long long)__builtin_bswap64(hi));
            if (++lo == 0) hi++;
        }
        enc_group(&kv, ctx->nr, b, w);
        for (int i = 0; i < w; i++) {
            __m128i d =
                _mm_loadu_si128((const __m128i *)(in + 16 * (off + i)));
            _mm_storeu_si128((__m128i *)(out + 16 * (off + i)),
                             _mm_xor_si128(d, b[i]));
        }
    }
    /* Write the advanced counter back for the caller/tail (in-place
     * contract of this function, matching the resume-state semantics). */
    {
        uint64_t hb = __builtin_bswap64(hi), lb = __builtin_bswap64(lo);
        memcpy(ctr, &hb, 8);
        memcpy(ctr + 8, &lb, 8);
    }
    if (tail) {
        uint8_t ks[16];
        b[0] = _mm_loadu_si128((const __m128i *)ctr);
        be_inc(ctr);
        enc_group(&kv, ctx->nr, b, 1);
        _mm_storeu_si128((__m128i *)ks, b[0]);
        for (size_t i = 0; i < tail; i++)
            out[16 * nblocks + i] = (uint8_t)(in[16 * nblocks + i] ^ ks[i]);
    }
}

void ot_aesni_cbc_dec_chunk(const ot_aes_ctx *ctx, const uint8_t prev0[16],
                            const uint8_t *in, uint8_t *out, size_t nblocks) {
    keyvec_t kv;
    __m128i b[STRIDE], prev[STRIDE + 1];
    load_dec_keys(ctx, &kv);
    prev[0] = _mm_loadu_si128((const __m128i *)prev0);
    for (size_t off = 0; off < nblocks; off += STRIDE) {
        int w = (int)(nblocks - off < STRIDE ? nblocks - off : STRIDE);
        for (int i = 0; i < w; i++) {
            prev[i + 1] =
                _mm_loadu_si128((const __m128i *)(in + 16 * (off + i)));
            b[i] = prev[i + 1];
        }
        dec_group(&kv, ctx->nr, b, w);
        for (int i = 0; i < w; i++)
            _mm_storeu_si128((__m128i *)(out + 16 * (off + i)),
                             _mm_xor_si128(b[i], prev[i]));
        prev[0] = prev[w];
    }
}

#else /* non-x86: portable core only */

int ot_aesni_available(void) { return 0; }
void ot_aesni_ecb_chunk(const ot_aes_ctx *ctx, int encrypt, const uint8_t *in,
                        uint8_t *out, size_t nblocks) {
    (void)ctx; (void)encrypt; (void)in; (void)out; (void)nblocks;
}
void ot_aesni_ctr_chunk(const ot_aes_ctx *ctx, uint8_t ctr[16],
                        const uint8_t *in, uint8_t *out, size_t nblocks,
                        size_t tail) {
    (void)ctx; (void)ctr; (void)in; (void)out; (void)nblocks; (void)tail;
}
void ot_aesni_cbc_dec_chunk(const ot_aes_ctx *ctx, const uint8_t prev0[16],
                            const uint8_t *in, uint8_t *out, size_t nblocks) {
    (void)ctx; (void)prev0; (void)in; (void)out; (void)nblocks;
}

#endif
