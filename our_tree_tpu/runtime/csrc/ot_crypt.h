/* our-tree-tpu native runtime: clean-room symmetric-cipher cores.
 *
 * This is the framework's C layer — the role the portable C / AES-NI /
 * CUDA trio plays in the reference repo (SURVEY.md §1 L0/L1), rebuilt from
 * the specifications (FIPS-197, NIST SP 800-38A, the ARC4 folklore spec)
 * rather than ported: the cipher state is the FIPS byte matrix, not the
 * reference's 32-bit T-table words (aes-modes/aes.c:601-645), and the only
 * lookup tables are the runtime-generated S-boxes.
 *
 * Bulk entry points are pthread-parallel with the same work split the
 * reference harnesses use — contiguous chunks, one worker each
 * (aes-modes/test.c:33-35) — so `--backend=c` benchmarks measure the same
 * parallelism scheme on CPU that the TPU backend expresses with shard_map.
 */
#ifndef OT_CRYPT_H
#define OT_CRYPT_H

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct {
    int nr;               /* rounds: 10/12/14 */
    uint8_t rk[15][16];   /* round keys as byte blocks, enc schedule */
} ot_aes_ctx;

/* keybits in {128, 192, 256}; returns 0 on success, -1 on bad size. */
int ot_aes_setkey(ot_aes_ctx *ctx, const uint8_t *key, int keybits);

void ot_aes_encrypt_block(const ot_aes_ctx *ctx, const uint8_t in[16],
                          uint8_t out[16]);
void ot_aes_decrypt_block(const ot_aes_ctx *ctx, const uint8_t in[16],
                          uint8_t out[16]);

/* Bulk ECB over nblocks 16-byte blocks, split across nthreads workers. */
void ot_aes_ecb(const ot_aes_ctx *ctx, int encrypt, const uint8_t *in,
                uint8_t *out, size_t nblocks, int nthreads);

/* CTR with a 128-bit big-endian post-increment counter (the reference's
 * semantics, aes-modes/aes.c:869-901); len in bytes, any length. Each
 * worker derives its chunk's counter offset — the seam bookkeeping of
 * SURVEY.md §7 hard part #6, on CPU. nonce is advanced in place by the
 * number of whole blocks consumed so streams can resume. */
void ot_aes_ctr(const ot_aes_ctx *ctx, uint8_t nonce[16], const uint8_t *in,
                uint8_t *out, size_t len, int nthreads);

/* CBC (SP 800-38A): encrypt is inherently sequential; decrypt is
 * chunk-parallel (each chunk's chain needs only ciphertext). iv updated in
 * place to the last ciphertext block, as in the reference (aes.c:792,807). */
void ot_aes_cbc_encrypt(const ot_aes_ctx *ctx, uint8_t iv[16],
                        const uint8_t *in, uint8_t *out, size_t nblocks);
void ot_aes_cbc_decrypt(const ot_aes_ctx *ctx, uint8_t iv[16],
                        const uint8_t *in, uint8_t *out, size_t nblocks,
                        int nthreads);

/* CFB128 with byte-granular resume offset, semantics of aes.c:822-863. */
void ot_aes_cfb128(const ot_aes_ctx *ctx, int encrypt, int *iv_off,
                   uint8_t iv[16], const uint8_t *in, uint8_t *out,
                   size_t len);

/* Hardware AES (AES-NI) chunk workers — the framework's SIMD backend
 * (reference component #2 role). Runtime-gated: callers must check
 * ot_aesni_available(); the bulk dispatchers in ot_parallel.c do this and
 * fall back to the portable core (OT_C_FORCE_PORTABLE env pins portable
 * for parity testing). Chunk functions mirror the per-worker loops. */
int ot_aesni_available(void);
void ot_aesni_ecb_chunk(const ot_aes_ctx *ctx, int encrypt, const uint8_t *in,
                        uint8_t *out, size_t nblocks);
void ot_aesni_ctr_chunk(const ot_aes_ctx *ctx, uint8_t ctr[16],
                        const uint8_t *in, uint8_t *out, size_t nblocks,
                        size_t tail);
void ot_aesni_cbc_dec_chunk(const ot_aes_ctx *ctx, const uint8_t prev0[16],
                            const uint8_t *in, uint8_t *out, size_t nblocks);

/* ARC4 in the reference's three phases (its one original design idea,
 * SURVEY.md §0): setup (KSA), prep (sequential PRGA -> keystream buffer),
 * crypt (parallel XOR). State persists across prep calls. */
typedef struct {
    int x, y;
    uint8_t m[256];
} ot_arc4_ctx;

void ot_arc4_setup(ot_arc4_ctx *ctx, const uint8_t *key, size_t keylen);
void ot_arc4_prep(ot_arc4_ctx *ctx, uint8_t *keystream, size_t len);
void ot_xor(const uint8_t *data, const uint8_t *keystream, uint8_t *out,
            size_t len, int nthreads);

#ifdef __cplusplus
}
#endif
#endif /* OT_CRYPT_H */
