/* ot_bench — the reference harness shape (test.c / aes-modes/test.c), one
 * executable, two dispatch targets:
 *
 *   --backend=c    sweep the native runtime in-process (pthread workers,
 *                  gettimeofday-style timing, CSV rows on stdout — the
 *                  modern form of reference aes-modes/test.c:353-446).
 *   --backend=tpu  embed CPython and hand the identical sweep arguments to
 *                  our_tree_tpu.harness.bench — the "thin shim" by which
 *                  the C harness calls the TPU path (BASELINE.json north
 *                  star; the reference's GPU analogue was a separate nvcc
 *                  binary, main_ecb_e.cu).
 *
 * CSV format matches the reference results corpus:
 *   <name>, <bytes>, <threads>, t1, ..., tN,
 *
 * Build: make ot_bench (links libpython for the tpu dispatch).
 */
#include "ot_crypt.h"

#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/time.h>

#define MAX_LIST 16

static long long now_us(void) {
    struct timeval tv;
    gettimeofday(&tv, NULL);
    return (long long)tv.tv_sec * 1000000 + tv.tv_usec;
}

/* Exact token match in a comma-separated mode list: plain strstr would
 * make --modes=ecb-dec also enable the "ecb" sweep. */
static int has_mode(const char *modes, const char *tok) {
    size_t n = strlen(tok);
    const char *p = modes;
    while ((p = strstr(p, tok)) != NULL) {
        int left_ok = (p == modes) || (p[-1] == ',');
        int right_ok = (p[n] == '\0') || (p[n] == ',');
        if (left_ok && right_ok) return 1;
        p += 1;
    }
    return 0;
}

static int parse_list(const char *s, long long *out, int cap) {
    int n = 0;
    while (*s && n < cap) {
        out[n++] = atoll(s);
        const char *c = strchr(s, ',');
        if (!c) break;
        s = c + 1;
    }
    return n;
}

/* xorshift PRNG, seeded 1337 like the reference (test.c:131). */
static unsigned long long rng_state = 1337;
static unsigned char rng_byte(void) {
    rng_state ^= rng_state << 13;
    rng_state ^= rng_state >> 7;
    rng_state ^= rng_state << 17;
    return (unsigned char)rng_state;
}

static void fill_random(unsigned char *p, size_t n) {
    for (size_t i = 0; i < n; i++) p[i] = rng_byte();
}

static void sweep_aes(const char *mode, size_t size, const long long *threads,
                      int nthreads_cnt, int iters, int keybits) {
    unsigned char *msg = malloc(size), *out = malloc(size);
    unsigned char key[32], nonce[16];
    if (!msg || !out) { fprintf(stderr, "alloc failed\n"); exit(1); }
    fill_random(msg, size);
    for (int t = 0; t < nthreads_cnt; t++) {
        int nt = (int)threads[t];
        printf("C AES-%d %s, %zu, %d, ", keybits, mode, size, nt);
        for (int it = 0; it < iters; it++) {
            fill_random(key, sizeof key);       /* per-iter rekey, test.c:301 */
            ot_aes_ctx ctx;
            if (ot_aes_setkey(&ctx, key, keybits) != 0) {
                fprintf(stderr, "invalid --keybits=%d\n", keybits);
                exit(1);
            }
            memset(nonce, 0xA5, sizeof nonce);
            long long t0 = now_us();
            if (strcmp(mode, "ECB") == 0)
                ot_aes_ecb(&ctx, 1, msg, out, size / 16, nt);
            else if (strcmp(mode, "ECB-DEC") == 0)
                /* Inverse cipher (decrypt rows measure the inverse round
                 * structure; throughput is data-independent, so decrypting
                 * random bytes is a faithful measurement). */
                ot_aes_ecb(&ctx, 0, msg, out, size / 16, nt);
            else if (strcmp(mode, "CBC-DEC") == 0)
                /* Chunk-parallel, unlike CBC encrypt (each chunk's chain
                 * needs only ciphertext — ot_crypt.h). */
                ot_aes_cbc_decrypt(&ctx, nonce, msg, out, size / 16, nt);
            else
                ot_aes_ctr(&ctx, nonce, msg, out, size, nt);
            printf("%lld, ", now_us() - t0);
        }
        printf("\n");
    }
    free(msg);
    free(out);
}

static void sweep_rc4(size_t size, const long long *threads, int nthreads_cnt,
                      int iters) {
    unsigned char *msg = malloc(size), *out = malloc(size);
    unsigned char *ks = malloc(size);
    unsigned char key[16];
    if (!msg || !out || !ks) { fprintf(stderr, "alloc failed\n"); exit(1); }
    fill_random(msg, size);
    for (int t = 0; t < nthreads_cnt; t++) {
        int nt = (int)threads[t];
        printf("RC4, %zu, %d, \n", size, nt);
        fill_random(key, sizeof key);
        ot_arc4_ctx ctx;
        long long t0 = now_us();
        ot_arc4_setup(&ctx, key, sizeof key);
        ot_arc4_prep(&ctx, ks, size);           /* sequential phase, timed */
        printf("Generated a new key in %lld, \n", now_us() - t0);
        for (int it = 0; it < iters; it++) {
            t0 = now_us();
            ot_xor(msg, ks, out, size, nt);      /* parallel phase */
            printf("%lld, ", now_us() - t0);
        }
        printf("\n");
    }
    free(msg); free(out); free(ks);
}

#ifdef OT_WITH_PYTHON
#include <Python.h>

static int dispatch_tpu(const char *sizes, const char *threads, int iters,
                        int keybits, const char *modes) {
    /* The thin shim: same sweep arguments, TPU execution. */
    char code[1024];
    snprintf(code, sizeof code,
             "import sys\n"
             "from our_tree_tpu.harness.bench import main\n"
             "sys.exit(main(['--sizes-mb','%s','--workers','%s',"
             "'--iters','%d','--keybits','%d','--modes','%s']))\n",
             sizes, threads, iters, keybits, modes);
    Py_Initialize();
    int rc = PyRun_SimpleString(code);
    if (Py_FinalizeEx() < 0) rc = 1;
    return rc == 0 ? 0 : 1;
}
#endif

int main(int argc, char **argv) {
    const char *backend = "c", *sizes_s = "1,10,100,1000";
    /* Default mode list matches harness/bench.py's default, so the tpu
     * shim forwards the same sweep either way it is invoked. */
    const char *threads_s = "1,2,4,8", *modes = "ecb,ecb-dec,ctr,cbc-dec,rc4";
    int iters = 10, keybits = 256;
    for (int i = 1; i < argc; i++) {
        if (strncmp(argv[i], "--backend=", 10) == 0) backend = argv[i] + 10;
        else if (strncmp(argv[i], "--sizes=", 8) == 0) sizes_s = argv[i] + 8;
        else if (strncmp(argv[i], "--threads=", 10) == 0) threads_s = argv[i] + 10;
        else if (strncmp(argv[i], "--iters=", 8) == 0) iters = atoi(argv[i] + 8);
        else if (strncmp(argv[i], "--keybits=", 10) == 0) keybits = atoi(argv[i] + 10);
        else if (strncmp(argv[i], "--modes=", 8) == 0) modes = argv[i] + 8;
        else {
            fprintf(stderr,
                    "usage: ot_bench [--backend=c|tpu] [--sizes=MB,..]\n"
                    "                [--threads=N,..] [--iters=N]\n"
                    "                [--keybits=128|192|256]\n"
                    "                [--modes=ecb,ecb-dec,ctr,cbc-dec,rc4]\n");
            return 1;
        }
    }

    if (strcmp(backend, "tpu") == 0) {
#ifdef OT_WITH_PYTHON
        return dispatch_tpu(sizes_s, threads_s, iters, keybits, modes);
#else
        fprintf(stderr, "ot_bench built without python embedding; "
                        "rebuild with `make ot_bench`\n");
        return 1;
#endif
    }

    long long sizes[MAX_LIST], threads[MAX_LIST];
    int ns = parse_list(sizes_s, sizes, MAX_LIST);
    int nt = parse_list(threads_s, threads, MAX_LIST);
    int do_ecb = has_mode(modes, "ecb");
    int do_ecbd = has_mode(modes, "ecb-dec");
    int do_cbcd = has_mode(modes, "cbc-dec");
    int do_ctr = has_mode(modes, "ctr");
    int do_rc4 = has_mode(modes, "rc4");
    for (int s = 0; s < ns; s++) {
        size_t bytes = (size_t)sizes[s] << 20;
        if (do_ecb) sweep_aes("ECB", bytes, threads, nt, iters, keybits);
        if (do_ecbd) sweep_aes("ECB-DEC", bytes, threads, nt, iters, keybits);
        if (do_ctr) sweep_aes("CTR", bytes, threads, nt, iters, keybits);
        if (do_cbcd) sweep_aes("CBC-DEC", bytes, threads, nt, iters, keybits);
        if (do_rc4) sweep_rc4(bytes, threads, nt, iters);
    }
    return 0;
}
