/* AES from FIPS-197, byte-matrix formulation.
 *
 * Deliberately NOT the reference's implementation style: the reference
 * (vendored PolarSSL, aes-modes/aes.c) packs the state into four
 * little-endian uint32 words and folds SubBytes+ShiftRows+MixColumns into
 * 32-bit T-table lookups; this file keeps the FIPS byte matrix and applies
 * each transformation directly, with the S-box generated at first use from
 * GF(2^8) arithmetic (the same derivation ops/gf.py uses in Python).
 * Compiled -O2 this is plenty for the correctness/portability backend; the
 * throughput story belongs to the TPU engines.
 */
#include "ot_crypt.h"

#include <pthread.h>
#include <string.h>

/* ---------------------------------------------------------------- GF(2^8) */

static uint8_t gf_xtime(uint8_t a) {
    return (uint8_t)((a << 1) ^ ((a >> 7) * 0x1B));
}

static uint8_t gf_mul(uint8_t a, uint8_t b) {
    uint8_t r = 0;
    while (b) {
        if (b & 1) r ^= a;
        b >>= 1;
        a = gf_xtime(a);
    }
    return r;
}

/* S-boxes generated once: S(x) = affine(x^254). pthread_once because
 * ctypes callers drop the GIL, so two threads may race the first setkey. */
static uint8_t SBOX[256], ISBOX[256];
static pthread_once_t tables_once = PTHREAD_ONCE_INIT;

static void gen_tables(void) {
    for (int x = 0; x < 256; x++) {
        /* x^254 by square-and-multiply (254 = 0b11111110). */
        uint8_t inv = 1, base = (uint8_t)x;
        for (int e = 254; e; e >>= 1) {
            if (e & 1) inv = gf_mul(inv, base);
            base = gf_mul(base, base);
        }
        uint8_t s = 0x63;
        for (int i = 0; i < 8; i++) {
            uint8_t bit = (uint8_t)((inv >> i) ^ (inv >> ((i + 4) & 7)) ^
                                    (inv >> ((i + 5) & 7)) ^
                                    (inv >> ((i + 6) & 7)) ^
                                    (inv >> ((i + 7) & 7))) & 1u;
            s ^= (uint8_t)(bit << i);
        }
        SBOX[x] = s;
        ISBOX[s] = (uint8_t)x;
    }
}

/* ------------------------------------------------------------ key schedule */

int ot_aes_setkey(ot_aes_ctx *ctx, const uint8_t *key, int keybits) {
    pthread_once(&tables_once, gen_tables);
    int nk;
    switch (keybits) {
        case 128: nk = 4;  ctx->nr = 10; break;
        case 192: nk = 6;  ctx->nr = 12; break;
        case 256: nk = 8;  ctx->nr = 14; break;
        default:  return -1;
    }
    int nwords = 4 * (ctx->nr + 1);
    uint8_t w[60][4];
    memcpy(w, key, (size_t)(4 * nk));
    uint8_t rcon = 1;
    for (int i = nk; i < nwords; i++) {
        uint8_t t[4];
        memcpy(t, w[i - 1], 4);
        if (i % nk == 0) {
            uint8_t tmp = t[0]; /* RotWord */
            t[0] = SBOX[t[1]] ^ rcon;
            t[1] = SBOX[t[2]];
            t[2] = SBOX[t[3]];
            t[3] = SBOX[tmp];
            rcon = gf_xtime(rcon);
        } else if (nk > 6 && i % nk == 4) {
            for (int j = 0; j < 4; j++) t[j] = SBOX[t[j]];
        }
        for (int j = 0; j < 4; j++) w[i][j] = w[i - nk][j] ^ t[j];
    }
    memcpy(ctx->rk, w, (size_t)(4 * nwords));
    return 0;
}

/* ------------------------------------------------------------- block core */

static void add_round_key(uint8_t s[16], const uint8_t rk[16]) {
    for (int i = 0; i < 16; i++) s[i] ^= rk[i];
}

static void sub_shift(uint8_t s[16]) {
    /* SubBytes + ShiftRows in one pass: byte i sits at row i%4, col i/4;
     * row r rotates left by r, so dst[4c+r] = S(src[4((c+r)%4)+r]). */
    uint8_t t[16];
    for (int c = 0; c < 4; c++)
        for (int r = 0; r < 4; r++)
            t[4 * c + r] = SBOX[s[4 * ((c + r) & 3) + r]];
    memcpy(s, t, 16);
}

static void inv_sub_shift(uint8_t s[16]) {
    uint8_t t[16];
    for (int c = 0; c < 4; c++)
        for (int r = 0; r < 4; r++)
            t[4 * c + r] = ISBOX[s[4 * ((c - r) & 3) + r]];
    memcpy(s, t, 16);
}

static void mix_columns(uint8_t s[16]) {
    for (int c = 0; c < 4; c++) {
        uint8_t *a = s + 4 * c;
        uint8_t all = (uint8_t)(a[0] ^ a[1] ^ a[2] ^ a[3]);
        uint8_t a0 = a[0];
        a[0] ^= all ^ gf_xtime((uint8_t)(a[0] ^ a[1]));
        a[1] ^= all ^ gf_xtime((uint8_t)(a[1] ^ a[2]));
        a[2] ^= all ^ gf_xtime((uint8_t)(a[2] ^ a[3]));
        a[3] ^= all ^ gf_xtime((uint8_t)(a[3] ^ a0));
    }
}

static void inv_mix_columns(uint8_t s[16]) {
    for (int c = 0; c < 4; c++) {
        uint8_t *a = s + 4 * c;
        uint8_t b[4];
        for (int r = 0; r < 4; r++)
            b[r] = (uint8_t)(gf_mul(14, a[r]) ^ gf_mul(11, a[(r + 1) & 3]) ^
                             gf_mul(13, a[(r + 2) & 3]) ^
                             gf_mul(9, a[(r + 3) & 3]));
        memcpy(a, b, 4);
    }
}

void ot_aes_encrypt_block(const ot_aes_ctx *ctx, const uint8_t in[16],
                          uint8_t out[16]) {
    uint8_t s[16];
    memcpy(s, in, 16);
    add_round_key(s, ctx->rk[0]);
    for (int r = 1; r < ctx->nr; r++) {
        sub_shift(s);
        mix_columns(s);
        add_round_key(s, ctx->rk[r]);
    }
    sub_shift(s);
    add_round_key(s, ctx->rk[ctx->nr]);
    memcpy(out, s, 16);
}

void ot_aes_decrypt_block(const ot_aes_ctx *ctx, const uint8_t in[16],
                          uint8_t out[16]) {
    /* Straight inverse cipher over the encryption schedule (FIPS-197 §5.3)
     * — no InvMixColumns-folded "equivalent" schedule needed. */
    uint8_t s[16];
    memcpy(s, in, 16);
    add_round_key(s, ctx->rk[ctx->nr]);
    inv_sub_shift(s);
    for (int r = ctx->nr - 1; r >= 1; r--) {
        add_round_key(s, ctx->rk[r]);
        inv_mix_columns(s);
        inv_sub_shift(s);
    }
    add_round_key(s, ctx->rk[0]);
    memcpy(out, s, 16);
}

/* ------------------------------------------------- sequential chain modes */

void ot_aes_cbc_encrypt(const ot_aes_ctx *ctx, uint8_t iv[16],
                        const uint8_t *in, uint8_t *out, size_t nblocks) {
    uint8_t x[16];
    for (size_t b = 0; b < nblocks; b++) {
        for (int i = 0; i < 16; i++) x[i] = (uint8_t)(in[16 * b + i] ^ iv[i]);
        ot_aes_encrypt_block(ctx, x, out + 16 * b);
        memcpy(iv, out + 16 * b, 16);
    }
}

void ot_aes_cfb128(const ot_aes_ctx *ctx, int encrypt, int *iv_off,
                   uint8_t iv[16], const uint8_t *in, uint8_t *out,
                   size_t len) {
    int n = *iv_off;
    for (size_t i = 0; i < len; i++) {
        if (n == 0) ot_aes_encrypt_block(ctx, iv, iv);
        uint8_t c;
        if (encrypt) {
            c = (uint8_t)(in[i] ^ iv[n]);
            iv[n] = c;
        } else {
            c = (uint8_t)(in[i] ^ iv[n]);
            iv[n] = in[i];
        }
        out[i] = c;
        n = (n + 1) & 0x0F;
    }
    *iv_off = n;
}
