"""ctypes bindings for the native C runtime + the `--backend=c` harness backend.

The shared library (runtime/csrc/libotcrypt.so) is built on first use with
the in-tree Makefile — a single `make`, cheap enough to run lazily and
cached by mtime against every source in csrc/ (globbed, so new files can't
silently go stale). Bindings use ctypes (no pybind11 in this image);
buffers cross the boundary as numpy arrays, zero-copy.

This layer plays the role of the reference's portable-C path *and* its
pthread harness (aes-modes/test.c): same contiguous-chunk work split, same
cipher semantics, our own implementation.
"""

from __future__ import annotations

import contextlib
import ctypes
import os
import pathlib

import numpy as np

from ..obs import trace as _trace
from ..resilience import faults, isolate, policy

_CSRC = pathlib.Path(__file__).parent / "csrc"
_LIB_PATH = _CSRC / "libotcrypt.so"
_lib = None


class AesCtx(ctypes.Structure):
    _fields_ = [("nr", ctypes.c_int), ("rk", ctypes.c_uint8 * (15 * 16))]


class Arc4Ctx(ctypes.Structure):
    _fields_ = [("x", ctypes.c_int), ("y", ctypes.c_int),
                ("m", ctypes.c_uint8 * 256)]


def _fresh() -> bool:
    srcs = sorted(_CSRC.glob("*.c")) + sorted(_CSRC.glob("*.h")) + [
        _CSRC / "Makefile"
    ]
    return _LIB_PATH.exists() and all(
        _LIB_PATH.stat().st_mtime >= s.stat().st_mtime for s in srcs
    )


@contextlib.contextmanager
def _build_lock():
    """Exclusive flock on a sidecar lockfile for the `make` critical
    section: two processes building the same libotcrypt.so concurrently
    (the first importer in a sweep + a child job) interleave compiler
    output and can corrupt the .so. Advisory-degrading like devlock — an
    unopenable lockfile (read-only tree) yields without the lock, because
    in that case `make` itself will fail with the real diagnostic."""
    lockfile = str(_LIB_PATH) + ".lock"
    try:
        import fcntl
        fd = os.open(lockfile, os.O_CREAT | os.O_RDWR, 0o644)
    except (ImportError, OSError):
        yield
        return
    try:
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
        except OSError:
            # Filesystem without flock support (some NFS mounts): degrade
            # to the unguarded build rather than reporting the native
            # runtime unavailable over a lock nobody could take.
            yield
            return
        yield
    finally:
        os.close(fd)  # closing the fd releases the flock


def _build() -> None:
    if _fresh():
        return
    with _build_lock():
        if _fresh():
            return  # a concurrent builder won the lock and already built

        def make(attempt):
            # The injection point CI's fault matrix uses to prove the
            # retry path: `OT_FAULTS=build_fail:1` fails exactly the
            # first make attempt (docs/RESILIENCE.md).
            faults.check("build_fail", "native runtime make")
            # Through the shared child runner (otlint subprocess-isolate):
            # a compiler wedged on a dead NFS mount used to hang this
            # build — and the whole importing sweep — forever; run_child
            # gives the make a wall deadline and SIGKILLs its whole
            # process group on expiry. The target is only the lib, not
            # ot_bench (the bindings need nothing else).
            r = isolate.run_child(
                ["make", "-C", str(_CSRC), "libotcrypt.so"],
                timeout_s=float(os.environ.get("OT_BUILD_DEADLINE", 600)),
                name="native-build-make")
            if not r.ok:
                raise RuntimeError(
                    f"native runtime build failed ({r.kind}):\n{r.out}\n"
                    f"{r.err}"
                )

        # Two attempts: a transiently-failing make (ENOSPC blip, a racing
        # clean) gets one more try before the callers' own fallbacks
        # (OT_ARC4_PREP=auto -> lax.scan, bench zero-line) take over; a
        # deterministic compile error still fails fast with its full log.
        # The span makes a cold-start build visible in the run trace —
        # a `make` landing inside a sweep's setup is exactly the kind of
        # one-off wall-clock sink per-row timings can't explain.
        with _trace.span("native-build", target="libotcrypt.so"):
            policy.RetryPolicy(
                attempts=2, base_delay_s=0.5, retry_on=(RuntimeError,),
                name="native-build",
            ).run(make)


_u8p = np.ctypeslib.ndpointer(dtype=np.uint8, flags="C_CONTIGUOUS")


def load():
    """Build (if stale) and load the native library, with typed signatures."""
    global _lib
    if _lib is not None:
        return _lib
    _build()
    lib = ctypes.CDLL(str(_LIB_PATH))
    lib.ot_aes_setkey.argtypes = [ctypes.POINTER(AesCtx), _u8p, ctypes.c_int]
    lib.ot_aes_setkey.restype = ctypes.c_int
    lib.ot_aes_ecb.argtypes = [ctypes.POINTER(AesCtx), ctypes.c_int, _u8p,
                               _u8p, ctypes.c_size_t, ctypes.c_int]
    lib.ot_aes_ctr.argtypes = [ctypes.POINTER(AesCtx), _u8p, _u8p, _u8p,
                               ctypes.c_size_t, ctypes.c_int]
    lib.ot_aes_cbc_encrypt.argtypes = [ctypes.POINTER(AesCtx), _u8p, _u8p,
                                       _u8p, ctypes.c_size_t]
    lib.ot_aes_cbc_decrypt.argtypes = [ctypes.POINTER(AesCtx), _u8p, _u8p,
                                       _u8p, ctypes.c_size_t, ctypes.c_int]
    lib.ot_aes_cfb128.argtypes = [ctypes.POINTER(AesCtx), ctypes.c_int,
                                  ctypes.POINTER(ctypes.c_int), _u8p, _u8p,
                                  _u8p, ctypes.c_size_t]
    lib.ot_arc4_setup.argtypes = [ctypes.POINTER(Arc4Ctx), _u8p,
                                  ctypes.c_size_t]
    lib.ot_arc4_prep.argtypes = [ctypes.POINTER(Arc4Ctx), _u8p,
                                 ctypes.c_size_t]
    lib.ot_xor.argtypes = [_u8p, _u8p, _u8p, ctypes.c_size_t, ctypes.c_int]
    lib.ot_aesni_available.argtypes = []
    lib.ot_aesni_available.restype = ctypes.c_int
    _lib = lib
    return lib


def aesni_available() -> bool:
    """True when the CPU's hardware AES path (ot_aesni.c) is usable.

    Note the runtime also honors OT_C_FORCE_PORTABLE (checked once per
    process in ot_parallel.c) — this only reports the cpuid capability."""
    return bool(load().ot_aesni_available())


#: Blocks per ECB thread before another thread pays for itself: 16 K
#: blocks = 256 KiB. Measured on the 2-core CI sandbox — a pthread spawn
#: costs ~0.3 ms against ~1 GB/s of AESNI, so splitting finer than this
#: LOSES throughput at the serve ladder's rungs (docs/PERF.md).
_THREAD_BLOCKS = 16384


def _default_threads(nblocks: int) -> int:
    """Size-based ECB thread count: one per ``_THREAD_BLOCKS`` chunk,
    capped at the core count, never below one — the reference's
    ``length/num_threads`` split with the threshold measured where spawn
    cost stops dominating (ctr_scattered_words docstring)."""
    return max(1, min(os.cpu_count() or 1, nblocks // _THREAD_BLOCKS))


def aes_ctx_from_schedule(nr: int, rk_words: np.ndarray) -> AesCtx:
    """An AesCtx primed directly from an EXPANDED schedule, no setkey.

    ``rk_words``: (4*(nr+1),) u32 little-endian round-key words (the
    ``ops.keyschedule.expand_key_enc`` layout). The C context stores the
    schedule as raw byte blocks (ot_crypt.h: ``rk[15][16]``) and the LE
    word packing is exactly that byte stream, so a memmove IS the key
    setup — which is what lets the serve key cache hand the native tier
    its HOST schedules without retaining raw key bytes
    (tests/test_native.py pins this against ot_aes_setkey).
    """
    load()  # ensure the library (and its table init path) exists
    nr = int(nr)
    if not 0 < nr <= 14:
        # rk is a fixed rk[15][16] C field — an oversized nr would
        # memmove past the ctypes buffer, not fail cleanly.
        raise ValueError(f"nr={nr} out of range for the C context "
                         f"(AES-128/192/256 = 10/12/14 rounds)")
    ctx = AesCtx()
    ctx.nr = nr
    b = np.ascontiguousarray(rk_words, dtype="<u4").view(np.uint8)
    if b.size != 16 * (nr + 1):
        raise ValueError(
            f"schedule has {b.size} bytes, expected {16 * (nr + 1)}")
    ctypes.memmove(ctx.rk, b.ctypes.data, b.size)
    return ctx


def ctr_scattered_words(ctxs, words: np.ndarray, ctr_words: np.ndarray,
                        key_slots: np.ndarray | None = None,
                        nthreads: int = 0) -> np.ndarray:
    """Scattered CTR on the native runtime: out = ECB(counters) ^ data.

    The host twin of ``models.aes.ctr_crypt_words_scattered_multikey`` —
    the serve dispatch's CPU fallback tier. ``words``/``ctr_words``: flat
    (4N,) u32 LE arrays (the serve boundary layout); ``ctxs``: one AesCtx
    per key slot; ``key_slots``: (N,) per-block slot indices (None = all
    slot 0). Blocks of one slot arrive as contiguous runs (the batcher
    packs per key group), so the dispatch is one threaded ECB call per
    run over the counter bytes plus one vectorised XOR — AESNI hardware
    rate with zero per-block Python.

    ``nthreads`` 0 picks a size-based default: one thread per 256 KiB
    chunk (capped at the core count) — the reference's
    ``length/num_threads`` chunk split (aes-modes/test.c:33-35), with a
    threshold measured where spawn cost stops dominating: on the 2-core
    CI sandbox a pthread spawn costs ~0.3 ms against ~1 GB/s AESNI, so
    threading below ~16 K blocks per thread LOSES throughput (the
    pre-tuned default threaded at 2048 blocks and ran 5x slower than
    single-threaded at the serve ladder's rungs).
    """
    lib = load()
    words = np.ascontiguousarray(words, dtype=np.uint32).reshape(-1)
    ctr_b = np.ascontiguousarray(
        ctr_words, dtype="<u4").reshape(-1).view(np.uint8)
    n = words.size // 4
    if ctr_b.size != 16 * n:
        # The C calls get explicit lengths the ndpointers cannot check:
        # a mismatched counter array would be a silent out-of-bounds
        # heap access, not an exception.
        raise ValueError(f"ctr_words holds {ctr_b.size // 16} blocks "
                         f"for a {n}-block batch")
    ks = np.empty_like(ctr_b)
    if key_slots is None:
        runs = [(0, 0, n)]
    else:
        key_slots = np.asarray(key_slots).reshape(-1)
        if key_slots.size != n:
            raise ValueError(f"key_slots has {key_slots.size} entries "
                             f"for a {n}-block batch")
        if not key_slots.any():  # single-slot batch: one run, no scan
            runs = [(0, 0, n)]
        else:
            edges = np.flatnonzero(np.diff(key_slots)) + 1
            bounds = np.concatenate(([0], edges, [n]))
            runs = [(int(key_slots[int(a)]), int(a), int(b))
                    for a, b in zip(bounds[:-1], bounds[1:])]
    for slot, start, stop in runs:
        nb = stop - start
        if nb <= 0:
            continue
        t = nthreads or _default_threads(nb)
        lib.ot_aes_ecb(ctypes.byref(ctxs[slot]), 1,
                       ctr_b[16 * start:16 * stop],
                       ks[16 * start:16 * stop], nb, t)
    # XOR in place into the keystream buffer: the serve path calls this
    # per batch, and a third N-word temporary is pure memory traffic.
    ks_w = ks.view("<u4")
    np.bitwise_xor(ks_w, words, out=ks_w)
    return ks_w


def ctr_requests_words(ctxs, words: np.ndarray, runs,
                       nthreads: int = 0) -> np.ndarray:
    """Per-REQUEST CTR on the native runtime: counters stay in C.

    The zero-counter-array fast path of the serve native tier:
    ``runs`` is the batch's request layout —
    ``[(slot, start_block, nblocks, nonce16), ...]`` — and each request
    is one ``ot_aes_ctr`` call (counter ripple, ECB, and XOR all inside
    C, per-chunk offsets for its threads). Against
    ``ctr_scattered_words`` this drops the materialised (N, 4) counter
    array, the separate keystream buffer, and the numpy XOR pass — at
    the big ladder rungs those passes cost more than the cipher
    (docs/PERF.md). Bit-exact with the counter-array path by the shared
    128-bit big-endian ripple (``ctr_add`` / ``np_ctr_le_blocks``;
    tests pin the two and the NIST KAT). Blocks no run covers (rung
    padding) are ZEROED — the buffer comes from ``np.empty`` and heap
    garbage (potentially another allocation's freed secrets) must not
    sit in a buffer callers may hold views over; full coverage (the
    common case) pays nothing.
    """
    lib = load()
    words = np.ascontiguousarray(words, dtype=np.uint32).reshape(-1)
    data = words.view(np.uint8)
    out = np.empty_like(data)
    n = words.size // 4
    nonce = np.empty(16, dtype=np.uint8)
    for slot, start, nb, nonce_bytes in runs:
        if nb <= 0:
            continue
        # The C call gets an explicit length the ndpointer cannot
        # check: a run past the buffer would be a silent out-of-bounds
        # heap write (adjacent to key material), not an exception.
        if start < 0 or start + nb > n:
            raise ValueError(
                f"run ({start}, {nb}) exceeds the {n}-block buffer")
        if not 0 <= slot < len(ctxs):
            raise ValueError(f"run slot {slot} outside {len(ctxs)} ctxs")
        t = nthreads or _default_threads(nb)
        nonce[:] = np.frombuffer(bytes(nonce_bytes), dtype=np.uint8)
        lib.ot_aes_ctr(ctypes.byref(ctxs[slot]), nonce,
                       data[16 * start:16 * (start + nb)],
                       out[16 * start:16 * (start + nb)], 16 * nb, t)
    pos = 0  # zero every uncovered byte (runs are disjoint)
    for start, nb in sorted((s, n) for _, s, n, _ in runs if n > 0):
        if start > pos:
            out[16 * pos:16 * start] = 0
        pos = max(pos, start + nb)
    out[16 * pos:] = 0
    return out.view("<u4")


# ---------------------------------------------------------------------------
# Pythonic wrappers (mirror the TPU-side API shapes).
# ---------------------------------------------------------------------------


class NativeAES:
    """C-runtime AES context; same surface idea as models.aes.AES."""

    def __init__(self, key: bytes):
        self._lib = load()
        self.key = bytes(key)
        self.ctx = AesCtx()
        kb = np.frombuffer(self.key, dtype=np.uint8)
        if self._lib.ot_aes_setkey(ctypes.byref(self.ctx), kb, len(key) * 8):
            raise ValueError(f"invalid AES key size {len(key)}")
        self.nr = self.ctx.nr

    def ecb(self, data: np.ndarray, encrypt: bool = True,
            nthreads: int = 1) -> np.ndarray:
        data = np.ascontiguousarray(data, dtype=np.uint8)
        if data.size % 16:
            raise ValueError("ECB data must be a multiple of 16 bytes")
        out = np.empty_like(data)
        self._lib.ot_aes_ecb(ctypes.byref(self.ctx), int(encrypt), data, out,
                             data.size // 16, nthreads)
        return out

    def ctr(self, nonce: np.ndarray, data: np.ndarray,
            nthreads: int = 1) -> tuple[np.ndarray, np.ndarray]:
        data = np.ascontiguousarray(data, dtype=np.uint8)
        nonce = np.ascontiguousarray(nonce, dtype=np.uint8).copy()
        out = np.empty_like(data)
        self._lib.ot_aes_ctr(ctypes.byref(self.ctx), nonce, data, out,
                             data.size, nthreads)
        return out, nonce

    def cbc(self, iv: np.ndarray, data: np.ndarray, encrypt: bool = True,
            nthreads: int = 1) -> tuple[np.ndarray, np.ndarray]:
        data = np.ascontiguousarray(data, dtype=np.uint8)
        if data.size % 16:
            raise ValueError("CBC data must be a multiple of 16 bytes")
        iv = np.ascontiguousarray(iv, dtype=np.uint8).copy()
        out = np.empty_like(data)
        if encrypt:
            self._lib.ot_aes_cbc_encrypt(ctypes.byref(self.ctx), iv, data,
                                         out, data.size // 16)
        else:
            self._lib.ot_aes_cbc_decrypt(ctypes.byref(self.ctx), iv, data,
                                         out, data.size // 16, nthreads)
        return out, iv

    def cfb128(self, iv_off: int, iv: np.ndarray, data: np.ndarray,
               encrypt: bool = True) -> tuple[np.ndarray, int, np.ndarray]:
        data = np.ascontiguousarray(data, dtype=np.uint8)
        iv = np.ascontiguousarray(iv, dtype=np.uint8).copy()
        out = np.empty_like(data)
        off = ctypes.c_int(iv_off)
        self._lib.ot_aes_cfb128(ctypes.byref(self.ctx), int(encrypt),
                                ctypes.byref(off), iv, data, out, data.size)
        return out, off.value, iv


def xor_parallel(data: np.ndarray, keystream: np.ndarray,
                 nthreads: int = 1) -> np.ndarray:
    """Thread-parallel XOR with the shape guard both ARC4 surfaces need: a
    short keystream would read out of bounds in C (and XOR against padding
    would pass tail plaintext through — see dist.xor_sharded)."""
    data = np.ascontiguousarray(data, dtype=np.uint8)
    keystream = np.ascontiguousarray(keystream, dtype=np.uint8)
    if data.shape != keystream.shape:
        raise ValueError(
            f"data/keystream shape mismatch: {data.shape} vs {keystream.shape}"
        )
    out = np.empty_like(data)
    load().ot_xor(data, keystream, out, data.size, nthreads)
    return out


class NativeARC4:
    def __init__(self, key: bytes):
        if len(key) == 0:
            raise ValueError("ARC4 key must be non-empty")
        self._lib = load()
        self.ctx = Arc4Ctx()
        kb = np.frombuffer(bytes(key), dtype=np.uint8)
        self._lib.ot_arc4_setup(ctypes.byref(self.ctx), kb, len(key))

    def prep(self, length: int) -> np.ndarray:
        ks = np.empty(length, dtype=np.uint8)
        self._lib.ot_arc4_prep(ctypes.byref(self.ctx), ks, length)
        return ks

    def crypt(self, data: np.ndarray, keystream: np.ndarray,
              nthreads: int = 1) -> np.ndarray:
        return xor_parallel(data, keystream, nthreads)


class CBackend:
    """Harness backend protocol over the native runtime (--backend=c).

    'Workers' are pthreads, exactly the reference's sweep axis
    (test.c:135-153). Device staging is a no-op; block_until_ready is
    identity (C calls are synchronous).
    """

    name = "c"

    def __init__(self):
        load()
        self.max_workers = os.cpu_count() or 8

    # -- protocol ----------------------------------------------------------
    def stage_words(self, data: np.ndarray):
        return np.ascontiguousarray(data, dtype=np.uint8)

    def to_device(self, arr: np.ndarray):
        return np.ascontiguousarray(arr)

    def block_until_ready(self, x):
        return x

    def make_key(self, key: bytes):
        return NativeAES(key)

    def ecb(self, ctx: NativeAES, data, workers: int):
        return ctx.ecb(data, encrypt=True, nthreads=workers)

    def ecb_dec(self, ctx: NativeAES, data, workers: int):
        return ctx.ecb(data, encrypt=False, nthreads=workers)

    def cbc_dec(self, ctx: NativeAES, data, iv, workers: int):
        # Parallel in C too: ot_aes_cbc_decrypt threads over chunks (each
        # chunk's prev-ciphertext comes from the input stream, not a carry).
        out, _ = ctx.cbc(iv, data, encrypt=False, nthreads=workers)
        return out

    def ctr(self, ctx: NativeAES, data, nonce, workers: int):
        out, _ = ctx.ctr(nonce, data, nthreads=workers)
        return out

    def cbc(self, ctx: NativeAES, data, iv, workers: int):
        if workers != 1:
            raise ValueError(
                "single-stream CBC encrypt is a sequential recurrence and "
                "cannot split over workers (same contract as TpuBackend.cbc)"
            )
        out, _ = ctx.cbc(iv, data, encrypt=True)
        return out

    def cfb128(self, ctx: NativeAES, data, iv, workers: int):
        if workers != 1:
            raise ValueError(
                "single-stream CFB128 encrypt is a sequential recurrence and "
                "cannot split over workers (same contract as TpuBackend.cfb128)"
            )
        out, _, _ = ctx.cfb128(0, iv, data, encrypt=True)
        return out

    def ctr_be_words(self, nonce: np.ndarray):
        return np.ascontiguousarray(nonce, dtype=np.uint8)

    def iv_words(self, iv: np.ndarray):
        return np.ascontiguousarray(iv, dtype=np.uint8)

    def arc4_setup_prep(self, key: bytes, length: int):
        return NativeARC4(key).prep(length)

    def arc4_crypt(self, data, ks, workers: int):
        return xor_parallel(data, ks, workers)
