"""Multi-chip distribution layer (mesh + shard_map kernels)."""

from .dist import (  # noqa: F401
    AXIS,
    ctr_crypt_sharded,
    ecb_crypt_sharded,
    gather_for_verification,
    make_mesh,
    xor_sharded,
)
