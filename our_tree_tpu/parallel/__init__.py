"""Multi-chip distribution layer (mesh + shard_map kernels)."""

from .dist import (  # noqa: F401
    AXIS,
    arc4_prep_batch_sharded,
    block_cyclic_to_contiguous,
    cbc_decrypt_sharded,
    cbc_encrypt_batch_sharded,
    cfb128_decrypt_sharded,
    ctr_crypt_sharded,
    ecb_crypt_sharded,
    gather_for_verification,
    make_mesh,
    xor_sharded,
)
