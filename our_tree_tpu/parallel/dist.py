"""Multi-chip distribution: 1-D device mesh + shard_map'd cipher kernels.

The reference's only parallelism is shared-memory pthreads — a message split
into `len/T` contiguous chunks, one thread each (aes-modes/test.c:33-35,
test.c:50-55) — and there is NO distributed communication backend at all
(SURVEY.md §2 "Distributed communication backend"). The workloads need no
cross-worker reduction: chunks are independent (ECB, CTR, XOR), so the whole
"collective" story is scatter (chunk assignment) + gather (disjoint writes).

The TPU-native re-design of that scheme (SURVEY.md §7 layer 6):

  * a 1-D `jax.sharding.Mesh` over however many chips exist (ICI within a
    host, DCN across hosts — XLA picks the transport; the code is identical),
  * inputs block-sharded over the mesh axis; the 240-byte round-key schedule
    replicated (the only "broadcast" the workload has),
  * `shard_map` kernels in which each shard derives its global position with
    `jax.lax.axis_index` — the moral equivalent of the reference threads'
    `offset = chunk_size * thread_id` pointer arithmetic (test.c:51-53),
  * CTR counter offsets computed per shard from that index, so shard seams
    produce bit-identical keystream to the single-chip path — the
    shard-invariance property the reference never tested (and whose absence
    let defect #1 in SURVEY.md §2 go unnoticed),
  * no collectives in the cipher hot path; the collectives that do exist
    each earn their place — the chained-mode halo `ppermute`
    (cbc/cfb128_decrypt_sharded), the ingest re-layout `all_to_all`
    (block_cyclic_to_contiguous), and a verification-only `all_gather`.

Everything here also runs unmodified on a single device (mesh of 1) and on
CPU-simulated meshes (tests/conftest.py forces 8 virtual CPU devices).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..models.aes import (CORES, CTR_FUSED, PALLAS_BACKED, _add_counter_be,
                          _as_block_words, _engine_knobs_key,
                          cbc_encrypt_words_batch, ctr_le_blocks,
                          resolve_engine)
from ..models.arc4 import keystream_scan_batch
from ..ops.pallas_aes import interpret_mode as _pallas_interpret

AXIS = "shards"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with a version-compat fallback.

    ``jax.shard_map`` became a top-level API (with ``check_vma``) only
    in newer jax; older runtimes (this CPU container ships 0.4.x) carry
    the same transform as ``jax.experimental.shard_map.shard_map`` with
    the check spelled ``check_rep`` (the replication checker that
    predates the varying-manual-axes rename). Every sharded kernel here
    routes through this one shim so the module runs on both: new jax
    takes the top-level path untouched; old jax maps ``check_vma`` onto
    ``check_rep``. The ``_vma_drop_bug`` probe composes with either —
    it classifies by error MESSAGE, and an old-jax checker that cannot
    handle a traced body (e.g. pallas_call, which the experimental
    checker has no replication rule for) reads as "check unusable
    here", disabling it exactly like the probed interpreter bug.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


@functools.lru_cache(None)
def _vma_drop_bug() -> bool:
    """Probe (once per process) for the pallas-INTERPRETER vma drop.

    jax 0.9.0's pallas interpreter loses vma (varying-manual-axes) tags
    across its internal scan, so a kernel round fori_loop under
    `shard_map(..., check_vma=True)` fails the carry check ("Scan carry
    input and output got mismatched varying manual axes") even though the
    values are correct — found by scripts/fuzz_parity.py --sharded with a
    pallas engine on an 8-virtual-device CPU mesh (regression:
    tests/test_parallel.py pallas-engine shard-parity cases).

    Rather than pinning a version range (the fix release is unknowable from
    here), this reproduces the bug directly: the real ECB shard body with a
    pallas interpreter kernel on a 1-device mesh, check_vma=True. The vma
    carry check is a TRACE-time structural check, so one device suffices.
    Only the documented mismatch error counts as "bug present"; any other
    failure keeps the safety check ON so the real path fails loudly instead
    of silently dropping verification (VERDICT r3 weak #3: the workaround
    must not outlive the bug)."""
    try:
        from jax._src import core as _core  # no public trace-state probe yet
        clean = _core.trace_state_clean()
    except Exception:
        clean = True  # can't tell — proceed; the classification guard below
        #               still fails toward keeping the check ON
    if not clean:
        raise RuntimeError(
            "_vma_drop_bug() called under an ambient jax trace — the probe "
            "would misclassify (its failure surfaces as a different "
            "exception inside a trace). Call _shard_check_vma from the "
            "un-jitted wrapper and pass the result as a static argument."
        )
    probe_axis = "_vma_probe"
    f = shard_map(
        functools.partial(_ecb_shard_body, nr=10, encrypt=True,
                          engine="pallas"),
        mesh=Mesh(np.asarray(jax.devices()[:1]), (probe_axis,)),
        in_specs=(P(probe_axis), P()),
        out_specs=P(probe_axis),
        check_vma=True,
    )
    try:
        f(jnp.zeros((32, 4), jnp.uint32), jnp.zeros((11, 4), jnp.uint32))
        return False
    except Exception as e:  # noqa: BLE001 — classified by message below
        # Two documented "the checker, not the kernel, is broken" shapes:
        # the 0.9.0 interpreter vma drop, and old jax's experimental
        # check_rep having no replication rule for pallas_call at all
        # (the compat shim maps check_vma onto it). Anything else keeps
        # the check ON so the real path fails loudly.
        return ("varying manual axes" in str(e)
                or "No replication rule" in str(e))


def _shard_check_vma(engine: str) -> bool:
    """check_vma for a sharded entry point running `engine`: full checking
    unless the engine routes into a pallas kernel that will run in
    interpreter mode AND the interpreter actually exhibits the vma-drop bug
    (probed, not assumed — a jax upgrade re-enables the check by itself).

    MUST be called from the un-jitted wrappers, never inside a jit trace:
    the probe executes a jax computation of its own, and under an ambient
    trace the failure surfaces as a different exception type, silently
    misclassifying the bug as absent (caught by
    test_ctr_sharded_fused_pallas_engine). The jitted entry points
    therefore take the flag as a static argument."""
    return (engine not in PALLAS_BACKED or not _pallas_interpret()
            or not _vma_drop_bug())


def make_mesh(n_devices: int | None = None, axis: str = AXIS) -> Mesh:
    """1-D mesh over the first `n_devices` devices (all, if None).

    The reference's analogue is the `num_threads` sweep parameter
    (test.c:135-153); here a "worker" is a chip.
    """
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"requested a {n_devices}-device mesh but only {len(devs)} "
                "devices exist — a silently smaller mesh would let shard-count "
                "assumptions go unvalidated"
            )
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def _pad_blocks(words: jnp.ndarray, n_shards: int):
    """Pad the block axis to a multiple of n_shards (zeros, sliced off after).

    Padding sits at the END of the stream, so every real block keeps its
    global index — counter/keystream indices stay parity-exact. Generic
    over dtype/shape (xor_sharded pads byte-granular ARC4 data with this
    too); AES word-stream wrappers use _pad_word_stream for flat streams,
    where padding must stay on whole 16-byte blocks.
    """
    n = words.shape[0]
    rem = (-n) % n_shards
    if rem:
        words = jnp.concatenate(
            [words, jnp.zeros((rem,) + words.shape[1:], words.dtype)], axis=0
        )
    return words, n


def _pad_word_stream(words: jnp.ndarray, n_shards: int):
    """_pad_blocks for a flat (4N,) u32 block stream (dense TPU boundary
    layout, models/aes.py:_as_block_words): pads by WHOLE 16-byte blocks to
    a block count divisible by n_shards, so shard seams fall on block
    boundaries and per-shard counter offsets stay exact."""
    n = words.shape[0]
    if n % 4:
        raise ValueError(
            f"flat word stream length must be a multiple of 4 u32 words "
            f"(one 16-byte block), got {n} words — pad the byte stream to "
            "16-byte blocks before sharding"
        )
    rem = 4 * ((-(n // 4)) % n_shards)
    if rem:
        words = jnp.concatenate([words, jnp.zeros(rem, words.dtype)], axis=0)
    return words, n


# ---------------------------------------------------------------------------
# Sharded mode kernels
# ---------------------------------------------------------------------------


def _ctr_shard_body(words, ctr_be, rk, nr, axis, engine="jnp"):
    """Per-shard CTR: global block index = axis_index * local_n + local iota.

    Matches the 128-bit big-endian post-increment counter semantics of the
    oracle (aes-modes/aes.c:869-901) across shard seams — the multi-chip
    counter bookkeeping called out as hard part #6 in SURVEY.md §7.
    """
    w2 = _as_block_words(words)
    n_local = w2.shape[0]
    base = jax.lax.axis_index(axis).astype(jnp.uint32) * jnp.uint32(n_local)
    fused = CTR_FUSED.get(engine)
    if fused is not None:  # counter + keystream stay on-chip per shard
        shard_ctr = _add_counter_be(ctr_be, base)
        out = fused(w2, shard_ctr, rk, nr)
    else:
        idx = base + jnp.arange(n_local, dtype=jnp.uint32)
        out = w2 ^ CORES[engine][0](ctr_le_blocks(ctr_be, idx), rk, nr)
    return out.reshape(words.shape)


@functools.partial(jax.jit,
                   static_argnames=("nr", "mesh", "axis", "engine",
                                    "check_vma", "knobs"))
def _ctr_sharded_jit(words, ctr_be, rk, *, nr, mesh, axis, engine="jnp",
                     check_vma=True, knobs=None):
    # `knobs` is compile-cache key only: pallas engines read TILE/MC at
    # trace time (models/aes.py:_engine_knobs_key — ADVICE r4 #1 applies
    # to the sharded paths too).
    del knobs
    f = shard_map(
        functools.partial(_ctr_shard_body, nr=nr, axis=axis, engine=engine),
        mesh=mesh,
        in_specs=(P(axis), P(), P()),
        out_specs=P(axis),
        # Full vma checking unless the probed interpreter bug is present —
        # see _shard_check_vma / _vma_drop_bug (evaluated by the caller,
        # outside this jit trace). On real hardware (Mosaic compile, no
        # interpreter) the check is always on; CPU pallas shard parity is
        # covered by test_parallel instead.
        check_vma=check_vma,
    )
    return f(words, ctr_be, rk)


def ctr_crypt_sharded(words, ctr_be, rk, nr, mesh: Mesh, axis: str = AXIS,
                      engine: str = "auto"):
    """CTR en/decrypt words sharded over `mesh` — (N, 4) block words or a
    flat (4N,) u32 stream (dense TPU boundary layout; shard seams stay on
    block boundaries either way).

    `ctr_be` is the initial 128-bit counter as (4,) big-endian u32 words;
    round keys are replicated to every shard (the schedule is the only
    broadcast this workload has, cf. cudaMemcpy of `ce_sched` AES.cu:222).
    """
    n_shards = mesh.devices.size
    pad = _pad_word_stream if words.ndim == 1 else _pad_blocks
    padded, n = pad(words, n_shards)
    eng = resolve_engine(engine)
    out = _ctr_sharded_jit(padded, ctr_be, rk, nr=nr, mesh=mesh, axis=axis,
                           engine=eng, check_vma=_shard_check_vma(eng),
                           knobs=_engine_knobs_key(eng))
    return out[:n]


def _ecb_shard_body(words, rk, nr, encrypt, engine="jnp"):
    fn = CORES[engine][0 if encrypt else 1]
    return fn(_as_block_words(words), rk, nr).reshape(words.shape)


@functools.partial(jax.jit,
                   static_argnames=("nr", "encrypt", "mesh", "axis", "engine",
                                    "check_vma", "knobs"))
def _ecb_sharded_jit(words, rk, *, nr, encrypt, mesh, axis, engine="jnp",
                     check_vma=True, knobs=None):
    del knobs  # compile-cache key only (see _ctr_sharded_jit)
    f = shard_map(
        functools.partial(_ecb_shard_body, nr=nr, encrypt=encrypt, engine=engine),
        mesh=mesh,
        in_specs=(P(axis), P()),
        out_specs=P(axis),
        # same pallas-interpreter vma drop; see _ctr_sharded_jit
        check_vma=check_vma,
    )
    return f(words, rk)


def ecb_crypt_sharded(words, rk, nr, mesh: Mesh, encrypt: bool = True,
                      axis: str = AXIS, engine: str = "auto"):
    """ECB over a sharded block axis — the reference's headline parallel mode
    (each pthread ran aes_crypt_ecb over its chunk, aes-modes/test.c:37-41)."""
    n_shards = mesh.devices.size
    pad = _pad_word_stream if words.ndim == 1 else _pad_blocks
    padded, n = pad(words, n_shards)
    eng = resolve_engine(engine)
    out = _ecb_sharded_jit(padded, rk, nr=nr, encrypt=encrypt, mesh=mesh,
                           axis=axis, engine=eng,
                           check_vma=_shard_check_vma(eng),
                           knobs=_engine_knobs_key(eng))
    return out[:n]


@functools.partial(jax.jit, static_argnames=("mesh", "axis"))
def _xor_sharded_jit(data, ks, *, mesh, axis):
    f = shard_map(
        jnp.bitwise_xor, mesh=mesh, in_specs=(P(axis), P(axis)), out_specs=P(axis)
    )
    return f(data, ks)


def xor_sharded(data, keystream, mesh: Mesh, axis: str = AXIS):
    """ARC4 phase 3 — the data-parallel XOR (arc4.c:101-112) as a sharded
    elementwise op. Works on any dtype/shape with leading axis divisible or
    padded to the shard count."""
    if data.shape != keystream.shape:
        # A short keystream must be an error: XOR-against-padding would pass
        # tail plaintext through unencrypted.
        raise ValueError(
            f"data/keystream shape mismatch: {data.shape} vs {keystream.shape}"
        )
    n_shards = mesh.devices.size
    padded, n = _pad_blocks(data, n_shards)
    ks_padded, _ = _pad_blocks(keystream, n_shards)
    return _xor_sharded_jit(padded, ks_padded, mesh=mesh, axis=axis)[:n]


def gather_for_verification(x, mesh: Mesh, axis: str = AXIS):
    """Optional all_gather so a host can bit-compare the full output — the
    lone collective, used only by tests (SURVEY.md §2: verification gather)."""
    padded, n = _pad_blocks(x, mesh.devices.size)
    f = shard_map(
        lambda s: jax.lax.all_gather(s, axis, tiled=True),
        mesh=mesh, in_specs=P(axis), out_specs=P(),
        check_vma=False,  # all_gather output is replicated; not inferred
    )
    return f(padded)[:n]


def block_cyclic_to_contiguous(x, mesh: Mesh, axis: str = AXIS):
    """All-to-all layout exchange: round-robin-sharded rows -> the
    contiguous-range sharding every cipher kernel here assumes.

    A producer that deals rows out round-robin (shard s holds global rows
    s, s+S, s+2S, ...) cannot feed the CTR/ECB kernels directly — their
    per-shard counter/offset math needs each chip to own one contiguous
    range (the reference's chunk split, test.c:51-53). This converts
    layouts entirely on-device with ONE `lax.all_to_all` over ICI: shard s
    slices its local rows into S groups by destination and receives its
    contiguous range's elements from everyone — no host gather, no
    full-array replication. Leading-axis length must divide evenly
    (cyclic layouts have no natural padding rows).

    With ppermute (halo exchange), all_gather (verification), and this
    all-to-all, the framework exercises each collective class the
    mesh/ICI design calls for.
    """
    S = mesh.devices.size
    n = x.shape[0]
    if n % (S * S):
        # Each shard must slice its n/S local rows into S equal groups.
        raise ValueError(
            f"row count {n} must be divisible by shards^2 ({S * S}) for an "
            "even all-to-all exchange"
        )

    def body(local):
        # local rows of shard s: global rows s + k*S (k = 0..n/S-1), i.e.
        # destination shard of local row k is k // (n/S/S). all_to_all
        # sends slice j of the split axis to shard j and concatenates what
        # arrives; interleaving each received group back by stride-S order
        # restores global order within the contiguous range.
        g = local.reshape((S, n // S // S) + local.shape[1:])
        recv = jax.lax.all_to_all(g, axis, split_axis=0, concat_axis=0,
                                  tiled=False)
        # recv[src, k] = global row (s * n//S) + k*S + src of this shard's
        # contiguous range -> transpose the (k, src) order.
        out = jnp.swapaxes(recv, 0, 1).reshape((n // S,) + local.shape[1:])
        return out

    f = shard_map(body, mesh=mesh, in_specs=P(axis), out_specs=P(axis))
    return f(x)


# ---------------------------------------------------------------------------
# Sequence-parallel chained modes: boundary exchange over ICI.
#
# CBC/CFB decryption recurrences read only *ciphertext*: plaintext block i
# needs ciphertext blocks i and i-1. Sharded over blocks, each shard needs
# exactly one block from its left neighbour — a halo exchange, the same
# communication pattern ring-attention uses for KV blocks, here one
# `ppermute` hop of 16 bytes per shard. This is the framework's one genuinely
# collective-dependent kernel (everything else is embarrassingly parallel;
# SURVEY.md §2 "Distributed communication backend").
# ---------------------------------------------------------------------------


def _shift_right_one(x, axis, mesh_size):
    """Each shard receives its left neighbour's value; shard 0 gets zeros."""
    perm = [(i, i + 1) for i in range(mesh_size - 1)]
    return jax.lax.ppermute(x, axis, perm)


def _halo_prev_stream(words, iv, axis, axis_size):
    """The prev-ciphertext stream for a chained-mode shard: local shift,
    seam block from the left neighbour via one ppermute hop, IV on shard 0."""
    seam = _shift_right_one(words[-1], axis, axis_size)
    first_prev = jnp.where(jax.lax.axis_index(axis) == 0, iv, seam)
    return jnp.concatenate([first_prev[None], words[:-1]], axis=0)


def _cbc_combine(words, prev, rk_dec, nr, engine):
    return CORES[engine][1](words, rk_dec, nr) ^ prev


def _cfb_combine(words, prev, rk_enc, nr, engine):
    return words ^ CORES[engine][0](prev, rk_enc, nr)


_CHAIN_COMBINE = {"cbc": _cbc_combine, "cfb128": _cfb_combine}


@functools.partial(jax.jit,
                   static_argnames=("nr", "mesh", "axis", "engine", "mode",
                                    "check_vma", "knobs"))
def _chained_dec_sharded_jit(words, iv, rk, *, nr, mesh, axis, engine, mode,
                             check_vma=True, knobs=None):
    del knobs  # compile-cache key only (see _ctr_sharded_jit)
    combine = _CHAIN_COMBINE[mode]

    def body(words, iv, rk):
        prev = _halo_prev_stream(words, iv, axis, mesh.shape[axis])
        return combine(words, prev, rk, nr, engine)

    f = shard_map(
        body, mesh=mesh, in_specs=(P(axis), P(), P()), out_specs=P(axis),
        # same pallas-interpreter vma drop as _ctr_sharded_jit: the halo
        # decrypt routes the per-shard bulk through CORES[engine], so a
        # pallas engine under interpreter mode hits the identical scan-carry
        # vma bug here (found by fuzz_parity --sharded --engines pallas)
        check_vma=check_vma,
    )
    return f(words, iv, rk)


def _chained_dec_sharded(words, iv_words, rk, nr, mesh, axis, engine, mode):
    w2 = _as_block_words(words)
    n = w2.shape[0]
    if n == 0:  # no-op, matching the single-chip path (models/aes.py)
        return words
    n_shards = mesh.shape[axis]
    if n % n_shards:
        raise ValueError(
            f"{mode.upper()} block count {n} must divide evenly over "
            f"{n_shards} shards (chained modes cannot be zero-padded)"
        )
    eng = resolve_engine(engine)
    out = _chained_dec_sharded_jit(
        w2, iv_words, rk, nr=nr, mesh=mesh, axis=axis,
        engine=eng, mode=mode, check_vma=_shard_check_vma(eng),
        knobs=_engine_knobs_key(eng),
    )
    return out.reshape(words.shape)


@functools.partial(jax.jit,
                   static_argnames=("nr", "mesh", "axis", "engine",
                                    "check_vma", "knobs"))
def _cbc_batch_sharded_jit(words, ivs, rk, *, nr, mesh, axis, engine,
                           check_vma, knobs):
    del knobs  # compile-cache key only (models/aes.py:_engine_knobs_key)
    f = shard_map(
        lambda w, iv, k: cbc_encrypt_words_batch(w, iv, k, nr, engine),
        mesh=mesh, in_specs=(P(axis), P(axis), P()),
        out_specs=(P(axis), P(axis)),
        check_vma=check_vma,
    )
    return f(words, ivs, rk)


def cbc_encrypt_batch_sharded(words, ivs, rk, nr, mesh: Mesh,
                              axis: str = AXIS, engine: str = "auto"):
    """Independent CBC streams sharded over chips — pipeline-style sequence
    parallelism for the chained mode: each chip runs its own streams'
    recurrences concurrently; streams are independent so there is no
    cross-chip communication (cf. the reference, where the chained modes
    simply could not use its pthread chunking at all).

    words: (S, N, 4) or (S, 4N); ivs: (S, 4). The stream axis is zero-
    padded to the shard count (padding streams are independent, so real
    streams are unaffected) and sliced back.
    """
    n_shards = mesh.devices.size
    padded_w, s = _pad_blocks(words, n_shards)
    padded_iv, _ = _pad_blocks(ivs, n_shards)
    eng = resolve_engine(engine)
    out, iv_out = _cbc_batch_sharded_jit(
        padded_w, padded_iv, rk, nr=nr, mesh=mesh, axis=axis, engine=eng,
        check_vma=_shard_check_vma(eng), knobs=_engine_knobs_key(eng))
    return out[:s], iv_out[:s]


@functools.partial(jax.jit, static_argnames=("length", "mesh", "axis"))
def _arc4_batch_sharded_jit(xs, ys, ms, *, length, mesh, axis):
    def body(x, y, m):
        (nx, ny, nm), ks = keystream_scan_batch((x, y, m), length)
        return nx, ny, nm, ks

    f = shard_map(
        body, mesh=mesh, in_specs=(P(axis), P(axis), P(axis)),
        out_specs=(P(axis), P(axis), P(axis), P(axis)),
    )
    return f(xs, ys, ms)


def arc4_prep_batch_sharded(states, length: int, mesh: Mesh,
                            axis: str = AXIS):
    """Keystreams for many independent ARC4 streams, sharded over chips.

    The keygen recurrence is the reference's sequential phase
    (arc4.c:72-97); like cbc_encrypt_batch_sharded, what cannot
    parallelise within a stream scales across streams — each chip scans
    its own streams concurrently, no cross-chip communication.
    ``states`` = (x, y, m) with shapes ((S,), (S,), (S, 256)) uint32;
    returns ((x', y', m'), keystream (S, length) uint8), stream count
    zero-padded to the shard count and sliced back.
    """
    xs, ys, ms = states
    s = xs.shape[0]
    n_shards = mesh.devices.size
    xs, _ = _pad_blocks(xs, n_shards)
    ys, _ = _pad_blocks(ys, n_shards)
    ms, _ = _pad_blocks(ms, n_shards)
    nx, ny, nm, ks = _arc4_batch_sharded_jit(xs, ys, ms, length=length,
                                             mesh=mesh, axis=axis)
    return (nx[:s], ny[:s], nm[:s]), ks[:s]


def cbc_decrypt_sharded(words, iv_words, rk_dec, nr, mesh: Mesh,
                        axis: str = AXIS, engine: str = "auto"):
    """CBC decrypt sharded over blocks with a one-block halo exchange.

    Bit-identical to the single-chip cbc_decrypt_words for every shard
    count. The block count must be nonzero and divide over the shards
    (padding a chained mode would corrupt the recurrence, so short inputs
    are rejected rather than padded).
    """
    return _chained_dec_sharded(words, iv_words, rk_dec, nr, mesh, axis,
                                engine, "cbc")


def cfb128_decrypt_sharded(words, iv_words, rk_enc, nr, mesh: Mesh,
                           axis: str = AXIS, engine: str = "auto"):
    """CFB128 decrypt sharded over blocks (keystream_i = E(C_{i-1}), so the
    same one-block halo exchange makes decryption fully parallel)."""
    return _chained_dec_sharded(words, iv_words, rk_enc, nr, mesh, axis,
                                engine, "cfb128")
