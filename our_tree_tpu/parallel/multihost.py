"""Multi-host initialization: the DCN side of the distributed backend.

The reference has no distributed communication layer at all (SURVEY.md §2
— pthreads and PCIe only). This framework's scaling axis is the device
mesh, and the same `shard_map` kernels in dist.py run unchanged whether
the mesh spans one chip, one host's chips (ICI), or many hosts (DCN): XLA
picks the transport per edge. The only multi-host-specific work is process
bootstrap, which this module wraps.

Usage, one call per process (all processes run the same program — SPMD):

    from our_tree_tpu.parallel import multihost
    multihost.initialize(coordinator="host0:8476",
                         num_processes=N, process_id=i)
    mesh = multihost.global_mesh()      # 1-D mesh over every chip anywhere
    out  = dist.ctr_crypt_sharded(words, ctr_be, rk, nr, mesh)

For CPU-only rehearsal without TPUs (the reference had no equivalent of
testing multi-device without owning the hardware, SURVEY.md §4):

    multihost.initialize(..., cpu_devices_per_process=4)

spawns each process with 4 virtual CPU devices; an N-process run then
exposes a 4N-device global mesh. tests/test_multihost.py drives a real
2-process x 2-device rehearsal through `ctr_crypt_sharded` and checks
bit-parity against the single-process result.
"""

from __future__ import annotations

import os
import re

import numpy as np


def initialize(coordinator: str, num_processes: int, process_id: int,
               cpu_devices_per_process: int | None = None) -> None:
    """Join the distributed system. Call before any other jax use.

    Args:
      coordinator: "host:port" of process 0's coordination service.
      num_processes: total process count (one per host, typically).
      process_id: this process's rank in [0, num_processes).
      cpu_devices_per_process: if set, force the CPU platform with this many
        virtual devices per process — the no-hardware rehearsal mode.
    """
    import jax

    if cpu_devices_per_process is not None:
        # Replace (not merely default) any inherited device-count flag: the
        # caller is describing the rehearsal topology, and a stale count
        # from e.g. a test runner would silently change the global mesh.
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+",
            "",
            os.environ.get("XLA_FLAGS", ""),
        )
        os.environ["XLA_FLAGS"] = (
            flags
            + f" --xla_force_host_platform_device_count={cpu_devices_per_process}"
        ).strip()
        jax.config.update("jax_platforms", "cpu")

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )


def global_mesh(axis: str = "shards"):
    """A 1-D mesh over every device in the system (all hosts)."""
    import jax
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()), (axis,))


def host_local_to_global(arr, mesh, axis: str = "shards"):
    """Assemble a globally-sharded array from per-host local shards.

    Each process passes its own contiguous chunk (equal sizes); the result
    is one global jax.Array block-sharded over `mesh` — the multi-host
    version of the scatter the reference did with pointer arithmetic
    (test.c:51-53).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P(axis))
    return jax.make_array_from_process_local_data(sharding, np.asarray(arr))
