"""Pallas TPU kernels for the AES round pipeline.

This is the framework's answer to the reference's CUDA kernels
(`AES_encrypt`/`AES_decrypt`, reference aes-gpu/Source/AES.cu:284-502): the
whole round pipeline as one fused device kernel. Where the CUDA version maps
one 16-byte block per thread and gathers from T-tables in shared memory, the
TPU version keeps the bitsliced plane formulation (ops/bitslice.py) and tiles
the *lane* axis: each grid step loads an (8, 16, TILE) u32 plane tile — TILE
lanes = 32·TILE blocks — into VMEM, runs all `nr` rounds on it without ever
touching HBM, and writes the ciphertext tile back. HBM traffic is exactly
input + output; the XLA fallback path (scan over rounds) re-materialises the
carry every round instead.

Differences from the plain-XLA bitslice path, forced by Mosaic:

  * ShiftRows: Mosaic has no vector gather, so the static byte-position
    permutation is a stack of 16 row slices instead of advanced indexing.
  * Rounds are a Python loop (nr is static) — fully unrolled straight-line
    code, like the CUDA kernels' `FULL_UNROLL` (reference AES.cu:35,298-365),
    but over 512-lane vectors instead of one block per thread.

On non-TPU backends the kernel runs in interpreter mode (tests exercise it
on CPU); `models.aes` registers it as the "pallas" engine either way.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import bitslice

import os

#: Lanes per grid step. (8, 16, 1024) u32 = 512 KiB per tile buffer; with
#: input + output + circuit intermediates this sits comfortably inside the
#: ~16 MiB of VMEM while keeping the lane dimension a multiple of 128.
#: OT_PALLAS_TILE overrides for on-hardware tuning without a code change.
TILE = int(os.environ.get("OT_PALLAS_TILE", 1024))
if TILE <= 0 or TILE % 128:
    raise ValueError(
        f"OT_PALLAS_TILE must be a positive multiple of 128, got {TILE}"
    )


def _perm_stack(x: jnp.ndarray, idx) -> jnp.ndarray:
    """Static permutation of the leading (byte-position) axis as slices."""
    return jnp.stack([x[int(j)] for j in idx], axis=0)


def _aes_kernel(kp_ref, in_ref, out_ref, *, nr: int, decrypt: bool):
    # ShiftRows is always the stack-of-slices permutation here: Mosaic has
    # no vector gather, and a pallas kernel may not capture the gather
    # form's constant index arrays.
    perm = _perm_stack
    planes = in_ref[...]
    kp = kp_ref[...]
    round_fn = bitslice.decrypt_round if decrypt else bitslice.encrypt_round
    p = planes ^ kp[0]

    # Middle rounds as a fori_loop rather than straight-line unrolling: the
    # loop keeps the traced circuit at one round (~800 vector ops), which
    # Mosaic compiles quickly and — in interpreter mode on CPU — avoids
    # handing XLA a 10x-unrolled graph it compiles pathologically slowly.
    def body(r, q):
        k = jax.lax.dynamic_index_in_dim(kp, r, axis=0, keepdims=False)
        return round_fn(q, k, False, perm=perm)

    p = jax.lax.fori_loop(1, nr, body, p)
    out_ref[...] = round_fn(p, kp[nr], True, perm=perm)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


@functools.partial(jax.jit, static_argnames=("nr", "decrypt", "tile"))
def _crypt_planes_pallas(planes, kp, *, nr, decrypt, tile):
    w = planes.shape[2]
    kernel = functools.partial(_aes_kernel, nr=nr, decrypt=decrypt)
    return pl.pallas_call(
        kernel,
        grid=(w // tile,),
        in_specs=[
            pl.BlockSpec((nr + 1, 8, 16, 1), lambda i: (0, 0, 0, 0)),
            pl.BlockSpec((8, 16, tile), lambda i: (0, 0, i)),
        ],
        out_specs=pl.BlockSpec((8, 16, tile), lambda i: (0, 0, i)),
        out_shape=jax.ShapeDtypeStruct(planes.shape, planes.dtype),
        interpret=_interpret(),
    )(kp, planes)


def _crypt_words(words, rk, nr, decrypt):
    n = words.shape[0]
    if n == 0:
        return words
    # Pad to whole 32-block lanes first, THEN pick the tile: choosing the
    # tile from the unpadded count can double the padded work for sizes
    # just under the tile span. This way padding never exceeds 31 blocks
    # plus tile alignment on the lane axis.
    w_lanes = (n + 31) // 32
    tile = min(TILE, w_lanes)
    pad = 32 * ((w_lanes + tile - 1) // tile * tile) - n
    if pad:
        words = jnp.concatenate([words, jnp.zeros((pad, 4), words.dtype)], axis=0)
    planes = bitslice.to_planes(words)
    kp = bitslice.key_planes(rk, nr)
    out = _crypt_planes_pallas(planes, kp, nr=nr, decrypt=decrypt, tile=tile)
    return bitslice.from_planes(out)[:n]


def encrypt_words(words: jnp.ndarray, rk: jnp.ndarray, nr: int) -> jnp.ndarray:
    """Pallas-kernel batch encrypt; contract of ops/block.py:encrypt_words."""
    return _crypt_words(words, rk, nr, decrypt=False)


def decrypt_words(words: jnp.ndarray, rk_dec: jnp.ndarray, nr: int) -> jnp.ndarray:
    """Pallas-kernel batch decrypt (InvMixColumns-folded schedule)."""
    return _crypt_words(words, rk_dec, nr, decrypt=True)
