"""Pallas TPU kernels for the AES round pipeline.

This is the framework's answer to the reference's CUDA kernels
(`AES_encrypt`/`AES_decrypt`, reference aes-gpu/Source/AES.cu:284-502): the
whole round pipeline as one fused device kernel. Where the CUDA version maps
one 16-byte block per thread and gathers from T-tables in shared memory, the
TPU version keeps the bitsliced plane formulation (ops/bitslice.py) and tiles
the *lane* axis: each grid step loads an (8, 16, TILE) u32 plane tile — TILE
lanes = 32·TILE blocks — into VMEM, runs all `nr` rounds on it without ever
touching HBM, and writes the ciphertext tile back. HBM traffic is exactly
input + output; the XLA fallback path (scan over rounds) re-materialises the
carry every round instead.

Differences from the plain-XLA bitslice path, forced by Mosaic:

  * ShiftRows: Mosaic has no vector gather, so the static byte-position
    permutation is a stack of 16 row slices instead of advanced indexing.
  * Rounds are a Python loop (nr is static) — fully unrolled straight-line
    code, like the CUDA kernels' `FULL_UNROLL` (reference AES.cu:35,298-365),
    but over 512-lane vectors instead of one block per thread.

On non-TPU backends the kernel runs in interpreter mode (tests exercise it
on CPU); `models.aes` registers it as the "pallas" engine either way.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import bitslice
from ..obs import trace as _trace
from ..resilience import faults as _faults
from ..resilience import watchdog as _watchdog

import os


def _dispatch_seam(what: str) -> None:
    """The Pallas kernel dispatch seam (ROADMAP follow-up): the last
    host-side point before a kernel launch enters the runtime, shared by
    every pallas entry path. ``dispatch_fail`` makes the launch raise
    (the remote_compile HTTP-500 class of failure — VERDICT r4 missing
    #3); ``dispatch_hang`` blocks it in a GIL-releasing sleep (the
    wedged-launch class the GPU-AES literature calls per-kernel launch
    hangs), for the watchdog to interrupt or a supervising parent to
    SIGKILL. A point *inside* the traced grid loop cannot exist — the
    kernel body is staged once and replayed by Mosaic — so the honest
    seam is the dispatch itself. One dict lookup each while unarmed.

    With tracing on, every launch also counts into the
    ``pallas_dispatch`` counter (obs/trace.py) — the trace-side answer
    to "how many kernel launches did this row actually make", which is
    a span-free counter because the launch itself is async: the wall
    time lands in the caller's barrier span, not here.
    """
    _trace.counter("pallas_dispatch", what=what)
    _faults.check("dispatch_fail", what)
    _watchdog.injected_hang("dispatch_hang", what)

#: Import defaults for the tuning knobs, exported so other modules (the
#: compile-probe's override guard in models/aes.py, scripts/tune_tpu.py's
#: mirror) can ask "is the effective config the default one?" without
#: re-stating the values.
DEFAULT_TILE, DEFAULT_MC = 1024, "perm"

#: Lanes per grid step. (8, 16, 1024) u32 = 512 KiB per tile buffer; with
#: input + output + circuit intermediates this sits comfortably inside the
#: ~16 MiB of VMEM while keeping the lane dimension a multiple of 128.
#: OT_PALLAS_TILE overrides for on-hardware tuning without a code change.
TILE = int(os.environ.get("OT_PALLAS_TILE", DEFAULT_TILE))
if TILE <= 0 or TILE % 128:
    raise ValueError(
        f"OT_PALLAS_TILE must be a positive multiple of 128, got {TILE}"
    )

#: MixColumns rotation lowering inside kernels: "perm" (leading-axis
#: slice-stacks, the conservative Mosaic form) or "roll" (reshape + sublane
#: roll — fewer data movements if the generation's Mosaic supports it).
#: A hardware tuning knob, like OT_PALLAS_TILE.
MC_LOWERING = os.environ.get("OT_PALLAS_MC", DEFAULT_MC)
if MC_LOWERING not in ("perm", "roll"):
    raise ValueError(
        f"OT_PALLAS_MC must be 'perm' or 'roll', got {MC_LOWERING!r}"
    )

#: Per-size tile overrides: {MiB ceiling: tile}, applied by message size
#: BEFORE the flat TILE (tile_for_blocks) — the per-size tune sweep
#: (scripts/tune_tile_sizes.py) persists winners here when a size bucket
#: prefers a different tile than the global winner (VERDICT r4 #7).
#: Empty by default; an explicit OT_PALLAS_TILE pin outranks the map
#: (enforced at the apply site, same precedence as the flat knob).
TILE_BY_MIB: dict[int, int] = {}


def tile_for_blocks(n_blocks: int) -> int:
    """Effective tile knob for an n-block batch: the smallest configured
    size-bucket ceiling that covers the batch, else the flat TILE."""
    if TILE_BY_MIB:
        nbytes = 16 * n_blocks
        for ceil_mib in sorted(TILE_BY_MIB):
            if nbytes <= ceil_mib << 20:
                return TILE_BY_MIB[ceil_mib]
    return TILE


def apply_knobs(kn: dict, respect_env: bool = True) -> dict:
    """Apply persisted tuned kernel knobs (utils/ranking.py:knobs) to this
    module's TILE / MC_LOWERING, returning what was actually applied.

    Both knobs are read at PYTHON call time in the entry points (which is
    also what lets tests monkeypatch them) and passed into the jitted
    wrappers as static arguments — part of the compile-cache key — so a
    mid-process change cleanly recompiles on the next call instead of
    silently reusing an executable built under the old setting. The
    models-level engine entry points thread the same values into THEIR
    compile keys (models/aes.py:_engine_knobs_key), so the guarantee
    holds through every public path, not just direct pallas calls
    (ADVICE r4 #1). With
    ``respect_env`` (the default), a knob the user pinned explicitly via
    OT_PALLAS_TILE / OT_PALLAS_MC is left alone: an explicit override
    outranks a stored measurement, same precedence as OT_BENCH_ENGINE over
    the engine ranking. Values are re-validated against the import-time
    constraints — the source is a data file, so invalid entries are
    skipped, never raised.
    """
    from ..utils.ranking import _KNOB_VALID  # single source of validity

    global TILE, MC_LOWERING, TILE_BY_MIB
    applied = {}
    tile_pinned = respect_env and "OT_PALLAS_TILE" in os.environ
    tile = kn.get("tile")
    if _KNOB_VALID["tile"](tile) and tile != TILE and not tile_pinned:
        TILE = applied["tile"] = tile
    # The per-size map rides the same env pin as the flat tile: an
    # explicit OT_PALLAS_TILE means "this tile, for everything".
    by_mib = kn.get("tile_by_mib")
    if _KNOB_VALID["tile_by_mib"](by_mib) and not tile_pinned:
        as_int = {int(k): v for k, v in by_mib.items()}
        if as_int != TILE_BY_MIB:
            TILE_BY_MIB = as_int
            applied["tile_by_mib"] = ",".join(
                f"<={k}MiB:{v}" for k, v in sorted(as_int.items()))
    mc = kn.get("mc")
    if (_KNOB_VALID["mc"](mc) and mc != MC_LOWERING
            and not (respect_env and "OT_PALLAS_MC" in os.environ)):
        MC_LOWERING = applied["mc"] = mc
    return applied


def apply_stored_knobs(device=None, respect_env: bool = True) -> dict:
    """Apply the persisted tuned knobs for `device` (default: the first
    jax device), reporting to stderr the first time anything changes.

    The ONE shared entry for every apply site — bench.py, the harness
    TpuBackend, and resolve_engine("auto") — so knob precedence and
    reporting cannot drift between copies. Cheap enough for per-call use:
    the ranking read is mtime-cached, and apply_knobs is idempotent (an
    already-applied knob reports nothing). No-op on CPU: stored knobs are
    keyed by accelerator device kind, and interpreter-mode kernels have
    nothing to tune.
    """
    if device is None:
        device = jax.devices()[0]
    if device.platform == "cpu":
        return {}
    from ..utils import ranking

    key = ranking.device_key(device.platform,
                             getattr(device, "device_kind", None))
    applied = apply_knobs(ranking.knobs(key), respect_env=respect_env)
    if applied:
        import sys

        print(f"# tuned knobs applied ({key}): " + " ".join(
            f"{k}={v}" for k, v in sorted(applied.items())), file=sys.stderr)
    return applied


def _perm_stack(x: jnp.ndarray, idx) -> jnp.ndarray:
    """Static permutation of the leading (byte-position) axis as slices."""
    return jnp.stack([x[int(j)] for j in idx], axis=0)


def _run_rounds(p, kp, nr: int, round_fn, interpret: bool, mc: str):
    """Whitened state -> state after the nr-1 middle rounds.

    ShiftRows / MixColumns rotations inside kernels are always the
    stack-of-slices permutation (_perm_stack): Mosaic has no vector gather,
    and a pallas kernel may not capture the gather form's constant index
    arrays — the traced body is only leading-axis slices, stacks, and u32
    bit ops, the most conservative Mosaic feature set. Shared by the ECB
    and fused-CTR kernels so the loop strategy cannot diverge between them.
    """
    if interpret:
        # Interpreter mode (CPU tests): a fori_loop keeps the traced circuit
        # at one round (~800 vector ops) — XLA-CPU compiles a 10x-unrolled
        # graph pathologically slowly.
        def body(r, q):
            k = jax.lax.dynamic_index_in_dim(kp, r, axis=0, keepdims=False)
            return round_fn(q, k, False, perm=_perm_stack, mc=mc)

        return jax.lax.fori_loop(1, nr, body, p)
    # Compiled: fully unrolled straight-line rounds with *static* key
    # indexing, like the CUDA kernels' FULL_UNROLL (reference
    # aes-gpu/Source/AES.cu:35,298-365) — no dynamic slicing for Mosaic
    # to trip on, and the round keys fold into the instruction stream.
    for r in range(1, nr):
        p = round_fn(p, kp[r], False, perm=_perm_stack, mc=mc)
    return p


#: Kernel-boundary layouts, shared by the ECB and counter-generating CTR
#: entry points: name -> (relayout_in, relayout_out, tile_shape, unpack,
#: pack). "planes" converts OUTSIDE the kernel (bitslice.to/from_planes as
#: XLA passes; identity inside); "grouped" crosses the boundary in the
#: (32, 4, W) grouped word layout (a pure relayout) and runs the SWAR
#: bit-transposition ladder INSIDE the kernel on VMEM tiles (the
#: "pallas-gt" engine). One table so padding/vma/grid plumbing exists once
#: and cannot drift between the two engines.
#:
#: Known tradeoff of the grouped layout: its 4-wide second-minor (sublane)
#: dim pads to 8 under TPU tiling, so grouped HBM streams and VMEM tiles
#: carry 2x the logical bytes. The kernel is compute-bound (docs/PERF.md
#: roofline: HBM ceiling is an order of magnitude above the VPU one), so
#: this should not decide the pallas-vs-pallas-gt A/B — but it does halve
#: the grouped path's buffer-size ceiling. The "dense" layout is the
#: follow-up that removes the tax: the (32, 4) axes merge into one leading
#: 128-row sublane dim (an exact multiple of the 8-row tile — zero
#: padding), and the in-kernel ladder runs directly on that form via
#: leading-axis reshapes (bitslice.transpose32_dense) — the same
#: conservative Mosaic feature set as the grouped ladder, no sublane
#: rolls. Registered as its own engine ("pallas-dense") so the first
#: hardware probe A/Bs the two boundary layouts and the ranking retires
#: the loser (utils/ranking.py).
_LAYOUTS = {
    "planes": (bitslice.to_planes, bitslice.from_planes,
               lambda tile: (8, 16, tile), None, None),
    "grouped": (bitslice.group_words, bitslice.ungroup_words,
                lambda tile: (32, 4, tile),
                bitslice.planes_from_grouped, bitslice.grouped_from_planes),
    "dense": (bitslice.dense_words, bitslice.undense_words,
              lambda tile: (128, tile),
              bitslice.planes_from_dense, bitslice.dense_from_planes),
}


def _tile_spec(shape_fn, tile: int) -> pl.BlockSpec:
    """BlockSpec gridding the LANE (last) axis, for any layout rank: the
    leading dims are whole, block i covers lanes [i*tile, (i+1)*tile)."""
    shape = shape_fn(tile)
    zeros = (0,) * (len(shape) - 1)
    return pl.BlockSpec(shape, lambda i, _z=zeros: _z + (i,))


def _aes_kernel(kp_ref, in_ref, out_ref, *, nr: int, decrypt: bool,
                interpret: bool, unpack=None, pack=None,
                sbox: str | None = None, mc: str = "perm"):
    kp = kp_ref[...]
    # sbox picks the forward S-box circuit per ENGINE (models/aes.py
    # registers formulation variants like "pallas-gt-bp"); decrypt always
    # takes the tower inverse — Boyar–Peralta published no comparably small
    # inverse circuit (ops/bitslice.py:inv_sbox_planes).
    round_fn = (bitslice.decrypt_round if decrypt
                else functools.partial(bitslice.encrypt_round, sbox=sbox))
    x = in_ref[...]
    p = unpack(x) if unpack is not None else x
    p = _run_rounds(p ^ kp[0], kp, nr, round_fn, interpret, mc)
    p = round_fn(p, kp[nr], True, perm=_perm_stack)
    out_ref[...] = pack(p) if pack is not None else p


def _to_varying(x: jnp.ndarray, axes) -> jnp.ndarray:
    """`pvary` through its non-deprecated successor when the runtime has
    one: jax 0.9 renamed `jax.lax.pvary` to `jax.lax.pcast(...,
    to='varying')` and the old name warns on every trace (VERDICT r4 weak
    #6) before eventually breaking. Feature-probed rather than
    version-pinned — the same policy as parallel/dist.py:_vma_drop_bug:
    reproduce/detect the actual runtime surface, don't guess releases."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is not None:
        return pcast(x, tuple(axes), to="varying")
    return jax.lax.pvary(x, tuple(axes))


def _match_vma(x: jnp.ndarray, like: jnp.ndarray) -> jnp.ndarray:
    """Promote x (e.g. replicated round keys) to `like`'s varying mesh axes.

    Under `jax.shard_map(..., check_vma=True)` mixing a replicated value
    into a shard-varying computation needs an explicit vary-promotion;
    outside shard_map both vma sets are empty and this is a no-op."""
    try:
        missing = jax.typeof(like).vma - jax.typeof(x).vma
    except Exception:
        return x
    return _to_varying(x, missing) if missing else x


def _out_struct(x: jnp.ndarray) -> jax.ShapeDtypeStruct:
    """Output spec matching x, carrying its varying-mesh-axes set.

    Inside `jax.shard_map(..., check_vma=True)` a pallas_call must declare
    which mesh axes its output varies over; mirroring the input's vma makes
    the kernels usable both standalone and as shard_map bodies
    (parallel/dist.py)."""
    try:
        vma = jax.typeof(x).vma
    except Exception:
        vma = None
    if vma is None:
        return jax.ShapeDtypeStruct(x.shape, x.dtype)
    try:
        return jax.ShapeDtypeStruct(x.shape, x.dtype, vma=vma)
    except TypeError:  # this jax predates the vma kwarg
        return jax.ShapeDtypeStruct(x.shape, x.dtype)


def interpret_mode() -> bool:
    """Public alias of _interpret for other modules (parallel/dist.py keys
    its shard_map vma-check workaround on interpreter mode)."""
    return _interpret()


def _interpret() -> bool:
    """Interpreter mode unless a real TPU device is attached.

    Checked against the *devices*, not `jax.default_backend()`: tunnelled
    TPU platforms can register under a different backend name while the
    device platform is still "tpu". OT_PALLAS_INTERPRET=0/1 overrides.
    """
    env = os.environ.get("OT_PALLAS_INTERPRET")
    if env is not None:
        return env not in ("0", "false", "")
    try:
        return not any(
            d.platform == "tpu" or "TPU" in (d.device_kind or "")
            for d in jax.devices()
        )
    except Exception:
        return True


@functools.partial(jax.jit,
                   static_argnames=("nr", "decrypt", "tile", "layout", "sbox",
                                    "mc"))
def _crypt_planes_pallas(x, kp, *, nr, decrypt, tile, layout="planes",
                         sbox=None, mc="perm"):
    _, _, shape_fn, unpack, pack = _LAYOUTS[layout]
    w = x.shape[-1]
    interpret = _interpret()
    kernel = functools.partial(
        _aes_kernel, nr=nr, decrypt=decrypt, interpret=interpret,
        unpack=unpack, pack=pack, sbox=sbox, mc=mc,
    )
    return pl.pallas_call(
        kernel,
        grid=(w // tile,),
        in_specs=[
            pl.BlockSpec((nr + 1, 8, 16, 1), lambda i: (0, 0, 0, 0)),
            _tile_spec(shape_fn, tile),
        ],
        out_specs=_tile_spec(shape_fn, tile),
        out_shape=_out_struct(x),
        interpret=interpret,
    )(kp, x)


def _lane_pad_and_tile(n: int, cap: int | None = None) -> tuple[int, int]:
    """(pad_blocks, tile) for an n-block batch.

    Pad to whole 32-block lanes first, THEN pick the tile: choosing the
    tile from the unpadded count can double the padded work for sizes
    just under the tile span. This way padding never exceeds 31 blocks
    plus tile alignment on the lane axis. Shared by every pallas entry
    point so the padding invariant cannot drift between them. ``cap``
    bounds the tile below the tuned knob for kernels whose VMEM
    footprint grows past the data tiles (the multi-key entry carries a
    full (nr+1, 8, 16, tile) effective-key-plane tensor).
    """
    w_lanes = (n + 31) // 32
    tile = min(tile_for_blocks(n), w_lanes)
    if cap is not None:
        tile = min(tile, cap)
    pad = 32 * ((w_lanes + tile - 1) // tile * tile) - n
    return pad, tile


def _crypt_words(words, rk, nr, decrypt, layout="planes", sbox=None):
    n = words.shape[0]
    if n == 0:
        return words
    _dispatch_seam(f"pallas {'decrypt' if decrypt else 'encrypt'} dispatch "
                   f"({layout})")
    pad, tile = _lane_pad_and_tile(n)
    if pad:
        words = jnp.concatenate([words, jnp.zeros((pad, 4), words.dtype)], axis=0)
    pre, post, *_ = _LAYOUTS[layout]
    x = pre(words)
    kp = _match_vma(bitslice.key_planes(rk, nr), x)
    # MC lowering is read at PYTHON call time and passed as a jit static:
    # a mid-process apply_knobs("mc") change recompiles instead of silently
    # reusing an executable traced under the old lowering.
    out = _crypt_planes_pallas(x, kp, nr=nr, decrypt=decrypt, tile=tile,
                               layout=layout, sbox=sbox, mc=MC_LOWERING)
    return post(out)[:n]


def encrypt_words(words: jnp.ndarray, rk: jnp.ndarray, nr: int) -> jnp.ndarray:
    """Pallas-kernel batch encrypt; contract of ops/block.py:encrypt_words."""
    return _crypt_words(words, rk, nr, decrypt=False)


def decrypt_words(words: jnp.ndarray, rk_dec: jnp.ndarray, nr: int) -> jnp.ndarray:
    """Pallas-kernel batch decrypt (InvMixColumns-folded schedule)."""
    return _crypt_words(words, rk_dec, nr, decrypt=True)


def encrypt_words_gt(words: jnp.ndarray, rk: jnp.ndarray, nr: int):
    """Grouped-transpose ECB encrypt (in-kernel SWAR ladder); contract of
    encrypt_words. The "pallas-gt" engine."""
    return _crypt_words(words, rk, nr, decrypt=False, layout="grouped")


def encrypt_words_gt_bp(words: jnp.ndarray, rk: jnp.ndarray, nr: int):
    """Grouped-transpose ECB encrypt with the Boyar–Peralta S-box circuit
    (119 vs the tower's 174 plane-ops — docs/PERF.md ledger item 7) pinned
    per-call, regardless of OT_SBOX. The "pallas-gt-bp" engine: registering
    the formulation as its own engine lets bench.py's probe stage A/B the
    two circuits on hardware in ONE run instead of needing an env-var
    re-import sweep (scripts/tune_tpu.py still covers the full matrix)."""
    return _crypt_words(words, rk, nr, decrypt=False, layout="grouped",
                        sbox="bp")


def decrypt_words_gt(words: jnp.ndarray, rk_dec: jnp.ndarray, nr: int):
    """Grouped-transpose ECB decrypt; contract of decrypt_words."""
    return _crypt_words(words, rk_dec, nr, decrypt=True, layout="grouped")


def encrypt_words_dense(words: jnp.ndarray, rk: jnp.ndarray, nr: int):
    """Dense-boundary ECB encrypt: the (128, W) zero-padding layout with
    the in-kernel ladder (bitslice.transpose32_dense). The "pallas-dense"
    engine — pallas-gt minus the grouped layout's 2x HBM/VMEM tax."""
    return _crypt_words(words, rk, nr, decrypt=False, layout="dense")


def decrypt_words_dense(words: jnp.ndarray, rk_dec: jnp.ndarray, nr: int):
    """Dense-boundary ECB decrypt; contract of decrypt_words."""
    return _crypt_words(words, rk_dec, nr, decrypt=True, layout="dense")


def encrypt_words_dense_bp(words: jnp.ndarray, rk: jnp.ndarray, nr: int):
    """Dense-boundary ECB encrypt with the Boyar–Peralta S-box pinned
    per-call (see encrypt_words_gt_bp). The "pallas-dense-bp" engine."""
    return _crypt_words(words, rk, nr, decrypt=False, layout="dense",
                        sbox="bp")


# ---------------------------------------------------------------------------
# Fused CTR: encrypt the counter tile AND xor the data tile in one kernel.
#
# The layered CTR path (models/aes.py: keystream = engine_encrypt(counters);
# out = data ^ keystream) writes the keystream to HBM, reads it back for the
# XOR, and writes the output — three full-buffer HBM passes beyond the
# unavoidable data read/out write. Keystream blocks never need to exist in
# HBM at all: this kernel takes the counter planes and the data planes as
# two inputs, runs the round pipeline on the counters in VMEM, xors the data
# tile, and writes only the ciphertext tile (semantics per the reference's
# CTR definition, aes-modes/aes.c:869-901: C = P ^ E(counter)).
# ---------------------------------------------------------------------------


def _ctr_kernel(kp_ref, ctr_ref, data_ref, out_ref, *, nr: int,
                interpret: bool, mc: str = "perm"):
    kp = kp_ref[...]
    p = _run_rounds(ctr_ref[...] ^ kp[0], kp, nr, bitslice.encrypt_round,
                    interpret, mc)
    ks = bitslice.encrypt_round(p, kp[nr], True, perm=_perm_stack)
    out_ref[...] = data_ref[...] ^ ks


@functools.partial(jax.jit, static_argnames=("nr", "tile", "mc"))
def _ctr_planes_pallas(ctr_planes, data_planes, kp, *, nr, tile, mc="perm"):
    w = ctr_planes.shape[2]
    interpret = _interpret()
    kernel = functools.partial(_ctr_kernel, nr=nr, interpret=interpret, mc=mc)
    spec = pl.BlockSpec((8, 16, tile), lambda i: (0, 0, i))
    return pl.pallas_call(
        kernel,
        grid=(w // tile,),
        in_specs=[
            pl.BlockSpec((nr + 1, 8, 16, 1), lambda i: (0, 0, 0, 0)),
            spec,
            spec,
        ],
        out_specs=spec,
        out_shape=_out_struct(ctr_planes),
        interpret=interpret,
    )(kp, ctr_planes, data_planes)


def ctr_crypt_words(words: jnp.ndarray, ctr_le: jnp.ndarray, rk: jnp.ndarray,
                    nr: int) -> jnp.ndarray:
    """Fused CTR en/decrypt: words ^ E(counter blocks), keystream VMEM-only.

    ``ctr_le`` is the (N, 4) u32 LE-word counter block stream (already
    offset/byteswapped by the caller — models/aes.py owns the 128-bit BE
    counter arithmetic). Symmetric, so it serves both directions.
    """
    n = words.shape[0]
    if n == 0:
        return words
    _dispatch_seam("pallas fused-CTR dispatch (materialised counters)")
    pad, tile = _lane_pad_and_tile(n)
    if pad:
        zeros = jnp.zeros((pad, 4), words.dtype)
        words = jnp.concatenate([words, zeros], axis=0)
        ctr_le = jnp.concatenate([ctr_le, zeros], axis=0)
    ctr_planes = bitslice.to_planes(ctr_le)
    data_planes = _match_vma(bitslice.to_planes(words), ctr_planes)
    ctr_planes = _match_vma(ctr_planes, data_planes)
    out = _ctr_planes_pallas(
        ctr_planes,
        data_planes,
        _match_vma(bitslice.key_planes(rk, nr), data_planes),
        nr=nr,
        tile=tile,
        mc=MC_LOWERING,
    )
    return bitslice.from_planes(out)[:n]


# ---------------------------------------------------------------------------
# Counter-generating fused CTR: the counter *bit-planes* are synthesised
# inside the kernel from the 128-bit base counter, so the counter stream
# never exists anywhere — not in HBM, not even as words. What the layered
# path spends per block on iota + 128-bit add + byteswap + SWAR transposition
# (plus two full HBM streams: write counters, read them back) collapses to a
# bitsliced ripple-carry adder on (1, TILE) lane vectors: block j of lane l
# in grid step g has index j = 32*(g*TILE + l) + t (t = bit position), so
# bits 0..4 of j are compile-time lane masks, bits 5+ are broadcast bits of
# the lane iota, and counter_bit_q(j) = bit q of (base + j) comes from a
# 128-step ripple add whose operands are bit-masks — ~5 tiny vector ops per
# counter bit, amortised over 32*TILE blocks.
# ---------------------------------------------------------------------------

#: Lane-constant bit masks of t = block position within a u32 lane:
#: bit t of _IOTA32_MASKS[q] == (t >> q) & 1.
_IOTA32_MASKS = tuple(
    sum(((t >> q) & 1) << t for t in range(32)) for q in range(5)
)


def _ctr_planes_from_base(base, g, tile: int):
    """(8, 16, tile) counter planes for blocks j = 32*(g*tile + lane) + t.

    ``base`` is a (128, 1) u32 array of full-lane masks: row q = bit q of
    the 128-bit big-endian base counter, replicated (0 or 0xFFFFFFFF).
    Byte order matches models/aes.py:ctr_le_blocks — plane[b, p] holds bit
    b of counter-stream byte p, and stream byte p is bits 8*(15-p)..+7 of
    the big-endian counter value (reference semantics aes-modes/aes.c:879-884).
    """
    one = jnp.uint32(1)
    lane = jax.lax.broadcasted_iota(jnp.uint32, (1, tile), 1)
    G = jnp.uint32(g) * jnp.uint32(tile) + lane
    jbits: list = []
    for q in range(128):
        if q < 5:
            jbits.append(jnp.full((1, tile), _IOTA32_MASKS[q], jnp.uint32))
        elif q - 5 < 32:
            # broadcast bit (q-5) of the lane index to all 32 block slots
            jbits.append(jnp.uint32(0) - ((G >> jnp.uint32(q - 5)) & one))
        else:
            jbits.append(None)  # j < 2^37 always (32·lane count)
    s = []
    carry = None
    for q in range(128):
        bq = base[q]  # (1,) -> broadcasts over (1, tile)
        jq = jbits[q]
        if jq is None:  # high bits: j contributes 0, only the carry ripples
            s.append(bq ^ carry)
            carry = bq & carry
            continue
        if carry is None:
            s.append(bq ^ jq)
            carry = bq & jq
        else:
            t = bq ^ jq
            s.append(t ^ carry)
            carry = (bq & jq) | (carry & t)
    planes = []
    for b in range(8):
        rows = [s[8 * (15 - p) + b] for p in range(16)]
        planes.append(jnp.concatenate(rows, axis=0))  # (16, tile)
    return jnp.stack(planes)


def _ctr_gen_kernel(kp_ref, base_ref, data_ref, out_ref, *, nr: int,
                    tile: int, interpret: bool, pack=None,
                    sbox: str | None = None, mc: str = "perm"):
    kp = kp_ref[...]
    ctr = _ctr_planes_from_base(base_ref[...], pl.program_id(0), tile)
    round_fn = functools.partial(bitslice.encrypt_round, sbox=sbox)
    p = _run_rounds(ctr ^ kp[0], kp, nr, round_fn, interpret, mc)
    ks = round_fn(p, kp[nr], True, perm=_perm_stack)
    # In the grouped layout (pack set) the DATA tile is never bit-transposed
    # at all: XOR commutes with the transposition, so only the synthesised
    # keystream converts (bitslice.grouped_from_planes) before the XOR.
    out_ref[...] = data_ref[...] ^ (pack(ks) if pack is not None else ks)


@functools.partial(jax.jit,
                   static_argnames=("nr", "tile", "layout", "sbox", "mc"))
def _ctr_gen_planes_pallas(x, base_masks, kp, *, nr, tile, layout="planes",
                           sbox=None, mc="perm"):
    _, _, shape_fn, _, pack = _LAYOUTS[layout]
    w = x.shape[-1]
    interpret = _interpret()
    kernel = functools.partial(_ctr_gen_kernel, nr=nr, tile=tile,
                               interpret=interpret, pack=pack, sbox=sbox,
                               mc=mc)
    spec = _tile_spec(shape_fn, tile)
    return pl.pallas_call(
        kernel,
        grid=(w // tile,),
        in_specs=[
            pl.BlockSpec((nr + 1, 8, 16, 1), lambda i: (0, 0, 0, 0)),
            pl.BlockSpec((128, 1), lambda i: (0, 0)),
            spec,
        ],
        out_specs=spec,
        out_shape=_out_struct(x),
        interpret=interpret,
    )(kp, base_masks, x)


def _ctr_gen_words(words, ctr_be_words, rk, nr, layout, sbox=None):
    n = words.shape[0]
    if n == 0:
        return words
    _dispatch_seam(f"pallas fused-CTR dispatch ({layout})")
    pad, tile = _lane_pad_and_tile(n)
    if pad:
        words = jnp.concatenate([words, jnp.zeros((pad, 4), words.dtype)],
                                axis=0)
    pre, post, *_ = _LAYOUTS[layout]
    x = pre(words)
    base = _match_vma(_base_bit_masks(ctr_be_words), x)
    kp = _match_vma(bitslice.key_planes(rk, nr), x)
    out = _ctr_gen_planes_pallas(x, base, kp, nr=nr, tile=tile, layout=layout,
                                 sbox=sbox, mc=MC_LOWERING)
    return post(out)[:n]


def ctr_crypt_words_gt(words: jnp.ndarray, ctr_be_words: jnp.ndarray,
                       rk: jnp.ndarray, nr: int) -> jnp.ndarray:
    """Fused counter-synthesising CTR in the grouped-transpose formulation.

    Registered as the "pallas-gt" engine's CTR_FUSED entry. Same 128-bit
    big-endian counter semantics as ctr_crypt_words_gen (block i's counter
    = base + i, aes-modes/aes.c:869-901); the only structural difference is
    where the bit transposition happens. Here the data is never
    bit-transposed AT ALL — it crosses the boundary in the (32, 4, W)
    grouped layout (one pure relayout) and the kernel converts only the
    synthesised keystream before the XOR. Which formulation wins on a given
    TPU generation depends on whether Mosaic schedules the in-kernel ladder
    better than XLA schedules the to/from_planes HBM round-trips
    (tune_tpu --engines pallas,pallas-gt measures both)."""
    return _ctr_gen_words(words, ctr_be_words, rk, nr, layout="grouped")


def ctr_crypt_words_gt_bp(words: jnp.ndarray, ctr_be_words: jnp.ndarray,
                          rk: jnp.ndarray, nr: int) -> jnp.ndarray:
    """ctr_crypt_words_gt with the Boyar–Peralta S-box pinned per-call —
    the "pallas-gt-bp" engine's CTR_FUSED entry (see encrypt_words_gt_bp
    for why the formulation is its own engine)."""
    return _ctr_gen_words(words, ctr_be_words, rk, nr, layout="grouped",
                          sbox="bp")


def ctr_crypt_words_dense(words: jnp.ndarray, ctr_be_words: jnp.ndarray,
                          rk: jnp.ndarray, nr: int) -> jnp.ndarray:
    """Counter-synthesising fused CTR over the dense (128, W) boundary —
    the "pallas-dense" engine's CTR_FUSED entry. Identical structure to
    ctr_crypt_words_gt (data never bit-transposed; only the synthesised
    keystream converts, via dense_from_planes, before the XOR), minus the
    grouped layout's padding tax — so a 1 GiB stream stages 1 GiB."""
    return _ctr_gen_words(words, ctr_be_words, rk, nr, layout="dense")


def ctr_crypt_words_dense_bp(words: jnp.ndarray, ctr_be_words: jnp.ndarray,
                             rk: jnp.ndarray, nr: int) -> jnp.ndarray:
    """ctr_crypt_words_dense with the Boyar–Peralta S-box pinned per-call —
    the "pallas-dense-bp" engine's CTR_FUSED entry."""
    return _ctr_gen_words(words, ctr_be_words, rk, nr, layout="dense",
                          sbox="bp")


def _base_bit_masks(ctr_be_words: jnp.ndarray) -> jnp.ndarray:
    """(4,) u32 BE counter words -> (128, 1) full-lane masks, row q = bit q
    of the 128-bit value (q = 0 least significant, i.e. word 3 bit 0)."""
    q = jnp.arange(128, dtype=jnp.uint32)
    word = ctr_be_words.astype(jnp.uint32)[3 - (q // 32)]
    bits = (word >> (q % 32)) & jnp.uint32(1)
    return (jnp.uint32(0) - bits).reshape(128, 1)


def ctr_crypt_words_gen(words: jnp.ndarray, ctr_be_words: jnp.ndarray,
                        rk: jnp.ndarray, nr: int) -> jnp.ndarray:
    """Fused CTR with in-kernel counter synthesis (counter for block i =
    base + i, 128-bit big-endian semantics per aes-modes/aes.c:869-901).

    Registered as the "pallas" engine's CTR_FUSED entry: relative to
    ctr_crypt_words it deletes the counter materialisation, its SWAR
    transposition, and one full-buffer HBM input stream. Symmetric, so it
    serves both directions; sharded callers pre-offset ``ctr_be_words`` to
    their shard's first block (parallel/dist.py)."""
    return _ctr_gen_words(words, ctr_be_words, rk, nr, layout="planes")


# ---------------------------------------------------------------------------
# Multi-key scattered CTR: one kernel launch, K independent schedules.
#
# The serve batcher coalesces many tenants' requests into one rung-shaped
# dispatch; with one schedule per launch every distinct key fragments the
# batch (the pre-multikey coalescing restriction). This kernel carries K
# expanded schedules at once — the batched-kernel lever of "GPU Accelerated
# AES Algorithm" (PAPERS.md) applied across KEYS, not just blocks. Per-block
# key selection happens INSIDE the kernel by masked select, not by gather
# (Mosaic has no vector gather, and the bitsliced layout mixes blocks of
# different keys within one 32-block lane word anyway):
#
#   * kp_all:   (K, nr+1, 8, 16, 1) full-lane key-plane masks, one set per
#               schedule slot (zero schedules in unused slots) — tiny,
#               broadcast to every grid step.
#   * masks:    (K, W) u32 lane masks; bit t of masks[k, l] says block
#               32*l + t uses slot k. Built OUTSIDE the kernel from the
#               PUBLIC per-block key-index vector (slot_lane_masks) — no
#               secret-indexed addressing anywhere.
#   * kp_eff[r] = OR_k(kp_all[k, r] & masks[k]): the per-block round-key
#               planes, reconstructed with K AND/OR sweeps — ~K*(nr+1)*128
#               vector ops amortised over 32*tile blocks, small next to the
#               ~120-op/round S-box circuit itself.
#
# Data rides the dense (128, W) boundary and is never bit-transposed at
# all (XOR commutes with the transposition, as in the single-key fused
# kernels): only the counter tile unpacks to planes and only the
# synthesised keystream packs back.
# ---------------------------------------------------------------------------


def slot_lane_masks(key_slots: jnp.ndarray, k: int) -> jnp.ndarray:
    """(N,) u32 per-block key-slot indices (N % 32 == 0) -> (K, W) u32
    lane masks: bit t of [k, l] == (key_slots[32*l + t] == k). Pure
    compare/shift arithmetic on the PUBLIC slot vector — the kernel-safe
    replacement for a per-block schedule gather."""
    s = key_slots.astype(jnp.uint32).reshape(-1, 32)        # (W, 32)
    ks = jnp.arange(k, dtype=jnp.uint32)[:, None, None]
    eq = (s[None] == ks).astype(jnp.uint32)                 # (K, W, 32)
    shifts = jnp.arange(32, dtype=jnp.uint32)[None, None, :]
    return jnp.sum(eq << shifts, axis=-1, dtype=jnp.uint32)


def _ctr_scat_mk_kernel(kp_ref, mask_ref, ctr_ref, data_ref, out_ref, *,
                        nr: int, interpret: bool, sbox: str | None,
                        mc: str = "perm"):
    kp_all = kp_ref[...]          # (K, nr+1, 8, 16, 1)
    masks = mask_ref[...]         # (K, tile)
    kp_eff = None
    for k in range(kp_all.shape[0]):
        term = kp_all[k] & masks[k][None, None, None, :]
        kp_eff = term if kp_eff is None else kp_eff | term
    round_fn = functools.partial(bitslice.encrypt_round, sbox=sbox)
    ctr_planes = bitslice.planes_from_dense(ctr_ref[...])
    p = _run_rounds(ctr_planes ^ kp_eff[0], kp_eff, nr, round_fn,
                    interpret, mc)
    ks = round_fn(p, kp_eff[nr], True, perm=_perm_stack)
    out_ref[...] = data_ref[...] ^ bitslice.dense_from_planes(ks)


#: Tile cap for the multi-key kernel: kp_eff is a real (nr+1, 8, 16, tile)
#: VMEM tensor (~4 MiB at tile 512, nr 14), not a broadcast — capped so it
#: plus three data tiles stays well inside the ~16 MiB of VMEM under the
#: default tuned tile of 1024.
_MK_TILE_CAP = 512


@functools.partial(jax.jit, static_argnames=("nr", "tile", "sbox", "mc"))
def _ctr_scat_mk_pallas(ctr_d, data_d, kp_all, masks, *, nr, tile,
                        sbox=None, mc="perm"):
    w = ctr_d.shape[-1]
    k = kp_all.shape[0]
    interpret = _interpret()
    kernel = functools.partial(_ctr_scat_mk_kernel, nr=nr,
                               interpret=interpret, sbox=sbox, mc=mc)
    spec = pl.BlockSpec((128, tile), lambda i: (0, i))
    return pl.pallas_call(
        kernel,
        grid=(w // tile,),
        in_specs=[
            pl.BlockSpec((k, nr + 1, 8, 16, 1),
                         lambda i: (0, 0, 0, 0, 0)),
            pl.BlockSpec((k, tile), lambda i: (0, i)),
            spec,
            spec,
        ],
        out_specs=spec,
        out_shape=_out_struct(data_d),
        interpret=interpret,
    )(kp_all, masks, ctr_d, data_d)


def _ctr_scattered_multikey(words, ctr_le, rks, key_slots, nr, sbox=None):
    n = words.shape[0]
    if n == 0:
        return words
    _dispatch_seam("pallas multikey scattered-CTR dispatch (dense)")
    pad, tile = _lane_pad_and_tile(n, cap=_MK_TILE_CAP)
    if pad:
        zeros = jnp.zeros((pad, 4), words.dtype)
        words = jnp.concatenate([words, zeros], axis=0)
        ctr_le = jnp.concatenate([ctr_le, zeros], axis=0)
        key_slots = jnp.concatenate(
            [key_slots, jnp.zeros((pad,), key_slots.dtype)], axis=0)
    x = bitslice.dense_words(words)
    c = _match_vma(bitslice.dense_words(ctr_le), x)
    kp_all = _match_vma(
        jax.vmap(lambda r: bitslice.key_planes(r, nr))(rks), x)
    masks = _match_vma(slot_lane_masks(key_slots, rks.shape[0]), x)
    out = _ctr_scat_mk_pallas(c, x, kp_all, masks, nr=nr, tile=tile,
                              sbox=sbox, mc=MC_LOWERING)
    return bitslice.undense_words(out)[:n]


def ctr_scattered_multikey_dense(words: jnp.ndarray, ctr_le: jnp.ndarray,
                                 rks: jnp.ndarray, key_slots: jnp.ndarray,
                                 nr: int) -> jnp.ndarray:
    """Multi-key scattered CTR on the dense boundary (tower S-box).

    ``words``/``ctr_le``: (N, 4) u32; ``rks``: (K, 4*(nr+1)) stacked
    expanded schedules; ``key_slots``: (N,) u32 PUBLIC per-block slot
    indices. Registered as the MULTIKEY_CTR entry of every tower-S-box
    Pallas engine (models/aes.py): the dense layout is the one with no
    sublane-padding tax, so every engine NAME's multi-key seam routes
    here rather than duplicating the kernel per boundary layout."""
    return _ctr_scattered_multikey(words, ctr_le, rks, key_slots, nr)


def ctr_scattered_multikey_dense_bp(words: jnp.ndarray, ctr_le: jnp.ndarray,
                                    rks: jnp.ndarray, key_slots: jnp.ndarray,
                                    nr: int) -> jnp.ndarray:
    """ctr_scattered_multikey_dense with the Boyar–Peralta S-box pinned
    per-call — the multi-key entry of the *-bp engines."""
    return _ctr_scattered_multikey(words, ctr_le, rks, key_slots, nr,
                                   sbox="bp")
