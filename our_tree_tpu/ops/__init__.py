"""Compute cores: GF(2^8) math, tables, key schedules, and the three block
engines (T-table gather, bitsliced circuit, Pallas kernels)."""
