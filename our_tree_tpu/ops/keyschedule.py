"""AES key expansion (host-side, numpy).

The key schedule is tiny (<=60 words) and inherently sequential, so like the
reference — which expands keys on the host CPU even for the GPU backend
(ExpandKey at aes-gpu/Source/AES.cu:68-149, AES-NI variant at
aes-modes/aesni.c:38-77) — it runs on host in numpy and the resulting round
keys are staged to the device once per key.

Word layout matches the parity oracle (`aes_setkey_enc`, reference
aes-modes/aes.c:442-542): little-endian packed uint32 words, flat array of
4*(nr+1) words. The decryption schedule reverses the round order and applies
InvMixColumns to the interior round keys (`aes_setkey_dec`, aes.c:547-599),
enabling the "equivalent inverse cipher" so decryption has the same dataflow
shape as encryption.
"""

from __future__ import annotations

import numpy as np

from .tables import RCON, SBOX, inv_mix_columns_word

#: key bits -> number of rounds
ROUNDS = {128: 10, 192: 12, 256: 14}


def _sub_word(w: int) -> int:
    return int(
        SBOX[w & 0xFF]
        | (SBOX[(w >> 8) & 0xFF] << 8)
        | (SBOX[(w >> 16) & 0xFF] << 16)
        | (SBOX[(w >> 24) & 0xFF] << 24)
    )


def _rot_word(w: int) -> int:
    # Spec RotWord([a0,a1,a2,a3]) -> [a1,a2,a3,a0]; in LE packing that is a
    # 32-bit rotate right by 8.
    return ((w >> 8) | (w << 24)) & 0xFFFFFFFF


def expand_key_enc(key: bytes) -> tuple[int, np.ndarray]:
    """Expand an AES key for encryption.

    Args:
      key: 16, 24 or 32 raw key bytes.

    Returns:
      (nr, rk): the round count and a (4*(nr+1),) uint32 array of round-key
      words, little-endian packed.
    """
    keybits = len(key) * 8
    if keybits not in ROUNDS:
        raise ValueError(f"AES key must be 128/192/256 bits, got {keybits}")
    nr = ROUNDS[keybits]
    nk = len(key) // 4
    nwords = 4 * (nr + 1)

    w = [0] * nwords
    kb = [int(x) for x in key]
    for i in range(nk):
        w[i] = kb[4 * i] | (kb[4 * i + 1] << 8) | (kb[4 * i + 2] << 16) | (kb[4 * i + 3] << 24)

    for i in range(nk, nwords):
        t = w[i - 1]
        if i % nk == 0:
            t = _sub_word(_rot_word(t)) ^ int(RCON[i // nk - 1])
        elif nk == 8 and i % nk == 4:
            t = _sub_word(t)
        w[i] = w[i - nk] ^ t

    return nr, np.array(w, dtype=np.uint32)


def dec_schedule_from_enc(nr: int, enc: np.ndarray) -> np.ndarray:
    """The decrypt schedule as a pure function of the ENCRYPT schedule:
    reversed round order with InvMixColumns on the interior round keys
    (`aes_setkey_dec`, aes.c:547-599). Split out so holders of an
    expanded encrypt schedule (the serve keycache's stacked view) can
    derive the decrypt twin without re-touching key bytes."""
    dec = np.zeros_like(enc)
    # Round 0 of decryption = last round key of encryption, untransformed.
    dec[0:4] = enc[4 * nr : 4 * nr + 4]
    # Interior rounds: reversed order with InvMixColumns applied.
    for r in range(1, nr):
        src = enc[4 * (nr - r) : 4 * (nr - r) + 4]
        dec[4 * r : 4 * r + 4] = inv_mix_columns_word(src)
    # Final: the original first round key.
    dec[4 * nr : 4 * nr + 4] = enc[0:4]
    return dec


def expand_key_dec(key: bytes) -> tuple[int, np.ndarray]:
    """Expand an AES key for decryption (equivalent inverse cipher schedule)."""
    nr, enc = expand_key_enc(key)
    return nr, dec_schedule_from_enc(nr, enc)


# ---------------------------------------------------------------------------
# On-device expansion. The host numpy path above is the default (like the
# reference, which expands keys on the host even for the GPU backend); this
# scan exists for workloads that rekey on device — e.g. per-iteration rekey
# sweeps — and to keep the whole pipeline traceable under jit.
# ---------------------------------------------------------------------------


def _device_schedule_consts(keybits: int):
    """Static per-step wiring for the expansion scan (host, cached)."""
    import numpy as _np

    nr = ROUNDS[keybits]
    nk = keybits // 32
    nwords = 4 * (nr + 1)
    steps = nwords - nk
    is_rot = _np.zeros(steps, dtype=_np.uint32)
    is_sub = _np.zeros(steps, dtype=_np.uint32)
    rcon = _np.zeros(steps, dtype=_np.uint32)
    for s in range(steps):
        i = nk + s
        if i % nk == 0:
            is_rot[s] = 1
            rcon[s] = RCON[i // nk - 1]
        elif nk == 8 and i % nk == 4:
            is_sub[s] = 1
    return nr, nk, is_rot, is_sub, rcon


def expand_key_enc_device(key_words, keybits: int):
    """jit-traceable key expansion: (keybits/32,) u32 LE words -> (nr, rk).

    Same recurrence as `expand_key_enc`, expressed as a `lax.scan` whose
    carry is the last nk words (the whole sequential dependency).
    """
    import jax
    import jax.numpy as jnp

    nr, nk, is_rot, is_sub, rcon = _device_schedule_consts(keybits)
    sbox = jnp.asarray(SBOX.astype(np.uint32))

    def sub_word(w):
        return (
            sbox[w & 0xFF]
            | (sbox[(w >> 8) & 0xFF] << 8)
            | (sbox[(w >> 16) & 0xFF] << 16)
            | (sbox[w >> 24] << 24)
        )

    def step(carry, x):
        rot_f, sub_f, rc = x
        t = carry[-1]
        rotated = (t >> 8) | (t << 24)
        t = jnp.where(
            rot_f, sub_word(rotated) ^ rc, jnp.where(sub_f, sub_word(t), t)
        )
        new = carry[0] ^ t
        return jnp.concatenate([carry[1:], new[None]]), new

    xs = (jnp.asarray(is_rot), jnp.asarray(is_sub), jnp.asarray(rcon))
    carry0 = jnp.asarray(key_words, dtype=jnp.uint32)
    _, tail = jax.lax.scan(step, carry0, xs)
    return nr, jnp.concatenate([carry0, tail])


def expand_key_dec_device(key_words, keybits: int):
    """Device decryption schedule: reverse rounds + InvMixColumns interior."""
    import jax.numpy as jnp

    from . import gf as _gf

    nr, enc = expand_key_enc_device(key_words, keybits)
    m9, m11, m13, m14 = (
        jnp.asarray(_gf.gmul_table(c)) for c in (9, 11, 13, 14)
    )

    def inv_mix(w):
        b0, b1, b2, b3 = w & 0xFF, (w >> 8) & 0xFF, (w >> 16) & 0xFF, w >> 24
        return (
            (m14[b0] ^ m11[b1] ^ m13[b2] ^ m9[b3])
            | ((m9[b0] ^ m14[b1] ^ m11[b2] ^ m13[b3]) << 8)
            | ((m13[b0] ^ m9[b1] ^ m14[b2] ^ m11[b3]) << 16)
            | ((m11[b0] ^ m13[b1] ^ m9[b2] ^ m14[b3]) << 24)
        )

    rounds = enc.reshape(nr + 1, 4)[::-1]  # reversed round order
    interior = inv_mix(rounds[1:nr])
    dec = jnp.concatenate([rounds[:1], interior, rounds[nr:]], axis=0)
    return nr, dec.reshape(-1)
