"""Bitsliced AES — the TPU-native throughput engine.

The byte-indexed T-table formulation (ops/block.py, mirroring the oracle's
`AES_FROUND`, reference aes-modes/aes.c:601-645) needs 16 table gathers per
round per block. The VPU has no cheap 256-way gather (SURVEY.md §7 hard part
#1), so this engine removes tables entirely: AES is computed as a boolean
circuit over *bit-planes* — option (c) from the survey, the most
TPU-idiomatic formulation, all XOR/AND/OR on uint32 lanes with zero memory
indirection. XLA fuses the whole round chain into elementwise VPU code.

Data layout
-----------
A batch of N blocks (padded to a multiple of 32) becomes a `(8, 16, W)`
uint32 array, W = N/32: ``planes[bit, byte_pos, w]`` holds, in its 32 lanes'
bit t, bit `bit` of state byte `byte_pos` of block ``32*w + t``. Byte order
within a block follows the oracle's little-endian packing
(`GET_ULONG_LE`, aes-modes/aes.c:43-49): byte_pos i lives in word i//4,
lane byte i%4, and maps to AES state row i%4, column i//4 (FIPS-197 §3.4).

SubBytes without a table
------------------------
S(x) = Aff(x^254) over GF(2^8). Inversion uses the Itoh-Tsujii-style
addition chain 254 = 2 + 12 + 240  (x2=x², x3=x²·x, x12=x3⁴, x15=x12·x3,
x240=x15¹⁶, x252=x240·x12, x254=x252·x2): 4 bitsliced multiplies — squaring
is *linear* in characteristic 2, so all squarings are free XOR networks.
Every linear layer (squaring, the affine map and its inverse, ×2 for
MixColumns, ×4 for the InvMixColumns pre-transform — the inverse mix
routes through the forward one, see inv_mixcolumns_planes — the tower
field's nibble maps, modular reduction) is a GF(2) matrix **derived
numerically at import time** from the field arithmetic in ops/gf.py — no
transcribed circuit constants to get subtly wrong; tests/test_bitslice.py
pins the circuits exhaustively against the S-box/field tables.

The round structure and key-schedule convention (decrypt uses the
InvMixColumns-folded schedule, so rounds run InvShiftRows → InvSubBytes →
InvMixColumns → AddRoundKey) match the T-table core exactly — both engines
are drop-in `(words, rk, nr) -> words` cores behind `models.aes.CORES`.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from . import gf, tables

#: Rounds inlined per scan step in the XLA path. >1 halves the scan-carry
#: HBM round-trips at the cost of a larger compiled body; tune on hardware
#: via env without a code change (the Pallas engine keeps all rounds in
#: VMEM and doesn't use this). DEFAULT_UNROLL exists so jax-free parents
#: (scripts/tune_tpu.py) can be pinned against it by tests rather than
#: mirroring a literal (same pattern as pallas_aes.DEFAULT_TILE).
DEFAULT_UNROLL = 1
try:
    ROUND_UNROLL = int(os.environ.get("OT_BITSLICE_UNROLL", DEFAULT_UNROLL))
except ValueError as e:
    raise ValueError(f"OT_BITSLICE_UNROLL must be an integer: {e}") from None
if ROUND_UNROLL < 1:
    raise ValueError(
        f"OT_BITSLICE_UNROLL must be a positive integer, got {ROUND_UNROLL}"
    )

# ---------------------------------------------------------------------------
# GF(2) linear-map derivation (numpy, import time).
# ---------------------------------------------------------------------------


def _linmat(f, n: int = 8) -> np.ndarray:
    """n x n GF(2) matrix of a linear function on n-bit values:
    column j = f(1<<j). n=8 for byte maps, n=4 for the tower's nibble maps."""
    m = np.zeros((n, n), dtype=np.uint8)
    for j in range(n):
        v = f(1 << j)
        for i in range(n):
            m[i, j] = (v >> i) & 1
    return m


def _gf2_inv(mat: np.ndarray) -> np.ndarray:
    """Invert a GF(2) matrix by Gauss-Jordan elimination."""
    n = mat.shape[0]
    a = np.concatenate([mat.copy(), np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        piv = next(r for r in range(col, n) if a[r, col])
        a[[col, piv]] = a[[piv, col]]
        for r in range(n):
            if r != col and a[r, col]:
                a[r] ^= a[col]
    return a[:, n:]


#: Squaring — linear because (a + b)² = a² + b² in characteristic 2.
MAT_SQ = _linmat(lambda x: gf.gmul(x, x))

#: The linear part L of the S-box affine layer: S(x) = L(x^-1) ^ 0x63.
#: Derived from the S-box table itself: L(y) = S(y^-1) ^ S(0).
MAT_AFF = _linmat(lambda y: int(tables.SBOX[gf.ginv(y)]) ^ 0x63)
MAT_AFF_INV = _gf2_inv(MAT_AFF)
AFF_CONST = 0x63

#: Constant multipliers: ×2 for MixColumns, ×4 for the InvMixColumns
#: pre-transform (inv_mixcolumns_planes routes through the forward mix).
MAT_MUL = {c: _linmat(lambda x, c=c: gf.gmul(c, x)) for c in (2, 4)}

#: Modular reduction of a degree-14 product: REDUCE[k] = x^k mod POLY.
REDUCE = np.array([gf.gpow(2, k) for k in range(15)], dtype=np.uint16)

#: ShiftRows as a static permutation of the 16 byte positions.
#: State byte i = row i%4, col i//4; row r rotates left by r (FIPS-197 §5.1.2)
#: so new[4c+r] = old[4*((c+r)%4) + r]; inverse has (c-r).
SR_PERM = np.array([4 * ((i // 4 + i % 4) % 4) + i % 4 for i in range(16)])
ISR_PERM = np.array([4 * ((i // 4 - i % 4) % 4) + i % 4 for i in range(16)])

#: MixColumns' row rotations as 16-byte-position permutations: ROT_PERM[k][i]
#: = the byte position holding a_(r+k) of byte i's column, i.e. 4c + (r+k)%4.
#: Lets a kernel express the column mix with the same leading-axis
#: permutation primitive as ShiftRows — no reshape/roll inside Pallas.
ROT_PERM = [np.array([4 * (i // 4) + (i % 4 + k) % 4 for i in range(16)])
            for k in range(4)]


# ---------------------------------------------------------------------------
# Composite-field ("tower") S-box derivation. The straightforward inversion
# x^254 costs 4 full GF(2^8) bitsliced multiplies (~64 ANDs + ~70 XORs each);
# re-expressing GF(2^8) as GF(2^4)[x]/(x^2 + x + λ) turns inversion into a
# handful of 4-bit field ops — (ax+b)^-1 = aΔ^-1·x + (a+b)Δ^-1 with
# Δ = λa² + ab + b² — roughly a third of the vector-op count. This is the
# hardware-S-box construction (Satoh/Canright lineage); everything below —
# λ, the field isomorphism, every 4-bit linear map — is searched/derived
# numerically from the field arithmetic at import time and pinned by the
# exhaustive circuit tests, so no transcribed constants can be subtly wrong.
# ---------------------------------------------------------------------------

GF16_POLY = 0b10011  # w^4 + w + 1, irreducible over GF(2)


def _gf16_mul(a: int, b: int) -> int:
    r = 0
    while b:
        if b & 1:
            r ^= a
        b >>= 1
        a <<= 1
        if a & 0x10:
            a ^= GF16_POLY
    return r & 0xF


def _pick_lambda() -> int:
    """Smallest λ making x^2 + x + λ irreducible over GF(2^4) (no root)."""
    for lam in range(1, 16):
        if all(_gf16_mul(r, r) ^ r ^ lam for r in range(16)):
            return lam
    raise AssertionError("no irreducible x^2+x+λ over GF(2^4)")


TOWER_LAMBDA = _pick_lambda()


def _tower_mul(u: int, v: int) -> int:
    """Multiply in GF(2^4)[x]/(x^2+x+λ); byte = (a<<4)|b for a·x+b."""
    a, b, c, d = u >> 4, u & 0xF, v >> 4, v & 0xF
    ac = _gf16_mul(a, c)
    hi = _gf16_mul(a, d) ^ _gf16_mul(b, c) ^ ac          # x^2 -> +x
    lo = _gf16_mul(b, d) ^ _gf16_mul(ac, TOWER_LAMBDA)   # x^2 -> +λ
    return (hi << 4) | lo


def _find_tower_iso() -> np.ndarray:
    """8x8 GF(2) matrix φ with φ(uv) = φ(u)φ(v) into the tower field.

    Built from discrete logs: g = 0x03 generates the AES field; for each
    tower element h of order 255, the candidate φ(g^k) = h^k is linear iff
    the matrix assembled from φ on the bit basis reproduces φ everywhere.
    """
    log = {}
    v = 1
    for k in range(255):
        log[v] = k
        v = gf.gmul(v, 0x03)
    for h in range(2, 256):
        powers = [1]
        for _ in range(254):
            powers.append(_tower_mul(powers[-1], h))
        if len(set(powers)) != 255:
            continue  # not a generator
        phi = [0] * 256
        for val, k in log.items():
            phi[val] = powers[k]
        m = np.zeros((8, 8), dtype=np.uint8)
        for j in range(8):
            img = phi[1 << j]
            for i in range(8):
                m[i, j] = (img >> i) & 1
        ok = True
        for x in range(256):
            bits = np.array([(x >> j) & 1 for j in range(8)], dtype=np.uint8)
            img_bits = (m @ bits) % 2
            img = int(sum(int(img_bits[i]) << i for i in range(8)))
            if img != phi[x]:
                ok = False
                break
        if ok:
            return m
    raise AssertionError("no field isomorphism found")


TOWER_ISO = _find_tower_iso()
TOWER_ISO_INV = _gf2_inv(TOWER_ISO)

#: Merged boundary maps: forward S-box = Aff∘inv_tower∘φ (+0x63 after);
#: inverse S-box = φ⁻¹∘inv_tower∘φ∘Aff⁻¹ (0x63 xored before).
M_SBOX_IN = TOWER_ISO
M_SBOX_OUT = (MAT_AFF @ TOWER_ISO_INV) % 2
M_ISBOX_IN = (TOWER_ISO @ MAT_AFF_INV) % 2
M_ISBOX_OUT = TOWER_ISO_INV


MAT_SQ4 = _linmat(lambda x: _gf16_mul(x, x), 4)
MAT_LAMSQ4 = _linmat(lambda x: _gf16_mul(TOWER_LAMBDA, _gf16_mul(x, x)), 4)

# ---------------------------------------------------------------------------
# Second tower level: GF(2^4) = GF(2^2)[u]/(u^2 + u + Λ), GF(2^2) =
# GF(2)[w]/(w^2 + w + 1). Purpose: the 4-bit inverse Δ^-1 inside the S-box.
# The flat form costs Δ^14 = two GF(2^4) multiplies + squarings; in the
# sub-tower, (a·u + b)^-1 = a·δ^-1·u + (a+b)·δ^-1 with δ = Λa² + ab + b²
# ∈ GF(2^2), where δ^-1 = δ² is LINEAR (x³ = 1 for x ≠ 0 in GF(4)) — the
# inversion bottoms out in free squarings (Satoh/Canright, one level down).
# The basis isomorphism ψ: GF(16)[w-basis] -> pair basis is derived like
# TOWER_ISO and costs nothing at runtime: it is folded into the multiply
# reduction matrices on entry (ψ∘reduce) and into a mixed-basis bilinear
# multiply on exit (see _mixed_mul_reduction), so no standalone basis
# conversion ops exist in the circuit.
# ---------------------------------------------------------------------------


def _gf4_mul(a: int, b: int) -> int:
    """GF(2^2) multiply, poly w^2 + w + 1."""
    r = 0
    while b:
        if b & 1:
            r ^= a
        b >>= 1
        a <<= 1
        if a & 4:
            a ^= 0b111
    return r & 3


def _pick_lambda4() -> int:
    """Λ ∈ GF(2^2) making u^2 + u + Λ irreducible over GF(2^2)."""
    for lam in range(1, 4):
        if all(_gf4_mul(r, r) ^ r ^ lam for r in range(4)):
            return lam
    raise AssertionError("no irreducible u^2+u+Λ over GF(2^2)")


SUB_LAMBDA = _pick_lambda4()


def _pair_mul(u: int, v: int) -> int:
    """Multiply in GF(2^2)[u]/(u^2+u+Λ); nibble = (a<<2)|b for a·u+b."""
    a, b, c, d = u >> 2, u & 3, v >> 2, v & 3
    ac = _gf4_mul(a, c)
    hi = _gf4_mul(a, d) ^ _gf4_mul(b, c) ^ ac
    lo = _gf4_mul(b, d) ^ _gf4_mul(ac, SUB_LAMBDA)
    return (hi << 2) | lo


def _find_sub_iso() -> np.ndarray:
    """4x4 GF(2) matrix ψ with ψ(uv) = ψ(u)ψ(v), GF(16) w-basis -> pair."""
    gen = next(g for g in range(2, 16)
               if len({functools.reduce(lambda x, _: _gf16_mul(x, g),
                                        range(k), 1) for k in range(15)}) == 15)
    log = {}
    v = 1
    for k in range(15):
        log[v] = k
        v = _gf16_mul(v, gen)
    for h in range(2, 16):
        powers = [1]
        for _ in range(14):
            powers.append(_pair_mul(powers[-1], h))
        if len(set(powers)) != 15:
            continue
        psi = [0] * 16
        for val, k in log.items():
            psi[val] = powers[k]
        m = np.zeros((4, 4), dtype=np.uint8)
        for j in range(4):
            for i in range(4):
                m[i, j] = (psi[1 << j] >> i) & 1
        if all(
            int(sum(int(x) << i for i, x in enumerate(
                (m @ [(x >> j) & 1 for j in range(4)]) % 2))) == psi[x]
            for x in range(16)
        ):
            return m
    raise AssertionError("no GF(16) sub-tower isomorphism found")


SUB_ISO = _find_sub_iso()
SUB_ISO_INV = _gf2_inv(SUB_ISO)

#: δ^-1 = δ² and the Λ'·x² map of the pair-basis inversion, as GF(2) maps
#: over the 2-bit planes; MAT_DELTA4 merges the two δ-terms over [hi; lo]
#: (δ_lin = Λ'hi² + lo²) for one CSE-factored network.
MAT_SQ2 = _linmat(lambda x: _gf4_mul(x, x), 2)
MAT_LAMSQ2 = _linmat(lambda x: _gf4_mul(SUB_LAMBDA, _gf4_mul(x, x)), 2)
MAT_DELTA4 = np.concatenate([MAT_LAMSQ2, MAT_SQ2], axis=1)


def _bilinear_reduction(out_map) -> np.ndarray:
    """(4, 16) GF(2) matrix R with out_k = XOR_{i,j: R[k, 4i+j]} a_i & b_j
    for the GF(16) product under ``out_map``: R[k, 4i+j] = bit k of
    out_map(e_i · e_j). Lets any post-multiply linear map (ψ, ψ⁻¹, identity)
    fold into the multiply for free."""
    m = np.zeros((4, 16), dtype=np.uint8)
    for i in range(4):
        for j in range(4):
            prod = out_map(i, j)
            for k in range(4):
                m[k, 4 * i + j] = (prod >> k) & 1
    return m


def _psi_apply(x: int) -> int:
    return int(sum(int(v) << i for i, v in enumerate(
        (SUB_ISO @ [(x >> j) & 1 for j in range(4)]) % 2)))


def _psi_inv_apply(x: int) -> int:
    return int(sum(int(v) << i for i, v in enumerate(
        (SUB_ISO_INV @ [(x >> j) & 1 for j in range(4)]) % 2)))


#: w-basis × w-basis -> pair-basis product (ψ folded into the reduction).
_MUL_W_W_TO_PAIR = _bilinear_reduction(
    lambda i, j: _psi_apply(_gf16_mul(1 << i, 1 << j)))
#: w-basis × pair-basis -> w-basis product (ψ⁻¹ folded in).
_MUL_W_PAIR_TO_W = _bilinear_reduction(
    lambda i, j: _gf16_mul(1 << i, _psi_inv_apply(1 << j)))

#: ψ∘(λ·x²) and ψ∘x² — the Δ-term maps emitting directly into pair basis,
#: concatenated into ONE map over the stacked [a; b] planes so the CSE
#: factoring sees (and the XOR network merges) both terms at once:
#: Δ_lin = [ψλ(·)² | ψ(·)²] @ [a; b] = ψ(λa² + b²).
MAT_LAMSQ4_PAIR = (SUB_ISO @ MAT_LAMSQ4) % 2
MAT_SQ4_PAIR = (SUB_ISO @ MAT_SQ4) % 2
MAT_DELTA8 = np.concatenate([MAT_LAMSQ4_PAIR, MAT_SQ4_PAIR], axis=1)

#: x^k mod (w^4+w+1) for the 4-bit schoolbook product's degree-6 terms.
GF16_REDUCE = []
for _k in range(7):
    _v = 1
    for _ in range(_k):
        _v = _gf16_mul(_v, 2)
    GF16_REDUCE.append(_v)
GF16_REDUCE = np.array(GF16_REDUCE, dtype=np.uint8)


# ---------------------------------------------------------------------------
# Bit-plane circuit primitives. A "byte" is a list of 8 same-shaped uint32
# arrays (LSB first); every op below is elementwise over those arrays, so the
# same code runs inside jit, scan bodies, and Pallas kernels.
# ---------------------------------------------------------------------------


_CSE_CACHE: dict = {}


def _xor_cse_schedule(mat: np.ndarray):
    """Greedy XOR common-subexpression factoring of a GF(2) matrix (Paar).

    Repeatedly extracts the input pair that co-occurs in the most output
    rows into a fresh intermediate variable. Machine-derived like the
    matrices themselves; cuts the XOR count of a dense 8×8 map roughly in
    half versus emitting each row as an independent chain (XLA/Mosaic CSE
    only merges syntactically identical trees, which left-associated
    per-row chains almost never are). Deterministic tie-breaking keeps the
    schedule stable across runs.

    Returns (pair_ops, out_rows): pair_ops = [(j, k), ...] — each defines
    new variable len(inputs)+idx = v_j ^ v_k; out_rows[i] = sorted variable
    indices whose XOR is output row i.
    """
    rows, cols = mat.shape
    terms = [{j for j in range(cols) if mat[i, j]} for i in range(rows)]
    nvars = cols
    pair_ops = []
    while True:
        counts: dict = {}
        for r in terms:
            rs = sorted(r)
            for x in range(len(rs)):
                for y in range(x + 1, len(rs)):
                    pr = (rs[x], rs[y])
                    counts[pr] = counts.get(pr, 0) + 1
        if not counts:
            break
        (j, k), c = max(counts.items(), key=lambda kv: (kv[1], kv[0]))
        if c < 2:
            break
        new = nvars
        nvars += 1
        pair_ops.append((j, k))
        for r in terms:
            if j in r and k in r:
                r.discard(j)
                r.discard(k)
                r.add(new)
    return pair_ops, [sorted(r) for r in terms]


def apply_linear(mat: np.ndarray, p: list) -> list:
    """y_i = XOR of p_j over j with mat[i, j] == 1 (static wiring, unrolled).

    Works for any GF(2) matrix shape — 8×8 byte maps and the tower field's
    4×4 nibble maps alike. The XOR network is emitted from a greedily
    CSE-factored schedule (see _xor_cse_schedule), cached per matrix."""
    rows, cols = mat.shape
    key = (rows, cols, mat.tobytes())
    sched = _CSE_CACHE.get(key)
    if sched is None:
        sched = _CSE_CACHE[key] = _xor_cse_schedule(mat)
    pair_ops, out_rows = sched
    v = list(p)
    for j, k in pair_ops:
        v.append(v[j] ^ v[k])
    out = []
    for r in out_rows:
        acc = None
        for j in r:
            acc = v[j] if acc is None else acc ^ v[j]
        out.append(acc if acc is not None else jnp.zeros_like(p[0]))
    return out


def xor_const(p: list, c: int) -> list:
    """XOR a constant byte into every lane: flip planes where c has a 1 bit."""
    return [x ^ jnp.uint32(0xFFFFFFFF) if (c >> i) & 1 else x for i, x in enumerate(p)]


#: Reduction of schoolbook partials as GF(2) matrices (degree-k term -> output
#: bits), so the XOR trees go through the CSE-factored apply_linear path.
_RED8 = np.array([[(int(REDUCE[k]) >> i) & 1 for k in range(15)]
                  for i in range(8)], dtype=np.uint8)


def gf_mul_planes(a: list, b: list) -> list:
    """Bitsliced GF(2^8) multiply: schoolbook partials + derived reduction."""
    c = [None] * 15
    for i in range(8):
        for j in range(8):
            t = a[i] & b[j]
            k = i + j
            c[k] = t if c[k] is None else c[k] ^ t
    return apply_linear(_RED8, c)


def gf_inv_planes(x: list) -> list:
    """x^254 (= x^-1, with 0 -> 0) via the 4-multiply addition chain."""
    sq = functools.partial(apply_linear, MAT_SQ)
    x2 = sq(x)
    x3 = gf_mul_planes(x2, x)
    x12 = sq(sq(x3))
    x15 = gf_mul_planes(x12, x3)
    x240 = sq(sq(sq(sq(x15))))
    x252 = gf_mul_planes(x240, x12)
    return gf_mul_planes(x252, x2)


_RED4 = np.array([[(int(GF16_REDUCE[k]) >> i) & 1 for k in range(7)]
                  for i in range(4)], dtype=np.uint8)


def gf16_mul_planes(a: list, b: list) -> list:
    """Bitsliced GF(2^4) multiply: 16 ANDs + the derived 7-term reduction."""
    c = [None] * 7
    for i in range(4):
        for j in range(4):
            t = a[i] & b[j]
            k = i + j
            c[k] = t if c[k] is None else c[k] ^ t
    return apply_linear(_RED4, c)


#: GF(2^2) product as a bilinear reduction: c[2i+j] = a_i & b_j, out rows
#: from the field table (w² = w + 1).
_MUL_GF4 = np.array(
    [[( _gf4_mul(1 << i, 1 << j) >> k) & 1 for i in range(2) for j in range(2)]
     for k in range(2)], dtype=np.uint8)


def gf4_mul_planes(a: list, b: list) -> list:
    """Bitsliced GF(2^2) multiply: 4 ANDs + the derived reduction."""
    c = [a[i] & b[j] for i in range(2) for j in range(2)]
    return apply_linear(_MUL_GF4, c)


def _mul16_planes(a: list, b: list, red: np.ndarray) -> list:
    """GF(16) bitsliced multiply through a folded bilinear reduction matrix
    (16 ANDs + one CSE-factored XOR network); ``red`` selects the operand /
    output bases (see _bilinear_reduction)."""
    c = [a[i] & b[j] for i in range(4) for j in range(4)]
    return apply_linear(red, c)


def tower_inv_planes(p: list) -> list:
    """GF(2^8) inversion in the tower basis: p = [b0..b3, a0..a3] for a·x+b.

    (a·x + b)^-1 = aΔ^-1·x + (a+b)Δ^-1 with Δ = λa² + ab + b². The 4-bit
    inverse Δ^-1 descends a second tower level (GF(2^2) pairs, basis change
    ψ folded into the surrounding multiplies): δ = Λ'h² + hl + l² over
    GF(2^2), δ^-1 = δ² — a linear map, so the recursion bottoms out in
    free squarings instead of the two extra GF(16) multiplies Δ^14 costs.
    Net: 3 GF(16) multiplies + 3 GF(4) multiplies for the whole inversion.
    """
    b, a = p[:4], p[4:]
    ab = _mul16_planes(a, b, _MUL_W_W_TO_PAIR)            # pair basis out
    dlin = apply_linear(MAT_DELTA8, a + b)                 # ψ(λa² + b²)
    delta = [dlin[i] ^ ab[i] for i in range(4)]            # ψ(Δ)
    lo, hi = delta[:2], delta[2:]                          # Δ = hi·u + lo
    hl = gf4_mul_planes(hi, lo)
    dlin2 = apply_linear(MAT_DELTA4, hi + lo)              # Λ'hi² + lo²
    d = [dlin2[i] ^ hl[i] for i in range(2)]               # δ ∈ GF(2^2)
    dinv = apply_linear(MAT_SQ2, d)                        # δ^-1 = δ²
    hi_out = gf4_mul_planes(hi, dinv)
    lo_out = gf4_mul_planes([hi[i] ^ lo[i] for i in range(2)], dinv)
    dinv4 = lo_out + hi_out                                # ψ(Δ^-1)
    a_out = _mul16_planes(a, dinv4, _MUL_W_PAIR_TO_W)      # ψ⁻¹ folded in
    b_out = _mul16_planes([a[i] ^ b[i] for i in range(4)], dinv4,
                          _MUL_W_PAIR_TO_W)
    return b_out + a_out


#: S-box implementation: "tower" (composite field, default — derived
#: construction, fewest ops among the derived forms), "bp" (the fixed
#: Boyar–Peralta 115-gate circuit — fewer ops still, forward direction;
#: see _bp_sbox_core), or "chain" (the x^254 addition chain, kept as an
#: independent formulation for cross-checking and benchmarking).
#: OT_SBOX overrides; all three are exhaustively pinned against
#: tables.SBOX by tests/test_circuit_size.py + test_bitslice.py.
SBOX_IMPL = os.environ.get("OT_SBOX", "tower")
if SBOX_IMPL not in ("tower", "bp", "chain"):
    raise ValueError(
        f"OT_SBOX must be 'tower', 'bp' or 'chain', got {SBOX_IMPL!r}"
    )


def _bp_sbox_core(p: list) -> list:
    """Boyar–Peralta forward S-box, minus the final 0x63 complement.

    The 115-gate (32 AND + 83 XOR/XNOR) combinational AES S-box from
    Boyar & Peralta, "A new combinational logic minimization technique
    with applications to cryptology" (SEA 2010) — a public, fixed circuit:
    a 23-XOR top linear layer computing 22 shared signals, a 44-gate shared
    GF(2^4) inversion middle (30 XOR + 14 AND), 18 AND "output
    multipliers", and a 30-XOR bottom linear layer. Its four XNOR outputs
    are exactly the S-box affine
    constant 0x63, so this core emits the pure-XOR form and the caller
    applies the shared ``xor_const(…, AFF_CONST)`` — identical accounting
    to the other formulations.

    Wire convention: the circuit's U0/S0 are the byte's MSB; our plane
    lists are LSB-first, hence the reversed pick-up/return order.
    """
    u0, u1, u2, u3, u4, u5, u6, u7 = reversed(p)
    # Top linear layer.
    y14 = u3 ^ u5
    y13 = u0 ^ u6
    y9 = u0 ^ u3
    y8 = u0 ^ u5
    t0 = u1 ^ u2
    y1 = t0 ^ u7
    y4 = y1 ^ u3
    y12 = y13 ^ y14
    y2 = y1 ^ u0
    y5 = y1 ^ u6
    y3 = y5 ^ y8
    t1 = u4 ^ y12
    y15 = t1 ^ u5
    y20 = t1 ^ u1
    y6 = y15 ^ u7
    y10 = y15 ^ t0
    y11 = y20 ^ y9
    y7 = u7 ^ y11
    y17 = y10 ^ y11
    y19 = y10 ^ y8
    y16 = t0 ^ y11
    y21 = y13 ^ y16
    y18 = u0 ^ y16
    # Shared nonlinear middle (GF(2^4) inversion).
    t2 = y12 & y15
    t3 = y3 & y6
    t4 = t3 ^ t2
    t5 = y4 & u7
    t6 = t5 ^ t2
    t7 = y13 & y16
    t8 = y5 & y1
    t9 = t8 ^ t7
    t10 = y2 & y7
    t11 = t10 ^ t7
    t12 = y9 & y11
    t13 = y14 & y17
    t14 = t13 ^ t12
    t15 = y8 & y10
    t16 = t15 ^ t12
    t17 = t4 ^ t14
    t18 = t6 ^ t16
    t19 = t9 ^ t14
    t20 = t11 ^ t16
    t21 = t17 ^ y20
    t22 = t18 ^ y19
    t23 = t19 ^ y21
    t24 = t20 ^ y18
    t25 = t21 ^ t22
    t26 = t21 & t23
    t27 = t24 ^ t26
    t28 = t25 & t27
    t29 = t28 ^ t22
    t30 = t23 ^ t24
    t31 = t22 ^ t26
    t32 = t31 & t30
    t33 = t32 ^ t24
    t34 = t23 ^ t33
    t35 = t27 ^ t33
    t36 = t24 & t35
    t37 = t36 ^ t34
    t38 = t27 ^ t36
    t39 = t29 & t38
    t40 = t25 ^ t39
    t41 = t40 ^ t37
    t42 = t29 ^ t33
    t43 = t29 ^ t40
    t44 = t33 ^ t37
    t45 = t42 ^ t41
    # Output multipliers.
    z0 = t44 & y15
    z1 = t37 & y6
    z2 = t33 & u7
    z3 = t43 & y16
    z4 = t40 & y1
    z5 = t29 & y7
    z6 = t42 & y11
    z7 = t45 & y17
    z8 = t41 & y10
    z9 = t44 & y12
    z10 = t37 & y3
    z11 = t33 & y4
    z12 = t43 & y13
    z13 = t40 & y5
    z14 = t29 & y2
    z15 = t42 & y9
    z16 = t45 & y14
    z17 = t41 & y8
    # Bottom linear layer (XNORs dropped: folded into the 0x63 constant).
    t46 = z15 ^ z16
    t47 = z10 ^ z11
    t48 = z5 ^ z13
    t49 = z9 ^ z10
    t50 = z2 ^ z12
    t51 = z2 ^ z5
    t52 = z7 ^ z8
    t53 = z0 ^ z3
    t54 = z6 ^ z7
    t55 = z16 ^ z17
    t56 = z12 ^ t48
    t57 = t50 ^ t53
    t58 = z4 ^ t46
    t59 = z3 ^ t54
    t60 = t46 ^ t57
    t61 = z14 ^ t57
    t62 = t52 ^ t58
    t63 = t49 ^ t58
    t64 = z4 ^ t59
    t65 = t61 ^ t62
    t66 = z1 ^ t63
    s0 = t59 ^ t63
    s6 = t56 ^ t62
    s7 = t48 ^ t60
    t67 = t64 ^ t65
    s3 = t53 ^ t66
    s4 = t51 ^ t66
    s5 = t47 ^ t65
    s1 = t64 ^ s3
    s2 = t55 ^ t67
    return [s7, s6, s5, s4, s3, s2, s1, s0]


def sbox_planes(p: list, impl: str | None = None) -> list:
    """Forward S-box on 8 stacked bit planes.

    ``impl`` overrides the module-level OT_SBOX choice per call site —
    engines register formulation variants (models/aes.py "pallas-gt-bp")
    so a single probing run can A/B the circuits on hardware without
    re-importing the module under a different env.
    """
    impl = impl or SBOX_IMPL
    if impl not in ("tower", "bp", "chain"):
        # The module-level OT_SBOX value is validated at import; a typo'd
        # per-call override must not silently fall through to the generic
        # x^254 chain (~2.3x the ops) and skew a hardware A/B.
        raise ValueError(f"unknown S-box impl {impl!r}")
    if impl == "tower":
        t = tower_inv_planes(apply_linear(M_SBOX_IN, p))
        return xor_const(apply_linear(M_SBOX_OUT, t), AFF_CONST)
    if impl == "bp":
        return xor_const(_bp_sbox_core(p), AFF_CONST)
    return xor_const(apply_linear(MAT_AFF, gf_inv_planes(p)), AFF_CONST)


def inv_sbox_planes(p: list) -> list:
    if SBOX_IMPL in ("tower", "bp"):
        # Boyar–Peralta published no comparably small inverse circuit; the
        # decrypt direction keeps the tower formulation under OT_SBOX=bp
        # (the north-star path — CTR — only ever uses the forward S-box).
        t = apply_linear(M_ISBOX_IN, xor_const(list(p), AFF_CONST))
        return apply_linear(M_ISBOX_OUT, tower_inv_planes(t))
    return gf_inv_planes(apply_linear(MAT_AFF_INV, xor_const(list(p), AFF_CONST)))


def _cols(x: jnp.ndarray) -> jnp.ndarray:
    """(16, ...) byte axis -> (4 cols, 4 rows, ...)."""
    return x.reshape((4, 4) + x.shape[1:])


def _flat(x: jnp.ndarray) -> jnp.ndarray:
    return x.reshape((16,) + x.shape[2:])


def mixcolumns_planes(p: list, perm=None) -> list:
    """out_r = 2·a_r + 3·a_(r+1) + a_(r+2) + a_(r+3) = xt(a_r ^ a_(r+1))
    ^ (Σ_r a_r) ^ a_r, vectorised over the column axis.

    With ``perm=None`` the rotations use reshape+roll (the cheap XLA
    lowering); a kernel-safe ``perm(x, idx16)`` callable switches them to
    leading-axis permutations (ROT_PERM) so Pallas/Mosaic sees only slices.

    Rotation count is minimised via t = a ^ rot1(a): the four-rotation sum
    a ^ rot1(a) ^ rot2(a) ^ rot3(a) equals t ^ rot2(t), so one rot1 and one
    rot2 suffice (out = xt(t) ^ t ^ rot2(t) ^ a)."""
    if perm is not None:
        a = p
        t = [x ^ perm(x, ROT_PERM[1]) for x in p]
        xt = apply_linear(MAT_MUL[2], t)
        return [xt[i] ^ t[i] ^ perm(t[i], ROT_PERM[2]) ^ a[i]
                for i in range(8)]
    a = [_cols(x) for x in p]
    t = [x ^ jnp.roll(x, -1, axis=1) for x in a]
    xt = apply_linear(MAT_MUL[2], t)
    return [_flat(xt[i] ^ t[i] ^ jnp.roll(t[i], -2, axis=1) ^ a[i])
            for i in range(8)]


def inv_mixcolumns_planes(p: list, perm=None) -> list:
    """out_r = 14·a_r + 11·a_(r+1) + 13·a_(r+2) + 9·a_(r+3) (FIPS-197 §5.3.3).

    Computed as MixColumns of a cheap pre-transform rather than four dense
    coefficient matrices: with d_r = a_r ^ 4·(a_r ^ a_(r+2)),
    MC([2,3,1,1])(d) expands to exactly [14,11,13,9](a) — check the
    coefficient algebra: 2(5a_r+4a_(r+2)) + 3(5a_(r+1)+4a_(r+3)) +
    (5a_(r+2)+4a_r) + (5a_(r+3)+4a_(r+1)) = 14,11,13,9. One sparse ×4 map
    and one rotation replace four dense 8×8 GF(2) matrices."""
    if perm is not None:
        t = [x ^ perm(x, ROT_PERM[2]) for x in p]
        four = apply_linear(MAT_MUL[4], t)
        d = [p[i] ^ four[i] for i in range(8)]
        return mixcolumns_planes(d, perm=perm)
    a = [_cols(x) for x in p]
    t = [x ^ jnp.roll(x, -2, axis=1) for x in a]
    four = apply_linear(MAT_MUL[4], t)
    d = [_flat(a[i] ^ four[i]) for i in range(8)]
    return mixcolumns_planes(d)


# ---------------------------------------------------------------------------
# Plane <-> word transposition and round-key planes.
# ---------------------------------------------------------------------------


def _transpose32_lead(a: jnp.ndarray) -> jnp.ndarray:
    """Transpose the 32x32 bit matrix held in the LEADING axis (u32 rows).

    Log-time SWAR ladder (the classic masked-swap network): 5 stages of
    half-word exchanges instead of materialising 8x-larger per-bit tensors.
    LSB-first convention: out[i] bit t == in[t] bit i. Involution — applying
    it twice is the identity — so the same function packs and unpacks.

    The 32-axis is axis 0 and every reshape/slice/stack touches only
    leading axes, leaving the minor (sublane, lane) dims untouched. That
    makes it both the conservative Mosaic feature set for in-kernel use
    (cf. pallas_aes._perm_stack) AND the only HBM-sane XLA form: a ladder
    over a MINOR 32/4 axis materialises stage tensors whose 4-wide minor
    dim pads to the 128-lane tile — 32x the logical bytes per stage, which
    throttled conversions and OOMed 1 GiB buffers before to_planes was
    routed through the grouped layout.
    """
    j = 16
    m = jnp.uint32(0x0000FFFF)
    while j:
        sh = a.shape
        b = a.reshape((32 // (2 * j), 2, j) + sh[1:])
        lo, hi = b[:, 0], b[:, 1]
        t = (lo >> j ^ hi) & m
        a = jnp.stack([lo ^ (t << j), hi ^ t], axis=1).reshape(sh)
        j >>= 1
        m = m ^ (m << j)
    return a


def group_words(words: jnp.ndarray) -> jnp.ndarray:
    """(N, 4) u32 words, N % 32 == 0 -> (32, 4, W) grouped layout:
    [t, c, l] = word c of block 32*l + t.

    One pure relayout (no bit math). The grouped form puts the lane axis
    minor with the 32-block axis LEADING, so a Pallas kernel can run the
    SWAR bit transposition itself on (32, 4, tile) VMEM tiles
    (planes_from_grouped) instead of paying to/from_planes as separate
    XLA passes over HBM around the kernel.
    """
    n = words.shape[0]
    return words.reshape(n // 32, 32, 4).transpose(1, 2, 0)


def ungroup_words(g: jnp.ndarray) -> jnp.ndarray:
    """(32, 4, W) grouped layout -> (32*W, 4) u32 words (group_words⁻¹)."""
    w = g.shape[2]
    return g.transpose(2, 0, 1).reshape(32 * w, 4)


def planes_from_grouped(g: jnp.ndarray) -> jnp.ndarray:
    """(32, 4, T) grouped words -> (8, 16, T) bit planes, kernel-safe.

    Equivalent to to_planes on the same blocks (pinned by tests), but the
    ladder runs on the leading 32-axis and the byte/bit redistribution is
    a static stack of leading-axis slices — legal inside a Mosaic kernel.
    """
    tr = _transpose32_lead(g)  # [i, c, l]: bit t of tr[i,c] = bit i of
    #                            word c of block 32l + t
    return jnp.stack([
        jnp.concatenate(
            [tr[8 * (p % 4) + b, p // 4][None] for p in range(16)], axis=0)
        for b in range(8)
    ])


def grouped_from_planes(p: jnp.ndarray) -> jnp.ndarray:
    """(8, 16, T) bit planes -> (32, 4, T) grouped words (kernel-safe
    inverse of planes_from_grouped)."""
    tr = jnp.stack([
        jnp.concatenate(
            [p[i % 8, 4 * c + i // 8][None] for c in range(4)], axis=0)
        for i in range(32)
    ])
    return _transpose32_lead(tr)


def dense_words(words: jnp.ndarray) -> jnp.ndarray:
    """(N, 4) u32 words, N % 32 == 0 -> (128, W) DENSE grouped layout:
    row 4*t + c = word c of block 32*l + t (lane l).

    The grouped (32, 4, W) boundary form pays a 2x tax on TPU: its 4-wide
    second-minor (sublane) dim pads to 8 under tiled layouts, doubling both
    the HBM streams and the VMEM tile footprint, and halving the buffer
    ceiling (ops/pallas_aes.py layout notes). Merging the (32, 4) axes into
    one leading 128 gives a sublane dim of 128 — an exact multiple of the
    8-row tile — so the boundary array is DENSE: 128·W u32 = exactly the
    logical bytes. Pure relayout (no bit math), same information as
    group_words; transpose32_dense runs the SWAR ladder directly on this
    form inside a kernel.

    Implementation note (round-4 hardware OOM): the obvious composition
    reshape(W, 32, 4) -> transpose(1, 2, 0) -> reshape(128, W) materialises
    a (W, 32, 4) stage tensor whose 4-wide minor dim pads to the 128-lane
    tile — 32x the logical bytes (a 1000 MiB buffer asked for a 32 GiB
    allocation on the 16 GiB v5e: "Allocation would exceed memory ...
    shape = u32[2048000,32,4]{2,1,0:T(8,128)}", docs/hwlogs/corpus.log —
    the failure that broke both the 1 GiB headline step and the corpus
    sweep). Row 4t+c, lane l of the dense form is flat-stream element
    128*l + 4t + c, so the SAME mapping is one reshape to (W, 128) — dense
    under tiling in BOTH dims — and one transpose between two dense tiled
    layouts: no intermediate with a padded minor dim anywhere.
    """
    n = words.shape[0]
    return words.reshape(n // 32, 128).T


def undense_words(d: jnp.ndarray) -> jnp.ndarray:
    """(128, W) dense grouped layout -> (32*W, 4) u32 words
    (dense_words⁻¹). Same padded-intermediate avoidance as dense_words:
    transpose first (dense->dense), then reshape."""
    w = d.shape[1]
    return d.T.reshape(32 * w, 4)


def transpose32_dense(a: jnp.ndarray) -> jnp.ndarray:
    """The 32x32 bit-transpose ladder on the dense (128, T) form.

    Same masked-swap network as _transpose32_lead, with the block-index
    axis t STRIDED at 4 inside the leading 128-axis (row = 4t + c): stage j
    pairs rows 4t+c and 4(t+j)+c, i.e. contiguous 4j-row chunks, so each
    stage is a leading-axis reshape to (32/(2j), 2, 4j, T) + the same
    half-word exchange — no minor-dim reshapes, no rolls, the conservative
    Mosaic feature set. Involution, like the grouped ladder.
    """
    j = 16
    m = jnp.uint32(0x0000FFFF)
    while j:
        sh = a.shape
        b = a.reshape((32 // (2 * j), 2, 4 * j) + sh[1:])
        lo, hi = b[:, 0], b[:, 1]
        t = (lo >> j ^ hi) & m
        a = jnp.stack([lo ^ (t << j), hi ^ t], axis=1).reshape(sh)
        j >>= 1
        m = m ^ (m << j)
    return a


def planes_from_dense(d: jnp.ndarray) -> jnp.ndarray:
    """(128, T) dense grouped words -> (8, 16, T) bit planes, kernel-safe.

    Bit-identical to planes_from_grouped∘(reshape to (32, 4, T)) — pinned
    by tests/test_bitslice.py — but every intermediate keeps the lane axis
    minor with a leading dim that is a multiple of 8, so no tiling padding
    anywhere. Row bookkeeping: after the ladder, transposed row 4i+c holds
    (in bit t) bit i of word c of block 32l+t; plane[b][p] = bit b of state
    byte p = bit (8*(p%4)+b) of word p//4.
    """
    tr = transpose32_dense(d)
    return jnp.stack([
        jnp.concatenate(
            [tr[4 * (8 * (p % 4) + b) + p // 4][None] for p in range(16)],
            axis=0)
        for b in range(8)
    ])


def dense_from_planes(p: jnp.ndarray) -> jnp.ndarray:
    """(8, 16, T) bit planes -> (128, T) dense grouped words (kernel-safe
    inverse of planes_from_dense)."""
    tr = jnp.concatenate([
        p[(r // 4) % 8, 4 * (r % 4) + (r // 4) // 8][None]
        for r in range(128)
    ], axis=0)
    return transpose32_dense(tr)


def to_planes(words: jnp.ndarray) -> jnp.ndarray:
    """(N, 4) u32 LE words, N % 32 == 0  ->  (8, 16, N/32) u32 planes.

    Column c of a 32-block group is a 32x32 bit matrix: row t = word c of
    block t, whose bit 8a+b is bit b of state byte 4c+a. Transposing gives
    row 8a+b = plane(byte 4c+a, bit b) with lane t = block t.

    Routed through the grouped (32, 4, W) layout so the ladder's 32-axis is
    LEADING and the lane axis stays minor in every stage tensor — the
    direct (W, 32, 4) formulation's intermediates have a 4-wide minor dim
    that TPU tiled layouts pad to 128 lanes (32x HBM inflation: measured as
    the 1.65 GB/s pallas-engine ceiling, and a 32 GiB allocation — OOM —
    on a 1 GiB buffer).
    """
    return planes_from_grouped(group_words(words))


def from_planes(planes: jnp.ndarray) -> jnp.ndarray:
    """(8, 16, W) u32 planes -> (32*W, 4) u32 LE words (to_planes⁻¹)."""
    return ungroup_words(grouped_from_planes(planes))


def key_planes(rk: jnp.ndarray, nr: int) -> jnp.ndarray:
    """(4*(nr+1),) u32 round keys -> (nr+1, 8, 16, 1) full-lane bit masks."""
    w = rk.astype(jnp.uint32).reshape(nr + 1, 4)
    sh = (jnp.arange(4, dtype=jnp.uint32) * 8)[None, None, :]
    by = ((w[:, :, None] >> sh) & 0xFF).reshape(nr + 1, 16)
    bits = (by[:, None, :] >> jnp.arange(8, dtype=jnp.uint32)[None, :, None]) & 1
    return (bits * jnp.uint32(0xFFFFFFFF))[..., None]


def multikey_planes(rk_blocks: jnp.ndarray, nr: int) -> jnp.ndarray:
    """Per-BLOCK round keys -> (nr+1, 8, 16, W) genuine key bit planes.

    ``rk_blocks``: (N, 4*(nr+1)) u32, row i = block i's expanded schedule
    (N % 32 == 0). Where ``key_planes`` broadcasts ONE key as full-lane
    masks, here every block may carry a different key, so round r's key
    planes are real data planes: ``to_planes`` of the (N, 4) round-r words.
    The round circuit is key-oblivious (AddRoundKey is the only key
    contact, and XOR broadcasts identically over (16, 1) masks and
    (16, W) planes), which is what makes the multi-key batch a pure
    layout change rather than a new cipher formulation.
    """
    r = rk_blocks.astype(jnp.uint32).reshape(rk_blocks.shape[0], nr + 1, 4)
    return jnp.stack([to_planes(r[:, i, :]) for i in range(nr + 1)])


# ---------------------------------------------------------------------------
# Rounds. Shared by the XLA path (scan over rounds) and the Pallas kernel
# (unrolled/fori inside the tile body) — see ops/pallas_aes.py.
# ---------------------------------------------------------------------------


def _perm_take(x: jnp.ndarray, idx: np.ndarray) -> jnp.ndarray:
    """Static byte-position permutation. Advanced indexing lowers to one
    gather, which also acts as the fusion boundary that keeps XLA-CPU's
    emitter from re-expanding the S-box circuit per consumer (see
    decrypt_round); Pallas kernels substitute a stack-of-rows version
    because Mosaic has no gather (ops/pallas_aes.py)."""
    return x[idx]


def encrypt_round(planes: jnp.ndarray, kp: jnp.ndarray, last: bool,
                  perm=_perm_take, mc="auto", sbox: str | None = None) -> jnp.ndarray:
    """One forward round on stacked planes; kp = (8, 16, 1) key masks.

    ``mc`` picks the MixColumns rotation lowering: "auto" follows ``perm``
    (gather form -> reshape+roll, kernel form -> leading-axis perms);
    "roll"/"perm" force one — a tuning knob for Mosaic, where the relative
    cost of sublane rolls vs slice-stacks is hardware-generation-dependent.
    ``sbox`` likewise overrides the S-box formulation per call (see
    sbox_planes); None keeps the module-level OT_SBOX choice.
    """
    mc_perm = _resolve_mc(perm, mc)
    p = sbox_planes([planes[i] for i in range(8)], impl=sbox)
    p = [perm(x, SR_PERM) for x in p]
    if not last:
        p = mixcolumns_planes(p, perm=mc_perm)
    return jnp.stack([p[i] ^ kp[i] for i in range(8)])


def _resolve_mc(perm, mc):
    if mc == "roll":
        return None
    if mc == "perm":
        return perm
    return None if perm is _perm_take else perm


def decrypt_round(planes: jnp.ndarray, kp: jnp.ndarray, last: bool,
                  perm=_perm_take, mc="auto") -> jnp.ndarray:
    """One inverse round, matching the folded-schedule ordering of the
    T-table core (AES_RROUND, reference aes-modes/aes.c:624-645):
    InvShiftRows/InvSubBytes (they commute — permutation vs byte-wise map;
    the substitution runs first so the round ends in a gather, which keeps
    XLA-CPU from fusing the whole inversion circuit into a downstream
    consumer and exploding compile time), then InvMixColumns, then rk_dec."""
    mc_perm = _resolve_mc(perm, mc)
    p = inv_sbox_planes([planes[i] for i in range(8)])
    p = [perm(x, ISR_PERM) for x in p]
    if not last:
        p = inv_mixcolumns_planes(p, perm=mc_perm)
    return jnp.stack([p[i] ^ kp[i] for i in range(8)])


def _crypt_planes(planes: jnp.ndarray, kp: jnp.ndarray, nr: int,
                  round_fn) -> jnp.ndarray:
    planes = planes ^ kp[0]
    if nr > 1:
        planes, _ = jax.lax.scan(
            lambda q, k: (round_fn(q, k, False), None), planes, kp[1:nr],
            unroll=ROUND_UNROLL,
        )
    return round_fn(planes, kp[nr], True)


# ---------------------------------------------------------------------------
# Engine surface: drop-in (words, rk, nr) -> words cores.
# ---------------------------------------------------------------------------


def _pad32(words: jnp.ndarray):
    n = words.shape[0]
    pad = (-n) % 32
    if pad:
        words = jnp.concatenate(
            [words, jnp.zeros((pad, 4), dtype=words.dtype)], axis=0
        )
    return words, n


def encrypt_words(words: jnp.ndarray, rk: jnp.ndarray, nr: int) -> jnp.ndarray:
    """Bitsliced batch encrypt; same contract as ops/block.py:encrypt_words."""
    padded, n = _pad32(words)
    out = _crypt_planes(to_planes(padded), key_planes(rk, nr), nr, encrypt_round)
    return from_planes(out)[:n]


def decrypt_words(words: jnp.ndarray, rk_dec: jnp.ndarray, nr: int) -> jnp.ndarray:
    """Bitsliced batch decrypt with the InvMixColumns-folded schedule."""
    padded, n = _pad32(words)
    out = _crypt_planes(to_planes(padded), key_planes(rk_dec, nr), nr, decrypt_round)
    return from_planes(out)[:n]


def encrypt_words_multikey(words: jnp.ndarray, rk_blocks: jnp.ndarray,
                           nr: int) -> jnp.ndarray:
    """Bitsliced batch encrypt where block i uses its OWN schedule.

    ``rk_blocks``: (N, 4*(nr+1)) u32 per-block round keys (the caller
    gathers them from a (K, 4*(nr+1)) stack with a PUBLIC key-index
    vector — models/aes.py:ctr_crypt_words_scattered_multikey). Same
    contract as encrypt_words otherwise; padding blocks get the
    all-zero schedule (their output is discarded by the caller).
    """
    padded, n = _pad32(words)
    pad = padded.shape[0] - rk_blocks.shape[0]
    if pad:
        rk_blocks = jnp.concatenate(
            [rk_blocks,
             jnp.zeros((pad, rk_blocks.shape[1]), rk_blocks.dtype)], axis=0)
    out = _crypt_planes(to_planes(padded), multikey_planes(rk_blocks, nr),
                        nr, encrypt_round)
    return from_planes(out)[:n]


def decrypt_words_multikey(words: jnp.ndarray, rk_blocks: jnp.ndarray,
                           nr: int) -> jnp.ndarray:
    """Bitsliced batch decrypt where block i uses its OWN
    InvMixColumns-folded schedule — the decrypt twin of
    ``encrypt_words_multikey`` (the parallel CBC-decrypt serve seam:
    models/aes.py:cbc_decrypt_words_scattered_multikey). The inverse
    round circuit is key-oblivious exactly like the forward one, so K
    keys again cost one ``to_planes`` pass over the gathered schedules."""
    padded, n = _pad32(words)
    pad = padded.shape[0] - rk_blocks.shape[0]
    if pad:
        rk_blocks = jnp.concatenate(
            [rk_blocks,
             jnp.zeros((pad, rk_blocks.shape[1]), rk_blocks.dtype)], axis=0)
    out = _crypt_planes(to_planes(padded), multikey_planes(rk_blocks, nr),
                        nr, decrypt_round)
    return from_planes(out)[:n]
