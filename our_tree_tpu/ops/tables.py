"""AES lookup tables, generated programmatically at import time.

One source of truth for the S-box, inverse S-box, the combined
SubBytes+MixColumns "T-tables" and the round-constant schedule. The reference
carries three separate copies of this data (runtime generator at
aes-modes/aes.c:361-435, a 1,382-line static file aes-gpu/Source/AES.tab, and
the hardware path needs none); here everything is derived from GF(2^8)
arithmetic in ~40 lines of numpy.

Byte-order convention: **little-endian 32-bit words**, matching the parity
oracle (`GET_ULONG_LE`, reference aes-modes/aes.c:43-49). A state column with
bytes (b0, b1, b2, b3) — b0 being row 0 — packs as
``b0 | b1<<8 | b2<<16 | b3<<24``.  The reference's GPU path uses the opposite
(big-endian, AES.cu:42); we deliberately do not.

Table math (standard T-table construction):
  FT0[x] = (2*S | S<<8 | S<<16 | 3*S<<24) where S = SBOX[x]; FTi = rotl(FT0, 8i)
  RT0[x] = (14*I | 9*I<<8 | 13*I<<16 | 11*I<<24) where I = INV_SBOX[x];
  RTi = rotl(RT0, 8i)
These fold SubBytes+MixColumns (resp. InvSubBytes+InvMixColumns) into four
256-entry uint32 lookups per state word.
"""

from __future__ import annotations

import numpy as np

from . import gf


def _rotl8(b: np.ndarray, n: int) -> np.ndarray:
    """8-bit rotate left of a uint array holding byte values."""
    return ((b << n) | (b >> (8 - n))) & 0xFF


def _rotl32(w: np.ndarray, n: int) -> np.ndarray:
    w = w.astype(np.uint64)
    return (((w << n) | (w >> (32 - n))) & 0xFFFFFFFF).astype(np.uint32)


def _make_sbox() -> tuple[np.ndarray, np.ndarray]:
    inv = np.array([gf.ginv(x) for x in range(256)], dtype=np.uint32)
    s = inv ^ _rotl8(inv, 1) ^ _rotl8(inv, 2) ^ _rotl8(inv, 3) ^ _rotl8(inv, 4) ^ 0x63
    sbox = s.astype(np.uint32)
    inv_sbox = np.zeros(256, dtype=np.uint32)
    inv_sbox[sbox] = np.arange(256, dtype=np.uint32)
    return sbox, inv_sbox


SBOX, INV_SBOX = _make_sbox()

# Forward tables: SubBytes + MixColumns folded, little-endian packing.
_m2, _m3 = gf.gmul_table(2), gf.gmul_table(3)
_S = SBOX
FT0 = (_m2[_S] | (_S << 8) | (_S << 16) | (_m3[_S] << 24)).astype(np.uint32)
FT1 = _rotl32(FT0, 8)
FT2 = _rotl32(FT0, 16)
FT3 = _rotl32(FT0, 24)

# Reverse tables: InvSubBytes + InvMixColumns folded.
_m9, _m11, _m13, _m14 = (gf.gmul_table(c) for c in (9, 11, 13, 14))
_I = INV_SBOX
RT0 = (_m14[_I] | (_m9[_I] << 8) | (_m13[_I] << 16) | (_m11[_I] << 24)).astype(np.uint32)
RT1 = _rotl32(RT0, 8)
RT2 = _rotl32(RT0, 16)
RT3 = _rotl32(RT0, 24)

#: Round constants for the key schedule (low byte of the LE word).
RCON = np.array(
    [gf.gpow(2, i) for i in range(10)], dtype=np.uint32
)

#: InvMixColumns applied to a packed LE word, as a function — used by the
#: decryption key schedule (reference aes-modes/aes.c:580-589 does this with
#: table lookups; we do the field math directly).
def inv_mix_columns_word(w: np.ndarray) -> np.ndarray:
    w = np.asarray(w, dtype=np.uint32)
    b0, b1, b2, b3 = (w >> 0) & 0xFF, (w >> 8) & 0xFF, (w >> 16) & 0xFF, (w >> 24) & 0xFF
    s0 = _m14[b0] ^ _m11[b1] ^ _m13[b2] ^ _m9[b3]
    s1 = _m9[b0] ^ _m14[b1] ^ _m11[b2] ^ _m13[b3]
    s2 = _m13[b0] ^ _m9[b1] ^ _m14[b2] ^ _m11[b3]
    s3 = _m11[b0] ^ _m13[b1] ^ _m9[b2] ^ _m14[b3]
    return (s0 | (s1 << 8) | (s2 << 16) | (s3 << 24)).astype(np.uint32)
