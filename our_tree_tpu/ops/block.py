"""Reference AES block cipher in pure jnp (T-table formulation).

This is the framework's *correctness core*: a direct, batched expression of
the round structure used by the parity oracle (`AES_FROUND`/`AES_RROUND`,
reference aes-modes/aes.c:601-645, and the round loops at aes.c:650-752). It
is data-parallel over a leading block axis — one 16-byte block per row — so a
single call encrypts N blocks with no Python-level looping over data
(the reference's pthread chunking, aes-modes/test.c:33-35, becomes a batched
array op).

Table lookups use `jnp.take`, which XLA lowers to gathers. That is correct
everywhere and reasonably fast on CPU; the TPU throughput path is the
bitsliced engine in `ops/bitslice.py` — this module is the oracle the fast
paths are tested against.

State layout: four uint32 columns per block, little-endian packed
(see utils/packing.py). All functions are jit-compatible; `nr` and table
constants are static.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from . import tables


def _tbl(t: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    # Indices are always masked to [0, 256), so promise in-bounds to skip
    # XLA's clamping.
    return t.at[idx.astype(jnp.int32)].get(mode="promise_in_bounds")


def _bytes_of(x: jnp.ndarray) -> tuple[jnp.ndarray, ...]:
    return x & 0xFF, (x >> 8) & 0xFF, (x >> 16) & 0xFF, x >> 24


def encrypt_words(x: jnp.ndarray, rk: jnp.ndarray, nr: int) -> jnp.ndarray:
    """Encrypt a batch of blocks.

    Args:
      x: (..., 4) uint32 — LE-packed state words, one block per row.
      rk: (4*(nr+1),) uint32 round keys from `expand_key_enc`.
      nr: static round count (10/12/14).

    Returns:
      (..., 4) uint32 ciphertext words.
    """
    ft0, ft1, ft2, ft3 = (jnp.asarray(t) for t in (tables.FT0, tables.FT1, tables.FT2, tables.FT3))
    fsb = jnp.asarray(tables.SBOX)
    rk = rk.astype(jnp.uint32)

    x0 = x[..., 0] ^ rk[0]
    x1 = x[..., 1] ^ rk[1]
    x2 = x[..., 2] ^ rk[2]
    x3 = x[..., 3] ^ rk[3]

    def fround(r, a0, a1, a2, a3):
        k = rk[4 * r : 4 * r + 4]
        b = [_bytes_of(a) for a in (a0, a1, a2, a3)]
        y0 = k[0] ^ _tbl(ft0, b[0][0]) ^ _tbl(ft1, b[1][1]) ^ _tbl(ft2, b[2][2]) ^ _tbl(ft3, b[3][3])
        y1 = k[1] ^ _tbl(ft0, b[1][0]) ^ _tbl(ft1, b[2][1]) ^ _tbl(ft2, b[3][2]) ^ _tbl(ft3, b[0][3])
        y2 = k[2] ^ _tbl(ft0, b[2][0]) ^ _tbl(ft1, b[3][1]) ^ _tbl(ft2, b[0][2]) ^ _tbl(ft3, b[1][3])
        y3 = k[3] ^ _tbl(ft0, b[3][0]) ^ _tbl(ft1, b[0][1]) ^ _tbl(ft2, b[1][2]) ^ _tbl(ft3, b[2][3])
        return y0, y1, y2, y3

    for r in range(1, nr):
        x0, x1, x2, x3 = fround(r, x0, x1, x2, x3)

    # Final round: SubBytes + ShiftRows + AddRoundKey, no MixColumns.
    k = rk[4 * nr : 4 * nr + 4]
    b = [_bytes_of(a) for a in (x0, x1, x2, x3)]

    def ffinal(j, kj):
        return kj ^ (
            _tbl(fsb, b[j % 4][0])
            | (_tbl(fsb, b[(j + 1) % 4][1]) << 8)
            | (_tbl(fsb, b[(j + 2) % 4][2]) << 16)
            | (_tbl(fsb, b[(j + 3) % 4][3]) << 24)
        )

    out = [ffinal(j, k[j]) for j in range(4)]
    return jnp.stack(out, axis=-1)


def encrypt_block_fused(x: jnp.ndarray, rk: jnp.ndarray, nr: int) -> jnp.ndarray:
    """Latency-oriented single-block encrypt: ONE gather per round.

    `encrypt_words` issues 16 independent scalar gathers per round — fine
    when a large block axis amortises them, but inside a sequential-mode
    `lax.scan` body (CBC/CFB encrypt, reference aes.c:757-816/822-863,
    necessarily serial) each gather pays device dispatch latency and the
    measured cost is ~103 us/block on a v5e chip. The reference's round
    reads each output word from T-tables indexed by a rotating byte
    pattern (AES_FROUND, aes.c:601-622): src(j, i) = (j + i) mod 4 — i.e.
    byte-plane i of the state, rolled by i. Stacking the four rolled
    byte-planes gives all 16 T-table indices as one (16,) vector into the
    concatenated (1024,) table, so a round is one fused gather + a 4-way
    XOR reduce: ~30 us/block measured, 3.4x the per-word formulation
    (docs/PERF.md ledger; one-hot MXU lookups measure the same, the floor
    is per-round dependency latency, not the lookup mechanism).

    x: (4,) u32 LE state words of ONE block. Batch callers should keep
    using `encrypt_words`; scan bodies and their vmapped stream batches
    use this.
    """
    tcat = jnp.asarray(np.concatenate([tables.FT0, tables.FT1,
                                       tables.FT2, tables.FT3]))
    fsb = jnp.asarray(tables.SBOX)
    rk = rk.astype(jnp.uint32)
    x = x ^ rk[0:4]

    def rolled_idx(x, offset_stride):
        # idx[j, i] = byte-plane i of word (j + i) mod 4  (+ table offset)
        cols = []
        for i in range(4):
            bi = (x >> jnp.uint32(8 * i)) & jnp.uint32(0xFF)
            cols.append(jnp.roll(bi, -i) + jnp.uint32(offset_stride * i))
        return jnp.stack(cols, axis=1).reshape(-1)  # (16,)

    for r in range(1, nr):
        vals = _tbl(tcat, rolled_idx(x, 256)).reshape(4, 4)
        x = (rk[4 * r : 4 * r + 4]
             ^ vals[:, 0] ^ vals[:, 1] ^ vals[:, 2] ^ vals[:, 3])

    # Final round: SubBytes + ShiftRows + AddRoundKey (no MixColumns) —
    # same roll pattern, S-box values recombined by byte position.
    sv = _tbl(fsb, rolled_idx(x, 0)).reshape(4, 4)
    y = sv[:, 0] | (sv[:, 1] << 8) | (sv[:, 2] << 16) | (sv[:, 3] << 24)
    return rk[4 * nr : 4 * nr + 4] ^ y


def decrypt_words(x: jnp.ndarray, rk_dec: jnp.ndarray, nr: int) -> jnp.ndarray:
    """Decrypt a batch of blocks with a decryption schedule from `expand_key_dec`."""
    rt0, rt1, rt2, rt3 = (jnp.asarray(t) for t in (tables.RT0, tables.RT1, tables.RT2, tables.RT3))
    rsb = jnp.asarray(tables.INV_SBOX)
    rk = rk_dec.astype(jnp.uint32)

    x0 = x[..., 0] ^ rk[0]
    x1 = x[..., 1] ^ rk[1]
    x2 = x[..., 2] ^ rk[2]
    x3 = x[..., 3] ^ rk[3]

    def rround(r, a0, a1, a2, a3):
        k = rk[4 * r : 4 * r + 4]
        b = [_bytes_of(a) for a in (a0, a1, a2, a3)]
        # Inverse ShiftRows: row i sourced from column (j - i) mod 4.
        y0 = k[0] ^ _tbl(rt0, b[0][0]) ^ _tbl(rt1, b[3][1]) ^ _tbl(rt2, b[2][2]) ^ _tbl(rt3, b[1][3])
        y1 = k[1] ^ _tbl(rt0, b[1][0]) ^ _tbl(rt1, b[0][1]) ^ _tbl(rt2, b[3][2]) ^ _tbl(rt3, b[2][3])
        y2 = k[2] ^ _tbl(rt0, b[2][0]) ^ _tbl(rt1, b[1][1]) ^ _tbl(rt2, b[0][2]) ^ _tbl(rt3, b[3][3])
        y3 = k[3] ^ _tbl(rt0, b[3][0]) ^ _tbl(rt1, b[2][1]) ^ _tbl(rt2, b[1][2]) ^ _tbl(rt3, b[0][3])
        return y0, y1, y2, y3

    for r in range(1, nr):
        x0, x1, x2, x3 = rround(r, x0, x1, x2, x3)

    k = rk[4 * nr : 4 * nr + 4]
    b = [_bytes_of(a) for a in (x0, x1, x2, x3)]

    def rfinal(j, kj):
        return kj ^ (
            _tbl(rsb, b[j % 4][0])
            | (_tbl(rsb, b[(j + 3) % 4][1]) << 8)
            | (_tbl(rsb, b[(j + 2) % 4][2]) << 16)
            | (_tbl(rsb, b[(j + 1) % 4][3]) << 24)
        )

    out = [rfinal(j, k[j]) for j in range(4)]
    return jnp.stack(out, axis=-1)
