"""GF(2^8) and GF(2^128) arithmetic for AES and GCM.

Host-side numpy/int, used at import time (table and key-schedule
generation) and at KEY time (GHASH mul-by-H matrix derivation). The
GF(2^8) half replaces the reference's runtime table generator
(`aes_gen_tables`, reference aes-modes/aes.c:361-435) with a
from-scratch implementation derived directly from FIPS-197; nothing
here is traced by JAX.

The GF(2^128) half is the GCM field (SP 800-38D §6.3: modulus
x^128 + x^7 + x^2 + x + 1, "reflected" bit order — the first byte's
most significant bit is the coefficient of x^0). Three formulations of
the same multiply, the per-primitive table-vs-dense tradeoff the engine
tiers map one field down (docs/ENGINES.md):

* ``gf128_mul`` — the bit-serial int reference (the parity twin every
  other formulation is pinned against);
* ``gf128_mul_table`` + ``gf128_tables`` — the byte-at-a-time
  precomputed-table variant (Shoup's method). HOST-ONLY on purpose: a
  traced version would index a key-derived table by secret GHASH state
  bytes — exactly the T-table timing channel the jaxpr auditor exists
  to flag (``constant-time`` on a secret-indexed gather);
* ``gf128_mul_matrix_words`` — multiply-by-a-FIXED-H as a 128x128
  GF(2) matrix: carry-less multiply is linear over GF(2) in one
  operand, so the traced GHASH kernel (aead/gcm.py) becomes pure
  XOR/AND matvec work on ``bitslice.py`` idioms — zero memory
  indirection, constant-time by construction. The matrix basis is
  WORD-BIT order (bit k = bit k%32 of LE-packed u32 word k//32 — i.e.
  byte k//8, bit k%8 of the block's byte stream), matching how the
  dispatch arrays already hold blocks, so the kernel never reshuffles
  bytes.
"""

from __future__ import annotations

import numpy as np

#: The AES field modulus x^8 + x^4 + x^3 + x + 1.
POLY = 0x11B


def xtime(a: int) -> int:
    """Multiply by x (i.e. 2) in GF(2^8)."""
    a <<= 1
    if a & 0x100:
        a ^= POLY
    return a & 0xFF


def gmul(a: int, b: int) -> int:
    """Carry-less multiply of two field elements, reduced mod POLY."""
    r = 0
    while b:
        if b & 1:
            r ^= a
        b >>= 1
        a = xtime(a)
    return r


def gpow(a: int, e: int) -> int:
    """a**e in GF(2^8) by square-and-multiply."""
    r = 1
    base = a
    while e:
        if e & 1:
            r = gmul(r, base)
        base = gmul(base, base)
        e >>= 1
    return r


def ginv(a: int) -> int:
    """Multiplicative inverse; AES convention maps 0 -> 0."""
    if a == 0:
        return 0
    return gpow(a, 254)


def gmul_table(c: int) -> np.ndarray:
    """(256,) uint32 table of gmul(c, x) for all x — used for table generation."""
    return np.array([gmul(c, x) for x in range(256)], dtype=np.uint32)


# ---------------------------------------------------------------------------
# GF(2^128): the GCM/GHASH field.
#
# Elements are 128-bit Python ints in the SPEC's bit-string order: the
# block's bytes big-endian, so int bit (127 - j) is the coefficient of
# x^j. ``R`` is the reduction constant 11100001 || 0^120 from SP
# 800-38D §6.3.
# ---------------------------------------------------------------------------

#: The GCM reduction constant: x^128 = x^7 + x^2 + x + 1, reflected.
GCM_R = 0xE1 << 120


def gf128_mul(x: int, y: int) -> int:
    """Bit-serial carry-less multiply in GF(2^128), reduced (SP 800-38D
    algorithm 1 translated to the big-endian int representation). The
    reference every table/matrix formulation is pinned against."""
    z, v = 0, x
    for i in range(128):
        if (y >> (127 - i)) & 1:
            z ^= v
        v = (v >> 1) ^ (GCM_R if v & 1 else 0)
    return z


def block_to_int(b) -> int:
    """16 block bytes -> the field element (big-endian bit string)."""
    return int.from_bytes(bytes(bytearray(b)), "big")


def int_to_block(z: int) -> bytes:
    """Field element -> 16 block bytes."""
    return z.to_bytes(16, "big")


#: x^8 as a field element (int bit 119): the per-byte shift constant
#: the table variant's Horner step multiplies by.
_X8 = 1 << 119


def gf128_tables(h: int) -> tuple[np.ndarray, np.ndarray]:
    """The byte-table variant's two precomputed tables for a fixed H:
    ``T0[b]`` = (b as the block's FIRST byte) * H, and ``R8[c]`` = the
    reduction feed-in of multiplying an element whose LAST byte is c by
    x^8. Both (256,) object arrays of ints (128-bit values)."""
    t0 = np.array([gf128_mul(b << 120, h) for b in range(256)],
                  dtype=object)
    r8 = np.array([gf128_mul(c, _X8) for c in range(256)], dtype=object)
    return t0, r8


def gf128_mul_table(x: int, tables: tuple[np.ndarray, np.ndarray]) -> int:
    """x * H byte-at-a-time via the precomputed tables (Shoup's method):
    Horner over x's 16 bytes, one table hit + one shift-reduce per byte.
    16 secret-indexed lookups per block — the formulation a traced
    kernel must NOT use (module docstring); host twin only."""
    t0, r8 = tables
    z = 0
    for i in range(15, -1, -1):
        z = (z >> 8) ^ int(r8[z & 0xFF])          # z *= x^8, reduced
        z ^= int(t0[(x >> (8 * (15 - i))) & 0xFF])
    return z


def wordbit_to_int(j: int) -> int:
    """The field element whose only set WORD-BIT is j (word-bit k =
    byte k//8, bit k%8 of the block's byte stream — the LE-u32-packed
    dispatch layout)."""
    byte_i, bit_t = j // 8, j % 8
    b = bytearray(16)
    b[byte_i] = 1 << bit_t
    return block_to_int(b)


def int_to_wordbits(z: int) -> np.ndarray:
    """Field element -> (128,) 0/1 uint32 vector in word-bit order."""
    b = int_to_block(z)
    out = np.empty(128, dtype=np.uint32)
    for i in range(16):
        for t in range(8):
            out[8 * i + t] = (b[i] >> t) & 1
    return out


def gf128_mul_matrix_words(h: int) -> np.ndarray:
    """Multiply-by-H as a (128, 128) GF(2) uint32 matrix in the
    WORD-BIT basis: column j = (word-bit j) * H. Carry-less multiply is
    linear over GF(2) in x for fixed H, so ``(M @ bits(x)) & 1`` IS the
    field multiply — the traced GHASH kernel's whole arithmetic
    (aead/gcm.py), no lookups, no carries. Derived per key at the
    keycache seam (H = E_K(0^128)); ~64 KiB per key."""
    m = np.empty((128, 128), dtype=np.uint32)
    for j in range(128):
        m[:, j] = int_to_wordbits(gf128_mul(wordbit_to_int(j), h))
    return m


def gf128_matvec_words(m: np.ndarray, x: int) -> int:
    """Host matvec twin of the traced kernel's step: x * H via the
    word-bit matrix (tests pin it against ``gf128_mul``)."""
    bits = int_to_wordbits(x)
    out = (m @ bits) & 1
    z = 0
    for j in range(128):
        if out[j]:
            z |= wordbit_to_int(j)
    return z
