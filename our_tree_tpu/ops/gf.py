"""GF(2^8) arithmetic for AES (Rijndael field, modulus x^8+x^4+x^3+x+1).

Host-side numpy, used only at import time to generate lookup tables and
key schedules. This replaces the reference's runtime table generator
(`aes_gen_tables`, reference aes-modes/aes.c:361-435) with a from-scratch
implementation derived directly from FIPS-197; nothing here is traced by JAX.
"""

from __future__ import annotations

import numpy as np

#: The AES field modulus x^8 + x^4 + x^3 + x + 1.
POLY = 0x11B


def xtime(a: int) -> int:
    """Multiply by x (i.e. 2) in GF(2^8)."""
    a <<= 1
    if a & 0x100:
        a ^= POLY
    return a & 0xFF


def gmul(a: int, b: int) -> int:
    """Carry-less multiply of two field elements, reduced mod POLY."""
    r = 0
    while b:
        if b & 1:
            r ^= a
        b >>= 1
        a = xtime(a)
    return r


def gpow(a: int, e: int) -> int:
    """a**e in GF(2^8) by square-and-multiply."""
    r = 1
    base = a
    while e:
        if e & 1:
            r = gmul(r, base)
        base = gmul(base, base)
        e >>= 1
    return r


def ginv(a: int) -> int:
    """Multiplicative inverse; AES convention maps 0 -> 0."""
    if a == 0:
        return 0
    return gpow(a, 254)


def gmul_table(c: int) -> np.ndarray:
    """(256,) uint32 table of gmul(c, x) for all x — used for table generation."""
    return np.array([gmul(c, x) for x in range(256)], dtype=np.uint32)
