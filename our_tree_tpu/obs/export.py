"""Run-dir parsing + the Chrome/Perfetto ``trace.json`` exporter.

``load_run(run_dir)`` stitches every ``trace-*.jsonl`` in a run
directory back into one picture: spans paired from their begin/end
events (an unmatched begin is an ORPHAN — the durable evidence of a
process SIGKILLed mid-span, rendered with the run's end as its close
and flagged), points/counters/gauges kept as events, and every line
checked against the v1 schema (violations are collected, not raised —
a half-written file from a killed child must not hide the rest of the
run). ``write_chrome_trace`` emits the Trace Event Format JSON that
both ``chrome://tracing`` and https://ui.perfetto.dev open directly.

The run dir may also carry ``metrics-*.jsonl`` files — the registry
flusher's periodic cumulative snapshots (``obs/metrics.py``). They are
parsed alongside the trace files with the same violations-not-raised
discipline (``--check`` gates their schema exactly like span events):
``Run.snapshots`` keeps the time series (each annotated with its pid),
``Run.metrics_totals()`` folds the LAST snapshot per process into final
counter totals / gauge last-values / merged histograms — what the
report's metrics table renders — and the Perfetto export emits every
snapshot gauge (``serve_inflight``, queue depth, ...) as a counter
track, so the registry's view rides the same timeline as the spans.

Stdlib-only, no intra-package imports (the report CLI and tests load it
without jax in sight).
"""

from __future__ import annotations

import glob
import json
import os
import re

from . import trace as _trace

#: Required fields per event type (the schema the --check gate enforces).
_REQUIRED = {
    "b": ("id", "name", "ts"),
    "e": ("id", "ts", "status"),
    "c": ("name", "ts", "n"),
    "g": ("name", "ts", "value"),
    "p": ("name", "ts"),
}

#: Required fields per metrics snapshot line, and the shape of each
#: series entry ([name, {labels}, value-or-hist]) — obs/metrics.py's
#: ``_snapshot_rec`` schema, gated by --check like span events.
_SNAP_SECTIONS = ("counters", "gauges", "hists")
METRICS_KIND = "ot-metrics"


class SpanRec:
    """One reconstructed span. ``end_ts`` is None for an orphan (no end
    event reached the file — the process died inside the span); callers
    use ``dur_us(run_end)`` which closes orphans at the run's end."""

    __slots__ = ("id", "name", "parent", "ts", "end_ts", "status", "attrs",
                 "pid", "proc", "tid")

    def __init__(self, rec: dict, pid: int, proc: str):
        self.id = rec["id"]
        self.name = rec["name"]
        self.parent = rec.get("parent")
        self.ts = rec["ts"]
        self.attrs = rec.get("attrs", {})
        self.pid, self.proc, self.tid = pid, proc, rec.get("tid", 0)
        self.end_ts = None
        self.status = None

    @property
    def orphan(self) -> bool:
        return self.end_ts is None

    def dur_us(self, run_end: int) -> int:
        return max((self.end_ts if self.end_ts is not None else run_end)
                   - self.ts, 0)


class Run:
    """A parsed run: ``spans`` (id -> SpanRec, orphans included),
    ``events`` (the raw c/g/p records, each annotated with ``pid``),
    ``procs`` (pid -> header), ``violations`` (file, line-no, reason),
    ``t0``/``t1`` (first/last event timestamps, µs)."""

    def __init__(self):
        self.spans: dict[str, SpanRec] = {}
        self.events: list[dict] = []
        self.procs: dict[int, dict] = {}
        #: proc token -> metrics-file header, and the snapshot time
        #: series (cumulative; each annotated with "pid" and "proc" —
        #: the token is the aggregation key, like the trace side, so
        #: pid reuse across a long run cannot merge two processes).
        self.metric_procs: dict[str, dict] = {}
        self.snapshots: list[dict] = []
        self.violations: list[tuple[str, int, str]] = []
        self.t0: int | None = None
        self.t1: int | None = None

    def _see(self, ts) -> None:
        if isinstance(ts, int):
            self.t0 = ts if self.t0 is None else min(self.t0, ts)
            self.t1 = ts if self.t1 is None else max(self.t1, ts)

    def orphans(self) -> list[SpanRec]:
        return [s for s in self.spans.values() if s.orphan]

    def points(self, name: str | None = None) -> list[dict]:
        return [e for e in self.events
                if e["ev"] == "p" and (name is None or e["name"] == name)]

    def counter_totals(self) -> dict[str, float]:
        out: dict[str, float] = {}
        for e in self.events:
            if e["ev"] == "c":
                out[e["name"]] = out.get(e["name"], 0) + e.get("n", 0)
        return out

    def ancestor_attr(self, span: SpanRec, key: str):
        """Walk the (cross-process) parent chain until a span carrying
        ``attrs[key]`` — how a barrier span deep inside a child is
        attributed to the supervisor's unit attempt."""
        seen = set()
        cur: SpanRec | None = span
        while cur is not None and cur.id not in seen:
            seen.add(cur.id)
            if key in cur.attrs:
                return cur.attrs[key]
            cur = self.spans.get(cur.parent) if cur.parent else None
        return None

    def clock_offsets(self) -> dict[int, int]:
        """Per-pid clock offsets (µs) estimated from the wire handshake.

        The router traces a ``wire-skew`` point per canary exchange:
        ``skew_us`` = backend reply timestamp minus the exchange
        midpoint, ``pid`` = the backend process (from the response
        frame). The MEDIAN per pid is that process's estimated offset
        from the router's clock — subtracting it re-aligns the merged
        timeline (``to_chrome_trace(align=True)``) so a backend with a
        skewed clock no longer renders its spans displaced from the
        router spans that caused them. Empty when no handshake points
        exist (single-process runs need no alignment)."""
        by_pid: dict[int, list[int]] = {}
        for p in self.points("wire-skew"):
            a = p.get("attrs", {})
            pid, skew = a.get("pid"), a.get("skew_us")
            if isinstance(pid, int) and isinstance(skew, (int, float)):
                by_pid.setdefault(pid, []).append(int(skew))
        out = {}
        for pid, skews in by_pid.items():
            skews.sort()
            out[pid] = skews[len(skews) // 2]
        return out

    def metrics_totals(self) -> dict:
        """Final registry totals across the run's processes: the LAST
        snapshot per pid (snapshots are cumulative), counters and
        histogram buckets SUMMED across pids, gauges last-write by
        snapshot timestamp. Keys are ``name`` / ``name{k=v,...}`` flat
        series names (obs.metrics.flat_name layout); hist values are
        {"buckets", "count", "sum"}."""
        last: dict[str, dict] = {}
        for snap in self.snapshots:
            # Keyed by the PROC TOKEN, not the pid: snapshots are
            # cumulative PER PROCESS, and a reused pid late in a soak
            # would otherwise silently replace (and so drop) the dead
            # process's final totals — the same reuse hazard the trace
            # file names absorb with their 8-hex token.
            proc = snap.get("proc", str(snap.get("pid", -1)))
            if proc not in last or snap.get("ts", 0) >= last[proc].get(
                    "ts", 0):
                last[proc] = snap
        counters: dict[str, float] = {}
        gauges: dict[str, tuple] = {}
        hists: dict[str, dict] = {}
        for _proc, snap in sorted(last.items()):
            ts = snap.get("ts", 0)
            for name, labels, v in snap.get("counters", []):
                key = _flat(name, labels)
                counters[key] = counters.get(key, 0) + v
            for name, labels, v in snap.get("gauges", []):
                key = _flat(name, labels)
                if key not in gauges or ts >= gauges[key][0]:
                    gauges[key] = (ts, v)
            for name, labels, h in snap.get("hists", []):
                key = _flat(name, labels)
                agg = hists.setdefault(
                    key, {"buckets": {}, "count": 0, "sum": 0.0})
                for b, c in h.get("buckets", {}).items():
                    agg["buckets"][b] = agg["buckets"].get(b, 0) + c
                agg["count"] += h.get("count", 0)
                agg["sum"] += h.get("sum", 0.0)
                # Tail exemplars (obs/metrics.py): per bucket, the max
                # observation wins across processes — same retention
                # rule the live registry applies within one.
                for b, e in (h.get("exemplars") or {}).items():
                    if not isinstance(e, dict) or "v" not in e:
                        continue
                    ex = agg.setdefault("exemplars", {})
                    cur = ex.get(b)
                    if cur is None or e["v"] >= cur.get("v", 0):
                        ex[b] = dict(e)
        return {"counters": counters,
                "gauges": {k: v for k, (_, v) in gauges.items()},
                "hists": hists}


def _segment_order(path: str):
    """Sort key putting a process's rotated segments in WRITE order.

    A rotating writer (``OT_TRACE_MAX_MB``) names segments
    ``trace-<pid>-<proc>.jsonl`` then ``trace-<pid>-<proc>-s1.jsonl``,
    ``-s2``, ... — and plain ``sorted()`` puts ``-s1`` BEFORE the bare
    first segment (``-`` < ``.``), which would feed span ends to the
    parser before their begins and misreport a healthy rotated run as
    full of violations. Key: (base name, segment number). The metrics
    snapshot files rotate under the same cap with the same naming, so
    the same key orders them (cumulative snapshots make order matter
    less there, but last-per-proc folding still wants write order)."""
    name = os.path.basename(path)
    m = re.fullmatch(
        r"((?:trace|metrics)-\d+-[0-9a-f]+)(?:-s(\d+))?\.jsonl", name)
    if m:
        return (m.group(1), int(m.group(2) or 0))
    return (name, 0)


def _flat(name, labels) -> str:
    """The flat series key (obs.metrics.flat_name layout, duplicated
    here because this module stays import-free of its siblings)."""
    if not labels:
        return str(name)
    inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


def _valid_series(entry, hist: bool) -> bool:
    """One snapshot series entry: [name, {labels}, number-or-hist]."""
    if not (isinstance(entry, (list, tuple)) and len(entry) == 3):
        return False
    name, labels, v = entry
    if not isinstance(name, str) or not isinstance(labels, dict):
        return False
    if hist:
        return (isinstance(v, dict)
                and isinstance(v.get("buckets"), dict)
                and isinstance(v.get("count"), int))
    return isinstance(v, (int, float))


def _load_metrics_file(run: Run, path: str) -> None:
    """Parse one ``metrics-*.jsonl`` snapshot file into ``run`` with the
    same violations-not-raised discipline as the trace files."""
    fname = os.path.basename(path)
    pid, proc = -1, "?"
    with open(path, "r", encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                run.violations.append((fname, lineno, "unparseable"))
                continue
            if lineno == 1:
                if rec.get("kind") != METRICS_KIND or rec.get("v") != 1:
                    run.violations.append(
                        (fname, 1, "bad or missing metrics header"))
                    break
                pid = rec.get("pid", -1)
                proc = str(rec.get("proc", pid))
                run.metric_procs[proc] = rec
                run._see(rec.get("start_us"))
                continue
            if not isinstance(rec.get("ts"), int):
                run.violations.append(
                    (fname, lineno, "snapshot missing ts"))
                continue
            bad = [s for s in _SNAP_SECTIONS
                   if not isinstance(rec.get(s), list)]
            if bad:
                run.violations.append(
                    (fname, lineno, f"snapshot missing {bad}"))
                continue
            malformed = (
                [e for s in ("counters", "gauges")
                 for e in rec[s] if not _valid_series(e, hist=False)]
                + [e for e in rec["hists"]
                   if not _valid_series(e, hist=True)])
            if malformed:
                run.violations.append(
                    (fname, lineno,
                     f"malformed series entry {malformed[0]!r}"))
                continue
            run._see(rec["ts"])
            rec["pid"], rec["proc"] = pid, proc
            run.snapshots.append(rec)


def load_run(run_dir: str) -> Run:
    """Parse every ``trace-*.jsonl`` (and ``metrics-*.jsonl``) under
    ``run_dir`` into a ``Run``
    (a process's rotated segments in write order — ``_segment_order``)."""
    run = Run()
    for path in sorted(glob.glob(os.path.join(run_dir, "metrics-*.jsonl")),
                       key=_segment_order):
        _load_metrics_file(run, path)
    for path in sorted(glob.glob(os.path.join(run_dir, "trace-*.jsonl")),
                       key=_segment_order):
        fname = os.path.basename(path)
        pid, proc = -1, "?"
        with open(path, "r", encoding="utf-8") as fh:
            for lineno, line in enumerate(fh, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    # Unparseable line — a torn tail from a killed
                    # writer, or a writer bug. Recorded as a violation
                    # either way: --check fails on any of them, which is
                    # fine because a run with killed children fails the
                    # orphan check regardless (healthy runs tear
                    # nothing: every event is written with one flushed
                    # write()).
                    run.violations.append((fname, lineno, "unparseable"))
                    continue
                if lineno == 1:
                    if (rec.get("kind") != _trace.KIND
                            or rec.get("v") != _trace.VERSION):
                        run.violations.append(
                            (fname, 1, "bad or missing header"))
                        break
                    pid, proc = rec.get("pid", -1), rec.get("proc", "?")
                    run.procs[pid] = rec
                    run._see(rec.get("start_us"))
                    continue
                ev = rec.get("ev")
                if ev not in _REQUIRED:
                    run.violations.append(
                        (fname, lineno, f"unknown ev {ev!r}"))
                    continue
                missing = [k for k in _REQUIRED[ev] if k not in rec]
                if missing:
                    run.violations.append(
                        (fname, lineno, f"{ev} missing {missing}"))
                    continue
                run._see(rec.get("ts"))
                if ev == "b":
                    run.spans[rec["id"]] = SpanRec(rec, pid, proc)
                elif ev == "e":
                    sp = run.spans.get(rec["id"])
                    if sp is None:
                        run.violations.append(
                            (fname, lineno, f"end without begin {rec['id']}"))
                        continue
                    sp.end_ts, sp.status = rec["ts"], rec["status"]
                    if rec.get("attrs"):
                        # End-event attrs (trace.note): measurements
                        # only known at close — device/host time split —
                        # merged into the reconstructed span.
                        sp.attrs = {**sp.attrs, **rec["attrs"]}
                else:
                    rec["pid"] = pid
                    run.events.append(rec)
    return run


def to_chrome_trace(run: Run, align: bool = True) -> dict:
    """The run as a Trace Event Format object (Perfetto/chrome loadable).

    Closed spans become complete ("X") events; orphans become "X" events
    stretched to the run's end with ``killed: true`` in their args — in
    the Perfetto timeline the hung child's dispatch reads as a bar cut
    off at the kill, which is exactly the picture that matters. Points
    are instants ("i"), counters cumulative "C" tracks, gauges "C"
    tracks of their raw value. Timestamps are rebased to the run's
    first event so traces open at t=0.

    ``align=True`` (the default) subtracts each process's estimated
    clock offset (``Run.clock_offsets``, from the wire-skew handshake
    points) from its timestamps, so a multi-HOST run's spans line up on
    one causally-consistent timeline — the router's dispatch bar and the
    backend's queued/dispatch bars nest instead of drifting apart. A
    run with no handshake points is unchanged.
    """
    t0 = run.t0 or 0
    run_end = run.t1 if run.t1 is not None else t0
    offsets = run.clock_offsets() if align else {}

    def ts_of(ts: int, pid: int) -> int:
        return ts - t0 - offsets.get(pid, 0)

    out: list[dict] = []
    for pid, hdr in sorted(run.procs.items()):
        out.append({"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                    "args": {"name": hdr.get("argv", "?")}})
    for sp in sorted(run.spans.values(), key=lambda s: s.ts):
        args = dict(sp.attrs)
        if sp.orphan:
            args["killed"] = True
        elif sp.status != "ok":
            args["status"] = sp.status
        out.append({"ph": "X", "cat": "ot", "name": sp.name, "pid": sp.pid,
                    "tid": sp.tid, "ts": ts_of(sp.ts, sp.pid),
                    "dur": sp.dur_us(run_end), "args": args})
    # Counter tracks are per-PROCESS in the Trace Event Format, so the
    # cumulative totals must be too — one shared total would show the
    # second child's track starting where the first's ended.
    totals: dict[tuple, float] = {}
    for e in sorted(run.events, key=lambda e: e["ts"]):
        if e["ev"] == "p":
            out.append({"ph": "i", "cat": "ot", "name": e["name"],
                        "pid": e["pid"], "tid": 0,
                        "ts": ts_of(e["ts"], e["pid"]),
                        "s": "p", "args": e.get("attrs", {})})
        elif e["ev"] == "c":
            key = (e["pid"], e["name"])
            totals[key] = totals.get(key, 0) + e.get("n", 0)
            out.append({"ph": "C", "name": e["name"], "pid": e["pid"],
                        "ts": ts_of(e["ts"], e["pid"]),
                        "args": {"value": totals[key]}})
        elif e["ev"] == "g":
            out.append({"ph": "C", "name": e["name"], "pid": e["pid"],
                        "ts": ts_of(e["ts"], e["pid"]),
                        "args": {"value": e.get("value", 0)}})
    # Registry snapshot gauges as counter tracks ("metrics:" prefixed so
    # the flusher's 2 s samples sit beside, not inside, the per-event
    # trace tracks): serve_inflight and serve_queue_depth become visible
    # ON the span timeline — queue pressure lined up against the
    # dispatches that caused it, at any OT_TRACE_SAMPLE rate.
    for snap in sorted(run.snapshots, key=lambda s: s["ts"]):
        for name, labels, v in snap.get("gauges", []):
            out.append({"ph": "C", "name": f"metrics:{_flat(name, labels)}",
                        "pid": snap.get("pid", -1),
                        "ts": ts_of(snap["ts"], snap.get("pid", -1)),
                        "args": {"value": v}})
    doc = {"traceEvents": out, "displayTimeUnit": "ms"}
    if offsets:
        doc["otClockOffsetsUs"] = {str(k): v for k, v in
                                   sorted(offsets.items())}
    return doc


def write_chrome_trace(run: Run, path: str, align: bool = True) -> str:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(to_chrome_trace(run, align=align), fh,
                  separators=(",", ":"), default=repr)
    return path
