"""Run-dir parsing + the Chrome/Perfetto ``trace.json`` exporter.

``load_run(run_dir)`` stitches every ``trace-*.jsonl`` in a run
directory back into one picture: spans paired from their begin/end
events (an unmatched begin is an ORPHAN — the durable evidence of a
process SIGKILLed mid-span, rendered with the run's end as its close
and flagged), points/counters/gauges kept as events, and every line
checked against the v1 schema (violations are collected, not raised —
a half-written file from a killed child must not hide the rest of the
run). ``write_chrome_trace`` emits the Trace Event Format JSON that
both ``chrome://tracing`` and https://ui.perfetto.dev open directly.

Stdlib-only, no intra-package imports (the report CLI and tests load it
without jax in sight).
"""

from __future__ import annotations

import glob
import json
import os
import re

from . import trace as _trace

#: Required fields per event type (the schema the --check gate enforces).
_REQUIRED = {
    "b": ("id", "name", "ts"),
    "e": ("id", "ts", "status"),
    "c": ("name", "ts", "n"),
    "g": ("name", "ts", "value"),
    "p": ("name", "ts"),
}


class SpanRec:
    """One reconstructed span. ``end_ts`` is None for an orphan (no end
    event reached the file — the process died inside the span); callers
    use ``dur_us(run_end)`` which closes orphans at the run's end."""

    __slots__ = ("id", "name", "parent", "ts", "end_ts", "status", "attrs",
                 "pid", "proc", "tid")

    def __init__(self, rec: dict, pid: int, proc: str):
        self.id = rec["id"]
        self.name = rec["name"]
        self.parent = rec.get("parent")
        self.ts = rec["ts"]
        self.attrs = rec.get("attrs", {})
        self.pid, self.proc, self.tid = pid, proc, rec.get("tid", 0)
        self.end_ts = None
        self.status = None

    @property
    def orphan(self) -> bool:
        return self.end_ts is None

    def dur_us(self, run_end: int) -> int:
        return max((self.end_ts if self.end_ts is not None else run_end)
                   - self.ts, 0)


class Run:
    """A parsed run: ``spans`` (id -> SpanRec, orphans included),
    ``events`` (the raw c/g/p records, each annotated with ``pid``),
    ``procs`` (pid -> header), ``violations`` (file, line-no, reason),
    ``t0``/``t1`` (first/last event timestamps, µs)."""

    def __init__(self):
        self.spans: dict[str, SpanRec] = {}
        self.events: list[dict] = []
        self.procs: dict[int, dict] = {}
        self.violations: list[tuple[str, int, str]] = []
        self.t0: int | None = None
        self.t1: int | None = None

    def _see(self, ts) -> None:
        if isinstance(ts, int):
            self.t0 = ts if self.t0 is None else min(self.t0, ts)
            self.t1 = ts if self.t1 is None else max(self.t1, ts)

    def orphans(self) -> list[SpanRec]:
        return [s for s in self.spans.values() if s.orphan]

    def points(self, name: str | None = None) -> list[dict]:
        return [e for e in self.events
                if e["ev"] == "p" and (name is None or e["name"] == name)]

    def counter_totals(self) -> dict[str, float]:
        out: dict[str, float] = {}
        for e in self.events:
            if e["ev"] == "c":
                out[e["name"]] = out.get(e["name"], 0) + e.get("n", 0)
        return out

    def ancestor_attr(self, span: SpanRec, key: str):
        """Walk the (cross-process) parent chain until a span carrying
        ``attrs[key]`` — how a barrier span deep inside a child is
        attributed to the supervisor's unit attempt."""
        seen = set()
        cur: SpanRec | None = span
        while cur is not None and cur.id not in seen:
            seen.add(cur.id)
            if key in cur.attrs:
                return cur.attrs[key]
            cur = self.spans.get(cur.parent) if cur.parent else None
        return None


def _segment_order(path: str):
    """Sort key putting a process's rotated segments in WRITE order.

    A rotating writer (``OT_TRACE_MAX_MB``) names segments
    ``trace-<pid>-<proc>.jsonl`` then ``trace-<pid>-<proc>-s1.jsonl``,
    ``-s2``, ... — and plain ``sorted()`` puts ``-s1`` BEFORE the bare
    first segment (``-`` < ``.``), which would feed span ends to the
    parser before their begins and misreport a healthy rotated run as
    full of violations. Key: (base name, segment number)."""
    name = os.path.basename(path)
    m = re.fullmatch(r"(trace-\d+-[0-9a-f]+)(?:-s(\d+))?\.jsonl", name)
    if m:
        return (m.group(1), int(m.group(2) or 0))
    return (name, 0)


def load_run(run_dir: str) -> Run:
    """Parse every ``trace-*.jsonl`` under ``run_dir`` into a ``Run``
    (a process's rotated segments in write order — ``_segment_order``)."""
    run = Run()
    for path in sorted(glob.glob(os.path.join(run_dir, "trace-*.jsonl")),
                       key=_segment_order):
        fname = os.path.basename(path)
        pid, proc = -1, "?"
        with open(path, "r", encoding="utf-8") as fh:
            for lineno, line in enumerate(fh, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    # Unparseable line — a torn tail from a killed
                    # writer, or a writer bug. Recorded as a violation
                    # either way: --check fails on any of them, which is
                    # fine because a run with killed children fails the
                    # orphan check regardless (healthy runs tear
                    # nothing: every event is written with one flushed
                    # write()).
                    run.violations.append((fname, lineno, "unparseable"))
                    continue
                if lineno == 1:
                    if (rec.get("kind") != _trace.KIND
                            or rec.get("v") != _trace.VERSION):
                        run.violations.append(
                            (fname, 1, "bad or missing header"))
                        break
                    pid, proc = rec.get("pid", -1), rec.get("proc", "?")
                    run.procs[pid] = rec
                    run._see(rec.get("start_us"))
                    continue
                ev = rec.get("ev")
                if ev not in _REQUIRED:
                    run.violations.append(
                        (fname, lineno, f"unknown ev {ev!r}"))
                    continue
                missing = [k for k in _REQUIRED[ev] if k not in rec]
                if missing:
                    run.violations.append(
                        (fname, lineno, f"{ev} missing {missing}"))
                    continue
                run._see(rec.get("ts"))
                if ev == "b":
                    run.spans[rec["id"]] = SpanRec(rec, pid, proc)
                elif ev == "e":
                    sp = run.spans.get(rec["id"])
                    if sp is None:
                        run.violations.append(
                            (fname, lineno, f"end without begin {rec['id']}"))
                        continue
                    sp.end_ts, sp.status = rec["ts"], rec["status"]
                else:
                    rec["pid"] = pid
                    run.events.append(rec)
    return run


def to_chrome_trace(run: Run) -> dict:
    """The run as a Trace Event Format object (Perfetto/chrome loadable).

    Closed spans become complete ("X") events; orphans become "X" events
    stretched to the run's end with ``killed: true`` in their args — in
    the Perfetto timeline the hung child's dispatch reads as a bar cut
    off at the kill, which is exactly the picture that matters. Points
    are instants ("i"), counters cumulative "C" tracks, gauges "C"
    tracks of their raw value. Timestamps are rebased to the run's
    first event so traces open at t=0.
    """
    t0 = run.t0 or 0
    run_end = run.t1 if run.t1 is not None else t0
    out: list[dict] = []
    for pid, hdr in sorted(run.procs.items()):
        out.append({"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                    "args": {"name": hdr.get("argv", "?")}})
    for sp in sorted(run.spans.values(), key=lambda s: s.ts):
        args = dict(sp.attrs)
        if sp.orphan:
            args["killed"] = True
        elif sp.status != "ok":
            args["status"] = sp.status
        out.append({"ph": "X", "cat": "ot", "name": sp.name, "pid": sp.pid,
                    "tid": sp.tid, "ts": sp.ts - t0,
                    "dur": sp.dur_us(run_end), "args": args})
    # Counter tracks are per-PROCESS in the Trace Event Format, so the
    # cumulative totals must be too — one shared total would show the
    # second child's track starting where the first's ended.
    totals: dict[tuple, float] = {}
    for e in sorted(run.events, key=lambda e: e["ts"]):
        if e["ev"] == "p":
            out.append({"ph": "i", "cat": "ot", "name": e["name"],
                        "pid": e["pid"], "tid": 0, "ts": e["ts"] - t0,
                        "s": "p", "args": e.get("attrs", {})})
        elif e["ev"] == "c":
            key = (e["pid"], e["name"])
            totals[key] = totals.get(key, 0) + e.get("n", 0)
            out.append({"ph": "C", "name": e["name"], "pid": e["pid"],
                        "ts": e["ts"] - t0,
                        "args": {"value": totals[key]}})
        elif e["ev"] == "g":
            out.append({"ph": "C", "name": e["name"], "pid": e["pid"],
                        "ts": e["ts"] - t0,
                        "args": {"value": e.get("value", 0)}})
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def write_chrome_trace(run: Run, path: str) -> str:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(to_chrome_trace(run), fh, separators=(",", ":"),
                  default=repr)
    return path
