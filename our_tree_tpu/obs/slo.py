"""SLO regression gates: compare a serve run against a committed baseline.

A committed ``SERVE_r*.json`` is a *promise* — p50/p95/p99, goodput,
zero errors, zero lost, zero recompiles on a known host class — and
until now nothing enforced it: a PR could halve serve goodput and every
CI gate would stay green as long as correctness held. This module is
the enforcement seam: ``compare(baseline, candidate, tolerances)``
checks the candidate run's metrics against the baseline artifact with
per-metric tolerances and names every violation, and
``serve.bench --slo <baseline.json>`` runs it in-process after a drive
(exit 1 on any regression — the CI gate against
``SERVE_r04_control.json``).

Metric classes, because regressions come in two shapes:

* **Bounded-ratio metrics** (latency percentiles up, goodput down):
  compared RELATIVELY — candidate latency may exceed baseline by at
  most ``1 + tol``, goodput may fall below by at most ``1 - tol``.
  Defaults are deliberately loose enough for same-host noise; CI
  running on a different host class passes wider ``--slo-tolerance``
  values (cross-host wall-clock is not a promise, order-of-magnitude
  sanity is).
* **Count metrics** (error total, lost, recompiles, probe mismatches):
  compared ABSOLUTELY — the candidate may not exceed the baseline
  count at all, tolerance ignored. A baseline with 0 errors means 0,
  on any host: these are the metrics whose regression is a bug, not
  noise.

Baselines and candidates are both the SERVE artifact schema (the
``load``/``queue``/``compiles`` sections) — ``extract`` also accepts
the one-line bench JSON, so ``python -m our_tree_tpu.obs.slo
baseline.json candidate.json`` gates recorded artifacts offline (the
red/green rehearsal harness) with the same code path the bench uses
live.

Stdlib-only: the gate must run in CI steps that never import jax.
"""

from __future__ import annotations

import argparse
import json
import sys

#: Relative tolerances for the bounded-ratio metrics: how much WORSE
#: the candidate may be. Latency: candidate <= baseline * (1 + tol);
#: goodput: candidate >= baseline * (1 - tol). Chosen for same-host
#: rerun noise (the CPU container's serve numbers wobble ~10-15% at
#: p99); cross-host CI overrides with wider values per metric.
DEFAULT_TOLERANCES = {
    "p50_ms": 0.50,
    "p95_ms": 0.50,
    "p99_ms": 0.75,
    "goodput_gbps": 0.25,
    #: per-STAGE p95 budget (the waterfall gate): each stage in the
    #: baseline artifact's "stages" section may grow by at most this
    #: fraction. Looser than the end-to-end bands on purpose — single
    #: stages are noisier than their sum — but tight enough that a
    #: regression names WHICH stage moved instead of only that the
    #: total did (the fleet-observability ISSUE's point).
    "stage_p95_us": 1.0,
    #: per-(engine x mode x rung) achieved-GB/s-moved budget (the cost
    #: section's roofline rows, obs/costmodel.py): each row's modeled-
    #: traffic-over-device-time may FALL by at most this fraction of
    #: the baseline. Wide by default (device-time on a shared CPU host
    #: is noisy); the point is the failure NAMES the engine x rung
    #: whose utilization moved, same shape as the per-stage gates.
    "cost_gbps": 0.5,
}

#: Lower-is-better vs higher-is-better among the ratio metrics.
_HIGHER_IS_BETTER = ("goodput_gbps",)

#: Zero-noise count metrics: candidate must not exceed baseline, ever.
COUNT_METRICS = ("errors_total", "lost", "recompiles", "mismatches",
                 "alerts_total")


def extract(doc: dict) -> dict:
    """Normalise a SERVE artifact (or the one-line bench JSON) into the
    flat metric dict ``compare`` consumes."""
    load = doc.get("load", doc)  # artifact nests under "load"; the
    #                              bench line is already flat
    out = {
        "p50_ms": float(load.get("p50_ms", 0.0)),
        "p95_ms": float(load.get("p95_ms", 0.0)),
        "p99_ms": float(load.get("p99_ms", 0.0)),
        "goodput_gbps": float(load.get("goodput_gbps", 0.0)),
        "errors_total": float(sum((load.get("errors") or {}).values())),
        "mismatches": float(load.get("mismatches", 0)),
        "requests": float(load.get("requests", 0)),
    }
    if "queue" in doc:
        out["lost"] = float(doc["queue"].get("lost", 0))
    else:
        out["lost"] = float(load.get("lost", 0))
    if "compiles" in doc:
        out["recompiles"] = float(doc["compiles"].get("steady", 0))
    else:
        out["recompiles"] = float(load.get("recompiles", 0))
    # Pulse alert count (artifact "alerts" section, obs/pulse.py): set
    # ONLY when the artifact carries the section — a baseline from
    # before the pulse engine (or with pulse disabled) promised
    # nothing, and ``compare`` skips count metrics the baseline never
    # recorded.
    alerts = doc.get("alerts")
    if isinstance(alerts, dict) and isinstance(
            alerts.get("total"), (int, float)):
        out["alerts_total"] = float(alerts["total"])
    # The per-stage waterfall budgets (artifact "stages" section:
    # {stage: {p50_us, p95_us, p99_us, count}} — route.bench /
    # serve.bench schema): p95 per stage is the gated quantity.
    stages = doc.get("stages")
    if isinstance(stages, dict):
        out["stages"] = {
            str(name): float(v.get("p95_us", 0.0)
                             if isinstance(v, dict) else v)
            for name, v in stages.items()}
    # The cost-section roofline rows (artifact "cost": {"rows": [...]},
    # obs/costmodel.py): achieved GB/s moved per engine x mode x rung —
    # the utilization-regression gate's surface. Explicit dispatches=0
    # rows (a warmed rung the traffic skipped — present since ot-scope
    # so trend diffs never read omission as coverage) are NOT gate
    # material: "no traffic at this rung this run" must gate nothing,
    # exactly as the row's former absence did.
    cost = doc.get("cost")
    if isinstance(cost, dict) and isinstance(cost.get("rows"), list):
        out["cost"] = {
            f"{r.get('engine')}|{r.get('mode')}|r{r.get('rung')}"
            f"|nr{r.get('nr', 0)}":
                float(r.get("achieved_gbps", 0.0))
            for r in cost["rows"]
            if isinstance(r, dict) and float(r.get("dispatches", 1)) > 0}
    return out


def parse_tolerances(spec: str | None) -> dict:
    """``p95_ms=2.0,goodput_gbps=0.5`` -> overrides merged over the
    defaults. Unknown metric names are rejected (a typo'd override that
    silently kept the default would gate the wrong thing)."""
    tol = dict(DEFAULT_TOLERANCES)
    for tok in (spec or "").split(","):
        tok = tok.strip()
        if not tok:
            continue
        name, sep, val = tok.partition("=")
        name = name.strip()
        if not sep or name not in DEFAULT_TOLERANCES:
            raise ValueError(
                f"bad --slo-tolerance token {tok!r} "
                f"(known: {', '.join(sorted(DEFAULT_TOLERANCES))})")
        tol[name] = max(float(val), 0.0)
    return tol


def compare(baseline: dict, candidate: dict,
            tolerances: dict | None = None) -> list[str]:
    """Every SLO the candidate violates, as human-readable one-liners
    (empty list = the gate is green). ``baseline``/``candidate`` are
    ``extract`` outputs (call it first on raw artifacts)."""
    tol = dict(DEFAULT_TOLERANCES)
    tol.update(tolerances or {})
    failures: list[str] = []
    for name, t in sorted(tol.items()):
        if name in ("stage_p95_us", "cost_gbps"):
            continue  # the per-stage / per-row loops below consume them
        base = baseline.get(name, 0.0)
        cand = candidate.get(name, 0.0)
        if not isinstance(base, (int, float)) or base <= 0:
            continue  # nothing promised (e.g. a zero-latency stub row)
        if name in _HIGHER_IS_BETTER:
            floor = base * (1.0 - t)
            if cand < floor:
                failures.append(
                    f"{name}: {cand:g} < {floor:g} "
                    f"(baseline {base:g}, tolerance -{t:.0%})")
        else:
            ceil = base * (1.0 + t)
            if cand > ceil:
                failures.append(
                    f"{name}: {cand:g} > {ceil:g} "
                    f"(baseline {base:g}, tolerance +{t:.0%})")
    for name in COUNT_METRICS:
        if name not in baseline:
            # Absent = the baseline never promised this count (e.g. a
            # pre-pulse artifact has no alerts_total). The classic four
            # are always present in extract()'s output, so this skip
            # only ever applies to later-added counts.
            continue
        base = baseline.get(name, 0.0)
        cand = candidate.get(name, 0.0)
        if cand > base:
            failures.append(
                f"{name}: {cand:g} > baseline {base:g} "
                "(count metric: no tolerance)")
    # The per-stage budgets: a regression here NAMES the stage that
    # moved (wire vs device vs queue), which is the whole reason the
    # waterfall exists. Stages only the candidate has are new work and
    # gate nothing; stages only the baseline has went to zero — fine.
    st = tol.get("stage_p95_us", 0.0)
    base_stages = baseline.get("stages") or {}
    cand_stages = candidate.get("stages") or {}
    for name in sorted(base_stages):
        base = base_stages.get(name, 0.0)
        cand = cand_stages.get(name, 0.0)
        if base <= 0:
            continue
        ceil = base * (1.0 + st)
        if cand > ceil:
            failures.append(
                f"stage:{name}: p95 {cand:g}µs > {ceil:g}µs "
                f"(baseline {base:g}µs, tolerance +{st:.0%}) — "
                "this stage moved")
    # The utilization budgets: achieved GB/s moved per engine x rung
    # (lower is worse — a drop past tolerance is a device-efficiency
    # regression that NAMES its engine x rung). Rows only the candidate
    # has are new coverage; rows only the baseline has saw no traffic
    # this run — neither gates.
    ct = tol.get("cost_gbps", 0.0)
    base_cost = baseline.get("cost") or {}
    cand_cost = candidate.get("cost") or {}
    for name in sorted(base_cost):
        base = base_cost.get(name, 0.0)
        cand = cand_cost.get(name)
        if base <= 0 or cand is None:
            continue
        floor = base * (1.0 - ct)
        if cand < floor:
            failures.append(
                f"cost:{name}: achieved {cand:g} GB/s moved < {floor:g} "
                f"(baseline {base:g}, tolerance -{ct:.0%}) — this "
                "engine x rung's device utilization moved")
    return failures


def render(baseline: dict, candidate: dict, failures: list[str],
           out=None, prefix: str = "# slo") -> None:
    """The per-metric gate table, pass or fail, repo-`#`-line style."""
    out = out if out is not None else sys.stdout  # bound at CALL time
    names = sorted((set(DEFAULT_TOLERANCES) | set(COUNT_METRICS))
                   - {"stage_p95_us", "cost_gbps"})
    for name in names:
        base = baseline.get(name, 0.0)
        cand = candidate.get(name, 0.0)
        bad = any(f.startswith(name + ":") for f in failures)
        out.write(f"{prefix}: {name:<14} baseline={base:<10g} "
                  f"run={cand:<10g} {'FAIL' if bad else 'ok'}\n")
    base_stages = baseline.get("stages") or {}
    cand_stages = candidate.get("stages") or {}
    for name in sorted(base_stages):
        bad = any(f.startswith(f"stage:{name}:") for f in failures)
        out.write(f"{prefix}: stage:{name:<14} "
                  f"baseline={base_stages.get(name, 0.0):<10g} "
                  f"run={cand_stages.get(name, 0.0):<10g} "
                  f"{'FAIL' if bad else 'ok'}\n")
    base_cost = baseline.get("cost") or {}
    cand_cost = candidate.get("cost") or {}
    for name in sorted(base_cost):
        if cand_cost.get(name) is None:
            continue  # no traffic at this engine x rung this run
        bad = any(f.startswith(f"cost:{name}:") for f in failures)
        out.write(f"{prefix}: cost:{name:<18} "
                  f"baseline={base_cost.get(name, 0.0):<10g} "
                  f"run={cand_cost.get(name, 0.0):<10g} "
                  f"{'FAIL' if bad else 'ok'}\n")
    for f in failures:
        out.write(f"{prefix}: REGRESSION {f}\n")


def gate(baseline_path: str, candidate_doc: dict,
         tolerance_spec: str | None = None, out=None) -> int:
    """Load the baseline artifact, compare, render, return the exit
    code (0 green / 1 regression) — the ``serve.bench --slo`` body."""
    out = out if out is not None else sys.stdout
    with open(baseline_path, encoding="utf-8") as fh:
        baseline = extract(json.load(fh))
    candidate = extract(candidate_doc)
    failures = compare(baseline, candidate,
                       parse_tolerances(tolerance_spec))
    render(baseline, candidate, failures, out=out)
    if failures:
        out.write(f"# slo: GATE FAILED against {baseline_path}: "
                  f"{len(failures)} regression(s)\n")
        return 1
    out.write(f"# slo: gate passed against {baseline_path}\n")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m our_tree_tpu.obs.slo",
        description="SLO regression gate between two SERVE_r*.json "
                    "artifacts (docs/OBSERVABILITY.md)")
    ap.add_argument("baseline", help="the committed promise")
    ap.add_argument("candidate", help="the run under test (artifact or "
                                      "bench JSON line file)")
    ap.add_argument("--tolerance", default=None, metavar="SPEC",
                    help="per-metric overrides, e.g. "
                         "'p95_ms=2.0,goodput_gbps=0.5' (fractions of "
                         "the baseline value)")
    args = ap.parse_args(argv)
    with open(args.candidate, encoding="utf-8") as fh:
        cand = json.load(fh)
    return gate(args.baseline, cand, args.tolerance)


if __name__ == "__main__":
    sys.exit(main())
