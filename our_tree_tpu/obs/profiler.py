"""Windowed device profiling: the serve stack's ONE capture seam.

PR 14's roofline can say *which* engine x rung underperforms; this
module answers *why inside the window* — a bounded capture armable
while the service runs, landing evidence in the same OT_TRACE_DIR run
layout every other obs artifact uses. Three arming paths share this one
implementation (the dedup satellite: ``harness.bench --profile`` and
``scripts/profile_ctr.py --capture`` route here too, so there is no
second capture stack to drift):

* ``serve.bench --profile-window <start_s>:<dur_s>`` — the CLI window;
* ``GET /profilez?seconds=N`` on the status endpoint (serve/status.py;
  the router FEDERATES it per backend, route/status.py) — the live
  operator window. Overlapping captures are refused with 409: two
  interleaved ``jax.profiler`` sessions corrupt each other, and two
  interleaved delta windows would misattribute each other's traffic;
* the incident flight recorder (``OT_PROFILE_ON_INCIDENT=<seconds>``,
  obs/incident.py) — an SLO breach / watchdog kill arms one capture per
  incident cooldown, so the evidence window covers the aftermath
  without a capture storm.

Two capture tiers, resolved per window:

* **jax** — ``jax.profiler.start_trace`` into a per-window directory
  beside the summary (TensorBoard/Perfetto-loadable XLA + host trace:
  the kernel-internal view the pipelined-AES paper's round-stage
  analysis needs). Tried first unless ``OT_PROFILE_TIER=stack``.
* **stack** — the native/CPU fallback: a sampler thread walks
  ``watchdog.current_stacks()`` (the SAME all-thread frame machinery
  the watchdog's expiry dump uses) at ``OT_PROFILE_HZ`` and aggregates
  stack signatures, so a host-tier server profiles too.

Whatever the tier, every window also snapshots the metrics registry at
open and close and summarises the DELTA: per-(engine, mode, rung, nr)
dispatches and device time (the per-rung kernel wall), per-stage
count/time, and the busy-vs-device split (transfer+host vs compute).
The summary lands as ``profile-<pid>-<tok>-<n>.json`` in the run dir;
``obs.report --profile`` joins it against the run dir's ``cost-*.json``
records (``crosscheck``) so modeled utilization gets a measured
in-window cross-check, and ``serve.bench`` stamps the same join into
the artifact's ``profile`` section.

Constitution: never wedges the caller (capture start/stop failures
degrade tiers or drop the window, counted), one window at a time
(``CaptureBusy``), and a window open at drain/exit still closes cleanly
(``finish``/atexit) so its summary is never lost.
"""

from __future__ import annotations

import atexit
import glob
import json
import os
import threading
import time
import uuid

from . import costmodel, metrics, trace

KIND = "ot-profile"
VERSION = 1

#: Summary schema (``validate_summary`` — what ``obs.report --profile
#: --check`` and the CI mid-drive curl gate).
REQUIRED_KEYS = ("kind", "v", "run", "pid", "t0_us", "t1_us", "seconds",
                 "tier", "armed_by", "rungs", "stages")
TIERS = ("jax", "stack")
#: The closed arming vocabulary (who opened the window).
ARMED_BY = ("cli", "http", "incident", "sweep", "api", "alert")


class CaptureBusy(RuntimeError):
    """A capture window is already open (one at a time; /profilez
    answers 409)."""


class CaptureDisabled(RuntimeError):
    """Tracing is off: there is no run layout for artifacts to land in
    (/profilez answers 503)."""


_LOCK = threading.Lock()
_ACTIVE: dict | None = None
#: Closes IN FLIGHT: _ACTIVE clears at the instant the window closes
#: (so a new window can arm), but the close work — jax flush, summary
#: write — may still be running; wait_idle()/finish() wait this out so
#: a caller never reads last_summary()/the run dir mid-close.
_CLOSING = 0
_SEQ = 0
_PROC = uuid.uuid4().hex[:8]
_LAST: dict | None = None
_DROPPED = 0
_ATEXIT = False


def sample_hz() -> float:
    """Stack-tier sampling rate (``OT_PROFILE_HZ``, default 25)."""
    try:
        return min(max(float(os.environ.get("OT_PROFILE_HZ", 25) or 25),
                       1.0), 200.0)
    except ValueError:
        return 25.0


def tier_override() -> str | None:
    v = str(os.environ.get("OT_PROFILE_TIER", "") or "").lower()
    return v if v in TIERS else None


def incident_seconds() -> float:
    """``OT_PROFILE_ON_INCIDENT``: capture length armed by the incident
    recorder (0/unset = off)."""
    try:
        return max(float(os.environ.get("OT_PROFILE_ON_INCIDENT", 0) or 0),
                   0.0)
    except ValueError:
        return 0.0


def alert_seconds() -> float:
    """``OT_PROFILE_ON_ALERT``: capture length armed by a pulse alert
    (obs/pulse.py; 0/unset = off). A separate knob from the incident
    one: warn-severity alerts never dump a bundle but may still want
    an evidence window."""
    try:
        return max(float(os.environ.get("OT_PROFILE_ON_ALERT", 0) or 0),
                   0.0)
    except ValueError:
        return 0.0


class _StackSampler(threading.Thread):
    """The native-tier capture: periodic all-thread stack signatures,
    aggregated in memory (bounded: at most ``_MAX_KEYS`` distinct
    signatures; overflow folds into an ``"(other)"`` bucket)."""

    _MAX_KEYS = 256

    def __init__(self, hz: float):
        super().__init__(daemon=True, name="ot-profile-sampler")
        self._period = 1.0 / hz
        # NOT named _stop: threading.Thread has a private _stop METHOD
        # that join() calls — shadowing it with an Event breaks join.
        self._halt = threading.Event()
        self.samples = 0
        self.counts: dict[str, int] = {}

    def run(self) -> None:
        from ..resilience import watchdog

        me = threading.get_ident()
        while not self._halt.is_set():
            try:
                for ident, (name, frames) in watchdog.current_stacks(
                        depth=4).items():
                    if ident == me:
                        continue
                    key = f"{name}: " + " < ".join(frames)
                    if (key not in self.counts
                            and len(self.counts) >= self._MAX_KEYS):
                        key = "(other)"
                    self.counts[key] = self.counts.get(key, 0) + 1
                self.samples += 1
            except Exception:  # noqa: BLE001 - sampling must never wedge
                pass
            self._halt.wait(self._period)

    def stop(self) -> dict:
        self._halt.set()
        self.join(timeout=2.0)
        return dict(self.counts)


def _try_jax_start(capture_dir: str) -> bool:
    """Start a jax.profiler trace; False on ANY failure (no jax, an
    unsupported platform, a profiler already running elsewhere) — the
    stack tier stands in."""
    try:
        import jax

        os.makedirs(capture_dir, exist_ok=True)
        jax.profiler.start_trace(capture_dir)
        return True
    except Exception:  # noqa: BLE001 - degrade to the stack tier
        return False


def _jax_stop() -> None:
    try:
        import jax

        jax.profiler.stop_trace()
    except Exception:  # noqa: BLE001 - a failed stop loses the capture,
        pass           # never the summary


def active() -> dict | None:
    """The open window's public view (seq, tier, armed_by, t0_us), or
    None — the /profilez 409 body."""
    entry = _ACTIVE
    if entry is None:
        return None
    return {"seq": entry["seq"], "tier": entry["tier"],
            "armed_by": entry["armed_by"], "t0_us": entry["t0_us"],
            "seconds": entry["seconds"]}


def start_window(seconds: float | None = None, armed_by: str = "api",
                 jax_dir: str | None = None) -> dict:
    """Open ONE capture window.

    ``seconds`` set: a closer thread ends the window after that long
    (the bounded-window contract); None: the window stays open until
    ``stop_window``/``finish`` (the sweep-capture shape). ``jax_dir``
    overrides the jax tier's artifact directory (``harness.bench
    --profile DIR`` keeps its operator-visible path) — and is the one
    case allowed with tracing OFF: the jax artifact still lands in the
    caller's dir, only the run-layout summary is skipped (there is no
    run layout to put it in). Raises ``CaptureBusy`` when a window is
    open and ``CaptureDisabled`` when tracing is off with no explicit
    dir. Returns {seq, tier, path, jax_dir?}.
    """
    global _ACTIVE, _SEQ, _ATEXIT
    if not trace.enabled() and jax_dir is None:
        raise CaptureDisabled("profiling needs the run layout: set "
                              "OT_TRACE_DIR")
    with _LOCK:
        if _ACTIVE is not None:
            raise CaptureBusy(
                f"capture {_ACTIVE['seq']} ({_ACTIVE['armed_by']}) is "
                "already in progress")
        d = None
        if trace.enabled():
            trace.ensure_run()
            d = trace.run_dir()
            os.makedirs(d, exist_ok=True)
        _SEQ += 1
        seq = _SEQ
        stem = f"profile-{os.getpid()}-{_PROC}-{seq}"
        entry = {
            "seq": seq, "armed_by": str(armed_by),
            "seconds": (float(seconds) if seconds else None),
            "run": trace.run_id(), "dir": d,
            "path": (os.path.join(d, stem + ".json") if d else None),
            "sampler": None, "jax_dir": None,
        }
        capture_dir = jax_dir or os.path.join(d, stem + ".jaxtrace")
        forced = tier_override()
        if forced != "stack" and _try_jax_start(capture_dir):
            entry["tier"] = "jax"
            entry["jax_dir"] = capture_dir
        else:
            sampler = _StackSampler(sample_hz())
            sampler.start()
            entry["tier"] = "stack"
            entry["sampler"] = sampler
        # t0 and the opening snapshot are stamped AFTER the capture
        # backend is live: jax.profiler's first start_trace pays a
        # seconds-scale one-time init, and that setup is neither
        # captured time nor captured traffic.
        entry["t0_us"] = trace.now_us()
        entry["t0_mono"] = time.monotonic()
        entry["before"] = metrics.snapshot()
        _ACTIVE = entry
        if not _ATEXIT:
            _ATEXIT = True
            atexit.register(finish)
    trace.point("profile-window", seq=seq, armed_by=str(armed_by),
                tier=entry["tier"], seconds=entry["seconds"])
    if seconds:
        threading.Thread(target=_close_after, args=(seconds, seq),
                         daemon=True, name="ot-profile-close").start()
    out = {"seq": seq, "tier": entry["tier"], "path": entry["path"]}
    if entry["jax_dir"]:
        out["jax_dir"] = entry["jax_dir"]
    return out


def _close_after(seconds: float, seq: int) -> None:
    time.sleep(max(seconds, 0.0))
    stop_window(expected_seq=seq)


def stop_window(expected_seq: int | None = None) -> str | None:
    """Close the open window and write its summary; returns the summary
    path (None when no window is open, or — with ``expected_seq`` — when
    the open window is a DIFFERENT one: the closer thread of a window
    already ended early by drain must not close its successor)."""
    global _ACTIVE, _CLOSING, _LAST, _DROPPED
    with _LOCK:
        entry = _ACTIVE
        if entry is None or (expected_seq is not None
                             and entry["seq"] != expected_seq):
            return None
        _ACTIVE = None
        _CLOSING += 1
    try:
        # The window CLOSES here: t1/seconds (and the closing metrics
        # snapshot) are stamped before the capture backend is stopped —
        # jax.profiler.stop_trace may spend seconds flushing its
        # artifact, and that flush is neither captured time nor
        # captured traffic.
        entry["t1_us"] = trace.now_us()
        entry["measured_s"] = round(
            time.monotonic() - entry["t0_mono"], 3)
        after = metrics.snapshot()
        stacks: dict = {}
        samples = 0
        if entry["tier"] == "jax":
            _jax_stop()
        elif entry["sampler"] is not None:
            stacks = entry["sampler"].stop()
            samples = entry["sampler"].samples
        if entry["path"] is None:
            return None  # explicit-dir capture with tracing off: the
            #              jax artifact is the whole product
        try:
            doc = _summarise(entry, after, stacks, samples)
            with open(entry["path"], "w", encoding="utf-8") as fh:
                json.dump(doc, fh, separators=(",", ":"),
                          sort_keys=True)
                fh.write("\n")
            _LAST = doc  # ot-san: owner=gil-ref-swap
            trace.point("profile-captured", seq=entry["seq"],
                        tier=entry["tier"],
                        file=os.path.basename(entry["path"]))
            metrics.counter("profile_captures", kind=entry["tier"])
            return entry["path"]
        except Exception:  # noqa: BLE001 - a lost summary must not take
            _DROPPED += 1  # ot-san: owner=gil-counter
            return None
    finally:
        with _LOCK:
            _CLOSING -= 1


def _hist_deltas(before: dict, after: dict, names: tuple) -> dict:
    """stage -> {count, sum_us} deltas for the stage histograms."""
    out: dict[str, dict] = {}
    for name in names:
        for key, h1 in after.get("hists", {}).items():
            if not key.startswith(name + "{"):
                continue
            stage = None
            for part in key[len(name) + 1:-1].split(","):
                k, _, v = part.partition("=")
                if k == "stage":
                    stage = v
            if stage is None:
                continue
            h0 = before.get("hists", {}).get(key, {})
            dc = int(h1.get("count", 0)) - int(h0.get("count", 0))
            ds = float(h1.get("sum", 0.0)) - float(h0.get("sum", 0.0))
            if dc <= 0:
                continue
            agg = out.setdefault(stage, {"count": 0, "sum_us": 0.0})
            agg["count"] += dc
            agg["sum_us"] = round(agg["sum_us"] + ds, 1)
    return out


def _counter_delta(before: dict, after: dict, name: str) -> float:
    tot = 0.0
    for key, v in after.get("counters", {}).items():
        if key == name or key.startswith(name + "{"):
            tot += v - before.get("counters", {}).get(key, 0.0)
    return tot


def _summarise(entry: dict, after: dict, stacks: dict,
               samples: int) -> dict:
    before = entry["before"]
    disp0 = costmodel.series_by_key(before.get("counters", {}),
                                    "serve_rung_dispatches")
    disp1 = costmodel.series_by_key(after.get("counters", {}),
                                    "serve_rung_dispatches")
    dev0 = costmodel.series_by_key(before.get("counters", {}),
                                   "serve_rung_device_us")
    dev1 = costmodel.series_by_key(after.get("counters", {}),
                                   "serve_rung_device_us")
    rungs = []
    for key in sorted(disp1):
        d = disp1[key] - disp0.get(key, 0.0)
        if d <= 0:
            continue
        rungs.append({
            "engine": key[0], "mode": key[1], "rung": key[2],
            "nr": key[3], "dispatches": int(d),
            "device_us": int(dev1.get(key, 0.0) - dev0.get(key, 0.0)),
        })
    busy_us = _counter_delta(before, after, "serve_lane_busy_us")
    device_us = _counter_delta(before, after, "serve_device_us")
    doc = {
        "kind": KIND, "v": VERSION, "run": entry["run"],
        "pid": os.getpid(), "proc": _PROC, "seq": entry["seq"],
        "t0_us": entry["t0_us"],
        "t1_us": entry.get("t1_us", trace.now_us()),
        "seconds": entry.get("measured_s",
                             round(time.monotonic() - entry["t0_mono"],
                                   3)),
        "armed_by": entry["armed_by"], "tier": entry["tier"],
        "rungs": rungs,
        "stages": _hist_deltas(before, after,
                               ("serve_stage_us", "route_stage_us")),
        # The transfer-vs-compute split over the window: lane busy wall
        # vs the device/engine-compute share of it.
        "busy_us": int(busy_us),
        "device_us": int(device_us),
        "host_us": int(max(busy_us - device_us, 0.0)),
    }
    if entry["jax_dir"]:
        doc["jax_dir"] = os.path.basename(entry["jax_dir"])
    if stacks:
        top = sorted(stacks.items(), key=lambda kv: -kv[1])[:20]
        doc["samples"] = samples
        doc["stacks"] = [{"frames": k, "count": c} for k, c in top]
    return doc


def finish(timeout_s: float = 5.0) -> str | None:
    """Close any open window NOW (drain/exit path) and wait for a
    closer already mid-close. Returns the summary path when this call
    did the closing."""
    path = stop_window()
    deadline = time.monotonic() + timeout_s
    while ((_ACTIVE is not None or _CLOSING)
           and time.monotonic() < deadline):
        time.sleep(0.01)
    return path


def wait_idle(timeout_s: float = 10.0) -> bool:
    """True once no window is open AND no close is in flight (the
    bench's pre-artifact barrier: a CLI window still capturing at
    drive end closes via its own closer; this waits out both the
    window and its summary write)."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if _ACTIVE is None and not _CLOSING:
            return True
        time.sleep(0.02)
    return _ACTIVE is None and not _CLOSING


class _SweepCapture:
    """Context manager for whole-run captures (``harness.bench
    --profile``, ``scripts/profile_ctr.py --capture``): opens an
    unbounded window on enter, closes it on exit. Start failures
    (window busy, tracing off) degrade to a no-op — a profile flag must
    never fail the sweep it observes."""

    def __init__(self, jax_dir: str | None = None,
                 armed_by: str = "sweep"):
        self._jax_dir = jax_dir
        self._armed_by = armed_by
        self._seq: int | None = None

    def __enter__(self):
        try:
            self._seq = start_window(None, armed_by=self._armed_by,
                                     jax_dir=self._jax_dir)["seq"]
        except (CaptureBusy, CaptureDisabled):
            self._seq = None
        return self

    def __exit__(self, *exc):
        if self._seq is not None:
            stop_window(expected_seq=self._seq)
        return False


def sweep_capture(jax_dir: str | None = None,
                  armed_by: str = "sweep") -> _SweepCapture:
    return _SweepCapture(jax_dir, armed_by)


def last_summary() -> dict | None:
    return _LAST


def profilez(seconds: float) -> tuple[int, dict]:
    """The /profilez body: (HTTP status, JSON doc). 200 = armed, 409 =
    a window is open, 503 = tracing off (no run layout)."""
    try:
        secs = min(max(float(seconds), 0.05), 120.0)
    except (TypeError, ValueError):
        secs = 1.0
    try:
        out = start_window(secs, armed_by="http")
    except CaptureBusy as e:
        return 409, {"error": str(e), "active": active()}
    except CaptureDisabled as e:
        return 503, {"error": str(e)}
    return 200, {"armed": True, "seconds": secs, **out}


def on_incident(reason: str) -> None:
    """The incident recorder's arming hook (called AFTER a bundle
    dumps, so the trigger cooldown — one bundle per incident — is also
    the capture cooldown): arm one window of OT_PROFILE_ON_INCIDENT
    seconds; a window already open or any failure is silently fine —
    an incident capture must never create a second incident. Arming
    happens on a short-lived daemon thread: trigger() fires from the
    serve event loop's thread, and the capture backend's startup cost
    (jax.profiler init) must not stall the loop mid-incident."""
    secs = incident_seconds()
    if not secs:
        return

    def _arm():
        try:
            start_window(secs, armed_by="incident")
        except Exception:  # noqa: BLE001 - never-raises on this path
            pass

    threading.Thread(target=_arm, daemon=True,
                     name="ot-profile-incident").start()


def on_alert(rule: str) -> None:
    """The pulse engine's arming hook (obs/pulse.py ``_fire``): arm one
    window of OT_PROFILE_ON_ALERT seconds over the alert's aftermath.
    Same contract as ``on_incident`` — a window already open or any
    failure is silently fine, and arming happens off the caller's
    thread (the pulse tick must not stall on jax.profiler init). The
    pulse edge-trigger is the storm guard: a sustained condition fires
    once, so at most one window arms per alert edge."""
    secs = alert_seconds()
    if not secs:
        return

    def _arm():
        try:
            start_window(secs, armed_by="alert")
        except Exception:  # noqa: BLE001 - never-raises on this path
            pass

    threading.Thread(target=_arm, daemon=True,
                     name="ot-profile-alert").start()


# ---------------------------------------------------------------------------
# Reading summaries (report --profile, the CI mid-drive gate).
# ---------------------------------------------------------------------------


def list_summaries(run_dir: str) -> list[str]:
    """Summary paths in one run dir, capture order (pid-token-seq
    naming orders within a process; mtime breaks ties across)."""
    paths = [p for p in glob.glob(os.path.join(run_dir, "profile-*.json"))
             if os.path.isfile(p)]

    def _key(p):
        try:
            return (os.path.getmtime(p), p)
        except OSError:
            return (0.0, p)

    return sorted(paths, key=_key)


def load_summary(path: str) -> dict | None:
    try:
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)
        return doc if isinstance(doc, dict) else None
    except (OSError, ValueError):
        return None


def validate_summary(doc: dict | None) -> list[str]:
    """Schema violations as human-readable strings (empty = valid)."""
    if not isinstance(doc, dict):
        return ["summary is not a JSON object"]
    out = []
    for k in REQUIRED_KEYS:
        if k not in doc:
            out.append(f"missing required key {k!r}")
    if doc.get("kind") != KIND:
        out.append(f"kind is {doc.get('kind')!r}, want {KIND!r}")
    if doc.get("tier") not in TIERS:
        out.append(f"tier {doc.get('tier')!r} outside {TIERS}")
    if doc.get("armed_by") not in ARMED_BY:
        out.append(f"armed_by {doc.get('armed_by')!r} outside {ARMED_BY}")
    rungs = doc.get("rungs")
    if not isinstance(rungs, list):
        out.append("rungs is not a list")
    else:
        for i, r in enumerate(rungs):
            if not isinstance(r, dict) or not {
                    "engine", "mode", "rung", "dispatches",
                    "device_us"} <= set(r):
                out.append(f"rungs[{i}] malformed")
    if not isinstance(doc.get("stages"), dict):
        out.append("stages is not an object")
    return out


def crosscheck(doc: dict, records, ceiling_gbps: float | None) -> dict:
    """The measured-vs-modeled join for one capture window: per rung,
    modeled HBM bytes (obs/costmodel.py) x in-window dispatches over
    in-window device time -> achieved GB/s moved inside the window,
    with utilization against the ceiling — the cross-check that says
    whether the roofline's modeled utilization holds when you actually
    look."""
    by_key = {}
    for rec in records or ():
        key = (rec.get("engine"), rec.get("mode"), int(rec.get("rung", 0)),
               int(rec.get("nr", 0)))
        by_key.setdefault(key, rec)
    rows = []
    for r in doc.get("rungs", []):
        key = (r.get("engine"), r.get("mode"), int(r.get("rung", 0)),
               int(r.get("nr", 0)))
        rec = by_key.get(key)
        dus = int(r.get("device_us", 0))
        row = {"engine": key[0], "mode": key[1], "rung": key[2],
               "nr": key[3], "dispatches": int(r.get("dispatches", 0)),
               "device_s": round(dus / 1e6, 6),
               "modeled_dispatch_bytes": (int(rec["hbm_bytes"])
                                          if rec else None)}
        if rec and dus > 0:
            gbps = (float(rec["hbm_bytes"]) * row["dispatches"]
                    / 1e9 / (dus / 1e6))
            row["window_gbps"] = round(gbps, 6)
            row["utilization"] = (round(gbps / ceiling_gbps, 6)
                                  if ceiling_gbps else None)
        else:
            row["window_gbps"] = None
            row["utilization"] = None
        rows.append(row)
    return {"ceiling_gbps": ceiling_gbps, "rows": rows}


def dropped() -> int:
    return _DROPPED


def reset_for_tests() -> None:
    """Close any open window and clear the last summary. ``_SEQ`` is
    deliberately NOT reset: a bounded window abandoned here may still
    have its closer thread sleeping, and a later window reusing its
    seq would match that stale closer's ``expected_seq`` and be closed
    mid-capture — monotonic seqs are what make stale closers inert."""
    global _ACTIVE, _LAST, _DROPPED
    entry = _ACTIVE
    if entry is not None:
        if entry["tier"] == "jax":
            _jax_stop()
        elif entry["sampler"] is not None:
            entry["sampler"].stop()
    _ACTIVE = None
    _LAST = None
    _DROPPED = 0
