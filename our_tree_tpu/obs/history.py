"""The perf-history ledger: every committed ``*_r*.json`` as one trend.

The repo root carries 20+ measured artifacts — ``BENCH_r*`` (offline
engine GB/s), ``SERVE_r*`` (the serving drives), ``ROUTE_r*`` (the
routed fleet), ``STREAM_r*`` (the chunked-transfer chaos drive),
``SESSION_r*`` (the stateful rc4 session drive),
``MULTICHIP_r*`` (device health) — each one a point on a
trajectory nothing machine-readable ever connected: the SLO gate
compares one run against ONE chosen baseline, so a regression that
lands together with a new baseline (or that only shows against the
best round three PRs ago) slips through. This module parses every
committed artifact into one schema'd trend series (the multicore
throughput study's scaling-trend methodology, arxiv 1403.7295, encoded
as a gate):

* ``python -m our_tree_tpu.obs.history`` renders the per-family
  trajectory — goodput / p95 / utilization per round, grouped into
  WORKLOAD CLASSES (modes x sizes x engine x lanes for serve; the
  drive config is part of the series identity, so the mixed-AEAD drive
  never gates against the 4 MiB CTR lineage);
* ``--check`` gates each class's HEAD artifact (highest round) against
  the class's **best-ever** — not just the last baseline — with
  per-metric tolerances: goodput-like metrics may sit below best-ever
  by at most the tolerance, count metrics (lost, recompiles,
  mismatches, errors) may never exceed the class minimum. A failure
  names the artifact and the metric that moved.

CI runs ``--check`` over the committed set (the obs job), so a
silently-regressing committed artifact fails the PR that commits it.

This module is stdlib-only (though ``python -m our_tree_tpu.obs.history``
pays the package import like every other CLI here), read-only, and
tolerant of schema drift: an artifact whose shape predates a section
simply contributes fewer metrics (absent is "nothing promised", never
zero).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

#: ``FAMILY_rNN[_variant].json`` at the repo root.
ARTIFACT_RE = re.compile(
    r"^([A-Z]+)_r(\d+)(?:_([A-Za-z0-9_]+))?\.json$")

#: Higher-is-better trend metrics and how far below best-ever the head
#: may sit (fraction of best). Wide enough for same-host rerun noise on
#: the shared CPU container; tight enough that an order-of-magnitude
#: rot (the failure mode trend diffs exist for) can never ride a new
#: artifact in.
DEFAULT_TOLERANCES = {
    "gbps": 0.25,          # BENCH offline GB/s
    "goodput_gbps": 0.35,  # serve/route payload goodput
    "utilization": 0.50,   # device-time utilization (noisy on CPU)
    "devices": 0.0,        # multichip healthy-device count
    "ok": 0.0,             # multichip all-healthy flag (1/0)
    "session_hit_rate": 0.05,  # keystream prefetch hit rate (SESSION)
}

#: Zero-noise count metrics: the head may never exceed the class's
#: best-ever (minimum) — a lineage that ever achieved 0 lost requests
#: has promised 0 forever.
COUNT_METRICS = ("lost", "recompiles", "mismatches", "errors_total",
                 "alerts_total")

#: Latency percentiles are RENDERED but not gated by default: they are
#: config-sensitive in exactly the way the class key cannot fully pin
#: (request counts, concurrency), and same-config latency gating is
#: the SLO gate's job (obs/slo.py).
RENDER_ONLY = ("p50_ms", "p95_ms", "p99_ms")


def _num(v) -> float | None:
    return float(v) if isinstance(v, (int, float)) else None


def _extract_servelike(doc: dict) -> dict:
    """SERVE_r* / ROUTE_r* artifacts share the load/queue/compiles
    shape (obs/slo.py's extract is the same contract; duplicated
    minimally here because history also reads families slo never
    sees)."""
    load = doc.get("load") or {}
    out: dict = {}
    for k in ("goodput_gbps", "p50_ms", "p95_ms", "p99_ms"):
        v = _num(load.get(k))
        if v is not None:
            out[k] = v
    errors = load.get("errors")
    if isinstance(errors, dict):
        out["errors_total"] = float(sum(errors.values()))
    v = _num(load.get("mismatches"))
    if v is not None:
        out["mismatches"] = v
    q = doc.get("queue") or {}
    v = _num(q.get("lost"))
    if v is not None:
        out["lost"] = v
    comp = doc.get("compiles") or {}
    v = _num(comp.get("steady"))
    if v is not None:
        out["recompiles"] = v
    dev = doc.get("device") or {}
    v = _num(dev.get("utilization"))
    if v is not None:
        out["utilization"] = v
    # Pulse alert counts (obs/pulse.py): only promised when the round
    # actually ran an engine — an artifact without the section (older
    # rounds, pulse disabled) promises nothing, same as any absent
    # metric.
    alerts = doc.get("alerts")
    if isinstance(alerts, dict):
        v = _num(alerts.get("total"))
        if v is not None:
            out["alerts_total"] = v
    return out


def _extract(family: str, doc: dict) -> dict:
    if family == "BENCH":
        parsed = doc.get("parsed") or {}
        out = {}
        if parsed.get("unit") == "GB/s" and _num(parsed.get("value")):
            out["gbps"] = float(parsed["value"])
        rc = _num(doc.get("rc"))
        if rc is not None:
            out["errors_total"] = rc
        return out
    if family == "MULTICHIP":
        out = {}
        v = _num(doc.get("n_devices"))
        if v is not None:
            out["devices"] = v
        if isinstance(doc.get("ok"), bool):
            out["ok"] = 1.0 if doc["ok"] else 0.0
        return out
    if family in ("SERVE", "ROUTE", "STREAM", "SESSION"):
        # STREAM (route.bench --transfer-sizes: the chunked-transfer
        # chaos drive) is servelike too — same load/queue/compiles
        # contract, plus a transfers section the class key pins below.
        # SESSION (serve.bench --sessions: the stateful rc4 drive) adds
        # the keystream prefetch hit rate as a gated gauge.
        out = _extract_servelike(doc)
        if family == "SESSION":
            sess = doc.get("sessions") or {}
            v = _num((sess.get("prefetch") or {}).get("hit_rate"))
            if v is not None:
                out["session_hit_rate"] = v
        return out
    return {}


def _series_class(family: str, doc: dict) -> str:
    """The workload-class half of a series' identity: two rounds only
    trend against each other when they drove the same shape of load.
    Config keys chosen so the real lineages line up (r03→r04→r07→r08
    share a class; the mixed-AEAD and tenant-heavy drives each get
    their own) without making every artifact a singleton."""
    c = doc.get("config") or {}
    if family in ("SERVE", "ROUTE", "STREAM", "SESSION"):
        modes = ",".join(c.get("modes") or ["ctr"])
        sizes = c.get("sizes") or ([c["size_bytes"]]
                                   if c.get("size_bytes") else [])
        parts = [f"modes={modes}",
                 f"sizes={','.join(str(s) for s in sizes)}",
                 f"engine={c.get('engine')}"]
        if family in ("SERVE", "SESSION"):
            parts.append(f"lanes={c.get('lanes')}")
        else:
            parts.append(f"backends={c.get('backends')}")
        if family == "STREAM":
            t = doc.get("transfers") or {}
            tsizes = t.get("sizes") or []
            parts.append(
                f"transfers={','.join(str(s) for s in tsizes)}")
        return ";".join(parts)
    return ""


def collect(root: str) -> list[dict]:
    """Every committed artifact as one trend record:
    {family, round, variant, file, series (family:variant@class),
    metrics, parsed} — sorted by (family, variant, round)."""
    records = []
    for path in sorted(glob.glob(os.path.join(root, "*_r*.json"))):
        m = ARTIFACT_RE.match(os.path.basename(path))
        if not m:
            continue
        family, rnd, variant = m.group(1), int(m.group(2)), m.group(3)
        try:
            with open(path, encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, ValueError) as e:
            records.append({
                "family": family, "round": rnd, "variant": variant,
                "file": os.path.basename(path), "series": family,
                "metrics": {}, "parsed": False,
                "error": f"unreadable: {e}"})
            continue
        if not isinstance(doc, dict):
            doc = {}
        metrics = _extract(family, doc)
        series = family + (f":{variant}" if variant else "")
        cls = _series_class(family, doc)
        if cls:
            series += f"@{cls}"
        records.append({
            "family": family, "round": rnd, "variant": variant,
            "file": os.path.basename(path), "series": series,
            "metrics": metrics, "parsed": bool(metrics)})
    records.sort(key=lambda r: (r["family"], r["variant"] or "",
                                r["round"]))
    return records


def parse_tolerances(spec: str | None) -> dict:
    """``goodput_gbps=0.5,gbps=0.1`` -> overrides merged over the
    defaults (same contract as obs/slo.py — unknown names rejected)."""
    tol = dict(DEFAULT_TOLERANCES)
    for tok in (spec or "").split(","):
        tok = tok.strip()
        if not tok:
            continue
        name, sep, val = tok.partition("=")
        name = name.strip()
        if not sep or name not in DEFAULT_TOLERANCES:
            raise ValueError(
                f"bad --tolerance token {tok!r} "
                f"(known: {', '.join(sorted(DEFAULT_TOLERANCES))})")
        tol[name] = max(float(val), 0.0)
    return tol


def check(records: list[dict],
          tolerances: dict | None = None) -> list[str]:
    """Best-ever gating: for each series, the HEAD (highest round) must
    hold every higher-is-better metric within tolerance of the series'
    best and every count metric at the series' minimum. Returns
    human-readable violations (empty = green). Unreadable artifacts
    are violations; artifacts with no extractable metrics (a schema
    this ledger does not know) are listed by render() but gate
    nothing."""
    tol = dict(DEFAULT_TOLERANCES)
    tol.update(tolerances or {})
    failures = [f"{r['file']}: {r['error']}"
                for r in records if r.get("error")]
    by_series: dict[str, list[dict]] = {}
    for r in records:
        if r["metrics"]:
            by_series.setdefault(r["series"], []).append(r)
    for series, rs in sorted(by_series.items()):
        head = max(rs, key=lambda r: r["round"])
        for name, t in sorted(tol.items()):
            vals = [(r["metrics"][name], r["file"]) for r in rs
                    if name in r["metrics"]]
            if not vals or name not in head["metrics"]:
                continue
            best, best_file = max(vals)
            floor = best * (1.0 - t)
            if head["metrics"][name] < floor:
                failures.append(
                    f"{series}: {name}: head {head['file']} "
                    f"{head['metrics'][name]:g} < {floor:g} "
                    f"(best-ever {best:g} in {best_file}, "
                    f"tolerance -{t:.0%}) — this metric moved")
        for name in COUNT_METRICS:
            vals = [(r["metrics"][name], r["file"]) for r in rs
                    if name in r["metrics"]]
            if not vals or name not in head["metrics"]:
                continue
            best, best_file = min(vals)
            if head["metrics"][name] > best:
                failures.append(
                    f"{series}: {name}: head {head['file']} "
                    f"{head['metrics'][name]:g} > best-ever {best:g} "
                    f"({best_file}; count metric: no tolerance)")
    return failures


#: The trajectory table's metric columns, in render order.
_COLUMNS = ("gbps", "goodput_gbps", "p95_ms", "p99_ms", "utilization",
            "devices", "errors_total", "lost", "recompiles")


def render(records: list[dict], out=None) -> None:
    """The per-series trajectory tables (the docs/PERF.md ledger view),
    one row per round, best-ever per column marked ``*``."""
    out = out if out is not None else sys.stdout  # bound at CALL time
    by_series: dict[str, list[dict]] = {}
    for r in records:
        by_series.setdefault(r["series"], []).append(r)
    for series, rs in sorted(by_series.items()):
        rs = sorted(rs, key=lambda r: r["round"])
        cols = [c for c in _COLUMNS
                if any(c in r["metrics"] for r in rs)]
        out.write(f"\n{series}: {len(rs)} round(s)\n")
        header = ["round", "file"] + list(cols)
        best = {}
        for c in cols:
            vals = [r["metrics"][c] for r in rs if c in r["metrics"]]
            if vals:
                best[c] = (min(vals) if c in COUNT_METRICS
                           or c in RENDER_ONLY else max(vals))
        rows = []
        for r in rs:
            row = [f"r{r['round']:02d}", r["file"]]
            for c in cols:
                v = r["metrics"].get(c)
                if v is None:
                    row.append("-")
                else:
                    mark = "*" if v == best.get(c) else ""
                    row.append(f"{v:g}{mark}")
            if not r["parsed"]:
                row[-1] = row[-1] if cols else ""
                row.append("(schema unknown to the ledger)")
            rows.append(row)
        widths = [max(len(str(x[i])) for x in [header] + rows)
                  for i in range(len(header))]
        for row in [header] + rows:
            out.write("  " + "  ".join(
                str(c).ljust(w) for c, w in zip(row, widths)).rstrip()
                + "\n")


def repo_root() -> str:
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m our_tree_tpu.obs.history",
        description="perf-history ledger over the committed *_r*.json "
                    "artifacts (docs/PERF.md)")
    ap.add_argument("--root", default=None,
                    help="artifact directory (default: the repo root)")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 unless every series' head artifact "
                         "holds best-ever within tolerance (the CI "
                         "gate: a silently-regressing commit names the "
                         "artifact and metric that moved)")
    ap.add_argument("--tolerance", default=None, metavar="SPEC",
                    help="per-metric overrides, e.g. "
                         "'goodput_gbps=0.5,gbps=0.1' (fractions of "
                         "best-ever; count metrics tolerate nothing)")
    ap.add_argument("--json", action="store_true",
                    help="emit the records as JSON instead of tables")
    args = ap.parse_args(argv)
    records = collect(args.root or repo_root())
    if not records:
        print("no *_r*.json artifacts found", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(records, indent=1, sort_keys=True))
    else:
        render(records)
    if args.check:
        failures = check(records, parse_tolerances(args.tolerance))
        for f in failures:
            print(f"# history: REGRESSION {f}", file=sys.stderr)
        n_series = len({r['series'] for r in records if r['metrics']})
        if failures:
            print(f"# history: CHECK FAILED: {len(failures)} "
                  f"regression(s) across {len(records)} artifact(s)",
                  file=sys.stderr)
            return 1
        print(f"# history: check green: {len(records)} artifact(s), "
              f"{n_series} series, every head holds best-ever",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
