"""Run-wide tracing & metrics (`our_tree_tpu/obs/`).

The resilience layer (PR 1-2) made failures survivable; this layer makes
runs *legible*. Before it, the evidence of what a sweep actually did was
smeared across four places — stderr notes, `# degraded:` trailers,
journal rows, and `OT_CRASH_DIR` stack dumps — none of them
machine-readable as one story. The AES-multicore paper (PAPERS.md) could
attribute its scaling cliffs only because it measured per-phase times
per worker; this package gives every run the same per-phase attribution:

* ``trace``  — the process-global tracer: ``span(name, **attrs)``
  context manager plus ``counter``/``gauge``/``point`` helpers,
  appending structured JSONL events to a per-run directory
  (``OT_TRACE_DIR``; off and near-free when unset). The run id is
  generated at top level and propagated to child processes via
  ``OT_TRACE_RUN``; a parent span id travels via ``OT_TRACE_PARENT`` so
  an ``--isolate`` child's spans nest under its supervisor's unit
  attempt. Stdlib-only and bare-loadable like ``resilience/degrade.py``
  (registered in ``sys.modules`` under its canonical dotted name so the
  counters stay one-per-process across bare and package contexts).
* ``metrics`` — the LIVE half of the telemetry plane: a process-global
  registry of exact O(1) counters / gauges / log2-bucket histograms
  with small closed label tuples, flushed as periodic
  ``metrics-<pid>.jsonl`` snapshots into the trace run dir and rendered
  as Prometheus text by the serve status endpoint. Exact even when span
  tracing is head-sampled (``OT_TRACE_SAMPLE`` — the saturation-run
  knob: steady-state spans mostly vanish, abnormal outcomes
  force-sample, the registry counts everything). Also the repo's one
  percentile implementation (exact nearest-rank + interpolated from
  log2 buckets).
* ``slo`` — SLO regression gates: compare a serve run against a
  committed ``SERVE_r*.json`` baseline with per-metric tolerances
  (count metrics tolerate nothing); ``serve.bench --slo`` runs it
  in-process, CI gates against ``SERVE_r04_control.json``. Gates the
  per-stage waterfall budgets and the cost section's per-(engine x
  rung) achieved-GB/s rows — a regression names WHICH stage or kernel
  moved.
* ``costmodel`` — static per-(engine, mode, rung) dispatch cost
  records: analytic jit-boundary HBM traffic (hand-derived from the
  dispatch signature, per-engine dataflow-aware) pinned within 10% of
  XLA's ``cost_analysis()``/``memory_analysis()`` byte counts where
  both exist. Computed once at serve warmup, stamped into
  ``SERVE_r*.json`` (the ``cost`` section), the run dir
  (``cost-<pid>-*.json`` — the report's roofline table + gap-explain
  line), and incident bundles.
* ``incident`` — the flight recorder: a bounded in-memory ring of
  recent dispatch records; watchdog kills, quarantines, SLO breaches,
  and auth-failure spikes dump self-contained evidence bundles
  (ring + exact metrics snapshot + degrade ledger + cost records)
  into the run layout, coalesced per incident. ``obs.report
  --incidents [--check]`` renders/gates them; ``/incidentz`` lists
  them live.
* ``profiler`` — ot-scope's ONE capture seam: bounded device-profiling
  windows (jax.profiler trace where available, host stack sampling on
  the native tier, a per-window metrics-registry delta summary either
  way) armable via ``serve.bench --profile-window``, live
  ``GET /profilez?seconds=N`` (router-federated per backend; overlap
  refused 409), or the incident recorder (``OT_PROFILE_ON_INCIDENT``,
  one capture per cooldown). Summaries land in the run layout;
  ``obs.report --profile`` joins them against the cost records.
* ``history`` — the perf-history ledger: every committed ``*_r*.json``
  parsed into classed trend series; ``--check`` gates each series'
  head against BEST-EVER with per-metric tolerances (CI runs it — a
  silently-regressing committed artifact names itself).
* ``export`` — run-dir parsing (schema validation for spans AND metrics
  snapshots, begin/end span pairing, orphan detection — an orphaned
  span IS the evidence of a SIGKILLed child) and the Chrome/Perfetto
  ``trace.json`` exporter (snapshot gauges become counter tracks).
* ``report`` — ``python -m our_tree_tpu.obs.report <run-dir>``: per-unit
  wall/device time, retries, faults injected vs. observed,
  degradations, quarantines, the slowest-span table, and the metrics
  table (counter totals, gauge last-values, histogram percentiles);
  ``--check`` fails on schema violations or orphaned spans (the CI
  gate); ``--trace-json`` writes the Perfetto export.

The instrumented seams, the event schema, and the Perfetto how-to are
documented in docs/OBSERVABILITY.md.
"""
