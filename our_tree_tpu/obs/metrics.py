"""The live metrics plane: a process-global, lock-cheap registry.

The tracer (``obs/trace.py``) answers *what happened, span by span* —
and pays one flushed JSONL write per event for it. At the serve rates
the TPU saturation run targets (~4-9k requests/s at 4 MiB) that price
is both an overhead hazard on the hot path and, once spans are SAMPLED
(``OT_TRACE_SAMPLE``), a completeness hazard: a sampled stream cannot
answer "how many requests, exactly". This module is the other half of
the telemetry plane:

* **Counters** — monotonic totals (``counter(name, n, **labels)``).
* **Gauges** — last-write values (``gauge``), plus a high-water variant
  (``gauge_max``) for peaks like queue depth.
* **Histograms** — fixed log2 buckets (``observe``): value ``v`` lands
  in bucket ``b`` where ``2^(b-1) <= v < 2^b``, so a latency or size
  distribution is ~40 small ints however long the run. Percentiles are
  interpolated from the buckets (``percentile_from_buckets``).

Every hot-path operation is one dict update under one lock — **no
I/O** — so the registry stays EXACT while span tracing samples: the
counters are the ground truth the sampled trace is reconciled against.
Labels are small closed tuples (lane, rung, engine, outcome, ...):
``ALLOWED_LABEL_KEYS`` is the contract otlint's ``metrics-labels`` rule
enforces statically — no request ids, no tenant digests — and
``_MAX_SERIES`` bounds the per-name series count at runtime, so the
registry can never become an unbounded-cardinality memory leak.

Durability is a single daemon FLUSHER thread: when tracing is enabled
(``OT_TRACE_DIR``) it appends cumulative snapshots of the whole
registry to ``metrics-<pid>-<tok>.jsonl`` in the same run directory the
trace files use, every ``OT_METRICS_FLUSH_S`` seconds (default 2) and
once at exit — the LAST snapshot is the final totals, the series of
snapshots is the time axis ``obs.export`` turns into Perfetto counter
tracks. ``obs.report`` renders the table; ``serve/status.py`` renders
the same registry as Prometheus text for ``/metrics``.

Same constitution as the tracer: **never raises** (a full disk or a
bad label degrades to a dropped update, counted in ``dropped``),
stdlib-only, no intra-package imports (the trace module is loaded
lazily under its canonical name for the run-dir layout), and
``reset_for_tests()`` for process-global state hygiene.

This module is also the repo's ONE percentile implementation
(``percentile_exact`` from full samples — ``serve/loadgen.py``
delegates here — and ``percentile_from_buckets`` for registry
histograms, used by ``obs.report`` and ``serve.bench``).
"""

from __future__ import annotations

import atexit
import json
import math
import os
import sys
import threading
import time
import uuid

KIND = "ot-metrics"
VERSION = 1

#: The closed label-key vocabulary. otlint's ``metrics-labels`` rule
#: checks every ``metrics.*(**labels)`` call site against this tuple —
#: a label key outside it, or a statically high-cardinality label VALUE
#: (request ids, tenant digests, f-strings), is a lint error: labels
#: multiply series, and series live forever in a process-global dict.
ALLOWED_LABEL_KEYS = ("lane", "rung", "engine", "outcome", "bucket",
                      "code", "state", "slots", "point", "kind", "mode",
                      "backend", "reason", "stage", "nr", "rule",
                      "severity")

#: Runtime backstop for the same hazard the lint rule prevents
#: statically: at most this many distinct label sets per metric name —
#: updates beyond it are dropped (and counted), never stored.
_MAX_SERIES = 64

#: Tail-exemplar retention bound PER SERIES: one exemplar per log2
#: bucket, highest buckets kept when the cap is hit — a p99 outlier's
#: identity survives, the registry's footprint stays O(1). Exemplars
#: are the one sanctioned place an identity-shaped value (a span id)
#: rides the registry: bounded by THIS cap, not by label cardinality
#: (they are not labels and create no series).
_EXEMPLAR_MAX = 6

#: The time-attribution waterfall's stage vocabulary, in request-path
#: order (docs/OBSERVABILITY.md): the router's stages, then the
#: backend's. The ONE definition — route.bench's completeness gate and
#: obs.report's fleet table both read it, so they can never disagree
#: about what a complete waterfall is.
WATERFALL_STAGES = ("router_queue", "retry", "wire", "backend_queue",
                    "pack", "worker_wait", "dispatch", "device", "reply")

_LOCK = threading.Lock()
#: (name, ((k, v), ...)) -> total / last value / _Hist.
_COUNTS: dict[tuple, float] = {}
_GAUGES: dict[tuple, float] = {}
_HISTS: dict[tuple, "_Hist"] = {}
#: name -> live series count (the _MAX_SERIES ledger).
_SERIES: dict[str, int] = {}
_DROPPED = 0

#: Lazily-opened snapshot file state {"run","fh","path",...}; None until
#: the first flush. Mirrors trace._STATE (reopens on a run-id change),
#: rotation fields included: under ``OT_TRACE_MAX_MB`` the snapshot file
#: rotates into ``-s<k>`` segments with the oldest deleted, same as the
#: trace stream — snapshots are CUMULATIVE, so eviction loses the time
#: axis's tail but never the totals (the last surviving snapshot is
#: complete). Evicted bytes are counted (``evicted_bytes``), surfaced in
#: every later snapshot line and on /metrics — bounded is never silent.
_SINK: dict | None = None
_EVICTED_BYTES = 0
_FLUSHER: threading.Thread | None = None
_ATEXIT_REGISTERED = False


class _Hist:
    """One log2-bucket histogram series: bucket exponent -> count, plus
    exact count/sum so means and Prometheus ``_sum``/``_count`` stay
    bucket-error-free. ``exemplars`` (lazy) maps bucket exponent -> the
    MAX observation's exemplar dict for that bucket ({"v", "ts", plus
    caller attrs like span/trace/lane/rung/engine/mode}), bounded by
    ``_EXEMPLAR_MAX`` — the tail-latency breadcrumb that turns a p99
    number into a resolvable span chain."""

    __slots__ = ("buckets", "count", "sum", "exemplars")

    def __init__(self):
        self.buckets: dict[int, int] = {}
        self.count = 0
        self.sum = 0.0
        self.exemplars: dict[int, dict] | None = None


def _trace():
    """our_tree_tpu.obs.trace under its canonical dotted name, lazily
    (the run-dir layout — run id, directory — is the tracer's; metrics
    files live beside the trace files). None when unloadable: the
    registry keeps counting in memory either way."""
    canonical = "our_tree_tpu.obs.trace"
    mod = sys.modules.get(canonical)
    if mod is None:
        try:
            import importlib.util
            spec = importlib.util.spec_from_file_location(
                canonical, os.path.join(
                    os.path.dirname(os.path.abspath(__file__)), "trace.py"))
            mod = importlib.util.module_from_spec(spec)
            sys.modules[canonical] = mod
            spec.loader.exec_module(mod)
        except Exception:
            sys.modules.pop(canonical, None)
            return None
    return mod


def enabled() -> bool:
    """Snapshot flushing is on iff tracing is (``OT_TRACE_DIR``): the
    registry itself always counts — in-memory dict updates are the
    whole hot-path cost either way."""
    return bool(os.environ.get("OT_TRACE_DIR"))


def flush_interval_s() -> float:
    try:
        return max(
            float(os.environ.get("OT_METRICS_FLUSH_S", 2.0) or 2.0), 0.05)
    except ValueError:
        return 2.0


def _key(name: str, labels: dict) -> tuple | None:
    """The series key, or None when the series budget for ``name`` is
    spent (caller drops). Caller holds no lock; the budget check runs
    under _LOCK inside the mutators."""
    if not labels:
        return (name, ())
    return (name, tuple(sorted(labels.items())))


def _admit_locked(store: dict, key: tuple) -> bool:
    """Series-cardinality backstop; caller holds _LOCK."""
    if key in store:
        return True
    name = key[0]
    n = _SERIES.get(name, 0)
    if n >= _MAX_SERIES:
        return False
    _SERIES[name] = n + 1
    return True


def counter(name: str, n: float = 1, **labels) -> None:
    """Add ``n`` to the named counter series. O(1), no I/O, exact."""
    global _DROPPED
    try:
        key = _key(name, labels)
        with _LOCK:
            if not _admit_locked(_COUNTS, key):
                _DROPPED += 1
                return
            _COUNTS[key] = _COUNTS.get(key, 0) + n
    except Exception:  # noqa: BLE001 - never-raises contract
        _DROPPED += 1


def gauge(name: str, value: float, **labels) -> None:
    """Set the named gauge series (last write wins)."""
    global _DROPPED
    try:
        key = _key(name, labels)
        with _LOCK:
            if not _admit_locked(_GAUGES, key):
                _DROPPED += 1
                return
            _GAUGES[key] = value
    except Exception:  # noqa: BLE001 - never-raises contract
        _DROPPED += 1


def gauge_max(name: str, value: float, **labels) -> None:
    """Raise the named gauge to ``value`` if higher (high-water marks:
    queue depth peaks, in-flight peaks)."""
    global _DROPPED
    try:
        key = _key(name, labels)
        with _LOCK:
            if not _admit_locked(_GAUGES, key):
                _DROPPED += 1
                return
            if value > _GAUGES.get(key, float("-inf")):
                _GAUGES[key] = value
    except Exception:  # noqa: BLE001 - never-raises contract
        _DROPPED += 1


def bucket_of(value: float) -> int:
    """The log2 bucket exponent of ``value``: bucket ``b >= 1`` spans
    ``[2^(b-1), 2^b)`` (``int(value).bit_length()``); bucket 0 holds
    everything below 1, non-positive values included."""
    v = int(value)
    return v.bit_length() if v >= 1 else 0


#: (raw env string, parsed flag) — one parse per distinct value, the
#: trace._SAMPLE_CACHE pattern: the flag is consulted per exemplar-
#: carrying observation on the hot path.
_EXEMPLAR_CACHE: tuple[str, bool] = ("\0unset", True)


def exemplars_enabled() -> bool:
    """Exemplar retention is on by default (bounded: ``_EXEMPLAR_MAX``
    per series); ``OT_EXEMPLARS=0`` disables it."""
    global _EXEMPLAR_CACHE
    raw = os.environ.get("OT_EXEMPLARS", "1")
    cached_raw, cached = _EXEMPLAR_CACHE
    if raw == cached_raw:
        return cached
    on = str(raw).lower() not in ("0", "off", "false")
    _EXEMPLAR_CACHE = (raw, on)
    return on


def observe(name: str, value: float, exemplar: dict | None = None,
            **labels) -> None:
    """Record one histogram observation in fixed log2 buckets.

    ``exemplar`` (optional, a small dict — span id, trace/run id, the
    closed lane/rung/engine/mode attrs) is retained iff this
    observation is the MAX seen in its bucket: the hot path stays one
    dict update, and the histogram's high buckets each remember the one
    concrete request that defined them (rendered by ``obs.report``'s
    slowest-exemplars table and emitted on ``/metrics`` in OpenMetrics
    exemplar syntax)."""
    global _DROPPED
    try:
        b = bucket_of(value)
        key = _key(name, labels)
        # Resolved OUTSIDE the lock (and cached): the flag gate must
        # not put an environ read inside the registry's hot section.
        keep_ex = exemplar is not None and exemplars_enabled()
        with _LOCK:
            if not _admit_locked(_HISTS, key):
                _DROPPED += 1
                return
            h = _HISTS.get(key)
            if h is None:
                h = _HISTS[key] = _Hist()
            h.buckets[b] = h.buckets.get(b, 0) + 1
            h.count += 1
            h.sum += float(value)
            if keep_ex:
                ex = h.exemplars
                if ex is None:
                    ex = h.exemplars = {}
                cur = ex.get(b)
                if cur is None or float(value) >= cur["v"]:
                    ex[b] = {"v": float(value),
                             "ts": time.time_ns() // 1000, **exemplar}
                    while len(ex) > _EXEMPLAR_MAX:
                        del ex[min(ex)]  # highest buckets win the cap
    except Exception:  # noqa: BLE001 - never-raises contract
        _DROPPED += 1


# ---------------------------------------------------------------------------
# Percentiles: the repo's one implementation (satellite: bench + report
# used to each carry their own).
# ---------------------------------------------------------------------------


def percentile_exact(sorted_vals, p: float) -> float:
    """Nearest-rank percentile over a full SORTED sample (0 < p <= 100).

    The exact method ``serve/loadgen.py`` always used (no binning error
    at the tail); it now lives here so the bench and the report cannot
    drift apart."""
    n = len(sorted_vals)
    if not n:
        return 0.0
    rank = max(math.ceil(p / 100.0 * n), 1)
    return sorted_vals[min(rank, n) - 1]


def percentile_from_buckets(buckets: dict, p: float) -> float:
    """Percentile interpolated from a log2-bucket histogram.

    ``buckets`` maps bucket exponent -> count (``bucket_of`` layout; str
    keys from a JSON snapshot are accepted). Linear interpolation inside
    the covering bucket ``[2^(b-1), 2^b)`` — the standard Prometheus
    histogram_quantile estimate, with log2 buckets bounding the relative
    error at 2x worst-case (the price of O(1) hot-path observation)."""
    items = sorted((int(b), int(c)) for b, c in buckets.items() if c)
    total = sum(c for _, c in items)
    if not total:
        return 0.0
    rank = max(math.ceil(p / 100.0 * total), 1)
    seen = 0
    for b, c in items:
        if seen + c >= rank:
            lo = 0.0 if b == 0 else float(1 << (b - 1))
            hi = 1.0 if b == 0 else float(1 << b)
            return lo + (hi - lo) * (rank - seen) / c
        seen += c
    return float(1 << items[-1][0])  # unreachable (rank <= total)


def merge_buckets(hists) -> dict:
    """Sum bucket dicts (e.g. one histogram name across label sets or
    processes) into one {exponent: count} dict."""
    out: dict[int, int] = {}
    for b in hists:
        for k, v in b.items():
            k = int(k)
            out[k] = out.get(k, 0) + int(v)
    return out


# ---------------------------------------------------------------------------
# Snapshots.
# ---------------------------------------------------------------------------


def _label_str(labels: tuple) -> str:
    return ",".join(f"{k}={v}" for k, v in labels)


def flat_name(name: str, labels: tuple) -> str:
    """``name{k=v,...}`` — the human-facing series key used in artifact
    JSON and the report table."""
    return f"{name}{{{_label_str(labels)}}}" if labels else name


def snapshot() -> dict:
    """The registry as one JSON-clean dict (flat series keys): what the
    serve bench stamps into SERVE_r*.json and what /healthz consumers
    see. Histograms carry buckets + count + sum; percentile rendering
    is the reader's (``percentile_from_buckets``)."""
    with _LOCK:
        counts = {flat_name(n, l): v for (n, l), v in _COUNTS.items()}
        gauges = {flat_name(n, l): v for (n, l), v in _GAUGES.items()}
        hists = {flat_name(n, l): _hist_doc(h)
                 for (n, l), h in _HISTS.items()}
    out: dict = {"counters": dict(sorted(counts.items())),
                 "gauges": dict(sorted(gauges.items())),
                 "hists": dict(sorted(hists.items()))}
    if _DROPPED:
        out["dropped"] = _DROPPED
    return out


def _hist_doc(h: "_Hist") -> dict:
    """One histogram series as its JSON-clean snapshot value (buckets +
    exact count/sum, plus the retained exemplars when any — the
    run-dir half of the exemplar story: ``obs.report`` resolves them
    against the trace stream post-hoc)."""
    doc = {"buckets": {str(b): c for b, c in sorted(h.buckets.items())},
           "count": h.count, "sum": round(h.sum, 3)}
    if h.exemplars:
        doc["exemplars"] = {str(b): dict(e)
                            for b, e in sorted(h.exemplars.items())}
    return doc


def _snapshot_rec(ts_us: int) -> dict:
    """One structured snapshot line for the metrics JSONL (lists of
    [name, {labels}, value] — the schema ``obs.export`` validates)."""
    with _LOCK:
        counters = [[n, dict(l), v] for (n, l), v in sorted(_COUNTS.items())]
        gauges = [[n, dict(l), v] for (n, l), v in sorted(_GAUGES.items())]
        hists = [[n, dict(l), _hist_doc(h)]
                 for (n, l), h in sorted(_HISTS.items())]
    rec = {"ts": ts_us, "counters": counters, "gauges": gauges,
           "hists": hists}
    if _DROPPED:
        rec["dropped"] = _DROPPED
    if _EVICTED_BYTES:
        rec["evicted_bytes"] = _EVICTED_BYTES
    return rec


def _max_bytes() -> int:
    """The snapshot-file disk cap: the SAME ``OT_TRACE_MAX_MB`` knob the
    trace stream rotates under (one soak-run cap for the whole run dir's
    per-process footprint). 0/unset = unbounded."""
    try:
        mb = float(os.environ.get("OT_TRACE_MAX_MB", 0) or 0)
    except ValueError:
        return 0
    return max(int(mb * (1 << 20)), 0)


def _segment_path(sink: dict) -> str:
    suffix = f"-s{sink['seg']}" if sink["seg"] else ""
    return os.path.join(
        sink["dir"], f"metrics-{sink['pid']}-{sink['proc']}{suffix}.jsonl")


def _open_segment(sink: dict) -> None:
    """Open the current segment and write its header (every segment is
    self-describing, SAME proc token — ``obs.export`` aggregates
    last-snapshot-per-proc across segments). ``sink`` is only mutated on
    full success."""
    path = _segment_path(sink)
    fh = open(path, "a", encoding="utf-8")
    try:
        header = {"kind": KIND, "v": VERSION, "run": sink["run"],
                  "pid": sink["pid"], "proc": sink["proc"],
                  "interval_s": flush_interval_s(),
                  "start_us": time.time_ns() // 1000}
        if sink["seg"]:
            header["seg"] = sink["seg"]
        fh.write(json.dumps(header, separators=(",", ":")) + "\n")
        fh.flush()
    except OSError:
        try:
            fh.close()
        except OSError:
            pass
        raise
    sink["fh"], sink["path"] = fh, path


# ot-san: absorb=amortized-cap-rotation (segment-full cadence only)
def _rotate_sink(sink: dict) -> None:
    """Open-next-then-retire (the trace rotation order: a failed open
    mid-ENOSPC keeps the live handle and retries later), then evict the
    oldest segments past the cap, counting every evicted byte."""
    global _EVICTED_BYTES
    old_fh, old_path = sink["fh"], sink["path"]
    sink["seg"] += 1
    try:
        _open_segment(sink)
    except OSError:
        sink["seg"] -= 1
        return
    try:
        old_fh.close()
    except OSError:
        pass
    sink["segments"].append(old_path)
    keep = max(int(sink["cap_bytes"] // sink["seg_bytes"]) - 1, 1)
    while len(sink["segments"]) > keep:
        victim = sink["segments"].pop(0)
        try:
            size = os.path.getsize(victim)
            os.unlink(victim)
            _EVICTED_BYTES += size
        except OSError:
            break


# ot-san: absorb=amortized-snapshot-sink (open once; flusher-cadence writes)
def _sink() -> dict | None:
    """Open (or reopen after a run-id change) the per-process metrics
    snapshot file, header line included. None while disabled or
    unwritable — the registry keeps counting regardless."""
    global _SINK, _DROPPED
    t = _trace()
    if t is None or not enabled():
        return None
    run = t.ensure_run()
    if _SINK is not None and _SINK["run"] == run:
        return _SINK
    _close_sink()
    try:
        d = t.run_dir()
        os.makedirs(d, exist_ok=True)
        cap = _max_bytes()
        sink = {"run": run, "dir": d, "pid": os.getpid(),
                "proc": uuid.uuid4().hex[:8], "seg": 0, "segments": [],
                "cap_bytes": cap,
                "seg_bytes": max(cap // 4, 4096) if cap else 0}
        _open_segment(sink)
        _SINK = sink
        return _SINK
    except OSError:
        _DROPPED += 1
        return None


def _close_sink() -> None:
    global _SINK
    if _SINK is not None:
        try:
            _SINK["fh"].close()
        except OSError:
            pass
        _SINK = None


def flush_now() -> bool:
    """Append one cumulative snapshot line (True on success). Callers
    with a natural end-of-run (serve stop, bench exit) flush explicitly
    so the final totals are on disk even if atexit never runs."""
    global _DROPPED
    try:
        sink = _sink()
        if sink is None:
            return False
        rec = _snapshot_rec(time.time_ns() // 1000)
        sink["fh"].write(json.dumps(rec, separators=(",", ":")) + "\n")
        sink["fh"].flush()
        if sink["seg_bytes"] and sink["fh"].tell() >= sink["seg_bytes"]:
            _rotate_sink(sink)
        return True
    except Exception:  # noqa: BLE001 - never-raises contract
        _DROPPED += 1
        return False


def _flusher_loop() -> None:
    while True:
        time.sleep(flush_interval_s())
        if enabled() and (_COUNTS or _GAUGES or _HISTS):
            flush_now()


def ensure_flusher() -> None:
    """Start the single daemon flusher thread (idempotent, cheap to call
    from hot-path modules' setup). Also registers the atexit final
    flush, so even a run that ends between intervals leaves its last —
    exact — totals on disk."""
    global _FLUSHER, _ATEXIT_REGISTERED
    if not _ATEXIT_REGISTERED:
        _ATEXIT_REGISTERED = True
        atexit.register(lambda: (enabled()
                                 and (_COUNTS or _GAUGES or _HISTS)
                                 and flush_now()))
    if _FLUSHER is None or not _FLUSHER.is_alive():
        _FLUSHER = threading.Thread(target=_flusher_loop, daemon=True,
                                    name="ot-metrics-flush")
        _FLUSHER.start()


# ---------------------------------------------------------------------------
# Prometheus text rendering (the /metrics endpoint's body).
# ---------------------------------------------------------------------------


def _prom_name(name: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)


#: Exemplar attr keys -> their OpenMetrics label names.
_EXEMPLAR_LABEL = {"span": "span_id", "trace": "trace_id"}


def _prom_num(v: float) -> str:
    """Full-precision sample rendering. ``%g`` would quantize to 6
    significant digits — a byte counter in the hundreds of MB could
    grow by thousands between scrapes while rendering the identical
    string, making scrape-side ``rate()`` read 0 and breaking the
    registry's exactness promise exactly where operators consume it."""
    if isinstance(v, bool):
        return str(int(v))
    if isinstance(v, int):
        return str(v)
    if isinstance(v, float) and v.is_integer() and abs(v) < 2 ** 63:
        return str(int(v))
    return repr(float(v))


def _prom_labels(labels, extra: str = "") -> str:
    parts = [f'{_prom_name(str(k))}="{v}"' for k, v in labels]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def render_prometheus(exemplars: bool = False) -> str:
    """The registry in Prometheus exposition text format (v0.0.4).

    Counters render as ``<name>_total``, gauges raw, histograms as
    cumulative ``_bucket{le=...}`` series over the log2 bounds plus
    ``_sum``/``_count`` — directly scrapeable, no client library.

    ``exemplars=True`` appends each bucket's retained tail exemplar in
    OpenMetrics exemplar syntax — legal ONLY in the OpenMetrics
    format, so the status endpoint sets it iff the scraper negotiated
    ``application/openmetrics-text`` (a classic 0.0.4 parser rejects
    the ``#`` tail, and a default scrape must never lose every serve
    metric to a parse error)."""
    lines: list[str] = []
    with _LOCK:
        counts = sorted(_COUNTS.items())
        gauges = sorted(_GAUGES.items())
        hists = sorted((k, {"buckets": dict(h.buckets),
                            "count": h.count, "sum": h.sum,
                            "exemplars": dict(h.exemplars or {})})
                       for k, h in _HISTS.items())
    seen: set[str] = set()
    for (name, labels), v in counts:
        pn = _prom_name(name) + "_total"
        if pn not in seen:
            seen.add(pn)
            lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn}{_prom_labels(labels)} {_prom_num(v)}")
    for (name, labels), v in gauges:
        pn = _prom_name(name)
        if pn not in seen:
            seen.add(pn)
            lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn}{_prom_labels(labels)} {_prom_num(v)}")
    for (name, labels), h in hists:
        pn = _prom_name(name)
        if pn not in seen:
            seen.add(pn)
            lines.append(f"# TYPE {pn} histogram")
        cum = 0
        for b, c in sorted(h["buckets"].items()):
            cum += c
            le = 'le="%d"' % (1 << b if b else 1)
            # The bucket's retained exemplar rides in OpenMetrics
            # exemplar syntax (`# {labels} value timestamp-seconds`):
            # the one concrete tail request behind this bucket,
            # scrape-side resolvable to its span chain.
            ex = h.get("exemplars", {}).get(b) if exemplars else None
            tail = ""
            if ex:
                exl = ",".join(
                    f'{_prom_name(_EXEMPLAR_LABEL.get(k, k))}="{v}"'
                    for k, v in sorted(ex.items())
                    if k not in ("v", "ts"))
                tail = (f" # {{{exl}}} {_prom_num(ex['v'])} "
                        f"{ex.get('ts', 0) / 1e6:.6f}")
            lines.append(
                f"{pn}_bucket{_prom_labels(labels, le)} {cum}{tail}")
        inf = _prom_labels(labels, 'le="+Inf"')
        lines.append(f"{pn}_bucket{inf} {h['count']}")
        sum_s = _prom_num(h['sum'])
        lines.append(f"{pn}_sum{_prom_labels(labels)} {sum_s}")
        lines.append(f"{pn}_count{_prom_labels(labels)} {h['count']}")
    if _DROPPED:
        lines.append("# TYPE ot_metrics_dropped_total counter")
        lines.append(f"ot_metrics_dropped_total {_DROPPED}")
    if _EVICTED_BYTES:
        lines.append("# TYPE ot_metrics_evicted_bytes_total counter")
        lines.append(f"ot_metrics_evicted_bytes_total {_EVICTED_BYTES}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Introspection helpers + test hygiene.
# ---------------------------------------------------------------------------


def counter_total(name: str) -> float:
    """Sum of one counter name across all its label sets."""
    with _LOCK:
        return sum(v for (n, _), v in _COUNTS.items() if n == name)


def hist_merged(name: str) -> dict:
    """One histogram name's buckets merged across label sets."""
    with _LOCK:
        parts = [dict(h.buckets) for (n, _), h in _HISTS.items()
                 if n == name]
    return merge_buckets(parts)


def counter_by_label(name: str, label_key: str) -> dict:
    """label value -> summed total for one counter name, grouped by one
    label key (e.g. ``serve_requests`` by ``mode`` — the per-workload
    split the serve artifact and obs.report render)."""
    out: dict[str, float] = {}
    with _LOCK:
        for (n, labels), v in _COUNTS.items():
            if n != name:
                continue
            lv = dict(labels).get(label_key)
            if lv is not None:
                out[str(lv)] = out.get(str(lv), 0) + v
    return dict(sorted(out.items()))


def hist_items(name: str) -> list:
    """[(labels dict, {"buckets", "count", "sum"})] for one histogram
    name (e.g. the per-(engine, rung) warmup compile-cost table)."""
    with _LOCK:
        return [(dict(labels),
                 {"buckets": dict(h.buckets), "count": h.count,
                  "sum": h.sum})
                for (n, labels), h in _HISTS.items() if n == name]


def hist_by_label(name: str, label_key: str) -> dict:
    """label value -> merged buckets for one histogram name, grouped by
    one label key (e.g. ``serve_stage_us`` by ``stage``)."""
    parts: dict[str, list] = {}
    with _LOCK:
        for (n, labels), h in _HISTS.items():
            if n != name:
                continue
            lv = dict(labels).get(label_key)
            if lv is not None:
                parts.setdefault(str(lv), []).append(dict(h.buckets))
    return {k: merge_buckets(v) for k, v in sorted(parts.items())}


def stage_percentiles(
        names=("route_stage_us", "serve_stage_us")) -> dict:
    """The bench artifacts' ``stages`` section: stage name ->
    {p50_us, p95_us, p99_us, count} interpolated from this process's
    stage histograms — the quantity ``obs/slo.py``'s per-stage budget
    gates compare, so a goodput regression names WHICH stage moved."""
    merged: dict[str, dict] = {}
    for name in names:
        for stage, buckets in hist_by_label(name, "stage").items():
            agg = merged.setdefault(stage, {})
            agg["buckets"] = merge_buckets(
                [agg.get("buckets", {}), buckets])
    out = {}
    for stage, agg in sorted(merged.items()):
        b = agg["buckets"]
        out[stage] = {
            "p50_us": round(percentile_from_buckets(b, 50), 1),
            "p95_us": round(percentile_from_buckets(b, 95), 1),
            "p99_us": round(percentile_from_buckets(b, 99), 1),
            "count": sum(b.values()),
        }
    return out


def dropped() -> int:
    return _DROPPED


def evicted_bytes() -> int:
    """Bytes of snapshot history deleted by the OT_TRACE_MAX_MB cap."""
    return _EVICTED_BYTES


def reset_for_tests() -> None:
    """Clear every series and close the snapshot sink (tests only)."""
    global _DROPPED, _EVICTED_BYTES
    _close_sink()
    with _LOCK:
        _COUNTS.clear()
        _GAUGES.clear()
        _HISTS.clear()
        _SERIES.clear()
    _DROPPED = 0
    _EVICTED_BYTES = 0
