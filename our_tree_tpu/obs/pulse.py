"""ot-pulse: streaming fleet analytics over the metrics registry.

Every other instrument in the stack is post-hoc — roofline rows, SLO
gates, ``obs.history --check`` all run after a bench exits — while the
live fleet's only judgment is the autoscaler's hand-tuned depth
thresholds. This module is the live analytics plane: a small streaming
engine that consumes cumulative registry snapshots (in-process frames,
or any committed run's ``metrics-*.jsonl`` stream offline), extracts
windowed rates and EWMA baselines, and evaluates a CLOSED set of typed
alert rules plus an online per-worker capacity model.

The rule catalog (``RULES`` — new rules are added here deliberately,
like ``incident.REASONS``):

* ``burn_rate`` — multi-window SLO error-budget burn (SRE-style): bad
  events (deadline expiries, failed/deadline batches, sheds — serve
  and route tiers both) over total offered events, divided by the
  budget fraction, must exceed the fast AND slow window thresholds
  together. The pair is what kills both failure modes of single-window
  alerting: the fast window alone pages on blips, the slow window
  alone pages an hour late.
* ``capacity_collapse`` — the measured per-(engine, mode) block
  throughput falls below ``collapse_frac`` of its own EWMA baseline
  while demand persists (queue non-empty): the worker is sick, not
  idle. The baseline freezes while the condition holds, so a long
  incident cannot drag its own reference down.
* ``quarantine_flap`` — lane/backend quarantine transitions
  (``serve_lane_transitions{state=quarantined}``,
  ``route_backend_transitions{state=quarantined}``) exceed ``flap_n``
  within the flap window: isolation is supposed to be rare and sticky;
  a flapping unit is a fleet-wide risk.
* ``compile_storm`` — steady-state recompiles (``serve_compile_us``
  observations AFTER traffic began — warmup's compile ramp is behind
  the window start by construction) exceed ``storm_n`` in the storm
  window: the ladder contract is being violated live.
* ``reassembly_pressure`` — ``serve_reassembly_held_bytes`` pinned at
  ``pressure_frac`` of ``serve_transfer_budget_bytes`` for
  ``pressure_ticks`` consecutive frames: the transfer plane is one
  slow consumer away from shedding every new transfer.

Every firing is emitted four ways through existing seams: a
``pulse_alerts{rule,severity}`` counter, a ``pulse-alert`` trace
point, a row on the ``/alertz`` status endpoint (the router federates
it like ``/profilez``), and — for page-severity rules — an incident
bundle (``incident.trigger("pulse-alert")``, whose cooldown coalesces
alert storms into one bundle) plus an ``OT_PROFILE_ON_ALERT`` capture
window (``profiler.on_alert``). Firing is EDGE-TRIGGERED with
hysteresis: a sustained condition fires once and re-arms only after
the condition clears, so a planted pattern in the replay tests fires
exactly once, not once per frame.

The capacity half is the ROADMAP payoff ("thresholds derived from a
measured capacity model"): the engine folds
``serve_rung_dispatches``/``serve_rung_device_us`` into a live
per-worker blocks/s estimate by engine x mode (cross-checked against
the ``obs/costmodel.py`` records when the server stamps them),
surfaced on ``/healthz`` (``capacity`` section) — which the gossip
scrape already caches per backend, so ``FleetSupervisor``'s
``headroom`` policy reads fleet capacity for free.

Determinism: the OFFLINE mode (``python -m our_tree_tpu.obs.pulse
<run-dir> [--check]``) replays each process's ``metrics-*.jsonl``
snapshot stream through the identical rule engine — same code, same
OT_PULSE_* knobs — and ``--check`` compares the replayed fired-rule
set against the ``pulse_alerts`` counters the live engine left in the
run's final snapshots. CI gates on it without timing lotteries.

Constitution: stdlib-only, never raises into the caller (the live
thread swallows everything, counted), bounded state (frames retained
only as far as the widest window; at most ``MAX_ALERT_ROWS`` alert
rows), and the CLI prints ``#``-prefixed human lines with one
parseable JSON line last.
"""

from __future__ import annotations

import argparse
import collections
import glob
import json
import os
import re
import sys
import threading
import time

from . import metrics, trace

KIND = "ot-pulse"
VERSION = 1

#: The closed rule vocabulary (a rule outside it is a schema bug).
RULES = ("burn_rate", "capacity_collapse", "quarantine_flap",
         "compile_storm", "reassembly_pressure")
SEVERITIES = ("warn", "page")
#: page-severity rules arm the evidence capture (incident bundle +
#: OT_PROFILE_ON_ALERT window); warn-severity rules only count/trace.
PAGE_RULES = ("burn_rate", "capacity_collapse")

#: serve_batches outcomes that spend error budget.
BAD_BATCH_OUTCOMES = ("deadline", "failed", "form-failed", "split-failed")

#: /alertz row retention (per engine instance).
MAX_ALERT_ROWS = 64


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default) or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default) or default)
    except ValueError:
        return default


def enabled() -> bool:
    """``OT_PULSE`` gate for the LIVE engine (default on — the tick is
    a registry snapshot + arithmetic). Offline replay ignores it."""
    return str(os.environ.get("OT_PULSE", "1")).lower() not in (
        "0", "off", "false", "no")


def every_s() -> float:
    """Live evaluation cadence (``OT_PULSE_EVERY_S``, default 2 s —
    the metrics flusher's cadence, so live frames and replayed
    snapshot frames see the same time resolution)."""
    return min(max(_env_float("OT_PULSE_EVERY_S", 2.0), 0.05), 60.0)


class PulseConfig:
    """The rule thresholds, every one an ``OT_PULSE_*`` env knob so a
    CI drive and its offline replay share one configuration by
    construction (``from_env``)."""

    def __init__(self, *,
                 fast_window_s: float = 30.0,
                 slow_window_s: float = 120.0,
                 budget: float = 0.05,
                 fast_burn: float = 8.0,
                 slow_burn: float = 2.0,
                 min_events: int = 20,
                 collapse_frac: float = 0.5,
                 ewma_alpha: float = 0.3,
                 baseline_frames: int = 3,
                 min_dispatches: int = 8,
                 flap_n: int = 3,
                 flap_window_s: float = 60.0,
                 storm_n: int = 5,
                 storm_window_s: float = 60.0,
                 pressure_frac: float = 0.9,
                 pressure_ticks: int = 3):
        self.fast_window_s = max(float(fast_window_s), 0.1)
        self.slow_window_s = max(float(slow_window_s), self.fast_window_s)
        self.budget = min(max(float(budget), 1e-6), 1.0)
        self.fast_burn = max(float(fast_burn), 1.0)
        self.slow_burn = max(float(slow_burn), 1.0)
        self.min_events = max(int(min_events), 1)
        self.collapse_frac = min(max(float(collapse_frac), 0.01), 1.0)
        self.ewma_alpha = min(max(float(ewma_alpha), 0.01), 1.0)
        self.baseline_frames = max(int(baseline_frames), 1)
        self.min_dispatches = max(int(min_dispatches), 1)
        self.flap_n = max(int(flap_n), 1)
        self.flap_window_s = max(float(flap_window_s), 0.1)
        self.storm_n = max(int(storm_n), 1)
        self.storm_window_s = max(float(storm_window_s), 0.1)
        self.pressure_frac = min(max(float(pressure_frac), 0.01), 1.0)
        self.pressure_ticks = max(int(pressure_ticks), 1)

    @classmethod
    def from_env(cls) -> "PulseConfig":
        return cls(
            fast_window_s=_env_float("OT_PULSE_FAST_S", 30.0),
            slow_window_s=_env_float("OT_PULSE_SLOW_S", 120.0),
            budget=_env_float("OT_PULSE_BUDGET", 0.05),
            fast_burn=_env_float("OT_PULSE_FAST_BURN", 8.0),
            slow_burn=_env_float("OT_PULSE_SLOW_BURN", 2.0),
            min_events=_env_int("OT_PULSE_MIN_EVENTS", 20),
            collapse_frac=_env_float("OT_PULSE_COLLAPSE_FRAC", 0.5),
            ewma_alpha=_env_float("OT_PULSE_ALPHA", 0.3),
            baseline_frames=_env_int("OT_PULSE_BASELINE_FRAMES", 3),
            min_dispatches=_env_int("OT_PULSE_MIN_DISPATCHES", 8),
            flap_n=_env_int("OT_PULSE_FLAP_N", 3),
            flap_window_s=_env_float("OT_PULSE_FLAP_S", 60.0),
            storm_n=_env_int("OT_PULSE_STORM_N", 5),
            storm_window_s=_env_float("OT_PULSE_STORM_S", 60.0),
            pressure_frac=_env_float("OT_PULSE_PRESSURE_FRAC", 0.9),
            pressure_ticks=_env_int("OT_PULSE_PRESSURE_TICKS", 3),
        )

    def doc(self) -> dict:
        return {k: v for k, v in sorted(vars(self).items())}


# ---------------------------------------------------------------------------
# Frames: one cumulative registry snapshot, flat-keyed.
# ---------------------------------------------------------------------------


_FLAT_RE = re.compile(r"^([^{]+)(?:\{(.*)\})?$")
_PARSE_CACHE: dict[str, tuple] = {}


def _parse_flat(key: str) -> tuple:
    """``name{k=v,...}`` -> (name, ((k, v), ...)) — the inverse of
    ``metrics.flat_name`` (cached: snapshot keys recur every frame)."""
    hit = _PARSE_CACHE.get(key)
    if hit is not None:
        return hit
    m = _FLAT_RE.match(key)
    if m is None:
        out = (key, ())
    else:
        name, lab = m.groups()
        pairs = []
        for part in (lab or "").split(","):
            if "=" in part:
                k, _, v = part.partition("=")
                pairs.append((k, v))
        out = (name, tuple(pairs))
    if len(_PARSE_CACHE) < 4096:
        _PARSE_CACHE[key] = out
    return out


def frame_from_snapshot(snap: dict, ts_us: int) -> dict:
    """One frame from ``metrics.snapshot()`` (the LIVE source).
    ``pulse_*`` series are excluded — the engine must not consume its
    own output (a fired alert would otherwise perturb later frames)."""
    counters = {k: float(v)
                for k, v in (snap.get("counters") or {}).items()
                if not k.startswith("pulse_")}
    gauges = {k: float(v) for k, v in (snap.get("gauges") or {}).items()}
    hcounts = {k: int((h or {}).get("count", 0))
               for k, h in (snap.get("hists") or {}).items()}
    return {"ts_us": int(ts_us), "counters": counters, "gauges": gauges,
            "hcounts": hcounts}


def frame_from_record(rec: dict) -> dict | None:
    """One frame from a ``metrics-*.jsonl`` snapshot line (the OFFLINE
    source — ``metrics._snapshot_rec``'s list-of-[name, labels, value]
    schema, rebuilt into the same flat keys the live source uses)."""
    if not isinstance(rec, dict) or "ts" not in rec:
        return None

    def _flat(name, labels):
        return metrics.flat_name(str(name),
                                 tuple(sorted((labels or {}).items())))

    counters: dict[str, float] = {}
    for name, labels, v in rec.get("counters") or []:
        if str(name).startswith("pulse_"):
            continue
        counters[_flat(name, labels)] = float(v)
    gauges = {_flat(n, lab): float(v)
              for n, lab, v in rec.get("gauges") or []}
    hcounts = {_flat(n, lab): int((doc or {}).get("count", 0))
               for n, lab, doc in rec.get("hists") or []}
    return {"ts_us": int(rec["ts"]), "counters": counters,
            "gauges": gauges, "hcounts": hcounts}


def _match(labels: tuple, want: dict) -> bool:
    d = dict(labels)
    return all(d.get(k) == v for k, v in want.items())


def _total(part: dict, name: str, **want) -> float:
    """Sum of one metric name across label sets (optionally filtered
    by a label subset) in one frame part."""
    out = 0.0
    for key, v in part.items():
        n, labels = _parse_flat(key)
        if n != name:
            continue
        if want and not _match(labels, want):
            continue
        out += v
    return out


def _by_labels(part: dict, name: str, keys: tuple) -> dict:
    """(label values tuple) -> summed value for one metric name."""
    out: dict[tuple, float] = {}
    for key, v in part.items():
        n, labels = _parse_flat(key)
        if n != name:
            continue
        d = dict(labels)
        k = tuple(d.get(lk, "") for lk in keys)
        out[k] = out.get(k, 0.0) + v
    return out


# ---------------------------------------------------------------------------
# The engine.
# ---------------------------------------------------------------------------


class PulseEngine:
    """The streaming rule engine: feed cumulative frames in time order
    via ``observe``; read ``alerts_doc`` (the /alertz body),
    ``capacity`` (the /healthz + artifact section), ``fired`` (rule ->
    count). One engine per metrics stream — cumulative counters are
    per-process, so the offline replay runs one engine per snapshot
    file. ``emit=False`` (replay) evaluates identically but emits
    nothing: no counters, no trace points, no bundles."""

    def __init__(self, config: PulseConfig | None = None, *,
                 source: str = "serve", proc: str | None = None,
                 emit: bool = True):
        self.config = config or PulseConfig.from_env()
        self.source = source
        self.proc = proc or f"{source}:{os.getpid()}"
        self._emit_enabled = bool(emit)
        self.frames: collections.deque = collections.deque()
        self.alerts: collections.deque = collections.deque(
            maxlen=MAX_ALERT_ROWS)
        self.fired: dict[str, int] = {}
        self.frames_seen = 0
        self.errors = 0
        #: edge-trigger state: rule-instance key -> armed?
        self._armed: dict[str, bool] = {}
        #: capacity baselines: (engine, mode) -> {"ewma", "updates"}
        self._baseline: dict[tuple, dict] = {}
        self._pressure_run = 0
        self._cost: dict[tuple, dict] = {}
        self._lock = threading.Lock()

    # -- inputs ------------------------------------------------------------
    def set_cost_records(self, records) -> None:
        """Attach the process's cost-model records (obs/costmodel.py)
        so capacity rows carry the modeled-bytes cross-check."""
        try:
            self._cost = {
                (r.get("engine"), r.get("mode"), int(r.get("rung", 0))): r
                for r in records or ()}
        except Exception:  # noqa: BLE001 - optional evidence only
            self._cost = {}

    def observe(self, frame: dict | None) -> list[dict]:
        """Push one frame and evaluate every rule; returns the alerts
        that fired ON THIS FRAME. Never raises (counted)."""
        try:
            if not isinstance(frame, dict) or "ts_us" not in frame:
                return []
            with self._lock:
                return self._observe_locked(frame)
        except Exception:  # noqa: BLE001 - never-raises contract
            self.errors += 1
            return []

    def _observe_locked(self, frame: dict) -> list[dict]:
        c = self.config
        prev = self.frames[-1] if self.frames else None
        if prev is not None and frame["ts_us"] <= prev["ts_us"]:
            return []  # out-of-order / duplicate snapshot: drop
        self.frames.append(frame)
        self.frames_seen += 1
        keep_us = int(max(c.slow_window_s, c.flap_window_s,
                          c.storm_window_s) * 1e6) + int(60e6)
        while (len(self.frames) > 2
               and frame["ts_us"] - self.frames[1]["ts_us"] > keep_us):
            self.frames.popleft()
        self._update_baselines(frame, prev)
        out: list[dict] = []
        for rule, key, cond, detail in self._conditions(frame):
            armed = self._armed.get(key, True)
            if cond and armed:
                self._armed[key] = False
                out.append(self._fire(rule, frame["ts_us"], detail))
            elif not cond:
                self._armed[key] = True
        return out

    # -- window helpers ----------------------------------------------------
    def _window_start(self, now_us: int, window_s: float) -> dict | None:
        """The newest frame at least ``window_s`` older than ``now_us``
        — None until the retained history covers the window, so a rule
        never judges a half-filled window (the ramp-in guard)."""
        cut = now_us - int(window_s * 1e6)
        start = None
        for f in self.frames:
            if f["ts_us"] <= cut:
                start = f
            else:
                break
        return start

    def _delta(self, frame: dict, start: dict, part: str, name: str,
               **want) -> float:
        return (_total(frame[part], name, **want)
                - _total(start[part], name, **want))

    # -- the rules ---------------------------------------------------------
    def _conditions(self, frame: dict):
        """Yield (rule, instance-key, condition, detail) for every rule
        instance — the one place the closed rule set is evaluated, live
        and replayed alike."""
        yield self._burn_rate(frame)
        yield from self._capacity_collapse(frame)
        yield self._quarantine_flap(frame)
        yield self._compile_storm(frame)
        yield self._reassembly_pressure(frame)

    def _bad_total(self, frame: dict, start: dict) -> tuple[float, float]:
        """(bad events, total offered events) across the window — both
        tiers' budget-spending signals summed (a process is one tier;
        the other tier's series are simply absent)."""
        bad = 0.0
        for outcome in BAD_BATCH_OUTCOMES:
            bad += self._delta(frame, start, "counters", "serve_batches",
                               outcome=outcome)
        bad += self._delta(frame, start, "counters",
                           "serve_deadline_expired")
        bad += self._delta(frame, start, "counters", "serve_shed")
        bad += self._delta(frame, start, "counters", "route_shed")
        bad += self._delta(frame, start, "counters", "route_exhausted")
        total = self._delta(frame, start, "counters", "serve_requests")
        total += self._delta(frame, start, "counters", "serve_shed")
        # The router's per-request admission signal is the router_queue
        # stage observation (one per admitted request).
        total += self._delta(frame, start, "hcounts", "route_stage_us",
                             stage="router_queue")
        total += self._delta(frame, start, "counters", "route_shed")
        total += self._delta(frame, start, "counters", "route_exhausted")
        return bad, total

    def _burn_rate(self, frame: dict):
        c = self.config
        now = frame["ts_us"]
        fast = self._window_start(now, c.fast_window_s)
        slow = self._window_start(now, c.slow_window_s)
        if fast is None or slow is None:
            return "burn_rate", "burn_rate", False, {}
        bad_f, tot_f = self._bad_total(frame, fast)
        bad_s, tot_s = self._bad_total(frame, slow)
        burn_f = (bad_f / tot_f / c.budget) if tot_f > 0 else 0.0
        burn_s = (bad_s / tot_s / c.budget) if tot_s > 0 else 0.0
        cond = (tot_f >= c.min_events and bad_f > 0
                and burn_f >= c.fast_burn and burn_s >= c.slow_burn)
        detail = {"burn_fast": round(burn_f, 3),
                  "burn_slow": round(burn_s, 3),
                  "bad_fast": int(bad_f), "total_fast": int(tot_f),
                  "budget": c.budget}
        return "burn_rate", "burn_rate", cond, detail

    def _rates_by_engine_mode(self, frame: dict,
                              start: dict) -> dict[tuple, dict]:
        """(engine, mode) -> {"blocks_per_s", "dispatches",
        "device_us"} over the window. Blocks are estimated as rung x
        dispatches (the rung label IS the padded block capacity), an
        upper bound the occupancy section refines post-hoc — consistent
        is what a baseline comparison needs, not exact."""
        dt_s = (frame["ts_us"] - start["ts_us"]) / 1e6
        if dt_s <= 0:
            return {}
        disp = {}
        for part, acc in ((frame, 1.0), (start, -1.0)):
            for key, v in part["counters"].items():
                n, labels = _parse_flat(key)
                if n not in ("serve_rung_dispatches",
                             "serve_rung_device_us"):
                    continue
                d = dict(labels)
                k = (d.get("engine", ""), d.get("mode", ""))
                row = disp.setdefault(
                    k, {"blocks": 0.0, "dispatches": 0.0,
                        "device_us": 0.0})
                if n == "serve_rung_dispatches":
                    row["dispatches"] += acc * v
                    try:
                        row["blocks"] += acc * v * float(d.get("rung", 0))
                    except ValueError:
                        pass
                else:
                    row["device_us"] += acc * v
        out = {}
        for k, row in disp.items():
            if row["dispatches"] <= 0:
                if self._baseline.get(k) is None:
                    continue
                row = {"blocks": 0.0, "dispatches": 0.0, "device_us": 0.0}
            out[k] = {"blocks_per_s": row["blocks"] / dt_s,
                      "dispatches": row["dispatches"],
                      "device_us": row["device_us"], "dt_s": dt_s}
        return out

    def _update_baselines(self, frame: dict, prev: dict | None) -> None:
        """Fold the fast-window throughput into the per-(engine, mode)
        EWMA — skipped while the collapse condition holds for that key
        (baseline freeze: an incident must not become its own new
        normal)."""
        c = self.config
        start = self._window_start(frame["ts_us"], c.fast_window_s)
        if start is None:
            return
        for k, row in self._rates_by_engine_mode(frame, start).items():
            if row["dispatches"] < c.min_dispatches:
                continue
            base = self._baseline.get(k)
            rate = row["blocks_per_s"]
            if base is None:
                self._baseline[k] = {"ewma": rate, "updates": 1}
                continue
            if (base["updates"] >= c.baseline_frames
                    and rate < c.collapse_frac * base["ewma"]):
                continue  # collapsing: freeze the reference
            base["ewma"] = (c.ewma_alpha * rate
                            + (1.0 - c.ewma_alpha) * base["ewma"])
            base["updates"] += 1

    def _capacity_collapse(self, frame: dict):
        c = self.config
        start = self._window_start(frame["ts_us"], c.fast_window_s)
        demand = frame["gauges"].get("serve_queue_depth", 0.0) > 0
        rates = (self._rates_by_engine_mode(frame, start)
                 if start is not None else {})
        for k, base in sorted(self._baseline.items()):
            key = f"capacity_collapse:{k[0]}:{k[1]}"
            row = rates.get(k)
            ready = base["updates"] >= c.baseline_frames
            cond = (ready and demand and row is not None
                    and base["ewma"] > 0
                    and row["blocks_per_s"]
                    < c.collapse_frac * base["ewma"])
            detail = {"engine": k[0], "mode": k[1],
                      "blocks_per_s": round(
                          row["blocks_per_s"], 3) if row else None,
                      "baseline_blocks_per_s": round(base["ewma"], 3),
                      "collapse_frac": c.collapse_frac}
            yield "capacity_collapse", key, cond, detail

    def _quarantine_flap(self, frame: dict):
        c = self.config
        start = self._window_start(frame["ts_us"], c.flap_window_s)
        if start is None:
            return "quarantine_flap", "quarantine_flap", False, {}
        n = self._delta(frame, start, "counters", "serve_lane_transitions",
                        state="quarantined")
        n += self._delta(frame, start, "counters",
                         "route_backend_transitions", state="quarantined")
        cond = n >= c.flap_n
        return ("quarantine_flap", "quarantine_flap", cond,
                {"transitions": int(n), "window_s": c.flap_window_s,
                 "flap_n": c.flap_n})

    def _compile_storm(self, frame: dict):
        c = self.config
        start = self._window_start(frame["ts_us"], c.storm_window_s)
        if start is None:
            return "compile_storm", "compile_storm", False, {}
        # Warmup guard: only a window whose START already saw traffic
        # counts — the warmup compile ramp is wholly behind it then.
        traffic = _total(start["counters"], "serve_batches") > 0
        n = self._delta(frame, start, "hcounts", "serve_compile_us")
        cond = traffic and n >= c.storm_n
        return ("compile_storm", "compile_storm", cond,
                {"compiles": int(n), "window_s": c.storm_window_s,
                 "storm_n": c.storm_n})

    def _reassembly_pressure(self, frame: dict):
        c = self.config
        held = frame["gauges"].get("serve_reassembly_held_bytes", 0.0)
        budget = frame["gauges"].get("serve_transfer_budget_bytes", 0.0)
        pinned = budget > 0 and held >= c.pressure_frac * budget
        self._pressure_run = self._pressure_run + 1 if pinned else 0
        cond = self._pressure_run >= c.pressure_ticks
        return ("reassembly_pressure", "reassembly_pressure", cond,
                {"held_bytes": int(held), "budget_bytes": int(budget),
                 "pressure_frac": c.pressure_frac,
                 "run": self._pressure_run})

    # -- emission ----------------------------------------------------------
    def _fire(self, rule: str, ts_us: int, detail: dict) -> dict:
        severity = "page" if rule in PAGE_RULES else "warn"
        alert = {"rule": rule, "severity": severity, "ts_us": ts_us,
                 "proc": self.proc, "detail": detail}
        self.alerts.append(alert)
        self.fired[rule] = self.fired.get(rule, 0) + 1
        if not self._emit_enabled:
            return alert
        try:
            metrics.counter("pulse_alerts", rule=rule, severity=severity)
        except Exception:  # noqa: BLE001 - never-raises contract
            pass
        try:
            trace.point("pulse-alert", rule=rule, severity=severity,
                        proc=self.proc)
        except Exception:  # noqa: BLE001 - never-raises contract
            pass
        if severity == "page":
            try:
                from . import incident

                incident.trigger("pulse-alert", rule=rule, **{
                    k: v for k, v in detail.items() if v is not None})
            except Exception:  # noqa: BLE001 - never a second incident
                pass
        try:
            from . import profiler

            profiler.on_alert(rule)
        except Exception:  # noqa: BLE001 - never-raises contract
            pass
        return alert

    # -- outputs -----------------------------------------------------------
    def capacity(self) -> dict:
        """The live capacity estimate: per-(engine, mode) measured
        blocks/s (fast-window rate + EWMA baseline), with the modeled
        HBM-bytes cross-check when cost records are attached. The
        ``total_blocks_per_s`` scalar is what the fleet supervisor's
        headroom policy reads off /healthz."""
        with self._lock:
            frame = self.frames[-1] if self.frames else None
            rates = {}
            if frame is not None:
                start = self._window_start(frame["ts_us"],
                                           self.config.fast_window_s)
                if start is not None:
                    rates = self._rates_by_engine_mode(frame, start)
            rows = []
            total = 0.0
            for k in sorted(set(self._baseline) | set(rates)):
                base = self._baseline.get(k)
                row = rates.get(k)
                ewma = base["ewma"] if base else 0.0
                cur = row["blocks_per_s"] if row else 0.0
                cap = max(ewma, cur)
                total += cap
                out = {"engine": k[0], "mode": k[1],
                       "blocks_per_s": round(cur, 3),
                       "ewma_blocks_per_s": round(ewma, 3),
                       "updates": base["updates"] if base else 0}
                if row and row["device_us"] > 0:
                    out["device_util"] = round(
                        row["device_us"] / (row["dt_s"] * 1e6), 6)
                rec = None
                if self._cost:
                    cands = [r for (e, m, _), r in self._cost.items()
                             if e == k[0] and m == k[1]]
                    rec = cands[0] if cands else None
                if rec and row and row["dt_s"] > 0:
                    out["modeled_gbps"] = round(
                        float(rec.get("hbm_bytes", 0))
                        * row["dispatches"] / 1e9 / row["dt_s"], 6)
                rows.append(out)
            return {"rows": rows,
                    "total_blocks_per_s": round(total, 3),
                    "measured": bool(rows),
                    "frames": self.frames_seen}

    def alerts_doc(self) -> dict:
        """The /alertz body for this engine."""
        with self._lock:
            return {"kind": KIND, "v": VERSION, "proc": self.proc,
                    "source": self.source, "frames": self.frames_seen,
                    "errors": self.errors,
                    "fired": dict(sorted(self.fired.items())),
                    "total": sum(self.fired.values()),
                    "alerts": list(self.alerts)}


# ---------------------------------------------------------------------------
# The live engine: one daemon thread per process.
# ---------------------------------------------------------------------------


class PulseThread(threading.Thread):
    """The live cadence: snapshot the registry every ``every_s`` and
    feed the engine. Daemon + never-raises — analytics must never take
    the service down."""

    def __init__(self, engine: PulseEngine, period_s: float | None = None):
        super().__init__(daemon=True, name="ot-pulse")
        self.engine = engine
        self._period = period_s if period_s is not None else every_s()
        self._halt = threading.Event()

    def run(self) -> None:
        while not self._halt.wait(self._period):
            self.tick()

    def tick(self) -> list[dict]:
        try:
            frame = frame_from_snapshot(metrics.snapshot(),
                                        time.time_ns() // 1000)
            return self.engine.observe(frame)
        except Exception:  # noqa: BLE001 - never-raises contract
            self.engine.errors += 1
            return []

    def stop(self) -> None:
        self._halt.set()


def start_live(source: str = "serve",
               config: PulseConfig | None = None,
               cost_records=None) -> PulseThread | None:
    """Start the per-process live engine (None when ``OT_PULSE=0``).
    The server and router call this from their start() paths."""
    if not enabled():
        return None
    try:
        # A live engine's verdict must be reproducible offline from the
        # run directory, so any process that runs one also journals its
        # metrics snapshot stream (the replay CLI's input).
        metrics.ensure_flusher()
    except Exception:  # noqa: BLE001 - never-raises contract
        pass
    engine = PulseEngine(config, source=source)
    if cost_records:
        engine.set_cost_records(cost_records)
    t = PulseThread(engine)
    t.start()
    return t


# ---------------------------------------------------------------------------
# Offline replay (the deterministic half).
# ---------------------------------------------------------------------------


_SEG_RE = re.compile(r"^(metrics-\d+-[0-9a-f]+)(?:-s(\d+))?\.jsonl$")


def _streams(run_dir: str) -> dict[str, list[str]]:
    """Process stream key -> ordered snapshot segment paths. Rotated
    ``-s<k>`` segments sort before the live tail file (rotation moves
    the OLDER prefix out, the base name stays the newest)."""
    out: dict[str, list] = {}
    for path in glob.glob(os.path.join(run_dir, "metrics-*.jsonl")):
        m = _SEG_RE.match(os.path.basename(path))
        if m is None:
            continue
        stem, seg = m.groups()
        out.setdefault(stem, []).append(
            (int(seg) if seg is not None else (1 << 30), path))
    return {stem: [p for _, p in sorted(segs)]
            for stem, segs in sorted(out.items())}


def replay_stream(paths: list[str],
                  config: PulseConfig | None = None,
                  proc: str | None = None) -> dict:
    """Replay one process's snapshot stream through a fresh engine
    (emit=False). Returns the engine's verdict plus the live-engine
    record: the ``pulse_alerts`` counters found in the stream's final
    snapshot (what the in-process engine actually fired)."""
    engine = PulseEngine(config, proc=proc or "replay", emit=False)
    frames = 0
    live: dict[str, int] = {}
    interval_s = None
    for path in paths:
        try:
            fh = open(path, encoding="utf-8")
        except OSError:
            continue
        with fh:
            for line in fh:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(rec, dict):
                    continue
                if rec.get("kind") == metrics.KIND:
                    interval_s = rec.get("interval_s", interval_s)
                    continue
                frame = frame_from_record(rec)
                if frame is None:
                    continue
                engine.observe(frame)
                frames += 1
                for name, labels, v in rec.get("counters") or []:
                    if name == "pulse_alerts":
                        rule = (labels or {}).get("rule", "?")
                        live[rule] = int(v)
    return {"proc": proc, "frames": frames, "interval_s": interval_s,
            "fired": dict(sorted(engine.fired.items())),
            "alerts": list(engine.alerts),
            "live_fired": dict(sorted(live.items())),
            "errors": engine.errors}


def replay_run(run_dir: str, config: PulseConfig | None = None) -> dict:
    """Replay every process stream in one run dir; merge per-stream
    verdicts into the run-level document the CLI prints (and --check
    gates)."""
    streams = []
    fired: dict[str, int] = {}
    live: dict[str, int] = {}
    alerts: list[dict] = []
    for stem, paths in _streams(run_dir).items():
        res = replay_stream(paths, config, proc=stem)
        streams.append(res)
        for rule, n in res["fired"].items():
            fired[rule] = fired.get(rule, 0) + n
        for rule, n in res["live_fired"].items():
            live[rule] = live.get(rule, 0) + n
        alerts.extend(res["alerts"])
    alerts.sort(key=lambda a: a.get("ts_us", 0))
    return {"kind": f"{KIND}-replay", "v": VERSION, "run_dir": run_dir,
            "streams": streams, "procs": len(streams),
            "frames": sum(s["frames"] for s in streams),
            "fired": dict(sorted(fired.items())),
            "live_fired": dict(sorted(live.items())),
            "alerts": alerts}


def check(doc: dict) -> list[str]:
    """The --check verdict: the replayed fired-rule SET must equal the
    rule set the live engine recorded (``pulse_alerts`` counters in the
    final snapshots). Sets, not counts: the live cadence and the
    flusher cadence sample the same stream at different phases, so
    firing multiplicity may differ by one while the judgment — which
    rules tripped — must not."""
    out = []
    if not doc.get("procs"):
        out.append("no metrics-*.jsonl streams found in run dir")
        return out
    replayed = set(doc.get("fired") or {})
    recorded = set(doc.get("live_fired") or {})
    for rule in sorted(recorded - replayed):
        out.append(f"live engine fired {rule!r} but replay did not")
    for rule in sorted(replayed - recorded):
        out.append(f"replay fired {rule!r} but the live engine did not")
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m our_tree_tpu.obs.pulse",
        description="Replay a run dir's metrics snapshots through the "
                    "pulse rule engine (deterministic offline alerts). "
                    "Run with the same OT_PULSE_* env as the live drive "
                    "— thresholds are configuration, not code.")
    ap.add_argument("run_dir", help="one OT_TRACE_DIR run directory")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 unless the replayed fired-rule set "
                         "matches the live engine's pulse_alerts record")
    ap.add_argument("--json", action="store_true",
                    help="print only the JSON document")
    args = ap.parse_args(argv)
    doc = replay_run(args.run_dir, PulseConfig.from_env())
    problems = check(doc) if args.check else []
    doc["check"] = {"ran": bool(args.check), "problems": problems}
    if not args.json:
        print(f"# pulse: {doc['procs']} stream(s), {doc['frames']} "
              f"frame(s) replayed from {args.run_dir}")
        for a in doc["alerts"]:
            print(f"# alert: {a['rule']} [{a['severity']}] "
                  f"proc={a['proc']} detail={json.dumps(a['detail'])}")
        if not doc["alerts"]:
            print("# alert: none fired")
        if args.check:
            for p in problems:
                print(f"# check: FAIL {p}")
            if not problems:
                print(f"# check: ok (replayed rules == live rules: "
                      f"{sorted(set(doc['fired'])) or '[]'})")
    print(json.dumps(doc, sort_keys=True))
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
