"""The incident flight recorder: a bounded dispatch ring + evidence bundles.

When a serve incident fires — a watchdog kill, a quarantine, an SLO
breach, an auth-failure spike — the evidence an operator needs is
scattered: the trace stream has the force-sampled spans (somewhere in a
rotating JSONL), the registry has the exact counters (last snapshot),
the cost model knows what the dispatches should have cost, and the
dispatch history right before the event is nowhere at all once spans
are sampled. This module is the black box:

* **The ring** (``record``): a bounded in-memory deque of the most
  recent TRAFFIC dispatch records — lane, rung, engine, mode, outcome,
  device/wall µs, batch label, timestamp — appended by the lane seam
  on every dispatch completion (serve/lanes.py), O(1), never-raises,
  ``OT_INCIDENT_RING`` entries (default 256, 0 disables). Warmup and
  canary dispatches are not traffic and stay out.
* **Triggers** (``trigger``): the four incident classes dump a
  self-contained bundle into the OT_TRACE_DIR run layout —
  ``incident-<pid>-<tok>-<n>.json`` beside the trace/metrics/cost
  files — holding the ring, the full metrics snapshot, the degrade
  ledger, the process's cost records, and the trigger's own attrs.
  The force-sampled spans the incident left live in the trace stream
  beside it (the bundle stamps the run id that joins them).
  Triggers COALESCE: one incident is usually several signals within
  milliseconds (the watchdog kill quarantines its lane), so a trigger
  inside ``OT_INCIDENT_COOLDOWN_S`` (default 30) of the last bundle is
  counted as suppressed instead of dumping a near-identical bundle —
  the CI lane-kill drive's "exactly one bundle" gate is this rule.
  ``OT_INCIDENT_MAX`` (default 8) bounds bundles per process.
* **Auth-failure spike** (``note_auth_failure``): single tag
  mismatches are data events (a per-request refusal, by design); a
  SPIKE — ``OT_INCIDENT_AUTH_SPIKE`` (default 3) failures within
  ``OT_INCIDENT_AUTH_WINDOW_S`` (default 10) — is an incident
  (key confusion, an attack, a broken client) and triggers.

Reading: ``obs.report --incidents <run-dir>`` renders every bundle and
``--check`` gates their schema (``validate_bundle``); the status
endpoint's ``/incidentz`` lists them live (serve/status.py). Same
constitution as trace/metrics: never raises, and with tracing OFF the
ring still records in memory (for /incidentz) while bundles are
skipped — the run layout is where bundles live.
"""

from __future__ import annotations

import collections
import json
import glob
import os
import time
import uuid

from . import metrics, trace

KIND = "ot-incident"
VERSION = 1

#: Bundle schema: the keys every bundle must carry, and the fields
#: every ring record must carry (``validate_bundle``).
REQUIRED_KEYS = ("kind", "v", "run", "pid", "ts_us", "reason", "ring",
                 "metrics")
RING_REQUIRED = ("t_us", "outcome")

#: The closed trigger vocabulary (a ``reason`` outside it is a schema
#: violation — new incident classes are added here deliberately).
REASONS = ("watchdog-kill", "quarantine", "slo-breach", "auth-spike",
           "pulse-alert")

_RING: collections.deque | None = None
_PROC = uuid.uuid4().hex[:8]
_BUNDLES = 0
_SUPPRESSED = 0
_LAST_TRIGGER_US: int | None = None
_AUTH_TS: collections.deque = collections.deque(maxlen=64)
_COST_RECORDS: list = []


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default) or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default) or default)
    except ValueError:
        return default


def ring_capacity() -> int:
    return max(_env_int("OT_INCIDENT_RING", 256), 0)


def _now_us() -> int:
    return time.time_ns() // 1000


def _ring() -> collections.deque | None:
    global _RING
    cap = ring_capacity()
    if cap <= 0:
        return None
    if _RING is None or _RING.maxlen != cap:
        _RING = collections.deque(_RING or (), maxlen=cap)  # ot-san: owner=gil-ref-swap
    return _RING


def record(**fields) -> None:
    """Append one dispatch record to the ring (O(1), no I/O, never
    raises). The lane seam calls it per traffic dispatch with lane,
    rung, engine, mode, outcome, device_us, wall_us, batch."""
    try:
        ring = _ring()
        if ring is None:
            return
        rec = {"t_us": _now_us()}
        rec.update(fields)
        ring.append(rec)
    except Exception:  # noqa: BLE001 - never-raises contract
        pass


def snapshot() -> list[dict]:
    """The ring's current contents, oldest first."""
    ring = _ring()
    return [dict(r) for r in ring] if ring else []


def set_cost_records(records) -> None:
    """Attach the process's cost-model records (obs/costmodel.py) so
    bundles are self-contained: the server stamps them at warmup."""
    global _COST_RECORDS
    try:
        _COST_RECORDS = list(records or [])
    except Exception:  # noqa: BLE001 - never-raises contract
        _COST_RECORDS = []


def counts() -> dict:
    """{dumped, suppressed, ring} — the /incidentz live header."""
    ring = _ring()
    return {"dumped": _BUNDLES, "suppressed": _SUPPRESSED,
            "ring": len(ring) if ring else 0}


# ot-san: absorb=rate-capped-evidence-dump (cooldown + per-process cap)
def trigger(reason: str, **attrs) -> str | None:
    """Dump one incident bundle (returns its path), or None when
    suppressed: tracing off (no run layout to dump into), within the
    cooldown of the previous bundle (one incident = one bundle even
    when it fires several signals), or past the per-process cap.
    Never raises — an incident dump failing must not create a second
    incident."""
    global _BUNDLES, _SUPPRESSED, _LAST_TRIGGER_US
    try:
        now = _now_us()
        if not trace.enabled():
            return None
        cooldown_us = int(
            max(_env_float("OT_INCIDENT_COOLDOWN_S", 30.0), 0.0) * 1e6)
        if (_LAST_TRIGGER_US is not None
                and now - _LAST_TRIGGER_US < cooldown_us):
            _SUPPRESSED += 1
            metrics.counter("serve_incidents", reason="suppressed")
            return None
        if _BUNDLES >= max(_env_int("OT_INCIDENT_MAX", 8), 1):
            _SUPPRESSED += 1
            metrics.counter("serve_incidents", reason="suppressed")
            return None
        run = trace.ensure_run()
        d = trace.run_dir()
        if d is None:
            return None
        os.makedirs(d, exist_ok=True)
        try:
            from ..resilience import degrade
            degraded = degrade.events()
        except Exception:  # noqa: BLE001 - the ledger is optional evidence
            degraded = []
        doc = {
            "kind": KIND, "v": VERSION, "run": run, "pid": os.getpid(),
            "ts_us": now, "reason": str(reason), "attrs": dict(attrs),
            "ring": snapshot(),
            "metrics": metrics.snapshot(),
            "cost": list(_COST_RECORDS),
            "degraded": degraded,
            "suppressed_before": _SUPPRESSED,
        }
        path = os.path.join(
            d, f"incident-{os.getpid()}-{_PROC}-{_BUNDLES}.json")
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, separators=(",", ":"), sort_keys=True)
            fh.write("\n")
        _BUNDLES += 1
        _LAST_TRIGGER_US = now
        metrics.counter("serve_incidents", reason=str(reason))
        trace.point("incident", reason=str(reason),
                    bundle=os.path.basename(path))
        # OT_PROFILE_ON_INCIDENT: arm one capture window over the
        # incident's aftermath (obs/profiler.py). AFTER the bundle
        # write and only on the non-suppressed path, so the trigger
        # cooldown above is also the capture cooldown — one capture
        # per incident, never a capture storm.
        try:
            from . import profiler

            profiler.on_incident(str(reason))
        except Exception:  # noqa: BLE001 - never a second incident
            pass
        return path
    except Exception:  # noqa: BLE001 - never-raises contract
        return None


def note_auth_failure() -> str | None:
    """One auth-failed refusal. A single mismatch is a data event; a
    SPIKE within the window is an incident and triggers a bundle."""
    try:
        now = _now_us()
        _AUTH_TS.append(now)
        window_us = int(
            max(_env_float("OT_INCIDENT_AUTH_WINDOW_S", 10.0), 0.0) * 1e6)
        spike = max(_env_int("OT_INCIDENT_AUTH_SPIKE", 3), 1)
        recent = sum(1 for t in _AUTH_TS if now - t <= window_us)
        if recent >= spike:
            return trigger("auth-spike", failures=recent,
                           window_s=window_us / 1e6)
        return None
    except Exception:  # noqa: BLE001 - never-raises contract
        return None


# ---------------------------------------------------------------------------
# Reading bundles (report, /incidentz, CI gates).
# ---------------------------------------------------------------------------


def list_bundles(run_dir: str) -> list[str]:
    """Bundle paths in one run dir, oldest first (the per-process
    sequence number orders within a pid; mtime breaks ties across)."""
    paths = glob.glob(os.path.join(run_dir, "incident-*.json"))

    def _key(p):
        try:
            return (os.path.getmtime(p), p)
        except OSError:
            return (0.0, p)

    return sorted(paths, key=_key)


def load_bundle(path: str) -> dict | None:
    try:
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)
        return doc if isinstance(doc, dict) else None
    except (OSError, ValueError):
        return None


def validate_bundle(doc: dict | None) -> list[str]:
    """Schema violations as human-readable strings (empty = valid) —
    what ``obs.report --incidents --check`` gates."""
    if not isinstance(doc, dict):
        return ["bundle is not a JSON object"]
    out = []
    for k in REQUIRED_KEYS:
        if k not in doc:
            out.append(f"missing required key {k!r}")
    if doc.get("kind") != KIND:
        out.append(f"kind is {doc.get('kind')!r}, want {KIND!r}")
    if not isinstance(doc.get("v"), int):
        out.append("v is not an int")
    if doc.get("reason") not in REASONS:
        out.append(f"reason {doc.get('reason')!r} outside {REASONS}")
    ring = doc.get("ring")
    if not isinstance(ring, list):
        out.append("ring is not a list")
    else:
        for i, rec in enumerate(ring):
            if not isinstance(rec, dict):
                out.append(f"ring[{i}] is not an object")
                continue
            for k in RING_REQUIRED:
                if k not in rec:
                    out.append(f"ring[{i}] missing {k!r}")
    if not isinstance(doc.get("metrics"), dict):
        out.append("metrics is not an object")
    return out


def bundle_index(run_dir: str) -> list[dict]:
    """Light per-bundle summaries for /incidentz (no payload bytes):
    file, reason, ts_us, ring length, valid flag."""
    out = []
    for path in list_bundles(run_dir):
        doc = load_bundle(path)
        out.append({
            "file": os.path.basename(path),
            "reason": (doc or {}).get("reason"),
            "ts_us": (doc or {}).get("ts_us"),
            "ring": len((doc or {}).get("ring", [])
                        if isinstance((doc or {}).get("ring"), list)
                        else []),
            "valid": not validate_bundle(doc),
        })
    return out


def reset_for_tests() -> None:
    global _RING, _BUNDLES, _SUPPRESSED, _LAST_TRIGGER_US, _COST_RECORDS
    _RING = None
    _BUNDLES = 0
    _SUPPRESSED = 0
    _LAST_TRIGGER_US = None
    _AUTH_TS.clear()
    _COST_RECORDS = []
