"""The process-global tracer: spans, counters, gauges, instant points.

One process = one append-only JSONL event file under the run directory
``$OT_TRACE_DIR/<run-id>/trace-<pid>-<tok>.jsonl``; one run = every
process that inherited the same ``OT_TRACE_RUN`` (the supervisor
generates the id at top level and children get it through the
environment). ``obs.export`` stitches the files back into one story —
including across the process boundary: a child's root spans carry the
parent span id handed down via ``OT_TRACE_PARENT`` (``child_env``), so
an ``--isolate`` child's dispatch spans nest under the supervisor's
unit-attempt span exactly as in-process spans nest under their
enclosing ``with``.

Design constraints, in order:

* **Off means free.** With ``OT_TRACE_DIR`` unset every public call is
  one module-global check; ``span()`` returns a shared no-op context
  manager. The instrumented seams include per-iteration timed regions
  (``harness.bench._time_us``, the TpuBackend barrier), so the disabled
  path must not show up in benchmark numbers.
* **SIGKILL-durable.** Span *begin* and span *end* are separate events,
  flushed as written: a child SIGKILLed mid-dispatch leaves its begin
  event on disk, and the unmatched begin — an *orphaned span* — is the
  primary evidence of where it died (``obs.report`` renders it as
  "closed by kill"). A single buffered end-of-span record would lose
  exactly the spans that matter most.
* **Never raises.** Tracing is an observer: a full disk or an
  unserializable attr must degrade to a dropped event (counted in
  ``_DROPPED``, surfaced in ``metrics_snapshot``), never to a failed
  sweep. Attrs serialize with ``default=repr`` so arbitrary objects
  cannot poison an event line.

Event schema (v1; every file starts with a header line — the full field
tables live in docs/OBSERVABILITY.md)::

    {"kind":"ot-trace","v":1,"run":...,"pid":...,"proc":"a1b2c3d4",
     "argv":"...","start_us":...}
    {"ev":"b","id":"a1b2c3d4.1","parent":null,"name":"unit","ts":...,
     "tid":0,"attrs":{"unit":"ecb:65536"}}
    {"ev":"e","id":"a1b2c3d4.1","ts":...,"status":"ok","attrs":{...}}
    {"ev":"c","name":"retry_failures","ts":...,"n":1,"attrs":{...}}
    {"ev":"g","name":"hbm_gib","ts":...,"value":1.5,"attrs":{...}}
    {"ev":"p","name":"fault-injected","ts":...,"attrs":{...}}

``ts`` is epoch microseconds (``time.time_ns()//1000``) — the one clock
that is comparable across the processes of a run; span ids are
``<proc-token>.<seq>`` and globally unique within a run (the 8-hex
process token absorbs pid reuse).

Long runs can cap their disk footprint with ``OT_TRACE_MAX_MB`` (see
``_max_bytes``): the event file rotates into ``-s<k>`` segments and the
oldest segments are deleted, keeping the process under the cap at the
cost of the evicted history — the soak-run tradeoff. High-rate serving
additionally HEAD-SAMPLES its per-request lifecycle spans
(``OT_TRACE_SAMPLE`` + ``sample()``/``maybe_span()``): the decision is
made once per request at admission, an unsampled span costs two clock
reads and no I/O, and abnormal outcomes force-materialise their spans
retroactively so incident evidence — including the orphan-as-kill
convention — survives any rate. The exact companion totals live in the
sibling ``obs/metrics.py`` registry.

Stdlib-only, no intra-package imports (bare-loadable by the jax-free
sweep parents and the repo-root bench.py). Bare loaders must register
this module under ``our_tree_tpu.obs.trace`` in ``sys.modules`` (see
``scripts/_devlock_loader.py:load_obs``) so span stacks and counters
stay one-per-process across bare and package import contexts.
"""

from __future__ import annotations

import json
import os
import random
import sys
import threading
import time
import uuid

KIND = "ot-trace"
VERSION = 1

#: Aggregated in-process metrics (the ``"obs"`` stamp in the bench JSON
#: line): name -> total for counters, name -> last value for gauges.
_COUNTS: dict[str, float] = {}
_GAUGES: dict[str, float] = {}
_SPANS_STARTED = 0
_DROPPED = 0
#: Bytes of trace history deleted by segment rotation (OT_TRACE_MAX_MB
#: eviction). Truncation must be visible, never silent: the counter
#: rides ``metrics_snapshot`` so a capped soak's artifacts say how much
#: history the cap cost.
_EVICTED_BYTES = 0

_LOCK = threading.Lock()
_TLS = threading.local()
_TIDS: dict[int, int] = {}

#: Lazily-opened per-process state: {"run","dir","fh","proc","seq"}.
#: None until the first enabled event; reset_for_tests() clears it.
_STATE: dict | None = None


def enabled() -> bool:
    """Tracing is on iff ``OT_TRACE_DIR`` is set (the one switch)."""
    return bool(os.environ.get("OT_TRACE_DIR"))


#: (raw env string, parsed rate) — one float parse per distinct value.
_SAMPLE_CACHE: tuple[str, float] = ("", 1.0)


def sample_rate() -> float:
    """The head-sampling rate (``OT_TRACE_SAMPLE``), clamped to [0, 1].

    Unset / 1 = every request's spans are traced (the pre-sampling
    behaviour, and the right default for rehearsals and CI gates that
    reconstruct complete runs). Below 1, per-REQUEST lifecycle spans are
    emitted for the sampled fraction only — the saturation-run knob:
    steady-state traffic pays near-zero trace cost while the metrics
    registry (``obs/metrics.py``) stays exact and abnormal outcomes are
    force-sampled (``maybe_span``). Sampling is decided per request at
    admission, never per span, so one request's spans appear or vanish
    together."""
    global _SAMPLE_CACHE
    raw = os.environ.get("OT_TRACE_SAMPLE", "")
    cached_raw, cached = _SAMPLE_CACHE
    if raw == cached_raw:
        return cached
    try:
        rate = min(max(float(raw), 0.0), 1.0) if raw else 1.0
    except ValueError:
        rate = 1.0
    _SAMPLE_CACHE = (raw, rate)
    return rate


def sample() -> bool:
    """One head-sampling coin flip (the admission-time decision)."""
    rate = sample_rate()
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return random.random() < rate


def _now_us() -> int:
    return time.time_ns() // 1000


def now_us() -> int:
    """Epoch microseconds — the run's ONE cross-process clock (every
    trace event's ``ts`` domain). Public for the wire handshake stamps
    (serve/worker.py reply clocks, route/proxy.py skew estimation):
    epoch time belongs to the tracer, and call sites that need it take
    it from here instead of reading the wall clock themselves (the
    otlint ``wallclock`` rule's contract)."""
    return time.time_ns() // 1000


def _tid() -> int:
    """Small per-thread index (0 = whichever thread traced first) —
    readable in the event stream and in Perfetto's track names, unlike
    the raw 64-bit ``threading.get_ident``."""
    ident = threading.get_ident()
    with _LOCK:
        return _TIDS.setdefault(ident, len(_TIDS))


def _stack() -> list:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


def run_id() -> str | None:
    """The current run id (None while disabled)."""
    if not enabled():
        return None
    state = _STATE
    if state is not None:
        return state["run"]
    return os.environ.get("OT_TRACE_RUN") or None


def ensure_run() -> str | None:
    """Generate-or-adopt the run id and publish it into ``os.environ``.

    Top-level entry points (harness.bench main, repo-root bench.py)
    call this once, early: a fresh id is minted only when the
    environment carries none, so an ``--isolate`` child — or any
    subprocess — joins its parent's run instead of starting a new one.
    Publishing into ``os.environ`` is what makes plain ``subprocess``
    spawns inherit the id without every call site learning about
    tracing. Returns the id, or None while disabled.
    """
    if not enabled():
        return None
    rid = os.environ.get("OT_TRACE_RUN")
    if not rid:
        rid = time.strftime("%Y%m%d-%H%M%S") + f"-{os.getpid()}"
        os.environ["OT_TRACE_RUN"] = rid
    return rid


def run_dir() -> str | None:
    """``$OT_TRACE_DIR/<run-id>`` (created on first event; None while
    disabled)."""
    if not enabled():
        return None
    return os.path.join(os.environ["OT_TRACE_DIR"], ensure_run())


def _max_bytes() -> int:
    """The per-process trace-size cap (``OT_TRACE_MAX_MB``), in bytes.

    0 / unset = unbounded (the default: short runs and CI gates want the
    complete stream). When set, the process's event file rotates into
    fixed-size segments and the OLDEST segments are deleted so this
    process never keeps more than the cap on disk — the week-long soak
    knob (ROADMAP PR-3 follow-up). A rotated run whose segments all
    SURVIVE reconstructs completely (``obs.export`` stitches segments in
    write order), so ``--check`` gating works under a cap as long as the
    run fits it — the serve CI lane-kill drive gates exactly that way.
    Past the cap, bounded necessarily means lossy: spans whose begin
    fell in a DELETED segment surface in ``obs.report`` as
    end-without-begin violations, so soak monitoring beyond the cap
    should read the self-contained events (counters/points/gauges).
    """
    try:
        mb = float(os.environ.get("OT_TRACE_MAX_MB", 0) or 0)
    except ValueError:
        return 0
    return max(int(mb * (1 << 20)), 0)


def _segment_path(state: dict) -> str:
    n = state["seg"]
    suffix = f"-s{n}" if n else ""
    return os.path.join(
        state["dir"],
        f"trace-{state['pid']}-{state['proc']}{suffix}.jsonl")


def _open_segment_locked(state: dict) -> None:
    """Open the current segment file and write its header; caller holds
    ``_LOCK``. Every segment is a self-describing trace file (same
    header schema — ``obs.export`` globs them all); ``seg`` rides along
    so a stitched report can say which slices survive. ``state`` is
    only mutated on full success (a handle is never leaked and a
    failure leaves the previous segment, if any, still live)."""
    path = _segment_path(state)
    fh = open(path, "a", encoding="utf-8")
    try:
        header = {"kind": KIND, "v": VERSION, "run": state["run"],
                  "pid": state["pid"], "proc": state["proc"],
                  "argv": " ".join(sys.argv[:6])[:300],
                  "start_us": _now_us()}
        if state["seg"]:
            header["seg"] = state["seg"]
        fh.write(json.dumps(header, separators=(",", ":"),
                            default=repr) + "\n")
        fh.flush()
    except OSError:
        try:
            fh.close()
        except OSError:
            pass
        raise
    state["fh"], state["path"] = fh, path


def _rotate_locked(state: dict) -> None:
    """Open the next segment, then retire the full one, then drop the
    oldest beyond the cap. Caller holds ``_LOCK``. Best-effort in that
    order on purpose: a failed OPEN (ENOSPC mid-soak — exactly when the
    cap matters) keeps the current handle live and retries on a later
    write, instead of stranding a closed handle that would silently end
    tracing for the rest of the process."""
    old_fh, old_path = state["fh"], state["path"]
    state["seg"] += 1
    try:
        _open_segment_locked(state)
    except OSError:
        state["seg"] -= 1  # still on the old segment; retry next write
        return
    try:
        old_fh.close()
    except OSError:
        pass
    state["segments"].append(old_path)
    # cap/4 per segment -> keep the active one + 3 closed: total <= cap.
    keep = max(int(state["cap_bytes"] // state["seg_bytes"]) - 1, 1)
    global _EVICTED_BYTES
    while len(state["segments"]) > keep:
        victim = state["segments"].pop(0)
        try:
            size = os.path.getsize(victim)
            os.unlink(victim)
            _EVICTED_BYTES += size  # ot-san: owner=lock:_LOCK
        except OSError:
            break


# ot-san: absorb=amortized-run-dir-init (makedirs/open once per process)
def _state() -> dict | None:
    """Open this process's event file (header included) on first use.

    Creation is serialized under ``_LOCK`` (double-checked): worker
    threads and the watchdog monitor can emit their first event
    concurrently, and an unguarded check-then-create would open two
    files, leak the loser's handle, and pair one state's span ids with
    the other's header. The header is written inline — ``_write`` takes
    the same non-reentrant lock.
    """
    global _STATE, _DROPPED
    with _LOCK:
        if _STATE is not None:
            # A run id that changed under us (tests re-pointing
            # OT_TRACE_RUN) means a new logical run: reopen rather than
            # cross-write.
            if _STATE["run"] == os.environ.get("OT_TRACE_RUN",
                                               _STATE["run"]):
                return _STATE
            _close_state_locked()
        try:
            d = run_dir()
            os.makedirs(d, exist_ok=True)
            cap = _max_bytes()
            state = {"run": os.environ["OT_TRACE_RUN"], "dir": d,
                     "proc": uuid.uuid4().hex[:8], "pid": os.getpid(),
                     "seq": 0, "seg": 0, "segments": [],
                     "cap_bytes": cap,
                     "seg_bytes": max(cap // 4, 4096) if cap else 0}
            _open_segment_locked(state)
            _STATE = state
            return _STATE
        except OSError:
            _DROPPED += 1
            return None


def _close_state_locked() -> None:
    """Close + clear _STATE; caller holds _LOCK."""
    global _STATE
    if _STATE is not None:
        try:
            _STATE["fh"].close()
        except OSError:
            pass
        _STATE = None  # ot-san: owner=lock:_LOCK


def _close_state() -> None:
    with _LOCK:
        _close_state_locked()


# ot-san: absorb=buffered-trace-write (flush, never fsync; O(us) append)
def _write(rec: dict) -> None:
    """One JSONL line, flushed (flush reaches the OS, so it survives the
    process's own SIGKILL — only a machine crash could lose it; fsync
    per event would tax the per-iteration seams for no added safety
    against the failure mode tracing exists for)."""
    global _DROPPED
    state = _STATE
    if state is None:
        return
    try:
        line = json.dumps(rec, separators=(",", ":"), default=repr)
    except (TypeError, ValueError):
        _DROPPED += 1  # ot-san: owner=gil-counter
        return
    try:
        with _LOCK:
            state["fh"].write(line + "\n")
            state["fh"].flush()
            if (state["seg_bytes"]
                    and state["fh"].tell() >= state["seg_bytes"]):
                _rotate_locked(state)
    except (OSError, ValueError):
        # ValueError covers a racing reopen/close ("I/O operation on
        # closed file"): the never-raises contract holds over losing
        # one event at a run-id switch.
        _DROPPED += 1  # ot-san: owner=gil-counter


class Span:
    """One live span (what ``span()`` yields): ``id`` is the handle a
    supervisor passes to children via ``child_env``."""

    __slots__ = ("id", "name")

    def __init__(self, sid: str, name: str):
        self.id, self.name = sid, name


class _SpanCM:
    def __init__(self, name: str, attrs: dict, detached: bool = False,
                 parent: str | None = None):
        self._name, self._attrs = name, attrs
        self._detached = detached
        self._parent_override = parent
        self._end_attrs: dict | None = None
        self._span: Span | None = None

    def __enter__(self) -> Span | None:
        global _SPANS_STARTED
        st = _state()  # the returned dict, NOT a re-read of _STATE: a
        if st is None:  # racing reopen may null the global between them
            return None
        with _LOCK:
            st["seq"] += 1
            sid = f"{st['proc']}.{st['seq']}"
        stack = _stack()
        parent = (self._parent_override
                  or (stack[-1] if stack
                      else os.environ.get("OT_TRACE_PARENT") or None))
        _SPANS_STARTED += 1
        rec = {"ev": "b", "id": sid, "parent": parent, "name": self._name,
               "ts": _now_us(), "tid": _tid()}
        if self._attrs:
            rec["attrs"] = self._attrs
        _write(rec)
        if not self._detached:
            stack.append(sid)
        self._span = Span(sid, self._name)
        return self._span

    def note(self, **attrs) -> None:
        """Attach attrs to the span's END event — measurements only
        known at close (device vs host time split, output sizes). The
        begin event keeps the identity attrs; ``obs.export`` merges the
        end attrs back into the reconstructed span."""
        if attrs:
            self._end_attrs = {**(self._end_attrs or {}), **attrs}

    def __exit__(self, exc_type, exc, tb):
        if self._span is None:
            return False
        if not self._detached:
            stack = _stack()
            if stack and stack[-1] == self._span.id:
                stack.pop()
        status = "ok" if exc_type is None else f"error:{exc_type.__name__}"
        rec = {"ev": "e", "id": self._span.id, "ts": _now_us(),
               "status": status}
        if self._end_attrs:
            rec["attrs"] = self._end_attrs
        _write(rec)
        self._span = None  # idempotent: a second exit writes nothing
        return False

    def force(self):
        """No-op on an eager span (it is already on disk) — the shared
        surface with ``_DeferredSpanCM`` so force-sampling call sites
        need no branch."""
        return self._span

    @property
    def span_id(self) -> str | None:
        """The live span's id (None before enter / after exit) — what a
        call site hands the metrics registry as a tail exemplar, so a
        histogram's max bucket can name the span that filled it."""
        return self._span.id if self._span is not None else None


class _DeferredSpanCM:
    """An UNSAMPLED detached span: begin is captured, not written.

    ``__enter__`` records the would-be begin (timestamp + parent) and
    writes NOTHING — the sampled-out steady-state path costs two clock
    reads and no I/O. The span materialises retroactively — begin
    written late with the ORIGINAL timestamp — only when the outcome
    turns out to matter:

    * ``__exit__`` with an exception writes begin + error end (a failed
      request/batch keeps full span evidence even when unsampled);
    * ``force()`` writes the begin and leaves the span OPEN — the
      force-sampling hook for the abandon-on-hang convention: a
      watchdog-killed dispatch of an unsampled batch still leaves its
      orphaned begin as kill evidence (``--expected-orphans``);
    * ``__exit__`` clean with no prior ``force()`` writes nothing at
      all — the sampled-out happy path.

    This is what "abnormal outcomes are force-sampled" means
    mechanically: head sampling decides the happy path's cost, the
    failure paths decide for themselves, and the evidence contract of
    ``obs.report --check`` survives any sample rate.
    """

    __slots__ = ("_name", "_attrs", "_ts", "_parent", "_span", "_done",
                 "_parent_override", "_end_attrs")

    def __init__(self, name: str, attrs: dict, parent: str | None = None):
        self._name, self._attrs = name, attrs
        self._ts: int | None = None
        self._parent = None
        self._parent_override = parent
        self._end_attrs: dict | None = None
        self._span: Span | None = None
        self._done = False

    def __enter__(self):
        self._ts = _now_us()
        stack = getattr(_TLS, "stack", None)
        self._parent = (self._parent_override
                        or (stack[-1] if stack
                            else os.environ.get("OT_TRACE_PARENT") or None))
        return None  # like a disabled span: no live Span handle

    def force(self) -> Span | None:
        """Materialise the begin event (original timestamp) if it is not
        on disk yet; idempotent. Returns the Span, or None when the
        begin could not be written."""
        global _SPANS_STARTED
        if self._span is not None or self._done or self._ts is None:
            return self._span
        st = _state()
        if st is None:
            return None
        with _LOCK:
            st["seq"] += 1
            sid = f"{st['proc']}.{st['seq']}"
        _SPANS_STARTED += 1
        rec = {"ev": "b", "id": sid, "parent": self._parent,
               "name": self._name, "ts": self._ts, "tid": _tid()}
        if self._attrs:
            rec["attrs"] = self._attrs
        _write(rec)
        self._span = Span(sid, self._name)
        return self._span

    def note(self, **attrs) -> None:
        """End-event attrs (the ``_SpanCM.note`` surface): kept even on
        the deferred path so a force-sampled span closes with the same
        measurements a sampled one would."""
        if attrs:
            self._end_attrs = {**(self._end_attrs or {}), **attrs}

    @property
    def span_id(self) -> str | None:
        """None until force-materialised: an unsampled span has no id
        on disk, so it contributes no exemplar (exemplars must resolve
        to real span chains)."""
        return self._span.id if self._span is not None else None

    def __exit__(self, exc_type, exc, tb):
        if self._done:
            return False
        if exc_type is not None:
            self.force()
        if self._span is not None:
            status = ("ok" if exc_type is None
                      else f"error:{exc_type.__name__}")
            rec = {"ev": "e", "id": self._span.id, "ts": _now_us(),
                   "status": status}
            if self._end_attrs:
                rec["attrs"] = self._end_attrs
            _write(rec)
        self._done = True
        self._span = None
        return False


class _NullCM:
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False

    def force(self):
        return None

    def note(self, **attrs):
        return None

    @property
    def span_id(self):
        return None


_NULL = _NullCM()


def span(name: str, **attrs):
    """Context manager timing a region. Yields a ``Span`` (or None when
    disabled). Nesting is tracked per thread; a root span's parent comes
    from ``OT_TRACE_PARENT`` when a supervisor handed one down."""
    if not enabled():
        return _NULL
    return _SpanCM(name, attrs)


def detached_span(name: str, parent: str | None = None, **attrs):
    """A span that never joins the per-thread nesting stack.

    The serve path's lifecycle spans (``request-queued`` from admission
    to batch formation, ``batch-dispatched`` around an engine call whose
    begin and end may straddle other work) OVERLAP freely on one thread;
    pushing them through the LIFO stack would corrupt parentage for
    every span opened in between. A detached span reads its parent from
    the live stack at begin and contributes nothing to it; enter/exit
    the returned context manager explicitly (``cm.__enter__()`` at the
    start of the lifecycle, ``cm.__exit__(exc_type, None, None)`` at the
    end — exit is idempotent). A detached span deliberately never
    exited is an ORPHAN: the serve dispatch loop abandons the span of a
    batch killed by the watchdog on purpose, so a hung dispatch leaves
    the same closed-by-kill evidence a SIGKILLed child does.

    ``parent`` overrides the ambient parent (thread stack /
    ``OT_TRACE_PARENT``) with an EXPLICIT span id — the cross-process
    propagation hook: a backend's per-request span carries the ROUTER's
    span id handed over the wire, so one request's spans chain across
    the fleet (docs/OBSERVABILITY.md, fleet tracing).
    """
    if not enabled():
        return _NULL
    return _SpanCM(name, attrs, detached=True, parent=parent)


def maybe_span(sampled: bool, name: str, parent: str | None = None,
               **attrs):
    """A detached span gated by the request's head-sampling decision.

    ``sampled=True`` (or rate 1, the default) is exactly
    ``detached_span``. ``sampled=False`` returns a deferred span that
    writes nothing on the happy path but still materialises — begin at
    the ORIGINAL timestamp — when the region fails (``__exit__`` with an
    exception) or when a call site force-samples it (``force()``: the
    hang/abandon path, where the orphaned begin IS the evidence). The
    serve path threads one admission-time ``trace.sample()`` decision
    through request -> batch -> dispatch so a batch's spans are emitted
    iff it carries a sampled rider, with abnormal outcomes (deadline,
    dispatch failure, watchdog kill, redispatch) always on disk.
    """
    if not enabled():
        return _NULL
    if sampled:
        return _SpanCM(name, attrs, detached=True, parent=parent)
    return _DeferredSpanCM(name, attrs, parent=parent)


def current_span_id() -> str | None:
    """The innermost live span's id on this thread (for ``child_env``)."""
    stack = getattr(_TLS, "stack", None)
    return stack[-1] if stack else None


def point(name: str, **attrs) -> None:
    """One instant event (fault firings, degradations, kills, ...)."""
    if not enabled() or _state() is None:
        return
    rec = {"ev": "p", "name": name, "ts": _now_us()}
    if attrs:
        rec["attrs"] = attrs
    _write(rec)


def counter(name: str, n: float = 1, **attrs) -> None:
    """Add ``n`` to the named counter (aggregated into
    ``metrics_snapshot``) and emit one ``c`` event."""
    if not enabled() or _state() is None:
        return
    with _LOCK:
        _COUNTS[name] = _COUNTS.get(name, 0) + n
    rec = {"ev": "c", "name": name, "ts": _now_us(), "n": n}
    if attrs:
        rec["attrs"] = attrs
    _write(rec)


def gauge(name: str, value: float, **attrs) -> None:
    """Set the named gauge (last-write-wins in ``metrics_snapshot``)
    and emit one ``g`` event."""
    if not enabled() or _state() is None:
        return
    with _LOCK:
        _GAUGES[name] = value
    rec = {"ev": "g", "name": name, "ts": _now_us(), "value": value}
    if attrs:
        rec["attrs"] = attrs
    _write(rec)


def metrics_snapshot() -> dict:
    """The flat snapshot stamped into the bench JSON line
    (``"obs": {...}``): run id, span count, counter totals, gauge
    values, and the dropped-event count when nonzero (a snapshot that
    hid drops would overstate its own completeness)."""
    snap: dict = {"run": run_id(), "spans": _SPANS_STARTED}
    with _LOCK:
        if _COUNTS:
            snap["counters"] = dict(sorted(_COUNTS.items()))
        if _GAUGES:
            snap["gauges"] = dict(sorted(_GAUGES.items()))
    if _DROPPED:
        snap["dropped"] = _DROPPED
    if _EVICTED_BYTES:
        snap["evicted_bytes"] = _EVICTED_BYTES
    return snap


def child_env(env: dict) -> dict:
    """Copy ``env`` with the run id and the CURRENT span id injected
    (``OT_TRACE_RUN`` / ``OT_TRACE_PARENT``), so a child process's root
    spans nest under the caller's live span. No-op while disabled."""
    if not enabled():
        return env
    out = dict(env)
    out["OT_TRACE_DIR"] = os.environ["OT_TRACE_DIR"]
    out["OT_TRACE_RUN"] = ensure_run()
    parent = current_span_id()
    if parent:
        out["OT_TRACE_PARENT"] = parent
    else:
        out.pop("OT_TRACE_PARENT", None)
    return out


def reset_for_tests() -> None:
    """Close the event file and clear every aggregate (tests only — a
    real process's trace is a fact about this process)."""
    global _SPANS_STARTED, _DROPPED, _EVICTED_BYTES
    _close_state()
    with _LOCK:
        _COUNTS.clear()
        _GAUGES.clear()
        _TIDS.clear()
    _SPANS_STARTED = 0
    _DROPPED = 0
    _EVICTED_BYTES = 0
    _TLS.stack = []
