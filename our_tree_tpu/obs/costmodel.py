"""Static dispatch cost records: modeled HBM traffic + op counts.

The serve stack measures *time* exhaustively — per-stage waterfalls,
device-window accounting, per-lane busy fractions — but until this
module nothing said what a dispatch *should* cost the hardware: how
many bytes one batch at (engine, mode, rung) moves across the HBM
boundary and roughly how many vector ops it issues. Without that,
"35.4 GB/s offline vs 1 GB/s served" is a gap with no decomposition:
achieved GB/s against a roofline needs a numerator (bytes actually
moved per dispatch), and the counter/keystream overhead of CTR means
payload goodput UNDERSTATES traffic by an engine-dependent factor.

Two sources, pinned against each other:

* **Analytic** (always available, every engine): the jit-boundary
  traffic derived by hand from the dispatch signature the serve seam
  actually calls (``models/aes.py``, ``aead/gcm.py``). Per rung ``N``
  (16-byte blocks), ``K`` key slots, ``nr`` rounds:

  - ``ctr`` (jax engines): payload in + counter words in + the
    (K, 4*(nr+1)) schedule stack + the (N,) slot vector; payload out.
  - ``ctr`` (native host tier): payload in + schedules; payload out —
    counters are generated in C registers per request (the ``runs``
    fast path), so no counter array ever crosses memory. This is the
    "per engine" half of the fallback: the traffic model follows the
    engine's actual dataflow, not one formula.
  - ``gcm``/``gcm-open``: the ctr arrays plus the (K, 128, 128)
    mul-by-H bit matrices, the inject state, and the seg_keep vector;
    out is the stacked (crypt, GHASH-state) pair — 2x payload.
  - ``cbc``: payload + PREV stream + decrypt schedules in; payload out.

  The op count is an order-of-magnitude AES budget (blocks x rounds x
  ~32 word-ops, + ~256/block for the GHASH matvec) — use the XLA flops
  when present; the *byte* model is the precise half.

* **XLA** (where available): ``jit(...).lower(...).compile()`` of the
  SAME entry points, reading ``cost_analysis()`` (flops, total "bytes
  accessed" — note this counts every HLO op's operands, a fused-
  intermediate measure far above boundary traffic) and
  ``memory_analysis()`` (argument/output buffer bytes — the exact
  jit-boundary quantity the analytic model predicts). The parity test
  (tests/test_costmodel.py) pins analytic-vs-XLA byte counts within
  10% on every engine where both exist: a dispatch-signature change
  that stales the hand model fails the pin instead of silently skewing
  every roofline number downstream.

Computed once per process (records memoized) at serve warmup — the
ladder is already being walked — and stamped three ways: the
``SERVE_r*.json`` ``cost`` section (``cost_section``), a
``cost-<pid>-*.json`` file in the OT_TRACE_DIR run layout so
``obs.report`` can render the roofline table post-hoc with no server
in sight, and the incident bundles (``obs/incident.py``).

``OT_COST_XLA`` bounds the warmup compile bill: ``0``/``off`` skips
the XLA half entirely, ``all`` compiles every (engine, mode, rung),
default ``top`` compiles only each mode's largest rung (byte counts
scale linearly in N below it; tests compile what they pin).

Module-level imports are stdlib-only (obs discipline — ``obs.report``
must stay importable in jax-free CI steps); numpy/jax load lazily
inside the XLA half, and every XLA failure degrades to the analytic
record, never an exception.
"""

from __future__ import annotations

import glob
import json
import os
import re

KIND = "ot-cost"
VERSION = 1

#: Order-of-magnitude word-ops per block per AES round (T-table shape:
#: 16 gathers + 12 combining XORs + 4 round-key XORs). The analytic op
#: budget, not a promise — XLA flops supersede it when present.
OPS_PER_BLOCK_ROUND = 32

#: Extra word-ops per block for the GHASH mul-by-H bit-matrix matvec
#: (128 AND+XOR steps over 4-word rows).
OPS_PER_GHASH_BLOCK = 256

#: (engine, mode, rung, nr, key_slots) -> record. Process-global on
#: purpose: every Server.start() in one process shares the ladder's
#: records (and the XLA half's compile bill is paid once).
_CACHE: dict[tuple, dict] = {}


def xla_policy() -> str:
    """``OT_COST_XLA``: ``off`` / ``top`` (default) / ``all``."""
    v = str(os.environ.get("OT_COST_XLA", "top") or "top").lower()
    if v in ("0", "off", "none", "false"):
        return "off"
    return "all" if v == "all" else "top"


def _exec_engine(engine: str, mode: str) -> str:
    """The engine tier that actually executes (engine, mode): the
    native host tier serves only ctr in C — AEAD/CBC batches on a
    native-tier server run the jnp engine in-process (the lane seam's
    documented tier detour)."""
    return "jnp" if engine == "native" and mode != "ctr" else engine


def analytic_cost(engine: str, mode: str, rung: int, nr: int,
                  key_slots: int) -> dict:
    """The hand-derived per-dispatch record (module docstring has the
    per-mode formulas). Bytes are jit-boundary traffic: what one
    dispatch reads and writes across the HBM seam."""
    n = int(rung)
    k = int(key_slots)
    blk = 16 * n                       # payload bytes at this rung
    sched = k * 4 * (int(nr) + 1) * 4  # the stacked schedules
    exec_eng = _exec_engine(engine, mode)
    ops = n * int(nr) * OPS_PER_BLOCK_ROUND
    if mode in ("gcm", "gcm-open"):
        hmats = k * 128 * 128 * 4
        bytes_in = blk + blk + sched + 4 * n + hmats + blk + 4 * n
        bytes_out = 2 * blk            # stacked (crypt, GHASH state)
        ops += n * OPS_PER_GHASH_BLOCK
    elif mode == "cbc":
        bytes_in = blk + blk + sched + 4 * n
        bytes_out = blk
    elif exec_eng == "native":
        # Counters are generated inside C per request (the runs fast
        # path): no counter array, no slot vector crosses memory.
        bytes_in = blk + sched
        bytes_out = blk
    else:
        bytes_in = blk + blk + sched + 4 * n
        bytes_out = blk
    return {
        "engine": engine, "exec_engine": exec_eng, "mode": mode,
        "rung": n, "nr": int(nr), "key_slots": k,
        "bytes_in": bytes_in, "bytes_out": bytes_out,
        "hbm_bytes": bytes_in + bytes_out,
        "ops": ops,
    }


def xla_cost(engine: str, mode: str, rung: int, nr: int,
             key_slots: int) -> dict | None:
    """The XLA half: lower + compile the REAL dispatch entry at this
    shape and read ``cost_analysis()`` + ``memory_analysis()``. None
    whenever anything is unavailable (native ctr has no XLA program;
    an old jax may lack either API; a Pallas engine may not lower on
    this host) — the analytic record stands alone then, and the parity
    test skips, it does not fail."""
    try:
        import numpy as np

        from ..models import aes

        exec_eng = _exec_engine(engine, mode)
        if exec_eng == aes.NATIVE_ENGINE:
            return None
        n, k = int(rung), int(key_slots)
        w = np.zeros(4 * n, dtype=np.uint32)
        c = np.zeros(4 * n, dtype=np.uint32)
        rks = np.zeros((k, 4 * (int(nr) + 1)), dtype=np.uint32)
        s = np.zeros(n, dtype=np.uint32)
        knobs = aes._engine_knobs_key(exec_eng)
        if mode in ("gcm", "gcm-open"):
            from ..aead import gcm as aead_gcm

            hm = np.zeros((k, 128, 128), dtype=np.uint32)
            lowered = aead_gcm._gcm_fused_jit.lower(
                w, c, rks, s, hm, w, s, int(nr), exec_eng,
                aead_gcm.SEAL if mode == "gcm" else aead_gcm.OPEN, knobs)
        elif mode == "cbc":
            lowered = aes._cbc_dec_scattered_multikey_jit.lower(
                w, c, rks, s, int(nr), exec_eng, knobs)
        else:
            lowered = aes._ctr_scattered_multikey_jit.lower(
                w, c, rks, s, int(nr), exec_eng, knobs)
        compiled = lowered.compile()
        out: dict = {}
        try:
            ca = compiled.cost_analysis()
            d = ca[0] if isinstance(ca, (list, tuple)) else ca
            if isinstance(d, dict):
                out["flops"] = float(d.get("flops", 0.0))
                out["bytes_accessed"] = float(d.get("bytes accessed", 0.0))
        except Exception:  # noqa: BLE001 - partial cost info is still info
            pass
        try:
            ma = compiled.memory_analysis()
            out["arg_bytes"] = int(ma.argument_size_in_bytes)
            out["out_bytes"] = int(ma.output_size_in_bytes)
            out["temp_bytes"] = int(ma.temp_size_in_bytes)
        except Exception:  # noqa: BLE001 - same
            pass
        return out or None
    except Exception:  # noqa: BLE001 - degrade to analytic, never raise
        return None


def cost_record(engine: str, mode: str, rung: int, nr: int,
                key_slots: int, with_xla: bool = False) -> dict:
    """One memoized record. ``with_xla`` requests the compile-backed
    half (an already-cached analytic-only record is upgraded in
    place, so the warmup policy and an eager test compose)."""
    key = (engine, mode, int(rung), int(nr), int(key_slots))
    rec = _CACHE.get(key)
    if rec is None:
        rec = analytic_cost(engine, mode, rung, nr, key_slots)
        rec["xla"] = None
        rec["source"] = "analytic"
        _CACHE[key] = rec
    if with_xla and rec["xla"] is None:
        x = xla_cost(engine, mode, rung, nr, key_slots)
        if x is not None:
            rec["xla"] = x
            rec["source"] = "analytic+xla"
    return rec


def ladder_costs(engine: str, modes, rungs, key_bits=(128,),
                 key_slots: int = 8) -> list[dict]:
    """Every (mode, rung, nr) record for one server's warmed ladder,
    with the XLA half per ``OT_COST_XLA`` (default: each mode's top
    rung only — the byte model is linear in N below it, and one
    compile per mode bounds the warmup bill)."""
    from ..ops.keyschedule import ROUNDS

    policy = xla_policy()
    rungs = tuple(int(r) for r in rungs)
    records = []
    for bits in key_bits:
        nr = ROUNDS[int(bits)]
        for mode in modes:
            for rung in rungs:
                want_xla = (policy == "all"
                            or (policy == "top" and rung == max(rungs)))
                records.append(cost_record(engine, mode, rung, nr,
                                           key_slots, with_xla=want_xla))
    return records


# ---------------------------------------------------------------------------
# The run-dir stamp (what obs.report joins post-hoc).
# ---------------------------------------------------------------------------


def write_run_records(records, engine: str,
                      ceiling_gbps: float | None = None) -> str | None:
    """Stamp the records into the OT_TRACE_DIR run layout as
    ``cost-<pid>-<tok>.json`` (never raises; None when tracing is off
    or the write fails — the in-memory records still serve the bench
    artifact either way)."""
    try:
        from . import trace

        if not trace.enabled():
            return None
        run = trace.ensure_run()
        d = trace.run_dir()
        if d is None:
            return None
        os.makedirs(d, exist_ok=True)
        import uuid

        path = os.path.join(
            d, f"cost-{os.getpid()}-{uuid.uuid4().hex[:8]}.json")
        doc = {"kind": KIND, "v": VERSION, "run": run,
               "pid": os.getpid(), "engine": engine,
               "ceiling_gbps": ceiling_gbps, "records": list(records)}
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, separators=(",", ":"), sort_keys=True)
            fh.write("\n")
        return path
    except Exception:  # noqa: BLE001 - never-raises discipline
        return None


def load_run_records(run_dir: str) -> tuple[list[dict], float | None]:
    """(deduped records, ceiling) from every ``cost-*.json`` in the run
    dir (a fleet writes one per process; identical ladders dedupe on
    (engine, mode, rung, nr)). Unparseable files are skipped."""
    records: list[dict] = []
    seen: set[tuple] = set()
    ceiling = None
    for path in sorted(glob.glob(os.path.join(run_dir, "cost-*.json"))):
        try:
            with open(path, encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            continue
        if not isinstance(doc, dict) or doc.get("kind") != KIND:
            continue
        if ceiling is None and doc.get("ceiling_gbps"):
            ceiling = float(doc["ceiling_gbps"])
        for rec in doc.get("records", []):
            if not isinstance(rec, dict):
                continue
            key = (rec.get("engine"), rec.get("mode"), rec.get("rung"),
                   rec.get("nr"))
            if key in seen:
                continue
            seen.add(key)
            records.append(rec)
    return records, ceiling


# ---------------------------------------------------------------------------
# The roofline join: records x measured per-rung dispatch counters.
# ---------------------------------------------------------------------------


_FLAT_RE = re.compile(r"^([A-Za-z0-9_]+)\{(.*)\}$")


def series_by_key(counters: dict, name: str) -> dict[tuple, float]:
    """{(engine, mode, rung, nr): total} for one flat-keyed counter
    name (the ``obs/metrics.py`` ``name{k=v,...}`` convention both the
    live snapshot and the run-dir totals share). Public: the profiler's
    window deltas (obs/profiler.py) parse the same series."""
    out: dict[tuple, float] = {}
    for key, v in counters.items():
        m = _FLAT_RE.match(key)
        if not m or m.group(1) != name:
            continue
        labels = dict(p.split("=", 1)
                      for p in m.group(2).split(",") if "=" in p)
        try:
            k = (labels.get("engine", "?"), labels.get("mode", "ctr"),
                 int(labels.get("rung", 0)), int(labels.get("nr", 0)))
        except ValueError:
            continue
        out[k] = out.get(k, 0.0) + float(v)
    return out


def cost_section(records, counters: dict,
                 ceiling_gbps: float | None = None) -> dict:
    """The artifact/report ``cost`` join: per (engine, mode, rung) with
    traffic, modeled bytes moved x measured dispatches over the rung's
    accumulated DEVICE time (``serve_rung_dispatches`` /
    ``serve_rung_device_us``, serve/lanes.py) -> achieved GB/s moved
    and utilization against the measured roofline. Every warmed record
    gets a row — a rung the traffic never reached shows
    ``dispatches=0`` rather than vanishing (a silently omitted row
    reads as "covered" in trend diffs; the explicit zero is the
    evidence that it was warmed and idle). ``per_engine`` aggregates
    the dispatched rows — the SERVE_r* ``cost`` section and the SLO
    gate's per-row surface (zero rows gate nothing: the SLO compare
    skips baselines <= 0)."""
    disp = series_by_key(counters, "serve_rung_dispatches")
    dev = series_by_key(counters, "serve_rung_device_us")
    rows = []
    seen: set[tuple] = set()
    per_engine: dict[str, dict] = {}
    for rec in records:
        # nr is part of the join: a 128- and a 256-bit ladder at the
        # same rung are DIFFERENT records (schedule traffic + rounds),
        # and the lane seam labels its counters accordingly.
        key = (rec.get("engine", "?"), rec.get("mode", "ctr"),
               int(rec.get("rung", 0)), int(rec.get("nr", 0)))
        if key in seen:
            continue
        seen.add(key)
        d = disp.get(key, 0.0)
        if d <= 0:
            rows.append({
                "engine": key[0], "mode": key[1], "rung": key[2],
                "nr": key[3], "dispatches": 0,
                "modeled_dispatch_bytes": int(rec["hbm_bytes"]),
                "modeled_bytes": 0, "device_s": 0.0,
                "achieved_gbps": 0.0, "utilization": None,
            })
            continue
        dus = dev.get(key, 0.0)
        moved = float(rec["hbm_bytes"]) * d
        gbps = (moved / 1e9 / (dus / 1e6)) if dus > 0 else 0.0
        rows.append({
            "engine": key[0], "mode": key[1], "rung": key[2],
            "nr": key[3],
            "dispatches": int(d),
            "modeled_dispatch_bytes": int(rec["hbm_bytes"]),
            "modeled_bytes": int(moved),
            "device_s": round(dus / 1e6, 6),
            "achieved_gbps": round(gbps, 6),
            "utilization": (round(gbps / ceiling_gbps, 6)
                            if ceiling_gbps else None),
        })
        agg = per_engine.setdefault(key[0], {"modeled_bytes": 0,
                                             "device_s": 0.0})
        agg["modeled_bytes"] += int(moved)
        agg["device_s"] += dus / 1e6
    for eng, agg in per_engine.items():
        gbps = (agg["modeled_bytes"] / 1e9 / agg["device_s"]
                if agg["device_s"] > 0 else 0.0)
        agg["device_s"] = round(agg["device_s"], 6)
        agg["achieved_gbps"] = round(gbps, 6)
        agg["utilization"] = (round(gbps / ceiling_gbps, 6)
                              if ceiling_gbps else None)
    rows.sort(key=lambda r: (r["engine"], r["mode"], r["rung"],
                             r["nr"]))
    return {"ceiling_gbps": ceiling_gbps, "records": list(records),
            "rows": rows, "per_engine": per_engine}


def reset_for_tests() -> None:
    _CACHE.clear()
