"""``python -m our_tree_tpu.obs.report <run-dir>`` — reconstruct a run.

The report answers, from the trace stream alone, the questions that used
to require stitching stderr + journal + crash dumps together: what did
each sweep unit cost (wall and device-seam time), which units were
retried or quarantined and why, which faults were injected vs. actually
observed, what degraded, and where the time went (slowest spans). An
orphaned span — a begin with no end — is rendered as what it is: a span
closed by the kill of its process, with the unit it belonged to.

Flags:

* ``--check``       exit nonzero on schema violations or orphaned spans
                    (the CI gate: a healthy traced sweep must produce a
                    clean, fully-closed stream).
* ``--expected-orphans NAMES``  comma list of span NAMES whose orphans
                    are expected (a FAULTED run's check gate: a
                    dispatch_hang rehearsal SIGKILLs a child inside
                    unit/row/timed-call, and those three orphans are the
                    scenario working as designed). Each listed name
                    licenses exactly ONE orphan — repeat a name to allow
                    more — so both an orphan with an unlisted name AND a
                    second orphan reusing a listed one (two killed
                    children where the rehearsal kills one) fail
                    ``--check``.
* ``--trace-json P``  also write the Chrome/Perfetto export to P.
* ``--top N``       size of the slowest-span table (default 10).

When any span carries an ``engine`` attr (the repo-root bench's probe
and measure spans do), the report adds a per-engine device-time table —
the trace-side answer to "which engine did this run actually spend its
device time in" that the probe's stderr GB/s lines only hint at. A
serve run's ``lane-dispatch``/``lane-probe`` spans (which carry a
``lane`` attr) additionally get a per-LANE table — dispatches, canary
probes, device time, busy-fraction (per-lane occupancy: device time
over run wall), and kills per fault domain, with an orphaned lane span
counted as the kill it is (docs/SERVING.md) — plus a ``serve overlap``
line reconstructing the ``serve_inflight`` gauge (the measured max
dispatch concurrency) against a peak-concurrent-lane-spans sweep.

When the run dir carries metrics snapshots (``metrics-*.jsonl``, the
``obs/metrics.py`` flusher), the report also renders the METRICS table:
final counter totals and gauge last-values across processes, and
histogram p50/p95/p99 per label set interpolated from the log2 buckets
— the exact view that stays complete when span tracing is sampled.

``<run-dir>`` is ``$OT_TRACE_DIR/<run-id>``; passing ``$OT_TRACE_DIR``
itself picks the newest run inside it (and says so).
"""

from __future__ import annotations

import argparse
import glob
import os
import re
import sys

from . import costmodel, export, incident, profiler
from . import metrics as _metrics

#: Span names that count as device-seam time in the per-unit table
#: (the tracer's analogue of the AES-multicore paper's per-phase,
#: per-worker attribution).
DEVICE_SPANS = ("timed-call", "barrier", "chained-dispatch")

#: Span names that represent one attempt at one sweep unit. The
#: supervisor's view ("unit-attempt", includes spawn/kill overhead)
#: wins over the in-process view ("unit") when both exist for a unit —
#: counting both would double every isolated unit's wall time.
ATTEMPT_SPANS = ("unit-attempt", "unit")

#: The fleet waterfall's stage order — the shared vocabulary
#: (obs/metrics.py; route/proxy.py _build_ledger and serve/server.py
#: produce it, route.bench's completeness gate consumes the same tuple).
WATERFALL_STAGES = _metrics.WATERFALL_STAGES


def exemplar_rows(run: export.Run, top: int = 10) -> list[dict]:
    """The slowest-exemplars rows: every tail exemplar the registry
    retained (obs/metrics.py, riding the metrics snapshots), ranked by
    value, each resolved against the trace stream — ``chain`` is the
    exemplar span's ancestor path and ``complete`` whether it reaches a
    root with no missing link. This is the exemplar -> trace
    walk-through as data: a p99 bucket's number becomes one concrete
    request's full span chain (the acceptance gate: rendered rows must
    all resolve on a sampled run)."""
    rows: list[dict] = []
    if not run.snapshots:
        return rows
    for key, h in run.metrics_totals()["hists"].items():
        for b, e in (h.get("exemplars") or {}).items():
            if not isinstance(e, dict):
                continue
            rows.append({"hist": key, "bucket": int(b),
                         "v": float(e.get("v", 0.0)),
                         "span": e.get("span"), "attrs": e})
    rows.sort(key=lambda r: (-r["v"], r["hist"]))
    rows = rows[:top]
    for r in rows:
        chain: list[str] = []
        complete = False
        seen: set[str] = set()
        sp = run.spans.get(r["span"]) if r["span"] else None
        while sp is not None and sp.id not in seen:
            seen.add(sp.id)
            chain.append(sp.name)
            if not sp.parent:
                complete = True  # reached a root: the chain is whole
                break
            sp = run.spans.get(sp.parent)
        r["chain"] = chain
        r["complete"] = complete
    return rows


def fleet_join_stats(run: export.Run) -> dict:
    """Cross-process trace joins: of the run's ``route-request`` spans
    (the router-side roots, one per sampled request), how many have a
    child span in ANOTHER process — i.e. the backend's ``request-queued``
    span actually chained under the router's span id over the wire. The
    CI route drive gates ``joined/total`` (``--min-join-frac``): a
    propagation regression shows up as roots with no cross-process
    children, not as a parse error."""
    roots = [s for s in run.spans.values() if s.name == "route-request"]
    children: dict[str, list] = {}
    for s in run.spans.values():
        if s.parent:
            children.setdefault(s.parent, []).append(s)
    joined = linked = 0
    for r in roots:
        kids = children.get(r.id, [])
        if kids:
            linked += 1
        if any(k.proc != r.proc for k in kids):
            joined += 1
    return {"roots": len(roots), "linked": linked, "joined": joined,
            "frac": (joined / len(roots)) if roots else 0.0}


def _resolve_run_dir(path: str, say=print) -> str:
    if glob.glob(os.path.join(path, "trace-*.jsonl")):
        return path
    runs = sorted(
        d for d in glob.glob(os.path.join(path, "*"))
        if os.path.isdir(d) and glob.glob(os.path.join(d, "trace-*.jsonl")))
    if runs:
        say(f"# {path} holds {len(runs)} run(s); reporting the newest: "
            f"{os.path.basename(runs[-1])}")
        return runs[-1]
    return path


def _s(us: int) -> str:
    return f"{us / 1e6:.3f}"


def _unit_of(run: export.Run, sp: export.SpanRec):
    return sp.attrs.get("unit") or run.ancestor_attr(sp, "unit")


def _nested_in_named_span(run: export.Run, sp: export.SpanRec,
                          names: tuple) -> bool:
    """Whether a span named in ``names`` encloses ``sp`` — only the
    outermost span of a chain may count toward a time sum."""
    seen = set()
    cur = run.spans.get(sp.parent) if sp.parent else None
    while cur is not None and cur.id not in seen:
        if cur.name in names:
            return True
        seen.add(cur.id)
        cur = run.spans.get(cur.parent) if cur.parent else None
    return False


def _nested_in_device_span(run: export.Run, sp: export.SpanRec) -> bool:
    """Whether another device-seam span encloses ``sp``. The e2e timing
    path opens a "barrier" span INSIDE its "timed-call" span (the timed
    region is `block_until_ready(run(...))`), so summing both would
    book the same wall time twice — only the outermost device span of a
    chain counts toward a unit's device_s."""
    return _nested_in_named_span(run, sp, DEVICE_SPANS)


def _table(rows: list[list[str]], header: list[str], out) -> None:
    widths = [max(len(r[i]) for r in [header] + rows)
              for i in range(len(header))]
    for r in [header] + rows:
        out.write("  " + "  ".join(c.ljust(w)
                                   for c, w in zip(r, widths)).rstrip()
                  + "\n")


def render(run: export.Run, top: int = 10, out=sys.stdout,
           expected_orphans: dict | None = None,
           run_dir: str | None = None) -> None:
    run_id = next((h.get("run", "?") for h in run.procs.values()), "?")
    run_end = run.t1 if run.t1 is not None else 0
    orphans = sorted(run.orphans(), key=lambda s: (s.ts, s.id))
    wall = (run.t1 - run.t0) if run.t0 is not None else 0
    out.write(f"run {run_id}: {len(run.procs)} process(es), "
              f"{len(run.spans)} span(s) ({len(orphans)} orphaned), "
              f"{len(run.events)} event(s), wall {_s(wall)}s\n")
    out.write("schema: " + ("OK" if not run.violations else
                            f"{len(run.violations)} violation(s)") + "\n")
    for fname, lineno, why in run.violations:
        out.write(f"  ! {fname}:{lineno}: {why}\n")

    # -- per-unit table ----------------------------------------------------
    attempts: dict[str, list[export.SpanRec]] = {}
    preferred: dict[str, str] = {}
    for sp in run.spans.values():
        if sp.name not in ATTEMPT_SPANS:
            continue
        unit = sp.attrs.get("unit")
        if unit is None:
            continue
        # First listed attempt-span name present for a unit wins
        # (supervisor view over in-process view).
        have = preferred.get(unit)
        if have is None or (ATTEMPT_SPANS.index(sp.name)
                            < ATTEMPT_SPANS.index(have)):
            preferred[unit] = sp.name
        attempts.setdefault(unit, []).append(sp)
    device: dict[str, int] = {}
    rows_fresh: dict[str, int] = {}
    for sp in run.spans.values():
        unit = _unit_of(run, sp)
        if unit is None:
            continue
        if sp.name in DEVICE_SPANS:
            # Closed spans only: an orphan's "duration" runs to the end
            # of the run, which would book the whole post-kill sweep as
            # this unit's device time. Orphans are reported as kills,
            # not as measurements. And outermost-of-chain only: a
            # barrier span nested inside its timed-call span is the
            # same wall time twice.
            if not sp.orphan and not _nested_in_device_span(run, sp):
                device[unit] = device.get(unit, 0) + sp.dur_us(run_end)
        elif sp.name == "row":
            rows_fresh[unit] = rows_fresh.get(unit, 0) + 1
    rows_replayed: dict[str, int] = {}
    for p in run.points("row-replayed"):
        u = p.get("attrs", {}).get("unit", "?")
        rows_replayed[u] = rows_replayed.get(u, 0) + 1
    replayed_units = {p.get("attrs", {}).get("unit")
                      for p in run.points("unit-replayed")}
    failures: dict[str, list[str]] = {}
    for p in run.points("unit-failed"):
        a = p.get("attrs", {})
        failures.setdefault(a.get("unit", "?"), []).append(
            a.get("reason", "?"))
    quarantined = {p.get("attrs", {}).get("unit")
                   for p in run.points("quarantine")}
    released = {p.get("attrs", {}).get("unit")
                for p in run.points("quarantine-release")}

    units = sorted(set(attempts) | set(failures) | quarantined - {None}
                   | (replayed_units - {None}))
    if units:
        out.write("\nper-unit:\n")
        table = []
        for unit in units:
            sps = sorted((s for s in attempts.get(unit, [])
                          if s.name == preferred.get(unit)),
                         key=lambda s: s.ts)
            n_kill = sum(1 for s in sps if s.orphan)
            wall_us = sum(s.dur_us(run_end) for s in sps)
            if unit in quarantined:
                outcome = "quarantined"
            elif sps and sps[-1].end_ts is not None \
                    and sps[-1].status == "ok":
                outcome = "ok"
            elif unit in replayed_units and not sps:
                outcome = "replayed"
            elif sps and sps[-1].orphan:
                outcome = "killed"
            else:
                outcome = (sps[-1].status if sps else "failed")
            fr = rows_fresh.get(unit, 0)
            rp = rows_replayed.get(unit, 0)
            table.append([
                unit, str(len(sps)), _s(wall_us),
                _s(device.get(unit, 0)),
                f"{fr}/{rp}" if fr or rp else "-",
                str(len(failures.get(unit, []))) + (
                    f" kill={n_kill}" if n_kill else ""),
                outcome,
            ])
        _table(table, ["unit", "attempts", "wall_s", "device_s",
                       "rows f/r", "failures", "outcome"], out)

    # -- per-engine device time --------------------------------------------
    # Attribution rides the `engine` attr (the repo-root bench stamps it
    # on probe/measure spans; harness spans inherit it via ancestors).
    # Closed spans only, outermost-of-chain only — same double-counting
    # rules as the per-unit device_s column.
    engine_spans = DEVICE_SPANS + ("measure", "batch-dispatched",
                                   "lane-dispatch", "lane-probe")
    eng_time: dict[str, int] = {}
    eng_count: dict[str, int] = {}
    for sp in run.spans.values():
        if sp.name not in engine_spans or sp.orphan:
            continue
        eng = sp.attrs.get("engine") or run.ancestor_attr(sp, "engine")
        if eng is None:
            continue
        if _nested_in_named_span(run, sp, engine_spans):
            continue
        eng = str(eng)
        eng_time[eng] = eng_time.get(eng, 0) + sp.dur_us(run_end)
        eng_count[eng] = eng_count.get(eng, 0) + 1
    if eng_time:
        out.write("\nper-engine device time:\n")
        _table([[eng, str(eng_count[eng]), _s(eng_time[eng])]
                for eng in sorted(eng_time,
                                  key=lambda e: (-eng_time[e], e))],
               ["engine", "spans", "device_s"], out)

    # -- per-lane device time (serve) --------------------------------------
    # The serve path's fault-domain breakdown: `lane-dispatch` /
    # `lane-probe` spans carry a `lane` attr (serve/lanes.py). Closed
    # spans sum into device_s; an ORPHANED lane span is a kill (a hung
    # dispatch the watchdog ended) and is counted, not timed.
    lane_time: dict[str, int] = {}
    lane_count: dict[str, int] = {}
    lane_probes: dict[str, int] = {}
    lane_kills: dict[str, int] = {}
    for sp in run.spans.values():
        if sp.name not in ("lane-dispatch", "lane-probe"):
            continue
        lane = sp.attrs.get("lane")
        if lane is None:
            continue
        key = str(lane)
        if sp.orphan:
            lane_kills[key] = lane_kills.get(key, 0) + 1
            continue
        if sp.name == "lane-probe":
            lane_probes[key] = lane_probes.get(key, 0) + 1
        else:
            lane_count[key] = lane_count.get(key, 0) + 1
        lane_time[key] = lane_time.get(key, 0) + sp.dur_us(run_end)
    lane_keys = sorted(set(lane_time) | set(lane_kills),
                       key=lambda k: (len(k), k))
    if lane_keys:
        out.write("\nper-lane device time (serve):\n")
        _table([[k, str(lane_count.get(k, 0)),
                 str(lane_probes.get(k, 0)), _s(lane_time.get(k, 0)),
                 (f"{lane_time.get(k, 0) / wall:.0%}" if wall else "-"),
                 str(lane_kills.get(k, 0))]
                for k in lane_keys],
               ["lane", "dispatches", "probes", "device_s", "busy",
                "killed"], out)

    # -- per-mode dispatch (serve) -----------------------------------------
    # The served-workload split (ot-aead): `mode` rides the request,
    # batch-blocks, dispatch-latency, and auth-failure series
    # (serve/queue.py MODES — ctr, gcm, gcm-open, cbc), so a mixed-mode
    # run renders one row per mode: exact request/auth-failed totals
    # from the counters, batches + payload blocks from the
    # serve_batch_blocks histogram, dispatch-latency p50/p95 from the
    # serve_dispatch_us buckets. Registry-fed, so the table stays exact
    # at any OT_TRACE_SAMPLE rate.
    if run.snapshots:
        totals_m = run.metrics_totals()

        def _by_mode(series: dict, name: str) -> dict:
            got: dict[str, list] = {}
            for key, v in series.items():
                m = re.fullmatch(re.escape(name) + r"\{(.*)\}", key)
                if not m:
                    continue
                labels = dict(p.split("=", 1)
                              for p in m.group(1).split(",") if "=" in p)
                mode = labels.get("mode")
                if mode is not None:
                    got.setdefault(mode, []).append(v)
            return got

        req_c = _by_mode(totals_m["counters"], "serve_requests")
        auth_c = _by_mode(totals_m["counters"], "serve_auth_failed")
        blocks_h = _by_mode(totals_m["hists"], "serve_batch_blocks")
        disp_h = _by_mode(totals_m["hists"], "serve_dispatch_us")
        mode_keys = sorted(set(req_c) | set(blocks_h) | set(disp_h))
        if mode_keys:
            rows = []
            for mk in mode_keys:
                batches = sum(h["count"] for h in blocks_h.get(mk, []))
                blocks = sum(h["sum"] for h in blocks_h.get(mk, []))
                disp = _metrics.merge_buckets(
                    [h["buckets"] for h in disp_h.get(mk, [])])
                rows.append([
                    mk, f"{sum(req_c.get(mk, [0])):g}",
                    str(batches), f"{blocks:g}",
                    (f"{_metrics.percentile_from_buckets(disp, 50):.0f}"
                     if disp else "-"),
                    (f"{_metrics.percentile_from_buckets(disp, 95):.0f}"
                     if disp else "-"),
                    f"{sum(auth_c.get(mk, [0])):g}",
                ])
            out.write("\nper-mode dispatch (serve):\n")
            _table(rows, ["mode", "requests", "batches", "blocks",
                          "disp_p50_us", "disp_p95_us", "auth_failed"],
                   out)

    # -- per-backend dispatch (route) --------------------------------------
    # The routing tier's fault-domain breakdown, mirroring the per-lane
    # table one level up: `route-dispatch` / `backend-probe` spans carry
    # a `backend` attr (route/proxy.py). Closed spans sum into wall_s;
    # an ORPHANED route-dispatch span is a kill (a hung backend request
    # the attempt deadline ended) and is counted, not timed.
    be_time: dict[str, int] = {}
    be_count: dict[str, int] = {}
    be_probes: dict[str, int] = {}
    be_kills: dict[str, int] = {}
    be_redisp: dict[str, int] = {}
    for sp in run.spans.values():
        if sp.name not in ("route-dispatch", "backend-probe"):
            continue
        backend = sp.attrs.get("backend")
        if backend is None:
            continue
        key = str(backend)
        if sp.orphan:
            be_kills[key] = be_kills.get(key, 0) + 1
            continue
        if sp.name == "backend-probe":
            be_probes[key] = be_probes.get(key, 0) + 1
        else:
            be_count[key] = be_count.get(key, 0) + 1
            if sp.attrs.get("redispatch"):
                be_redisp[key] = be_redisp.get(key, 0) + 1
        be_time[key] = be_time.get(key, 0) + sp.dur_us(run_end)
    be_keys = sorted(set(be_time) | set(be_kills), key=lambda k: (len(k), k))
    if be_keys:
        out.write("\nper-backend dispatch (route):\n")
        _table([[k, str(be_count.get(k, 0)), str(be_probes.get(k, 0)),
                 str(be_redisp.get(k, 0)), _s(be_time.get(k, 0)),
                 str(be_kills.get(k, 0))]
                for k in be_keys],
               ["backend", "dispatches", "probes", "redispatched",
                "wall_s", "killed"], out)

    # -- serve overlap: the in-flight gauge, reconstructed -----------------
    # The lane pool emits a `serve_inflight` gauge event on every
    # TRAFFIC-dispatch lane window (serve/lanes.py:_inflight — canary
    # probes are excluded: they bypass the server's in-flight cap, so
    # counting them would let a serialized control run read as
    # overlapped); its max over the run is the measured dispatch
    # concurrency — the number the overlapped lane executors exist to
    # push past 1, and the one `serve.bench --min-inflight` gates. The
    # lane-SPAN sweep is the independent cross-check over the SAME
    # population (lane-dispatch spans only): peak simultaneous open
    # spans, orphans counted in flight until the end of the run (a
    # wedged dispatch WAS occupying its lane while it hung).
    inflight = [e for e in run.events
                if e["ev"] == "g" and e["name"] == "serve_inflight"]
    if inflight:
        peak_gauge = int(max(e.get("value", 0) for e in inflight))
        edges: list[tuple[int, int]] = []
        for sp in run.spans.values():
            if sp.name != "lane-dispatch":
                continue
            edges.append((sp.ts, 1))
            edges.append((run_end if sp.end_ts is None else sp.end_ts, -1))
        live = peak_spans = 0
        for _, d in sorted(edges):
            live += d
            peak_spans = max(peak_spans, live)
        out.write(f"\nserve overlap: max in-flight {peak_gauge} "
                  f"(gauge, {len(inflight)} samples), peak concurrent "
                  f"lane spans {peak_spans}\n")

    # -- the metrics registry (final snapshot totals) ----------------------
    # The flusher's cumulative snapshots (obs/metrics.py): counters
    # summed across processes, gauges last-write, histogram percentiles
    # interpolated from the log2 buckets. This table stays EXACT when
    # span tracing is sampled — it is the reconciliation surface for a
    # sampled run ("did we really serve N requests?").
    if run.snapshots:
        totals = run.metrics_totals()
        out.write(f"\nmetrics ({len(run.snapshots)} snapshot(s) from "
                  f"{len(run.metric_procs)} process(es)):\n")
        if totals["counters"]:
            _table([[k, f"{v:g}"]
                    for k, v in sorted(totals["counters"].items())],
                   ["counter", "total"], out)
        if totals["gauges"]:
            _table([[k, f"{v:g}"]
                    for k, v in sorted(totals["gauges"].items())],
                   ["gauge", "last"], out)
        if totals["hists"]:
            rows = []
            for k, h in sorted(totals["hists"].items()):
                b = h["buckets"]
                rows.append([
                    k, str(h["count"]),
                    f"{_metrics.percentile_from_buckets(b, 50):.0f}",
                    f"{_metrics.percentile_from_buckets(b, 95):.0f}",
                    f"{_metrics.percentile_from_buckets(b, 99):.0f}",
                    (f"{h['sum'] / h['count']:.0f}" if h["count"] else "-"),
                ])
            _table(rows, ["histogram", "count", "p50", "p95", "p99",
                          "mean"], out)

    # -- the fleet waterfall (per-stage time attribution) ------------------
    # The cross-process answer to "where does a request's latency go":
    # the router and backends each observe their ledger stages into
    # `route_stage_us{stage=...}` / `serve_stage_us{stage=...}` (the
    # registry is the fleet-wide aggregation — the flusher's snapshots
    # from every process merge here), rendered in request-path order
    # with percentiles interpolated from the log2 buckets. This is the
    # table the TPU-saturation gap decomposes on (docs/OBSERVABILITY.md
    # cookbook): a goodput miss names its stage, not just its total.
    stage_hists: dict[str, dict] = {}
    if run.snapshots:
        totals_w = run.metrics_totals()
        for key, h in totals_w["hists"].items():
            m = re.fullmatch(r"(?:route|serve)_stage_us\{stage=(\w+)\}",
                             key)
            if m:
                agg = stage_hists.setdefault(
                    m.group(1), {"buckets": {}, "count": 0, "sum": 0.0})
                agg["buckets"] = _metrics.merge_buckets(
                    [agg["buckets"], h["buckets"]])
                agg["count"] += h["count"]
                agg["sum"] += h["sum"]
        if stage_hists:
            out.write("\nfleet waterfall (per-stage time attribution, "
                      "µs):\n")
            rows = []
            known = [s for s in WATERFALL_STAGES if s in stage_hists]
            extra = sorted(set(stage_hists) - set(known))
            for name in known + extra:
                h = stage_hists[name]
                b = h["buckets"]
                rows.append([
                    name, str(h["count"]),
                    f"{_metrics.percentile_from_buckets(b, 50):.0f}",
                    f"{_metrics.percentile_from_buckets(b, 95):.0f}",
                    f"{_metrics.percentile_from_buckets(b, 99):.0f}",
                    (f"{h['sum'] / h['count']:.0f}" if h["count"]
                     else "-"),
                ])
            _table(rows, ["stage", "count", "p50", "p95", "p99", "mean"],
                   out)

    # -- slowest exemplars (histogram tails -> span chains) ----------------
    # The registry's retained tail exemplars (obs/metrics.py), ranked
    # by value and resolved against the trace: the table that turns "a
    # p99 bucket exists" into "THIS request, THIS chain". A row whose
    # chain breaks (span or an ancestor missing from the stream) says
    # so — `--profile --check` gates that none do on a sampled run.
    ex_rows = exemplar_rows(run, top=top)
    if ex_rows:
        out.write("\nslowest exemplars (histogram tails -> span "
                  "chains):\n")
        _table([[r["hist"], f"{r['v']:.0f}", str(r["span"] or "-"),
                 (" < ".join(r["chain"]) if r["chain"] else "-"),
                 ("complete" if r["complete"] else "BROKEN")]
                for r in ex_rows],
               ["histogram", "value_us", "span", "chain", "resolve"],
               out)

    # -- the roofline (cost model x measured device time) ------------------
    # The run dir's cost-*.json records (obs/costmodel.py, stamped at
    # serve warmup) joined with the registry's per-rung dispatch/device
    # counters: modeled HBM bytes moved over measured device time, per
    # engine x mode x rung, with utilization against the measured
    # ceiling when one was recorded — the table that decomposes a serve
    # number below the offline BENCH_r* figure into "which kernel, what
    # utilization, which rung".
    cost_recs: list = []
    ceiling = None
    if run_dir:
        cost_recs, ceiling = costmodel.load_run_records(run_dir)
    if cost_recs and run.snapshots:
        counters_flat = run.metrics_totals()["counters"]
        cs = costmodel.cost_section(cost_recs, counters_flat,
                                    ceiling_gbps=ceiling)
        if cs["rows"]:
            out.write("\nroofline (modeled HBM traffic vs achieved "
                      "device rate):\n")
            _table([[r["engine"], r["mode"], str(r["rung"]),
                     str(r.get("nr", 0)),
                     str(r["dispatches"]),
                     f"{r['modeled_dispatch_bytes'] / 1e6:.3f}",
                     f"{r['device_s']:.3f}",
                     f"{r['achieved_gbps']:.3f}",
                     (f"{r['utilization']:.1%}"
                      if r["utilization"] is not None else "-")]
                    for r in cs["rows"]],
                   ["engine", "mode", "rung", "nr", "disp", "MB/disp",
                    "device_s", "GB/s moved", "util"], out)
            # The one-line gap explain: payload vs modeled traffic over
            # the device windows, utilization vs the roofline, and the
            # dominant NON-device waterfall stage — the saturation-run
            # decomposition (docs/OBSERVABILITY.md cookbook) in a
            # sentence instead of four tables.
            moved = sum(r["modeled_bytes"] for r in cs["rows"])
            dev_s = sum(r["device_s"] for r in cs["rows"])
            served = counters_flat.get("serve_served_bytes", 0.0)
            parts = []
            if dev_s > 0:
                parts.append(f"device moved {moved / 1e9 / dev_s:.3f} "
                             f"GB/s modeled"
                             + (f" ({served / 1e9 / dev_s:.3f} GB/s "
                                f"payload)" if served else ""))
            if ceiling and dev_s > 0:
                parts.append(f"{moved / 1e9 / dev_s / ceiling:.1%} of "
                             f"the {ceiling:g} GB/s ceiling")
            off_device = {s: h for s, h in stage_hists.items()
                          if s != "device" and h["count"]}
            if off_device:
                worst = max(off_device.items(),
                            key=lambda kv: kv[1]["sum"])
                total_stage = sum(h["sum"] for h in stage_hists.values())
                frac = (worst[1]["sum"] / total_stage
                        if total_stage else 0.0)
                parts.append(
                    f"biggest off-device stage: {worst[0]} "
                    f"(p95 {_metrics.percentile_from_buckets(worst[1]['buckets'], 95):.0f}µs, "
                    f"{frac:.0%} of summed stage time)")
            if parts:
                out.write("gap explain: " + "; ".join(parts) + "\n")

    # -- warmup compile cost ------------------------------------------------
    # serve_compile_us{engine, rung}: the jax.monitoring compile events
    # routed into the registry at warmup (serve/server.py) — exact at
    # any sample rate, so the startup compile bill is attributable per
    # rung even on a fully sampled-out run.
    if run.snapshots:
        comp_rows = []
        for key, h in sorted(run.metrics_totals()["hists"].items()):
            m = re.fullmatch(r"serve_compile_us\{engine=([^,}]*),"
                             r"rung=(\d+)\}", key)
            if not m:
                continue
            comp_rows.append([
                m.group(1), m.group(2), str(h["count"]),
                f"{h['sum'] / 1e6:.3f}",
                f"{_metrics.percentile_from_buckets(h['buckets'], 95) / 1e6:.3f}",
            ])
        if comp_rows:
            comp_rows.sort(key=lambda r: (r[0], int(r[1])))
            out.write("\nwarmup compile cost (serve_compile_us):\n")
            _table(comp_rows,
                   ["engine", "rung", "compiles", "total_s", "p95_s"],
                   out)

    # -- incident bundles ---------------------------------------------------
    if run_dir:
        bundles = incident.bundle_index(run_dir)
        if bundles:
            reasons = ", ".join(str(b["reason"]) for b in bundles)
            bad = sum(1 for b in bundles if not b["valid"])
            out.write(f"\nincidents: {len(bundles)} bundle(s): {reasons}"
                      + (f" ({bad} INVALID)" if bad else "")
                      + "  [obs.report --incidents renders them]\n")

    # -- pulse alerts (obs/pulse.py trace points) --------------------------
    alerts = run.points("pulse-alert")
    if alerts:
        by_rule: dict[tuple[str, str], int] = {}
        for p in alerts:
            a = p.get("attrs", {})
            k = (str(a.get("rule", "?")), str(a.get("severity", "?")))
            by_rule[k] = by_rule.get(k, 0) + 1
        out.write(f"\npulse alerts: {len(alerts)}: "
                  + ", ".join(f"{r} x{n} ({sev})"
                              for (r, sev), n in sorted(by_rule.items()))
                  + "  [obs.pulse <run-dir> replays the rule engine]\n")

    # -- cross-process joins + clock skew (fleet tracing) ------------------
    join = fleet_join_stats(run)
    if join["roots"]:
        out.write(f"\nfleet join: {join['joined']}/{join['roots']} "
                  "route-request spans joined by a cross-process backend "
                  f"span ({join['frac']:.1%}; {join['linked']} with any "
                  "child)\n")
    offsets = run.clock_offsets()
    if offsets:
        out.write("clock skew (wire handshake): "
                  + ", ".join(f"pid {pid}: {off:+d}µs"
                              for pid, off in sorted(offsets.items()))
                  + "\n")

    # -- faults: injected vs observed --------------------------------------
    injected: dict[str, int] = {}
    for p in run.points("fault-injected"):
        name = p.get("attrs", {}).get("point", "?")
        injected[name] = injected.get(name, 0) + 1
    observed = {
        "watchdog-expired": len(run.points("watchdog-expired")),
        "child-killed": len(run.points("child-killed")),
        "unit-failed": len(run.points("unit-failed")),
    }
    out.write("\nfaults injected: "
              + (", ".join(f"{k} x{v}" for k, v in sorted(injected.items()))
                 if injected else "none") + "\n")
    out.write("faults observed: "
              + ", ".join(f"{k}={v}" for k, v in sorted(observed.items()))
              + "\n")

    # -- degradations / quarantines ----------------------------------------
    degr = run.points("degrade")
    out.write("degradations: " + (
        "; ".join(
            f"{p['attrs'].get('kind', '?')}"
            + (f" ({p['attrs'].get('why')})" if p.get("attrs", {}).get("why")
               else "")
            for p in degr) if degr else "none") + "\n")
    q = sorted(u for u in quarantined if u)
    out.write("quarantined: " + (", ".join(q) if q else "none"))
    r = sorted(u for u in released if u)
    out.write((f"  released: {', '.join(r)}" if r else "") + "\n")

    # -- slowest spans ------------------------------------------------------
    ranked = sorted(run.spans.values(),
                    key=lambda s: (-s.dur_us(run_end), s.ts, s.id))[:top]
    if ranked:
        out.write(f"\nslowest spans (top {min(top, len(ranked))}):\n")
        _table([[sp.name, _unit_of(run, sp) or "-", str(sp.pid),
                 _s(sp.dur_us(run_end)),
                 "killed" if sp.orphan else (sp.status or "?")]
                for sp in ranked],
               ["span", "unit", "pid", "dur_s", "status"], out)

    # -- orphans ------------------------------------------------------------
    if orphans:
        out.write(f"\norphaned spans ({len(orphans)} — begin with no end: "
                  "the process was killed or died mid-span):\n")
        budget = dict(expected_orphans or {})
        for sp in orphans:
            tag = ""
            if budget.get(sp.name, 0) > 0:
                budget[sp.name] -= 1
                tag = " (expected)"
            out.write(f"  {sp.name} (unit={_unit_of(run, sp) or '-'}, "
                      f"pid {sp.pid}) open {_s(sp.dur_us(run_end))}s "
                      f"until end of run — closed by kill{tag}\n")


def render_incidents(run_dir: str, check: bool = False,
                     out=None, tail: int = 8) -> int:
    """The ``--incidents`` mode: render every flight-recorder bundle in
    the run dir (reason, trigger attrs, the ring's tail, snapshot
    headline counters, cost-record count) and — with ``check`` — exit
    2 unless every bundle validates against the schema
    (``incident.validate_bundle``). A run with NO bundles is a clean
    rc 0 either way: bundle COUNT expectations are the CI drive's own
    asserts, presence is not an error."""
    out = out if out is not None else sys.stdout  # bound at CALL time
    paths = incident.list_bundles(run_dir)
    if not paths:
        out.write(f"no incident bundles under {run_dir}\n")
        return 0
    bad = 0
    for path in paths:
        doc = incident.load_bundle(path)
        viols = incident.validate_bundle(doc)
        d = doc or {}
        out.write(f"incident {os.path.basename(path)}: "
                  f"reason={d.get('reason')} pid={d.get('pid')} "
                  f"ts_us={d.get('ts_us')} "
                  f"ring={len(d.get('ring') or [])} "
                  f"cost_records={len(d.get('cost') or [])}"
                  + (" SCHEMA-INVALID" if viols else "") + "\n")
        for a, v in sorted((d.get("attrs") or {}).items()):
            out.write(f"  attr {a} = {v}\n")
        ring = d.get("ring") or []
        for rec in ring[-tail:]:
            if not isinstance(rec, dict):
                continue
            out.write(
                "  ring "
                f"t={rec.get('t_us')} lane={rec.get('lane')} "
                f"rung={rec.get('rung')} engine={rec.get('engine')} "
                f"mode={rec.get('mode')} outcome={rec.get('outcome')} "
                f"device_us={rec.get('device_us')} "
                f"wall_us={rec.get('wall_us')}\n")
        counters = (d.get("metrics") or {}).get("counters") or {}
        for k in ("serve_served_bytes", "serve_redispatch",
                  "serve_lane_timeout", "serve_auth_failed"):
            hits = {kk: v for kk, v in counters.items()
                    if kk == k or kk.startswith(k + "{")}
            if hits:
                out.write(f"  metric {k} = "
                          f"{sum(hits.values()):g}\n")
        for v in viols:
            out.write(f"  ! {v}\n")
            bad += 1
    if check and bad:
        print(f"CHECK FAILED: {bad} incident-bundle schema "
              "violation(s)", file=sys.stderr)
        return 2
    return 0


def render_profile(run_dir: str, check: bool = False, out=None) -> int:
    """The ``--profile`` section: every capture summary in the run dir
    (obs/profiler.py) — window span, tier, the per-rung kernel wall —
    JOINED against the run dir's cost records (``profiler.crosscheck``)
    so modeled utilization gets its measured in-window cross-check,
    plus the stack-tier hot frames when that tier captured. With
    ``check``: exit 2 on schema-invalid summaries or when NO capture
    exists (the CI mid-drive curl gates that the armed window actually
    landed its artifact)."""
    out = out if out is not None else sys.stdout  # bound at CALL time
    paths = profiler.list_summaries(run_dir)
    if not paths:
        out.write(f"no profile captures under {run_dir}\n")
        if check:
            print("CHECK FAILED: --profile expected at least one "
                  "capture summary in the run dir", file=sys.stderr)
            return 2
        return 0
    cost_recs, ceiling = costmodel.load_run_records(run_dir)
    bad = 0
    for path in paths:
        doc = profiler.load_summary(path)
        viols = profiler.validate_summary(doc)
        d = doc or {}
        out.write(
            f"profile {os.path.basename(path)}: "
            f"tier={d.get('tier')} armed_by={d.get('armed_by')} "
            f"window={d.get('seconds')}s pid={d.get('pid')} "
            f"device {d.get('device_us', 0) / 1e6:.3f}s / busy "
            f"{d.get('busy_us', 0) / 1e6:.3f}s in-window"
            + (" SCHEMA-INVALID" if viols else "") + "\n")
        if d.get("jax_dir"):
            out.write(f"  jax trace: {d['jax_dir']} (TensorBoard / "
                      "ui.perfetto.dev loadable)\n")
        cc = profiler.crosscheck(d, cost_recs, ceiling)
        if cc["rows"]:
            _table([[r["engine"], r["mode"], str(r["rung"]),
                     str(r["dispatches"]), f"{r['device_s']:.3f}",
                     (f"{r['window_gbps']:.3f}"
                      if r["window_gbps"] is not None else "-"),
                     (f"{r['utilization']:.1%}"
                      if r["utilization"] is not None else "-")]
                    for r in cc["rows"]],
                   ["engine", "mode", "rung", "disp", "device_s",
                    "GB/s moved", "util"], out)
        for st in (d.get("stacks") or [])[:5]:
            out.write(f"  stack x{st.get('count')}: "
                      f"{st.get('frames')}\n")
        for v in viols:
            out.write(f"  ! {v}\n")
            bad += 1
    if check and bad:
        print(f"CHECK FAILED: {bad} profile-summary schema "
              "violation(s)", file=sys.stderr)
        return 2
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="reconstruct a traced run (our_tree_tpu.obs)")
    ap.add_argument("run_dir", help="$OT_TRACE_DIR/<run-id> (or "
                                    "$OT_TRACE_DIR: newest run inside)")
    ap.add_argument("--check", action="store_true",
                    help="exit 2 on schema violations or orphaned spans")
    ap.add_argument("--expected-orphans", default="", metavar="NAMES",
                    help="comma list of span names whose orphans are "
                         "EXPECTED (faulted-run gating: a dispatch_hang "
                         "rehearsal's SIGKILLed child leaves exactly its "
                         "open spans orphaned). Each listed name licenses "
                         "ONE orphan (repeat a name to allow more); an "
                         "unlisted-name orphan or an extra orphan past a "
                         "name's budget still fails --check")
    ap.add_argument("--incidents", action="store_true",
                    help="INCIDENT mode: render the run dir's "
                         "flight-recorder bundles (incident-*.json, "
                         "obs/incident.py) instead of the trace "
                         "report; with --check, exit 2 unless every "
                         "bundle is schema-valid (orphan/violation "
                         "gating stays with the plain report run)")
    ap.add_argument("--profile", action="store_true",
                    help="PROFILE mode: render the run dir's capture "
                         "summaries (profile-*.json, obs/profiler.py) "
                         "joined against its cost records — per-rung "
                         "in-window kernel wall vs modeled traffic — "
                         "after the trace report; with --check, exit 2 "
                         "unless at least one capture exists, every "
                         "summary is schema-valid, AND every rendered "
                         "slowest-exemplar row resolves to a complete "
                         "span chain")
    ap.add_argument("--trace-json", default=None, metavar="PATH",
                    help="also write the Chrome/Perfetto trace.json "
                         "(clock-aligned across processes when wire-skew "
                         "handshake points exist)")
    ap.add_argument("--min-join-frac", type=float, default=None,
                    metavar="FRAC",
                    help="fail (exit 2) unless at least FRAC of the "
                         "run's route-request spans are joined by a "
                         "cross-process backend span — the fleet trace-"
                         "propagation gate (no-op when the run has no "
                         "route-request spans)")
    ap.add_argument("--top", type=int, default=10,
                    help="slowest-span table size")
    args = ap.parse_args(argv)

    run_dir = _resolve_run_dir(args.run_dir,
                               say=lambda m: print(m, file=sys.stderr))
    if args.incidents:
        return render_incidents(run_dir, check=args.check)
    run = export.load_run(run_dir)
    if not run.procs:
        print(f"no trace-*.jsonl files under {run_dir}", file=sys.stderr)
        return 1
    expected: dict[str, int] = {}
    for tok in args.expected_orphans.split(","):
        tok = tok.strip()
        if tok:
            expected[tok] = expected.get(tok, 0) + 1
    render(run, top=args.top, expected_orphans=expected,
           run_dir=run_dir)
    if args.profile:
        rc = render_profile(run_dir, check=args.check)
        if rc:
            return rc
        if args.check:
            broken = [r for r in exemplar_rows(run, top=args.top)
                      if not r["complete"]]
            if broken:
                print(f"CHECK FAILED: {len(broken)} slowest-exemplar "
                      "row(s) do not resolve to a complete span chain: "
                      + ", ".join(f"{r['hist']}->{r['span']}"
                                  for r in broken), file=sys.stderr)
                return 2
    if args.trace_json:
        path = export.write_chrome_trace(run, args.trace_json)
        print(f"# perfetto export: {path} "
              f"({len(run.spans)} spans) — open at https://ui.perfetto.dev",
              file=sys.stderr)
    # Per-name BUDGET, not a name allowlist: each listed name licenses
    # one orphan, so two killed children in a rehearsal that kills one
    # cannot hide behind the same three span names.
    budget = dict(expected)
    unexpected = []
    for s in run.orphans():
        if budget.get(s.name, 0) > 0:
            budget[s.name] -= 1
        else:
            unexpected.append(s)
    if args.check and (run.violations or unexpected):
        n_ok = len(run.orphans()) - len(unexpected)
        print(f"CHECK FAILED: {len(run.violations)} schema violation(s), "
              f"{len(unexpected)} unexpected orphaned span(s)"
              + (f" ({n_ok} expected orphan(s) allowed)" if n_ok else ""),
              file=sys.stderr)
        return 2
    if args.min_join_frac is not None:
        join = fleet_join_stats(run)
        if join["roots"] and join["frac"] < args.min_join_frac:
            print(f"CHECK FAILED: only {join['joined']}/{join['roots']} "
                  f"({join['frac']:.1%}) route-request spans joined "
                  f"across processes (< {args.min_join_frac:.1%}) — "
                  "cross-process trace propagation regressed",
                  file=sys.stderr)
            return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
