C AES-256 ECB, 1048576, 1, 747, 831, 721, 785,
# derived: 1.454 GB/s (best of 4)
C AES-256 ECB, 1048576, 2, 746, 1542, 733, 772,
# derived: 1.431 GB/s (best of 4)
C AES-256 ECB, 1048576, 4, 814, 763, 769, 778,
# derived: 1.374 GB/s (best of 4)
C AES-256 ECB, 1048576, 8, 961, 964, 896, 944,
# derived: 1.170 GB/s (best of 4)
C AES-256 ECB, 10485760, 1, 11002, 11387, 7885, 7737,
# derived: 1.355 GB/s (best of 4)
C AES-256 ECB, 10485760, 2, 8149, 7662, 7557, 7709,
# derived: 1.388 GB/s (best of 4)
C AES-256 ECB, 10485760, 4, 7398, 7492, 7731, 10357,
# derived: 1.417 GB/s (best of 4)
C AES-256 ECB, 10485760, 8, 7821, 13280, 8240, 11982,
# derived: 1.341 GB/s (best of 4)
C AES-256 ECB, 67108864, 1, 71190, 79478, 72643, 77458,
# derived: 0.943 GB/s (best of 4)
C AES-256 ECB, 67108864, 2, 74787, 86471, 92870, 87339,
# derived: 0.897 GB/s (best of 4)
C AES-256 ECB, 67108864, 4, 86748, 80100, 81052, 81091,
# derived: 0.838 GB/s (best of 4)
C AES-256 ECB, 67108864, 8, 82216, 83259, 96264, 80078,
# derived: 0.838 GB/s (best of 4)
C AES-256 CTR, 1048576, 1, 911, 950, 905, 924,
# derived: 1.159 GB/s (best of 4)
C AES-256 CTR, 1048576, 2, 941, 920, 928, 912,
# derived: 1.150 GB/s (best of 4)
C AES-256 CTR, 1048576, 4, 999, 1024, 936, 985,
# derived: 1.120 GB/s (best of 4)
C AES-256 CTR, 1048576, 8, 1145, 1229, 984, 951,
# derived: 1.103 GB/s (best of 4)
C AES-256 CTR, 10485760, 1, 7501, 11940, 7513, 7431,
# derived: 1.411 GB/s (best of 4)
C AES-256 CTR, 10485760, 2, 7185, 8238, 8138, 7465,
# derived: 1.459 GB/s (best of 4)
C AES-256 CTR, 10485760, 4, 9513, 7538, 10369, 8638,
# derived: 1.391 GB/s (best of 4)
C AES-256 CTR, 10485760, 8, 10310, 7457, 12545, 7855,
# derived: 1.406 GB/s (best of 4)
C AES-256 CTR, 67108864, 1, 69954, 70866, 74554, 73241,
# derived: 0.959 GB/s (best of 4)
C AES-256 CTR, 67108864, 2, 70583, 70647, 73976, 71025,
# derived: 0.951 GB/s (best of 4)
C AES-256 CTR, 67108864, 4, 84905, 76654, 68878, 67757,
# derived: 0.990 GB/s (best of 4)
C AES-256 CTR, 67108864, 8, 66395, 69954, 70247, 67587,
# derived: 1.011 GB/s (best of 4)
RC4, 1048576, 1, 
Generated a new key in 3861, 
832, 834, 820, 877,
# derived: 1.279 GB/s (best of 4)
RC4, 1048576, 2, 
Generated a new key in 3806, 
860, 850, 863, 851,
# derived: 1.234 GB/s (best of 4)
RC4, 1048576, 4, 
Generated a new key in 3819, 
856, 909, 884, 898,
# derived: 1.225 GB/s (best of 4)
RC4, 1048576, 8, 
Generated a new key in 3754, 
1034, 986, 982, 978,
# derived: 1.072 GB/s (best of 4)
RC4, 10485760, 1, 
Generated a new key in 41805, 
8565, 15112, 9396, 8162,
# derived: 1.285 GB/s (best of 4)
RC4, 10485760, 2, 
Generated a new key in 38258, 
9066, 12819, 8661, 8373,
# derived: 1.252 GB/s (best of 4)
RC4, 10485760, 4, 
Generated a new key in 38591, 
12281, 12393, 8505, 8350,
# derived: 1.256 GB/s (best of 4)
RC4, 10485760, 8, 
Generated a new key in 43344, 
10861, 12828, 12231, 8540,
# derived: 1.228 GB/s (best of 4)
RC4, 67108864, 1, 
Generated a new key in 272049, 
81908, 81671, 83196, 81441,
# derived: 0.824 GB/s (best of 4)
RC4, 67108864, 2, 
Generated a new key in 292651, 
103933, 82914, 79604, 94613,
# derived: 0.843 GB/s (best of 4)
RC4, 67108864, 4, 
Generated a new key in 289878, 
80149, 80660, 80586, 80005,
# derived: 0.839 GB/s (best of 4)
RC4, 67108864, 8, 
Generated a new key in 273611, 
89241, 86122, 81358, 80454,
# derived: 0.834 GB/s (best of 4)
Shard invariance [1, 2, 4, 8]: passed
ARC4 test #1: passed
ARC4 test #2: passed
ARC4 test #3: passed
