"""Table-generation unit tests (reference aes_gen_tables, aes.c:361-435)."""

import numpy as np

from our_tree_tpu.ops import gf, tables


def test_sbox_known_entries():
    # FIPS-197 figure 7 spot checks.
    assert tables.SBOX[0x00] == 0x63
    assert tables.SBOX[0x01] == 0x7C
    assert tables.SBOX[0x53] == 0xED
    assert tables.SBOX[0xFF] == 0x16


def test_sbox_is_bijection():
    assert sorted(tables.SBOX.tolist()) == list(range(256))
    assert np.array_equal(tables.INV_SBOX[tables.SBOX], np.arange(256))


def test_gf_inverse():
    for a in range(1, 256):
        assert gf.gmul(a, gf.ginv(a)) == 1
    assert gf.ginv(0) == 0


def test_ft_tables_structure():
    # FT0[x] packs (2S, S, S, 3S) little-endian; FTi are byte rotations.
    for x in (0x00, 0x01, 0x7F, 0xFF):
        s = int(tables.SBOX[x])
        expect = gf.gmul(2, s) | (s << 8) | (s << 16) | (gf.gmul(3, s) << 24)
        assert int(tables.FT0[x]) == expect
    w = tables.FT0.astype(np.uint64)
    assert np.array_equal(tables.FT1, (((w << 8) | (w >> 24)) & 0xFFFFFFFF).astype(np.uint32))


def test_rt_tables_structure():
    for x in (0x00, 0x01, 0x7F, 0xFF):
        i = int(tables.INV_SBOX[x])
        expect = (
            gf.gmul(14, i)
            | (gf.gmul(9, i) << 8)
            | (gf.gmul(13, i) << 16)
            | (gf.gmul(11, i) << 24)
        )
        assert int(tables.RT0[x]) == expect


def test_inv_mix_columns_word_roundtrip():
    # MixColumns then InvMixColumns is identity on random words.
    rng = np.random.default_rng(0)
    m2, m3 = gf.gmul_table(2), gf.gmul_table(3)

    def mix(w):
        b = [(w >> (8 * k)) & 0xFF for k in range(4)]
        s0 = m2[b[0]] ^ m3[b[1]] ^ b[2] ^ b[3]
        s1 = b[0] ^ m2[b[1]] ^ m3[b[2]] ^ b[3]
        s2 = b[0] ^ b[1] ^ m2[b[2]] ^ m3[b[3]]
        s3 = m3[b[0]] ^ b[1] ^ b[2] ^ m2[b[3]]
        return (s0 | (s1 << 8) | (s2 << 16) | (s3 << 24)).astype(np.uint32)

    w = rng.integers(0, 1 << 32, 64, dtype=np.uint32)
    assert np.array_equal(tables.inv_mix_columns_word(mix(w)), w)
