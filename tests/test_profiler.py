"""ot-scope's capture seam (our_tree_tpu/obs/profiler.py): window
open/close + the one-at-a-time contract, the registry-delta summary and
its costmodel cross-check, /profilez over the live status endpoint
(200 armed / 409 overlapping / 503 untraced), incident arming under the
trigger cooldown (no capture storm), clean close at drain, and the
report --profile join."""

import asyncio
import io
import json
import time
import urllib.error
import urllib.request

import pytest

from our_tree_tpu.obs import (costmodel, export, incident, metrics,
                              profiler, report, trace)
from our_tree_tpu.resilience import degrade, faults
from our_tree_tpu.serve.server import Server, ServerConfig

LADDER = dict(min_bucket_blocks=32, max_bucket_blocks=256)


@pytest.fixture(autouse=True)
def _clean_process_state(monkeypatch):
    monkeypatch.delenv("OT_FAULTS", raising=False)
    monkeypatch.delenv("OT_PROFILE_ON_INCIDENT", raising=False)
    # The stack tier by default: tests must not leave a process-global
    # jax profiler session behind (one per process, and another suite's
    # capture would collide with it).
    monkeypatch.setenv("OT_PROFILE_TIER", "stack")
    monkeypatch.setenv("OT_PROFILE_HZ", "100")
    faults.reset()
    degrade.clear()
    metrics.reset_for_tests()
    profiler.reset_for_tests()
    incident.reset_for_tests()
    yield
    profiler.reset_for_tests()
    incident.reset_for_tests()
    metrics.reset_for_tests()
    faults.reset()
    degrade.clear()


@pytest.fixture
def traced(tmp_path, monkeypatch):
    monkeypatch.setenv("OT_TRACE_DIR", str(tmp_path / "tr"))
    monkeypatch.setenv("OT_TRACE_RUN", "t-prof")
    monkeypatch.delenv("OT_TRACE_PARENT", raising=False)
    trace.reset_for_tests()
    metrics.reset_for_tests()
    yield tmp_path / "tr" / "t-prof"
    trace.reset_for_tests()
    metrics.reset_for_tests()


# ---------------------------------------------------------------------------
# The window contract.
# ---------------------------------------------------------------------------


def test_window_requires_tracing(monkeypatch):
    monkeypatch.delenv("OT_TRACE_DIR", raising=False)
    with pytest.raises(profiler.CaptureDisabled):
        profiler.start_window(0.1)


def test_window_refuses_overlap_and_summarises_deltas(traced):
    metrics.counter("serve_rung_dispatches", 5, rung=64, engine="jnp",
                    mode="ctr", nr=10)
    out = profiler.start_window(0.2, armed_by="api")
    assert out["tier"] == "stack"
    with pytest.raises(profiler.CaptureBusy):
        profiler.start_window(0.2)
    assert profiler.active()["seq"] == out["seq"]
    # Traffic INSIDE the window: only the delta lands in the summary.
    metrics.counter("serve_rung_dispatches", 3, rung=64, engine="jnp",
                    mode="ctr", nr=10)
    metrics.counter("serve_rung_device_us", 4000, rung=64, engine="jnp",
                    mode="ctr", nr=10)
    metrics.counter("serve_lane_busy_us", 9000, lane=0)
    metrics.counter("serve_device_us", 4000, lane=0)
    metrics.observe("serve_stage_us", 777, stage="device")
    assert profiler.wait_idle(10)
    doc = profiler.last_summary()
    assert profiler.validate_summary(doc) == []
    assert doc["rungs"] == [{"engine": "jnp", "mode": "ctr", "rung": 64,
                             "nr": 10, "dispatches": 3,
                             "device_us": 4000}]
    assert doc["stages"]["device"]["count"] == 1
    assert doc["busy_us"] == 9000 and doc["device_us"] == 4000
    assert doc["host_us"] == 5000
    assert doc["samples"] >= 1 and doc["stacks"]
    # The summary is on disk in the run layout, and a SECOND window may
    # open once the first closed.
    paths = profiler.list_summaries(str(traced))
    assert len(paths) == 1
    assert profiler.load_summary(paths[0])["seq"] == doc["seq"]
    out2 = profiler.start_window(0.05)
    assert out2["seq"] != out["seq"]
    assert profiler.wait_idle(10)
    assert len(profiler.list_summaries(str(traced))) == 2


def test_drain_close_is_clean(traced):
    """A window still open at drain closes EARLY and completely: the
    closer thread that would have fired later must not close the NEXT
    window (the expected_seq guard)."""
    out = profiler.start_window(30.0, armed_by="http")
    path = profiler.finish()
    assert path is not None
    doc = profiler.load_summary(path)
    assert profiler.validate_summary(doc) == []
    assert doc["seconds"] < 5.0  # closed at drain, not after 30 s
    # A new window opened right away is NOT closed by the first
    # window's (still sleeping) closer thread.
    out2 = profiler.start_window(None, armed_by="api")
    assert out2["seq"] == out["seq"] + 1
    assert profiler.active() is not None
    assert profiler.stop_window(expected_seq=out["seq"]) is None
    assert profiler.active() is not None  # untouched
    assert profiler.stop_window() is not None


def test_crosscheck_joins_cost_records(traced):
    rec = costmodel.analytic_cost("jnp", "ctr", 64, 10, 8)
    doc = {"rungs": [{"engine": "jnp", "mode": "ctr", "rung": 64,
                      "nr": 10, "dispatches": 10, "device_us": 1000},
                     {"engine": "jnp", "mode": "gcm", "rung": 64,
                      "nr": 10, "dispatches": 2, "device_us": 0}]}
    cc = profiler.crosscheck(doc, [rec], ceiling_gbps=10.0)
    row = cc["rows"][0]
    want = rec["hbm_bytes"] * 10 / 1e9 / 1e-3
    assert abs(row["window_gbps"] - want) < 1e-6 * want
    assert abs(row["utilization"] - want / 10.0) < 1e-6
    # No record / no device time -> present but unrated, never omitted.
    assert cc["rows"][1]["window_gbps"] is None
    assert cc["rows"][1]["modeled_dispatch_bytes"] is None


def test_sweep_capture_noop_when_disabled(monkeypatch):
    monkeypatch.delenv("OT_TRACE_DIR", raising=False)
    with profiler.sweep_capture():
        assert profiler.active() is None  # degraded to a no-op
    assert profiler.last_summary() is None


def test_validate_summary_flags_malformed():
    assert profiler.validate_summary(None)
    assert profiler.validate_summary({"kind": "nope"})
    viols = profiler.validate_summary(
        {"kind": profiler.KIND, "v": 1, "run": "r", "pid": 1,
         "t0_us": 0, "t1_us": 1, "seconds": 1.0, "tier": "warp",
         "armed_by": "cli", "rungs": [{}], "stages": {}})
    assert any("tier" in v for v in viols)
    assert any("rungs[0]" in v for v in viols)


# ---------------------------------------------------------------------------
# /profilez on the live endpoint.
# ---------------------------------------------------------------------------


def _run_server(config, fn):
    async def main():
        server = Server(config)
        await server.start()
        try:
            return server, await fn(server)
        finally:
            await server.stop()

    return asyncio.run(main())


def _fetch(port, path):
    req = urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10)
    with req as r:
        return r.status, json.loads(r.read().decode())


def test_profilez_arms_refuses_overlap_and_lands_artifact(traced):
    async def drive(server):
        port = server.status.port
        loop = asyncio.get_running_loop()
        code, doc = await loop.run_in_executor(
            None, _fetch, port, "/profilez?seconds=0.3")
        assert code == 200 and doc["armed"] and doc["tier"] == "stack"
        # Overlapping request: 409, naming the open capture.
        with pytest.raises(urllib.error.HTTPError) as ei:
            await loop.run_in_executor(None, _fetch, port,
                                       "/profilez?seconds=1")
        assert ei.value.code == 409
        body = json.loads(ei.value.read().decode())
        assert "already in progress" in body["error"]
        assert body["active"]["armed_by"] == "http"
        return doc

    _run_server(ServerConfig(lanes=1, status_port=0, **LADDER), drive)
    # The drive drained with the window possibly still open: the close
    # is clean and the artifact exists, loads, and validates.
    assert profiler.wait_idle(10)
    paths = profiler.list_summaries(str(traced))
    assert len(paths) == 1
    doc = profiler.load_summary(paths[0])
    assert profiler.validate_summary(doc) == []
    assert doc["armed_by"] == "http"


def test_profilez_503_when_untraced(monkeypatch):
    monkeypatch.delenv("OT_TRACE_DIR", raising=False)
    trace.reset_for_tests()

    async def drive(server):
        port = server.status.port
        loop = asyncio.get_running_loop()
        with pytest.raises(urllib.error.HTTPError) as ei:
            await loop.run_in_executor(None, _fetch, port,
                                       "/profilez?seconds=1")
        return ei.value.code

    _, code = _run_server(ServerConfig(lanes=1, status_port=0, **LADDER),
                          drive)
    assert code == 503


# ---------------------------------------------------------------------------
# Incident arming (OT_PROFILE_ON_INCIDENT).
# ---------------------------------------------------------------------------


def test_incident_arms_one_capture_per_cooldown(traced, monkeypatch):
    monkeypatch.setenv("OT_PROFILE_ON_INCIDENT", "0.1")
    monkeypatch.setenv("OT_INCIDENT_COOLDOWN_S", "30")
    # Two triggers within the cooldown: ONE bundle, ONE capture — the
    # coalescing rule is also the capture-storm guard. Arming is
    # ASYNC (a daemon thread, so trigger never stalls the serve
    # loop): poll the run dir for the summary.
    assert incident.trigger("watchdog-kill", lane=0) is not None
    assert incident.trigger("quarantine", lane=0) is None  # suppressed
    deadline = time.monotonic() + 10.0
    while (not profiler.list_summaries(str(traced))
           and time.monotonic() < deadline):
        time.sleep(0.02)
    assert profiler.wait_idle(10)
    assert len(profiler.list_summaries(str(traced))) == 1
    doc = profiler.load_summary(profiler.list_summaries(str(traced))[0])
    assert doc["armed_by"] == "incident"
    assert incident.counts()["dumped"] == 1


def test_incident_capture_off_by_default(traced):
    assert incident.trigger("slo-breach") is not None
    assert profiler.active() is None
    assert profiler.list_summaries(str(traced)) == []


# ---------------------------------------------------------------------------
# report --profile: the rendered join + gates.
# ---------------------------------------------------------------------------


def test_report_profile_renders_join_and_gates(traced, capsys):
    rec = costmodel.analytic_cost("jnp", "ctr", 64, 10, 8)
    costmodel.write_run_records([rec], engine="jnp", ceiling_gbps=5.0)
    profiler.start_window(None, armed_by="api")
    metrics.counter("serve_rung_dispatches", 4, rung=64, engine="jnp",
                    mode="ctr", nr=10)
    metrics.counter("serve_rung_device_us", 2000, rung=64, engine="jnp",
                    mode="ctr", nr=10)
    profiler.stop_window()
    buf = io.StringIO()
    rc = report.render_profile(str(traced), check=True, out=buf)
    out = buf.getvalue()
    assert rc == 0
    assert "tier=stack" in out and "armed_by=api" in out
    assert "GB/s moved" in out and "jnp" in out
    # CLI surface: --profile --check over the same run dir is rc 0.
    assert report.main([str(traced), "--profile", "--check"]) == 0
    capsys.readouterr()


def test_report_profile_check_fails_without_capture(traced):
    trace.point("anything")  # materialise the run dir + trace file
    buf = io.StringIO()
    assert report.render_profile(str(traced), check=True, out=buf) == 2
    assert report.render_profile(str(traced), check=False,
                                 out=io.StringIO()) == 0


def test_report_profile_check_fails_on_invalid_summary(traced):
    trace.point("anything")
    bad = traced / "profile-1-deadbeef-1.json"
    bad.write_text(json.dumps({"kind": "nope"}))
    buf = io.StringIO()
    assert report.render_profile(str(traced), check=True, out=buf) == 2
    assert "SCHEMA-INVALID" in buf.getvalue()


def test_exemplar_rows_resolve_span_chains(traced):
    with trace.span("outer", unit="u"):
        cm = trace.detached_span("inner")
        cm.__enter__()
        sid = cm.span_id
        metrics.observe("serve_dispatch_us", 5000,
                        exemplar={"span": sid, "trace": trace.run_id()})
        cm.__exit__(None, None, None)
    # A second exemplar pointing NOWHERE: its chain must read broken.
    metrics.observe("serve_stage_us", 9000, stage="device",
                    exemplar={"span": "nope.1", "trace": trace.run_id()})
    metrics.flush_now()
    run = export.load_run(str(traced))
    rows = report.exemplar_rows(run, top=10)
    by_hist = {r["hist"]: r for r in rows}
    good = by_hist["serve_dispatch_us"]
    assert good["complete"] and good["chain"] == ["inner", "outer"]
    bad = by_hist["serve_stage_us{stage=device}"]
    assert not bad["complete"] and bad["chain"] == []
    # With a valid capture on file, --profile renders rc 0 — and
    # --check still fails, now naming the BROKEN exemplar row.
    profiler.start_window(None, armed_by="api")
    profiler.stop_window()
    assert report.main([str(traced), "--profile"]) == 0
    rc = report.main([str(traced), "--profile", "--check"])
    assert rc == 2


# ---------------------------------------------------------------------------
# Router federation (route/status.py): one operator request, per-backend
# relay through the proxy seam.
# ---------------------------------------------------------------------------


class _StubSpec:
    def __init__(self, status_port):
        self.status_port = status_port


class _StubBackend:
    def __init__(self, status_port, result):
        self.spec = _StubSpec(status_port)
        self._result = result
        self.asked_seconds = None

    async def poll_profilez(self, seconds, timeout_s=5.0):
        self.asked_seconds = seconds
        if isinstance(self._result, Exception):
            raise self._result
        return self._result


class _StubRouter:
    def __init__(self, backends):
        self.backends = backends


def test_router_profilez_federates_per_backend():
    from our_tree_tpu.route.status import RouterStatus

    b0 = _StubBackend(1234, {"code": 200, "doc": {"armed": True,
                                                  "tier": "stack"}})
    b1 = _StubBackend(1235, {"code": 409, "doc": {"error": "busy"}})
    b2 = _StubBackend(1236, None)            # unreachable
    b3 = _StubBackend(None, None)            # no status port: skipped
    rs = RouterStatus(_StubRouter({"b0": b0, "b1": b1, "b2": b2,
                                   "b3": b3}), port=0)
    code, doc = asyncio.run(rs.profilez_async(2.0))
    assert code == 200 and doc["armed"] == 1
    assert doc["federated"]["b0"]["tier"] == "stack"
    assert doc["federated"]["b1"]["code"] == 409
    assert doc["federated"]["b2"] == {"error": "unreachable"}
    assert "b3" not in doc["federated"]
    assert b0.asked_seconds == 2.0
    # Every backend busy -> 409; none reachable -> 503.
    rs = RouterStatus(_StubRouter({"b1": b1}), port=0)
    assert asyncio.run(rs.profilez_async(1.0))[0] == 409
    rs = RouterStatus(_StubRouter({"b2": b2}), port=0)
    assert asyncio.run(rs.profilez_async(1.0))[0] == 503
