"""Harness CLIs: sweep CSV format, shard invariance, decrypt round-trip."""

import numpy as np
import pytest

from our_tree_tpu.harness import bench as bench_mod
from our_tree_tpu.harness import decrypt as decrypt_mod


def test_bench_sweep_csv(tmp_path, capsys):
    out = tmp_path / "results.test.tpu"
    rc = bench_mod.main([
        "--sizes-mb", "0.0625", "--workers", "1,2", "--iters", "2",
        "--modes", "ecb,ctr,rc4", "--out", str(out),
    ])
    assert rc == 0
    lines = out.read_text().splitlines()
    # Reference row shape: "<name>, <bytes>, <workers>, t1, t2," — and the
    # run must end with the ARC4 self-test like reference test.c:156.
    ecb_rows = [l for l in lines if l.startswith("TPU AES-256 ECB")]
    assert len(ecb_rows) == 2
    for row in ecb_rows:
        fields = [f for f in row.split(",") if f.strip()]
        assert fields[1].strip() == "65536"
        assert int(fields[2]) in (1, 2)
        assert len(fields) == 3 + 2  # name, bytes, workers, two timings
        assert all(int(f) >= 0 for f in fields[3:])
    assert "Shard invariance [1, 2]: passed" in lines
    assert "ARC4 test #3: passed" in lines
    # Every timing row carries a derived-GB/s companion line (SURVEY.md §5
    # metrics: reference format "plus derived GB/s"), and the number matches
    # bytes / best-µs exactly.
    for i, row in enumerate(lines):
        if row.startswith("TPU AES-256 ECB"):
            fields = [f for f in row.split(",") if f.strip()]
            best = min(int(f) for f in fields[3:])
            want = int(fields[1]) / best / 1e3
            derived = lines[i + 1]
            assert derived.startswith("# derived: ")
            assert abs(float(derived.split()[2]) - want) < 0.0005


def test_bench_device_timing_chained(tmp_path):
    """--timing device rows come from the chained-difference methodology
    (backends.chained_device_times_us): the sweep still emits the
    reference CSV shape with non-negative µs values and derived lines.
    On CPU the helper clamps the chain length, so this stays fast while
    tracing the exact code path the TPU corpus capture runs."""
    out = tmp_path / "results.test.tpu"
    rc = bench_mod.main([
        "--sizes-mb", "0.0625", "--workers", "1", "--iters", "2",
        "--modes", "ecb,ctr,cbc,rc4", "--timing", "device",
        "--out", str(out),
    ])
    assert rc == 0
    lines = out.read_text().splitlines()
    for prefix in ("TPU AES-256 ECB,", "TPU AES-256 CTR,",
                   "TPU AES-256 CBC,"):
        rows = [l for l in lines if l.startswith(prefix)]
        assert len(rows) == 1, prefix
        fields = [f for f in rows[0].split(",") if f.strip()]
        assert len(fields) == 3 + 2
        assert all(int(f) >= 0 for f in fields[3:])
    # RC4's XOR row (the line after the keygen line) is chained too.
    assert any(l.startswith("RC4, 65536, 1") for l in lines)
    assert sum(1 for l in lines if l.startswith("# derived: ")) >= 3


def test_bench_rejects_unknown_mode():
    with pytest.raises(ValueError):
        bench_mod.main(["--sizes-mb", "0.001", "--modes", "rot13", "--iters", "1"])


@pytest.mark.slow
def test_bench_batch_modes(tmp_path):
    """cbc-batch / rc4-batch sweep rows: multi-stream sequence parallelism
    driven from the CLI, with worker-count invariance checked in-run."""
    out = tmp_path / "results.test.tpu"
    rc = bench_mod.main([
        "--sizes-mb", "0.0625", "--workers", "1,2", "--iters", "2",
        "--modes", "cbc-batch,rc4-batch", "--streams", "4",
        "--out", str(out),
    ])
    assert rc == 0
    lines = out.read_text().splitlines()
    cbc_rows = [l for l in lines if l.startswith("TPU AES-256 CBC-BATCHx4")]
    assert len(cbc_rows) == 2
    for row in cbc_rows:
        fields = [f for f in row.split(",") if f.strip()]
        assert fields[1].strip() == "65536"
        assert int(fields[2]) in (1, 2)
        assert len(fields) == 3 + 2
    assert any(l.startswith("RC4-KEYGEN-BATCHx4, 65536, 2") for l in lines)
    assert any(l.startswith("Generated 4 key schedules in") for l in lines)
    assert "CBC-batch shard invariance [1, 2]: passed" in lines
    assert "RC4-batch shard invariance [1, 2]: passed" in lines


def test_backend_chained_modes_reject_workers():
    """Both backends' cbc/cfb128 must reject workers > 1 loudly, not
    silently ignore them (a silently-ignored knob is how the reference's
    defect #1 class of bug survives)."""
    import jax.numpy as jnp

    from our_tree_tpu.harness.backends import make_backend

    backend = make_backend("tpu")
    ctx = backend.make_key(bytes(32))
    words = jnp.zeros(16, jnp.uint32)
    ivw = jnp.zeros(4, jnp.uint32)
    for fn in (backend.cbc, backend.cfb128):
        with pytest.raises(ValueError, match="sequential"):
            fn(ctx, words, ivw, 2)

    cback = make_backend("c")
    cctx = cback.make_key(bytes(32))
    data = np.zeros(32, np.uint8)
    iv = np.zeros(16, np.uint8)
    for fn in (cback.cbc, cback.cfb128):
        with pytest.raises(ValueError, match="sequential"):
            fn(cctx, data, iv, 2)


def test_bench_cbc_pins_workers(tmp_path):
    """A cbc sweep with a multi-worker list pins to workers=1 and announces
    it in the results, instead of dying or silently ignoring the flag."""
    out = tmp_path / "results.test.tpu"
    rc = bench_mod.main([
        "--sizes-mb", "0.0625", "--workers", "1,2", "--iters", "1",
        "--modes", "cbc", "--out", str(out),
    ])
    assert rc == 0
    lines = out.read_text().splitlines()
    assert any("sweeping workers=1 only" in l for l in lines)
    rows = [l for l in lines if l.startswith("TPU AES-256 CBC,")]
    assert len(rows) == 1 and rows[0].split(",")[2].strip() == "1"


def test_decrypt_cli_nist_roundtrip(capsys):
    key = "000102030405060708090a0b0c0d0e0f"
    assert decrypt_mod.main([key, "00112233445566778899aabbccddeeff",
                             "--encrypt"]) == 0
    assert capsys.readouterr().out.strip() == "69c4e0d86a7b0430d8cdb78070b4c55a"
    assert decrypt_mod.main([key, "69c4e0d86a7b0430d8cdb78070b4c55a"]) == 0
    assert capsys.readouterr().out.strip() == "00112233445566778899aabbccddeeff"


def test_decrypt_cli_cbc_ctr_match_context(capsys):
    rng = np.random.default_rng(5)
    key = rng.integers(0, 256, 16, np.uint8)
    iv = rng.integers(0, 256, 16, np.uint8)
    data = rng.integers(0, 256, 48, np.uint8)
    from our_tree_tpu.models.aes import AES, AES_ENCRYPT

    a = AES(key.tobytes())
    for mode in ("cbc", "ctr"):
        assert decrypt_mod.main([
            key.tobytes().hex(), data.tobytes().hex(),
            "--encrypt", "--mode", mode, "--iv", iv.tobytes().hex(),
        ]) == 0
        got = capsys.readouterr().out.strip()
        if mode == "cbc":
            expect, _ = a.crypt_cbc(AES_ENCRYPT, iv, data)
        else:
            expect, *_ = a.crypt_ctr(0, iv.copy(), np.zeros(16, np.uint8), data)
        assert got == expect.tobytes().hex()


@pytest.mark.slow
def test_decrypt_cli_cfb128_roundtrip_and_resume(capsys):
    """--mode cfb128: odd lengths are legal (byte-granular), decrypt inverts
    encrypt, and --iv-off resumes mid-block exactly like the context API's
    iv_off carry (reference aes.c:822-863)."""
    rng = np.random.default_rng(11)
    key = rng.integers(0, 256, 32, np.uint8)
    iv = rng.integers(0, 256, 16, np.uint8)
    data = rng.integers(0, 256, 53, np.uint8)  # odd, > 3 blocks
    from our_tree_tpu.models.aes import AES, AES_DECRYPT, AES_ENCRYPT

    a = AES(key.tobytes())
    expect, _, _ = a.crypt_cfb128(AES_ENCRYPT, 0, iv, data)
    assert decrypt_mod.main([
        key.tobytes().hex(), data.tobytes().hex(),
        "--encrypt", "--mode", "cfb128", "--iv", iv.tobytes().hex(),
    ]) == 0
    assert capsys.readouterr().out.strip() == expect.tobytes().hex()

    assert decrypt_mod.main([
        key.tobytes().hex(), expect.tobytes().hex(),
        "--mode", "cfb128", "--iv", iv.tobytes().hex(),
    ]) == 0
    assert capsys.readouterr().out.strip() == data.tobytes().hex()

    # Resume: crypt the first 5 bytes through the context API, then hand the
    # carried (iv_off, iv register) to the CLI for the tail.
    head, off, reg = a.crypt_cfb128(AES_DECRYPT, 0, iv, expect[:5])
    assert off == 5
    assert decrypt_mod.main([
        key.tobytes().hex(), expect[5:].tobytes().hex(),
        "--mode", "cfb128", "--iv", reg.tobytes().hex(),
        "--iv-off", str(off),
    ]) == 0
    tail = capsys.readouterr().out.strip()
    assert head.tobytes().hex() + tail == data.tobytes().hex()


def test_decrypt_cli_rejects_bad_input(capsys):
    assert decrypt_mod.main(["zz", "00" * 16]) == 1
    assert decrypt_mod.main(["00" * 5, "00" * 16]) == 1
    assert decrypt_mod.main(["00" * 16, "00" * 15]) == 1
    assert decrypt_mod.main(["00" * 16, "00" * 16, "--mode", "cfb128",
                             "--iv-off", "16"]) == 1
    assert decrypt_mod.main(["00" * 16, "00" * 16, "--mode", "ctr",
                             "--iv-off", "5"]) == 1


def test_bench_c_backend_cli(tmp_path):
    """The full sweep through the native C backend (--backend c)."""
    out = tmp_path / "results.test.c"
    rc = bench_mod.main([
        "--backend", "c", "--sizes-mb", "0.0625", "--workers", "1,2",
        "--iters", "2", "--modes", "ecb,ctr,rc4", "--out", str(out),
    ])
    assert rc == 0
    lines = out.read_text().splitlines()
    assert any(l.startswith("C AES-256 ECB, 65536, 2") for l in lines)
    assert "Shard invariance [1, 2]: passed" in lines
    assert "ARC4 test #3: passed" in lines


@pytest.mark.slow
def test_ctr_stream_chunked_parity():
    """backends.TpuBackend.ctr_stream: chunked staging with counter carry
    across seams must be byte-identical to the one-shot context API, for
    sharded and unsharded worker counts and a non-block-aligned tail."""
    import numpy as np

    from our_tree_tpu.harness.backends import make_backend
    from our_tree_tpu.harness.bench import NONCE
    from our_tree_tpu.models.aes import AES

    rng = np.random.default_rng(21)
    key = rng.integers(0, 256, 32, np.uint8).tobytes()
    msg = rng.integers(0, 256, 16 * 300 + 11, np.uint8)
    want, *_ = AES(key).crypt_ctr(0, NONCE.copy(), np.zeros(16, np.uint8), msg)

    backend = make_backend("tpu")
    ctx = backend.make_key(key)
    for workers in (1, 4):
        got = backend.ctr_stream(ctx, msg, NONCE, chunk_bytes=16 * 64,
                                 workers=workers)
        np.testing.assert_array_equal(got, want)


@pytest.mark.slow
def test_ctr_stream_pallas_engine_parity():
    """ctr_stream driven through a Pallas engine — the one engine x path
    combination nothing covered before round 4 (VERDICT r3 weak #6): the
    chunk-seam counter carry must hold when each chunk's keystream comes
    from the fused pallas-dense CTR kernel (interpreter here, Mosaic on
    hardware), sharded and unsharded, with a non-block-aligned tail."""
    import numpy as np

    from our_tree_tpu.harness.backends import make_backend
    from our_tree_tpu.harness.bench import NONCE
    from our_tree_tpu.models.aes import AES

    rng = np.random.default_rng(22)
    key = rng.integers(0, 256, 16, np.uint8).tobytes()
    msg = rng.integers(0, 256, 16 * 96 + 7, np.uint8)
    want, *_ = AES(key, engine="jnp").crypt_ctr(
        0, NONCE.copy(), np.zeros(16, np.uint8), msg)

    backend = make_backend("tpu", "pallas-dense")
    ctx = backend.make_key(key)
    for workers in (1, 2):
        got = backend.ctr_stream(ctx, msg, NONCE, chunk_bytes=16 * 32,
                                 workers=workers)
        np.testing.assert_array_equal(got, want)
