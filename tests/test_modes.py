"""Mode tests: SP800-38A known answers + streaming/resume semantics.

Mode semantics under test are those of the reference (aes-modes/aes.c):
CBC at aes.c:757-816, CFB128 at aes.c:822-863, CTR (post-increment BE
counter) at aes.c:869-901. Bit-parity against the compiled reference itself
is in test_parity.py; these are the public NIST vectors.
"""

import numpy as np
import pytest

from our_tree_tpu.models.aes import AES, AES_DECRYPT, AES_ENCRYPT

KEY128 = bytes.fromhex("2b7e151628aed2a6abf7158809cf4f3c")
KEY192 = bytes.fromhex("8e73b0f7da0e6452c810f32b809079e562f8ead2522c6b7b")
KEY256 = bytes.fromhex("603deb1015ca71be2b73aef0857d77811f352c073b6108d72d9810a30914dff4")
IV = bytes.fromhex("000102030405060708090a0b0c0d0e0f")
CTR0 = bytes.fromhex("f0f1f2f3f4f5f6f7f8f9fafbfcfdfeff")
PT4 = bytes.fromhex(
    "6bc1bee22e409f96e93d7e117393172a"
    "ae2d8a571e03ac9c9eb76fac45af8e51"
    "30c81c46a35ce411e5fbc1191a0a52ef"
    "f69f2445df4f9b17ad2b417be66c3710"
)

ECB_CT = {
    128: "3ad77bb40d7a3660a89ecaf32466ef97f5d3d58503b9699de785895a96fdbaaf"
    "43b1cd7f598ece23881b00e3ed0306887b0c785e27e8ad3f8223207104725dd4",
    192: "bd334f1d6e45f25ff712a214571fa5cc974104846d0ad3ad7734ecb3ecee4eef"
    "ef7afd2270e2e60adce0ba2face6444e9a4b41ba738d6c72fb16691603c18e0e",
    256: "f3eed1bdb5d2a03c064b5a7e3db181f8591ccb10d410ed26dc5ba74a31362870"
    "b6ed21b99ca6f4f9f153e7b1beafed1d23304b7a39f9f3ff067d8d8f9e24ecc7",
}
CBC_CT = {
    128: "7649abac8119b246cee98e9b12e9197d5086cb9b507219ee95db113a917678b2"
    "73bed6b8e3c1743b7116e69e222295163ff1caa1681fac09120eca307586e1a7",
    192: "4f021db243bc633d7178183a9fa071e8b4d9ada9ad7dedf4e5e738763f69145a"
    "571b242012fb7ae07fa9baac3df102e008b0e27988598881d920a9e64f5615cd",
    256: "f58c4c04d6e5f1ba779eabfb5f7bfbd69cfc4e967edb808d679f777bc6702c7d"
    "39f23369a9d9bacfa530e26304231461b2eb05e2c39be9fcda6c19078c6a9d1b",
}
CFB_CT = {
    128: "3b3fd92eb72dad20333449f8e83cfb4ac8a64537a0b3a93fcde3cdad9f1ce58b"
    "26751f67a3cbb140b1808cf187a4f4dfc04b05357c5d1c0eeac4c66f9ff7f2e6",
    192: "cdc80d6fddf18cab34c25909c99a417467ce7f7f81173621961a2b70171d3d7a"
    "2e1e8a1dd59b88b1c8e60fed1efac4c9c05f9f9ca9834fa042ae8fba584b09ff",
    256: "dc7e84bfda79164b7ecd8486985d386039ffed143b28b1c832113c6331e5407b"
    "df10132415e54b92a13ed0a8267ae2f975a385741ab9cef82031623d55b1e471",
}
CTR_CT = {
    128: "874d6191b620e3261bef6864990db6ce9806f66b7970fdff8617187bb9fffdff"
    "5ae4df3edbd5d35e5b4f09020db03eab1e031dda2fbe03d1792170a0f3009cee",
    192: "1abc932417521ca24f2b0459fe7e6e0b090339ec0aa6faefd5ccc2c6f4ce8e94"
    "1e36b26bd1ebc670d1bd1d665620abf74f78a7f6d29809585a97daec58c6b050",
    256: "601ec313775789a5b7a7f504bbf3d228f443e3ca4d62b59aca84e990cacaf5c5"
    "2b0930daa23de94ce87017ba2d84988ddfc9c58db67aada613c2dd08457941a6",
}
KEYS = {128: KEY128, 192: KEY192, 256: KEY256}


@pytest.mark.parametrize("bits", [128, pytest.param(192, marks=pytest.mark.slow), pytest.param(256, marks=pytest.mark.slow)])
def test_sp800_38a_ecb(bits):
    a = AES(KEYS[bits])
    assert a.crypt_ecb(AES_ENCRYPT, PT4).tobytes().hex() == ECB_CT[bits]
    assert a.crypt_ecb(AES_DECRYPT, bytes.fromhex(ECB_CT[bits])).tobytes() == PT4


@pytest.mark.parametrize("bits", [128, pytest.param(192, marks=pytest.mark.slow), pytest.param(256, marks=pytest.mark.slow)])
def test_sp800_38a_cbc(bits):
    a = AES(KEYS[bits])
    ct, iv_out = a.crypt_cbc(AES_ENCRYPT, np.frombuffer(IV, np.uint8), PT4)
    assert ct.tobytes().hex() == CBC_CT[bits]
    assert iv_out.tobytes() == ct.tobytes()[-16:]
    pt, div_out = a.crypt_cbc(AES_DECRYPT, np.frombuffer(IV, np.uint8), ct)
    assert pt.tobytes() == PT4
    assert div_out.tobytes() == ct.tobytes()[-16:]


@pytest.mark.parametrize("bits", [128, pytest.param(192, marks=pytest.mark.slow), pytest.param(256, marks=pytest.mark.slow)])
def test_sp800_38a_cfb128(bits):
    a = AES(KEYS[bits])
    ct, off, iv_out = a.crypt_cfb128(AES_ENCRYPT, 0, np.frombuffer(IV, np.uint8), PT4)
    assert ct.tobytes().hex() == CFB_CT[bits]
    assert off == 0
    pt, _, _ = a.crypt_cfb128(AES_DECRYPT, 0, np.frombuffer(IV, np.uint8), ct)
    assert pt.tobytes() == PT4


@pytest.mark.parametrize("bits", [128, pytest.param(192, marks=pytest.mark.slow), pytest.param(256, marks=pytest.mark.slow)])
def test_sp800_38a_ctr(bits):
    a = AES(KEYS[bits])
    sb = np.zeros(16, np.uint8)
    ct, off, _, _ = a.crypt_ctr(0, np.frombuffer(CTR0, np.uint8), sb, PT4)
    assert ct.tobytes().hex() == CTR_CT[bits]
    assert off == 0
    pt, _, _, _ = a.crypt_ctr(0, np.frombuffer(CTR0, np.uint8), sb, ct)
    assert pt.tobytes() == PT4


@pytest.mark.slow
def test_ctr_chunked_equals_oneshot():
    """Streaming resume: arbitrary chunking must be invisible in the output —
    the reference's nc_off/stream_block contract (aes.c:869-901)."""
    rng = np.random.default_rng(3)
    a = AES(KEY128)
    data = rng.integers(0, 256, 1000, dtype=np.uint8)
    sb = np.zeros(16, np.uint8)
    one, off1, nc1, sb1 = a.crypt_ctr(0, np.frombuffer(CTR0, np.uint8), sb, data)

    out = []
    off, nc, sbl = 0, np.frombuffer(CTR0, np.uint8), np.zeros(16, np.uint8)
    for lo, hi in [(0, 3), (3, 20), (20, 21), (21, 500), (500, 1000)]:
        o, off, nc, sbl = a.crypt_ctr(off, nc, sbl, data[lo:hi])
        out.append(o)
    assert np.concatenate(out).tobytes() == one.tobytes()
    assert off == off1 and nc.tobytes() == nc1.tobytes() and sbl.tobytes() == sb1.tobytes()


@pytest.mark.slow
def test_ctr_block_aligned_end_stream_block():
    """A CTR call that ends EXACTLY on a block boundary must still leave
    stream_block = E(last counter): the reference's byte loop regenerates
    it for every block (aes.c:876-884), so it is part of the bit-identical
    resume surface even though it is dead state while nc_off == 0. The
    bulk path's fused kernels never materialise the keystream, which hid
    this until the randomized fuzzer caught it (chunks [2501, 2283]:
    mid-block drain, then an aligned end)."""
    a = AES(KEY128)
    rng = np.random.default_rng(21)
    data = rng.integers(0, 256, 4784, dtype=np.uint8)  # the fuzzer's repro
    nc0 = np.frombuffer(CTR0, np.uint8)

    # One-shot, aligned end (4784 = 299 blocks exactly).
    out1, off1, nc1, sb1 = a.crypt_ctr(0, nc0.copy(), np.zeros(16, np.uint8),
                                       data)
    assert off1 == 0
    last_ctr = (int.from_bytes(nc1.tobytes(), "big") - 1) % (1 << 128)
    want_sb = a.crypt_ecb(AES_ENCRYPT, last_ctr.to_bytes(16, "big"))
    assert sb1.tobytes() == want_sb.tobytes()

    # Chunked with a mid-block seam, same aligned total: identical output
    # AND identical full resume state.
    out, off, nc, sb = [], 0, nc0.copy(), np.zeros(16, np.uint8)
    for lo, hi in [(0, 2501), (2501, 4784)]:
        o, off, nc, sb = a.crypt_ctr(off, nc, sb, data[lo:hi])
        out.append(o)
    assert np.concatenate(out).tobytes() == out1.tobytes()
    assert (off, nc.tobytes(), sb.tobytes()) == (off1, nc1.tobytes(),
                                                 sb1.tobytes())


@pytest.mark.slow
def test_cfb_chunked_equals_oneshot():
    rng = np.random.default_rng(4)
    a = AES(KEY256)
    data = rng.integers(0, 256, 777, dtype=np.uint8)
    one, off1, iv1 = a.crypt_cfb128(AES_ENCRYPT, 0, np.frombuffer(IV, np.uint8), data)
    out = []
    off, iv = 0, np.frombuffer(IV, np.uint8)
    for lo, hi in [(0, 5), (5, 16), (16, 160), (160, 161), (161, 777)]:
        o, off, iv = a.crypt_cfb128(AES_ENCRYPT, off, iv, data[lo:hi])
        out.append(o)
    assert np.concatenate(out).tobytes() == one.tobytes()
    assert off == off1 and iv.tobytes() == iv1.tobytes()


def test_ctr_counter_wraparound():
    """Carry must ripple through all 16 counter bytes (aes.c:879-884)."""
    a = AES(KEY128)
    nonce = np.frombuffer(b"\xff" * 15 + b"\xfe", np.uint8)
    data = np.zeros(16 * 5, np.uint8)
    sb = np.zeros(16, np.uint8)
    one, _, nc, _ = a.crypt_ctr(0, nonce, sb, data)
    # block keystreams must be E(...fe), E(...ff), E(0), E(1), E(2)
    ks = [a.crypt_ecb(AES_ENCRYPT, int(v).to_bytes(16, "big")) for v in
          [(1 << 128) - 2, (1 << 128) - 1, 0, 1, 2]]
    assert one.tobytes() == b"".join(k.tobytes() for k in ks)
    assert nc.tobytes() == (3).to_bytes(16, "big")


def test_cbc_chaining_vs_blockwise():
    """CBC ciphertext block i depends on all prior blocks; verify scan
    equals the sequential definition."""
    rng = np.random.default_rng(5)
    a = AES(KEY192)
    data = rng.integers(0, 256, 16 * 9, dtype=np.uint8)
    ct, _ = a.crypt_cbc(AES_ENCRYPT, np.frombuffer(IV, np.uint8), data)
    iv = np.frombuffer(IV, np.uint8)
    expect = []
    for i in range(9):
        blk = np.bitwise_xor(data[16 * i : 16 * i + 16], iv)
        iv = a.crypt_ecb(AES_ENCRYPT, blk)
        expect.append(iv)
    assert ct.tobytes() == np.concatenate(expect).tobytes()


@pytest.mark.slow
def test_mode_words_flat_stream_parity():
    """Every words-level mode entry point accepts a flat (4N,) u32 stream
    (the dense TPU boundary layout, models/aes.py:_as_block_words) and must
    match the (N, 4) form — including CBC/CFB, which the benchmark harness
    feeds flat-staged words (harness/backends.py:stage_words)."""
    import jax.numpy as jnp

    from our_tree_tpu.models import aes as aes_mod
    from our_tree_tpu.ops.keyschedule import expand_key_dec, expand_key_enc
    from our_tree_tpu.utils import packing

    rng = np.random.default_rng(23)
    key = bytes(range(16))
    nr, rk = expand_key_enc(key)
    _, rkd = expand_key_dec(key)
    rk, rkd = jnp.asarray(rk), jnp.asarray(rkd)
    iv = jnp.asarray(packing.np_bytes_to_words(
        np.frombuffer(bytes(range(16, 32)), np.uint8)))
    data = rng.integers(0, 256, 16 * 19, np.uint8)
    w2 = jnp.asarray(packing.np_bytes_to_words(data).reshape(-1, 4))
    wf = w2.reshape(-1)

    o2, iv2 = aes_mod.cbc_encrypt_words(w2, iv, rk, nr)
    of, ivf = aes_mod.cbc_encrypt_words(wf, iv, rk, nr)
    np.testing.assert_array_equal(np.asarray(of).reshape(-1, 4), np.asarray(o2))
    np.testing.assert_array_equal(np.asarray(ivf), np.asarray(iv2))

    d2, l2 = aes_mod.cbc_decrypt_words(o2, iv, rkd, nr)
    df, lf = aes_mod.cbc_decrypt_words(of, iv, rkd, nr)
    np.testing.assert_array_equal(np.asarray(df).reshape(-1, 4), np.asarray(d2))
    np.testing.assert_array_equal(np.asarray(lf), np.asarray(l2))

    o2, iv2 = aes_mod.cfb128_encrypt_words(w2, iv, rk, nr)
    of, ivf = aes_mod.cfb128_encrypt_words(wf, iv, rk, nr)
    np.testing.assert_array_equal(np.asarray(of).reshape(-1, 4), np.asarray(o2))

    d2, l2 = aes_mod.cfb128_decrypt_words(o2, iv, rk, nr)
    df, lf = aes_mod.cfb128_decrypt_words(of, iv, rk, nr)
    np.testing.assert_array_equal(np.asarray(df).reshape(-1, 4), np.asarray(d2))
    np.testing.assert_array_equal(np.asarray(lf), np.asarray(l2))

    e2 = aes_mod.ecb_encrypt_words(w2, rk, nr)
    ef = aes_mod.ecb_encrypt_words(wf, rk, nr)
    np.testing.assert_array_equal(np.asarray(ef).reshape(-1, 4), np.asarray(e2))
