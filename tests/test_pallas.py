"""Pallas AES kernel (interpreter mode on CPU) vs the T-table core.

One shape only: interpret-mode compiles of the unrolled final round cost
tens of seconds on this class of host, so the test drives a single batch
through both directions and both a 128- and 256-bit key, which covers the
tile-padding path (n=33 -> one 32-block lane group + pad), the fori_loop
round body, and the folded-schedule decrypt ordering.

This module is the CORE third of the Pallas suite; the multi-grid engine
gauntlets live in test_pallas_grid.py and the many-engine mode/long-key
gauntlets in test_pallas_modes.py (VERDICT r3 weak #4/#8: the former
single module outgrew per-module cache clearing and needed a per-test
`jax.clear_caches()` hammer that recompiled shared references every test;
the three-way split re-bounds XLA-CPU compiler state at module granularity
with no hammer and no lost coverage).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from our_tree_tpu.models import aes as aes_mod
from our_tree_tpu.ops.keyschedule import expand_key_dec, expand_key_enc


@pytest.mark.parametrize("bits", [128, 192, 256])
@pytest.mark.slow
def test_pallas_matches_ttable(bits):
    rng = np.random.default_rng(bits)
    key = rng.integers(0, 256, bits // 8, dtype=np.uint8).tobytes()
    nr, rk = expand_key_enc(key)
    _, rkd = expand_key_dec(key)
    rk, rkd = jnp.asarray(rk), jnp.asarray(rkd)
    w = jnp.asarray(rng.integers(0, 2**32, (33, 4)).astype(np.uint32))
    np.testing.assert_array_equal(
        np.asarray(aes_mod.ecb_encrypt_words(w, rk, nr, "pallas")),
        np.asarray(aes_mod.ecb_encrypt_words(w, rk, nr, "jnp")),
    )
    np.testing.assert_array_equal(
        np.asarray(aes_mod.ecb_decrypt_words(w, rkd, nr, "pallas")),
        np.asarray(aes_mod.ecb_decrypt_words(w, rkd, nr, "jnp")),
    )


@pytest.mark.slow
def test_pallas_mc_roll_lowering(monkeypatch):
    """OT_PALLAS_MC=roll (reshape + sublane-roll MixColumns inside kernels)
    must be byte-identical to the T-table core — pinned in interpreter mode
    so hardware tuning sweeps only measure speed, never correctness."""
    from our_tree_tpu.ops import pallas_aes

    monkeypatch.setattr(pallas_aes, "MC_LOWERING", "roll")
    rng = np.random.default_rng(77)
    nr, rk = expand_key_enc(bytes(range(16)))
    rk = jnp.asarray(rk)
    # 65 blocks -> a (8, 16, 3) plane shape no other test compiles, so the
    # jit cache (keyed on shapes/statics, blind to the module-global
    # lowering knob) cannot hand back a slice-stack compilation.
    w = jnp.asarray(rng.integers(0, 2**32, (65, 4)).astype(np.uint32))
    got = np.asarray(aes_mod.ecb_encrypt_words(w, rk, nr, "pallas"))
    want = np.asarray(aes_mod.ecb_encrypt_words(w, rk, nr, "jnp"))
    np.testing.assert_array_equal(got, want)


def test_headline_engines_small_fast(monkeypatch):
    """FAST-tier correctness representative for every kernel engine
    (pallas, pallas-gt, pallas-dense — all three boundary layouts): tiny
    shapes (33 blocks -> the pad-to-32 path, one grid step) through ECB
    both directions and the counter-synthesising CTR, vs the T-table
    core. Exists so a kernel regression fails the DEFAULT test run — the
    full-size multi-grid gauntlets stay in the gate tier. The -bp
    variants differ only by the S-box circuit, which test_bitslice.py
    pins exhaustively at the circuit level in the fast tier."""
    from our_tree_tpu.ops import pallas_aes
    from our_tree_tpu.utils import packing

    monkeypatch.setattr(pallas_aes, "TILE", 128)
    rng = np.random.default_rng(53)
    nr, rk = expand_key_enc(bytes(range(16)))
    rk = jnp.asarray(rk)
    _, rk_dec = expand_key_dec(bytes(range(16)))
    rk_dec = jnp.asarray(rk_dec)
    nonce = np.frombuffer(
        bytes.fromhex("000102030405060708ffffffffffffff"), np.uint8)
    ctr_be = jnp.asarray(packing.np_bytes_to_words(nonce).byteswap())
    w = jnp.asarray(rng.integers(0, 2**32, (33, 4)).astype(np.uint32))
    want_e = np.asarray(aes_mod.ecb_encrypt_words(w, rk, nr, "jnp"))
    want_c = np.asarray(aes_mod.ctr_crypt_words(w, ctr_be, rk, nr, "jnp"))
    for engine in ("pallas", "pallas-gt", "pallas-dense"):
        got = np.asarray(aes_mod.ecb_encrypt_words(w, rk, nr, engine))
        np.testing.assert_array_equal(got, want_e, err_msg=engine)
        back = np.asarray(aes_mod.ecb_decrypt_words(
            jnp.asarray(got), rk_dec, nr, engine))
        np.testing.assert_array_equal(back, np.asarray(w), err_msg=engine)
        got = np.asarray(aes_mod.ctr_crypt_words(w, ctr_be, rk, nr, engine))
        np.testing.assert_array_equal(got, want_c, err_msg=engine)


def test_pallas_fused_ctr_counter_carry():
    """Fused CTR kernel (ops/pallas_aes.py:ctr_crypt_words) across a 32-bit
    counter-word overflow: the low BE word wraps mid-batch, so the carry
    ripple (reference aes-modes/aes.c:879-884 semantics) must agree with the
    layered keystream path bit-for-bit."""
    from our_tree_tpu.utils import packing

    rng = np.random.default_rng(3)
    nr, rk = expand_key_enc(bytes(range(16)))
    rk = jnp.asarray(rk)
    # Low word = 2^32 - 5: wraps after 5 of the 40 blocks.
    nonce = np.frombuffer(
        bytes(range(12)) + (2**32 - 5).to_bytes(4, "big"), dtype=np.uint8
    )
    ctr_be = jnp.asarray(packing.np_bytes_to_words(nonce).byteswap())
    w = jnp.asarray(rng.integers(0, 2**32, (40, 4)).astype(np.uint32))
    np.testing.assert_array_equal(
        np.asarray(aes_mod.ctr_crypt_words(w, ctr_be, rk, nr, "pallas")),
        np.asarray(aes_mod.ctr_crypt_words(w, ctr_be, rk, nr, "jnp")),
    )


@pytest.mark.slow
def test_pallas_ctr_gen_matches_materialised():
    """The counter-synthesising kernel (ctr_crypt_words_gen — in-kernel
    bitsliced 128-bit ripple add) vs the counter-materialising fused kernel
    (ctr_crypt_words) vs the layered path, across a multi-word carry: the
    low TWO BE words are at all-ones, so the mid-batch wrap ripples through
    64 bits — every adder lane of the in-kernel generator past word 3 is
    exercised."""
    from our_tree_tpu.models.aes import ctr_le_blocks
    from our_tree_tpu.ops import pallas_aes
    from our_tree_tpu.utils import packing

    rng = np.random.default_rng(11)
    nr, rk = expand_key_enc(bytes(range(23, 39)))
    rk = jnp.asarray(rk)
    nonce = np.frombuffer(
        bytes(range(8)) + b"\xff" * 7 + b"\xf9", dtype=np.uint8
    )  # wraps 64 bits after 7 of the 40 blocks
    ctr_be = jnp.asarray(packing.np_bytes_to_words(nonce).byteswap())
    w = jnp.asarray(rng.integers(0, 2**32, (40, 4)).astype(np.uint32))
    want = np.asarray(aes_mod.ctr_crypt_words(w, ctr_be, rk, nr, "jnp"))
    got_gen = np.asarray(pallas_aes.ctr_crypt_words_gen(w, ctr_be, rk, nr))
    idx = jnp.arange(40, dtype=jnp.uint32)
    got_mat = np.asarray(
        pallas_aes.ctr_crypt_words(w, ctr_le_blocks(ctr_be, idx), rk, nr)
    )
    np.testing.assert_array_equal(got_gen, want)
    np.testing.assert_array_equal(got_mat, want)


def test_pallas_multikey_scattered_ctr_parity():
    """The multi-key masked-select kernel (ops/pallas_aes.py:
    ctr_scattered_multikey_dense[_bp]) vs the jnp multi-key core (itself
    NIST-KAT-pinned in test_serve): K=3 interleaved tenants, n=34 so the
    lane-pad path runs (one 32-block lane group + 2 padded), every block's
    keystream reconstructed through slot_lane_masks + the kp_eff OR-select
    — a bit-ordering slip in the mask build or the masked select would
    corrupt exactly the cross-tenant boundary the serve path rides on."""
    from our_tree_tpu.ops import pallas_aes
    from our_tree_tpu.utils import packing

    rng = np.random.default_rng(41)
    keys = [bytes([i]) * 16 for i in (1, 2, 3)]
    slots = np.asarray((([0, 1, 0, 2, 2, 0, 1, 0, 2, 1, 0] * 3) + [2]),
                       dtype=np.uint32)  # 34 blocks, arbitrary interleave
    n = slots.size
    nr = None
    rks = []
    for k in keys:
        nr, rk = expand_key_enc(k)
        rks.append(np.asarray(rk, np.uint32))
    rks = np.stack(rks)
    ctr = np.empty((n, 4), np.uint32)
    for s in range(len(keys)):
        mine = np.flatnonzero(slots == s)
        ctr[mine] = packing.np_ctr_le_blocks(
            bytes([s]) * 16, np.arange(mine.size, dtype=np.uint32))
    words = packing.np_bytes_to_words(
        rng.integers(0, 256, 16 * n, dtype=np.uint8))
    want = np.asarray(aes_mod.ctr_crypt_words_scattered_multikey(
        words, ctr.reshape(-1), rks, slots, nr, "jnp"))
    w2 = jnp.asarray(words.reshape(-1, 4))
    c2 = jnp.asarray(ctr)
    for fn in (pallas_aes.ctr_scattered_multikey_dense,
               pallas_aes.ctr_scattered_multikey_dense_bp):
        got = np.asarray(fn(w2, c2, jnp.asarray(rks), jnp.asarray(slots),
                            nr))
        np.testing.assert_array_equal(got.reshape(-1), want.reshape(-1))
