"""Pallas AES kernel (interpreter mode on CPU) vs the T-table core.

One shape only: interpret-mode compiles of the unrolled final round cost
tens of seconds on this class of host, so the test drives a single batch
through both directions and both a 128- and 256-bit key, which covers the
tile-padding path (n=33 -> one 32-block lane group + pad), the fori_loop
round body, and the folded-schedule decrypt ordering.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from our_tree_tpu.models import aes as aes_mod
from our_tree_tpu.ops.keyschedule import expand_key_dec, expand_key_enc


@pytest.fixture(autouse=True)
def _clear_caches_per_test():
    """Interpreter-mode Pallas tests are the heaviest compilations in the
    suite; with the round-3 engine-matrix additions the per-MODULE cache
    clearing (tests/conftest.py) stopped bounding XLA-CPU's accumulated
    compiler state — the gate run segfaulted in backend_compile partway
    through this module (the crash class conftest documents). Per-test
    clearing here keeps the footprint bounded; these tests compile fresh
    shapes each time anyway, so nothing useful is evicted."""
    yield
    jax.clear_caches()


@pytest.mark.parametrize("bits", [128, 192, 256])
@pytest.mark.slow
def test_pallas_matches_ttable(bits):
    rng = np.random.default_rng(bits)
    key = rng.integers(0, 256, bits // 8, dtype=np.uint8).tobytes()
    nr, rk = expand_key_enc(key)
    _, rkd = expand_key_dec(key)
    rk, rkd = jnp.asarray(rk), jnp.asarray(rkd)
    w = jnp.asarray(rng.integers(0, 2**32, (33, 4)).astype(np.uint32))
    np.testing.assert_array_equal(
        np.asarray(aes_mod.ecb_encrypt_words(w, rk, nr, "pallas")),
        np.asarray(aes_mod.ecb_encrypt_words(w, rk, nr, "jnp")),
    )
    np.testing.assert_array_equal(
        np.asarray(aes_mod.ecb_decrypt_words(w, rkd, nr, "pallas")),
        np.asarray(aes_mod.ecb_decrypt_words(w, rkd, nr, "jnp")),
    )


@pytest.mark.slow
def test_pallas_mc_roll_lowering(monkeypatch):
    """OT_PALLAS_MC=roll (reshape + sublane-roll MixColumns inside kernels)
    must be byte-identical to the T-table core — pinned in interpreter mode
    so hardware tuning sweeps only measure speed, never correctness."""
    from our_tree_tpu.ops import pallas_aes

    monkeypatch.setattr(pallas_aes, "MC_LOWERING", "roll")
    rng = np.random.default_rng(77)
    nr, rk = expand_key_enc(bytes(range(16)))
    rk = jnp.asarray(rk)
    # 65 blocks -> a (8, 16, 3) plane shape no other test compiles, so the
    # jit cache (keyed on shapes/statics, blind to the module-global
    # lowering knob) cannot hand back a slice-stack compilation.
    w = jnp.asarray(rng.integers(0, 2**32, (65, 4)).astype(np.uint32))
    got = np.asarray(aes_mod.ecb_encrypt_words(w, rk, nr, "pallas"))
    want = np.asarray(aes_mod.ecb_encrypt_words(w, rk, nr, "jnp"))
    np.testing.assert_array_equal(got, want)


def test_headline_engines_small_fast(monkeypatch):
    """FAST-tier correctness representative for every kernel engine
    (pallas, pallas-gt, pallas-dense — all three boundary layouts): tiny
    shapes (33 blocks -> the pad-to-32 path, one grid step) through ECB
    both directions and the counter-synthesising CTR, vs the T-table
    core. Exists so a kernel regression fails the DEFAULT test run — the
    full-size multi-grid gauntlets stay in the gate tier. The -bp
    variants differ only by the S-box circuit, which test_bitslice.py
    pins exhaustively at the circuit level in the fast tier."""
    from our_tree_tpu.ops import pallas_aes
    from our_tree_tpu.utils import packing

    monkeypatch.setattr(pallas_aes, "TILE", 128)
    rng = np.random.default_rng(53)
    nr, rk = expand_key_enc(bytes(range(16)))
    rk = jnp.asarray(rk)
    _, rk_dec = expand_key_dec(bytes(range(16)))
    rk_dec = jnp.asarray(rk_dec)
    nonce = np.frombuffer(
        bytes.fromhex("000102030405060708ffffffffffffff"), np.uint8)
    ctr_be = jnp.asarray(packing.np_bytes_to_words(nonce).byteswap())
    w = jnp.asarray(rng.integers(0, 2**32, (33, 4)).astype(np.uint32))
    want_e = np.asarray(aes_mod.ecb_encrypt_words(w, rk, nr, "jnp"))
    want_c = np.asarray(aes_mod.ctr_crypt_words(w, ctr_be, rk, nr, "jnp"))
    for engine in ("pallas", "pallas-gt", "pallas-dense"):
        got = np.asarray(aes_mod.ecb_encrypt_words(w, rk, nr, engine))
        np.testing.assert_array_equal(got, want_e, err_msg=engine)
        back = np.asarray(aes_mod.ecb_decrypt_words(
            jnp.asarray(got), rk_dec, nr, engine))
        np.testing.assert_array_equal(back, np.asarray(w), err_msg=engine)
        got = np.asarray(aes_mod.ctr_crypt_words(w, ctr_be, rk, nr, engine))
        np.testing.assert_array_equal(got, want_c, err_msg=engine)


def test_pallas_fused_ctr_counter_carry():
    """Fused CTR kernel (ops/pallas_aes.py:ctr_crypt_words) across a 32-bit
    counter-word overflow: the low BE word wraps mid-batch, so the carry
    ripple (reference aes-modes/aes.c:879-884 semantics) must agree with the
    layered keystream path bit-for-bit."""
    from our_tree_tpu.utils import packing

    rng = np.random.default_rng(3)
    nr, rk = expand_key_enc(bytes(range(16)))
    rk = jnp.asarray(rk)
    # Low word = 2^32 - 5: wraps after 5 of the 40 blocks.
    nonce = np.frombuffer(
        bytes(range(12)) + (2**32 - 5).to_bytes(4, "big"), dtype=np.uint8
    )
    ctr_be = jnp.asarray(packing.np_bytes_to_words(nonce).byteswap())
    w = jnp.asarray(rng.integers(0, 2**32, (40, 4)).astype(np.uint32))
    np.testing.assert_array_equal(
        np.asarray(aes_mod.ctr_crypt_words(w, ctr_be, rk, nr, "pallas")),
        np.asarray(aes_mod.ctr_crypt_words(w, ctr_be, rk, nr, "jnp")),
    )


@pytest.mark.slow
def test_pallas_ctr_gen_matches_materialised():
    """The counter-synthesising kernel (ctr_crypt_words_gen — in-kernel
    bitsliced 128-bit ripple add) vs the counter-materialising fused kernel
    (ctr_crypt_words) vs the layered path, across a multi-word carry: the
    low TWO BE words are at all-ones, so the mid-batch wrap ripples through
    64 bits — every adder lane of the in-kernel generator past word 3 is
    exercised."""
    from our_tree_tpu.models.aes import ctr_le_blocks
    from our_tree_tpu.ops import pallas_aes
    from our_tree_tpu.utils import packing

    rng = np.random.default_rng(11)
    nr, rk = expand_key_enc(bytes(range(23, 39)))
    rk = jnp.asarray(rk)
    nonce = np.frombuffer(
        bytes(range(8)) + b"\xff" * 7 + b"\xf9", dtype=np.uint8
    )  # wraps 64 bits after 7 of the 40 blocks
    ctr_be = jnp.asarray(packing.np_bytes_to_words(nonce).byteswap())
    w = jnp.asarray(rng.integers(0, 2**32, (40, 4)).astype(np.uint32))
    want = np.asarray(aes_mod.ctr_crypt_words(w, ctr_be, rk, nr, "jnp"))
    got_gen = np.asarray(pallas_aes.ctr_crypt_words_gen(w, ctr_be, rk, nr))
    idx = jnp.arange(40, dtype=jnp.uint32)
    got_mat = np.asarray(
        pallas_aes.ctr_crypt_words(w, ctr_le_blocks(ctr_be, idx), rk, nr)
    )
    np.testing.assert_array_equal(got_gen, want)
    np.testing.assert_array_equal(got_mat, want)


@pytest.mark.slow
def test_pallas_ctr_gen_multi_grid_step(monkeypatch):
    """Counter synthesis across grid steps: with a 128-lane tile, 12288
    blocks give a 3-step grid, so the in-kernel block index j = 32*(g*tile
    + lane) + t must mix the program_id into the adder correctly for g > 0
    (a bug there is invisible to single-tile tests)."""
    from our_tree_tpu.ops import pallas_aes

    monkeypatch.setattr(pallas_aes, "TILE", 128)
    rng = np.random.default_rng(5)
    nr, rk = expand_key_enc(bytes(range(16)))
    rk = jnp.asarray(rk)
    from our_tree_tpu.utils import packing

    nonce = np.frombuffer(bytes(range(100, 116)), dtype=np.uint8)
    ctr_be = jnp.asarray(packing.np_bytes_to_words(nonce).byteswap())
    w = jnp.asarray(rng.integers(0, 2**32, (32 * 384, 4)).astype(np.uint32))
    got = np.asarray(pallas_aes.ctr_crypt_words_gen(w, ctr_be, rk, nr))
    want = np.asarray(aes_mod.ctr_crypt_words(w, ctr_be, rk, nr, "jnp"))
    np.testing.assert_array_equal(got, want)


@pytest.mark.slow
def test_ctr_flat_stream_equals_block_words():
    """ctr_crypt_words accepts a flat (4N,) u32 stream (the dense TPU
    boundary layout — a (N, 4) boundary array pads its minor dim to the
    128-lane tile) and must produce byte-identical output to the (N, 4)
    form on every engine."""
    from our_tree_tpu.utils import packing

    rng = np.random.default_rng(17)
    nr, rk = expand_key_enc(bytes(range(16)))
    rk = jnp.asarray(rk)
    nonce = np.frombuffer(bytes(range(50, 66)), dtype=np.uint8)
    ctr_be = jnp.asarray(packing.np_bytes_to_words(nonce).byteswap())
    data = rng.integers(0, 256, 16 * 77, np.uint8)
    w2 = jnp.asarray(packing.np_bytes_to_words(data).reshape(-1, 4))
    wf = jnp.asarray(packing.np_bytes_to_words(data))
    for engine in ("jnp", "bitslice", "pallas", "pallas-gt", "pallas-gt-bp",
                   "pallas-dense"):
        o2 = np.asarray(aes_mod.ctr_crypt_words(w2, ctr_be, rk, nr, engine))
        of = np.asarray(aes_mod.ctr_crypt_words(wf, ctr_be, rk, nr, engine))
        assert of.shape == (4 * 77,)
        np.testing.assert_array_equal(of.reshape(-1, 4), o2, err_msg=engine)


@pytest.mark.slow
def test_pallas_engine_ctr_context():
    """The pallas core through the CTR mode path and the AES context."""
    import numpy as np

    from our_tree_tpu.models.aes import AES

    data = np.random.default_rng(9).integers(0, 256, 16 * 40 + 7, np.uint8)
    nonce = np.arange(16, dtype=np.uint8)
    outs = {}
    for engine in ("jnp", "pallas", "pallas-gt", "pallas-gt-bp",
                   "pallas-dense"):
        a = AES(bytes(range(16)), engine=engine)
        outs[engine], *_ = a.crypt_ctr(0, nonce.copy(),
                                       np.zeros(16, np.uint8), data)
    for engine in ("pallas", "pallas-gt", "pallas-gt-bp", "pallas-dense"):
        np.testing.assert_array_equal(outs["jnp"], outs[engine],
                                      err_msg=engine)


@pytest.mark.parametrize("keybytes", [24, 32])
@pytest.mark.slow
def test_pallas_kernels_long_keys(keybytes, monkeypatch):
    """AES-192/256 (nr = 12/14) through both pallas engines: the kernels
    unroll rounds with nr as a static parameter, so the nr > 10 straight-
    line paths are distinct compiled code that AES-128-only tests never
    touch (cf. the reference CUDA kernels' Nr>10/Nr>12 guard blocks,
    aes-gpu/Source/AES.cu:342-365 — which no test there exercised either)."""
    from our_tree_tpu.ops import pallas_aes
    from our_tree_tpu.utils import packing

    monkeypatch.setattr(pallas_aes, "TILE", 128)
    rng = np.random.default_rng(41)
    key = bytes(range(keybytes))
    nr, rk = expand_key_enc(key)
    rk = jnp.asarray(rk)
    nonce = np.frombuffer(bytes(range(200, 216)), np.uint8)
    ctr_be = jnp.asarray(packing.np_bytes_to_words(nonce).byteswap())
    w = jnp.asarray(rng.integers(0, 2**32, (32 * 128, 4)).astype(np.uint32))
    want_ctr = np.asarray(aes_mod.ctr_crypt_words(w, ctr_be, rk, nr, "jnp"))
    want_ecb = np.asarray(aes_mod.ecb_encrypt_words(w, rk, nr, "jnp"))
    for engine in ("pallas", "pallas-gt", "pallas-gt-bp"):
        got = np.asarray(aes_mod.ctr_crypt_words(w, ctr_be, rk, nr, engine))
        np.testing.assert_array_equal(got, want_ctr, err_msg=f"ctr {engine}")
        got = np.asarray(aes_mod.ecb_encrypt_words(w, rk, nr, engine))
        np.testing.assert_array_equal(got, want_ecb, err_msg=f"ecb {engine}")


@pytest.mark.slow
def test_pallas_dense_engine_matches_jnp(monkeypatch):
    """Dense-boundary kernels ((128, W) layout, in-kernel ladder via
    bitslice.transpose32_dense) vs the T-table core: ECB both directions
    and counter-synthesising CTR (both S-box variants), 3-step grid, near-
    wraparound nonce — the same gauntlet as the grouped twin below, since
    the dense engine exists to replace it (VERDICT r2 #3)."""
    from our_tree_tpu.ops import pallas_aes
    from our_tree_tpu.utils import packing

    monkeypatch.setattr(pallas_aes, "TILE", 128)
    rng = np.random.default_rng(29)
    nr, rk = expand_key_enc(bytes(range(16)))
    rk = jnp.asarray(rk)
    _, rk_dec = expand_key_dec(bytes(range(16)))
    rk_dec = jnp.asarray(rk_dec)
    nonce = np.frombuffer(
        bytes.fromhex("00000000fffffffffffffffffffffff0"), np.uint8)
    ctr_be = jnp.asarray(packing.np_bytes_to_words(nonce).byteswap())
    w = jnp.asarray(rng.integers(0, 2**32, (32 * 384, 4)).astype(np.uint32))

    got = np.asarray(pallas_aes.encrypt_words_dense(w, rk, nr))
    want = np.asarray(aes_mod.ecb_encrypt_words(w, rk, nr, "jnp"))
    np.testing.assert_array_equal(got, want)
    back = np.asarray(
        pallas_aes.decrypt_words_dense(jnp.asarray(got), rk_dec, nr))
    np.testing.assert_array_equal(back, np.asarray(w))

    want = np.asarray(aes_mod.ctr_crypt_words(w, ctr_be, rk, nr, "jnp"))
    got = np.asarray(pallas_aes.ctr_crypt_words_dense(w, ctr_be, rk, nr))
    np.testing.assert_array_equal(got, want)
    got = np.asarray(pallas_aes.ctr_crypt_words_dense_bp(w, ctr_be, rk, nr))
    np.testing.assert_array_equal(got, want)


@pytest.mark.slow
def test_pallas_gt_engine_matches_jnp(monkeypatch):
    """Grouped-transpose kernels (in-kernel SWAR ladder) vs the T-table
    core: ECB both directions and counter-synthesising CTR, with a 3-step
    grid so the lane/program_id bookkeeping is exercised past tile 0."""
    from our_tree_tpu.ops import pallas_aes
    from our_tree_tpu.utils import packing

    monkeypatch.setattr(pallas_aes, "TILE", 128)
    rng = np.random.default_rng(23)
    nr, rk = expand_key_enc(bytes(range(16)))
    rk = jnp.asarray(rk)
    _, rk_dec = expand_key_dec(bytes(range(16)))
    rk_dec = jnp.asarray(rk_dec)
    # Near-wraparound nonce: the in-kernel ripple adder must carry across
    # words exactly like ctr_le_blocks.
    nonce = np.frombuffer(
        bytes.fromhex("00000000fffffffffffffffffffffff0"), np.uint8)
    ctr_be = jnp.asarray(packing.np_bytes_to_words(nonce).byteswap())
    w = jnp.asarray(rng.integers(0, 2**32, (32 * 384, 4)).astype(np.uint32))

    got = np.asarray(pallas_aes.encrypt_words_gt(w, rk, nr))
    want = np.asarray(aes_mod.ecb_encrypt_words(w, rk, nr, "jnp"))
    np.testing.assert_array_equal(got, want)
    back = np.asarray(pallas_aes.decrypt_words_gt(jnp.asarray(got), rk_dec, nr))
    np.testing.assert_array_equal(back, np.asarray(w))

    got = np.asarray(pallas_aes.ctr_crypt_words_gt(w, ctr_be, rk, nr))
    want = np.asarray(aes_mod.ctr_crypt_words(w, ctr_be, rk, nr, "jnp"))
    np.testing.assert_array_equal(got, want)
