"""ot-aead (our_tree_tpu/aead + the GF(2^128) half of ops/gf): AES-GCM
as a first-class workload.

Covers the three GF(2^128) multiply formulations pinned against each
other (bit-serial int reference, Shoup byte tables, the mul-by-H
128x128 bit matrix the traced kernel uses), the traced Horner GHASH vs
the int reference, the traced constant-time tag compare vs its host
twin, the inc32 counter materialiser (including the 2^32 wrap), the
NIST SP 800-38D KATs (tests/golden/gcm_kats.json) through the models
API with per-byte tamper rejection, the fuzz-parity satellite
(gcm_seal/gcm_open vs the pure-host numpy reference over random
lengths/AAD splits, empty AAD, non-block-aligned tails, non-96-bit
IVs), and the parallel CBC-decrypt seam (bitsliced multikey decrypt +
the scattered dispatch vs the models single-key path).
"""

import json
import pathlib

import numpy as np
import pytest

from our_tree_tpu.aead import gcm, ghash
from our_tree_tpu.models import TagMismatchError, aes, gcm_open, gcm_seal
from our_tree_tpu.ops import bitslice, gf
from our_tree_tpu.ops.keyschedule import (dec_schedule_from_enc,
                                          expand_key_dec, expand_key_enc)
from our_tree_tpu.utils import packing

GOLDEN = pathlib.Path(__file__).resolve().parent / "golden" / "gcm_kats.json"


def _kats():
    return json.loads(GOLDEN.read_text())["kats"]


# ---------------------------------------------------------------------------
# GF(2^128): the three multiply formulations agree.
# ---------------------------------------------------------------------------


def _rand128(rng) -> int:
    return int.from_bytes(rng.bytes(16), "big")


def test_gf128_mul_field_axioms():
    """Identity, commutativity, associativity, distributivity — on the
    bit-serial reference everything else is pinned against."""
    rng = np.random.default_rng(7)
    one = 1 << 127  # the polynomial "1" in the reflected bit order
    for _ in range(20):
        a, b, c = (_rand128(rng) for _ in range(3))
        assert gf.gf128_mul(a, one) == a
        assert gf.gf128_mul(a, b) == gf.gf128_mul(b, a)
        assert gf.gf128_mul(gf.gf128_mul(a, b), c) == \
            gf.gf128_mul(a, gf.gf128_mul(b, c))
        assert gf.gf128_mul(a ^ b, c) == \
            gf.gf128_mul(a, c) ^ gf.gf128_mul(b, c)


def test_gf128_table_and_matrix_match_reference():
    rng = np.random.default_rng(8)
    for _ in range(5):
        h = _rand128(rng)
        tables = gf.gf128_tables(h)
        m = gf.gf128_mul_matrix_words(h)
        for _ in range(10):
            x = _rand128(rng)
            want = gf.gf128_mul(x, h)
            assert gf.gf128_mul_table(x, tables) == want
            assert gf.gf128_matvec_words(m, x) == want


def test_wordbit_basis_roundtrip():
    """The word-bit basis change is its own inverse and maps exactly
    one bit per index."""
    for j in (0, 1, 7, 8, 31, 32, 63, 64, 100, 127):
        z = gf.wordbit_to_int(j)
        bits = gf.int_to_wordbits(z)
        assert bits.sum() == 1 and bits[j] == 1
    rng = np.random.default_rng(9)
    z = _rand128(rng)
    back = 0
    for j, bit in enumerate(gf.int_to_wordbits(z)):
        if bit:
            back |= gf.wordbit_to_int(j)
    assert back == z


# ---------------------------------------------------------------------------
# GHASH: traced Horner kernel vs the int reference; tag compare twins.
# ---------------------------------------------------------------------------


def _words_of_bytes(b: bytes) -> np.ndarray:
    return packing.np_bytes_to_words(np.frombuffer(b, np.uint8))


def test_ghash_words_matches_int_reference():
    rng = np.random.default_rng(10)
    h = _rand128(rng)
    m = gf.gf128_mul_matrix_words(h)
    for nblocks in (1, 2, 5, 32):
        data = rng.bytes(16 * nblocks)
        y = np.asarray(gcm.ghash_words(_words_of_bytes(data), m))
        got = gf.block_to_int(packing.np_words_to_bytes(y).tobytes())
        assert got == ghash.ghash_int(h, data)


def test_ghash_words_y0_continuation():
    """Seeding y0 continues the Horner chain bit-exactly — the property
    the serve batcher's AAD-prefix injection relies on."""
    rng = np.random.default_rng(11)
    h = _rand128(rng)
    m = gf.gf128_mul_matrix_words(h)
    a, b = rng.bytes(32), rng.bytes(48)
    y_a = ghash.ghash_int(h, a)
    y0 = _words_of_bytes(gf.int_to_block(y_a))
    y = np.asarray(gcm.ghash_words(_words_of_bytes(b), m, y0))
    got = gf.block_to_int(packing.np_words_to_bytes(y).tobytes())
    assert got == ghash.ghash_int(h, a + b)


def test_tag_compare_twins_and_constant_shape():
    rng = np.random.default_rng(12)
    a = rng.bytes(16)
    for b in (a, a[:15] + bytes([a[15] ^ 1]), rng.bytes(16)):
        want = a == b
        assert ghash.np_tag_eq(a, b) is want
        got = bool(gcm.tag_eq_words(_words_of_bytes(a),
                                    _words_of_bytes(b)))
        assert got is want


def test_inc32_counter_blocks_wrap():
    """np_gcm_ctr_blocks implements inc32: ONLY the low 32 bits move,
    mod 2^32 — pinned across the wrap against the byte-loop reference."""
    j0 = bytes(range(12)) + b"\xff\xff\xff\xfe"  # low word near 2^32
    idx = np.arange(5, dtype=np.uint32)
    got = ghash.np_gcm_ctr_blocks(j0, idx)
    for k in range(5):
        want = _words_of_bytes(ghash.inc32(j0, k))
        assert np.array_equal(got[k], want), k


# ---------------------------------------------------------------------------
# NIST SP 800-38D KATs through the models API.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kat", _kats(), ids=lambda k: k["name"])
def test_gcm_kat_models_api(kat):
    key, iv = bytes.fromhex(kat["key"]), bytes.fromhex(kat["iv"])
    aad, pt = bytes.fromhex(kat["aad"]), bytes.fromhex(kat["pt"])
    ct, tag = gcm_seal(key, iv, aad, pt)
    assert ct.hex() == kat["ct"]
    assert tag.hex() == kat["tag"]
    assert gcm_open(key, iv, aad, ct, tag) == pt


@pytest.mark.parametrize("kat", [k for k in _kats() if k["ct"]],
                         ids=lambda k: k["name"])
def test_gcm_kat_tamper_rejected(kat):
    """One flipped bit anywhere — ciphertext, tag, or AAD — must refuse
    with TagMismatchError and never return partial plaintext."""
    key, iv = bytes.fromhex(kat["key"]), bytes.fromhex(kat["iv"])
    aad, ct = bytes.fromhex(kat["aad"]), bytes.fromhex(kat["ct"])
    tag = bytes.fromhex(kat["tag"])
    bad_ct = bytes([ct[0] ^ 1]) + ct[1:]
    with pytest.raises(TagMismatchError):
        gcm_open(key, iv, aad, bad_ct, tag)
    bad_tag = tag[:-1] + bytes([tag[-1] ^ 0x80])
    with pytest.raises(TagMismatchError):
        gcm_open(key, iv, aad, ct, bad_tag)
    if aad:
        bad_aad = bytes([aad[0] ^ 1]) + aad[1:]
        with pytest.raises(TagMismatchError):
            gcm_open(key, iv, bad_aad, ct, tag)


# ---------------------------------------------------------------------------
# Fuzz parity: traced seal/open vs the pure-host reference.
# ---------------------------------------------------------------------------


def test_gcm_fuzz_parity_against_host_reference():
    """The fuzz-parity satellite: random lengths (block-aligned, ragged
    tails, empty), AAD splits (empty, short, multi-block, ragged),
    96-bit and non-96-bit IVs, all three key sizes — traced gcm_seal/
    gcm_open must agree with np_gcm_seal/np_gcm_open byte-for-byte."""
    rng = np.random.default_rng(0xAEAD)
    pt_lens = [0, 1, 15, 16, 17, 48, 65, 256, 1000]
    aad_lens = [0, 1, 16, 20, 33]
    cases = 0
    for keylen in (16, 24, 32):
        key = rng.bytes(keylen)
        for ivlen in (12, 8, 16):
            iv = rng.bytes(ivlen)
            for _ in range(6):
                pt = rng.bytes(int(rng.choice(pt_lens)))
                aad = rng.bytes(int(rng.choice(aad_lens)))
                ct, tag = gcm_seal(key, iv, aad, pt)
                ct_ref, tag_ref = ghash.np_gcm_seal(key, iv, aad, pt)
                assert ct == ct_ref and tag == tag_ref, \
                    (keylen, ivlen, len(pt), len(aad))
                assert gcm_open(key, iv, aad, ct, tag) == pt
                assert ghash.np_gcm_open(key, iv, aad, ct, tag) == pt
                cases += 1
    assert cases == 54


def test_gcm_open_refuses_what_host_refuses():
    rng = np.random.default_rng(0xBEEF)
    key, iv = rng.bytes(16), rng.bytes(12)
    pt, aad = rng.bytes(100), rng.bytes(20)
    ct, tag = gcm_seal(key, iv, aad, pt)
    bad = bytes([ct[50] ^ 4]) + b"" if len(ct) < 51 else \
        ct[:50] + bytes([ct[50] ^ 4]) + ct[51:]
    assert ghash.np_gcm_open(key, iv, aad, bad, tag) is None
    with pytest.raises(TagMismatchError):
        gcm_open(key, iv, aad, bad, tag)


# ---------------------------------------------------------------------------
# Parallel CBC decrypt: the multikey seam vs the single-key models path.
# ---------------------------------------------------------------------------


def _np_cbc_encrypt(key: bytes, iv16: bytes, pt: bytes) -> bytes:
    nr, rk = expand_key_enc(key)
    prev, out = iv16, bytearray()
    for i in range(0, len(pt), 16):
        blk = bytes(a ^ b for a, b in zip(pt[i:i + 16], prev))
        prev = ghash.np_aes_encrypt_block(nr, rk, blk).tobytes()
        out += prev
    return bytes(out)


def test_dec_schedule_from_enc_matches_expand_key_dec():
    rng = np.random.default_rng(13)
    for keylen in (16, 24, 32):
        key = rng.bytes(keylen)
        nr, enc = expand_key_enc(key)
        _nr, dec = expand_key_dec(key)
        assert np.array_equal(dec_schedule_from_enc(nr, enc), dec)


@pytest.mark.parametrize("engine", ["jnp", "bitslice"])
def test_cbc_decrypt_scattered_multikey_parity(engine):
    """Two requests under two keys, concatenated into ONE dispatch with
    the host-built PREV stream — byte-identical to per-request CBC
    decrypt, which is itself pinned to the encrypt chain's inverse."""
    rng = np.random.default_rng(14)
    reqs = []
    for _ in range(2):
        key = rng.bytes(16)
        iv = rng.bytes(16)
        pt = rng.bytes(16 * int(rng.integers(1, 6)))
        reqs.append((key, iv, pt, _np_cbc_encrypt(key, iv, pt)))
    nr = 10
    rks_dec = np.zeros((4, 44), dtype=np.uint32)
    words, prev, slots = [], [], []
    for si, (key, iv, pt, ct) in enumerate(reqs):
        rks_dec[si] = expand_key_dec(key)[1]
        w = _words_of_bytes(ct)
        words.append(w)
        pv = _words_of_bytes(iv + ct[:-16])
        prev.append(pv)
        slots.append(np.full(len(ct) // 16, si, np.uint32))
    out = np.asarray(aes.cbc_decrypt_words_scattered_multikey(
        np.concatenate(words), np.concatenate(prev), rks_dec,
        np.concatenate(slots), nr, engine))
    got = packing.np_words_to_bytes(out).tobytes()
    want = b"".join(pt for (_k, _iv, pt, _ct) in reqs)
    assert got == want


def test_bitslice_decrypt_words_multikey_matches_per_key():
    rng = np.random.default_rng(15)
    n = 8
    words = rng.integers(0, 2**32, 4 * n, dtype=np.uint32)
    keys = [rng.bytes(16) for _ in range(2)]
    rk_rows = np.stack([expand_key_dec(k)[1] for k in keys])
    slot = np.array([0, 1] * (n // 2), np.uint32)
    w2 = words.reshape(n, 4)
    got = np.asarray(bitslice.decrypt_words_multikey(
        w2, rk_rows[slot], 10))
    for i in range(n):
        ref = np.asarray(bitslice.decrypt_words(
            w2[i:i + 1], rk_rows[slot[i]], 10))
        assert np.array_equal(got.reshape(n, 4)[i], ref.reshape(-1)), i
