"""recover_watch's plan-step resume through resilience.journal (the
ROADMAP follow-up this PR absorbs): completed steps are journaled as they
finish, a restarted watcher skips them without any hand-carried
--start-step index, and an edited plan invalidates the record."""

import importlib.util
import json
import os
import pathlib
import sys

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture()
def rw(tmp_path, monkeypatch):
    """A recover_watch module instance sandboxed into tmp_path: its
    committed ledger, log mirror target, and devlock marker must never
    touch the real repo from a test."""
    spec = importlib.util.spec_from_file_location(
        "_rw_under_test", ROOT / "scripts" / "recover_watch.py")
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_rw_under_test"] = mod
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "REPO", str(tmp_path))
    monkeypatch.setattr(mod, "LEDGER", str(tmp_path / "probes.log"))
    monkeypatch.setattr(mod, "probe", lambda timeout_s: (True, 0.1))
    monkeypatch.setenv("OT_BENCH_BUSY_FILE", str(tmp_path / "busy"))
    steps = [
        ("s1", [sys.executable, "-c", "print('one')"], {}, 60),
        ("s2", [sys.executable, "-c", "import sys; sys.exit(3)"], {}, 60),
    ]
    monkeypatch.setattr(mod, "plan", lambda: steps)
    yield mod
    sys.modules.pop("_rw_under_test", None)


def _run(mod, monkeypatch, plan_dir, extra=()):
    monkeypatch.setattr(sys, "argv",
                        ["recover_watch.py", "--plan-dir", str(plan_dir),
                         "--budget-h", "0.05", "--probe-interval", "1",
                         *extra])
    return mod.main()


def test_completed_steps_resume_from_journal(rw, tmp_path, monkeypatch):
    plan_dir = tmp_path / "plan"
    assert _run(rw, monkeypatch, plan_dir) == 0  # both steps ran
    journal = plan_dir / "plan.jsonl"
    recs = [json.loads(l) for l in open(journal)][1:]
    # Both steps recorded — including s2, whose NONZERO rc is this
    # plan's "done with the step" (the log has its story; a restart must
    # not re-run a finished 4 h sweep because its rc was 3).
    assert [(r["unit"], r["lines"]) for r in recs] == [
        ("s1", ["rc=0"]), ("s2", ["rc=3"])]
    log1 = (plan_dir / "s1.log").read_text()

    # Restart: both steps skip via the journal; no child runs again.
    assert _run(rw, monkeypatch, plan_dir) == 0
    assert (plan_dir / "s1.log").read_text() == log1  # not re-attempted
    recs2 = [json.loads(l) for l in open(journal)][1:]
    assert len(recs2) == 2  # no duplicate records


def test_start_step_override_skips_journal_and_reruns_safely(
        rw, tmp_path, monkeypatch):
    """The manual --start-step escape hatch jumps over journaled steps,
    which breaks replay order; the journal distrusts the tail and the
    watcher must RE-RUN the step (safe direction), not crash
    dereferencing a distrusted record."""
    plan_dir = tmp_path / "plan"
    assert _run(rw, monkeypatch, plan_dir) == 0  # journals s1 and s2
    assert _run(rw, monkeypatch, plan_dir, ["--start-step", "1"]) == 0
    # The jumped-over record is distrusted along with the tail (replay
    # is strictly ordered); re-running is the accepted cost of the
    # manual override. s2 ran again and was re-recorded.
    recs = [json.loads(l) for l in open(plan_dir / "plan.jsonl")][1:]
    assert [r["unit"] for r in recs] == ["s2"]


def test_changed_plan_invalidates_step_journal(rw, tmp_path, monkeypatch):
    plan_dir = tmp_path / "plan"
    assert _run(rw, monkeypatch, plan_dir) == 0
    # Edit the plan: replaying "step done" into different steps would be
    # the wrong-slot replay the config hash exists to prevent.
    monkeypatch.setattr(rw, "plan", lambda: [
        ("s1", [sys.executable, "-c", "print('changed')"], {}, 60)])
    assert _run(rw, monkeypatch, plan_dir) == 0
    recs = [json.loads(l) for l in open(plan_dir / "plan.jsonl")]
    assert len(recs) == 2  # fresh header + the re-run step
    assert recs[1]["unit"] == "s1" and recs[1]["lines"] == ["rc=0"]
    assert "changed" in (plan_dir / "s1.log").read_text()
