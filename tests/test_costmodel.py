"""The cost/attribution plane (our_tree_tpu/obs/costmodel.py): the
analytic-vs-XLA byte-count parity pin (the hand model must track the
real dispatch signature — a signature change that stales it fails
here, not silently downstream), graceful degradation where
cost_analysis()/memory_analysis() are unavailable, the per-process
record cache, the run-dir stamp roundtrip, the cost_section join, and
the SLO gate's per-(engine x rung) utilization budgets."""

import json

import pytest

from our_tree_tpu.obs import costmodel, metrics, slo, trace

NR128 = 10  # AES-128 rounds
K = 8


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv("OT_COST_XLA", raising=False)
    yield


# ---------------------------------------------------------------------------
# The analytic model.
# ---------------------------------------------------------------------------


def test_analytic_ctr_formula_exact():
    rec = costmodel.analytic_cost("jnp", "ctr", 64, NR128, K)
    blk = 16 * 64
    sched = K * 4 * (NR128 + 1) * 4
    assert rec["bytes_in"] == blk + blk + sched + 4 * 64
    assert rec["bytes_out"] == blk
    assert rec["hbm_bytes"] == rec["bytes_in"] + rec["bytes_out"]
    assert rec["ops"] > 0


def test_analytic_native_skips_counter_traffic():
    """The native host tier generates counters inside C (the runs fast
    path): its traffic model must NOT charge a counter array or slot
    vector — the per-engine half of the analytic fallback."""
    nat = costmodel.analytic_cost("native", "ctr", 64, NR128, K)
    jnp_ = costmodel.analytic_cost("jnp", "ctr", 64, NR128, K)
    blk = 16 * 64
    assert jnp_["bytes_in"] - nat["bytes_in"] == blk + 4 * 64
    assert nat["exec_engine"] == "native"


def test_analytic_gcm_counts_hmats_and_state_output():
    rec = costmodel.analytic_cost("jnp", "gcm", 64, NR128, K)
    assert rec["bytes_out"] == 2 * 16 * 64  # stacked (crypt, GHASH)
    assert rec["bytes_in"] >= K * 128 * 128 * 4  # the mul-by-H matrices


def test_analytic_aead_on_native_tier_models_jnp():
    """AEAD batches on a native-tier server run the jnp engine
    in-process (the lane seam's tier detour): the record must model
    THAT dataflow, not the C one."""
    rec = costmodel.analytic_cost("native", "gcm", 64, NR128, K)
    twin = costmodel.analytic_cost("jnp", "gcm", 64, NR128, K)
    assert rec["exec_engine"] == "jnp"
    assert rec["hbm_bytes"] == twin["hbm_bytes"]


# ---------------------------------------------------------------------------
# The XLA pin (the acceptance contract: byte counts within 10% on
# every engine where both halves exist).
# ---------------------------------------------------------------------------


def _pin(engine, mode, rung=64):
    rec = costmodel.analytic_cost(engine, mode, rung, NR128, K)
    x = costmodel.xla_cost(engine, mode, rung, NR128, K)
    if x is None or "arg_bytes" not in x:
        pytest.skip(f"XLA cost analysis unavailable for {engine}/{mode}")
    assert abs(x["arg_bytes"] - rec["bytes_in"]) \
        <= 0.10 * max(x["arg_bytes"], 1), (rec, x)
    assert abs(x["out_bytes"] - rec["bytes_out"]) \
        <= 0.10 * max(x["out_bytes"], 1), (rec, x)


def test_xla_parity_jnp_ctr():
    _pin("jnp", "ctr")


@pytest.mark.slow
def test_xla_parity_jnp_gcm():
    # slow: the fused GCM lower+compile costs seconds. Tier-1 keeps the
    # fast ctr pin above; the CI obs job runs this suite UNFILTERED, so
    # the full every-engine acceptance pin is still enforced per PR.
    _pin("jnp", "gcm")


@pytest.mark.slow
def test_xla_parity_jnp_cbc():
    _pin("jnp", "cbc")


@pytest.mark.slow
def test_xla_parity_bitslice_ctr():
    _pin("bitslice", "ctr")


def test_xla_half_absent_on_native_ctr():
    assert costmodel.xla_cost("native", "ctr", 64, NR128, K) is None


def test_xla_half_never_raises_on_junk_engine():
    # An unknown engine name lowers through the jit's bitslice
    # fallback on this jax, or degrades to None on one where it
    # cannot — either way, NEVER an exception (the warmup path calls
    # this inline).
    out = costmodel.xla_cost("no-such-engine", "ctr", 64, NR128, K)
    assert out is None or isinstance(out, dict)


# ---------------------------------------------------------------------------
# Record cache + the ladder policy.
# ---------------------------------------------------------------------------


def test_cost_record_cached_and_upgraded():
    costmodel.reset_for_tests()
    a = costmodel.cost_record("jnp", "ctr", 32, NR128, K)
    assert a["source"] == "analytic" and a["xla"] is None
    b = costmodel.cost_record("jnp", "ctr", 32, NR128, K)
    assert b is a  # memoized
    c = costmodel.cost_record("jnp", "ctr", 32, NR128, K, with_xla=True)
    assert c is a
    if c["xla"] is not None:  # upgraded in place where XLA exists
        assert c["source"] == "analytic+xla"


def test_ladder_policy_off_and_top(monkeypatch):
    costmodel.reset_for_tests()
    monkeypatch.setenv("OT_COST_XLA", "0")
    recs = costmodel.ladder_costs("jnp", ("ctr",), (32, 64), (128,), K)
    assert [r["rung"] for r in recs] == [32, 64]
    assert all(r["xla"] is None for r in recs)
    costmodel.reset_for_tests()
    monkeypatch.setenv("OT_COST_XLA", "top")
    recs = costmodel.ladder_costs("jnp", ("ctr",), (32, 64), (128,), K)
    by_rung = {r["rung"]: r for r in recs}
    assert by_rung[32]["xla"] is None  # below the top rung: analytic
    # Top rung attempted (non-None wherever this jax supports it).


# ---------------------------------------------------------------------------
# Run-dir stamp + the cost_section join.
# ---------------------------------------------------------------------------


def test_write_and_load_run_records(tmp_path, monkeypatch):
    monkeypatch.setenv("OT_TRACE_DIR", str(tmp_path / "tr"))
    monkeypatch.setenv("OT_TRACE_RUN", "t-cost")
    trace.reset_for_tests()
    try:
        recs = [costmodel.analytic_cost("jnp", "ctr", 32, NR128, K)]
        path = costmodel.write_run_records(recs, engine="jnp",
                                           ceiling_gbps=35.4)
        assert path is not None
        doc = json.loads(open(path).read())
        assert doc["kind"] == costmodel.KIND
        loaded, ceiling = costmodel.load_run_records(
            str(tmp_path / "tr" / "t-cost"))
        assert ceiling == 35.4
        assert loaded[0]["hbm_bytes"] == recs[0]["hbm_bytes"]
    finally:
        trace.reset_for_tests()


def test_write_run_records_disabled_without_trace(monkeypatch):
    monkeypatch.delenv("OT_TRACE_DIR", raising=False)
    trace.reset_for_tests()
    assert costmodel.write_run_records([], engine="jnp") is None


def test_cost_section_join_and_utilization():
    rec = costmodel.analytic_cost("jnp", "ctr", 4096, NR128, K)
    counters = {
        "serve_rung_dispatches{engine=jnp,mode=ctr,nr=10,rung=4096}":
            1000.0,
        "serve_rung_device_us{engine=jnp,mode=ctr,nr=10,rung=4096}": 1e4,
        # A rung that never dispatched must not produce a row.
        "serve_rung_dispatches{engine=jnp,mode=ctr,nr=10,rung=64}": 0.0,
    }
    cs = costmodel.cost_section([rec], counters, ceiling_gbps=10.0)
    assert len(cs["rows"]) == 1
    row = cs["rows"][0]
    assert row["dispatches"] == 1000
    assert row["modeled_bytes"] == 1000 * rec["hbm_bytes"]
    expect_gbps = 1000 * rec["hbm_bytes"] / 1e9 / 0.01
    assert abs(row["achieved_gbps"] - expect_gbps) < 1e-3 * expect_gbps
    assert abs(row["utilization"] - expect_gbps / 10.0) \
        < 1e-3 * expect_gbps
    eng = cs["per_engine"]["jnp"]
    assert eng["modeled_bytes"] == row["modeled_bytes"]


def test_cost_section_no_device_time_zero_rate():
    rec = costmodel.analytic_cost("jnp", "ctr", 32, NR128, K)
    counters = {
        "serve_rung_dispatches{engine=jnp,mode=ctr,nr=10,rung=32}": 2.0}
    cs = costmodel.cost_section([rec], counters)
    assert cs["rows"][0]["achieved_gbps"] == 0.0
    assert cs["rows"][0]["utilization"] is None


def test_cost_section_splits_key_sizes_at_one_rung():
    """A mixed 128/256-bit run prices each key size with ITS record:
    nr is part of the join, so AES-256 traffic at a rung is never
    priced with the AES-128 schedule-stack bytes."""
    r128 = costmodel.analytic_cost("jnp", "ctr", 64, 10, K)
    r256 = costmodel.analytic_cost("jnp", "ctr", 64, 14, K)
    counters = {
        "serve_rung_dispatches{engine=jnp,mode=ctr,nr=10,rung=64}": 3.0,
        "serve_rung_dispatches{engine=jnp,mode=ctr,nr=14,rung=64}": 5.0,
    }
    cs = costmodel.cost_section([r128, r256], counters)
    by_nr = {r["nr"]: r for r in cs["rows"]}
    assert set(by_nr) == {10, 14}
    assert by_nr[10]["modeled_bytes"] == 3 * r128["hbm_bytes"]
    assert by_nr[14]["modeled_bytes"] == 5 * r256["hbm_bytes"]
    assert r256["hbm_bytes"] > r128["hbm_bytes"]  # bigger stack


# ---------------------------------------------------------------------------
# The SLO gate's cost budgets.
# ---------------------------------------------------------------------------


def _doc(gbps, rung=4096):
    return {"load": {"p50_ms": 1, "p95_ms": 1, "p99_ms": 1,
                     "goodput_gbps": 1.0, "errors": {}, "requests": 10},
            "queue": {"lost": 0}, "compiles": {"steady": 0},
            "cost": {"rows": [{"engine": "native", "mode": "ctr",
                               "rung": rung, "nr": 10,
                               "achieved_gbps": gbps}]}}


def test_slo_cost_regression_names_engine_and_rung():
    base = slo.extract(_doc(10.0))
    good = slo.extract(_doc(9.0))
    bad = slo.extract(_doc(3.0))
    assert slo.compare(base, good) == []  # within the 50% default band
    fails = slo.compare(base, bad)
    assert len(fails) == 1
    assert fails[0].startswith("cost:native|ctr|r4096|nr10:")
    # A rung the candidate never served gates nothing.
    other = slo.extract(_doc(10.0, rung=64))
    assert slo.compare(other, slo.extract(_doc(10.0))) == []


def test_slo_cost_tolerance_override():
    base = slo.extract(_doc(10.0))
    cand = slo.extract(_doc(9.0))
    tol = slo.parse_tolerances("cost_gbps=0.05")
    fails = slo.compare(base, cand, tol)
    assert any(f.startswith("cost:") for f in fails)


def test_slo_render_includes_cost_rows():
    import io

    base = slo.extract(_doc(10.0))
    cand = slo.extract(_doc(3.0))
    fails = slo.compare(base, cand)
    buf = io.StringIO()
    slo.render(base, cand, fails, out=buf)
    assert "cost:native|ctr|r4096|nr10" in buf.getvalue()
    assert "REGRESSION" in buf.getvalue()


# ---------------------------------------------------------------------------
# Compile-time accounting: exact at any sample rate.
# ---------------------------------------------------------------------------


def test_compile_histogram_exact_under_sampling(tmp_path, monkeypatch):
    """serve_compile_us is registry-fed by the jax.monitoring listener:
    its total count must equal the server's measured warmup compile
    count EXACTLY even when span tracing samples everything out
    (OT_TRACE_SAMPLE=0) — compile cost is incident-grade evidence and
    must never depend on the sampling coin."""
    import asyncio

    from our_tree_tpu.serve.server import Server, ServerConfig

    monkeypatch.setenv("OT_TRACE_DIR", str(tmp_path / "tr"))
    monkeypatch.setenv("OT_TRACE_RUN", "t-compile")
    monkeypatch.setenv("OT_TRACE_SAMPLE", "0")
    monkeypatch.setenv("OT_COST_XLA", "0")
    trace.reset_for_tests()
    metrics.reset_for_tests()
    try:
        async def main():
            s = Server(ServerConfig(engine="jnp", lanes=1,
                                    min_bucket_blocks=32,
                                    max_bucket_blocks=64))
            await s.start()
            try:
                return s.warmup_compiles
            finally:
                await s.stop()

        warmup = asyncio.run(main())
        items = metrics.hist_items("serve_compile_us")
        total = sum(h["count"] for _, h in items)
        assert total == warmup
        # Every ladder compile is attributed to a real rung label.
        rungs = {int(labels["rung"]) for labels, _ in items}
        if warmup:
            assert rungs <= {0, 32, 64}
    finally:
        trace.reset_for_tests()
        metrics.reset_for_tests()


def test_cost_section_emits_zero_dispatch_rows_for_warmed_records():
    """A warmed (engine, rung) that saw no post-warmup traffic still
    gets its row — dispatches=0, never silent omission (a missing row
    reads as 'covered' in trend diffs); zero rows price nothing into
    per_engine and gate nothing in the SLO compare (base <= 0 skips)."""
    hot = costmodel.analytic_cost("jnp", "ctr", 4096, NR128, K)
    cold = costmodel.analytic_cost("jnp", "ctr", 64, NR128, K)
    counters = {
        "serve_rung_dispatches{engine=jnp,mode=ctr,nr=10,rung=4096}": 4.0,
        "serve_rung_device_us{engine=jnp,mode=ctr,nr=10,rung=4096}": 1e3,
    }
    cs = costmodel.cost_section([hot, cold], counters, ceiling_gbps=10.0)
    by_rung = {r["rung"]: r for r in cs["rows"]}
    assert set(by_rung) == {64, 4096}
    zero = by_rung[64]
    assert zero["dispatches"] == 0
    assert zero["modeled_dispatch_bytes"] == cold["hbm_bytes"]
    assert zero["modeled_bytes"] == 0 and zero["device_s"] == 0.0
    assert zero["achieved_gbps"] == 0.0 and zero["utilization"] is None
    # per_engine aggregates only the dispatched traffic.
    assert cs["per_engine"]["jnp"]["modeled_bytes"] \
        == by_rung[4096]["modeled_bytes"]
    # The SLO surface: a zero row in a BASELINE gates nothing.
    base = slo.extract({"load": {}, "cost": cs})
    cand = slo.extract({"load": {}, "cost": {"rows": []}})
    assert not [f for f in slo.compare(base, cand)
                if f.startswith("cost:")]


def test_slo_skips_zero_dispatch_candidate_rows():
    """A candidate's explicit dispatches=0 row (warmed rung, no traffic
    this run) gates NOTHING against a baseline that dispatched there —
    exactly as the row's pre-ot-scope absence did; a spurious 0-GB/s
    'regression' would also fire a bogus SLO-breach incident."""
    base = slo.extract(_doc(10.0))
    zero_row = {"engine": "native", "mode": "ctr", "rung": 4096,
                "nr": 10, "dispatches": 0, "achieved_gbps": 0.0}
    cand = slo.extract({"load": {}, "cost": {"rows": [zero_row]}})
    assert "native|ctr|r4096|nr10" not in cand.get("cost", {})
    assert not [f for f in slo.compare(base, cand)
                if f.startswith("cost:")]
