"""Resilience layer (our_tree_tpu/resilience/): retry policy, fault
injection seam, degradation ledger, sweep journal, and the native-build
lock/retry — the shared defenses every entry point now routes through
(docs/RESILIENCE.md)."""

import json
import os
import subprocess
import sys

import pytest

from our_tree_tpu.resilience import degrade, faults, journal, policy


@pytest.fixture(autouse=True)
def _clean_fault_state(monkeypatch):
    """Every test starts with no armed faults and an empty ledger, and
    leaves none behind (the registries are process-global on purpose)."""
    monkeypatch.delenv("OT_FAULTS", raising=False)
    faults.reset()
    degrade.clear()
    yield
    monkeypatch.delenv("OT_FAULTS", raising=False)
    faults.reset()
    degrade.clear()


# ---------------------------------------------------------------------------
# policy.RetryPolicy
# ---------------------------------------------------------------------------


def test_policy_first_try_success_no_sleep():
    slept = []
    out = policy.RetryPolicy(attempts=3, base_delay_s=9,
                             sleep=slept.append).run(lambda a: "v")
    assert out == "v" and slept == []


def test_policy_retries_with_exponential_backoff():
    slept = []

    def op(a):
        if a.index < 2:
            raise ValueError(a.index)
        return a.index

    out = policy.RetryPolicy(attempts=3, base_delay_s=0.5, factor=2.0,
                             retry_on=(ValueError,),
                             sleep=slept.append).run(op)
    assert out == 2
    assert slept == [0.5, 1.0]  # base * factor**index, deterministic


def test_policy_jitter_is_seeded_deterministic():
    def delays(seed):
        slept = []

        def op(a):
            if a.index < 2:
                raise ValueError
            return 1

        policy.RetryPolicy(attempts=3, base_delay_s=1.0, jitter_frac=0.5,
                           jitter_seed=seed, retry_on=(ValueError,),
                           sleep=slept.append).run(op)
        return slept

    a, b = delays(7), delays(7)
    assert a == b  # same seed -> same sequence: CI scripts reproduce
    # delay_i = base * factor**i * (1 + jitter_frac * u), u in [0, 1)
    assert 1.0 <= a[0] <= 1.5 and 2.0 <= a[1] <= 3.0
    assert delays(8) != a  # and the jitter is real


def test_policy_exhaustion_raises_with_cause():
    with pytest.raises(policy.PolicyExhausted) as ei:
        policy.RetryPolicy(attempts=2, retry_on=(ValueError,),
                           sleep=lambda s: None, name="t").run(
            lambda a: (_ for _ in ()).throw(ValueError("boom")))
    assert ei.value.attempts == 2
    assert isinstance(ei.value.last, ValueError)
    assert isinstance(ei.value.__cause__, ValueError)


def test_policy_on_exhausted_fallback_returns():
    seen = []
    out = policy.RetryPolicy(
        attempts=1, retry_on=(ValueError,),
        on_exhausted=lambda last: seen.append(type(last).__name__) or "fb",
    ).run(lambda a: (_ for _ in ()).throw(ValueError()))
    assert out == "fb" and seen == ["ValueError"]


def test_policy_unlisted_exception_propagates_immediately():
    calls = []

    def op(a):
        calls.append(a.index)
        raise KeyError("not retryable")

    with pytest.raises(KeyError):
        policy.RetryPolicy(attempts=3, retry_on=(ValueError,),
                           sleep=lambda s: None).run(op)
    assert calls == [0]


def test_policy_budget_stops_retries():
    clock = [0.0]
    calls = []

    def op(a):
        calls.append(a.remaining_s)
        clock[0] += 10.0  # each attempt costs 10 "seconds"
        raise ValueError

    with pytest.raises(policy.PolicyExhausted) as ei:
        policy.RetryPolicy(attempts=None, budget_s=25.0,
                           retry_on=(ValueError,), sleep=lambda s: None,
                           clock=lambda: clock[0]).run(op)
    # Attempts at t=0, 10, 20; at t=30 the budget (25) is spent.
    assert ei.value.attempts == 3
    assert calls == [25.0, 15.0, 5.0]


def test_policy_stop_when_predicate():
    calls = []

    def op(a):
        calls.append(a.index)
        raise ValueError

    with pytest.raises(policy.PolicyExhausted):
        policy.RetryPolicy(attempts=5, retry_on=(ValueError,),
                           sleep=lambda s: None,
                           stop_when=lambda a: a.index >= 2).run(op)
    assert calls == [0, 1]  # retry #2 was vetoed before running


def test_policy_exception_retry_delay_overrides_backoff():
    class Busy(Exception):
        retry_delay_s = 7.5

    slept = []

    def op(a):
        if a.index == 0:
            raise Busy
        return "ok"

    assert policy.RetryPolicy(attempts=2, base_delay_s=99, retry_on=(Busy,),
                              sleep=slept.append).run(op) == "ok"
    assert slept == [7.5]


def test_policy_attempt_timeout_clamped_to_budget():
    clock = [0.0]
    seen = []

    def op(a):
        seen.append(a.timeout_s)
        clock[0] += 8.0
        raise ValueError

    with pytest.raises(policy.PolicyExhausted):
        policy.RetryPolicy(attempts=3, per_attempt_s=10.0, budget_s=12.0,
                           retry_on=(ValueError,), sleep=lambda s: None,
                           clock=lambda: clock[0]).run(op)
    assert seen == [10.0, 4.0]  # second attempt sees only what's left


def test_budget_accounting_and_debit():
    clock = [0.0]
    b = policy.Budget(10.0, clock=lambda: clock[0])
    clock[0] = 3.0
    assert b.spent() == 3.0 and b.remaining() == 7.0 and not b.exhausted()
    # A simulated fault debits without wall clock passing — the shared
    # _burn: the rehearsal must cost what the real outage costs.
    b.debit(6.0)
    assert b.spent() == 9.0 and not b.exhausted()
    b.debit(1.0)
    assert b.exhausted() and b.remaining() == 0.0


def test_budget_zero_means_unbudgeted():
    b = policy.Budget(0)
    b.debit(1e9)
    assert b.remaining() == float("inf") and not b.exhausted()


# ---------------------------------------------------------------------------
# faults: the OT_FAULTS grammar and the registry semantics
# ---------------------------------------------------------------------------


def test_faults_unset_is_inert():
    assert not faults.active()
    assert not faults.fire("init_hang")
    faults.check("dispatch_fail")  # must not raise


def test_faults_counted_token_fires_exactly_n_times(monkeypatch):
    monkeypatch.setenv("OT_FAULTS", "dispatch_fail:2")
    faults.reset()
    assert faults.fire("dispatch_fail")
    assert faults.fire("dispatch_fail")
    assert not faults.fire("dispatch_fail")
    assert not faults.fire("dispatch_fail")  # stays quiet forever after


def test_faults_bare_token_fires_forever(monkeypatch):
    monkeypatch.setenv("OT_FAULTS", "build_fail")
    faults.reset()
    for _ in range(5):
        assert faults.fire("build_fail")
    assert faults.remaining("build_fail") == faults.ALWAYS


def test_faults_grammar_whitespace_accumulation_and_zero(monkeypatch):
    monkeypatch.setenv("OT_FAULTS", " init_hang:1 , init_hang:2 ,"
                                    " lock_busy:0 ,, dispatch_fail : nope")
    faults.reset()
    # repeated tokens accumulate; zero-count disarms; malformed ignored
    assert faults.remaining("init_hang") == 3
    assert faults.remaining("lock_busy") == 0
    assert faults.remaining("dispatch_fail") == 0


def test_faults_check_raises_injected_fault(monkeypatch):
    monkeypatch.setenv("OT_FAULTS", "dispatch_fail:1")
    faults.reset()
    with pytest.raises(faults.InjectedFault, match="dispatch_fail"):
        faults.check("dispatch_fail", "here")
    faults.check("dispatch_fail")  # consumed: second check passes
    assert issubclass(faults.InjectedFault, RuntimeError)


def test_faults_unknown_point_warns_but_arms(monkeypatch, capsys):
    monkeypatch.setenv("OT_FAULTS", "tpyo_fail:1")
    faults.reset()
    assert "unknown injection point" in capsys.readouterr().err
    assert faults.fire("tpyo_fail")  # armed anyway (forward compat)


def test_faults_new_points_are_known(monkeypatch, capsys):
    """dispatch_hang / unit_crash are registered names: arming them must
    not trip the unknown-point warning (a warned-but-armed point is how
    TYPOS are caught; a real point warning would train people to ignore
    it)."""
    monkeypatch.setenv("OT_FAULTS", "dispatch_hang:1,unit_crash:1")
    faults.reset()
    assert sorted(faults.armed()) == ["dispatch_hang", "unit_crash"]
    assert "unknown" not in capsys.readouterr().err


def test_faults_armed_snapshot_is_fire_safe(monkeypatch):
    monkeypatch.setenv("OT_FAULTS", "init_hang:1,build_fail")
    faults.reset()
    for point in faults.armed():  # the metering loop's shape
        faults.fire(point)
    assert faults.remaining("init_hang") == 0
    assert faults.remaining("build_fail") == faults.ALWAYS
    assert faults.armed() == ("build_fail",)


# ---------------------------------------------------------------------------
# degrade: the demotion ledger
# ---------------------------------------------------------------------------


def test_degrade_records_in_order_and_dedupes():
    degrade.degrade("tpu->cpu", "first")
    degrade.degrade("native->lax.scan", "second")
    degrade.degrade("tpu->cpu", "repeat must not duplicate")
    assert degrade.events() == ["tpu->cpu", "native->lax.scan"]
    assert degrade.detail()[0] == ("tpu->cpu", "first")
    degrade.clear()
    assert degrade.events() == []


def test_degrade_is_shared_across_import_contexts():
    """The bare-loaded module (what repo-root bench.py uses) and the
    package import must be the SAME object — a split ledger would let a
    package-context demotion vanish from the bare-context JSON line."""
    loader = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts")
    sys.path.insert(0, loader)
    try:
        from _devlock_loader import load_resilience

        assert load_resilience("degrade") is degrade
        assert load_resilience("faults") is faults
    finally:
        sys.path.remove(loader)


# ---------------------------------------------------------------------------
# journal.SweepJournal
# ---------------------------------------------------------------------------


def _mkjournal(tmp_path, config=None, name="j.jsonl"):
    return journal.SweepJournal(str(tmp_path / name),
                                config if config is not None else {"s": 1})


def test_journal_record_and_replay_roundtrip(tmp_path):
    j = _mkjournal(tmp_path)
    j.record("ecb:65536", ["row1,", "# derived"], {"st": 42}, ["tpu->cpu"])
    j.record("ctr:65536", ["row2,"], {"st": 43}, [])
    j.close()
    j2 = _mkjournal(tmp_path)
    assert j2.pending == 2
    e = j2.skip("ecb:65536")
    assert e["lines"] == ["row1,", "# derived"]
    assert e["rng_state"] == {"st": 42} and e["degraded"] == ["tpu->cpu"]
    assert j2.skip("ctr:65536")["lines"] == ["row2,"]
    assert j2.skip("rc4:65536") is None  # nothing left
    j2.close()


def test_journal_config_hash_mismatch_invalidates(tmp_path):
    j = _mkjournal(tmp_path, {"seed": 1})
    j.record("u", ["l"], None, [])
    j.close()
    j2 = _mkjournal(tmp_path, {"seed": 2})  # changed sweep identity
    assert j2.pending == 0  # nothing replayable
    j2.close()
    # and the file was restarted for the NEW config
    head = json.loads(open(tmp_path / "j.jsonl").readline())
    assert head["config_hash"] == journal.config_hash({"seed": 2})


def test_journal_torn_tail_is_truncated(tmp_path):
    j = _mkjournal(tmp_path)
    j.record("a", ["1"], None, [])
    j.record("b", ["2"], None, [])
    j.close()
    p = tmp_path / "j.jsonl"
    with open(p, "ab") as f:  # the SIGKILL-mid-write artifact
        f.write(b'{"unit": "c", "lines": ["tor')
    j2 = _mkjournal(tmp_path)
    assert j2.pending == 2  # the valid prefix survives, the tear is gone
    assert j2.skip("a") and j2.skip("b")
    j2.record("c", ["3"], None, [])
    j2.close()
    recs = [json.loads(l) for l in open(p)]
    assert [r.get("unit") for r in recs] == [None, "a", "b", "c"]


def test_journal_order_mismatch_distrusts_tail(tmp_path):
    j = _mkjournal(tmp_path)
    j.record("a", ["1"], None, [])
    j.record("b", ["2"], None, [])
    j.close()
    j2 = _mkjournal(tmp_path)
    assert j2.skip("a")
    assert j2.skip("ZZZ") is None  # order broke: replay must stop
    assert j2.skip("b") is None  # ...and the stale tail is not offered
    j2.record("ZZZ", ["3"], None, [])
    j2.close()
    recs = [json.loads(l) for l in open(tmp_path / "j.jsonl")]
    assert [r.get("unit") for r in recs] == [None, "a", "ZZZ"]


def test_journal_failure_rows_count_but_never_replay(tmp_path):
    """The quarantine ledger's substrate: failure rows accumulate counts
    (across handles — the ledger survives restarts), stay out of the
    replay list, and interleave freely with completed rows."""
    j = _mkjournal(tmp_path)
    j.record_failure("a", "timeout:5s")
    j.record_failure("a", "crash:rc=-9")
    j.record("b", ["rowB"], None, [])
    j.record_failure("c", "timeout:5s")
    assert j.fail_count("a") == 2 and j.fail_count("c") == 1
    j.close()
    j2 = _mkjournal(tmp_path)
    assert j2.fail_count("a") == 2 and j2.fail_count("c") == 1
    assert j2.pending == 1  # only b replays
    assert not j2.is_completed("a") and j2.is_completed("b")
    # is_completed gates skip(): asking for the failed unit must not be
    # treated as an order mismatch (which would truncate b away).
    assert not j2.is_completed("a")
    assert j2.skip("b")["lines"] == ["rowB"]
    # a late success after failures: the unit completes normally
    j2.record("a", ["rowA"], None, [])
    j2.close()
    j3 = _mkjournal(tmp_path)
    assert j3.is_completed("a") and j3.fail_count("a") == 2


def test_journal_reload_tail_absorbs_other_writers(tmp_path):
    """The isolate supervisor's read path: rows appended by a CHILD
    process (same file, separate handle) become visible to the parent's
    open handle via reload_tail — completed rows join replay, failure
    rows join the counts, and the parent's own appends still land after
    them."""
    j = _mkjournal(tmp_path)
    other = _mkjournal(tmp_path)  # stands in for the child's handle
    other.record("u1", ["r1"], None, [])
    other.record_failure("u2", "timeout:1s")
    other.close()
    assert j.pending == 0  # not yet visible to the parent handle
    assert j.reload_tail() == 1
    assert j.is_completed("u1") and j.fail_count("u2") == 1
    j.record_failure("u2", "timeout:1s")
    j.close()
    j2 = _mkjournal(tmp_path)
    assert j2.fail_count("u2") == 2 and j2.is_completed("u1")


def test_journal_reload_tail_truncates_torn_child_write(tmp_path):
    """The SIGKILL-mid-append artifact, supervisor-side: a child killed
    while writing leaves a partial line; reload_tail must cut it off
    BEFORE the parent appends its failure row, or the two glue into one
    unparseable line and the next load discards everything after it —
    quarantine counts would reset every run."""
    j = _mkjournal(tmp_path)
    with open(tmp_path / "j.jsonl", "ab") as f:  # the killed child's torn row
        f.write(b'{"unit": "x", "lines": ["par')
    assert j.reload_tail() == 0
    j.record_failure("x", "timeout:1s")
    j.record("y", ["rowY"], None, [])
    j.close()
    j2 = _mkjournal(tmp_path)
    assert j2.fail_count("x") == 1  # the failure row survived the tear
    assert j2.skip("y")["lines"] == ["rowY"]


def test_journal_fresh_file_has_header_immediately(tmp_path):
    j = _mkjournal(tmp_path, {"x": 9})
    j.close()  # killed before the first completed row
    head = json.loads(open(tmp_path / "j.jsonl").readline())
    assert head["kind"] == journal.KIND
    assert head["config_hash"] == journal.config_hash({"x": 9})


# ---------------------------------------------------------------------------
# native build: flock + retry + build_fail injection
# ---------------------------------------------------------------------------


def test_native_build_retries_past_injected_failure(tmp_path, monkeypatch):
    """OT_FAULTS=build_fail:1 fails exactly the first make attempt; the
    shared policy's second attempt builds — the deterministic rehearsal of
    a transiently-failing make."""
    from our_tree_tpu.runtime import native

    calls = []
    monkeypatch.setattr(native, "_CSRC", tmp_path)
    monkeypatch.setattr(native, "_LIB_PATH", tmp_path / "libotcrypt.so")
    (tmp_path / "x.c").write_text("int x;\n")  # staleness: lib missing

    def fake_make(argv, *a, **kw):
        calls.append(argv)
        return native.isolate.ChildResult("ok", 0, "", "", 0.0)

    monkeypatch.setattr(native.isolate, "run_child", fake_make)
    monkeypatch.setenv("OT_FAULTS", "build_fail:1")
    faults.reset()
    native._build()
    assert len(calls) == 1  # attempt 1 injected-failed, attempt 2 ran make


def test_native_build_deterministic_failure_raises(tmp_path, monkeypatch):
    from our_tree_tpu.runtime import native

    monkeypatch.setattr(native, "_CSRC", tmp_path)
    monkeypatch.setattr(native, "_LIB_PATH", tmp_path / "libotcrypt.so")
    (tmp_path / "x.c").write_text("int x;\n")

    def fake_make(argv, *a, **kw):
        return native.isolate.ChildResult("crash", 2, "", "cc: error", 0.0)

    monkeypatch.setattr(native.isolate, "run_child", fake_make)
    with pytest.raises(policy.PolicyExhausted) as ei:
        native._build()
    assert "cc: error" in str(ei.value.last)


def test_native_build_lock_serializes_concurrent_builders(tmp_path,
                                                          monkeypatch):
    """The flock critical section: while another process holds the sidecar
    lock, _build blocks; after the holder (having built) releases, _build
    re-checks staleness and skips the make entirely — the
    concurrent-corruption race is closed at both ends."""
    from our_tree_tpu.runtime import native

    monkeypatch.setattr(native, "_CSRC", tmp_path)
    lib = tmp_path / "libotcrypt.so"
    monkeypatch.setattr(native, "_LIB_PATH", lib)
    (tmp_path / "Makefile").write_text("libotcrypt.so:\n")
    src = tmp_path / "x.c"
    src.write_text("int x;\n")

    lockfile = str(lib) + ".lock"
    holder = subprocess.Popen(
        [sys.executable, "-c", f"""
import fcntl, os, sys, time
fd = os.open({lockfile!r}, os.O_CREAT | os.O_RDWR, 0o644)
fcntl.flock(fd, fcntl.LOCK_EX)
print("locked", flush=True)
time.sleep(1.0)
# the concurrent builder finishes its build before releasing:
open({str(lib)!r}, "w").write("built-by-holder")
os.utime({str(lib)!r})
os.close(fd)
"""],
        stdout=subprocess.PIPE, text=True)
    try:
        assert holder.stdout.readline().strip() == "locked"
        os.utime(src)  # stale from this process's point of view

        def fail_make(*a, **kw):  # must never run: holder's build wins
            raise AssertionError("make ran despite a concurrent build")

        monkeypatch.setattr(native.isolate, "run_child", fail_make)
        import time
        t0 = time.perf_counter()
        native._build()  # blocks on the flock, then sees the fresh lib
        assert time.perf_counter() - t0 > 0.3  # it really waited
        assert lib.read_text() == "built-by-holder"
    finally:
        holder.wait()
