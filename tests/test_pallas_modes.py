"""Pallas engines through long keys, flat streams, and the mode/context
layer (interpreter mode on CPU).

Split out of test_pallas.py (VERDICT r3 weak #4/#8): these gauntlets sweep
MANY engines per test over small-to-medium shapes, so their compile mix is
disjoint from the multi-grid module (test_pallas_grid.py) and the core
module (test_pallas.py). Module-granular `jax.clear_caches()`
(tests/conftest.py) re-bounds XLA-CPU compiler state between the three
without test_pallas.py's former per-test hammer.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from our_tree_tpu.models import aes as aes_mod
from our_tree_tpu.ops.keyschedule import expand_key_enc


@pytest.mark.parametrize("keybytes", [24, 32])
@pytest.mark.slow
def test_pallas_kernels_long_keys(keybytes, monkeypatch):
    """AES-192/256 (nr = 12/14) through both pallas engines: the kernels
    unroll rounds with nr as a static parameter, so the nr > 10 straight-
    line paths are distinct compiled code that AES-128-only tests never
    touch (cf. the reference CUDA kernels' Nr>10/Nr>12 guard blocks,
    aes-gpu/Source/AES.cu:342-365 — which no test there exercised either)."""
    from our_tree_tpu.ops import pallas_aes
    from our_tree_tpu.utils import packing

    monkeypatch.setattr(pallas_aes, "TILE", 128)
    rng = np.random.default_rng(41)
    key = bytes(range(keybytes))
    nr, rk = expand_key_enc(key)
    rk = jnp.asarray(rk)
    nonce = np.frombuffer(bytes(range(200, 216)), np.uint8)
    ctr_be = jnp.asarray(packing.np_bytes_to_words(nonce).byteswap())
    # 32*32 blocks (a partial 32-lane tile): the property under test is
    # the nr>10/nr>12 straight-line ROUND paths, which are per-grid-step
    # code independent of tile fill; full-tile multi-grid coverage lives
    # in test_pallas_grid (AES-128). gt-bp shares gt's round structure —
    # only the S-box circuit differs, pinned exhaustively in
    # test_bitslice — so the tower/bp pair needs no long-key twin here.
    w = jnp.asarray(rng.integers(0, 2**32, (32 * 32, 4)).astype(np.uint32))
    want_ctr = np.asarray(aes_mod.ctr_crypt_words(w, ctr_be, rk, nr, "jnp"))
    want_ecb = np.asarray(aes_mod.ecb_encrypt_words(w, rk, nr, "jnp"))
    for engine in ("pallas", "pallas-gt"):
        got = np.asarray(aes_mod.ctr_crypt_words(w, ctr_be, rk, nr, engine))
        np.testing.assert_array_equal(got, want_ctr, err_msg=f"ctr {engine}")
        got = np.asarray(aes_mod.ecb_encrypt_words(w, rk, nr, engine))
        np.testing.assert_array_equal(got, want_ecb, err_msg=f"ecb {engine}")


@pytest.mark.slow
def test_ctr_flat_stream_equals_block_words():
    """ctr_crypt_words accepts a flat (4N,) u32 stream (the dense TPU
    boundary layout — a (N, 4) boundary array pads its minor dim to the
    128-lane tile) and must produce byte-identical output to the (N, 4)
    form on every engine."""
    from our_tree_tpu.utils import packing

    rng = np.random.default_rng(17)
    nr, rk = expand_key_enc(bytes(range(16)))
    rk = jnp.asarray(rk)
    nonce = np.frombuffer(bytes(range(50, 66)), np.uint8)
    ctr_be = jnp.asarray(packing.np_bytes_to_words(nonce).byteswap())
    # 33 blocks: crosses the 32-block lane boundary (pad path) while
    # keeping interpreter cost bounded; one engine per boundary layout —
    # the property under test is the models-level flat/(N, 4) wrapper,
    # and the -bp variants share their base engine's boundary code
    # exactly (they differ only in the in-kernel S-box circuit).
    data = rng.integers(0, 256, 16 * 33, np.uint8)
    w2 = jnp.asarray(packing.np_bytes_to_words(data).reshape(-1, 4))
    wf = jnp.asarray(packing.np_bytes_to_words(data))
    for engine in ("jnp", "bitslice", "pallas", "pallas-gt",
                   "pallas-dense"):
        o2 = np.asarray(aes_mod.ctr_crypt_words(w2, ctr_be, rk, nr, engine))
        of = np.asarray(aes_mod.ctr_crypt_words(wf, ctr_be, rk, nr, engine))
        assert of.shape == (4 * 33,)
        np.testing.assert_array_equal(of.reshape(-1, 4), o2, err_msg=engine)


@pytest.mark.slow
def test_pallas_engine_ctr_context():
    """The pallas core through the CTR mode path and the AES context."""
    from our_tree_tpu.models.aes import AES

    # One engine per boundary layout + the ragged tail; gt-bp differs
    # from gt only in the S-box circuit (exhaustively pinned elsewhere).
    data = np.random.default_rng(9).integers(0, 256, 16 * 20 + 7, np.uint8)
    nonce = np.arange(16, dtype=np.uint8)
    outs = {}
    for engine in ("jnp", "pallas", "pallas-gt", "pallas-dense"):
        a = AES(bytes(range(16)), engine=engine)
        outs[engine], *_ = a.crypt_ctr(0, nonce.copy(),
                                       np.zeros(16, np.uint8), data)
    for engine in ("pallas", "pallas-gt", "pallas-dense"):
        np.testing.assert_array_equal(outs["jnp"], outs[engine],
                                      err_msg=engine)
