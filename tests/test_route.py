"""ot-route (our_tree_tpu/route): the front-end routing tier.

In-process rehearsals: several REAL serve Servers (small ladder, native
or resolved engine) each behind a ``serve.worker.RequestFrontend`` on
an ephemeral loopback port, with a ``route.proxy.Router`` over them —
the full production wire path (framed protocol, /healthz gossip,
canaries) minus the process boundary, which route.bench and the CI
router drive cover with real spawned workers.

Covers: NIST-KAT bit-exactness THROUGH the router (failover included —
the re-dispatched request's bytes must be identical), key affinity
(same key -> same backend; control arm spreads), the backend health
machine under backend_fail/backend_hang (@backend= scoping), the
quarantine -> gossip-ok -> canary -> probation -> release cycle, shed
backpressure propagation (retry-with-backoff on the replica ring, then
shed-at-router through degrade()), journal-persisted quarantine +
--unquarantine, graceful drain (lost == 0), membership changes with
minimal-motion accounting, the router /healthz membership view, and
the worker frontend's wire-protocol containment.
"""

import asyncio
import json
import pathlib

import numpy as np
import pytest

from our_tree_tpu.models.aes import AES
from our_tree_tpu.obs import export, trace
from our_tree_tpu.resilience import degrade, faults
from our_tree_tpu.route import bench as route_bench
from our_tree_tpu.route import health, ring
from our_tree_tpu.route.proxy import BackendSpec, Router, RouterConfig
from our_tree_tpu.route.status import RouterStatus
from our_tree_tpu.serve import wire
from our_tree_tpu.serve.queue import ERR_SHED
from our_tree_tpu.serve.server import Server, ServerConfig
from our_tree_tpu.serve.worker import RequestFrontend

ROOT = pathlib.Path(__file__).resolve().parent.parent

#: Small ladder: 4 rungs, 256-block ceiling — fast warmup per backend.
LADDER = dict(min_bucket_blocks=32, max_bucket_blocks=256, lanes=1)

NIST_KEY = bytes.fromhex("2b7e151628aed2a6abf7158809cf4f3c")
NIST_CTR0 = bytes.fromhex("f0f1f2f3f4f5f6f7f8f9fafbfcfdfeff")
NIST_PT = bytes.fromhex(
    "6bc1bee22e409f96e93d7e117393172a"
    "ae2d8a571e03ac9c9eb76fac45af8e51"
    "30c81c46a35ce411e5fbc1191a0a52ef"
    "f69f2445df4f9b17ad2b417be66c3710")
NIST_CT = bytes.fromhex(
    "874d6191b620e3261bef6864990db6ce"
    "9806f66b7970fdff8617187bb9fffdff"
    "5ae4df3edbd5d35e5b4f09020db03eab"
    "1e031dda2fbe03d1792170a0f3009cee")


@pytest.fixture(autouse=True)
def _clean_process_state(monkeypatch):
    monkeypatch.delenv("OT_FAULTS", raising=False)
    monkeypatch.delenv("OT_DISPATCH_DEADLINE", raising=False)
    faults.reset()
    degrade.clear()
    yield
    monkeypatch.delenv("OT_FAULTS", raising=False)
    faults.reset()
    degrade.clear()


@pytest.fixture
def traced(tmp_path, monkeypatch):
    monkeypatch.setenv("OT_TRACE_DIR", str(tmp_path / "tr"))
    monkeypatch.setenv("OT_TRACE_RUN", "t-route")
    monkeypatch.delenv("OT_TRACE_PARENT", raising=False)
    trace.reset_for_tests()
    yield tmp_path / "tr" / "t-route"
    trace.reset_for_tests()


class Cluster:
    """N in-process backends + a router, torn down in order."""

    def __init__(self, n=3, router_cfg=None, server_kw=None,
                 journal=None):
        self.n = n
        self.router_cfg = router_cfg
        self.server_kw = dict(LADDER, **(server_kw or {}))
        self.journal = journal
        self.servers, self.fronts, self.specs = [], [], []
        self.router = None

    async def __aenter__(self):
        for i in range(self.n):
            s = Server(ServerConfig(status_port=0, **self.server_kw))
            await s.start()
            f = RequestFrontend(s, 0)
            await f.start()
            self.servers.append(s)
            self.fronts.append(f)
            self.specs.append(BackendSpec(
                f"b{i}", "127.0.0.1", f.port, s.status.port))
        cfg = self.router_cfg or RouterConfig(
            gossip_every_s=0.0, attempt_timeout_s=2.0,
            journal=self.journal)
        self.router = Router(self.specs, cfg)
        await self.router.start()
        return self

    async def __aexit__(self, *exc):
        await self.router.stop()
        for f in self.fronts:
            await f.stop()
        for s in self.servers:
            await s.stop()


def _tenant_for(router, backend_name: str, key: bytes) -> str:
    """A tenant whose affinity home is ``backend_name`` (so a scoped
    fault on that backend deterministically intersects the request)."""
    for t in range(128):
        aff = ring.affinity_key(f"t{t}", key)
        if router.ring.node_for(aff) == backend_name:
            return f"t{t}"
    raise AssertionError(f"no tenant maps to {backend_name}")


# ---------------------------------------------------------------------------
# Bit-exactness + affinity.
# ---------------------------------------------------------------------------


def test_router_end_to_end_bit_exact_nist_kat():
    async def main():
        async with Cluster(n=3) as c:
            pt = np.frombuffer(NIST_PT, np.uint8)
            resp = await c.router.submit("t0", NIST_KEY, NIST_CTR0, pt)
            assert resp.ok
            assert bytes(np.asarray(resp.payload)) == NIST_CT
            # Decrypt = the same CTR pass over the ciphertext.
            back = await c.router.submit(
                "t0", NIST_KEY, NIST_CTR0, np.asarray(resp.payload))
            assert bytes(np.asarray(back.payload)) == NIST_PT
            assert c.router.stats()["lost"] == 0

    asyncio.run(main())


def test_affinity_same_key_lands_one_backend_control_spreads():
    async def main():
        async with Cluster(n=3) as c:
            key, nonce = b"\x01" * 16, b"\x02" * 16
            pt = np.zeros(64, np.uint8)
            tenants = [f"t{i}" for i in range(12)]
            for _ in range(3):
                for t in tenants:
                    assert (await c.router.submit(t, key, nonce, pt)).ok
            # Affinity: every tenant's requests all landed on its ring
            # home — per-tenant placement is a function of the key, so
            # repeat traffic is all hits.
            st = c.router.stats()
            assert st["affinity"]["ratio"] == 1.0
            # And the ring spread the 12 tenants over >1 backend.
            used = [b for b in st["backends"].values()
                    if b["dispatches"] > 0]
            assert len(used) >= 2

        # Control arm: seeded-random routing spreads EACH tenant's
        # traffic, which is exactly the keycache-miss behaviour the
        # A/B measures.
        cfg = RouterConfig(gossip_every_s=0.0, attempt_timeout_s=2.0,
                           affinity=False, seed=3)
        async with Cluster(n=3, router_cfg=cfg) as c:
            key, nonce = b"\x01" * 16, b"\x02" * 16
            pt = np.zeros(64, np.uint8)
            for _ in range(12):
                assert (await c.router.submit("t0", key, nonce, pt)).ok
            used = [b for b in c.router.stats()["backends"].values()
                    if b["dispatches"] > 0]
            assert len(used) >= 2  # one tenant, many backends

    asyncio.run(main())


def test_router_gcm_kat_seal_open_affinity_and_failover(monkeypatch):
    """AEAD through the routing tier (the ot-aead follow-up): the NIST
    GCM KATs seal AND open bit-exactly THROUGH the router — ciphertext
    and tag ride the wire's mode fields both ways — the AEAD traffic
    gets the same key-affinity placement as ctr, and a scoped
    backend_fail mid-seal re-dispatches on the next ring node with
    byte-identical ciphertext+tag (failover-before-error holds for
    modes that carry a tag across the wire)."""
    kats = [k for k in json.loads(
                (ROOT / "tests" / "golden" / "gcm_kats.json")
                .read_text())["kats"]
            if len(k["iv"]) == 24 and k["ct"] and len(k["ct"]) % 32 == 0]
    assert kats, "no block-aligned 96-bit-IV KATs in the golden file"
    # One key size: the in-process cluster warms 128-bit ladders only.
    kats = [k for k in kats if len(k["key"]) == 32]

    async def main():
        async with Cluster(
                n=3,
                server_kw=dict(modes=("ctr", "gcm", "gcm-open"))) as c:
            for k in kats:
                key, iv = bytes.fromhex(k["key"]), bytes.fromhex(k["iv"])
                aad = bytes.fromhex(k["aad"])
                pt = np.frombuffer(bytes.fromhex(k["pt"]), np.uint8)
                ct = bytes.fromhex(k["ct"])
                seal = await c.router.submit("t0", key, b"", pt,
                                             mode="gcm", iv=iv, aad=aad)
                assert seal.ok, (k["name"], seal.error, seal.detail)
                assert bytes(np.asarray(seal.payload)).hex() == k["ct"]
                assert seal.tag.hex() == k["tag"], k["name"]
                opened = await c.router.submit(
                    "t0", key, b"", np.frombuffer(ct, np.uint8),
                    mode="gcm-open", iv=iv, aad=aad,
                    tag=bytes.fromhex(k["tag"]))
                assert opened.ok
                assert bytes(np.asarray(opened.payload)).hex() == k["pt"]
            # A tampered tag answers the per-request auth refusal
            # through the wire, not an exception anywhere.
            k = kats[0]
            bad = await c.router.submit(
                "t0", bytes.fromhex(k["key"]), b"",
                np.frombuffer(bytes.fromhex(k["ct"]), np.uint8),
                mode="gcm-open", iv=bytes.fromhex(k["iv"]),
                aad=bytes.fromhex(k["aad"]),
                tag=b"\x00" * 16)
            assert not bad.ok and bad.error == "auth-failed"
            # AEAD rides affinity like ctr: same (tenant, key) -> same
            # home backend for every request above.
            st = c.router.stats()
            assert st["affinity"]["ratio"] == 1.0
            # Failover: wedge the KAT key's home backend for ONE
            # request; the seal must re-dispatch bit-exactly.
            k = kats[-1]
            key = bytes.fromhex(k["key"])
            tenant = _tenant_for(c.router, "b1", key)
            monkeypatch.setenv("OT_FAULTS", "backend_fail:1@backend=1")
            faults.reset()
            seal = await c.router.submit(
                tenant, key, b"",
                np.frombuffer(bytes.fromhex(k["pt"]), np.uint8),
                mode="gcm", iv=bytes.fromhex(k["iv"]),
                aad=bytes.fromhex(k["aad"]))
            assert seal.ok
            assert bytes(np.asarray(seal.payload)).hex() == k["ct"]
            assert seal.tag.hex() == k["tag"]
            st = c.router.stats()
            assert st["redispatches"] == 1
            assert st["lost"] == 0

    asyncio.run(main())


# ---------------------------------------------------------------------------
# The fault matrix at the backend seam.
# ---------------------------------------------------------------------------


def test_backend_fail_scoped_redispatch_bit_exact(monkeypatch):
    async def main():
        async with Cluster(n=3) as c:
            tenant = _tenant_for(c.router, "b1", NIST_KEY)
            monkeypatch.setenv("OT_FAULTS", "backend_fail:1@backend=1")
            faults.reset()
            pt = np.frombuffer(NIST_PT, np.uint8)
            resp = await c.router.submit(tenant, NIST_KEY, NIST_CTR0, pt)
            # Failover-before-error: the rider sees the right BYTES,
            # never the fault.
            assert resp.ok and bytes(np.asarray(resp.payload)) == NIST_CT
            st = c.router.stats()
            assert st["redispatches"] == 1
            assert st["backends"]["b1"]["state"] == health.SUSPECT
            assert st["lost"] == 0
            # The scoped shot hit backend 1 and no other.
            assert st["backends"]["b1"]["failures"] == 1
            assert all(st["backends"][b]["failures"] == 0
                       for b in ("b0", "b2"))

    asyncio.run(main())


def test_backend_hang_quarantine_gossip_release_cycle(
        monkeypatch, traced):
    async def main():
        async with Cluster(n=3) as c:
            tenant = _tenant_for(c.router, "b1", NIST_KEY)
            monkeypatch.setenv("OT_FAULTS", "backend_hang:1@backend=1")
            faults.reset()
            cfg = c.router.config
            cfg.attempt_timeout_s = 0.5
            pt = np.frombuffer(NIST_PT, np.uint8)
            resp = await c.router.submit(tenant, NIST_KEY, NIST_CTR0, pt)
            # The hung attempt timed out at the deadline, the request
            # re-dispatched BIT-EXACTLY, b1 is quarantined (a hang is
            # never transient) and the quarantine is stamped.
            assert resp.ok and bytes(np.asarray(resp.payload)) == NIST_CT
            assert c.router.redispatches == 1
            assert c.router.quarantine_events() == 1
            assert c.router.backends["b1"].health.state == \
                health.QUARANTINED
            assert "quarantined:backend:b1" in degrade.events()
            # Gossip sees the backend's own /healthz is fine -> canary
            # (bit-exact, via the pinned expectation) -> probation.
            await c.router.gossip_once()
            assert c.router.backends["b1"].health.state == health.PROBATION
            # Probation served through real traffic -> released.
            for _ in range(4):
                assert (await c.router.submit(
                    tenant, NIST_KEY, NIST_CTR0, pt)).ok
            assert c.router.backends["b1"].health.state == health.HEALTHY
            assert c.router.release_events() == 1
            assert c.router.stats()["lost"] == 0

    asyncio.run(main())
    # The hang's evidence: exactly one abandoned route-dispatch span.
    run = export.load_run(str(traced))
    orphans = [s for s in run.orphans()]
    assert [s.name for s in orphans] == ["route-dispatch"]
    assert str(orphans[0].attrs.get("backend")) == "1"


def test_rescue_canaries_quarantined_backend_when_none_placeable(
        monkeypatch):
    async def main():
        async with Cluster(n=1) as c:
            monkeypatch.setenv("OT_FAULTS", "backend_hang:1@backend=0")
            faults.reset()
            c.router.config.attempt_timeout_s = 0.5
            pt = np.zeros(64, np.uint8)
            r1 = await c.router.submit("t0", b"\x01" * 16, b"\x02" * 16, pt)
            # Single backend: the hung request itself exhausts (it
            # already tried the only backend — the lane rule), coded by
            # what stopped it...
            assert r1.error == "deadline"
            assert c.router.quarantine_events() == 1
            # ...but the NEXT request's rescue canary re-proves the
            # quarantined backend instead of answering errors forever —
            # a single-backend deployment self-heals.
            r2 = await c.router.submit("t0", b"\x01" * 16, b"\x02" * 16, pt)
            assert r2.ok
            assert c.router.backends["b0"].health.state == health.PROBATION
            assert c.router.stats()["lost"] == 0

    asyncio.run(main())


# ---------------------------------------------------------------------------
# Backpressure propagation (fake backends speaking the wire protocol).
# ---------------------------------------------------------------------------


async def _fake_backend(answer):
    """A minimal wire-speaking backend: answers every request with
    ``answer(header, payload)`` -> (header dict, payload bytes)."""

    async def handle(reader, writer):
        try:
            while True:
                frame = await wire.read_frame(reader)
                if frame is None:
                    return
                h, p = answer(*frame)
                writer.write(wire.encode_frame(h, p))
                await writer.drain()
        except wire.WireError:
            pass
        finally:
            writer.close()

    srv = await asyncio.start_server(handle, "127.0.0.1", 0)
    return srv, srv.sockets[0].getsockname()[1]


def test_shed_propagates_retry_then_router_shed():
    async def main():
        def echo_or_shed(h, p):
            if h.get("t") == "_canary":
                return {"ok": True}, p  # canary: CTR of zeros under a
                #                          zero key is NOT all-zero, but
                #                          both fakes agree -> pinned
            return {"ok": False, "error": ERR_SHED, "detail": "full"}, b""

        s1, p1 = await _fake_backend(echo_or_shed)
        s2, p2 = await _fake_backend(echo_or_shed)
        router = Router(
            [BackendSpec("b0", "127.0.0.1", p1),
             BackendSpec("b1", "127.0.0.1", p2)],
            RouterConfig(gossip_every_s=0.0, attempt_timeout_s=1.0,
                         shed_backoff_s=0.001))
        await router.start()
        resp = await router.submit("t0", b"\x01" * 16, b"\x02" * 16,
                                   np.zeros(64, np.uint8))
        # Both replicas shed -> the router sheds, through the ledger;
        # health is UNTOUCHED (shed is the queue working, not sickness).
        assert resp.error == ERR_SHED
        st = router.stats()
        assert st["shed_retries"] >= 1 and st["router_sheds"] == 1
        assert all(b["state"] == health.HEALTHY
                   for b in st["backends"].values())
        assert "route->shed" in degrade.events()
        await router.stop()
        s1.close()
        s2.close()

    asyncio.run(main())


def test_join_canary_mismatch_quarantines_new_backend():
    async def main():
        ok = lambda h, p: ({"ok": True}, p)
        corrupt = lambda h, p: ({"ok": True}, b"\xff" * len(p))
        s1, p1 = await _fake_backend(ok)
        s2, p2 = await _fake_backend(corrupt)
        router = Router([BackendSpec("b0", "127.0.0.1", p1)],
                        RouterConfig(gossip_every_s=0.0,
                                     attempt_timeout_s=1.0))
        await router.start()
        # A joiner must match the PINNED canary bytes before placement
        # trusts it: the corrupt one starts quarantined.
        await router.add_backend(BackendSpec("b1", "127.0.0.1", p2))
        assert router.backends["b1"].health.state == health.QUARANTINED
        assert "quarantined:backend:b1" in degrade.events()
        await router.stop()
        s1.close()
        s2.close()

    asyncio.run(main())


# ---------------------------------------------------------------------------
# Membership, drain, status, journal.
# ---------------------------------------------------------------------------


def test_membership_change_traces_minimal_motion(traced):
    async def main():
        ok = lambda h, p: ({"ok": True}, p)
        srvs = []
        specs = []
        for i in range(3):
            s, p = await _fake_backend(ok)
            srvs.append(s)
            specs.append(BackendSpec(f"b{i}", "127.0.0.1", p))
        router = Router(specs[:2],
                        RouterConfig(gossip_every_s=0.0,
                                     attempt_timeout_s=1.0))
        await router.start()
        for t in range(40):  # populate the tracked-key sample
            await router.submit(f"t{t}", b"\x01" * 16, b"\x02" * 16,
                                np.zeros(16, np.uint8))
        await router.add_backend(specs[2])
        assert list(router.ring.members()) == ["b0", "b1", "b2"]
        router.remove_backend("b2")
        assert router.ring_changes == 2
        await router.stop()
        for s in srvs:
            s.close()

    asyncio.run(main())
    run = export.load_run(str(traced))
    rebal = [p["attrs"] for p in run.points("ring-rebalance")]
    assert [a["action"] for a in rebal] == ["join", "leave"]
    join = rebal[0]
    assert join["tracked"] == 40
    # Minimal motion: the joiner stole ~K/3 of the tracked keys — and
    # never more than the whole sample (a naive mod-N rehash moves
    # ~2/3; the bound splits the difference decisively).
    assert 0 < join["moved"] <= join["tracked"] * 0.6


def test_drain_answers_everything_and_refuses_new(traced):
    async def main():
        async with Cluster(n=2) as c:
            pt = np.zeros(1024, np.uint8)
            pending = [asyncio.ensure_future(c.router.submit(
                f"t{i}", b"\x01" * 16, b"\x02" * 16, pt))
                for i in range(16)]
            stop = asyncio.ensure_future(c.router.stop())
            done = await asyncio.gather(*pending)
            await stop
            # Every in-flight rider answered; the ledger balances.
            assert all(r.ok for r in done)
            assert c.router.accepted == c.router.answered == 16
            late = await c.router.submit("tx", b"\x01" * 16,
                                         b"\x02" * 16, pt)
            assert late.error == "shutdown"

    asyncio.run(main())
    run = export.load_run(str(traced))
    drained = run.points("route-drained")
    assert drained and drained[-1]["attrs"]["lost"] == 0


def test_router_healthz_membership_view_and_draining():
    async def main():
        async with Cluster(n=2) as c:
            status = RouterStatus(c.router, 0)
            await status.start()

            async def get(path):
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", status.port)
                writer.write(f"GET {path} HTTP/1.1\r\n\r\n"
                             .encode("latin-1"))
                await writer.drain()
                raw = await reader.read(1 << 20)
                writer.close()
                head, _, body = raw.partition(b"\r\n\r\n")
                return head, body

            for t in range(8):
                await c.router.submit(f"t{t}", b"\x01" * 16, b"\x02" * 16,
                                      np.zeros(16, np.uint8))
            head, body = await get("/healthz")
            assert head.startswith(b"HTTP/1.1 200")
            doc = json.loads(body)
            # The membership view: ring + per-backend placement + states
            # readable WITHOUT traces.
            assert doc["status"] == "ok"
            assert doc["ring"]["members"] == ["b0", "b1"]
            assert doc["ring"]["tracked_keys"] == 8
            assert sum(doc["ring"]["placement"].values()) == 8
            assert set(doc["backends"]) == {"b0", "b1"}
            assert all(b["state"] == "healthy"
                       for b in doc["backends"].values())
            head, body = await get("/metrics")
            assert head.startswith(b"HTTP/1.1 200")
            assert b"route_affinity" in body
            await c.router.stop()
            _, body = await get("/healthz")
            assert json.loads(body)["status"] == "draining"
            await status.stop()

    asyncio.run(main())


def test_journal_quarantine_persists_and_unquarantine(
        monkeypatch, tmp_path, capsys):
    jpath = str(tmp_path / "route.journal")

    async def phase1():
        async with Cluster(n=2, journal=jpath) as c:
            tenant = _tenant_for(c.router, "b1", b"\x01" * 16)
            monkeypatch.setenv("OT_FAULTS", "backend_hang:1@backend=1")
            faults.reset()
            c.router.config.attempt_timeout_s = 0.5
            resp = await c.router.submit(tenant, b"\x01" * 16,
                                         b"\x02" * 16,
                                         np.zeros(64, np.uint8))
            assert resp.ok
            assert c.router.backends["b1"].health.state == \
                health.QUARANTINED

    async def phase2():
        async with Cluster(n=2, journal=jpath) as c:
            # The restart adopts the RECORDED quarantine — no live
            # failure needed, same journal rows as lanes/sweep units.
            assert c.router.backends["b1"].health.state == \
                health.QUARANTINED

    asyncio.run(phase1())
    monkeypatch.delenv("OT_FAULTS")
    faults.reset()
    asyncio.run(phase2())
    # The shared release edit, through the bench CLI.
    rc = route_bench.main(["--journal", jpath,
                           "--unquarantine", "backend:b1"])
    assert rc == 0
    assert "cleared 1 failure row(s)" in capsys.readouterr().out

    async def phase3():
        async with Cluster(n=2, journal=jpath) as c:
            assert c.router.backends["b1"].health.state == health.HEALTHY

    asyncio.run(phase3())


# ---------------------------------------------------------------------------
# The worker frontend's wire containment.
# ---------------------------------------------------------------------------


def test_frontend_refuses_torn_and_oversized_frames():
    async def main():
        s = Server(ServerConfig(**LADDER))
        await s.start()
        f = RequestFrontend(s, 0)
        await f.start()
        # Oversized header line: refused as a protocol error; the
        # server keeps serving on a fresh connection.
        reader, writer = await asyncio.open_connection("127.0.0.1", f.port)
        writer.write(b"x" * (wire.MAX_HEADER + 10) + b"\n")
        await writer.drain()
        frame = await wire.read_frame(reader)
        assert frame is not None and frame[0]["ok"] is False
        writer.close()
        # A clean exchange still works after the bad peer.
        reader, writer = await asyncio.open_connection("127.0.0.1", f.port)
        writer.write(wire.encode_frame(
            {"t": "t0", "k": (b"\x01" * 16).hex(),
             "n": (b"\x02" * 16).hex()}, b"\x00" * 64))
        await writer.drain()
        h, body = await wire.read_frame(reader)
        assert h["ok"] and len(body) == 64
        writer.close()
        assert f.protocol_errors == 1
        s.queue.close()
        await f.stop()
        await s.stop()

    asyncio.run(main())
