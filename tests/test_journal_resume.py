"""Sweep journal end-to-end (harness.bench --journal): a killed sweep
resumed from its journal must reproduce the uninterrupted corpus byte for
byte, and a changed config must invalidate the journal.

Determinism comes from two seams: OT_FAKE_TIME_US pins every timed region
to a fixed µs value (the work still runs; only the clock is faked), and
the shared RNG stream is restored from the journal on resume. The portable
C path (OT_C_FORCE_PORTABLE=1) slows the rows enough that SIGTERM reliably
lands mid-sweep.
"""

import json
import os
import pathlib
import signal
import subprocess
import sys
import time

ROOT = pathlib.Path(__file__).resolve().parent.parent

#: Sweep config shared by every run in this file: 3 modes x 2 sizes
#: (+ shard-invariance + self-test = 8 units), portable-C rows slow enough
#: to interrupt, fake clock for byte-comparable output.
ARGS = ["--backend", "c", "--modes", "ecb,ctr,rc4",
        "--sizes-mb", "0.0625,16", "--workers", "1,2", "--iters", "3"]
ENV = {"OT_FAKE_TIME_US": "7", "OT_C_FORCE_PORTABLE": "1",
       "JAX_PLATFORMS": "cpu"}


def _cmd(out, journal, extra=()):
    return [sys.executable, "-m", "our_tree_tpu.harness.bench",
            *ARGS, "--out", str(out), "--journal", str(journal), *extra]


def _env():
    env = dict(os.environ, PYTHONPATH="")
    env.update(ENV)
    return env


def _entries(journal_path):
    with open(journal_path) as f:
        return [json.loads(line) for line in f]


def _units(journal_path):
    """Completed-UNIT records only: the journal also carries intra-unit
    worker-row checkpoints (``"row"`` records, docs/OBSERVABILITY.md)
    and failure rows, neither of which is a completed unit."""
    return [e for e in _entries(journal_path)[1:]
            if e.get("row") is None and not e.get("failed")]


def test_sigterm_resume_reproduces_uninterrupted_corpus(tmp_path):
    # 1. The uninterrupted reference corpus.
    ref = tmp_path / "ref.txt"
    subprocess.run(_cmd(ref, tmp_path / "jref.jsonl"), env=_env(), cwd=ROOT,
                   capture_output=True, text=True, timeout=420, check=True)
    ref_bytes = ref.read_bytes()
    n_units = len(_units(tmp_path / "jref.jsonl"))
    assert n_units == 8

    # 2. Same sweep, SIGTERMed mid-run: poll the journal until at least
    # two units committed, then kill. fsync-per-entry makes the poll a
    # reliable progress signal.
    journal = tmp_path / "j.jsonl"
    proc = subprocess.Popen(_cmd(tmp_path / "b.txt", journal), env=_env(),
                            cwd=ROOT, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 300
        while time.time() < deadline:
            try:
                if len(_units(journal)) >= 2:  # >= 2 completed units
                    break
            except (OSError, ValueError):
                pass
            if proc.poll() is not None:
                raise AssertionError(
                    "sweep finished before it could be interrupted — "
                    "slow the rows down")
            time.sleep(0.01)
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
    finally:
        proc.kill()
    assert rc != 0  # killed, not completed
    done = len(_units(journal))
    assert 2 <= done < n_units  # genuinely mid-sweep

    # 3. Resume: completed rows are skipped, the corpus is byte-identical.
    out2 = tmp_path / "resumed.txt"
    res = subprocess.run(_cmd(out2, journal), env=_env(), cwd=ROOT,
                         capture_output=True, text=True, timeout=420,
                         check=True)
    assert f"# journal: {done} completed unit(s) on file" in res.stderr
    assert f"# journal: skipped {done} completed unit(s)" in res.stderr
    assert out2.read_bytes() == ref_bytes
    # ...and the journal now holds every unit exactly once, in order.
    names = [e["unit"] for e in _units(journal)]
    assert names == [e["unit"] for e in _units(tmp_path / "jref.jsonl")]


def test_replay_restores_degraded_record(tmp_path):
    """A demotion stamped into a journaled unit must survive resume: the
    replayed run restores the entry's degraded kinds into the live ledger,
    so the corpus trailer (`# degraded: ...`) matches what the original
    degraded run emitted — a resumed fallback run can't launder itself
    into a healthy-looking corpus."""
    journal = tmp_path / "j.jsonl"
    quick = ["--backend", "c", "--modes", "ecb", "--sizes-mb", "0.0625",
             "--workers", "1", "--iters", "2", "--journal", str(journal)]
    subprocess.run(
        [sys.executable, "-m", "our_tree_tpu.harness.bench", *quick,
         "--out", str(tmp_path / "a.txt")],
        env=_env(), cwd=ROOT, capture_output=True, timeout=300, check=True)
    # Doctor the recorded unit as if it had run degraded (backend c never
    # degrades on this host, so the record is planted by hand).
    lines = open(journal).read().splitlines()
    entry = json.loads(lines[1])
    entry["degraded"] = ["native->lax.scan"]
    with open(journal, "w") as f:
        f.write(lines[0] + "\n" + json.dumps(entry) + "\n")
    res = subprocess.run(
        [sys.executable, "-m", "our_tree_tpu.harness.bench", *quick,
         "--out", str(tmp_path / "b.txt")],
        env=_env(), cwd=ROOT, capture_output=True, text=True, timeout=300,
        check=True)
    assert "skipped 1 completed unit" in res.stderr
    out = (tmp_path / "b.txt").read_text().splitlines()
    assert "# degraded: native->lax.scan" in out


def test_changed_config_invalidates_journal(tmp_path):
    journal = tmp_path / "j.jsonl"
    quick = ["--backend", "c", "--modes", "ecb", "--sizes-mb", "0.0625",
             "--workers", "1", "--iters", "2"]
    subprocess.run(
        [sys.executable, "-m", "our_tree_tpu.harness.bench", *quick,
         "--seed", "1", "--out", str(tmp_path / "a.txt"),
         "--journal", str(journal)],
        env=_env(), cwd=ROOT, capture_output=True, text=True, timeout=300,
        check=True)
    hash1 = _entries(journal)[0]["config_hash"]
    state1 = _entries(journal)[1]["rng_state"]
    assert len(_entries(journal)) == 2  # header + the one unit
    # Same journal path, different seed: nothing may be replayed.
    res = subprocess.run(
        [sys.executable, "-m", "our_tree_tpu.harness.bench", *quick,
         "--seed", "2", "--out", str(tmp_path / "b.txt"),
         "--journal", str(journal)],
        env=_env(), cwd=ROOT, capture_output=True, text=True, timeout=300,
        check=True)
    assert "resuming" not in res.stderr
    entries = _entries(journal)
    assert entries[0]["config_hash"] != hash1  # restarted for the new config
    assert len(entries) == 2
    # Different seed -> a different RNG trajectory recorded: proof the
    # second run executed its unit rather than replaying the first's (the
    # visible rows are seed-independent under the fake clock, so the
    # corpus bytes cannot tell — the journal's own state can).
    assert entries[1]["rng_state"] != state1
