"""Circuit-level regression net: exhaustive S-box checks + vector-op budgets.

Runs the bitsliced circuits on 256-bit Python ints (one bit per test case —
ints support ^/&, which is all the circuit primitives use), so the whole
exhaustive check costs milliseconds instead of the minutes the jax version
takes, and every vector op can be *counted*. The op-count assertions guard
the throughput engines' arithmetic budget: on TPU the bitsliced round is
issue-limited, so a silent +20% in ops is a silent -20% in GB/s
(docs/ENGINES.md records the measured sizes these bounds protect).
"""

import types

import numpy as np
import pytest

from our_tree_tpu.ops import bitslice, tables

MASK = (1 << 256) - 1


class OpInt(int):
    """int wrapper counting XOR/AND ops globally."""

    counts = {"xor": 0, "and": 0, "or": 0}

    def __xor__(self, o):
        OpInt.counts["xor"] += 1
        return OpInt(int(self) ^ int(o))

    __rxor__ = __xor__

    def __and__(self, o):
        OpInt.counts["and"] += 1
        return OpInt(int(self) & int(o))

    __rand__ = __and__

    def __or__(self, o):
        OpInt.counts["or"] += 1
        return OpInt(int(self) | int(o))

    __ror__ = __or__


def _reset():
    OpInt.counts = {"xor": 0, "and": 0, "or": 0}


def _total():
    return sum(OpInt.counts.values())


def _planes_all_bytes():
    # plane[i] = int whose bit v (v in 0..255) is bit i of byte value v.
    return [OpInt(sum(((v >> i) & 1) << v for v in range(256)))
            for i in range(8)]


def _extract(planes) -> np.ndarray:
    return np.array([
        sum(((int(planes[i]) >> v) & 1) << i for i in range(len(planes)))
        for v in range(256)
    ])


def _mk_round_planes(seed):
    """(8, 16) object planes of distinct OpInt bitsets for round-cost tests."""
    arr = np.empty((8, 16), dtype=object)
    for b in range(8):
        for pos in range(16):
            arr[b, pos] = OpInt((seed + b * 16 + pos)
                                * 0x9E3779B97F4A7C15 & MASK)
    return arr


def _perm_stack(x, idx):
    return np.array([x[int(j)] for j in idx], dtype=object)


@pytest.fixture
def int_circuit(monkeypatch):
    """Route the circuit's few jnp touchpoints to int-compatible stubs."""
    stub = types.SimpleNamespace(
        uint32=lambda v=0: OpInt(v),
        zeros_like=lambda x: OpInt(0),
        full_like=lambda x, v: OpInt(MASK if v else 0),
        stack=lambda xs, axis=0: list(xs),
    )
    monkeypatch.setattr(bitslice, "jnp", stub)
    monkeypatch.setattr(
        bitslice, "xor_const",
        lambda p, c: [x ^ OpInt(MASK) if (c >> i) & 1 else x
                      for i, x in enumerate(p)],
    )
    _reset()


def test_sbox_exhaustive_and_budget(int_circuit):
    out = _extract(bitslice.sbox_planes(_planes_all_bytes()))
    np.testing.assert_array_equal(out, np.asarray(tables.SBOX))
    assert _total() <= 180, f"forward S-box grew to {_total()} vector ops"


def test_inv_sbox_exhaustive_and_budget(int_circuit):
    out = _extract(bitslice.inv_sbox_planes(_planes_all_bytes()))
    np.testing.assert_array_equal(out, np.asarray(tables.INV_SBOX))
    assert _total() <= 185, f"inverse S-box grew to {_total()} vector ops"


def test_sbox_chain_formulation_exhaustive(int_circuit, monkeypatch):
    monkeypatch.setattr(bitslice, "SBOX_IMPL", "chain")
    out = _extract(bitslice.sbox_planes(_planes_all_bytes()))
    np.testing.assert_array_equal(out, np.asarray(tables.SBOX))


def test_sbox_bp_formulation_exhaustive_and_budget(int_circuit, monkeypatch):
    """Boyar–Peralta circuit: all 256 inputs + the op budget it exists for
    (115 core gates + the 4 affine-constant complements = 119, vs the
    tower's 174)."""
    monkeypatch.setattr(bitslice, "SBOX_IMPL", "bp")
    out = _extract(bitslice.sbox_planes(_planes_all_bytes()))
    np.testing.assert_array_equal(out, np.asarray(tables.SBOX))
    assert _total() <= 120, f"BP S-box grew to {_total()} vector ops"
    assert OpInt.counts["and"] == 32, "BP nonlinearity must stay 32 ANDs"


def test_sbox_bp_inverse_falls_back_exhaustive(int_circuit, monkeypatch):
    """Under OT_SBOX=bp the inverse S-box keeps the tower formulation and
    must still be exhaustively correct."""
    monkeypatch.setattr(bitslice, "SBOX_IMPL", "bp")
    out = _extract(bitslice.inv_sbox_planes(_planes_all_bytes()))
    np.testing.assert_array_equal(out, np.asarray(tables.INV_SBOX))


def test_round_budget(int_circuit):
    """Full rounds on (8, 16) object planes; budget in (16, W)-op units."""
    for fn, budget in ((bitslice.encrypt_round, 230),
                       (bitslice.decrypt_round, 250)):
        _reset()
        fn(_mk_round_planes(3), _mk_round_planes(5), False,
           perm=_perm_stack, mc="perm")
        per16 = _total() / 16
        assert per16 <= budget, f"{fn.__name__} grew to {per16:.0f} ops"


def test_round_budget_bp(int_circuit, monkeypatch):
    """Encrypt round under the Boyar–Peralta S-box: the 174 -> 119 S-box cut
    must show up as a ~162-unit round (the whole point of OT_SBOX=bp)."""
    monkeypatch.setattr(bitslice, "SBOX_IMPL", "bp")
    _reset()
    bitslice.encrypt_round(_mk_round_planes(3), _mk_round_planes(5), False,
                           perm=_perm_stack, mc="perm")
    per16 = _total() / 16
    assert per16 <= 175, f"bp encrypt_round grew to {per16:.0f} ops"
