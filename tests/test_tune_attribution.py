"""Ranking attribution rules of the tuning sweep (scripts/tune_tpu.py).

The persisted engine ranking must only hold rows the production path can
reproduce — since round 4 that means rows measured at the knob setting the
sweep persists (pallas_aes.apply_stored_knobs re-applies it everywhere),
with engines that ignore the Pallas knobs attributable from any row. These
tests pin the attribution function directly; the sweep's subprocess grid is
exercised on hardware by the watcher plan.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))

from tune_tpu import _rankable_engine_name  # noqa: E402


def test_pallas_rows_attributed_only_at_ref_knobs():
    assert _rankable_engine_name(
        "pallas-dense", 2048, "roll", "tower", "1", 2048, "roll"
    ) == "pallas-dense"
    # Off-reference tile or MC: not reproducible by the applied config.
    assert _rankable_engine_name(
        "pallas-dense", 1024, "roll", "tower", "1", 2048, "roll") is None
    assert _rankable_engine_name(
        "pallas-dense", 2048, "perm", "tower", "1", 2048, "roll") is None


def test_knob_blind_engines_attributed_from_any_row():
    # bitslice ignores OT_PALLAS_TILE/MC — every such row measures the
    # identical code, so any (tile, mc) qualifies...
    assert _rankable_engine_name(
        "bitslice", 512, "perm", "tower", "1", 2048, "roll") == "bitslice"
    # ...but unroll IS read by bitslice and nothing re-applies it.
    assert _rankable_engine_name(
        "bitslice", 512, "perm", "tower", "2", 2048, "roll") is None


def test_bp_sbox_maps_to_registered_bp_engine():
    assert _rankable_engine_name(
        "pallas-gt", 1024, "perm", "bp", "1", 1024, "perm"
    ) == "pallas-gt-bp"
    # No registered bp twin (no Boyar-Peralta bitslice engine): dropped.
    assert _rankable_engine_name(
        "bitslice", 1024, "perm", "bp", "1", 1024, "perm") is None


def test_parent_default_knobs_match_library():
    """tune_tpu's parent stays jax-free, so it mirrors the library's
    default knobs by hand (_DEFAULT_TILE/_DEFAULT_MC/_DEFAULT_UNROLL). If
    the library defaults drift, sweep attribution and the knobs_changed
    computation silently diverge (ADVICE r4 #2) — pin them equal here,
    where importing jax is fine."""
    import tune_tpu

    from our_tree_tpu.ops import bitslice, pallas_aes

    assert tune_tpu._DEFAULT_TILE == pallas_aes.DEFAULT_TILE
    assert tune_tpu._DEFAULT_MC == pallas_aes.DEFAULT_MC
    # The parent mirrors unroll in env-string form.
    assert int(tune_tpu._DEFAULT_UNROLL) == bitslice.DEFAULT_UNROLL
