"""ARC4 tests: Rescorla vectors (reference arc4.c:124-143) + phase-split
semantics (setup/prep/crypt, arc4.c:43-112) + resume state."""

import numpy as np

from our_tree_tpu.models.arc4 import ARC4, key_schedule, keystream_np

# The three vectors posted by Eric Rescorla (Sep 1994), as carried by the
# reference's arc4_self_test.
RESCORLA = [
    ("0123456789abcdef", "0123456789abcdef", "75b7878099e0c596"),
    ("0123456789abcdef", "0000000000000000", "7494c2e7104b0879"),
    ("0000000000000000", "0000000000000000", "de188941a3375d3a"),
]


def test_rescorla_vectors_scan():
    for keyh, pth, cth in RESCORLA:
        r = ARC4(bytes.fromhex(keyh))
        ks = r.prep(8)
        out = r.crypt(bytes.fromhex(pth), ks)
        assert out.tobytes().hex() == cth


def test_rescorla_vectors_numpy():
    for keyh, pth, cth in RESCORLA:
        r = ARC4(bytes.fromhex(keyh))
        ks = r.prep(8, backend="np")
        out = np.bitwise_xor(np.frombuffer(bytes.fromhex(pth), np.uint8), ks)
        assert out.tobytes().hex() == cth


def test_scan_matches_numpy_long():
    key = bytes(range(13))
    a, b = ARC4(key), ARC4(key)
    assert a.prep(1000).tobytes() == b.prep(1000, backend="np").tobytes()
    # state carried identically
    assert (a.x, a.y) == (b.x, b.y)
    assert np.array_equal(a.m, b.m)


def test_prep_resume():
    """Chunked keystream generation must equal one-shot — the {x, y, m}
    carry contract (arc4.c:93-94)."""
    key = b"resume-key"
    one = ARC4(key).prep(500)
    r = ARC4(key)
    parts = [r.prep(n) for n in (1, 99, 150, 250)]
    assert np.concatenate(parts).tobytes() == one.tobytes()


def test_crypt_roundtrip():
    rng = np.random.default_rng(9)
    data = rng.integers(0, 256, 333, dtype=np.uint8)
    ct = ARC4(b"k" * 16).crypt(data.tobytes())
    pt = ARC4(b"k" * 16).crypt(ct.tobytes())
    assert pt.tobytes() == data.tobytes()


def test_key_schedule_identity_permutation_property():
    m = key_schedule(b"\x00")
    assert sorted(m.tolist()) == list(range(256))


def test_prep_batch_matches_single_streams():
    from our_tree_tpu.models.arc4 import ARC4

    keys = [b"stream-a", b"stream-b", b"stream-c-longer"]
    batch = ARC4.prep_batch(keys, 512)
    assert batch.shape == (3, 512)
    for i, k in enumerate(keys):
        np.testing.assert_array_equal(batch[i], ARC4(k).prep(512))
