"""Multi-grid-step Pallas gauntlets (interpreter mode on CPU), all engines.

Split out of test_pallas.py (VERDICT r3 weak #4/#8): these three gauntlets
share the (32*384, 4) boundary shape and TILE=128, so the T-table reference
compilations (jnp ECB encrypt + jnp fused CTR at that shape) are compiled
ONCE here and reused across all three — under test_pallas.py's per-test
`jax.clear_caches()` mitigation they were recompiled per test. Keeping the
heaviest interpreter-mode compiles in their own module also re-bounds
XLA-CPU's accumulated compiler state at module granularity (the crash class
tests/conftest.py documents) without the per-test hammer.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from our_tree_tpu.models import aes as aes_mod
from our_tree_tpu.ops.keyschedule import expand_key_dec, expand_key_enc


@pytest.mark.slow
def test_pallas_ctr_gen_multi_grid_step(monkeypatch):
    """Counter synthesis across grid steps: with a 128-lane tile, 12288
    blocks give a 3-step grid, so the in-kernel block index j = 32*(g*tile
    + lane) + t must mix the program_id into the adder correctly for g > 0
    (a bug there is invisible to single-tile tests)."""
    from our_tree_tpu.ops import pallas_aes
    from our_tree_tpu.utils import packing

    monkeypatch.setattr(pallas_aes, "TILE", 128)
    rng = np.random.default_rng(5)
    nr, rk = expand_key_enc(bytes(range(16)))
    rk = jnp.asarray(rk)

    nonce = np.frombuffer(bytes(range(100, 116)), dtype=np.uint8)
    ctr_be = jnp.asarray(packing.np_bytes_to_words(nonce).byteswap())
    w = jnp.asarray(rng.integers(0, 2**32, (32 * 384, 4)).astype(np.uint32))
    got = np.asarray(pallas_aes.ctr_crypt_words_gen(w, ctr_be, rk, nr))
    want = np.asarray(aes_mod.ctr_crypt_words(w, ctr_be, rk, nr, "jnp"))
    np.testing.assert_array_equal(got, want)


@pytest.mark.slow
def test_pallas_dense_engine_matches_jnp(monkeypatch):
    """Dense-boundary kernels ((128, W) layout, in-kernel ladder via
    bitslice.transpose32_dense) vs the T-table core: ECB both directions
    and counter-synthesising CTR (both S-box variants), 3-step grid, near-
    wraparound nonce — the same gauntlet as the grouped twin below, since
    the dense engine exists to replace it (VERDICT r2 #3)."""
    from our_tree_tpu.ops import pallas_aes
    from our_tree_tpu.utils import packing

    monkeypatch.setattr(pallas_aes, "TILE", 128)
    rng = np.random.default_rng(29)
    nr, rk = expand_key_enc(bytes(range(16)))
    rk = jnp.asarray(rk)
    _, rk_dec = expand_key_dec(bytes(range(16)))
    rk_dec = jnp.asarray(rk_dec)
    nonce = np.frombuffer(
        bytes.fromhex("00000000fffffffffffffffffffffff0"), np.uint8)
    ctr_be = jnp.asarray(packing.np_bytes_to_words(nonce).byteswap())
    w = jnp.asarray(rng.integers(0, 2**32, (32 * 384, 4)).astype(np.uint32))

    got = np.asarray(pallas_aes.encrypt_words_dense(w, rk, nr))
    want = np.asarray(aes_mod.ecb_encrypt_words(w, rk, nr, "jnp"))
    np.testing.assert_array_equal(got, want)
    back = np.asarray(
        pallas_aes.decrypt_words_dense(jnp.asarray(got), rk_dec, nr))
    np.testing.assert_array_equal(back, np.asarray(w))

    want = np.asarray(aes_mod.ctr_crypt_words(w, ctr_be, rk, nr, "jnp"))
    got = np.asarray(pallas_aes.ctr_crypt_words_dense(w, ctr_be, rk, nr))
    np.testing.assert_array_equal(got, want)
    got = np.asarray(pallas_aes.ctr_crypt_words_dense_bp(w, ctr_be, rk, nr))
    np.testing.assert_array_equal(got, want)


@pytest.mark.slow
def test_pallas_gt_engine_matches_jnp(monkeypatch):
    """Grouped-transpose kernels (in-kernel SWAR ladder) vs the T-table
    core: ECB both directions and counter-synthesising CTR, with a 3-step
    grid so the lane/program_id bookkeeping is exercised past tile 0."""
    from our_tree_tpu.ops import pallas_aes
    from our_tree_tpu.utils import packing

    monkeypatch.setattr(pallas_aes, "TILE", 128)
    rng = np.random.default_rng(23)
    nr, rk = expand_key_enc(bytes(range(16)))
    rk = jnp.asarray(rk)
    _, rk_dec = expand_key_dec(bytes(range(16)))
    rk_dec = jnp.asarray(rk_dec)
    # Near-wraparound nonce: the in-kernel ripple adder must carry across
    # words exactly like ctr_le_blocks.
    nonce = np.frombuffer(
        bytes.fromhex("00000000fffffffffffffffffffffff0"), np.uint8)
    ctr_be = jnp.asarray(packing.np_bytes_to_words(nonce).byteswap())
    w = jnp.asarray(rng.integers(0, 2**32, (32 * 384, 4)).astype(np.uint32))

    got = np.asarray(pallas_aes.encrypt_words_gt(w, rk, nr))
    want = np.asarray(aes_mod.ecb_encrypt_words(w, rk, nr, "jnp"))
    np.testing.assert_array_equal(got, want)
    back = np.asarray(pallas_aes.decrypt_words_gt(jnp.asarray(got), rk_dec, nr))
    np.testing.assert_array_equal(back, np.asarray(w))

    got = np.asarray(pallas_aes.ctr_crypt_words_gt(w, ctr_be, rk, nr))
    want = np.asarray(aes_mod.ctr_crypt_words(w, ctr_be, rk, nr, "jnp"))
    np.testing.assert_array_equal(got, want)
