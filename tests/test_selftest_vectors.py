"""The reference's full `aes_self_test` vector suite, ported (SURVEY.md §4).

These are the NIST rijndael-vals 10,000-iteration chained ECB/CBC vectors
and the RFC 3686 CTR vectors compiled into the reference
(aes-modes/aes.c:912-1081) but never called by any of its mains. Here they
run in CI, with the 10k chains expressed the TPU way: a `lax.fori_loop`
over the block cipher inside one jit, not 10,000 host round-trips.

Chaining schemes per aes_self_test (aes.c:1106-1230):
  ECB: buf <- crypt(buf), 10000x, zero key/buf.
  CBC dec: iv/prv/buf zero; buf <- D_cbc(buf) with iv carried.
  CBC enc: encrypt-then-swap — input alternates with the previous round's
           input (prv), the classic chained-MCT shape.

Also: fused RC4 (models/rc4.py) vs the phase-split path, and the on-device
key schedule vs the host one.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from our_tree_tpu.models.arc4 import ARC4
from our_tree_tpu.models.rc4 import RC4
from our_tree_tpu.ops import block
from our_tree_tpu.ops.keyschedule import (
    expand_key_dec,
    expand_key_dec_device,
    expand_key_enc,
    expand_key_enc_device,
)
from our_tree_tpu.utils import packing

# aes.c:912-950 (NIST rijndael-vals chained results, zero key, 10k iters)
ECB_ENC = [
    "c34c052cc0da8d73451afe5f03be297f",
    "f3f6752ae8d7831138f041560631b114",
    "8b79eecc93a0ee5dff30b4ea21636da4",
]
ECB_DEC = [
    "44416ac2d1f53c583303917e6be9ebe0",
    "48e31e9e256718f29229319c19f15ba4",
    "058ccffdbbcb382d1f6f56585d8a4ade",
]
CBC_ENC = [
    "8a05fc5e095af4848a08d328d3688e3d",
    "7bd966d53ad8c1bb85d2adfae87bb104",
    "fe3c53653e2f45b56fcd88b2cc898ff0",
]
CBC_DEC = [
    "faca37e0b0c85373df706e73f7c9af86",
    "5df678dd17ba4e75b61768c6adef7c7b",
    "4804e1818fe6297519a3e88c57310413",
]

# RFC 3686 vectors 1-3 (aes.c:1022-1080)
CTR_VECTORS = [
    ("ae6852f8121067cc4bf7a5765577f39e",
     "00000030000000000000000000000001",
     "53696e676c6520626c6f636b206d7367",
     "e4095d4fb7a7b3792d6175a3261311b8"),
    ("7e24067817fae0d743d6ce1f32539163",
     "006cb6dbc0543b59da48d90b00000001",
     "000102030405060708090a0b0c0d0e0f101112131415161718191a1b1c1d1e1f",
     "5104a106168a72d9790d41ee8edad388eb2e1efc46da57c8fce630df9141be28"),
    ("7691be035e5020a8ac6e618529f9a0dc",
     "00e0017b27777f3f4a1786f000000001",
     "000102030405060708090a0b0c0d0e0f101112131415161718191a1b1c1d1e1f"
     "20212223",
     "c1cf48a89f2ffdd9cf4652e9efdb72d74540a42bde6d7836d59a5ceaaef31053"
     "25b2072f"),
]

KEY_BITS = [128, 192, 256]


def _zero_key_schedules(bits):
    key = bytes(bits // 8)
    nr, rk = expand_key_enc(key)
    _, rkd = expand_key_dec(key)
    return nr, jnp.asarray(rk), jnp.asarray(rkd)


@pytest.mark.parametrize("idx,bits", list(enumerate(KEY_BITS)))
def test_nist_chained_ecb(idx, bits):
    nr, rk, rkd = _zero_key_schedules(bits)
    zero = jnp.zeros((1, 4), jnp.uint32)

    @jax.jit
    def chain_enc(buf):
        return jax.lax.fori_loop(
            0, 10000, lambda _, b: block.encrypt_words(b, rk, nr), buf
        )

    @jax.jit
    def chain_dec(buf):
        return jax.lax.fori_loop(
            0, 10000, lambda _, b: block.decrypt_words(b, rkd, nr), buf
        )

    got_e = packing.np_words_to_bytes(np.asarray(chain_enc(zero))).tobytes()
    assert got_e.hex() == ECB_ENC[idx]
    got_d = packing.np_words_to_bytes(np.asarray(chain_dec(zero))).tobytes()
    assert got_d.hex() == ECB_DEC[idx]


@pytest.mark.parametrize("idx,bits", list(enumerate(KEY_BITS)))
def test_nist_chained_cbc(idx, bits):
    nr, rk, rkd = _zero_key_schedules(bits)
    zero = jnp.zeros(4, jnp.uint32)

    @jax.jit
    def chain_dec(buf):
        # aes.c:1178-1187: buf <- D(buf) ^ iv; iv <- old buf.
        def body(_, c):
            iv, buf = c
            out = block.decrypt_words(buf[None], rkd, nr)[0] ^ iv
            return buf, out

        iv, buf = jax.lax.fori_loop(0, 10000, body, (zero, buf))
        return buf

    got_d = packing.np_words_to_bytes(np.asarray(chain_dec(zero))[None]).tobytes()
    assert got_d.hex() == CBC_DEC[idx]

    @jax.jit
    def chain_enc(buf):
        # aes.c:1190-1205: encrypt (buf^iv), then the next input is the
        # previous round's input (prv) — the classic chained-MCT swap.
        def body(_, c):
            iv, prv, buf = c
            ct = block.encrypt_words((buf ^ iv)[None], rk, nr)[0]
            return ct, ct, prv

        iv, prv, buf = jax.lax.fori_loop(0, 10000, body, (zero, zero, buf))
        return prv  # after the final swap, prv holds the last ciphertext

    got_e = packing.np_words_to_bytes(np.asarray(chain_enc(zero))[None]).tobytes()
    assert got_e.hex() == CBC_ENC[idx]


@pytest.mark.parametrize("key,nonce,pt,ct", CTR_VECTORS)
def test_rfc3686_ctr(key, nonce, pt, ct):
    from our_tree_tpu.models.aes import AES

    a = AES(bytes.fromhex(key))
    out, *_ = a.crypt_ctr(
        0,
        np.frombuffer(bytes.fromhex(nonce), np.uint8),
        np.zeros(16, np.uint8),
        np.frombuffer(bytes.fromhex(pt), np.uint8),
    )
    assert out.tobytes().hex() == ct


def test_fused_rc4_matches_phase_split():
    data = np.random.default_rng(21).integers(0, 256, 4096, np.uint8)
    fused = RC4(b"fused-vs-split").crypt(data)
    rc = ARC4(b"fused-vs-split")
    split = rc.crypt(data, rc.prep(data.size))
    np.testing.assert_array_equal(fused, split)
    # Resume semantics: two fused calls == one (state carries across calls,
    # like the dead reference rc4.c would have via its ctx).
    r2 = RC4(b"fused-vs-split")
    np.testing.assert_array_equal(
        np.concatenate([r2.crypt(data[:100]), r2.crypt(data[100:])]), fused
    )


def test_fused_rc4_rescorla():
    out = RC4(bytes.fromhex("0123456789abcdef")).crypt(
        bytes.fromhex("0123456789abcdef")
    )
    assert out.tobytes().hex() == "75b7878099e0c596"


@pytest.mark.parametrize("bits", KEY_BITS)
def test_device_key_schedule_matches_host(bits):
    key = np.random.default_rng(bits).integers(0, 256, bits // 8, np.uint8)
    kw = jnp.asarray(packing.np_bytes_to_words(key))
    nr_h, rk_h = expand_key_enc(key.tobytes())
    nr_d, rk_d = expand_key_enc_device(kw, bits)
    assert nr_h == nr_d
    np.testing.assert_array_equal(np.asarray(rk_d), rk_h)
    _, rkd_h = expand_key_dec(key.tobytes())
    _, rkd_d = expand_key_dec_device(kw, bits)
    np.testing.assert_array_equal(np.asarray(rkd_d), rkd_h)


def test_blockcipher_interface():
    """BlockCipher ABC parity (reference BlockCipher.h:31-107)."""
    from our_tree_tpu.models.base import (
        DIR_BOTH, DIR_DECRYPT, DIR_ENCRYPT, AESCipher, BlockCipher,
    )

    c = AESCipher()
    assert isinstance(c, BlockCipher)
    with pytest.raises(ValueError):
        c.encrypt(b"\x00" * 16)  # no key installed
    c.make_key(bytes(range(16)), DIR_ENCRYPT)
    assert (c.block_bits, c.block_size, c.key_bits, c.key_size) == (128, 16, 128, 16)
    ct = c.encrypt(bytes.fromhex("00112233445566778899aabbccddeeff"))
    assert ct.tobytes().hex() == "69c4e0d86a7b0430d8cdb78070b4c55a"
    with pytest.raises(ValueError):
        c.decrypt(ct)  # encrypt-only key, like DIR_ENCRYPT in the reference
    c.make_key(bytes(range(16)), DIR_BOTH)
    assert c.decrypt(ct).tobytes().hex() == "00112233445566778899aabbccddeeff"
