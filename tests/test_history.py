"""The perf-history ledger (our_tree_tpu/obs/history.py): artifact
parsing into classed trend series, best-ever gating (green and red),
count-metric strictness, tolerance parsing, and the committed repo
artifacts themselves staying green — the CI gate's own contract."""

import json
import os

import pytest

from our_tree_tpu.obs import history


def _write(root, name, doc):
    with open(os.path.join(root, name), "w") as fh:
        json.dump(doc, fh)


def _serve_doc(gbps, lost=0, steady=0, modes=("ctr",), sizes=(4096,),
               engine="native", lanes=8):
    return {
        "config": {"modes": list(modes), "sizes": list(sizes),
                   "engine": engine, "lanes": lanes},
        "load": {"goodput_gbps": gbps, "p50_ms": 1.0, "p95_ms": 2.0,
                 "p99_ms": 3.0, "errors": {}, "mismatches": 0},
        "queue": {"lost": lost},
        "compiles": {"steady": steady},
    }


def test_collect_classes_and_families(tmp_path):
    root = str(tmp_path)
    _write(root, "SERVE_r01.json", _serve_doc(1.0))
    _write(root, "SERVE_r02.json", _serve_doc(1.1))
    _write(root, "SERVE_r03.json", _serve_doc(0.1, modes=("ctr", "gcm")))
    _write(root, "SERVE_r02_control.json", _serve_doc(0.5))
    _write(root, "BENCH_r01.json",
           {"rc": 0, "parsed": {"value": 35.4, "unit": "GB/s"}})
    _write(root, "MULTICHIP_r01.json", {"n_devices": 8, "ok": True})
    _write(root, "notes.json", {"x": 1})  # not an artifact: ignored
    recs = history.collect(root)
    assert len(recs) == 6
    by_file = {r["file"]: r for r in recs}
    # The ctr and mixed drives form DIFFERENT series; the control
    # variant is its own lineage.
    assert (by_file["SERVE_r01.json"]["series"]
            == by_file["SERVE_r02.json"]["series"])
    assert (by_file["SERVE_r03.json"]["series"]
            != by_file["SERVE_r01.json"]["series"])
    assert ":control" in by_file["SERVE_r02_control.json"]["series"]
    assert by_file["BENCH_r01.json"]["metrics"]["gbps"] == 35.4
    assert by_file["MULTICHIP_r01.json"]["metrics"] == {
        "devices": 8.0, "ok": 1.0}


def test_check_green_within_tolerance_red_past_it(tmp_path):
    root = str(tmp_path)
    _write(root, "SERVE_r01.json", _serve_doc(1.0))
    _write(root, "SERVE_r02.json", _serve_doc(0.8))  # -20%: inside 35%
    recs = history.collect(root)
    assert history.check(recs) == []
    _write(root, "SERVE_r03.json", _serve_doc(0.5))  # -50%: regression
    recs = history.collect(root)
    fails = history.check(recs)
    assert len(fails) == 1
    # The failure names the artifact, the metric, and the best-ever.
    assert "SERVE_r03.json" in fails[0]
    assert "goodput_gbps" in fails[0]
    assert "SERVE_r01.json" in fails[0]


def test_check_gates_head_against_best_ever_not_last(tmp_path):
    """The whole point vs an SLO baseline: r03 regressing against r01's
    best still fails even though r02 (the would-be last baseline) was
    already lower."""
    root = str(tmp_path)
    _write(root, "SERVE_r01.json", _serve_doc(2.0))
    _write(root, "SERVE_r02.json", _serve_doc(1.4))
    _write(root, "SERVE_r03.json", _serve_doc(1.2))
    fails = history.check(history.collect(root))
    assert fails and "best-ever 2" in fails[0]


def test_count_metrics_tolerate_nothing(tmp_path):
    root = str(tmp_path)
    _write(root, "SERVE_r01.json", _serve_doc(1.0, lost=0))
    _write(root, "SERVE_r02.json", _serve_doc(1.0, lost=1))
    fails = history.check(history.collect(root))
    assert any("lost" in f and "no tolerance" in f for f in fails)
    # And a recompile regression in the head names recompiles.
    _write(root, "SERVE_r02.json", _serve_doc(1.0, steady=2))
    fails = history.check(history.collect(root))
    assert any("recompiles" in f for f in fails)


def test_unreadable_artifact_is_a_violation(tmp_path):
    root = str(tmp_path)
    (tmp_path / "SERVE_r01.json").write_text("{not json")
    recs = history.collect(root)
    assert recs[0]["error"]
    assert any("unreadable" in f for f in history.check(recs))


def test_unknown_schema_lists_but_gates_nothing(tmp_path):
    root = str(tmp_path)
    _write(root, "SERVE_r01_weird.json", {"claim": "an A/B doc"})
    recs = history.collect(root)
    assert recs[0]["parsed"] is False
    assert history.check(recs) == []


def test_tolerance_spec_rejects_unknown_names():
    tol = history.parse_tolerances("goodput_gbps=0.5")
    assert tol["goodput_gbps"] == 0.5
    with pytest.raises(ValueError):
        history.parse_tolerances("nope=1")


def test_committed_artifacts_are_green(capsys):
    """The repo's own committed *_r*.json set must pass --check: this
    is the same gate CI runs, pinned here so a regressing artifact
    fails the suite before it fails the workflow."""
    rc = history.main(["--check"])
    err = capsys.readouterr().err
    assert rc == 0, err
    assert "check green" in err
    records = history.collect(history.repo_root())
    assert len(records) >= 20  # the committed set, all collected
    families = {r["family"] for r in records}
    assert {"BENCH", "SERVE", "ROUTE", "MULTICHIP"} <= families
