"""Bitsliced engine: circuit exhaustiveness + parity with the T-table core.

The bitsliced engine's linear layers are derived numerically from the field
arithmetic (ops/bitslice.py), so these tests close the loop: every byte value
through the S-box circuits, and full-cipher equality against the gather core
(which is itself pinned to the reference oracle by tests/test_parity.py).

Circuit-level checks run the plane primitives eagerly on tiny arrays — an
XLA-CPU quirk makes some standalone fully-unrolled circuit graphs
pathologically slow to compile, while the shipped scan-over-rounds form
(bitslice.encrypt_words) compiles in seconds; eager evaluation sidesteps the
quirk without losing coverage.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from our_tree_tpu.models import aes as aes_mod
from our_tree_tpu.models.aes import AES, AES_DECRYPT, AES_ENCRYPT
from our_tree_tpu.ops import bitslice, tables
from our_tree_tpu.ops.keyschedule import expand_key_dec, expand_key_enc


def _all_bytes_planes():
    # 256 blocks; block b has all 16 bytes equal to b -> planes (8, 16, 8).
    by = np.repeat(np.arange(256, dtype=np.uint8), 16).reshape(256, 16)
    return bitslice.to_planes(jnp.asarray(by.view("<u4")))


def _planes_to_first_byte(planes) -> np.ndarray:
    words = np.asarray(bitslice.from_planes(jnp.stack(planes)))
    return words.view(np.uint8).reshape(256, 16)[:, 0]


def test_sbox_circuit_exhaustive():
    pl = _all_bytes_planes()
    out = _planes_to_first_byte(bitslice.sbox_planes([pl[i] for i in range(8)]))
    np.testing.assert_array_equal(out, np.asarray(tables.SBOX, dtype=np.uint8))


def test_inv_sbox_circuit_exhaustive():
    pl = _all_bytes_planes()
    out = _planes_to_first_byte(bitslice.inv_sbox_planes([pl[i] for i in range(8)]))
    np.testing.assert_array_equal(out, np.asarray(tables.INV_SBOX, dtype=np.uint8))


@pytest.mark.parametrize("impl", ["tower", "bp", "chain"])
def test_sbox_impls_exhaustive(impl, monkeypatch):
    """Every S-box formulation — the composite-field tower (default), the
    fixed Boyar–Peralta circuit, and the x^254 addition chain — must match
    the table for every byte, in both directions. Independent derivations
    cross-checking each other."""
    monkeypatch.setattr(bitslice, "SBOX_IMPL", impl)
    pl = _all_bytes_planes()
    out = _planes_to_first_byte(bitslice.sbox_planes([pl[i] for i in range(8)]))
    np.testing.assert_array_equal(out, np.asarray(tables.SBOX, dtype=np.uint8))
    out = _planes_to_first_byte(bitslice.inv_sbox_planes([pl[i] for i in range(8)]))
    np.testing.assert_array_equal(out, np.asarray(tables.INV_SBOX, dtype=np.uint8))


def test_grouped_layout_helpers_match_to_planes():
    """group_words/planes_from_grouped (the kernel-safe leading-axis forms
    used by the pallas-gt kernels) must agree exactly with the reference
    to_planes/from_planes pair, and both pairs must invert cleanly."""
    import jax.numpy as jnp

    rng = np.random.default_rng(31)
    w = jnp.asarray(rng.integers(0, 2**32, (32 * 7, 4), dtype=np.uint32))
    g = bitslice.group_words(w)
    np.testing.assert_array_equal(np.asarray(bitslice.ungroup_words(g)),
                                  np.asarray(w))
    np.testing.assert_array_equal(np.asarray(bitslice.planes_from_grouped(g)),
                                  np.asarray(bitslice.to_planes(w)))
    np.testing.assert_array_equal(
        np.asarray(bitslice.grouped_from_planes(bitslice.planes_from_grouped(g))),
        np.asarray(g))


def test_dense_layout_helpers_match_to_planes():
    """dense_words/planes_from_dense (the zero-padding (128, W) boundary
    used by the pallas-dense kernels) must agree exactly with the
    to_planes/from_planes pair and with the grouped form they replace, and
    invert cleanly (transpose32_dense is an involution like the grouped
    ladder)."""
    import jax.numpy as jnp

    rng = np.random.default_rng(37)
    w = jnp.asarray(rng.integers(0, 2**32, (32 * 7, 4), dtype=np.uint32))
    d = bitslice.dense_words(w)
    assert d.shape == (128, 7)
    np.testing.assert_array_equal(np.asarray(bitslice.undense_words(d)),
                                  np.asarray(w))
    # pure relayout of the grouped form: same bytes, merged leading axes
    np.testing.assert_array_equal(
        np.asarray(d), np.asarray(bitslice.group_words(w)).reshape(128, 7))
    np.testing.assert_array_equal(np.asarray(bitslice.planes_from_dense(d)),
                                  np.asarray(bitslice.to_planes(w)))
    np.testing.assert_array_equal(
        np.asarray(bitslice.dense_from_planes(bitslice.planes_from_dense(d))),
        np.asarray(d))
    np.testing.assert_array_equal(
        np.asarray(bitslice.transpose32_dense(bitslice.transpose32_dense(d))),
        np.asarray(d))


def test_gf16_mul_planes_matches_field():
    """Bitsliced GF(2^4) multiply vs the scalar field op, all 256 pairs."""
    import jax.numpy as jnp

    a_vals = np.repeat(np.arange(16, dtype=np.uint32), 16)   # 256 lanes
    b_vals = np.tile(np.arange(16, dtype=np.uint32), 16)
    a_planes = [jnp.asarray((a_vals >> i) & 1, jnp.uint32) * jnp.uint32(0xFFFFFFFF)
                for i in range(4)]
    b_planes = [jnp.asarray((b_vals >> i) & 1, jnp.uint32) * jnp.uint32(0xFFFFFFFF)
                for i in range(4)]
    out = bitslice.gf16_mul_planes(a_planes, b_planes)
    got = sum((np.asarray(out[i]) & 1) << i for i in range(4))
    want = np.array([bitslice._gf16_mul(int(a), int(b))
                     for a, b in zip(a_vals, b_vals)])
    np.testing.assert_array_equal(got, want)


def test_gf_mul_planes_matches_field():
    from our_tree_tpu.ops import gf

    # One plane set holds x = all byte values; multiply by constants.
    pl = _all_bytes_planes()
    x = [pl[i] for i in range(8)]
    for c in (0x02, 0x53, 0xCA):
        cpl = [jnp.full_like(x[0], 0xFFFFFFFF if (c >> i) & 1 else 0) for i in range(8)]
        out = _planes_to_first_byte(bitslice.gf_mul_planes(x, cpl))
        expect = np.array([gf.gmul(v, c) for v in range(256)], dtype=np.uint8)
        np.testing.assert_array_equal(out, expect)


def test_transpose_roundtrip():
    rng = np.random.default_rng(3)
    w = jnp.asarray(rng.integers(0, 2**32, (64, 4)).astype(np.uint32))
    back = bitslice.from_planes(bitslice.to_planes(w))
    np.testing.assert_array_equal(np.asarray(back), np.asarray(w))


@pytest.mark.parametrize("bits", [128, pytest.param(192, marks=pytest.mark.slow), pytest.param(256, marks=pytest.mark.slow)])
def test_bitslice_matches_ttable(bits):
    rng = np.random.default_rng(bits)
    key = rng.integers(0, 256, bits // 8, dtype=np.uint8).tobytes()
    nr, rk = expand_key_enc(key)
    _, rkd = expand_key_dec(key)
    rk, rkd = jnp.asarray(rk), jnp.asarray(rkd)
    # 33 blocks: exercises the pad-to-32 path and a full lane group.
    w = jnp.asarray(rng.integers(0, 2**32, (33, 4)).astype(np.uint32))
    np.testing.assert_array_equal(
        np.asarray(aes_mod.ecb_encrypt_words(w, rk, nr, "bitslice")),
        np.asarray(aes_mod.ecb_encrypt_words(w, rk, nr, "jnp")),
    )
    np.testing.assert_array_equal(
        np.asarray(aes_mod.ecb_decrypt_words(w, rkd, nr, "bitslice")),
        np.asarray(aes_mod.ecb_decrypt_words(w, rkd, nr, "jnp")),
    )


@pytest.mark.slow
def test_full_cipher_under_bp_sbox(monkeypatch):
    """The whole CTR path through the bitslice AND pallas engines with the
    Boyar–Peralta S-box selected — the exact configuration the hardware
    tuning sweep runs under OT_SBOX=bp. jit caches don't key on SBOX_IMPL
    (it's an import-time constant in production), so caches are cleared
    around the monkeypatch to force a retrace under the bp circuit and to
    keep other tests isolated from it."""
    import jax

    from our_tree_tpu.utils import packing

    rng = np.random.default_rng(53)
    key = bytes(range(16))
    nr, rk = expand_key_enc(key)
    rk = jnp.asarray(rk)
    nonce = np.frombuffer(bytes(range(60, 76)), np.uint8)
    ctr_be = jnp.asarray(packing.np_bytes_to_words(nonce).byteswap())
    w = jnp.asarray(rng.integers(0, 2**32, (33, 4)).astype(np.uint32))
    want = np.asarray(aes_mod.ctr_crypt_words(w, ctr_be, rk, nr, "jnp"))

    jax.clear_caches()
    monkeypatch.setattr(bitslice, "SBOX_IMPL", "bp")
    try:
        for engine in ("bitslice", "pallas", "pallas-gt", "pallas-gt-bp"):
            got = np.asarray(aes_mod.ctr_crypt_words(w, ctr_be, rk, nr,
                                                     engine))
            np.testing.assert_array_equal(got, want, err_msg=engine)
    finally:
        jax.clear_caches()  # don't leak bp-compiled executables


@pytest.mark.slow
def test_context_engine_parity_ctr():
    data = np.random.default_rng(7).integers(0, 256, 16 * 50 + 5, dtype=np.uint8)
    nonce = np.arange(16, dtype=np.uint8)
    sb = np.zeros(16, dtype=np.uint8)
    outs = {}
    for engine in ("jnp", "bitslice"):
        a = AES(bytes(range(32)), engine=engine)
        outs[engine], *_ = a.crypt_ctr(0, nonce.copy(), sb.copy(), data)
    np.testing.assert_array_equal(outs["jnp"], outs["bitslice"])


def test_nist_ecb_vector_bitslice():
    # FIPS-197 appendix C.1: AES-128, key/pt 00112233..., famous ciphertext.
    key = bytes.fromhex("000102030405060708090a0b0c0d0e0f")
    pt = bytes.fromhex("00112233445566778899aabbccddeeff")
    a = AES(key, engine="bitslice")
    ct = a.crypt_ecb(AES_ENCRYPT, pt)
    assert ct.tobytes().hex() == "69c4e0d86a7b0430d8cdb78070b4c55a"
    back = a.crypt_ecb(AES_DECRYPT, ct)
    assert back.tobytes() == pt
