"""Deviceless Mosaic compile gate for the Pallas kernels.

Round 3's flagship kernels shipped interpreter-verified only — Mosaic had
never seen them, and a first-contact compile failure was an acknowledged
unhandled risk (VERDICT r3 weak #2). scripts/aot_check.py closes that gap
without hardware: a deviceless PJRT TPU topology (bundled libtpu, verified
to answer locally without touching the tunnel) plus
``jax.jit(...).lower().compile()`` runs the full Pallas -> Mosaic ->
TPU-executable pipeline for every pallas-backed engine's encrypt, decrypt,
and fused-CTR entry, and the sharded CTR path over a 4-chip v5e mesh.

Subprocess-isolated (the check force-disables interpreter mode and builds
a TPU topology — neither belongs in this CPU test process), slow tier
(~13 compiles), persistent-compile-cache friendly.
"""

import json
import os
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parents[1]


@pytest.mark.slow
def test_aot_mosaic_compile_all_kernels():
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "aot_check.py")],
        capture_output=True, text=True, timeout=1800, env=env)
    if r.returncode == 3:
        pytest.skip(f"no deviceless TPU topology on this host: {r.stdout}")
    assert r.returncode == 0, (r.stdout, r.stderr[-2000:])
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["failed"] == [], summary
    # Every pallas engine must be represented (a silently shrunken case
    # list would pass while checking nothing).
    from our_tree_tpu.models.aes import PALLAS_BACKED

    for eng in PALLAS_BACKED:
        assert any(k.startswith(f"{eng}:enc") for k in summary["results"]), (
            eng, summary)
    assert any(k.startswith("sharded-ctr") for k in summary["results"])
