"""The otlint static-analysis subsystem (our_tree_tpu/analysis/).

Three layers of coverage (docs/ANALYSIS.md):

* AST rules on fixture modules with seeded violations — every rule must
  flag its planted violation and stay quiet on the compliant twin.
* The jaxpr auditor's constant-time regression: a PLANTED secret-indexed
  table lookup must be detected, the bitsliced kernels and the RC4 XOR
  phase must audit clean (the acceptance bar for the whole layer), and
  taint must not false-positive on constant-index permutations.
* The baseline round-trip: findings suppress by fingerprint, reasons are
  mandatory, stale entries are reported, and the committed repo baseline
  keeps `python -m our_tree_tpu.analysis --fail-on-new` green.
"""

from __future__ import annotations

import json
import pathlib
import textwrap

import numpy as np
import pytest

from our_tree_tpu.analysis import (astrules, baseline, driver, jaxpr_audit,
                                   sanrules)
from our_tree_tpu.analysis.findings import Finding

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _lint(tmp_path, src: str, name: str = "fixture.py"):
    p = tmp_path / name
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    return astrules.lint_paths([str(p)], str(tmp_path))


def _rules(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# Layer 1: one seeded violation (and one compliant twin) per rule.
# ---------------------------------------------------------------------------


def test_subprocess_rule_flags_bare_spawns(tmp_path):
    fs = _lint(tmp_path, """
        import subprocess
        import os

        def boom():
            os.fork()
            subprocess.run(["ls"])  # the import already flagged
    """)
    assert _rules(fs) == ["subprocess-isolate"]
    assert len([f for f in fs if f.rule == "subprocess-isolate"]) == 2


def test_subprocess_rule_exempts_the_isolate_chokepoint(tmp_path):
    fs = _lint(tmp_path, """
        import subprocess
    """, name="resilience/isolate.py")
    assert fs == []


def test_dispatch_rule_flags_unguarded_and_passes_guarded(tmp_path):
    fs = _lint(tmp_path, """
        import jax
        from our_tree_tpu.resilience import watchdog

        def bad(x):
            return jax.block_until_ready(x)

        def also_bad(x):
            return jax.device_put(x)

        def good(x):
            with watchdog.deadline(30, what="guarded dispatch"):
                return jax.block_until_ready(x)
    """)
    flagged = [f for f in fs if f.rule == "dispatch-watchdog"]
    assert len(flagged) == 2
    assert all("watchdog" in f.message for f in flagged)


def test_degrade_rule_flags_handrolled_lines_and_bad_kinds(tmp_path):
    fs = _lint(tmp_path, """
        from our_tree_tpu.resilience import degrade

        def bad_report():
            print("# degraded: tpu->cpu")  # not fed by the ledger

        def bad_kind():
            degrade.degrade("went sideways somehow", "why")

        def good_report():
            print("# degraded: " + ",".join(degrade.events()))

        def good_kind():
            degrade.degrade("tpu->cpu", "why")
            degrade.degrade("quarantined:ecb:65536", "why")
            degrade.degrade("dispatch-timeout", "why")
    """)
    flagged = [f for f in fs if f.rule == "degrade-chokepoint"]
    assert len(flagged) == 2


def test_wallclock_rule_flags_time_time_outside_obs(tmp_path):
    fs = _lint(tmp_path, """
        import time

        def bad():
            return time.time()

        def good():
            return time.monotonic() + time.perf_counter()
    """)
    assert _rules(fs) == ["wallclock"]
    assert len(fs) == 1
    # obs/ owns the epoch clock: the same source under obs/ is clean.
    assert _lint(tmp_path, "import time\nx = time.time_ns()\n",
                 name="obs/clock.py") == []


def test_trace_attrs_rule_flags_unserializable_literals(tmp_path):
    fs = _lint(tmp_path, """
        from our_tree_tpu.obs import trace

        def bad():
            trace.point("x", blob=b"raw-bytes")
            trace.counter("c", 1, tags={"a", "b"})

        def good():
            trace.point("x", unit="ecb:65536", n=3, ok=True, f=1.5)
            with trace.span("s", mode="ctr"):
                pass
    """)
    flagged = [f for f in fs if f.rule == "trace-attrs"]
    assert len(flagged) == 2
    assert any("bytes" in f.message for f in flagged)
    assert any("set" in f.message for f in flagged)


def test_fault_points_rule_checks_the_live_registry(tmp_path):
    fs = _lint(tmp_path, """
        from our_tree_tpu.resilience import faults, watchdog

        def bad():
            faults.check("dispach_hang")  # typo'd point never fires

        def good():
            faults.check("dispatch_fail")
            faults.fire("init_hang")
            watchdog.injected_hang("dispatch_hang")
    """)
    flagged = [f for f in fs if f.rule == "fault-points"]
    assert len(flagged) == 1
    assert "dispach_hang" in flagged[0].message


def test_serve_lane_seam_rule(tmp_path):
    """Device dispatch in serve/ only through serve/lanes.py: a raw
    scattered-CTR call (or block_until_ready/device_put) anywhere else
    under serve/ flags; the same code inside lanes.py is the seam."""
    src = """
        import jax
        from our_tree_tpu.models import aes

        def dispatch(words, ctr, rk, nr):
            out = aes.ctr_crypt_words_scattered(words, ctr, rk, nr, "jnp")
            jax.block_until_ready(out)
            return out
    """
    fs = _lint(tmp_path, src, name="our_tree_tpu/serve/server.py")
    flagged = [f for f in fs if f.rule == "serve-lane-seam"]
    assert len(flagged) == 2  # the scattered call AND the barrier
    assert "serve/lanes.py" in flagged[0].message
    # The seam file itself is the allowed caller...
    fs = _lint(tmp_path, src, name="our_tree_tpu/serve/lanes.py")
    assert "serve-lane-seam" not in _rules(fs)
    # ...and the rule only scopes serve/ (harness dispatch has its own
    # watchdog rule).
    fs = _lint(tmp_path, src, name="our_tree_tpu/harness/foo.py")
    assert "serve-lane-seam" not in _rules(fs)


def test_serve_lane_seam_rule_covers_multikey_and_native(tmp_path):
    """The multi-key entry point and the native host-tier dispatch are
    lane-seam dispatches too: reachable from serve/ ONLY through
    Lane.engine_call — a batcher 'optimisation' calling either directly
    would dodge the watchdog, health accounting, and failover."""
    violating = """
        from our_tree_tpu.models import aes
        from our_tree_tpu.runtime import native

        def fast_path(words, ctr, rks, slots, nr, ctxs):
            out = aes.ctr_crypt_words_scattered_multikey(
                words, ctr, rks, slots, nr, "jnp")
            return native.ctr_scattered_words(ctxs, out, ctr, slots)
    """
    fs = _lint(tmp_path, violating, name="our_tree_tpu/serve/batcher.py")
    flagged = [f for f in fs if f.rule == "serve-lane-seam"]
    assert len(flagged) == 2  # the multikey call AND the native tier
    assert any("ctr_crypt_words_scattered_multikey" in f.message
               for f in flagged)
    assert any("ctr_scattered_words" in f.message for f in flagged)
    # The compliant twin: the same calls inside the seam file are the
    # seam (Lane.engine_call's body is exactly this shape).
    fs = _lint(tmp_path, violating, name="our_tree_tpu/serve/lanes.py")
    assert "serve-lane-seam" not in _rules(fs)


def test_serve_lane_seam_rule_flags_threads_outside_executor(tmp_path):
    """Worker threads in serve/ exist only inside the lane executor
    (serve/dispatch.py): a thread spawned anywhere else — the lane seam
    file included — carries work past the thread-kill-hook guard that
    gives the watchdog its off-main delivery path."""
    src = """
        import threading

        def spawn(work):
            t = threading.Thread(target=work, daemon=True)
            t.start()
            return t
    """
    fs = _lint(tmp_path, src, name="our_tree_tpu/serve/batcher.py")
    flagged = [f for f in fs if f.rule == "serve-lane-seam"]
    assert len(flagged) == 1
    assert "serve/dispatch.py" in flagged[0].message
    # The lane seam file owns DEVICE contact, not threads: it flags too.
    fs = _lint(tmp_path, src, name="our_tree_tpu/serve/lanes.py")
    assert "serve-lane-seam" in _rules(fs)
    # The executor module is the one allowed spawner...
    fs = _lint(tmp_path, src, name="our_tree_tpu/serve/dispatch.py")
    assert "serve-lane-seam" not in _rules(fs)
    # ...and the rule only scopes serve/.
    fs = _lint(tmp_path, src, name="our_tree_tpu/harness/foo.py")
    assert "serve-lane-seam" not in _rules(fs)


def test_dispatch_watchdog_rule_guards_executor_unit(tmp_path):
    """The executor worker's `unit()` invocation is legal only inside
    the `watchdog.thread_kill_hook` guard: a deadline armed inside an
    unguarded unit would expire with no delivery path (SIGALRM cannot
    reach a worker thread) — the waiter blocks forever."""
    violating = """
        def _run(q):
            while True:
                fut, unit = q.get()
                result = unit()
                fut.set_result(result)
    """
    fs = _lint(tmp_path, violating, name="our_tree_tpu/serve/dispatch.py")
    flagged = [f for f in fs if f.rule == "dispatch-watchdog"]
    assert len(flagged) == 1
    assert "thread_kill_hook" in flagged[0].message
    compliant = """
        from our_tree_tpu.resilience import watchdog

        def _run(q):
            while True:
                fut, unit = q.get()

                def kill(exc, fut=fut):
                    fut.set_exception(exc)

                with watchdog.thread_kill_hook(kill):
                    fut.set_result(unit())
    """
    fs = _lint(tmp_path, compliant, name="our_tree_tpu/serve/dispatch.py")
    assert "dispatch-watchdog" not in _rules(fs)
    # Outside the executor module a bare `unit()` is just a function
    # call — not this rule's business.
    fs = _lint(tmp_path, violating, name="our_tree_tpu/serve/other.py")
    assert "dispatch-watchdog" not in _rules(fs)


def test_fault_points_rule_covers_lane_helpers(tmp_path):
    """check_lane/scoped literals are validated against KNOWN_POINTS
    like every other fault-method literal — and the registered lane
    points pass."""
    fs = _lint(tmp_path, """
        from our_tree_tpu.resilience import faults

        def bad(i):
            faults.check_lane("lane_fial", i)  # typo'd point never fires

        def good(i):
            faults.check_lane("lane_fail", i)
            faults.fire(faults.scoped("lane_hang", i))
    """)
    flagged = [f for f in fs if f.rule == "fault-points"]
    assert len(flagged) == 1
    assert "lane_fial" in flagged[0].message


def test_metrics_labels_rule_flags_keys_and_high_cardinality(tmp_path):
    """The registry-cardinality rule: label keys must come from
    obs.metrics.ALLOWED_LABEL_KEYS, and label VALUES that are
    statically high-cardinality (request ids, tenant digests,
    f-strings, **splats) flag — the process-global registry must never
    become an unbounded memory leak."""
    fs = _lint(tmp_path, """
        from our_tree_tpu.obs import metrics

        def bad(req, tenant, extra):
            metrics.counter("serve_requests", tenant=tenant)
            metrics.observe("lat_us", 5, outcome=f"req-{req.kind}")
            metrics.counter("x", code=req.id)
            metrics.gauge("g", 1, **extra)
    """)
    flagged = [f for f in fs if f.rule == "metrics-labels"]
    assert len(flagged) == 4
    msgs = " | ".join(f.message for f in flagged)
    assert "ALLOWED_LABEL_KEYS" in msgs          # bad key: tenant
    assert "f-string" in msgs                    # assembled value
    assert "high-cardinality" in msgs            # req.id value
    assert "splat" in msgs                       # **extra


def test_metrics_labels_rule_passes_compliant_twin(tmp_path):
    fs = _lint(tmp_path, """
        from our_tree_tpu.obs import metrics

        def good(lane, rung, engine_name):
            metrics.counter("serve_redispatch", lane=lane)
            metrics.observe("serve_dispatch_us", 12, rung=rung,
                            engine=engine_name, outcome="ok")
            metrics.gauge("serve_queue_depth", 3)
            metrics.gauge_max("serve_queue_depth_peak", 3)
            metrics.counter("serve_refused", code="bad-request")
    """)
    assert not [f for f in fs if f.rule == "metrics-labels"]


def test_fingerprints_survive_line_moves(tmp_path):
    """The baseline's matching contract: moving a violation down the
    file (new code above it) must not change its fingerprint."""
    a = _lint(tmp_path, "import time\nx = time.time()\n", name="a.py")
    b = _lint(tmp_path, "import time\n\n\ny = 1\nx = time.time()\n",
              name="a.py")
    assert a[0].fingerprint == b[0].fingerprint


# ---------------------------------------------------------------------------
# Layer 2: the constant-time regression + the clean-kernel acceptance bar.
# ---------------------------------------------------------------------------


def test_planted_secret_indexed_gather_is_detected():
    """The regression the rule exists for: a T-table-style lookup indexed
    by key-derived bytes must flag."""
    import jax.numpy as jnp

    table = np.arange(256, dtype=np.uint32)

    def leaky(key, data):
        t = jnp.asarray(table)
        return t[(data ^ key) & 0xFF]  # secret-indexed gather

    fs = jaxpr_audit.audit_fn(
        "planted", leaky,
        (np.zeros(64, np.uint32), np.zeros(64, np.uint32)), {0})
    assert [f.rule for f in fs] == ["constant-time"]
    assert "gather" in fs[0].message


def test_constant_index_permutation_does_not_false_positive():
    """Bitslice's ShiftRows is x[SR_PERM] with STATIC indices — the taint
    must not smear from the gathered operand onto the index."""
    import jax.numpy as jnp

    perm = np.array([2, 0, 3, 1], dtype=np.int32)

    def shuffled(secret):
        return secret[jnp.asarray(perm)]

    assert jaxpr_audit.audit_fn(
        "perm", shuffled, (np.zeros((4, 8), np.uint32),), {0}) == []


def test_scan_carry_taint_reaches_fixpoint():
    """A secret that enters the loop STATE only after iteration 1 —
    carry-out feeding carry-in — must still taint a carry-indexed
    lookup. A single walk of the scan body under the initial carry's
    taint (a public literal) would miss exactly this shape; the
    auditor iterates the body to fixpoint on the carry."""
    import jax
    import jax.numpy as jnp

    table = np.arange(256, dtype=np.uint32)

    def leaky_via_carry(secret_xs):
        t = jnp.asarray(table)

        def step(c, x):
            return (c + x) & 0xFF, t[c]  # c is secret from iteration 1 on

        return jax.lax.scan(step, jnp.uint32(0), secret_xs)

    fs = jaxpr_audit.audit_fn(
        "carry-leak", leaky_via_carry, (np.zeros(64, np.uint32),), {0})
    assert "constant-time" in [f.rule for f in fs], \
        [f.render() for f in fs]
    # And the fixpoint must not over-taint a PUBLIC carry: the same scan
    # over public xs with a secret used only elementwise stays clean.

    def clean_scan(secret, public_xs):
        def step(c, x):
            return c + x, x ^ secret[0]

        return jax.lax.scan(step, jnp.uint32(0),
                            jnp.asarray(public_xs))

    assert jaxpr_audit.audit_fn(
        "carry-clean", clean_scan,
        (np.zeros(4, np.uint32), np.zeros(64, np.uint32)), {0}) == []


def test_bitsliced_kernels_audit_clean():
    """THE acceptance bar: the TPU production circuit has no secret-
    indexed lookups, no argument-derived transfers, no widening, for
    both directions."""
    from our_tree_tpu.ops import bitslice

    for name, fn in (("enc", bitslice.encrypt_words),
                     ("dec", bitslice.decrypt_words)):
        fs = jaxpr_audit.audit_fn(
            f"bitslice-{name}", lambda w, rk, f=fn: f(w, rk, 10),
            (np.zeros((32, 4), np.uint32), np.zeros(44, np.uint32)), {0, 1})
        assert fs == [], [f.render() for f in fs]


def test_rc4_xor_phase_audits_clean_and_prep_flags():
    """The paper's phase split, as a security property: the sequential
    PRGA is state-indexed by definition (flags — baselined with that
    reason), while the data-parallel XOR phase the TPU scales must be
    constant-time clean."""
    from our_tree_tpu.models import arc4

    clean = jaxpr_audit.audit_fn(
        "rc4-crypt", arc4.crypt,
        (np.zeros(512, np.uint8), np.zeros(512, np.uint8)), {0, 1})
    assert clean == []
    prep = jaxpr_audit.audit_fn(
        "rc4-prep",
        lambda st: arc4.keystream_scan(st, 64),
        ((np.uint32(0), np.uint32(0), np.zeros(256, np.uint32)),), {0})
    assert "constant-time" in [f.rule for f in prep]


def test_dtype_widening_is_flagged():
    import jax
    import jax.numpy as jnp

    def widens_int(x):
        # x64 is disabled suite-wide, so the widening must be forced —
        # exactly the accidental-promotion shape the rule watches for.
        with jax.experimental.enable_x64():
            return x.astype(jnp.int64)

    fs = jaxpr_audit.audit_fn("widen", widens_int,
                              (np.zeros(8, np.uint32),), {0})
    assert "dtype-widening" in [f.rule for f in fs]


def test_public_entries_carry_no_new_jaxpr_findings():
    """The audited entry set against the COMMITTED baseline: bitslice
    entries clean, jnp/rc4 findings exactly the baselined ones, no
    audit-error (an entry the auditor can't trace would blind it)."""
    fs = jaxpr_audit.audit(("jnp", "bitslice"))
    assert not [f for f in fs if f.rule == "audit-error"], \
        [f.render() for f in fs]
    assert not [f for f in fs if "[bitslice]" in f.anchor
                or "bitslice-" in f.anchor], [f.render() for f in fs]
    base = baseline.load(str(ROOT / "analysis" / "baseline.json"))
    baseline.apply(fs, base)
    assert [f.render() for f in fs if not f.baselined] == []


# ---------------------------------------------------------------------------
# Baseline round-trip + the CLI gate.
# ---------------------------------------------------------------------------


def test_baseline_round_trip_and_staleness(tmp_path):
    f1 = Finding("wallclock", "warning", "m1", "a.py", 3, anchor="x = 1")
    f2 = Finding("wallclock", "warning", "m2", "b.py", 7, anchor="y = 2")
    path = tmp_path / "base.json"
    baseline.write(str(path), [f1, f2])
    # Reasonless (TODO) entries must not load — justification is the deal.
    with pytest.raises(baseline.BaselineError):
        baseline.load(str(path))
    data = json.loads(path.read_text())
    for e in data["findings"]:
        e["reason"] = "a real reason"
    path.write_text(json.dumps(data))
    loaded = baseline.load(str(path))
    assert set(loaded) == {f1.fingerprint, f2.fingerprint}
    # Round trip: both suppress; with f2 fixed, its entry reports stale.
    fs = [Finding("wallclock", "warning", "m1", "a.py", 3, anchor="x = 1")]
    stale = baseline.apply(fs, loaded)
    assert fs[0].baselined and fs[0].baseline_reason == "a real reason"
    assert stale == sorted([f2.fingerprint])
    # Rewrite preserves the human-written reason by fingerprint.
    baseline.write(str(path), fs, loaded)
    assert baseline.load(str(path))[f1.fingerprint]["reason"] \
        == "a real reason"


def test_cli_runs_clean_against_committed_baseline():
    """The acceptance criterion: `python -m our_tree_tpu.analysis
    --baseline analysis/baseline.json --fail-on-new` exits 0 on this
    tree — and the AST layer alone finds nothing new either (fast
    path, no jax tracing)."""
    rc = driver.main(["--baseline", str(ROOT / "analysis" / "baseline.json"),
                      "--fail-on-new", "--no-jaxpr"])
    assert rc == 0


def test_cli_fails_on_new_findings(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import subprocess\n")
    rc = driver.main([str(bad), "--no-jaxpr", "--fail-on-new"])
    assert rc == 1
    # Without the gate flag the same run reports but exits 0.
    assert driver.main([str(bad), "--no-jaxpr"]) == 0


# ---------------------------------------------------------------------------
# otlint --fix: mechanical rewrites (the wallclock seed rule).
# ---------------------------------------------------------------------------


def test_fix_wallclock_fixture_pair_relints_clean(tmp_path):
    """The fixture-pair contract: a file with wallclock violations,
    fixed by `--fix`, re-lints CLEAN for the rule — and the rewrite is
    exactly the monotonic twin, byte-for-byte predictable."""
    before = textwrap.dedent("""\
        import time


        def took():
            t0 = time.time()
            work()
            ns = time.time_ns()
            return time.time() - t0, ns
    """)
    after = textwrap.dedent("""\
        import time


        def took():
            t0 = time.monotonic()
            work()
            ns = time.monotonic_ns()
            return time.monotonic() - t0, ns
    """)
    f = tmp_path / "wall.py"
    f.write_text(before)
    findings = astrules.lint_paths([str(f)], str(tmp_path))
    assert sum(1 for x in findings if x.rule == "wallclock") == 3
    fixed = astrules.fix_paths([str(f)], str(tmp_path))
    assert fixed == {"wall.py": 3}
    assert f.read_text() == after
    refound = astrules.lint_paths([str(f)], str(tmp_path))
    assert not [x for x in refound if x.rule == "wallclock"]
    # Idempotent: a second --fix rewrites nothing.
    assert astrules.fix_paths([str(f)], str(tmp_path)) == {}


def test_fix_leaves_judgment_sites_alone(tmp_path):
    """time.time(x...) shapes (args/kwargs) and unparseable files are
    not --fix's business; the finding still stands for the reviewer."""
    f = tmp_path / "odd.py"
    f.write_text("import time\nt = time.time\nbad = time.time(*a)\n")
    assert astrules.fix_paths([str(f)], str(tmp_path)) == {}
    g = tmp_path / "broken.py"
    g.write_text("def (:\n")
    assert astrules.fix_paths([str(g)], str(tmp_path)) == {}


def test_fix_cli_applies_then_reports_postfix_state(tmp_path, capsys):
    f = tmp_path / "wall.py"
    f.write_text("import time\nt0 = time.time()\n")
    rc = driver.main([str(f), "--no-jaxpr", "--fix", "--fail-on-new"])
    assert rc == 0  # the fix landed BEFORE the lint: nothing new left
    assert "time.monotonic()" in f.read_text()
    err = capsys.readouterr().err
    assert "--fix" in err and "1 rewrite(s)" in err


def test_fix_exempts_baselined_violations(tmp_path):
    """A reasoned baseline entry marks a DELIBERATE wallclock site
    (devlock's epoch-vs-mtime staleness compare): --fix must leave it
    byte-identical while still fixing unbaselined sites in the same
    file."""
    f = tmp_path / "wall.py"
    f.write_text("import time\n"
                 "fresh = time.time() - mtime <= 60\n"
                 "t0 = time.time()\n")
    findings = astrules.lint_paths([str(f)], str(tmp_path))
    wall = [x for x in findings if x.rule == "wallclock"]
    assert len(wall) == 2
    keep = [x for x in wall if "fresh" in x.anchor]
    base = {keep[0].fingerprint: {"reason": "epoch vs mtime on purpose"}}
    fixed = astrules.fix_paths([str(f)], str(tmp_path), baseline=base)
    assert fixed == {"wall.py": 1}
    src = f.read_text()
    assert "fresh = time.time() - mtime <= 60" in src   # protected
    assert "t0 = time.monotonic()" in src               # fixed
    # And the REAL baseline protects the real tree: a --fix dry run
    # over the repo's own default paths with the committed baseline
    # must not touch the baselined devlock/watchdog sites (verified by
    # fixing into a COPY, never the tree itself).
    import pathlib
    import shutil
    repo = pathlib.Path(astrules.__file__).resolve().parents[2]
    from our_tree_tpu.analysis import baseline as baseline_mod
    committed = baseline_mod.load(str(repo / "analysis" / "baseline.json"))
    for rel in ("our_tree_tpu/utils/devlock.py",
                "our_tree_tpu/resilience/watchdog.py"):
        dst = tmp_path / pathlib.Path(rel).name
        shutil.copy(repo / rel, dst)
        before = dst.read_text()
        astrules.fix_file(str(dst), rel, baseline=committed)
        assert dst.read_text() == before, f"--fix touched baselined {rel}"


# ---------------------------------------------------------------------------
# Layer 3 (ot-san): the whole-program concurrency auditor.
# ---------------------------------------------------------------------------


def _san(tmp_path, files):
    """Write {relpath: src} fixtures under tmp_path and run the san
    layer over them (same path contract as the driver)."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return sanrules.analyze_paths([str(tmp_path)], str(tmp_path))


def _by_rule(findings, rule):
    return [f for f in findings if f.rule == rule]


def test_san_loop_stall_fixture_pair(tmp_path):
    violating = """
        import asyncio
        import time


        def slow():
            time.sleep(1.0)


        async def handler():
            slow()
    """
    fs = _by_rule(_san(tmp_path, {"pkg/stall.py": violating}), "loop-stall")
    assert len(fs) == 1
    f = fs[0]
    assert f.path == "pkg/stall.py" and f.severity == "error"
    assert "time.sleep" in f.message and "handler" in f.message
    # Compliant twin: the same call hopped through asyncio.to_thread.
    compliant = """
        import asyncio
        import time


        def slow():
            time.sleep(1.0)


        async def handler():
            await asyncio.to_thread(slow)
    """
    assert not _by_rule(_san(tmp_path, {"pkg/stall.py": compliant}),
                        "loop-stall")


def test_san_executor_hop_is_not_a_false_positive(tmp_path):
    """run_in_executor severs blocking propagation: the callee runs on
    a worker thread, so the coroutine holding the future is fine —
    and the hopped target becomes thread-affine, not loop-affine."""
    src = """
        import asyncio
        import time


        def slow():
            time.sleep(1.0)


        async def handler():
            loop = asyncio.get_running_loop()
            return await loop.run_in_executor(None, slow)
    """
    assert not _san(tmp_path, {"pkg/hop.py": src})


def test_san_loop_stall_flags_only_the_top_loop_frame(tmp_path):
    """One bug, one fix site, one finding: the async->sync boundary is
    flagged; the sync frames inside the chain are not re-flagged."""
    src = """
        import asyncio


        def leaf():
            open("/tmp/x").read()


        def mid():
            leaf()


        async def handler():
            mid()
    """
    fs = _by_rule(_san(tmp_path, {"pkg/chain.py": src}), "loop-stall")
    assert len(fs) == 1
    assert "mid" in fs[0].message and "open" in fs[0].message


def test_san_lock_await_fixture_pair(tmp_path):
    violating = """
        import asyncio
        import threading


        class S:
            def __init__(self):
                self._lock = threading.Lock()

            async def step(self):
                with self._lock:
                    await asyncio.sleep(0)
    """
    fs = _by_rule(_san(tmp_path, {"pkg/la.py": violating}), "lock-await")
    assert len(fs) == 1 and fs[0].severity == "error"
    # Compliant twin: asyncio.Lock held across await is the normal
    # async critical-section pattern.
    compliant = """
        import asyncio


        class S:
            def __init__(self):
                self._lock = asyncio.Lock()

            async def step(self):
                async with self._lock:
                    await asyncio.sleep(0)
    """
    assert not _san(tmp_path, {"pkg/la.py": compliant})


def test_san_sync_with_on_asyncio_lock_is_flagged(tmp_path):
    src = """
        import asyncio


        class S:
            def __init__(self):
                self._lock = asyncio.Lock()

            def step(self):
                with self._lock:
                    return 1
    """
    fs = _by_rule(_san(tmp_path, {"pkg/sw.py": src}), "lock-await")
    assert len(fs) == 1


def test_san_lock_order_cross_file_cycle(tmp_path):
    """A two-lock cycle split across modules — each file is locally
    consistent; only the whole-program acquisition graph sees it —
    reports exactly ONE finding (per SCC, not per edge)."""
    a = """
        import threading

        from . import b

        LOCK_A = threading.Lock()


        def fwd():
            with LOCK_A:
                b.take_b()


        def take_a():
            with LOCK_A:
                pass
    """
    b = """
        import threading

        from . import a

        LOCK_B = threading.Lock()


        def rev():
            with LOCK_B:
                a.take_a()


        def take_b():
            with LOCK_B:
                pass
    """
    fs = _by_rule(_san(tmp_path, {"pkg/a.py": a, "pkg/b.py": b}),
                  "lock-order")
    assert len(fs) == 1
    assert "LOCK_A" in fs[0].message and "LOCK_B" in fs[0].message
    # Compliant twin: both paths honor the same global order.
    b_ordered = """
        import threading

        from . import a

        LOCK_B = threading.Lock()


        def rev():
            with LOCK_B:
                pass


        def take_b():
            with LOCK_B:
                pass
    """
    assert not _san(tmp_path, {"pkg/a.py": a, "pkg/b.py": b_ordered})


def test_san_thread_ownership_fixture_pair(tmp_path):
    violating = """
        import asyncio
        import threading

        COUNT = 0


        def worker():
            global COUNT
            COUNT += 1


        async def main():
            global COUNT
            threading.Thread(target=worker).start()
            COUNT = 0
    """
    fs = _by_rule(_san(tmp_path, {"pkg/own.py": violating}),
                  "thread-ownership")
    assert len(fs) == 1
    assert "COUNT" in fs[0].message
    # Compliant twin A: one write carries the owner annotation.
    annotated = violating.replace(
        "COUNT += 1",
        "COUNT += 1  # ot-san: owner=test-seam")
    assert not _san(tmp_path, {"pkg/own.py": annotated})
    # Compliant twin B: every write holds the same thread lock.
    locked = """
        import asyncio
        import threading

        COUNT = 0
        LOCK = threading.Lock()


        def worker():
            global COUNT
            with LOCK:
                COUNT += 1


        async def main():
            global COUNT
            threading.Thread(target=worker).start()
            with LOCK:
                COUNT = 0
    """
    assert not _san(tmp_path, {"pkg/own.py": locked})


def test_san_malformed_annotation_is_itself_a_finding(tmp_path):
    """A typo must not silently waive the rule: the bad comment is
    flagged AND the ownership finding still stands."""
    src = """
        import asyncio
        import threading

        COUNT = 0


        def worker():
            global COUNT
            COUNT += 1  # ot-san: onwer=test-seam


        async def main():
            global COUNT
            threading.Thread(target=worker).start()
            COUNT = 0
    """
    fs = _by_rule(_san(tmp_path, {"pkg/bad.py": src}), "thread-ownership")
    assert any("malformed" in f.message for f in fs)
    assert any("COUNT" in f.message and "malformed" not in f.message
               for f in fs)


def test_san_fingerprints_stable_across_line_shift(tmp_path):
    """The acceptance criterion: each planted violation keeps its
    fingerprint when the file shifts underneath it."""
    stall = ("import asyncio\nimport time\n\n\n"
             "def slow():\n    time.sleep(1.0)\n\n\n"
             "async def handler():\n    slow()\n")
    la = ("import asyncio\nimport threading\n\n\n"
          "class S:\n"
          "    def __init__(self):\n"
          "        self._lock = threading.Lock()\n\n"
          "    async def step(self):\n"
          "        with self._lock:\n"
          "            await asyncio.sleep(0)\n")
    own = ("import asyncio\nimport threading\n\nCOUNT = 0\n\n\n"
           "def worker():\n    global COUNT\n    COUNT += 1\n\n\n"
           "async def main():\n    global COUNT\n"
           "    threading.Thread(target=worker).start()\n    COUNT = 0\n")
    files = {"pkg/stall.py": stall, "pkg/la.py": la, "pkg/own.py": own}
    before = _san(tmp_path, files)
    assert len(before) == 3
    shifted = {rel: "# a comment\n# another\n\n" + src
               for rel, src in files.items()}
    after = _san(tmp_path, shifted)
    assert {f.fingerprint for f in before} == {f.fingerprint for f in after}
    for f in after:
        assert f.fingerprint.startswith("san:")


def test_san_rule_version_changes_the_fingerprint():
    f1 = Finding("loop-stall", "error", "m", "a.py", 3,
                 anchor="x()", layer="san", version=1)
    f2 = Finding("loop-stall", "error", "m", "a.py", 3,
                 anchor="x()", layer="san", version=2)
    assert f1.fingerprint != f2.fingerprint
    assert f1.fingerprint.startswith("san:loop-stall:")


def test_baseline_migrates_reasons_across_version_bumps(tmp_path):
    """A rule version bump changes every fingerprint; the rewrite must
    carry the human-written reason over by (rule, location) so the
    justification survives the migration."""
    old = Finding("loop-stall", "error", "m", "a.py", 3,
                  anchor="x()", layer="san", version=1)
    path = tmp_path / "base.json"
    baseline.write(str(path), [old])
    data = json.loads(path.read_text())
    data["findings"][0]["reason"] = "a migrated reason"
    path.write_text(json.dumps(data))
    loaded = baseline.load(str(path))
    new = Finding("loop-stall", "error", "m", "a.py", 3,
                  anchor="x()", layer="san", version=2)
    assert new.fingerprint != old.fingerprint
    baseline.write(str(path), [new], loaded)
    reloaded = baseline.load(str(path))
    assert reloaded[new.fingerprint]["reason"] == "a migrated reason"


def test_san_cli_runs_clean_against_committed_baseline():
    """The acceptance criterion: `--san --baseline analysis/baseline.json
    --fail-on-new` exits 0 on this tree, with every baselined entry
    carrying a reason (the loader enforces that part)."""
    rc = driver.main(["--san", "--no-jaxpr",
                      "--baseline", str(ROOT / "analysis" / "baseline.json"),
                      "--fail-on-new"])
    assert rc == 0


def test_san_fixed_files_stay_loop_stall_free():
    """Satellite regression: the serve/route status surfaces and the
    fleet spawn path were FIXED in this change, not baselined — the
    auditor must keep them clean."""
    pkg = ROOT / "our_tree_tpu"
    fs = sanrules.analyze_paths([str(pkg)], str(ROOT))
    fixed = ("our_tree_tpu/serve/status.py", "our_tree_tpu/route/status.py")
    stalls = [f for f in _by_rule(fs, "loop-stall") if f.path in fixed]
    assert not stalls, [f.message for f in stalls]
    fleet_stalls = [f for f in _by_rule(fs, "loop-stall")
                    if f.path == "our_tree_tpu/route/fleet.py"
                    and "ProcessWorkerHandle.start" in f.message]
    assert not fleet_stalls, [f.message for f in fleet_stalls]
