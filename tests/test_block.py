"""Single-block cipher tests vs FIPS-197 appendix C (reference aes.c:650-752)."""

import jax.numpy as jnp
import numpy as np

from our_tree_tpu.models.aes import AES, AES_DECRYPT, AES_ENCRYPT
from our_tree_tpu.ops import block
from our_tree_tpu.ops.keyschedule import expand_key_dec, expand_key_enc
from our_tree_tpu.utils import packing

PT = bytes.fromhex("00112233445566778899aabbccddeeff")
VECTORS = [
    ("000102030405060708090a0b0c0d0e0f", "69c4e0d86a7b0430d8cdb78070b4c55a"),
    ("000102030405060708090a0b0c0d0e0f1011121314151617", "dda97ca4864cdfe06eaf70a0ec0d7191"),
    (
        "000102030405060708090a0b0c0d0e0f101112131415161718191a1b1c1d1e1f",
        "8ea2b7ca516745bfeafc49904b496089",
    ),
]


def test_fips197_all_key_sizes():
    for keyhex, cthex in VECTORS:
        a = AES(bytes.fromhex(keyhex))
        ct = a.crypt_ecb(AES_ENCRYPT, PT)
        assert ct.tobytes().hex() == cthex
        assert a.crypt_ecb(AES_DECRYPT, ct).tobytes() == PT


def test_batched_equals_blockwise():
    """N-block batch must equal N independent single-block calls — the
    invariance that would have caught reference defect #1 (SURVEY.md §2)."""
    rng = np.random.default_rng(7)
    key = rng.integers(0, 256, 16, dtype=np.uint8).tobytes()
    data = rng.integers(0, 256, 64 * 16, dtype=np.uint8)
    nr, rk = expand_key_enc(key)
    w = jnp.asarray(packing.np_bytes_to_words(data).reshape(-1, 4))
    batched = np.asarray(block.encrypt_words(w, jnp.asarray(rk), nr))
    for i in range(0, 64, 17):
        single = np.asarray(block.encrypt_words(w[i], jnp.asarray(rk), nr))
        assert np.array_equal(batched[i], single)


def test_decrypt_inverts_encrypt_random():
    rng = np.random.default_rng(11)
    for keylen in (16, 24, 32):
        key = rng.integers(0, 256, keylen, dtype=np.uint8).tobytes()
        nr, rk_e = expand_key_enc(key)
        _, rk_d = expand_key_dec(key)
        w = jnp.asarray(rng.integers(0, 1 << 32, (32, 4), dtype=np.uint32))
        ct = block.encrypt_words(w, jnp.asarray(rk_e), nr)
        back = block.decrypt_words(ct, jnp.asarray(rk_d), nr)
        assert np.array_equal(np.asarray(back), np.asarray(w))
