"""Driver-entry bench.py under injected faults (OT_FAULTS): the
always-prints-a-JSON-line contract, now exercisable on CPU in CI.

These are the fault-matrix rows for the two seams a wedged tunnel actually
hits (docs/RESILIENCE.md): the PJRT init probe (init_hang -> the shared
retry policy demotes tpu->cpu) and the measurement dispatch
(dispatch_fail -> the native-runtime fallback). Both must end in a
parseable JSON line carrying the ``degraded`` record — a fallback run must
never masquerade as a healthy one, and a faulted run must never die with a
traceback instead of a line.
"""

import json
import os
import pathlib
import subprocess
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _run_bench(tmp_path, extra_env, timeout=280):
    env = dict(
        os.environ,
        PYTHONPATH="",
        # Isolated lock path: the real default may be legitimately held by
        # a measurement job on this host (same reasoning as
        # test_root_bench's unreachable-accelerator test).
        OT_BENCH_BUSY_FILE=str(tmp_path / "busy"),
        OT_BENCH_BYTES=str(4 << 20),
        OT_BENCH_ITERS="2",
        OT_BENCH_REPS="1",
    )
    env.update(extra_env)
    out = subprocess.run(
        [sys.executable, str(ROOT / "bench.py")], env=env, cwd=ROOT,
        capture_output=True, text=True, timeout=timeout,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1]), out.stderr


def test_init_hang_demotes_to_cpu_with_degraded_record(tmp_path):
    """OT_FAULTS=init_hang:2 — the acceptance scenario: two injected probe
    hangs (each debiting its attempt's full timeout from the deadline
    budget, like a real hang) exhaust the shared retry policy, the bench
    demotes to CPU, and the JSON line carries degraded:["tpu->cpu"]."""
    env = {"OT_FAULTS": "init_hang:2", "OT_BENCH_DEADLINE": "60"}
    env["JAX_PLATFORMS"] = ""  # the probe path must run (no CPU pin)
    line, err = _run_bench(tmp_path, env)
    assert line["unit"] == "GB/s"
    assert line["degraded"] == ["tpu->cpu"]
    assert "cpu" in line["metric"]
    assert "probe attempt 1 failed (InjectedFault)" in err
    assert "# degraded: tpu->cpu" in err


def test_dispatch_fail_on_cpu_still_prints_degraded_json(tmp_path):
    """OT_FAULTS=dispatch_fail:1 with a CPU pin: the headline dispatch dies
    (the injected stand-in for a device that wedged mid-measurement), and
    the run still exits 0 with a parseable JSON line whose degraded field
    names the demotion — not a traceback (the reference's unchecked-launch
    defect class, inverted)."""
    line, err = _run_bench(tmp_path, {
        "OT_FAULTS": "dispatch_fail:1",
        "JAX_PLATFORMS": "cpu",
        "OT_BENCH_DEADLINE": "240",
        "OT_BENCH_CPU_NATIVE": "0",
    })
    assert line["unit"] == "GB/s"
    assert line["degraded"] == ["device->native"]
    assert "native" in line["metric"]
    assert line["value"] > 0  # a real framework number, clearly labeled
    assert "headline failed (InjectedFault" in err


def test_lock_busy_diverts_to_native_without_contending(tmp_path):
    """Bare OT_FAULTS=lock_busy — a simulated devlock holder that outlasts
    the wait budget: the bench must take the documented busy path (wait
    out the bounded budget, fail acquisition, confirm the holder, report
    the native host runtime) without ever touching a device — previously
    only testable with a real second process (test_root_bench's slow
    holder-subprocess test)."""
    line, err = _run_bench(tmp_path, {
        "OT_FAULTS": "lock_busy",
        "JAX_PLATFORMS": "",  # busy path only runs when CPU is not pinned
        "OT_BENCH_DEADLINE": "40",
    }, timeout=240)
    assert "device busy" in line["metric"]
    assert line["degraded"] == ["tpu->cpu"]
    assert "not contending" in err


def test_dispatch_hang_watchdog_interrupts_and_reports(tmp_path):
    """OT_FAULTS=dispatch_hang:1 — the wedged-not-failed dispatch: the
    measure stage blocks in a GIL-releasing sleep, the stage watchdog
    (resilience/watchdog.py) interrupts it at the stage budget, dumps
    all-thread stacks to a crash report, and the fallback chain still
    ends in one parseable JSON line whose degraded record names BOTH
    facts: the watchdog demotion and the native fallback."""
    line, err = _run_bench(tmp_path, {
        "OT_FAULTS": "dispatch_hang:1",
        "JAX_PLATFORMS": "cpu",
        "OT_BENCH_DEADLINE": "12",  # stage budget ≈ 7 s: a fast rehearsal
        "OT_HANG_S": "300",
        "OT_BENCH_CPU_NATIVE": "0",
        "OT_CRASH_DIR": str(tmp_path / "crash"),
    })
    assert line["unit"] == "GB/s"
    assert line["degraded"] == ["dispatch-timeout", "device->native"]
    assert "native" in line["metric"]
    assert "headline failed (DispatchTimeout" in err
    import pathlib

    assert list(pathlib.Path(tmp_path / "crash").glob("watchdog-*.txt"))


def test_faults_unset_healthy_line_has_no_degraded_key(tmp_path):
    """The no-op guarantee: with OT_FAULTS unset the injection seam must
    not perturb the output contract — same schema, no degraded key."""
    line, _ = _run_bench(tmp_path, {
        "JAX_PLATFORMS": "cpu",
        "OT_BENCH_DEADLINE": "240",
        "OT_BENCH_CPU_NATIVE": "0",
        "OT_BENCH_BYTES": str(1 << 20),
    })
    assert line["unit"] == "GB/s"
    assert "degraded" not in line
