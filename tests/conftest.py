"""Test configuration: force CPU with 8 virtual devices.

The 8 virtual devices let sharding tests (tests/test_parallel.py) validate
multi-chip paths without a pod — a capability the reference had no equivalent
of (SURVEY.md §4: multi-device was "tested" only by owning the hardware).

Env vars alone are not enough on hosts whose site hooks pre-register an
accelerator backend at interpreter start, so the platform is also forced
through `jax.config`. That update only takes effect while no backend has
been *initialized* yet (it is a silent no-op afterwards) — which holds here
because conftest imports before any test touches jax.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
