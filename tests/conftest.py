"""Test configuration: force CPU with 8 virtual devices.

The 8 virtual devices let sharding tests (tests/test_parallel.py) validate
multi-chip paths without a pod — a capability the reference had no equivalent
of (SURVEY.md §4: multi-device was "tested" only by owning the hardware).
Must run before jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
