"""Test configuration: force CPU with 8 virtual devices.

The 8 virtual devices let sharding tests (tests/test_parallel.py) validate
multi-chip paths without a pod — a capability the reference had no equivalent
of (SURVEY.md §4: multi-device was "tested" only by owning the hardware).

Env vars alone are not enough on hosts whose site hooks pre-register an
accelerator backend at interpreter start, so the platform is also forced
through `jax.config`. That update only takes effect while no backend has
been *initialized* yet (it is a silent no-op afterwards) — which holds here
because conftest imports before any test touches jax.
"""

import os
import re

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
_m = re.search(r"--xla_force_host_platform_device_count=(\d+)", _flags)
if _m is None or int(_m.group(1)) < 8:
    if _m is not None:  # a smaller pre-set count would break every mesh test
        _flags = _flags.replace(_m.group(0), "")
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False,
        help="run tests marked slow (interpreter-mode Pallas, 10k-iteration "
             "KATs, multi-process rehearsals). OT_RUN_SLOW=1 does the same.")


def pytest_collection_modifyitems(config, items):
    """Tier the suite (VERDICT r2 #7): the full run stopped fitting any
    practical single budget on this host (~37 min; test_pallas.py alone at
    ~17 min in interpreter mode), so the realistic failure mode was nobody
    running all of it. The default invocation now runs the core subset
    (~9 min here — still every engine incl. a compact three-layout kernel
    matrix, every mode, seam, and sharded path, via cheaper
    representatives); `--runslow` / OT_RUN_SLOW=1 is the round-gate
    invocation that runs everything. Explicitly selecting only slow tests
    (`-m slow`) also runs them.
    """
    # The markexpr test matches "slow" as a whole word (ADVICE r3): a
    # substring test would let any expression merely containing the letters
    # — a future "slowio" marker, say — disable the skip-marking path.
    # "not slow" matching too is correct: -m deselection already governs
    # there, and adding skip markers on top would only muddy the report.
    if (config.getoption("--runslow")
            or os.environ.get("OT_RUN_SLOW", "") not in ("", "0", "false")
            or re.search(r"\bslow\b", config.getoption("markexpr", "") or "")):
        return
    skip = pytest.mark.skip(
        reason="slow tier: pass --runslow (or OT_RUN_SLOW=1) to run")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


#: Modules KNOWN to compile at most a handful of XLA programs (file/json
#: plumbing, table generation, host-side key schedules). The cache-clear
#: mitigation below skips only these — a blocklist of known-light
#: modules, not an allowlist of heavy ones, so a new or borderline
#: module fails SAFE (gets cleared) instead of silently re-accumulating
#: toward the segfault threshold.
_COMPILE_LIGHT = ("test_devlock", "test_tables", "test_keyschedule",
                  "test_ranking", "test_tune_attribution",
                  "test_circuit_size")


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules(request):
    """Drop compiled executables after each compile-heavy test module.

    The full suite compiles hundreds of XLA CPU programs in one process;
    past ~130 tests the accumulated compiler state reproducibly segfaulted
    XLA's CPU backend_compile on this class of host (single-core container,
    jaxlib 0.9.x) — always at the same downstream compile. Each module's
    compilations are independent, so clearing between the heavy modules
    keeps the per-process compiler footprint bounded without affecting
    coverage (VERDICT r4 #9: scoped down from the every-module hammer,
    but by a known-LIGHT blocklist so unknown modules still clear).
    """
    yield
    if request.module.__name__.rsplit(".", 1)[-1] not in _COMPILE_LIGHT:
        jax.clear_caches()
