"""ot-session: served RC4 streaming sessions (serve/session.py).

Four layers, inside-out:

* the batched-PRGA device entries (``models/arc4.py``) — the vmapped
  ``prep_batch_words`` lane layout and the serve XOR against the
  pure-numpy PRGA oracle (``keystream_np``);
* the ``SessionManager`` engine over a host-oracle dispatcher — the
  bounded LRU store (tenant isolation, idle eviction, the
  eviction-mid-session REFUSAL), the keystream window/budget
  backpressure (shed, never wedge), the ``keystream_miss`` /
  ``session_stall`` / ``session_evict`` fault seams, and
  drain-with-open-sessions;
* the serve integration — an in-process ``Server`` with rc4 enabled:
  interleaved multi-session chunks bit-exact against the host oracle
  with ZERO post-warmup compiles, and the lane-kill drill (a hung lane
  quarantined mid-refill, the carry replayed bit-exactly on the
  healthy lane);
* the wire + router seams — the worker frontend's ``ss`` sub-protocol
  and the router's pin-required contract for session data.
"""

from __future__ import annotations

import asyncio

import numpy as np
import pytest

from our_tree_tpu.models import arc4
from our_tree_tpu.obs import metrics
from our_tree_tpu.resilience import degrade, faults
from our_tree_tpu.serve import session as session_mod
from our_tree_tpu.serve import wire
from our_tree_tpu.serve.queue import (ERR_BAD_REQUEST, ERR_SHED,
                                      ERR_SHUTDOWN, Response)
from our_tree_tpu.serve.server import Server, ServerConfig
from our_tree_tpu.serve.worker import RequestFrontend

LADDER = dict(min_bucket_blocks=32, max_bucket_blocks=256, lanes=1)


@pytest.fixture(autouse=True)
def _clean_process_state(monkeypatch):
    monkeypatch.delenv("OT_FAULTS", raising=False)
    monkeypatch.delenv("OT_DISPATCH_DEADLINE", raising=False)
    faults.reset()
    degrade.clear()
    metrics.reset_for_tests()
    yield
    monkeypatch.delenv("OT_FAULTS", raising=False)
    faults.reset()
    degrade.clear()
    metrics.reset_for_tests()


def _oracle_rows(m_words, xy_words, length: int) -> np.ndarray:
    """The host twin of ``arc4.prep_batch_words``: per-slot PRGA via the
    pure-numpy oracle, packed into the same (S, 258 + L/4) row layout."""
    S = int(xy_words.shape[0]) // 2
    rows = np.zeros((S, 258 + length // 4), np.uint32)
    for i in range(S):
        state = (int(xy_words[i]), int(xy_words[S + i]),
                 m_words[i * 256:(i + 1) * 256].astype(np.uint8))
        ks, (x2, y2, m2) = arc4.keystream_np(state, length)
        rows[i, 0], rows[i, 1] = x2, y2
        rows[i, 2:258] = m2
        rows[i, 258:] = np.frombuffer(np.asarray(ks, np.uint8).tobytes(),
                                      "<u4")
    return rows


def _host_dispatch(quantum: int):
    """A SessionManager dispatcher that runs the oracle on the host —
    the manager's engine logic exercised without a jax dispatch."""
    async def dispatch(m_words, xy_words, sampled):
        return _oracle_rows(m_words, xy_words, quantum), 0
    return dispatch


def _manager(quantum=1024, window=2048, slots=4, per_tenant=4,
             budget=1 << 20, dispatch=None):
    return session_mod.SessionManager(
        dispatch or _host_dispatch(quantum), per_tenant=per_tenant,
        window_bytes=window, quantum_bytes=quantum, prefetch_slots=slots,
        budget_bytes=budget)


# ---------------------------------------------------------------------------
# The device entries (models/arc4.py).
# ---------------------------------------------------------------------------


def test_prep_batch_words_matches_host_oracle():
    rng = np.random.default_rng(3)
    S, L = 3, 128
    m_words = np.zeros(S * 256, np.uint32)
    xy_words = np.zeros(2 * S, np.uint32)
    for i in range(S):
        key = rng.integers(0, 256, 16, dtype=np.uint8).tobytes()
        m_words[i * 256:(i + 1) * 256] = arc4.key_schedule(key)
    got = np.asarray(arc4.prep_batch_words(m_words, xy_words, L))
    want = _oracle_rows(m_words, xy_words, L)
    assert got.shape == want.shape
    assert np.array_equal(got, want)


def test_prep_batch_words_resumes_from_carry():
    # Two L-byte quanta from carries == one 2L run: the bit-exact
    # failover story's substrate (a carry is a pure resume point).
    key = bytes(range(16))
    m_words = arc4.key_schedule(key).astype(np.uint32)
    r1 = np.asarray(arc4.prep_batch_words(m_words, np.zeros(2, np.uint32),
                                          64))
    r2 = np.asarray(arc4.prep_batch_words(r1[0, 2:258], r1[0, :2], 64))
    ks = (r1[0, 258:].astype("<u4").tobytes()
          + r2[0, 258:].astype("<u4").tobytes())
    want, _ = arc4.keystream_np((0, 0, arc4.key_schedule(key)), 128)
    assert ks == np.asarray(want, np.uint8).tobytes()


def test_xor_words_is_the_crypt_phase():
    rng = np.random.default_rng(4)
    a = rng.integers(0, 2**32, 64, dtype=np.uint32)
    b = rng.integers(0, 2**32, 64, dtype=np.uint32)
    got = np.asarray(arc4.xor_words(a, b))
    assert np.array_equal(got, np.bitwise_xor(a, b))


# ---------------------------------------------------------------------------
# SessionManager over the host-oracle dispatcher.
# ---------------------------------------------------------------------------


def test_reserve_streams_bit_exact_and_hit_dominated():
    async def go():
        sm = _manager(quantum=1024, window=4096)
        key = b"\x01" * 16
        assert (await sm.open("t", 0, key)).ok
        ref = arc4.ARC4(key)
        for n in (256, 1024, 512):
            ks, off = await sm.reserve("t", 0, n)
            assert bytes(ks) == ref.prep(n, backend="np").tobytes()
            sm.ack("t", 0, off, n)
        st = sm.stats()
        # The open prefilled a whole window, so every chunk above was a
        # cache hit — the steady state the artifact gate pins >= 0.9.
        assert st["prefetch"]["hits"] == 3
        assert st["prefetch"]["misses"] == 0
        assert (await sm.close("t", 0)).ok
        await sm.drain()
    asyncio.run(go())


def test_tenant_isolation_same_sid_different_state():
    async def go():
        sm = _manager()
        ka, kb = b"\xaa" * 16, b"\xbb" * 16
        assert (await sm.open("ta", 7, ka)).ok
        assert (await sm.open("tb", 7, kb)).ok
        ra, rb = arc4.ARC4(ka), arc4.ARC4(kb)
        ks_a, off_a = await sm.reserve("ta", 7, 256)
        ks_b, off_b = await sm.reserve("tb", 7, 256)
        assert bytes(ks_a) == ra.prep(256, backend="np").tobytes()
        assert bytes(ks_b) == rb.prep(256, backend="np").tobytes()
        sm.ack("ta", 7, off_a, 256)
        sm.ack("tb", 7, off_b, 256)
        # Closing one tenant's sid 7 leaves the other's untouched.
        assert (await sm.close("ta", 7)).ok
        ks_b2, _ = await sm.reserve("tb", 7, 256)
        assert bytes(ks_b2) == rb.prep(256, backend="np").tobytes()
        await sm.drain()
    asyncio.run(go())


def test_store_lru_evicts_idle_and_refuses_busy():
    async def go():
        sm = _manager(per_tenant=2)
        for sid in (0, 1):
            assert (await sm.open("t", sid, bytes([sid]) * 16)).ok
        # Touch sid 0 so sid 1 is the LRU row; both are idle.
        _ks, off = await sm.reserve("t", 0, 256)
        sm.ack("t", 0, off, 256)
        assert (await sm.open("t", 2, b"\x02" * 16)).ok
        assert sm.stats()["evicted"] == 1
        r = await sm.reserve("t", 1, 16)  # the evicted LRU row
        assert isinstance(r, Response) and r.error == ERR_BAD_REQUEST
        # Now make every row busy (a reserved, unacked chunk) — the
        # eviction-mid-session refusal: open sheds instead of yanking
        # state from under in-flight chunks.
        for sid in (0, 2):
            await sm.reserve("t", sid, 256)
        r = await sm.open("t", 3, b"\x03" * 16)
        assert not r.ok and r.error == ERR_SHED
        assert sm.stats()["shed"] == 1
        await sm.drain()
    asyncio.run(go())


def test_keystream_budget_sheds_until_acks_release(monkeypatch):
    async def go():
        # One quantum of global budget: A's prefill pins it, B's open
        # sheds typed; acking A's chunk releases the window and B opens.
        sm = _manager(quantum=1024, window=1024, budget=1024)
        assert (await sm.open("t", 0, b"\x0a" * 16)).ok
        r = await sm.open("t", 1, b"\x0b" * 16)
        assert not r.ok and r.error == ERR_SHED
        assert sm.stats()["open"] == 1  # the shed open left no row
        ks, off = await sm.reserve("t", 0, 1024)
        assert len(ks) == 1024
        sm.ack("t", 0, off, 1024)
        assert sm.stats()["held_bytes"] == 0
        assert (await sm.open("t", 1, b"\x0b" * 16)).ok
        await sm.drain()
    asyncio.run(go())


def test_keystream_miss_regenerates_bit_exact(monkeypatch):
    async def go():
        sm = _manager(quantum=512, window=1024)
        key = b"\x42" * 16
        assert (await sm.open("t", 0, key)).ok
        ref = arc4.ARC4(key)
        ks, off = await sm.reserve("t", 0, 256)
        assert bytes(ks) == ref.prep(256, backend="np").tobytes()
        sm.ack("t", 0, off, 256)
        monkeypatch.setenv("OT_FAULTS", "keystream_miss:1@session=0")
        faults.reset()
        # The cached window is discarded at reserve; the engine
        # regenerates forward from the acked-checkpoint carry and the
        # bytes MUST be identical — the deterministic-PRGA guarantee.
        ks2, off2 = await sm.reserve("t", 0, 512)
        assert bytes(ks2) == ref.prep(512, backend="np").tobytes()
        sm.ack("t", 0, off2, 512)
        st = sm.stats()["prefetch"]
        assert st["injected_misses"] == 1 and st["replays"] >= 1
        await sm.drain()
    asyncio.run(go())


def test_session_stall_is_backpressure_not_a_wedge(monkeypatch):
    async def go():
        monkeypatch.setenv("OT_FAULTS", "session_stall:1@session=0")
        monkeypatch.setenv("OT_SLOW_S", "0.01")
        faults.reset()
        sm = _manager(quantum=512, window=512)
        key = b"\x05" * 16
        assert (await sm.open("t", 0, key)).ok  # the prefill stalls...
        ks, off = await sm.reserve("t", 0, 512)  # ...then serves
        assert bytes(ks) == arc4.ARC4(key).prep(
            512, backend="np").tobytes()
        sm.ack("t", 0, off, 512)
        assert sm.stats()["prefetch"]["stalls"] == 1
        await sm.drain()
    asyncio.run(go())


def test_session_evict_fault_forces_the_idle_path(monkeypatch):
    async def go():
        monkeypatch.setenv("OT_FAULTS", "session_evict:1@session=1")
        faults.reset()
        sm = _manager(per_tenant=8)
        assert (await sm.open("t", 0, b"\x00" * 16)).ok
        # The rehearsal: the next open force-evicts the LRU-idle row
        # even though the store is nowhere near capacity.
        assert (await sm.open("t", 1, b"\x01" * 16)).ok
        assert sm.stats()["evicted"] == 1
        r = await sm.reserve("t", 0, 16)
        assert isinstance(r, Response) and r.error == ERR_BAD_REQUEST
        await sm.drain()
    asyncio.run(go())


def test_drain_with_open_sessions_counts_and_refuses():
    async def go():
        sm = _manager()
        for sid in (0, 1):
            assert (await sm.open("t", sid, bytes([sid]) * 16)).ok
        await sm.drain()
        assert sm.stats()["drained_open"] == 2
        r = await sm.open("t", 9, b"\x09" * 16)
        assert not r.ok and r.error == ERR_SHUTDOWN
        r = await sm.reserve("t", 0, 16)
        assert isinstance(r, Response) and r.error == ERR_BAD_REQUEST
    asyncio.run(go())


def test_open_validates_sid_and_key():
    async def go():
        sm = _manager()
        assert (await sm.open("t", "x", b"\x01" * 16)).error == \
            ERR_BAD_REQUEST
        assert (await sm.open("t", -1, b"\x01" * 16)).error == \
            ERR_BAD_REQUEST
        assert (await sm.open("t", 0, b"")).error == ERR_BAD_REQUEST
        assert (await sm.open("t", 0, b"\x01" * 16)).ok
        r = await sm.open("t", 0, b"\x01" * 16)  # double open
        assert not r.ok and r.error == ERR_BAD_REQUEST
        await sm.drain()
    asyncio.run(go())


# ---------------------------------------------------------------------------
# Serve integration: rc4 sessions through an in-process Server.
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def served_rc4():
    """One in-process rc4-enabled Server + frontend (module-scoped: the
    warmup — every rung's XOR program plus the one fixed-shape prep
    program — is the expensive part)."""
    server = Server(ServerConfig(status_port=None, modes=("ctr", "rc4"),
                                 session_quantum_bytes=2048,
                                 session_prefetch_slots=2,
                                 session_window_bytes=4096, **LADDER))
    loop = asyncio.new_event_loop()
    loop.run_until_complete(server.start())
    front = RequestFrontend(server, 0)
    loop.run_until_complete(front.start())
    yield loop, server, front
    loop.run_until_complete(front.stop())
    loop.run_until_complete(server.stop())
    loop.close()


def test_server_interleaved_sessions_bit_exact_no_recompiles(served_rc4):
    loop, server, _front = served_rc4
    base = server.steady_compiles()
    rng = np.random.default_rng(17)
    keys = {i: rng.integers(0, 256, 16, dtype=np.uint8).tobytes()
            for i in range(3)}
    refs = {i: arc4.ARC4(k) for i, k in keys.items()}

    async def go():
        for i in range(3):
            r = await server.open_session(f"t{i % 2}", i, keys[i])
            assert r.ok, (r.error, r.detail)
        for rnd in range(3):
            for i in range(3):
                n = 16 * int(rng.integers(1, 64))
                data = rng.integers(0, 256, n, dtype=np.uint8)
                r = await server.submit(f"t{i % 2}", b"", b"", data,
                                        mode="rc4", sid=i)
                assert r.ok, (i, rnd, r.error, r.detail)
                ks = refs[i].prep(n, backend="np")
                assert np.asarray(r.payload, np.uint8).tobytes() == \
                    np.bitwise_xor(data, ks).tobytes(), (i, rnd)
        for i in range(3):
            assert (await server.close_session(f"t{i % 2}", i)).ok
    loop.run_until_complete(go())
    st = server.stats()["sessions"]
    assert st["chunks"] == 9 and st["closed"] >= 3
    # The zero-recompile contract holds with session traffic riding:
    # every XOR rung and the one prep shape were primed at warmup.
    assert server.steady_compiles() - base == 0


def test_server_rc4_without_session_is_refused(served_rc4):
    loop, server, _front = served_rc4
    data = np.zeros(64, np.uint8)
    r = loop.run_until_complete(
        server.submit("t0", b"", b"", data, mode="rc4", sid=999))
    assert not r.ok and r.error == ERR_BAD_REQUEST
    r = loop.run_until_complete(
        server.submit("t0", b"", b"", data, mode="rc4"))  # sid missing
    assert not r.ok and r.error == ERR_BAD_REQUEST


def test_server_without_rc4_mode_has_no_session_store():
    server = Server(ServerConfig(status_port=None, **LADDER))
    assert server.sessions is None

    async def go():
        await server.start()
        try:
            return await server.open_session("t", 0, b"\x01" * 16)
        finally:
            await server.stop()
    r = asyncio.run(go())
    assert not r.ok and r.error == ERR_BAD_REQUEST


def test_lane_hang_mid_refill_replays_carry_bit_exact(monkeypatch):
    """The lane-kill drill at the session seam: a hung lane is
    quarantined by the watchdog and the SAME carry re-dispatches on the
    healthy lane — every chunk byte-identical to the host oracle."""
    monkeypatch.setenv("OT_FAULTS", "lane_hang:1")
    monkeypatch.setenv("OT_DISPATCH_DEADLINE", "2")
    faults.reset()
    server = Server(ServerConfig(status_port=None, modes=("ctr", "rc4"),
                                 min_bucket_blocks=32,
                                 max_bucket_blocks=256, lanes=2,
                                 session_quantum_bytes=2048,
                                 session_prefetch_slots=2,
                                 session_window_bytes=4096))
    key = bytes(range(16))
    ref = arc4.ARC4(key)

    async def go():
        await server.start()
        try:
            assert (await server.open_session("t", 0, key)).ok
            rng = np.random.default_rng(1)
            for i in range(6):
                data = rng.integers(0, 256, 16 * 128, dtype=np.uint8)
                r = await server.submit("t", b"", b"", data,
                                        mode="rc4", sid=0)
                assert r.ok, (i, r.error, r.detail)
                ks = ref.prep(data.size, backend="np")
                assert np.asarray(r.payload, np.uint8).tobytes() == \
                    np.bitwise_xor(data, ks).tobytes(), i
            return server.stats(), server.pool.quarantine_events()
        finally:
            await server.stop()

    stats, quarantines = asyncio.run(go())
    assert quarantines == 1
    assert stats["sessions"]["prefetch"]["replays"] >= 1


# ---------------------------------------------------------------------------
# The ss wire sub-protocol + the router's pin contract.
# ---------------------------------------------------------------------------


async def _ss_exchange(port: int, frames: list[tuple[dict, bytes]]):
    """Send each (header, payload) frame and collect one answer per."""
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    out = []
    try:
        for header, payload in frames:
            writer.write(wire.encode_frame(header, payload))
            await writer.drain()
            h, body = await wire.read_frame(reader)
            out.append((h, body))
        return out
    finally:
        writer.close()


def test_worker_ss_protocol_round_trip(served_rc4):
    loop, _server, front = served_rc4
    key = b"\x77" * 16
    ref = arc4.ARC4(key)
    rng = np.random.default_rng(23)
    chunks = [rng.integers(0, 256, 16 * n, dtype=np.uint8)
              for n in (4, 32)]
    frames = [({"ss": "open", "t": "wt", "sid": 5, "k": key.hex()}, b"")]
    frames += [({"ss": "data", "t": "wt", "sid": 5}, c.tobytes())
               for c in chunks]
    frames.append(({"ss": "close", "t": "wt", "sid": 5}, b""))
    answers = loop.run_until_complete(_ss_exchange(front.port, frames))
    assert answers[0][0]["ok"] and answers[0][0]["ss"] == "open"
    for c, (h, body) in zip(chunks, answers[1:-1]):
        assert h["ok"] and h["ss"] == "data"
        ks = ref.prep(c.size, backend="np")
        assert body == np.bitwise_xor(c, ks).tobytes()
    assert answers[-1][0]["ok"] and answers[-1][0]["ss"] == "close"


def test_worker_ss_frame_validation(served_rc4):
    loop, _server, front = served_rc4
    answers = loop.run_until_complete(_ss_exchange(front.port, [
        ({"ss": "open", "t": "wt", "sid": "nope"}, b""),
        ({"ss": "bogus-op", "t": "wt", "sid": 1}, b""),
        ({"ss": "data", "t": "wt", "sid": 404}, b"\x00" * 16),
    ]))
    for h, _body in answers:
        assert not h["ok"] and h["error"] == ERR_BAD_REQUEST


def test_router_session_data_requires_a_pin():
    from our_tree_tpu.route.proxy import (BackendSpec, Router,
                                          RouterConfig)
    router = Router([BackendSpec("b0", "127.0.0.1", 1)], RouterConfig())
    r = asyncio.run(router.submit_session("t", 3, b"\x00" * 16))
    assert not r.ok and r.error == ERR_BAD_REQUEST
    assert "not open" in r.detail
