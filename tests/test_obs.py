"""Run-wide tracing & metrics (our_tree_tpu/obs): the tracer contract —
JSONL schema stability, span nesting across process boundaries — the
report CLI's golden output on a synthetic run, per-worker-row journal
resume (spans recording replayed-vs-fresh rows), the quarantine-release
flow, and the fault-matrix acceptance run: injected faults appear as
trace events and the hung child's span reads as closed by SIGKILL."""

import io
import json
import os
import pathlib
import subprocess
import sys

import pytest

from our_tree_tpu.obs import export, report, trace
from our_tree_tpu.resilience import faults, isolate

ROOT = pathlib.Path(__file__).resolve().parent.parent
TRACE_PY = str(ROOT / "our_tree_tpu" / "obs" / "trace.py")

#: The journal-suite's fast deterministic sweep config (fake clock,
#: portable C), plus two worker counts so units have two ROWS.
ARGS = ["--backend", "c", "--modes", "ecb", "--sizes-mb", "0.0625",
        "--workers", "1,2", "--iters", "2"]
ENV = {"OT_FAKE_TIME_US": "7", "OT_C_FORCE_PORTABLE": "1",
       "JAX_PLATFORMS": "cpu", "PYTHONPATH": ""}


@pytest.fixture
def traced(tmp_path, monkeypatch):
    """Point the process-global tracer at a fresh dir with a pinned run
    id; reset its state on both sides (it is process-global on purpose)."""
    monkeypatch.setenv("OT_TRACE_DIR", str(tmp_path / "tr"))
    monkeypatch.setenv("OT_TRACE_RUN", "t-run")
    monkeypatch.delenv("OT_TRACE_PARENT", raising=False)
    trace.reset_for_tests()
    yield tmp_path / "tr" / "t-run"
    trace.reset_for_tests()


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("OT_FAULTS", raising=False)
    faults.reset()
    yield
    monkeypatch.delenv("OT_FAULTS", raising=False)
    faults.reset()


def _env(extra=None):
    env = dict(os.environ)
    env.update(ENV)
    # This pytest process may itself be traced (the `traced` fixture);
    # subprocess runs must not join ITS run unless the test says so.
    env.pop("OT_TRACE_DIR", None)
    env.pop("OT_TRACE_RUN", None)
    env.pop("OT_TRACE_PARENT", None)
    env.update(extra or {})
    return env


# ---------------------------------------------------------------------------
# Tracer contract.
# ---------------------------------------------------------------------------


def test_disabled_tracer_is_inert(tmp_path, monkeypatch):
    monkeypatch.delenv("OT_TRACE_DIR", raising=False)
    trace.reset_for_tests()
    assert not trace.enabled()
    with trace.span("x", a=1) as sp:
        assert sp is None
    trace.counter("c")
    trace.point("p")
    assert trace.run_id() is None and trace.ensure_run() is None
    assert trace.metrics_snapshot()["spans"] == 0
    assert trace.child_env({"A": "1"}) == {"A": "1"}


def test_jsonl_schema_and_nesting(traced):
    """Schema stability: exact key sets per event type, parent ids from
    the thread-local span stack, error statuses from exceptions."""
    with trace.span("outer", unit="u1") as outer:
        with trace.span("inner") as inner:
            trace.counter("hits", 2, where="inner")
            trace.gauge("depth", 1.5)
        trace.point("marker", note="x")
    with pytest.raises(ValueError):
        with trace.span("boom"):
            raise ValueError("no")
    files = list(traced.glob("trace-*.jsonl"))
    assert len(files) == 1
    recs = [json.loads(line) for line in files[0].read_text().splitlines()]
    header, events = recs[0], recs[1:]
    assert set(header) == {"kind", "v", "run", "pid", "proc", "argv",
                           "start_us"}
    assert header["kind"] == "ot-trace" and header["v"] == 1
    assert header["run"] == "t-run" and header["pid"] == os.getpid()
    by_ev = {}
    for r in events:
        by_ev.setdefault(r["ev"], []).append(r)
    assert set(by_ev) == {"b", "e", "c", "g", "p"}
    for b in by_ev["b"]:
        assert set(b) <= {"ev", "id", "parent", "name", "ts", "tid", "attrs"}
        assert set(b) >= {"ev", "id", "parent", "name", "ts", "tid"}
    for e in by_ev["e"]:
        assert set(e) == {"ev", "id", "ts", "status"}
    assert set(by_ev["c"][0]) == {"ev", "name", "ts", "n", "attrs"}
    assert set(by_ev["g"][0]) == {"ev", "name", "ts", "value"}
    assert set(by_ev["p"][0]) == {"ev", "name", "ts", "attrs"}
    # Nesting: inner.parent == outer.id; outer is a root (parent None).
    b = {r["name"]: r for r in by_ev["b"]}
    assert b["outer"]["parent"] is None
    assert b["inner"]["parent"] == outer.id
    assert inner.id != outer.id
    # End statuses: ok for clean exits, error:<Type> for the raise.
    status = {r["id"]: r["status"] for r in by_ev["e"]}
    assert status[outer.id] == "ok"
    assert status[b["boom"]["id"]] == "error:ValueError"
    # The aggregate snapshot mirrors the stream.
    snap = trace.metrics_snapshot()
    assert snap["run"] == "t-run" and snap["spans"] == 3
    assert snap["counters"] == {"hits": 2} and snap["gauges"] == {"depth": 1.5}
    # load_run agrees and sees no orphans or violations.
    run = export.load_run(str(traced))
    assert not run.violations and not run.orphans()
    assert run.counter_totals() == {"hits": 2}
    assert run.ancestor_attr(run.spans[inner.id], "unit") == "u1"


def test_span_nesting_across_process_boundary(traced):
    """A subprocess spawned through isolate.run_child inherits the run id
    and a parent span id (child_env), so its spans nest under the
    caller's live span in the merged run."""
    code = (
        "import importlib.util, sys\n"
        f"spec = importlib.util.spec_from_file_location("
        f"'our_tree_tpu.obs.trace', {TRACE_PY!r})\n"
        "m = importlib.util.module_from_spec(spec)\n"
        "sys.modules['our_tree_tpu.obs.trace'] = m\n"
        "spec.loader.exec_module(m)\n"
        "with m.span('childwork'):\n"
        "    pass\n")
    with trace.span("parentwork", unit="xp"):
        r = isolate.run_child([sys.executable, "-c", code], 60,
                              name="obs-test")
    assert r.ok, (r.out, r.err)
    run = export.load_run(str(traced))
    assert not run.violations and not run.orphans()
    childwork = next(s for s in run.spans.values() if s.name == "childwork")
    # The chain crosses the process boundary: childwork -> (run_child's
    # "child" span) -> parentwork, so the parent's unit attr resolves.
    assert run.spans[childwork.parent].name == "child"
    assert run.ancestor_attr(childwork, "unit") == "xp"
    assert {s.name for s in run.spans.values()} == {"parentwork", "child",
                                                    "childwork"}


# ---------------------------------------------------------------------------
# Report golden output on a synthetic run.
# ---------------------------------------------------------------------------


def _synthetic_run(d: pathlib.Path) -> None:
    """A hand-written two-process run: a supervisor whose first unit
    attempt was killed (the child's spans never closed), then failed,
    quarantined; fixed timestamps so the report is byte-stable."""
    sup = [
        {"kind": "ot-trace", "v": 1, "run": "synth", "pid": 100,
         "proc": "aaaa0000", "argv": "bench --isolate", "start_us": 1000000},
        {"ev": "b", "id": "aaaa0000.1", "parent": None, "name": "sweep",
         "ts": 1000000, "tid": 0},
        {"ev": "b", "id": "aaaa0000.2", "parent": "aaaa0000.1",
         "name": "unit-attempt", "ts": 1100000, "tid": 0,
         "attrs": {"unit": "ecb:65536", "attempt": 1}},
        {"ev": "b", "id": "aaaa0000.3", "parent": "aaaa0000.2",
         "name": "child", "ts": 1150000, "tid": 0,
         "attrs": {"label": "isolate:ecb:65536", "attempt": 0}},
        {"ev": "p", "name": "child-killed", "ts": 3150000,
         "attrs": {"label": "isolate:ecb:65536", "wall_s": 2.0}},
        {"ev": "e", "id": "aaaa0000.3", "ts": 3160000, "status": "ok"},
        {"ev": "e", "id": "aaaa0000.2", "ts": 3170000, "status": "ok"},
        {"ev": "p", "name": "unit-failed", "ts": 3180000,
         "attrs": {"unit": "ecb:65536", "reason": "timeout:2s",
                   "attempt": 1}},
        {"ev": "p", "name": "quarantine", "ts": 3190000,
         "attrs": {"unit": "ecb:65536", "fails": 1}},
        {"ev": "p", "name": "degrade", "ts": 3200000,
         "attrs": {"kind": "quarantined:ecb:65536",
                   "why": "1 recorded failure(s)"}},
        {"ev": "e", "id": "aaaa0000.1", "ts": 3500000, "status": "ok"},
    ]
    child = [
        {"kind": "ot-trace", "v": 1, "run": "synth", "pid": 200,
         "proc": "bbbb0000", "argv": "bench --isolate-child ecb:65536",
         "start_us": 1200000},
        {"ev": "b", "id": "bbbb0000.1", "parent": "aaaa0000.3",
         "name": "unit", "ts": 1210000, "tid": 0,
         "attrs": {"unit": "ecb:65536"}},
        {"ev": "b", "id": "bbbb0000.2", "parent": "bbbb0000.1",
         "name": "row", "ts": 1220000, "tid": 0,
         "attrs": {"mode": "ecb", "size": 65536, "workers": 1}},
        {"ev": "b", "id": "bbbb0000.3", "parent": "bbbb0000.2",
         "name": "timed-call", "ts": 1230000, "tid": 0,
         "attrs": {"seam": "harness._time_us"}},
        {"ev": "p", "name": "fault-injected", "ts": 1240000,
         "attrs": {"point": "dispatch_hang", "left": 0}},
    ]
    d.mkdir(parents=True)
    for fname, recs in (("trace-100-aaaa0000.jsonl", sup),
                        ("trace-200-bbbb0000.jsonl", child)):
        (d / fname).write_text(
            "".join(json.dumps(r, separators=(",", ":")) + "\n"
                    for r in recs))


GOLDEN = """\
run synth: 2 process(es), 6 span(s) (3 orphaned), 5 event(s), wall 2.500s
schema: OK

per-unit:
  unit       attempts  wall_s  device_s  rows f/r  failures  outcome
  ecb:65536  1         2.070   0.000     1/0       1         quarantined

faults injected: dispatch_hang x1
faults observed: child-killed=1, unit-failed=1, watchdog-expired=0
degradations: quarantined:ecb:65536 (1 recorded failure(s))
quarantined: ecb:65536

slowest spans (top 5):
  span          unit       pid  dur_s  status
  sweep         -          100  2.500  ok
  unit          ecb:65536  200  2.290  killed
  row           ecb:65536  200  2.280  killed
  timed-call    ecb:65536  200  2.270  killed
  unit-attempt  ecb:65536  100  2.070  ok

orphaned spans (3 — begin with no end: the process was killed or died mid-span):
  unit (unit=ecb:65536, pid 200) open 2.290s until end of run — closed by kill
  row (unit=ecb:65536, pid 200) open 2.280s until end of run — closed by kill
  timed-call (unit=ecb:65536, pid 200) open 2.270s until end of run — closed by kill
"""


def test_report_golden_on_synthetic_run(tmp_path):
    d = tmp_path / "synth"
    _synthetic_run(d)
    run = export.load_run(str(d))
    out = io.StringIO()
    report.render(run, top=5, out=out)
    assert out.getvalue() == GOLDEN
    # --check semantics: orphans present -> nonzero.
    assert report.main([str(d), "--check"]) == 2
    # The expected-orphan allowlist (the FAULTED-run gate): naming exactly
    # the killed child's open spans passes, naming only some still fails —
    # an unexpected orphan can never hide behind the allowlist.
    assert report.main([str(d), "--check",
                        "--expected-orphans", "unit,row,timed-call"]) == 0
    assert report.main([str(d), "--check",
                        "--expected-orphans", "unit,row"]) == 2
    # The Perfetto export loads as Trace Event Format and carries the
    # kill evidence.
    path = tmp_path / "trace.json"
    export.write_chrome_trace(run, str(path))
    t = json.loads(path.read_text())
    evs = t["traceEvents"]
    assert evs and all("ph" in e and "pid" in e for e in evs)
    killed = [e for e in evs
              if e["ph"] == "X" and e.get("args", {}).get("killed")]
    assert {e["name"] for e in killed} == {"unit", "row", "timed-call"}
    assert any(e["ph"] == "i" and e["name"] == "fault-injected"
               for e in evs)


def test_report_expected_orphans_budget_is_per_name(tmp_path):
    """Each listed name licenses ONE orphan: two killed children's `unit`
    orphans cannot both hide behind a single `unit` entry — the gate for
    a rehearsal that kills one child must go red when two die."""
    d = tmp_path / "two"
    d.mkdir()
    recs = [
        {"kind": "ot-trace", "v": 1, "run": "r", "pid": 1, "proc": "dddd0000",
         "argv": "x", "start_us": 1000000},
        {"ev": "b", "id": "dddd0000.1", "parent": None, "name": "unit",
         "ts": 1000000, "tid": 0, "attrs": {"unit": "a"}},
        {"ev": "b", "id": "dddd0000.2", "parent": None, "name": "unit",
         "ts": 1100000, "tid": 0, "attrs": {"unit": "b"}},
    ]
    (d / "trace-1-dddd0000.jsonl").write_text(
        "".join(json.dumps(r, separators=(",", ":")) + "\n" for r in recs))
    assert report.main([str(d), "--check",
                        "--expected-orphans", "unit"]) == 2
    assert report.main([str(d), "--check",
                        "--expected-orphans", "unit,unit"]) == 0


def test_report_per_engine_device_time_table(tmp_path):
    """Spans carrying the `engine` attr (the root bench's probe/measure
    spans) aggregate into a per-engine device-time table; nested
    device-seam spans inherit the engine via the ancestor chain without
    double-counting, and an engine-less run renders no table (the
    golden test pins that absence)."""
    d = tmp_path / "eng"
    d.mkdir()
    recs = [
        {"kind": "ot-trace", "v": 1, "run": "r", "pid": 1, "proc": "cccc0000",
         "argv": "bench", "start_us": 1000000},
        # Two probe measures on one engine, one on another; a barrier
        # nested INSIDE a measure must not double its time.
        {"ev": "b", "id": "cccc0000.1", "parent": None, "name": "measure",
         "ts": 1000000, "tid": 0, "attrs": {"engine": "pallas-gt", "mib": 4}},
        {"ev": "b", "id": "cccc0000.2", "parent": "cccc0000.1",
         "name": "barrier", "ts": 1100000, "tid": 0},
        {"ev": "e", "id": "cccc0000.2", "ts": 1400000, "status": "ok"},
        {"ev": "e", "id": "cccc0000.1", "ts": 2000000, "status": "ok"},
        {"ev": "b", "id": "cccc0000.3", "parent": None, "name": "measure",
         "ts": 2000000, "tid": 0, "attrs": {"engine": "pallas-gt", "mib": 4}},
        {"ev": "e", "id": "cccc0000.3", "ts": 2500000, "status": "ok"},
        {"ev": "b", "id": "cccc0000.4", "parent": None, "name": "measure",
         "ts": 2500000, "tid": 0, "attrs": {"engine": "bitslice", "mib": 4}},
        {"ev": "e", "id": "cccc0000.4", "ts": 2600000, "status": "ok"},
    ]
    (d / "trace-1-cccc0000.jsonl").write_text(
        "".join(json.dumps(r, separators=(",", ":")) + "\n" for r in recs))
    out = io.StringIO()
    report.render(export.load_run(str(d)), out=out)
    text = out.getvalue()
    assert "per-engine device time:" in text
    lines = [l.strip() for l in text.splitlines()]
    i = lines.index("engine     spans  device_s")
    assert lines[i + 1] == "pallas-gt  2      1.500"  # 1.0s + 0.5s, no double
    assert lines[i + 2] == "bitslice   1      0.100"


def test_report_check_flags_schema_violations(tmp_path):
    d = tmp_path / "bad"
    d.mkdir()
    (d / "trace-1-x.jsonl").write_text(
        json.dumps({"kind": "ot-trace", "v": 1, "run": "r", "pid": 1,
                    "proc": "x", "argv": "", "start_us": 0}) + "\n"
        + json.dumps({"ev": "b", "id": "x.1", "ts": 5}) + "\n"  # no name
        + "{torn")
    run = export.load_run(str(d))
    assert len(run.violations) == 2
    assert report.main([str(d), "--check"]) == 2


# ---------------------------------------------------------------------------
# Per-worker-row journal granularity (+ replayed-vs-fresh spans).
# ---------------------------------------------------------------------------


def _run_bench(out, journal, extra_args=(), extra_env=None, timeout=300):
    argv = [sys.executable, "-m", "our_tree_tpu.harness.bench", *ARGS,
            "--journal", str(journal), "--out", str(out), *extra_args]
    return subprocess.run(argv, env=_env(extra_env), cwd=ROOT,
                          capture_output=True, text=True, timeout=timeout)


def _points(run_dir, name):
    out = []
    for f in pathlib.Path(run_dir).glob("*/trace-*.jsonl"):
        for line in f.read_text().splitlines():
            rec = json.loads(line)
            if rec.get("ev") == "p" and rec.get("name") == name:
                out.append(rec.get("attrs", {}))
    return out


def test_row_granularity_resume_at_last_completed_row(tmp_path):
    """A unit that dies on its SECOND worker row (dispatch_hang:1@2 — the
    @skip grammar defers the hang past row 1's two timed calls) resumes
    at row 2: row 1 replays from its journal checkpoint, the resumed
    corpus is byte-identical to an uninterrupted run's, and the trace
    records the replayed row as a point and the fresh one as a span."""
    ref = _run_bench(tmp_path / "ref.txt", tmp_path / "jref.jsonl")
    assert ref.returncode == 0, ref.stderr[-2000:]

    r1 = _run_bench(tmp_path / "r1.txt", tmp_path / "j.jsonl",
                    ["--dispatch-deadline", "6"],
                    {"OT_FAULTS": "dispatch_hang:1@2", "OT_HANG_S": "60",
                     "OT_CRASH_DIR": str(tmp_path / "crash")})
    assert r1.returncode == 0, r1.stderr[-2000:]
    assert "# watchdog:" in r1.stderr
    recs = [json.loads(line) for line in open(tmp_path / "j.jsonl")][1:]
    rows = [e for e in recs if e.get("row") is not None]
    assert [(e["unit"], e["row"]) for e in rows] == [("ecb:65536", "1")]
    fails = [e for e in recs if e.get("failed")]
    assert len(fails) == 1 and fails[0]["reason"].startswith("watchdog:")

    r2 = _run_bench(tmp_path / "r2.txt", tmp_path / "j.jsonl",
                    extra_env={"OT_TRACE_DIR": str(tmp_path / "tr")})
    assert r2.returncode == 0, r2.stderr[-2000:]
    assert (tmp_path / "r2.txt").read_bytes() == \
        (tmp_path / "ref.txt").read_bytes()
    # Replayed-vs-fresh in the trace: row 1 replayed as a point, row 2 a
    # fresh "row" span with workers=2.
    assert _points(tmp_path / "tr", "row-replayed") == [
        {"unit": "ecb:65536", "row": "1"}]
    run = export.load_run(str(next((tmp_path / "tr").iterdir())))
    fresh = [s for s in run.spans.values() if s.name == "row"]
    assert [s.attrs["workers"] for s in fresh] == [2]


# ---------------------------------------------------------------------------
# Quarantine release (--unquarantine).
# ---------------------------------------------------------------------------


def test_unquarantine_clears_failures_and_traces_release(tmp_path):
    iso = ["--isolate", "--unit-deadline", "15", "--quarantine-after", "1"]
    r1 = _run_bench(tmp_path / "r1.txt", tmp_path / "j.jsonl", iso,
                    {"OT_FAULTS": "dispatch_hang:1"})
    assert r1.returncode == 0, r1.stderr[-2000:]
    assert "quarantined:ecb:65536" in r1.stderr

    rq = subprocess.run(
        [sys.executable, "-m", "our_tree_tpu.harness.bench",
         "--journal", str(tmp_path / "j.jsonl"),
         "--unquarantine", "ecb:65536"],
        env=_env({"OT_TRACE_DIR": str(tmp_path / "tr")}), cwd=ROOT,
        capture_output=True, text=True, timeout=120)
    assert rq.returncode == 0, rq.stderr[-2000:]
    assert "cleared 1 failure row(s)" in rq.stderr
    assert _points(tmp_path / "tr", "quarantine-release") == [
        {"unit": "ecb:65536", "cleared": 1}]
    recs = [json.loads(line) for line in open(tmp_path / "j.jsonl")][1:]
    assert not [e for e in recs if e.get("failed")]

    # The released unit runs again (no quarantine skip, no degraded
    # trailer) on the next sweep.
    r2 = _run_bench(tmp_path / "r2.txt", tmp_path / "j.jsonl", iso)
    assert r2.returncode == 0, r2.stderr[-2000:]
    out2 = (tmp_path / "r2.txt").read_text()
    assert "quarantined" not in out2
    assert "ECB, 65536, 1" in out2.replace("C AES-256 ECB", "ECB")


def test_unquarantine_requires_journal():
    r = subprocess.run(
        [sys.executable, "-m", "our_tree_tpu.harness.bench",
         "--unquarantine", "ecb:65536"],
        env=_env(), cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert r.returncode == 2 and "--journal" in r.stderr


# ---------------------------------------------------------------------------
# Fault-matrix acceptance: kill -> retry -> quarantine, all in the trace.
# ---------------------------------------------------------------------------


def test_fault_matrix_run_traces_kill_retry_quarantine(tmp_path):
    """The PR's acceptance scenario: OT_TRACE_DIR + OT_FAULTS under
    --isolate yields a trace where the hung child's span is closed by
    SIGKILL (orphaned), its retry and the quarantine are events, the
    report shows per-unit timings, and the Perfetto export loads."""
    tr = tmp_path / "tr"
    r = _run_bench(tmp_path / "out.txt", tmp_path / "j.jsonl",
                   ["--isolate", "--unit-deadline", "15",
                    "--quarantine-after", "2"],
                   {"OT_FAULTS": "dispatch_hang:2",
                    "OT_TRACE_DIR": str(tr)})
    assert r.returncode == 0, r.stderr[-2000:]
    run_dir = str(next(tr.iterdir()))
    run = export.load_run(run_dir)
    assert not run.violations
    # Injected faults appear as trace events — exactly the two CHILD
    # firings (the supervisor's metering is bookkeeping, not injection).
    inj = _points(tr, "fault-injected")
    assert [a["point"] for a in inj] == ["dispatch_hang", "dispatch_hang"]
    # The hung children's dispatch spans never closed: orphans, i.e.
    # closed by the supervisor's SIGKILL; both attempts are spans.
    orphan_names = {s.name for s in run.orphans()}
    assert "timed-call" in orphan_names
    attempts = [s for s in run.spans.values() if s.name == "unit-attempt"
                and s.attrs.get("unit") == "ecb:65536"]
    assert sorted(s.attrs["attempt"] for s in attempts) == [1, 2]
    assert len(_points(tr, "child-killed")) == 2
    assert _points(tr, "quarantine") == [{"unit": "ecb:65536", "fails": 2}]
    # The report renders the story and --check flags the orphans.
    out = io.StringIO()
    report.render(run, out=out)
    text = out.getvalue()
    assert "quarantined" in text and "closed by kill" in text
    assert report.main([run_dir, "--check"]) == 2
    # Perfetto export: loads as JSON, kill evidence in args.
    path = tmp_path / "trace.json"
    export.write_chrome_trace(run, str(path))
    t = json.loads(path.read_text())
    assert any(e.get("args", {}).get("killed") for e in t["traceEvents"])


# ---------------------------------------------------------------------------
# The bench JSON line's "obs" stamp.
# ---------------------------------------------------------------------------


def test_root_bench_report_stamps_obs_snapshot(tmp_path, monkeypatch, capsys):
    monkeypatch.setenv("OT_TRACE_DIR", str(tmp_path / "tr"))
    monkeypatch.setenv("OT_TRACE_RUN", "t-bench")
    trace.reset_for_tests()
    try:
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "rootbench_obs", ROOT / "bench.py")
        rb = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(rb)
        with trace.span("measure", engine="test"):
            pass
        rb._report(16 << 20, "cpu", "test-engine", 0x1, 1.5,
                   (1.0, 2.0, 3))
        line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert line["obs"]["run"] == "t-bench"
        assert line["obs"]["spans"] >= 1
    finally:
        trace.reset_for_tests()


# ---------------------------------------------------------------------------
# Detached spans (the serve path's overlapping lifecycles) and the
# OT_TRACE_MAX_MB soak-run rotation.
# ---------------------------------------------------------------------------


def test_detached_spans_overlap_without_stack_corruption(traced):
    """Two detached spans closed in FIFO (non-LIFO) order must not
    disturb the parentage of regular spans opened in between, and a
    deliberately unexited one is an orphan."""
    cm_a = trace.detached_span("request-queued", req=0)
    sp_a = cm_a.__enter__()
    cm_b = trace.detached_span("request-queued", req=1)
    cm_b.__enter__()
    with trace.span("batch-formed") as formed:
        assert trace.current_span_id() == formed.id
    cm_a.__exit__(None, None, None)   # FIFO: a before b
    cm_a.__exit__(None, None, None)   # idempotent: second exit is a no-op
    cm_b.__exit__(TimeoutError, None, None)
    with trace.span("outer") as outer:
        # Detached begins while a regular span is live adopt it as parent.
        cm_c = trace.detached_span("batch-dispatched")
        sp_c = cm_c.__enter__()
        assert trace.current_span_id() == outer.id  # stack untouched
    run = export.load_run(str(traced))
    assert not run.violations
    a, c = run.spans[sp_a.id], run.spans[sp_c.id]
    assert a.status == "ok" and a.parent is None
    assert c.orphan and c.parent == outer.id  # cm_c never exited
    assert [s.name for s in run.orphans()] == ["batch-dispatched"]
    statuses = {s.attrs.get("req"): s.status for s in run.spans.values()
                if s.name == "request-queued"}
    assert statuses == {0: "ok", 1: "error:TimeoutError"}


def test_trace_rotation_caps_disk(tmp_path, monkeypatch):
    """With OT_TRACE_MAX_MB set, the event file rotates into segments
    and the oldest are deleted: total size stays under the cap, every
    surviving segment is a valid self-describing trace file, and the
    newest events survive."""
    monkeypatch.setenv("OT_TRACE_DIR", str(tmp_path / "tr"))
    monkeypatch.setenv("OT_TRACE_RUN", "t-rot")
    cap_mb = 0.05  # 50 KiB cap -> ~12 KiB segments
    monkeypatch.setenv("OT_TRACE_MAX_MB", str(cap_mb))
    trace.reset_for_tests()
    try:
        n = 2000
        for i in range(n):
            trace.point("soak", i=i, pad="x" * 80)
    finally:
        files = sorted((tmp_path / "tr" / "t-rot").glob("trace-*.jsonl"))
        trace.reset_for_tests()
        monkeypatch.delenv("OT_TRACE_MAX_MB")
    assert len(files) > 1  # it rotated
    total = sum(f.stat().st_size for f in files)
    assert total <= cap_mb * (1 << 20) * 1.1  # capped (one event of slack)
    last_seen = -1
    for f in files:
        recs = [json.loads(l) for l in f.read_text().splitlines()]
        assert recs[0]["kind"] == "ot-trace" and recs[0]["v"] == 1
        pts = [r for r in recs[1:] if r.get("ev") == "p"]
        assert pts, f"segment {f.name} carries no events"
        last_seen = max(last_seen, max(r["attrs"]["i"] for r in pts))
    assert last_seen == n - 1  # the newest history survives
    # Early history was evicted: that is the documented soak tradeoff.
    earliest = min(
        json.loads(f.read_text().splitlines()[1])["attrs"]["i"]
        for f in files if len(f.read_text().splitlines()) > 1)
    assert earliest > 0


def test_rotated_run_reconstructs_spans_across_segments(
        tmp_path, monkeypatch):
    """A span whose begin landed in segment 0 and whose end landed in a
    later segment must reconstruct as ONE closed span: export stitches
    segments in WRITE order (plain sorted() puts ``-s1.jsonl`` before
    the bare first segment, which used to feed ends to the parser
    before their begins and misreport a rotated run as violation-ridden
    — the quarantine-event-survives-rotation contract of the serve
    lane-kill CI drive rests on this)."""
    monkeypatch.setenv("OT_TRACE_DIR", str(tmp_path / "tr"))
    monkeypatch.setenv("OT_TRACE_RUN", "t-seg")
    monkeypatch.setenv("OT_TRACE_MAX_MB", "0.02")  # ~5 KiB segments
    trace.reset_for_tests()
    try:
        cm = trace.detached_span("long-lived", tag="spans-the-rotation")
        cm.__enter__()
        trace.point("quarantine", unit="lane:3", reason="rehearsal")
        for i in range(40):  # push past one segment threshold, not four
            trace.point("filler", i=i, pad="x" * 100)
        cm.__exit__(None, None, None)
    finally:
        run_dir = tmp_path / "tr" / "t-seg"
        files = sorted(run_dir.glob("trace-*.jsonl"))
        trace.reset_for_tests()
    assert len(files) >= 2  # it rotated
    # Plain lexicographic order is WRONG order for these files — the
    # regression this test pins: -s1 sorts before the bare segment.
    assert [f.name for f in files] != \
        [f.name for f in sorted(files, key=export._segment_order)]
    # And load_run still reconstructs: no violations, the cross-segment
    # span is closed, and the quarantine point survives.
    run = export.load_run(str(run_dir))
    assert not run.violations
    assert not run.orphans()
    long = [s for s in run.spans.values() if s.name == "long-lived"]
    assert len(long) == 1 and long[0].end_ts is not None
    assert [p["attrs"]["unit"] for p in run.points("quarantine")] \
        == ["lane:3"]


def test_trace_rotation_survives_failed_segment_open(tmp_path, monkeypatch):
    """ENOSPC mid-soak (a failed new-segment open) must leave the
    CURRENT handle live — events keep flowing to the full segment and
    rotation retries later — rather than stranding a closed handle that
    silently ends tracing for the process."""
    monkeypatch.setenv("OT_TRACE_DIR", str(tmp_path / "tr"))
    monkeypatch.setenv("OT_TRACE_RUN", "t-rotfail")
    monkeypatch.setenv("OT_TRACE_MAX_MB", "0.01")
    trace.reset_for_tests()
    try:
        trace.point("first")  # opens segment 0
        def refuse(state):
            raise OSError(28, "No space left on device")
        monkeypatch.setattr(trace, "_open_segment_locked", refuse)
        for i in range(200):  # crosses the segment threshold repeatedly
            trace.point("soak", i=i, pad="x" * 100)
        dropped_mid = trace.metrics_snapshot().get("dropped", 0)
        monkeypatch.undo()  # restore the real opener ("space freed")
        monkeypatch.setenv("OT_TRACE_DIR", str(tmp_path / "tr"))
        monkeypatch.setenv("OT_TRACE_RUN", "t-rotfail")
        monkeypatch.setenv("OT_TRACE_MAX_MB", "0.01")
        trace.point("after", tag="recovered")
    finally:
        files = sorted((tmp_path / "tr" / "t-rotfail").glob("trace-*.jsonl"))
        trace.reset_for_tests()
    assert dropped_mid == 0  # nothing lost while rotation was refused
    recs = [json.loads(l) for f in files for l in f.read_text().splitlines()]
    pts = [r for r in recs if r.get("ev") == "p"]
    assert sum(1 for r in pts if r["name"] == "soak") == 200
    assert any(r["name"] == "after" for r in pts)  # rotation resumed
    assert len(files) >= 2  # and did eventually rotate
